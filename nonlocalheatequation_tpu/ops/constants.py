"""Scaling constants for the nonlocal operator.

The constant ``c`` is chosen so that the nonlocal operator converges to the
local diffusion operator k*laplace(u) as the horizon shrinks (reference math:
description/problem_description.tex:149-158 and 625-710).

These reproduce the *code's* constants, not the paper's (SURVEY.md section 0):

* 1D: the reference declares ``c_1d`` as ``long`` (src/1d_nonlocal_serial.cpp:57)
  and assigns ``(k * 3) / pow(eps * dx, 3)`` (src/1d_nonlocal_serial.cpp:74), so
  the value is TRUNCATED to an integer.  E.g. for k=0.02, eps=40, dx=0.019 the
  constant truncates to 0.  The manufactured-solution test is self-consistent
  (the source term uses the same constant), so correctness tests pass either
  way, but a faithful oracle must truncate.
* 2D: ``c_2d = (k * 8) / pow(eps * dh, 4)`` kept as double
  (src/2d_nonlocal_serial.cpp:76, src/2d_nonlocal_distributed.cpp:445).  The
  paper's constant has a 1/pi factor the code drops.
* 3D: no 3D solver exists in the reference; we extend the paper's
  moment-matching recipe (problem_description.tex:625-710) with J=1 on the
  sphere.  Requiring c * integral_{|z|<eps} z_x^2 dz = 2k gives
  c = 2k / (4*pi*eps^5/15) = 15k / (2*pi*eps^5), i.e. with eps in grid units
  c_3d = 15*k / (2*pi*(eps*h)^5).  (We keep the pi here: the reference's 2D
  pi-drop is a quirk we reproduce only where the reference code exists.)
"""

import math

# --------------------------------------------------------------------------
# Precision tiers
# --------------------------------------------------------------------------
#
# The reference is f64-only C++ (SURVEY.md section 0); precision tiers are a
# capability of the reimplementation.  A tier names the STORAGE/OPERAND
# precision of the neighbor-sum reads — the bandwidth-heavy side of the
# memory-bound kernels — never the precision of the accumulation or of the
# time-integration carry:
#
# * "f32" (default): the state dtype is used end to end.  Bit-identical to
#   the pre-tier code by construction (no rounding is inserted anywhere).
# * "bf16": every operator evaluation reads the bfloat16 ROUNDING of the
#   state (operand windows at half the bytes), accumulates in the state
#   dtype (f32 in production, f64 on the CPU oracle suite), and the forward-
#   Euler carry u + dt*du stays in the state dtype — the classic mixed-
#   precision shape (low-precision storage, high-precision accumulate+master).
#   The center term Wsum*u uses the SAME rounded operand as the neighbor
#   sum, so L(const) == 0 holds exactly in the tier too.
#
# Error model (documented; pinned by tests/test_precision_tier.py): bf16
# carries an 8-bit mantissa, so rounding injects a relative perturbation
# ~2^-9 into the OPERAND of L each step.  Because the carry is f32, the
# perturbation enters the state only through dt*L(round(u)) — scaled by
# dt*c*h^d*Wsum, which forward-Euler stability bounds by <= 1 — so per-step
# state error is O(2^-9 * |u|) *damped by the diffusion dynamics*, not a
# compounding rounding of the carry itself.  It still cannot meet the 1e-12
# oracle-parity bar of the f32 fast paths (the operand rounding is real), so
# the tier ships with its own measured-accuracy contract below instead of
# pretending to bit-parity.  ``resync_every=R`` additionally evaluates every
# R-th step's operator on the UNROUNDED state (a full-precision step) for
# workloads that want to bound operand-rounding drift further.

PRECISION_TIERS = ("f32", "bf16")

# Manufactured-solution accuracy budget for the bf16 tier, at a STABLE
# timestep.  Stability caveat (measured, not theoretical): several of the
# reference's ctest parameter rows sit marginally past the forward-Euler
# bound dt*c*h^d*Wsum <= 1 and only look stable because f32/f64 rounding
# seeds the amplified modes at ~1e-7/1e-16 — the bf16 tier re-seeds them
# at ~2^-9 every step, which those configs amplify into garbage.  The
# tier is therefore contracted (and tested) at dt = 0.8x the stability
# bound, the regime bench.py and any production run use.  Measured
# error_l2/#points there: ~3.5e-7 across 48^2/eps4, 50^2/eps5, 64^2/eps8
# at nt 40-45 (tests/test_precision_tier.py re-measures each run) — the
# tier meets the reference's own 1e-6 bar at these scales, and the
# pinned budget below adds ~6x margin so a real regression fails loudly
# while backend jitter does not.  The f32 contract (1e-6) is NOT
# relaxed — this budget exists only for paths that explicitly opted into
# precision="bf16".
BF16_L2_BUDGET = 2e-6

# Autotuner gate for the precision dimension (utils/autotune.py): a bf16
# candidate may only win a probe if its multi-step output stays within
# this l2/#points of the f32 per-step program on the same probe state.
# Probe states are O(1) random fields over PROBE_STEPS steps; the bound
# is derived from the same 2^-9-per-step operand model with margin.
BF16_TUNE_GATE = 1e-5


def validate_precision(precision: str) -> str:
    """Validate a precision-tier name (see PRECISION_TIERS above)."""
    if precision not in PRECISION_TIERS:
        raise ValueError(
            f"unknown precision tier {precision!r}; valid: {PRECISION_TIERS}"
        )
    return precision


# --------------------------------------------------------------------------
# Time-integrator stability model
# --------------------------------------------------------------------------
#
# The operator's spectrum lies in [-2*c*h^d*Wsum, 0] (docs/math_spec.md
# section 6: the neighbor sum is bounded by Wsum*|u| and the center term
# subtracts exactly Wsum*u, so every eigenvalue is real and non-positive
# with |lambda| <= 2*c*h^d*Wsum).  A one-step method with stability
# polynomial P is stable iff |P(dt*lambda)| <= 1 over that interval:
#
# * forward Euler: P(z) = 1 + z, stable for z in [-2, 0]
#     -> dt <= 1 / (c*h^d*Wsum)
# * RKC (s-stage Runge-Kutta-Chebyshev, first order, damped):
#     P(z) = T_s(w0 + w1*z)/T_s(w0), stable for z in [-beta(s), 0] with
#     beta(s) = (1 + w0)/w1 ~ 2*s^2 for small damping
#     -> dt <= beta(s) / (2*c*h^d*Wsum)  (~s^2/2 x the Euler bound)
# * exponential (spectral, method='fft' only): e^{dt*lambda} <= 1 for any
#     dt since lambda <= 0 -> unconditionally stable (bound = inf).
#
# Historical bug this section fixes (ISSUE 8 satellite): every CLI
# computed its stability advice with the Euler-only constant and silently
# accepted any --dt, even when a super-stepping integrator could take (or
# required refusing) larger steps.  stable_dt() below is the single
# source of truth; the CLIs print the bound actually in force and refuse
# (rc 2) an explicit --dt beyond it for the opted-into steppers.

#: Chebyshev damping factor for the RKC stepper: w0 = 1 + eta/s^2 pulls
#: the internal stability polynomial off the real-axis touch points so
#: |P| <= ~1 - eta/2 strictly inside the interval (Verwer's classic
#: choice), trading ~2.6% of the stability interval for robustness
#: against spectrum-estimate error.
RKC_DAMPING = 0.05


def _cheb_pair(s: int, w0: float) -> tuple:
    """(T_s(w0), T_s'(w0)) by the three-term recurrences (exact
    polynomial evaluation; s is small, the recurrence is stable for
    w0 >= 1)."""
    t_prev, t = 1.0, w0  # T_0, T_1
    d_prev, d = 0.0, 1.0  # T_0', T_1'
    for _ in range(2, s + 1):
        t_prev, t = t, 2.0 * w0 * t - t_prev
        d_prev, d = d, 2.0 * t_prev + 2.0 * w0 * d - d_prev
    return (t, d) if s >= 1 else (1.0, 0.0)


def rkc_beta(stages: int) -> float:
    """Real-axis stability-interval length beta(s) of the damped s-stage
    RKC polynomial: P(z) = T_s(w0 + w1*z)/T_s(w0) keeps |P| <= 1 while
    w0 + w1*z >= -1, i.e. for z in [-(1 + w0)/w1, 0].  beta(2) ~ 7.7,
    beta(10) ~ 193 (~2*s^2*(1 - 4/3*eta) for small damping eta)."""
    s = int(stages)
    if s < 2:
        raise ValueError(f"RKC needs stages >= 2, got {stages}")
    w0 = 1.0 + RKC_DAMPING / (s * s)
    ts, dts = _cheb_pair(s, w0)
    w1 = ts / dts
    return (1.0 + w0) / w1


def stable_dt(c: float, h: float, dim: int, wsum: float,
              stepper: str = "euler", stages: int = 0) -> float:
    """Max stable dt for the (stepper, stages) pair on an operator with
    scaling constant ``c``, grid spacing ``h``, dimension ``dim`` and
    mask weight sum ``wsum`` — see the section comment for the model.
    A degenerate operator (c truncated to 0, the reference's 1D long
    cast) has an empty spectrum: every dt is stable (inf)."""
    lam_max = 2.0 * c * (h ** dim) * wsum  # |lambda|_max
    if stepper == "expo":
        return math.inf
    if lam_max <= 0.0:
        return math.inf
    if stepper == "euler":
        return 2.0 / lam_max
    if stepper == "rkc":
        return rkc_beta(stages) / lam_max
    raise ValueError(f"unknown stepper {stepper!r} (euler|rkc|expo)")


def stable_dt_op(op, stepper: str = "euler", stages: int = 0) -> float:
    """:func:`stable_dt` with (c, h, dim, wsum) read off an operator."""
    dim = op.weights.ndim
    h = op.dx if dim == 1 else op.dh
    return stable_dt(op.c, h, dim, op.wsum, stepper=stepper, stages=stages)


def c_1d(k: float, eps: int, dx: float) -> float:
    """1D scaling constant, integer-truncated exactly like the reference.

    Mirrors src/1d_nonlocal_serial.cpp:74 where the result of
    ``(k * 3) / pow(eps * dx, 3)`` is stored into a ``long``.
    """
    return float(int((k * 3) / math.pow(eps * dx, 3)))


def c_2d(k: float, eps: int, dh: float) -> float:
    """2D scaling constant (src/2d_nonlocal_serial.cpp:76), kept as double."""
    return (k * 8) / math.pow(eps * dh, 4)


def c_3d(k: float, eps: int, dh: float) -> float:
    """3D scaling constant (extension; no 3D exists in the reference).

    c = 2k / integral_{|z|<eps*h} z_x^2 dz = 15k / (2*pi*(eps*h)^5), so the
    nonlocal operator converges to k*laplace(u) as the horizon shrinks.  See
    the module docstring for the derivation.
    """
    return (k * 15) / (2.0 * math.pi * math.pow(eps * dh, 5))
