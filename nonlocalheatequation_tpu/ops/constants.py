"""Scaling constants for the nonlocal operator.

The constant ``c`` is chosen so that the nonlocal operator converges to the
local diffusion operator k*laplace(u) as the horizon shrinks (reference math:
description/problem_description.tex:149-158 and 625-710).

These reproduce the *code's* constants, not the paper's (SURVEY.md section 0):

* 1D: the reference declares ``c_1d`` as ``long`` (src/1d_nonlocal_serial.cpp:57)
  and assigns ``(k * 3) / pow(eps * dx, 3)`` (src/1d_nonlocal_serial.cpp:74), so
  the value is TRUNCATED to an integer.  E.g. for k=0.02, eps=40, dx=0.019 the
  constant truncates to 0.  The manufactured-solution test is self-consistent
  (the source term uses the same constant), so correctness tests pass either
  way, but a faithful oracle must truncate.
* 2D: ``c_2d = (k * 8) / pow(eps * dh, 4)`` kept as double
  (src/2d_nonlocal_serial.cpp:76, src/2d_nonlocal_distributed.cpp:445).  The
  paper's constant has a 1/pi factor the code drops.
* 3D: no 3D solver exists in the reference; we extend the paper's
  moment-matching recipe (problem_description.tex:625-710) with J=1 on the
  sphere.  Requiring c * integral_{|z|<eps} z_x^2 dz = 2k gives
  c = 2k / (4*pi*eps^5/15) = 15k / (2*pi*eps^5), i.e. with eps in grid units
  c_3d = 15*k / (2*pi*(eps*h)^5).  (We keep the pi here: the reference's 2D
  pi-drop is a quirk we reproduce only where the reference code exists.)
"""

import math


def c_1d(k: float, eps: int, dx: float) -> float:
    """1D scaling constant, integer-truncated exactly like the reference.

    Mirrors src/1d_nonlocal_serial.cpp:74 where the result of
    ``(k * 3) / pow(eps * dx, 3)`` is stored into a ``long``.
    """
    return float(int((k * 3) / math.pow(eps * dx, 3)))


def c_2d(k: float, eps: int, dh: float) -> float:
    """2D scaling constant (src/2d_nonlocal_serial.cpp:76), kept as double."""
    return (k * 8) / math.pow(eps * dh, 4)


def c_3d(k: float, eps: int, dh: float) -> float:
    """3D scaling constant (extension; no 3D exists in the reference).

    c = 2k / integral_{|z|<eps*h} z_x^2 dz = 15k / (2*pi*(eps*h)^5), so the
    nonlocal operator converges to k*laplace(u) as the horizon shrinks.  See
    the module docstring for the derivation.
    """
    return (k * 15) / (2.0 * math.pi * math.pow(eps * dh, 5))
