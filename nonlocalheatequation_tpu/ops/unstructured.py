"""Variable-horizon nonlocal operator on unstructured point clouds.

Framework extension (SURVEY.md section 7 stretch item): the reference only
solves uniform grids with one global integer horizon, but its math
(problem_description.tex:131-158) is defined for any node set and any
horizon field.  This module evaluates

    L(u)[i] = c_i * sum_{j in N(i)} J(|x_j - x_i| / eps_i) (u_j - u_i) * vol_j

with N(i) = {j : |x_j - x_i| <= eps_i} (the center point included, matching
the grid raster's center-in-stencil convention, ops/stencil.py).

TPU-first evaluation: the neighbor structure is a static edge list built once
on the host (cell-binned radius search; the OpenMP builder in
native/edges.cc when built, with the NumPy implementation as fallback and
parity oracle), and the jit'd operator is a padded-row (ELL) gather +
row-sum by default, with the edge-list ``jax.ops.segment_sum`` form for
skewed degree profiles and the sharded path.

The per-point constant uses exact discrete moment matching,

    c_i = 2 * d * k / sum_j |x_j - x_i|^2 * J(.) * vol_j,

which makes L converge to k*laplace(u) for ANY node layout (on the uniform
grid with the paper's continuum moment this reduces to the 2k*d/integral
recipe; the reference's hard-coded 8k/(eps*dh)^4 drops a pi — ops/constants
reproduces that quirk on the grid path, where bit-parity matters).
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

import jax
import jax.numpy as jnp

from nonlocalheatequation_tpu.parallel.multihost import put_global
from nonlocalheatequation_tpu.utils.checkpoint import CheckpointMixin
from nonlocalheatequation_tpu.utils.devices import device_list


def _load_native():
    from nonlocalheatequation_tpu.utils.native import load_native_lib

    lib = load_native_lib("libedges.so", ("nl_edges_count", "nl_edges_fill"))
    if lib is None:
        return None
    f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    lib.nl_edges_count.restype = ctypes.c_int64
    lib.nl_edges_count.argtypes = [ctypes.c_int32, ctypes.c_int64, f64p, f64p, i64p]
    lib.nl_edges_fill.restype = None
    lib.nl_edges_fill.argtypes = [
        ctypes.c_int32, ctypes.c_int64, f64p, f64p, i64p, i32p, i32p,
    ]
    return lib


_native_lib = _load_native()


def _build_edges_native(points: np.ndarray, eps: np.ndarray):
    """Native (OpenMP) cell-binned search; None when unavailable/unsuitable.

    Same membership rule and output order as the NumPy builder (verified by
    tests/test_unstructured.py parity test); d <= 3 only.
    """
    n, d = points.shape
    if _native_lib is None or d > 3:
        return None
    pts = np.ascontiguousarray(points, np.float64)
    eps = np.ascontiguousarray(eps, np.float64)
    deg = np.zeros(n, np.int64)
    total = _native_lib.nl_edges_count(d, n, pts, eps, deg)
    if total < 0:  # invalid input or key-packing overflow: fall back
        return None
    starts = np.zeros(n + 1, np.int64)
    np.cumsum(deg, out=starts[1:])
    tgt = np.empty(total, np.int32)
    src = np.empty(total, np.int32)
    _native_lib.nl_edges_fill(d, n, pts, eps, starts, tgt, src)
    return tgt, src


def build_edges(points: np.ndarray, eps: np.ndarray):
    """Radius-neighbor edge list via cell binning; O(N * nbhd) host-side.

    points: (N, d) float64; eps: (N,) per-point horizon radii.
    Returns (targets, sources) int32 arrays sorted by target, center included.

    Binning uses ONE global cell size, ``eps.max()``, with candidates drawn
    from the +/-1 cell neighborhood.  Consequences:

    * correctness: any neighbor with |x_j - x_i| <= eps_i <= eps.max() lands
      within one cell of i, so no true neighbor is missed; a point that only
      qualifies through the (1 + 1e-12) floating-point mask tolerance while
      sitting beyond eps.max() of a cell boundary could in principle fall in
      a +/-2 cell and be excluded — boundary-exact neighbors are therefore
      not guaranteed when eps_i == eps.max() exactly;
    * performance: a strongly varying horizon field degrades the search
      toward O(N * max-ball) because every point scans candidates within
      eps.max(), not its own eps_i.  For such fields, bin per horizon scale
      before calling (or accept the host-side one-time cost — the edge list
      is built once and reused for the whole solve).
    """
    points = np.asarray(points, np.float64)
    eps = np.broadcast_to(np.asarray(eps, np.float64), (points.shape[0],))
    n, d = points.shape
    cell = float(eps.max())
    if cell <= 0:
        raise ValueError("horizon radii must be positive")
    native = _build_edges_native(points, eps)
    if native is not None:
        return native
    keys = np.floor((points - points.min(axis=0)) / cell).astype(np.int64)
    # bin points by cell
    bins: dict[tuple, list[int]] = {}
    for i, key in enumerate(map(tuple, keys)):
        bins.setdefault(key, []).append(i)
    offsets = np.array(
        np.meshgrid(*([(-1, 0, 1)] * d), indexing="ij")
    ).reshape(d, -1).T
    targets: list[np.ndarray] = []
    sources: list[np.ndarray] = []
    for key, members in bins.items():
        cand: list[int] = []
        for off in offsets:
            cand.extend(bins.get(tuple(np.add(key, off)), ()))
        cand_arr = np.asarray(cand, np.int64)
        mem = np.asarray(members, np.int64)
        diff = points[mem][:, None, :] - points[cand_arr][None, :, :]
        dist2 = np.einsum("ijk,ijk->ij", diff, diff)
        mask = dist2 <= (eps[mem][:, None] ** 2) * (1 + 1e-12)
        ti, si = np.nonzero(mask)
        targets.append(mem[ti])
        sources.append(cand_arr[si])
    tgt = np.concatenate(targets)
    src = np.concatenate(sources)
    order = np.lexsort((src, tgt))
    return tgt[order].astype(np.int32), src[order].astype(np.int32)


class UnstructuredNonlocalOp:
    """Nonlocal horizon operator for arbitrary node sets (any dimension)."""

    def __init__(
        self,
        points: np.ndarray,
        eps,
        k: float,
        dt: float,
        vol=None,
        influence=None,
        c=None,
    ):
        self.points = np.asarray(points, np.float64)
        n, d = self.points.shape
        self.n, self.d = n, d
        self.eps = np.broadcast_to(np.asarray(eps, np.float64), (n,)).copy()
        self.k = float(k)
        self.dt = float(dt)
        self.vol = (
            np.ones(n) if vol is None
            else np.broadcast_to(np.asarray(vol, np.float64), (n,)).copy()
        )
        tgt, src = build_edges(self.points, self.eps)
        self.tgt, self.src = tgt, src
        diff = self.points[src] - self.points[tgt]
        dist = np.sqrt(np.einsum("ij,ij->i", diff, diff))
        if influence is None:
            w = np.ones(len(tgt))
        else:
            # J(|x_j - x_i| / eps_i): normalized by the target's horizon
            w = np.vectorize(influence)(dist / self.eps[tgt])
        self.edge_w = w * self.vol[src]
        # exact discrete moment matching per point (see module docstring)
        m2 = np.zeros(n)
        np.add.at(m2, tgt, dist * dist * self.edge_w)
        if c is None:
            with np.errstate(divide="ignore"):
                self.c = np.where(m2 > 0, 2.0 * d * self.k / m2, 0.0)
        else:
            self.c = np.broadcast_to(np.asarray(c, np.float64), (n,)).copy()
        # row sums of weights (the u_i coefficient; center adds zero)
        wsum = np.zeros(n)
        np.add.at(wsum, tgt, self.edge_w)
        self.wsum = wsum
        deg = np.bincount(tgt, minlength=n) if len(tgt) else np.zeros(n, np.int64)
        self.kmax = int(deg.max()) if len(tgt) else 0
        self._ell_arrays = None  # built lazily; see _ell()
        self._windowed_plan = None  # built lazily; see windowed_plan()
        self._windowed_stats = None  # cached (coverage, p_bytes) precheck
        self._windowed_search = None  # the gate's ladder search, reused
        # by the default-kwargs windowed_plan() build
        self._offset_plan = None  # built lazily; see offset_plan()

    # ELL (padded-row) layout of the same edges: neighbor column ids and
    # weights as dense (n, kmax) with zero-weight padding.  A regular
    # gather + row-sum beats the edge-list scatter-add on TPU by ~1.44x at
    # 7.7M edges (measured round 3, docs/bench/BENCH_TABLE_r03.jsonl) —
    # but dense padding is O(n * kmax), so it is built LAZILY (the sharded
    # wrapper never pays for it) and only worth it when degrees are fairly
    # uniform; "auto" falls back to the edge list when padding would more
    # than double the stored entries (e.g. one wide-horizon hub node).
    _ELL_MAX_PAD_RATIO = 2.0

    def _ell(self):
        if self._ell_arrays is None:
            n, tgt, src = self.n, self.tgt, self.src
            deg = np.bincount(tgt, minlength=n)
            starts = np.zeros(n + 1, np.int64)
            np.cumsum(deg, out=starts[1:])
            col = np.zeros((n, self.kmax), np.int32)
            w = np.zeros((n, self.kmax), np.float64)
            pos = np.arange(len(tgt)) - starts[tgt]
            col[tgt, pos] = src
            w[tgt, pos] = self.edge_w
            self._ell_arrays = (col, w)
        return self._ell_arrays

    def _ell_worthwhile(self) -> bool:
        return (len(self.tgt) > 0
                and self.n * self.kmax
                <= self._ELL_MAX_PAD_RATIO * len(self.tgt))

    # Windowed block-dense layout (ops/windowed.py): the gather-free Pallas
    # path.  Worthwhile when the cloud is large enough that gathers dominate
    # (the plan build is an O(E log E) host one-time cost), the Morton
    # windows actually capture the edges, and the dense strips fit a budget.
    _WINDOWED_MIN_N = 65536
    _WINDOWED_MIN_COVERAGE = 0.90

    def windowed_plan(self, **kwargs):
        """Build and return the windowed layout plan (cached per kwargs:
        asking with different parameters rebuilds rather than silently
        returning a plan built under other constraints)."""
        key = tuple(sorted(kwargs.items()))
        if self._windowed_plan is None or self._windowed_plan[0] != key:
            from .windowed import build_plan

            # default-kwargs builds reuse the worthwhileness gate's
            # ladder search (computed with the real edge weights) so the
            # accept path pays the O(E log E) search once, not twice
            search = self._windowed_search if not kwargs else None
            self._windowed_plan = (key, build_plan(
                self.points, self.eps, self.tgt, self.src, self.edge_w,
                self.c, self.wsum, search=search, **kwargs,
            ))
        return self._windowed_plan[1]

    def _windowed_budget_bytes(self) -> int:
        return int(os.environ.get("NLHEAT_WINDOWED_BUDGET_MB", "2048")) << 20

    def _windowed_worthwhile(self) -> bool:
        forced = os.environ.get("NLHEAT_WINDOWED")
        if forced is not None:
            return forced not in ("", "0")
        if self.n < self._WINDOWED_MIN_N or len(self.tgt) == 0:
            return False
        if jax.default_backend() != "tpu":
            # gathers are cheap on CPU; the strips only pay off where the
            # gather path is the bottleneck
            return False
        # stats-only precheck (ADVICE r4): judge coverage and strip bytes
        # from the ladder search alone — the dense strips are only
        # materialized (by windowed_plan()) once the plan is accepted.
        # Cached: the edge set is immutable and the per-step auto path
        # consults this gate on every apply.  Run with the REAL edge
        # weights so windowed_plan() can reuse the search on accept.
        if self._windowed_stats is None:
            from .windowed import _plan_search

            sr = _plan_search(self.points, self.eps, self.tgt, self.src,
                              self.edge_w, bm=128, wmax=4096,
                              max_overflow_frac=0.02, order="morton",
                              windows=2)
            self._windowed_search = sr
            cov = 1.0 if sr["total"] == 0 else sr["covered"] / sr["total"]
            self._windowed_stats = (
                cov, sr["n_pad"] * sr["R"] * sr["we"] * 4)
        coverage, p_bytes = self._windowed_stats
        return (coverage >= self._WINDOWED_MIN_COVERAGE
                and p_bytes <= self._windowed_budget_bytes())

    # Offset (DIA) layout: the fastest path when src-tgt index offsets
    # cluster (quasi-uniform clouds in their natural order — a jittered
    # 512^2 grid keeps the whole 7.7M-edge set on 45 distinct offsets).
    _OFFSETS_MIN_N = 4096
    _OFFSETS_MIN_COVERAGE = 0.98

    def offset_plan(self, **kwargs):
        """Build and return the diagonal-offset layout plan (cached per
        kwargs, same rebuild-on-mismatch rule as :meth:`windowed_plan`)."""
        key = tuple(sorted(kwargs.items()))
        if self._offset_plan is None or self._offset_plan[0] != key:
            from .windowed import build_offset_plan

            self._offset_plan = (key, build_offset_plan(
                self.tgt, self.src, self.edge_w, self.c, self.wsum, self.n,
                **kwargs,
            ))
        return self._offset_plan[1]

    def _offsets_worthwhile(self) -> bool:
        forced = os.environ.get("NLHEAT_OFFSETS")
        if forced is not None:
            return forced not in ("", "0")
        if self.n < self._OFFSETS_MIN_N or len(self.tgt) == 0:
            return False
        if jax.default_backend() != "tpu":
            return False
        # cheap precheck: judge coverage/size from the offset histogram
        # alone; the dense diagonals are only materialized if accepted
        from .windowed import offset_stats

        coverage, _, w_bytes = offset_stats(self.tgt, self.src, self.n)
        return (coverage >= self._OFFSETS_MIN_COVERAGE
                and w_bytes <= self._windowed_budget_bytes())

    def choose_layout(self) -> str:
        """The auto policy, in one place: offsets (quasi-grid clouds) >
        windowed (Morton-sortable clouds, TPU) > ELL > edges."""
        if self._offsets_worthwhile():
            return "offsets"
        if self._windowed_worthwhile():
            return "windowed"
        return "ell" if self._ell_worthwhile() else "edges"

    # -- operator -----------------------------------------------------------
    def apply_np(self, u: np.ndarray) -> np.ndarray:
        acc = np.zeros(self.n)
        np.add.at(acc, self.tgt, self.edge_w * u[self.src])
        return self.c * (acc - self.wsum * u)

    def apply(self, u: jnp.ndarray, layout: str = "auto") -> jnp.ndarray:
        """L(u) on device.  ``layout="offsets"`` runs the diagonal (DIA)
        layout — static shifted slices, the fast path for quasi-grid
        clouds; ``layout="windowed"`` the gather-free block-dense Pallas
        path (ops/windowed.py; permute in, invert out); ``layout="ell"``
        the padded-row gather + row-sum; ``layout="edges"`` the segment_sum
        scatter-add (O(edges) memory, any degree profile); ``"auto"``
        (default) resolves via :meth:`choose_layout`.  Same edges every
        way, different reduction order — all hold the 1e-6 contract; the
        sharded path keeps the edge layout."""
        if layout == "auto":
            layout = self.choose_layout()
        if layout == "offsets":
            return self.offset_plan().for_dtype(u.dtype).L(u)
        if layout == "windowed":
            return self.windowed_plan().for_dtype(u.dtype).L(u)
        if layout == "ell":
            col, w = self._ell()
            acc = jnp.sum(jnp.asarray(w, u.dtype) * u[jnp.asarray(col)],
                          axis=1)
        else:
            edge_w = jnp.asarray(self.edge_w, u.dtype)
            acc = jax.ops.segment_sum(
                edge_w * u[self.src], jnp.asarray(self.tgt),
                num_segments=self.n,
            )
        return jnp.asarray(self.c, u.dtype) * (
            acc - jnp.asarray(self.wsum, u.dtype) * u
        )

    # -- manufactured solution (product of sines at the node coords) --------
    def spatial_profile(self) -> np.ndarray:
        TWO_PI = 2.0 * np.pi
        return np.prod(np.sin(TWO_PI * self.points), axis=1)

    def source_parts(self):
        g = self.spatial_profile()
        return g, self.apply_np(g)

    def manufactured_solution(self, t: int) -> np.ndarray:
        return np.cos(2.0 * np.pi * (t * self.dt)) * self.spatial_profile()


def _ring_exchange(mine, lo: int, hi: int, S: int):
    """[left band | own block | right band] over the 1D shard ring via
    ``lax.ppermute`` — the one exchange both the per-step offsets apply
    and the superstep K-block use (any fix to direction/wrap handling
    lands in both).  Ring wrap delivers garbage bands at the global
    boundary; callers neutralize them (zero weights per-step, the
    out-of-domain mask in the superstep)."""
    B = mine.shape[0]
    parts = []
    if lo:  # band from the LEFT neighbor: everyone sends right
        parts.append(jax.lax.ppermute(
            mine[B - lo:], "p", [(i, (i + 1) % S) for i in range(S)]))
    parts.append(mine)
    if hi:  # band from the RIGHT neighbor: everyone sends left
        parts.append(jax.lax.ppermute(
            mine[:hi], "p", [(i, (i - 1) % S) for i in range(S)]))
    return jnp.concatenate(parts) if len(parts) > 1 else mine


class ShardedUnstructuredOp:
    """Multi-device evaluation of an UnstructuredNonlocalOp via shard_map.

    TPU-first layout: nodes are partitioned into equal contiguous index
    blocks over a 1D device mesh (axis ``p``); the edge list is partitioned
    by TARGET-node shard (so every scatter-add is device-local) and padded to
    the max per-shard edge count (static shapes for XLA).

    The halo has two forms (``halo=`` "auto"/"export"/"gather"):

    * **export** — each shard exports only the nodes some other shard's
      edges actually reference (precomputed index sets); one all_gather of
      the (S, Emax) export blocks replaces the full-state gather, cutting
      per-step comm from S*B to S*Emax values.  With a locality-preserving
      node ordering (grids, utils/decompose.py output) the exports are just
      the near-boundary nodes — the true unstructured halo.
    * **gather** — all-gather the whole state: the honest general form for
      adversarial orderings where everything is referenced everywhere.

    "auto" picks export when the export volume is under half the full
    gather (``halo_comm_ratio``); both forms are BIT-identical (same edge
    order, same addends — only where the source value is read from
    differs).

    ``layout="offsets"`` (picked by ``layout="auto"`` + ``halo="auto"``
    when the cloud's src-tgt offsets fully cluster, see ops/windowed.py)
    replaces the per-edge gather entirely: each shard keeps the (|O|, B)
    slices of the dense diagonal weights and exchanges only
    pad_lo/pad_hi-wide halo bands with its ring neighbors via
    ``lax.ppermute`` — the same ICI pattern as the grid solvers' halo.
    Reduction order then follows the diagonal sum (1e-12-close to the
    edge forms, not bit-identical).

    Numerics match the single-device operator to float-addition order:
    partitioning by target preserves each target's edge order, so per-segment
    accumulation sums the same values in the same sequence.
    """

    def __init__(self, op: UnstructuredNonlocalOp, mesh=None, devices=None,
                 halo: str = "auto", layout: str = "auto"):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        self.inner = op
        self.n, self.dt = op.n, op.dt
        if mesh is None:
            devices = list(devices if devices is not None else device_list())
            mesh = Mesh(np.asarray(devices), ("p",))
        self.mesh = mesh
        S = int(mesh.devices.size)
        self.S = S
        B = -(-op.n // S)  # block size (last block zero-padded)
        self.B = B
        self.pad = S * B - op.n

        # offsets (DIA) layout — gather-free sharded form for quasi-grid
        # clouds: per-shard dense diagonals + ppermute halo bands (the
        # multichip mirror of the single-device offsets layout in
        # ops/windowed.py).  Requires full offset coverage (any residual
        # edge would need a cross-shard gather) and one-hop halos.
        if layout not in ("auto", "offsets", "edges"):
            raise ValueError(f"layout must be auto/offsets/edges, got {layout!r}")
        if layout == "offsets" and halo != "auto":
            raise ValueError(
                "layout='offsets' replaces the edge halo machinery; it "
                f"cannot honor halo={halo!r} — drop one of the two")
        if layout == "offsets" and not len(op.tgt):
            raise ValueError("layout='offsets' needs a non-empty edge list")
        if layout == "auto" and halo != "auto":
            # an explicit halo request is a request for the edge layout's
            # halo machinery — don't silently route around it
            layout = "edges"
        if layout in ("auto", "offsets") and len(op.tgt):
            if op._offset_plan is not None:  # already built: reuse, no
                plan = op.offset_plan()      # second histogram pass
                cov = plan.coverage
            else:
                from .windowed import offset_stats

                cov, _keep_n, _ = offset_stats(op.tgt, op.src, op.n)
                plan = op.offset_plan() if cov >= 1.0 else None
            fits = (plan is not None and plan.coverage >= 1.0
                    and plan.pad_lo <= B and plan.pad_hi <= B)
            if layout == "offsets" and not fits:
                raise ValueError(
                    "layout='offsets' needs full offset coverage and "
                    f"one-hop halos (coverage {cov:.4f}, pads "
                    f"{getattr(plan, 'pad_lo', '?')}/"
                    f"{getattr(plan, 'pad_hi', '?')} vs block {B})")
            if fits:
                self._init_offsets(plan, mesh, S, B)
                return
        self.layout = "edges"

        # partition edges by target shard; order within a shard (and within
        # each target) is preserved from the global lexsorted edge list
        shard_of = op.tgt // B
        counts = np.bincount(shard_of, minlength=S)
        M = max(int(counts.max()), 1)
        tgt_l = np.zeros((S, M), np.int32)
        src_g = np.zeros((S, M), np.int32)
        w = np.zeros((S, M), np.float64)
        for s in range(S):
            m = shard_of == s
            c = int(m.sum())
            tgt_l[s, :c] = op.tgt[m] - s * B
            src_g[s, :c] = op.src[m]
            w[s, :c] = op.edge_w[m]  # padding keeps w == 0 -> contributes 0

        # export sets: nodes of shard r referenced by some OTHER shard
        exports = []
        for r in range(S):
            remote = (op.src // B == r) & (shard_of != r)
            exports.append(np.unique(op.src[remote]))
        Emax = max(1, max(len(e) for e in exports))
        export_volume = S * Emax
        self.halo_comm_ratio = export_volume / float(S * B)
        if halo not in ("auto", "export", "gather"):
            raise ValueError(f"halo must be auto/export/gather, got {halo!r}")
        if halo == "auto":
            halo = "export" if (S > 1 and 2 * export_volume <= S * B) else "gather"
        self.halo_mode = halo

        if halo == "export":
            exp_idx = np.zeros((S, Emax), np.int32)
            # global node id -> slot in its owner's export block (vectorized)
            slot = np.zeros(S * B, np.int64)
            for r, e in enumerate(exports):
                exp_idx[r, : len(e)] = e - r * B
                slot[e] = np.arange(len(e))
            # remap src into the concatenated [own B | gathered S*Emax] frame
            src_cat = np.zeros((S, M), np.int32)
            for s in range(S):
                m = shard_of == s
                c = int(m.sum())
                srcs = op.src[m]
                owner = srcs // B
                local = srcs - s * B
                remote = B + owner * Emax + slot[srcs]
                src_cat[s, :c] = np.where(owner == s, local, remote)
            self._exp_idx = None  # set below with sharding
        else:
            exp_idx = src_cat = None

        def blk(x):  # (n,) host array -> (S, B) with zero padding
            xp = np.zeros(S * B, np.float64)
            xp[: op.n] = x
            return xp.reshape(S, B)

        row = NamedSharding(mesh, P("p"))
        self._tgt = put_global(tgt_l, row)
        self._src = put_global(src_cat if halo == "export" else src_g, row)
        self._w = put_global(w, row)
        self._c = put_global(blk(op.c), row)
        self._wsum = put_global(blk(op.wsum), row)
        if halo == "export":
            self._exp_idx = put_global(exp_idx, row)

        from nonlocalheatequation_tpu.utils.compat import shard_map

        B_ = B

        def local_apply_gather(u_blk, tgt, src, w_, c_, wsum_):
            # u_blk: (1, B) block of the padded state; gather the full state
            u_all = jax.lax.all_gather(u_blk[0], "p", tiled=True)  # (S*B,)
            acc = jax.ops.segment_sum(
                w_[0] * u_all[src[0]], tgt[0], num_segments=B_
            )
            return (c_[0] * (acc - wsum_[0] * u_blk[0]))[None]

        def local_apply_export(u_blk, exp, tgt, src, w_, c_, wsum_):
            mine = u_blk[0]
            gathered = jax.lax.all_gather(
                mine[exp[0]], "p", tiled=True)  # (S*Emax,)
            u_cat = jnp.concatenate([mine, gathered])
            acc = jax.ops.segment_sum(
                w_[0] * u_cat[src[0]], tgt[0], num_segments=B_
            )
            return (c_[0] * (acc - wsum_[0] * mine))[None]

        p = P("p")
        if halo == "export":
            self._sharded = shard_map(
                local_apply_export, mesh=mesh,
                in_specs=(p, p, p, p, p, p, p), out_specs=p,
            )
        else:
            self._sharded = shard_map(
                local_apply_gather, mesh=mesh,
                in_specs=(p, p, p, p, p, p), out_specs=p,
            )

    def _init_offsets(self, plan, mesh, S: int, B: int) -> None:
        """Sharded DIA form: shard s keeps the (|O|, B) slice of every
        diagonal's weight vector; the step exchanges only pad_lo/pad_hi
        halo bands with ring neighbors (lax.ppermute — the same ICI
        pattern as the grid solvers' halo, parallel/halo.py) and sums
        static shifted slices.  Ring wrap delivers garbage bands at the
        global boundary, which is exact anyway: no edge crosses the
        boundary, so the corresponding weights are zero."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from nonlocalheatequation_tpu.utils.compat import shard_map

        op = self.inner
        self.layout = "offsets"
        self.halo_mode = "offsets-ppermute"
        pad_lo, pad_hi = plan.pad_lo, plan.pad_hi
        self.halo_comm_ratio = (pad_lo + pad_hi) / float(S * B)
        offs = plan.offs
        n_pad = S * B
        w3 = np.zeros((len(offs), n_pad), np.float64)
        w3[:, : op.n] = plan.W
        w3 = w3.reshape(len(offs), S, B).transpose(1, 0, 2)  # (S, |O|, B)

        def blk(x):
            xp = np.zeros(n_pad, np.float64)
            xp[: op.n] = x
            return xp.reshape(S, B)

        row = NamedSharding(mesh, P("p"))
        self._w3 = put_global(w3, row)
        self._c = put_global(blk(op.c), row)
        self._wsum = put_global(blk(op.wsum), row)

        def local_apply(u_blk, w3_, c_, wsum_):
            mine = u_blk[0]
            up = _ring_exchange(mine, pad_lo, pad_hi, S)
            acc = jnp.zeros_like(mine)
            for j, o in enumerate(offs):
                start = pad_lo + o
                acc = acc + w3_[0, j] * jax.lax.slice(up, (start,),
                                                      (start + B,))
            return (c_[0] * (acc - wsum_[0] * mine))[None]

        p = P("p")
        self._sharded = shard_map(
            local_apply, mesh=mesh, in_specs=(p, p, p, p), out_specs=p,
        )

    # duck-type the single-device operator's surface
    def apply_np(self, u):
        return self.inner.apply_np(u)

    def spatial_profile(self):
        return self.inner.spatial_profile()

    def source_parts(self):
        return self.inner.source_parts()

    def manufactured_solution(self, t: int):
        return self.inner.manufactured_solution(t)

    def apply_args(self) -> tuple:
        """The operator's device arrays, in ``apply_with`` order.  Callers
        that jit around the operator pass these as ARGUMENTS — a closure
        capture of arrays spanning a cross-process mesh is rejected by
        multi-controller JAX (docs/multihost.md)."""
        if self.layout == "offsets":
            return (self._w3, self._c, self._wsum)
        if self.halo_mode == "export":
            return (self._exp_idx, self._tgt, self._src, self._w,
                    self._c, self._wsum)
        return (self._tgt, self._src, self._w, self._c, self._wsum)

    def apply_with(self, u: jnp.ndarray, args: tuple) -> jnp.ndarray:
        """L(u) with the device arrays supplied by the caller (traced jit
        arguments); ``apply`` is the closure convenience form."""
        up = jnp.pad(u, (0, self.pad)).reshape(self.S, self.B)
        return self._sharded(up, *args).reshape(self.S * self.B)[: self.n]

    def apply(self, u: jnp.ndarray) -> jnp.ndarray:
        return self.apply_with(u, self.apply_args())

    def superstep_fits(self, ksteps: int) -> bool:
        """Can the K-block program run?  Offsets layout only (residual
        edges would need arbitrary cross-shard gathers), with the K-wide
        bands still one-hop (K*pad <= block)."""
        if self.layout != "offsets" or ksteps < 2:
            return False
        plan = self.inner.offset_plan()
        return (ksteps * plan.pad_lo <= self.B
                and ksteps * plan.pad_hi <= self.B)

    def superstep_check(self, ksteps: int) -> None:
        """The ONE refusal for an unfit K (constructors and builders share
        it, so the early and late gates can never drift apart)."""
        if self.superstep_fits(ksteps):
            return
        if ksteps < 2:
            raise ValueError(
                f"superstep needs K >= 2 (got {ksteps}); K=1 IS the "
                "per-step path")
        plan = (self.inner.offset_plan()
                if self.layout == "offsets" else None)
        raise ValueError(
            f"superstep {ksteps} does not fit the sharded offsets form "
            f"(layout={self.layout!r}, pads "
            f"{getattr(plan, 'pad_lo', '?')}/"
            f"{getattr(plan, 'pad_hi', '?')}, block {self.B}): needs "
            "the offsets layout and K*pad <= block")

    def make_superstep(self, ksteps: int, dtype, test: bool):
        """Communication-avoiding K-block for the sharded offsets layout:
        ONE (K*pad_lo, K*pad_hi)-wide ring ppermute exchange per K steps,
        then K local levels on shrinking regions — the grid solvers'
        superstep schedule (distributed2d.py ``_superstep`` /
        gang.make_gang_run_superstep) in the 1D DIA domain.

        Static fields (diagonal weights, c, wsum, sources) are globally
        known on the host, so each shard's EXTENDED slices are cut once
        here (no per-step exchange for them); only the state rides the
        ring.  Out-of-domain positions (ring wrap garbage at the global
        boundary, the block-padding tail) are masked to zero on entry and
        after every intermediate level — the volumetric BC analog.
        Intermediate levels are pinned with optimization_barrier, same
        ulp discipline as the grid schedule.

        Returns ``(args, block_fn)``: ``block_fn(u, t, args)`` advances
        the global (n,) state K steps; ``args`` are device arrays passed
        through the caller's jit as ARGUMENTS (multi-controller rule).
        """
        from jax.sharding import NamedSharding, PartitionSpec as P
        from nonlocalheatequation_tpu.utils.compat import shard_map

        from nonlocalheatequation_tpu.ops.nonlocal_op import source_at

        K = int(ksteps)
        self.superstep_check(K)
        plan = self.inner.offset_plan()
        pad_lo, pad_hi, offs = plan.pad_lo, plan.pad_hi, plan.offs
        S, B, n = self.S, self.B, self.n
        PL, PH = K * pad_lo, K * pad_hi
        n_pad = S * B
        ext = PL + B + PH
        np_dtype = np.dtype(jnp.dtype(dtype).name)

        def ext_blocks(vec):
            """(n,) global host field -> (S, ext) per-shard extended
            slices, zero beyond the domain."""
            vp = np.zeros(PL + n_pad + PH, np_dtype)
            vp[PL: PL + n] = np.asarray(vec)
            return np.stack([vp[s * B: s * B + ext] for s in range(S)])

        Wg = np.zeros((len(offs), PL + n_pad + PH), np_dtype)
        Wg[:, PL: PL + n] = plan.W
        w3x = np.stack([Wg[:, s * B: s * B + ext] for s in range(S)])
        host_args = [w3x, ext_blocks(self.inner.c),
                     ext_blocks(self.inner.wsum)]
        if test:
            g, lg = self.inner.source_parts()
            host_args += [ext_blocks(g), ext_blocks(lg)]
        row = NamedSharding(self.mesh, P("p"))
        args = tuple(put_global(a, row) for a in host_args)

        dt = self.dt

        def local_block(u_blk, w3x_, cx_, wsx_, *rest):
            if test:
                gx_, lgx_, t = rest
                gx_, lgx_ = gx_[0], lgx_[0]
            else:
                (t,) = rest
            mine = u_blk[0]
            cur = _ring_exchange(mine, PL, PH, S)
            # global index of cur[0] is s*B - PL; zero everything outside
            # [0, n) — ring wrap garbage and the padding tail must not
            # enter the intermediates
            gpos0 = jax.lax.axis_index("p") * B - PL
            idx = gpos0 + jax.lax.iota(jnp.int32, ext)
            cur = jnp.where((idx >= 0) & (idx < n), cur,
                            jnp.zeros_like(cur))
            w3s, cs, wss = w3x_[0], cx_[0], wsx_[0]
            for j in range(1, K + 1):
                m_lo = (K - j) * pad_lo
                m_hi = (K - j) * pad_hi
                L = m_lo + B + m_hi
                o0 = PL - m_lo  # static-slice offset for this level
                acc = jnp.zeros((L,), cur.dtype)
                for jo, o in enumerate(offs):
                    acc = acc + (
                        jax.lax.slice(w3s[jo], (o0,), (o0 + L,))
                        * jax.lax.slice(cur, (pad_lo + o,),
                                        (pad_lo + o + L,)))
                center = jax.lax.slice(cur, (pad_lo,), (pad_lo + L,))
                du = (jax.lax.slice(cs, (o0,), (o0 + L,))
                      * (acc - jax.lax.slice(wss, (o0,), (o0 + L,))
                         * center))
                if test:
                    du = du + source_at(
                        jax.lax.slice(gx_, (o0,), (o0 + L,)),
                        jax.lax.slice(lgx_, (o0,), (o0 + L,)),
                        t + (j - 1), dt)
                nxt = center + jnp.asarray(dt, cur.dtype) * du
                if j < K:
                    lidx = (gpos0 + o0) + jax.lax.iota(jnp.int32, L)
                    nxt = jnp.where((lidx >= 0) & (lidx < n), nxt,
                                    jnp.zeros_like(nxt))
                    nxt = jax.lax.optimization_barrier(nxt)
                cur = nxt
            return cur[None]

        p = P("p")
        n_args = 5 if test else 3
        sharded = shard_map(
            local_block, mesh=self.mesh,
            in_specs=(p,) * (1 + n_args) + (P(),), out_specs=p)

        def block_fn(u, t, args_):
            up = jnp.pad(u, (0, self.pad)).reshape(S, B)
            return sharded(up, *args_, t).reshape(S * B)[: n]

        return args, block_fn


class UnstructuredSolver(CheckpointMixin):
    """Forward-Euler solver on a point cloud, same contract as the grid
    solvers: ``test_init`` + ``do_work`` + ``error_l2/#points <= 1e-6``."""

    def __init__(self, op: UnstructuredNonlocalOp, nt: int, backend="jit",
                 layout: str = "auto",
                 checkpoint_path: str | None = None, ncheckpoint: int = 0,
                 superstep: int = 1):
        self.op = op
        self.nt = int(nt)
        self.backend = backend
        self.layout = layout
        self.checkpoint_path = checkpoint_path
        self.ncheckpoint = int(ncheckpoint)
        self.t0 = 0
        self.test = False
        self.u0 = np.zeros(op.n)
        self.u = None
        self.error_l2 = 0.0
        self.error_linf = 0.0
        # superstep K > 1: one (K*pad)-wide ring exchange per K steps on
        # the SHARDED offsets operator (ShardedUnstructuredOp
        # .make_superstep) — refuse anywhere the schedule cannot engage
        # rather than silently stepping one exchange at a time
        self.ksteps = max(1, int(superstep))
        if self.ksteps > 1:
            if backend != "jit" or getattr(op, "superstep_check",
                                           None) is None:
                raise ValueError(
                    "superstep > 1 needs the jit backend on a "
                    "ShardedUnstructuredOp (offsets layout)")
            op.superstep_check(self.ksteps)  # the shared fit refusal

    def _ckpt_params(self) -> dict:
        """Canonical params for the point cloud: eps is a per-point FIELD
        here, so record scalar invariants of it (mean + L2) rather than the
        grid mixin's single integer."""
        inner = getattr(self.op, "inner", self.op)  # unwrap Sharded
        return dict(
            shape=[int(inner.n)],
            eps=float(np.mean(inner.eps)),
            eps_l2=float(np.sum(inner.eps ** 2)),
            k=float(inner.k),
            dt=float(self.op.dt),
            test=bool(self.test),
        )

    @property
    def _grid_shape(self):
        return (getattr(self.op, "inner", self.op).n,)

    def test_init(self):
        self.test = True
        self.u0 = self.op.spatial_profile()

    def input_init(self, values):
        self.test = False
        self.u0 = np.asarray(values, np.float64).reshape(self.op.n)

    def do_work(self) -> np.ndarray:
        from nonlocalheatequation_tpu.ops.nonlocal_op import source_at

        g, lg = self.op.source_parts() if self.test else (None, None)
        op = self.op
        if self.backend == "oracle":
            u = self.u0.copy()
            for t in range(self.t0, self.nt):
                du = op.apply_np(u)
                if self.test:
                    du = du + source_at(g, lg, t, op.dt)
                u = u + op.dt * du
                self._maybe_checkpoint(t, u)
        else:
            test = self.test
            dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
            layout = self.layout
            if getattr(op, "choose_layout", None) is None:
                # sharded/wrapped operators own their layout; an explicit
                # request cannot apply, so fall back instead of TypeError-ing
                layout = "auto"
            elif layout == "auto":
                layout = op.choose_layout()
            # windowed fast path: the whole scan runs in Morton order (one
            # permute in, one un-permute out PER CHUNK, not per step), so
            # chunk-boundary state — checkpoints, logging — stays in the
            # original node order and resume is portable across layouts
            windowed = (layout == "windowed"
                        and getattr(op, "windowed_plan", None) is not None)
            if windowed:
                ex = op.windowed_plan().for_dtype(dtype)
            # a sharded operator exposes its device arrays so the jit'd
            # scan can take them as ARGUMENTS — a closure capture of
            # arrays spanning a cross-process mesh is rejected by JAX
            # (the grid solvers' sources-as-arguments rule)
            consts = (op.apply_args()
                      if getattr(op, "apply_args", None) is not None else ())
            multiproc = bool(consts) and jax.process_count() > 1
            if multiproc:
                from jax.sharding import NamedSharding, PartitionSpec

                rep = NamedSharding(op.mesh, PartitionSpec())
                place = lambda x: put_global(  # noqa: E731
                    np.asarray(x, np.dtype(dtype)), rep)
            if test:
                if windowed:
                    perm_np = np.asarray(ex.perm)
                    gd = jnp.asarray(g[perm_np], dtype)
                    lgd = jnp.asarray(lg[perm_np], dtype)
                elif multiproc:
                    gd, lgd = place(g), place(lg)
                else:
                    gd, lgd = jnp.asarray(g, dtype), jnp.asarray(lg, dtype)
            extras = (gd, lgd) if test else ()

            def step_with(u, t, consts, extras):
                if windowed:
                    du = ex.L_perm(u)
                elif consts:
                    du = op.apply_with(u, consts)
                elif layout == "auto":
                    du = op.apply(u)
                else:
                    du = op.apply(u, layout=layout)
                if test:
                    du = du + source_at(extras[0], extras[1], t, op.dt)
                return u + op.dt * du, None

            ss_args = ss_block = None
            if self.ksteps > 1:
                if not any(c >= self.ksteps
                           for _, c in self._ckpt_chunks()):
                    # every barrier segment is shorter than K: no K-block
                    # could ever form and the flag would silently run
                    # per-step — same honesty rule as the elastic gates
                    raise RuntimeError(
                        f"superstep {self.ksteps} cannot engage: every "
                        "segment between checkpoint barriers is shorter "
                        "than K (ncheckpoint/nt vs superstep); widen the "
                        "cadence or drop superstep")
                ss_args, ss_block = op.make_superstep(self.ksteps, dtype,
                                                      test)
            K = self.ksteps

            def make_runner(count):
                @jax.jit
                def run(u, t0, consts, extras, ss):
                    if windowed:
                        u = u[ex.perm]
                    nblocks = count // K if ss_block is not None else 0
                    if nblocks:
                        tb = t0 + K * jnp.arange(nblocks)
                        u = jax.lax.scan(
                            lambda c, t: (ss_block(c, t, ss), None),
                            u, tb)[0]
                    rem = count - nblocks * K
                    if rem:
                        ts = t0 + nblocks * K + jnp.arange(rem)
                        u = jax.lax.scan(
                            lambda c, t: step_with(c, t, consts, extras),
                            u, ts)[0]
                    if windowed:
                        u = u[ex.rank]
                    return u

                return lambda u, start: run(u, jnp.int32(start), consts,
                                            extras, ss_args)

            if multiproc:
                from nonlocalheatequation_tpu.parallel.multihost import (
                    fetch_global,
                )

                u = place(self.u0)
                to_host = fetch_global
            else:
                u = jnp.asarray(self.u0, dtype)
                to_host = np.asarray
            if self.checkpoint_path and self.ncheckpoint:
                u = np.asarray(to_host(self._run_chunked(u, make_runner)))
            else:
                u = np.asarray(to_host(
                    make_runner(self.nt - self.t0)(u, self.t0)))
        self.u = u
        if self.test:
            d = u - op.manufactured_solution(self.nt)
            self.error_l2 = float(np.sum(d * d))
            self.error_linf = float(np.max(np.abs(d))) if d.size else 0.0
        return u
