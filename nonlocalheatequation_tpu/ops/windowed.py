"""Windowed block-dense TPU path for the unstructured nonlocal operator.

The operator is L(u)_i = c_i * (sum_j w_ij u_j - wsum_i u_i) over radius
neighborhoods of an arbitrary point cloud — the unstructured generalization
of the reference's grid operator (math:
/root/reference/description/problem_description.tex:131-158; the reference
itself has no unstructured solver, this family is a framework extension).

ops/unstructured.py evaluates the neighbor sum either as an edge-list
``segment_sum`` or as an ELL-row gather; both lower to per-element gathers,
which TPUs execute far off the HBM roofline (measured 84.9 ms/step at 262k
nodes / kmax=45 in round 3 — four orders below the grid kernels).  This
module replaces the gather with a layout the hardware natively streams:

* nodes are reordered by a Morton (Z-order) curve over horizon-sized cells,
  so each run of ``bm`` consecutive rows draws its neighbors from a FEW
  short contiguous windows of the reordered state vector (quadrant jumps
  in the curve split a block's sources into clusters, so R windows of
  ``we`` columns each — R=2 by default — cover what one much wider window
  would: measured 768+768 ≈ one 4096-wide window on the shuffled bench
  cloud, ~2.7x less strip traffic);
* per row-block, the nonzero weights are scattered (once, on the host) into
  a dense ``(bm, R*we)`` strip P whose column groups align to the block's
  128-aligned per-window starts ``s128[b, r]``;
* the per-step kernel is then one ``pallas_call`` over row blocks: stream
  P from HBM (Mosaic double-buffers), dynamic-slice each u-window via its
  scalar-prefetched block index (``PrefetchScalarGridSpec``), and
  multiply-accumulate on the VPU — no gather instruction anywhere;
* edges that fall outside their block's best window (Morton boundary jumps,
  horizon outliers) go to a residual edge list evaluated with the original
  ``segment_sum`` path, so ANY ordering/horizon field stays exact — worst
  case degrades toward the old path instead of breaking.

Cost model: the step streams ``n_pad * W`` weights; with Morton ordering a
262k-node / kmax=45 cloud fits W≈512–1024, i.e. ~0.5–1.1 GB per step ≈
0.7–1.3 ms at v5e HBM bandwidth — vs 84.9 ms for the gather paths.
FLOPs (n*W madds) are ~100x below the VPU roofline at that traffic, so the
strip stream is the only cost that matters.

The reduction ORDER differs from the oracle (per-window accumulation), so
parity with ``apply_np`` is 1e-12-close in f64, not bit-identical — same
contract as the grid kernels' SAT/conv method family.

This module also carries the OFFSET (DIA) layout — the even faster sibling
for quasi-uniform clouds: when the index offsets ``src - tgt`` cluster on a
small set O (a jittered grid in its natural ordering keeps the circle
raster's ~|H_eps| offsets exactly — measured 45 distinct offsets at 262k
nodes / 7.7M edges), the operator is a sum of |O| diagonals:
``acc = sum_o W_o * shift(u, o)`` with dense per-offset weight vectors.
Shifted STATIC slices of a padded u — no gather, no permutation, no Pallas
even needed (XLA fuses the slice-multiply-add chain) — streaming |O|*n
weights per step (~47 MB vs the windowed path's gigabytes at the bench
scale).  Residual edges off the kept offsets use the same segment_sum
fallback, so any cloud stays exact; detection simply fails toward the
windowed/ELL paths when offsets don't cluster.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_kernel import _elem_spec, _kernel_params, _reject_f64_on_tpu

LANE = 128

# W escalation ladder (all multiples of LANE); stops at the first rung whose
# out-of-window residual is small enough
_W_LADDER = (128, 256, 384, 512, 768, 1024, 1536, 2048, 3072, 4096)


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def morton_perm(points: np.ndarray, cell: float) -> np.ndarray:
    """Stable Z-order permutation of points binned into ``cell``-sized cells.

    Generic in dimension: interleaves the cell-coordinate bits across dims
    (21 bits per dim — enough for any horizon field with n < 2^63 cells).
    Within a cell the original order is kept (stable sort).
    """
    pts = np.asarray(points, np.float64)
    if pts.shape[0] == 0:  # pts.min() on an empty axis raises
        return np.zeros(0, np.int64)
    cells = np.floor((pts - pts.min(axis=0)) / float(cell)).astype(np.uint64)
    n, d = cells.shape
    bits = min(21, 63 // max(d, 1))
    key = np.zeros(n, np.uint64)
    for b in range(bits):
        for j in range(d):
            key |= ((cells[:, j] >> np.uint64(b)) & np.uint64(1)) << np.uint64(
                b * d + j
            )
    return np.argsort(key, kind="stable")


class _WindowedExec:
    """Per-dtype device arrays + the compiled matvec for one plan."""

    def __init__(self, plan: "WindowedPlan", dtype):
        self.dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(dtype))
        self.n = plan.n
        self.n_pad = plan.n_pad
        self.W = plan.W
        self.u_rows = (plan.n_pad + plan.we) // LANE
        self.perm = jnp.asarray(plan.perm)
        self.rank = jnp.asarray(plan.rank)
        self.P = jnp.asarray(plan.P, self.dtype)
        self.s128 = jnp.asarray(plan.s128)
        self.c_p = jnp.asarray(plan.c_p, self.dtype)
        self.wsum_p = jnp.asarray(plan.wsum_p, self.dtype)
        self.ov_tgt = jnp.asarray(plan.ov_tgt)
        self.ov_src = jnp.asarray(plan.ov_src)
        self.ov_w = jnp.asarray(plan.ov_w, self.dtype)
        self.has_overflow = plan.ov_tgt.size > 0
        self._matvec = _build_windowed_matvec(
            plan.nb, plan.bm, plan.we, plan.R, self.u_rows, self.dtype.name
        )

    def neighbor_sum_perm(self, u_perm: jnp.ndarray) -> jnp.ndarray:
        """sum_j w_ij u_j in Morton order (targets AND sources permuted)."""
        u_pad = jnp.pad(u_perm, (0, self.u_rows * LANE - self.n))
        acc = self._matvec(self.s128, self.P, u_pad.reshape(self.u_rows, LANE))
        acc = acc[: self.n, 0]
        if self.has_overflow:
            acc = acc + jax.ops.segment_sum(
                self.ov_w * u_perm[self.ov_src],
                self.ov_tgt,
                num_segments=self.n,
            )
        return acc

    def L_perm(self, u_perm: jnp.ndarray) -> jnp.ndarray:
        """The full operator in Morton order."""
        return self.c_p * (
            self.neighbor_sum_perm(u_perm) - self.wsum_p * u_perm
        )

    def L(self, u: jnp.ndarray) -> jnp.ndarray:
        """Original-order contract: permute in, invert out."""
        return self.L_perm(u[self.perm])[self.rank]


@functools.lru_cache(maxsize=None)
def _build_windowed_matvec(nb: int, bm: int, we: int, R: int, u_rows: int,
                           dtype_name: str):
    """One grid step per row block: out[b*bm:(b+1)*bm] = sum over the
    block's R windows r of P_b[:, r*we:(r+1)*we] @ u[s_br : s_br+we].

    Each of the R windows moves by its own scalar-prefetched per-block
    offset (s128[b, r], in 128-row units of the (u_rows, 128) state
    layout) — the same u array is passed R times so every window gets its
    own BlockSpec; P streams block-by-block; the product runs as we/128
    lane-chunks of elementwise multiply-accumulate plus one final lane
    reduction — VPU only, no gathers, no relayouts.  Multiple windows
    exist because Morton-curve quadrant jumps split a block's sources
    into a few clusters: two 768-wide windows cover what one 4096-wide
    window does (measured on the shuffled 512^2 bench cloud), at ~2.7x
    less strip traffic.
    """
    dtype = jnp.dtype(dtype_name)
    _reject_f64_on_tpu(dtype)

    def kernel(s_ref, p_ref, *u_and_out):
        del s_ref  # consumed by the index maps
        u_refs, out_ref = u_and_out[:-1], u_and_out[-1]
        acc = None
        col = 0
        for u_ref in u_refs:
            for c in range(we // LANE):
                term = p_ref[:, col:col + LANE] * u_ref[c, :][None, :]
                acc = term if acc is None else acc + term
                col += LANE
        out_ref[:] = jnp.sum(acc, axis=1, keepdims=True).astype(dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb,),
        in_specs=[
            _elem_spec((bm, R * we), lambda i, s: (i * bm, 0), pltpu.VMEM),
        ] + [
            _elem_spec((we // LANE, LANE),
                       lambda i, s, r=r: (s[i, r], 0), pltpu.VMEM)
            for r in range(R)
        ],
        out_specs=_elem_spec((bm, 1), lambda i, s: (i * bm, 0), pltpu.VMEM),
    )

    def matvec(s128, P, u2d):
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((nb * bm, 1), dtype),
            **_kernel_params(),
        )(s128, P, *([u2d] * R))

    return matvec


class WindowedPlan:
    """Host-side product of :func:`build_plan`; hands out per-dtype execs.

    ``W`` is the TOTAL strip width (R windows of ``we`` columns each);
    ``s128[b, r]`` is window r's start for block b in 128-row units."""

    def __init__(self, *, n, n_pad, bm, we, R, nb, perm, rank, s128, P,
                 ov_tgt, ov_src, ov_w, c_p, wsum_p, coverage):
        self.n, self.n_pad, self.bm, self.nb = n, n_pad, bm, nb
        self.we, self.R, self.W = we, R, R * we
        self.perm, self.rank, self.s128, self.P = perm, rank, s128, P
        self.ov_tgt, self.ov_src, self.ov_w = ov_tgt, ov_src, ov_w
        self.c_p, self.wsum_p = c_p, wsum_p
        self.coverage = coverage  # fraction of edges inside windows
        self._execs: dict = {}

    @property
    def p_bytes_f32(self) -> int:
        return self.P.size * 4

    def for_dtype(self, dtype) -> _WindowedExec:
        key = jnp.dtype(dtype).name
        if key not in self._execs:
            self._execs[key] = _WindowedExec(self, dtype)
        return self._execs[key]


def _plan_search(points, eps, tgt, src, edge_w, *, bm, wmax,
                 max_overflow_frac, order, windows):
    """The permutation + per-block column sets + W-ladder search shared by
    :func:`build_plan` and :func:`plan_stats`.  Everything here is
    O(E log E) host work with O(E) allocations — the dense strips are NOT
    materialized (the point: worthwhileness gates must be able to reject
    a plan without paying its memory)."""
    points = np.asarray(points, np.float64)
    n = points.shape[0]
    tgt = np.asarray(tgt, np.int64)
    src = np.asarray(src, np.int64)
    edge_w = np.asarray(edge_w, np.float64)
    if order == "morton":
        cell = float(np.max(np.broadcast_to(np.asarray(eps, np.float64),
                                            (n,)))) if n else 1.0
        perm = morton_perm(points, max(cell, np.finfo(np.float64).tiny))
    elif order == "keep":
        perm = np.arange(n)
    else:
        raise ValueError(f"unknown order {order!r}")
    rank = np.empty(n, np.int64)
    rank[perm] = np.arange(n)

    n_pad = max(_round_up(n, bm), bm)
    nb = n_pad // bm

    tgt_p = rank[tgt]
    src_p = rank[src]
    order_e = np.argsort(tgt_p, kind="stable")
    tgt_s, src_s, w_s = tgt_p[order_e], src_p[order_e], edge_w[order_e]
    blk = tgt_s // bm
    blk_bounds = np.searchsorted(blk, np.arange(nb + 1))
    cols_by_blk = [
        np.sort(src_s[blk_bounds[b]:blk_bounds[b + 1]]) for b in range(nb)
    ]

    total = len(tgt_s)
    wmax = min(_round_up(max(wmax, LANE), LANE), max(n_pad, LANE))
    # R windows of we columns each, total width R*we <= wmax; quadrant
    # jumps in the Morton curve split a block's sources into a few
    # clusters, so two modest windows cover what one huge one does
    R = max(1, min(int(windows), wmax // LANE))
    ladder = [w for w in _W_LADDER if R * w <= wmax]
    top = wmax // R // LANE * LANE
    if not ladder or top > ladder[-1]:
        ladder.append(top)

    def solve_starts(we):
        """Greedy per block: best window, then best window of the rest."""
        s128 = np.zeros((nb, R), np.int32)
        covered = 0
        for b, cols in enumerate(cols_by_blk):
            rest = cols
            for r in range(R):
                if rest.size == 0:
                    break
                cand = np.unique(rest // LANE) * LANE
                hi = np.searchsorted(rest, cand + we, side="left")
                lo = np.searchsorted(rest, cand, side="left")
                best = int(np.argmax(hi - lo))
                s = int(cand[best])
                s128[b, r] = s // LANE
                covered += int(hi[best] - lo[best])
                rest = rest[(rest < s) | (rest >= s + we)]
        return s128, covered

    for cand_w in ladder:
        s128, covered = solve_starts(cand_w)
        we = cand_w
        if total == 0 or (total - covered) <= max_overflow_frac * total:
            break

    return dict(n=n, n_pad=n_pad, nb=nb, R=R, we=we, perm=perm, rank=rank,
                tgt_s=tgt_s, src_s=src_s, w_s=w_s, blk=blk, s128=s128,
                covered=covered, total=total)


def plan_stats(points, eps, tgt, src, *, bm: int = LANE, wmax: int = 4096,
               max_overflow_frac: float = 0.02, order: str = "morton",
               windows: int = 2):
    """Cheap precheck for the windowed layout: ``(coverage, p_bytes_f32)``
    of the plan :func:`build_plan` would produce under the same parameters,
    WITHOUT materializing the dense strips — the :func:`offset_stats`
    analog for this layout, so the auto policy can reject an over-budget
    plan before allocating it (a large low-locality cloud escalated to the
    top ladder rung would otherwise transiently allocate multi-GB of host
    memory only to be refused)."""
    sr = _plan_search(points, eps, tgt, src,
                      np.zeros(np.asarray(tgt).shape[0], np.float64),
                      bm=bm, wmax=wmax, max_overflow_frac=max_overflow_frac,
                      order=order, windows=windows)
    coverage = 1.0 if sr["total"] == 0 else sr["covered"] / sr["total"]
    return coverage, sr["n_pad"] * sr["R"] * sr["we"] * 4


def build_plan(points, eps, tgt, src, edge_w, c, wsum, *, bm: int = LANE,
               wmax: int = 4096, max_overflow_frac: float = 0.02,
               order: str = "morton", windows: int = 2,
               search=None) -> WindowedPlan:
    """Build the windowed layout for an edge set.

    ``order="morton"`` reorders nodes along a Z-curve over eps.max()-sized
    cells (the locality the windows rely on); ``order="keep"`` trusts the
    caller's ordering.  W walks the ladder until the residual edge fraction
    drops under ``max_overflow_frac`` (or the ladder ends — the plan is
    still exact then, just with a larger residual; callers judge
    worthwhileness via ``plan.coverage``).  ``search`` accepts a
    precomputed :func:`_plan_search` result (run with the SAME inputs and
    the real ``edge_w``) so a worthwhileness gate that already paid the
    O(E log E) search doesn't pay it twice on the accept path.
    """
    sr = search if search is not None else _plan_search(
        points, eps, tgt, src, edge_w, bm=bm, wmax=wmax,
        max_overflow_frac=max_overflow_frac, order=order, windows=windows)
    n, n_pad, nb, R, we = sr["n"], sr["n_pad"], sr["nb"], sr["R"], sr["we"]
    perm, rank = sr["perm"], sr["rank"]
    tgt_s, src_s, w_s = sr["tgt_s"], sr["src_s"], sr["w_s"]
    blk, s128 = sr["blk"], sr["s128"]
    covered, total = sr["covered"], sr["total"]

    # dense strips; every edge lands in the FIRST window that contains it
    # (windows of one block may overlap — the assigned mask keeps each
    # edge's weight in exactly one column).  Direct assignment is valid
    # because (tgt, src) pairs are unique by construction of build_edges —
    # verified here, with a scatter-add fallback just in case a caller
    # hands in duplicates
    P = np.zeros((n_pad, R * we), np.float64)
    pair_keys = tgt_s * np.int64(n_pad) + src_s
    unique_pairs = len(pair_keys) == len(np.unique(pair_keys))
    assigned = np.zeros(total, bool)
    for r in range(R):
        off = src_s - s128[blk, r].astype(np.int64) * LANE
        in_r = (off >= 0) & (off < we) & ~assigned
        if unique_pairs:
            P[tgt_s[in_r], r * we + off[in_r]] = w_s[in_r]
        else:  # pragma: no cover - build_edges never produces duplicates
            np.add.at(P, (tgt_s[in_r], r * we + off[in_r]), w_s[in_r])
        assigned |= in_r
    ov = ~assigned

    c_p = np.asarray(c, np.float64)[perm]
    wsum_p = np.asarray(wsum, np.float64)[perm]
    return WindowedPlan(
        n=n, n_pad=n_pad, bm=bm, we=we, R=R, nb=nb, perm=perm, rank=rank,
        s128=s128, P=P,
        ov_tgt=tgt_s[ov].astype(np.int32), ov_src=src_s[ov].astype(np.int32),
        ov_w=w_s[ov],
        c_p=c_p, wsum_p=wsum_p,
        coverage=1.0 if total == 0 else covered / total,
    )


# --------------------------------------------------------------------------
# Offset (DIA) layout
# --------------------------------------------------------------------------


class _OffsetExec:
    """Per-dtype device arrays for one :class:`OffsetPlan`."""

    def __init__(self, plan: "OffsetPlan", dtype):
        self.dtype = jax.dtypes.canonicalize_dtype(jnp.dtype(dtype))
        self.n = plan.n
        self.offs = plan.offs
        self.pad_lo, self.pad_hi = plan.pad_lo, plan.pad_hi
        self.W = jnp.asarray(plan.W, self.dtype)
        self.c = jnp.asarray(plan.c, self.dtype)
        self.wsum = jnp.asarray(plan.wsum, self.dtype)
        self.ov_tgt = jnp.asarray(plan.ov_tgt)
        self.ov_src = jnp.asarray(plan.ov_src)
        self.ov_w = jnp.asarray(plan.ov_w, self.dtype)
        self.has_overflow = plan.ov_tgt.size > 0

    def neighbor_sum(self, u: jnp.ndarray) -> jnp.ndarray:
        """sum_j w_ij u_j as a static-slice diagonal sum (original order)."""
        up = jnp.pad(u, (self.pad_lo, self.pad_hi))
        acc = jnp.zeros_like(u)
        for j, o in enumerate(self.offs):
            start = self.pad_lo + o
            acc = acc + self.W[j] * jax.lax.slice(up, (start,),
                                                  (start + self.n,))
        if self.has_overflow:
            acc = acc + jax.ops.segment_sum(
                self.ov_w * u[self.ov_src], self.ov_tgt,
                num_segments=self.n,
            )
        return acc

    def L(self, u: jnp.ndarray) -> jnp.ndarray:
        return self.c * (self.neighbor_sum(u) - self.wsum * u)


class OffsetPlan:
    """Host-side product of :func:`build_offset_plan`."""

    def __init__(self, *, n, offs, W, pad_lo, pad_hi, ov_tgt, ov_src, ov_w,
                 c, wsum, coverage):
        self.n, self.offs, self.W = n, offs, W
        self.pad_lo, self.pad_hi = pad_lo, pad_hi
        self.ov_tgt, self.ov_src, self.ov_w = ov_tgt, ov_src, ov_w
        self.c, self.wsum = c, wsum
        self.coverage = coverage
        self._execs: dict = {}

    @property
    def w_bytes_f32(self) -> int:
        return self.W.size * 4

    def for_dtype(self, dtype) -> _OffsetExec:
        key = jnp.dtype(dtype).name
        if key not in self._execs:
            self._execs[key] = _OffsetExec(self, dtype)
        return self._execs[key]


def offset_stats(tgt, src, n, *, max_offsets: int = 256,
                 coverage_target: float = 1.0):
    """Cheap precheck for the offset layout: (coverage, kept_offsets,
    w_bytes_f32) WITHOUT materializing the dense diagonals — worthwhileness
    gates can reject a layout without paying its memory."""
    tgt = np.asarray(tgt, np.int64)
    src = np.asarray(src, np.int64)
    E = len(tgt)
    if E == 0:
        return 1.0, 0, 0
    vals, counts = np.unique(src - tgt, return_counts=True)
    order = np.argsort(-counts, kind="stable")
    csum = np.cumsum(counts[order]) / E
    keep_n = int(np.searchsorted(csum, coverage_target - 1e-15) + 1)
    keep_n = min(keep_n, max_offsets, len(vals))
    coverage = float(csum[keep_n - 1]) if keep_n else 0.0
    return coverage, keep_n, keep_n * n * 4


def build_offset_plan(tgt, src, edge_w, c, wsum, n, *,
                      max_offsets: int = 256,
                      coverage_target: float = 1.0) -> OffsetPlan:
    """Detect the dominant index offsets and lay their weights out as dense
    diagonals.  Offsets are kept most-common-first until ``coverage_target``
    of the edges is reached or ``max_offsets`` is hit; the rest go to the
    residual edge list.  No reordering: the caller's node order IS the
    structure this layout exploits."""
    tgt = np.asarray(tgt, np.int64)
    src = np.asarray(src, np.int64)
    edge_w = np.asarray(edge_w, np.float64)
    E = len(tgt)
    off = src - tgt
    vals, counts = (np.unique(off, return_counts=True) if E
                    else (np.zeros(0, np.int64), np.zeros(0, np.int64)))
    order = np.argsort(-counts, kind="stable")
    keep_n = len(vals)
    if E:
        csum = np.cumsum(counts[order]) / E
        keep_n = int(np.searchsorted(csum, coverage_target - 1e-15) + 1)
    keep_n = min(keep_n, max_offsets, len(vals))
    kept = np.sort(vals[order[:keep_n]])
    slot = np.searchsorted(kept, off)
    slot_ok = (slot < len(kept))
    inw = slot_ok & (kept[np.minimum(slot, max(len(kept) - 1, 0))] == off) \
        if len(kept) else np.zeros(E, bool)
    W = np.zeros((len(kept), n), np.float64)
    # (tgt, off) pairs are unique exactly when (tgt, src) pairs are —
    # verified, with a scatter-add fallback for callers that hand in
    # duplicate edges (same contract as the windowed strips' build)
    pair_keys = tgt * np.int64(max(n, 1)) + src
    if len(pair_keys) == len(np.unique(pair_keys)):
        W[slot[inw], tgt[inw]] = edge_w[inw]
    else:
        np.add.at(W, (slot[inw], tgt[inw]), edge_w[inw])
    ov = ~inw
    offs = tuple(int(o) for o in kept)
    return OffsetPlan(
        n=n, offs=offs, W=W,
        pad_lo=max(0, -min(offs)) if offs else 0,
        pad_hi=max(0, max(offs)) if offs else 0,
        ov_tgt=tgt[ov].astype(np.int32), ov_src=src[ov].astype(np.int32),
        ov_w=edge_w[ov],
        c=np.asarray(c, np.float64), wsum=np.asarray(wsum, np.float64),
        coverage=1.0 if E == 0 else float(inw.sum()) / E,
    )
