from nonlocalheatequation_tpu.ops.constants import c_1d, c_2d, c_3d  # noqa: F401
from nonlocalheatequation_tpu.ops.stencil import (  # noqa: F401
    column_half_heights,
    horizon_mask_1d,
    horizon_mask_2d,
    horizon_mask_3d,
)
from nonlocalheatequation_tpu.ops.nonlocal_op import (  # noqa: F401
    NonlocalOp1D,
    NonlocalOp2D,
)
