"""The nonlocal horizon operator — the framework's hot op.

Semantics (matching the reference exactly, SURVEY.md section 0):

    L(u)[p] = c * h^d * ( sum_{o in mask} J(o) * ubar[p+o]  -  Wsum * u[p] )

where ``ubar`` is u extended by 0 outside the domain (volumetric boundary
condition: reference boundary() returns 0 out of range,
src/2d_nonlocal_serial.cpp:213-221), ``mask`` is the rasterized eps-ball
(ops/stencil.py), J the influence function (J==1 in the reference) and
``Wsum = sum_o J(o)`` (the center point counts).  Forward Euler:

    u^{t+1} = u^t + dt * ( L(u^t) + b_t )        (src/2d_nonlocal_serial.cpp:281-283)

The manufactured-solution source used by every reference test
(src/2d_nonlocal_serial.cpp:235-252) factors as

    b_t = -2*pi*sin(2*pi*t*dt) * G  -  cos(2*pi*t*dt) * L(G)

with G the spatial product sin(2*pi*x*dh) [* sin(2*pi*y*dh)], because
w(t,p) = cos(2*pi*t*dt)*G[p] is separable in time.  We precompute G and L(G)
once instead of re-rasterizing the horizon per point per step — same math,
O(1) extra arrays, and the whole timestep becomes one fused XLA program.

Three interchangeable evaluation strategies for the neighbor sum (all
identical up to float addition order):

* ``shift`` — one padded slice-add per mask offset.  Reference-closest; great
  for oracles and small eps.
* ``conv``  — ``lax.conv_general_dilated`` with the 0/1 (or J-weighted) mask
  as kernel.  XLA lowers this well on TPU.
* ``sat``   — per-column running-sum trick: cumsum along y once, then one
  subtraction per x-offset: O(eps) instead of O(eps^2) work per point.  This
  is the TPU-first formulation (the circle raster is exactly a set of
  variable-width column windows).  Caveat: prefix-sum differencing carries
  absolute error that grows with the cumsum magnitude (~ny*|u|), so in f32 on
  long axes it is less accurate than conv/shift; use it in f64, or tiled
  (Pallas) where the running sum spans one tile.

``shift`` and ``conv`` are identical up to float addition order; ``sat``
additionally reassociates across the whole column (see caveat above).

Precision tiers (``precision=`` on the 1D/2D/3D ops; ops/constants.py):
``"f32"`` (default) changes nothing — the pre-tier programs are produced
bit for bit.  ``"bf16"`` evaluates every neighbor sum AND the matching
``Wsum * u`` center term on the bfloat16 ROUNDING of the state (operand
windows at half the bytes on the bandwidth-bound kernels), accumulated in
the state dtype, while the forward-Euler carry ``u + dt * du`` stays in
the state dtype — mixed precision with an f32 master.  ``resync_every=R``
runs every R-th step's operator on the unrounded state (a full-precision
step) to bound operand-rounding drift.  The tier holds a measured
accuracy contract (constants.BF16_L2_BUDGET, tests/test_precision_tier),
not the f32 paths' 1e-12 oracle parity — bf16 rounding of ``u`` makes
that bar unreachable by construction, and we say so rather than fake it.
"""

from __future__ import annotations

import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from nonlocalheatequation_tpu.ops.constants import (
    c_1d,
    c_2d,
    c_3d,
    validate_precision,
)
from nonlocalheatequation_tpu.ops.stencil import (
    column_half_heights,
    horizon_mask_1d,
    horizon_mask_2d,
    horizon_mask_3d,
    influence_weights,
)

TWO_PI = 2.0 * np.pi


def _bf16_round(x: jnp.ndarray) -> jnp.ndarray:
    """bf16 storage rounding, upcast back to the accumulate dtype.

    The round-trip IS the tier's semantic: values pass through bfloat16
    (8-bit mantissa) exactly once, then every add runs in the original
    (>= f32) dtype.  On TPU the compiled kernels read genuinely-bf16
    operands instead; this form is the backend-independent reference the
    CPU suite pins them against."""
    return x.astype(jnp.bfloat16).astype(x.dtype)


class _PrecisionPolicy:
    """Shared precision-tier plumbing for the grid operators.

    Sets ``self.precision`` / ``self.resync_every`` (validated) and
    provides ``_operand`` — the tier's state-operand transform, applied to
    every neighbor-sum input and center term so the operator stays
    internally consistent (L(const) == 0 exactly in any tier).
    """

    def _init_precision(self, precision: str, resync_every: int) -> None:
        self.precision = validate_precision(precision)
        self.resync_every = int(resync_every)
        if self.resync_every < 0:
            raise ValueError(f"resync_every must be >= 0, got {resync_every}")
        if self.resync_every and self.precision == "f32":
            raise ValueError(
                "resync_every is a bf16-tier knob; precision='f32' already "
                "evaluates every step at full precision"
            )

    def _operand(self, x: jnp.ndarray) -> jnp.ndarray:
        return _bf16_round(x) if self.precision == "bf16" else x


class NonlocalOp1D(_PrecisionPolicy):
    """1D horizon operator (reference: src/1d_nonlocal_serial.cpp:198-206).

    ``method``: ``shift`` (default — the reference-shaped slice-add loop)
    or ``fft`` (the circulant spectral apply, ops/spectral.py: O(N log N)
    and eps-independent, exact for the volumetric boundary by the padded
    collar embedding; <= 1e-12 of the shift path, not bit-identical —
    the FFT reassociates every sum)."""

    def __init__(self, eps: int, k: float, dt: float, dx: float, influence=None,
                 method: str = "shift",
                 precision: str = "f32", resync_every: int = 0):
        self.eps = int(eps)
        self.k = float(k)
        self.dt = float(dt)
        self.dx = float(dx)
        self.c = c_1d(k, eps, dx)
        self.weights = influence_weights(horizon_mask_1d(self.eps), influence, dx)
        self.wsum = float(self.weights.sum())
        self._influence = influence
        self.uniform = influence is None
        self.method = method
        self._init_precision(precision, resync_every)

    def with_method(self, method: str) -> "NonlocalOp1D":
        """Twin operator differing only in evaluation method (the
        autotuner's stencil<->fft crossover probe builds these)."""
        return NonlocalOp1D(
            self.eps, self.k, self.dt, self.dx, influence=self._influence,
            method=method, precision=self.precision,
            resync_every=self.resync_every)

    # -- neighbor sum -------------------------------------------------------
    def neighbor_sum_np(self, u: np.ndarray) -> np.ndarray:
        nx = u.shape[0]
        up = np.zeros(nx + 2 * self.eps, dtype=u.dtype)
        up[self.eps : self.eps + nx] = u
        acc = np.zeros_like(u)
        for o in range(2 * self.eps + 1):
            w = self.weights[o]
            if w:
                acc += w * up[o : o + nx]
        return acc

    def neighbor_sum(self, u: jnp.ndarray) -> jnp.ndarray:
        if self.method == "fft":
            from nonlocalheatequation_tpu.ops import spectral

            return spectral.neighbor_sum_fft(self, self._operand(u))
        up = self._operand(jnp.pad(u, (self.eps, self.eps)))
        nx = u.shape[0]
        acc = jnp.zeros_like(u)
        for o in range(2 * self.eps + 1):
            w = float(self.weights[o])
            if w:
                acc = acc + w * lax.slice(up, (o,), (o + nx,))
        return acc

    # -- operator and source ------------------------------------------------
    def apply_np(self, u: np.ndarray) -> np.ndarray:
        return self.c * self.dx * (self.neighbor_sum_np(u) - self.wsum * u)

    def apply(self, u: jnp.ndarray) -> jnp.ndarray:
        return self.c * self.dx * (
            self.neighbor_sum(u) - self.wsum * self._operand(u)
        )

    def spatial_profile(self, nx: int, x0: int = 0) -> np.ndarray:
        """G[x] = sin(2*pi*(x*dx)) for global positions x0..x0+nx."""
        x = np.arange(x0, x0 + nx, dtype=np.float64)
        return np.sin(TWO_PI * (x * self.dx))

    def source_parts(self, nx: int):
        """(G, L(G)) for the manufactured source (1d_nonlocal_serial.cpp:186-195)."""
        g = self.spatial_profile(nx)
        return g, self.apply_np(g)

    def manufactured_solution(self, nx: int, t: int) -> np.ndarray:
        return np.cos(TWO_PI * (t * self.dt)) * self.spatial_profile(nx)


def _auto_method(dtype, backend, off_tpu_method: str, probe_fits) -> str:
    """Shared 'auto' policy: Pallas on TPU when the shape fits its VMEM
    budget AND the dtype is f32 (Mosaic has no f64 vector ops; the XLA
    methods run f64 via emulation); 'sat' as the TPU fallback; the given
    CPU-fast method off-TPU (pallas would run interpreted there)."""
    if backend is None:
        backend = jax.default_backend()
    if backend != "tpu":
        return off_tpu_method
    if dtype.itemsize == 8:
        return "sat"
    try:
        probe_fits()
        return "pallas"
    except ValueError:  # shape exceeds the kernel's VMEM budget
        return "sat"


def _auto_method_2d(eps: int, nx: int, ny: int, dtype, backend=None) -> str:
    from nonlocalheatequation_tpu.ops.pallas_kernel import _choose_tm

    # n_aux=2: one resolution covers BOTH consumers of the choice — the bare
    # neighbor-sum kernel (n_aux=0) and the fused test-source step kernel
    # (n_aux=2, engaged by make_step_fn under auto) — so probe the larger
    # footprint; near-budget shapes lose pallas rather than risk a mid-run
    # ValueError from the fused path
    return _auto_method(
        dtype, backend, "conv",
        lambda: _choose_tm(nx, ny, eps, dtype.itemsize, n_aux=2),
    )


def _auto_method_3d(eps: int, nx: int, ny: int, nz: int, dtype, backend=None) -> str:
    from nonlocalheatequation_tpu.ops.pallas_kernel import _choose_tiles_3d

    return _auto_method(
        dtype, backend, "sat",
        lambda: _choose_tiles_3d(nx, ny, nz, eps, dtype.itemsize),
    )


class NonlocalOp2D(_PrecisionPolicy):
    """2D horizon operator (reference: src/2d_nonlocal_serial.cpp:256-270).

    Arrays are indexed [x, y] with shape (nx, ny), mirroring the reference's
    sx/sy loop order.
    """

    def __init__(
        self,
        eps: int,
        k: float,
        dt: float,
        dh: float,
        influence=None,
        method: str = "conv",
        precision: str = "f32",
        resync_every: int = 0,
    ):
        self.eps = int(eps)
        self.k = float(k)
        self.dt = float(dt)
        self.dh = float(dh)
        self.c = c_2d(k, eps, dh)
        self.mask = horizon_mask_2d(self.eps)
        self._influence = influence  # kept so with_precision can rebuild
        self.weights = influence_weights(self.mask, influence, dh)
        self.wsum = float(self.weights.sum())
        self.uniform = influence is None  # J == 1: sat/pallas paths are valid
        if method in ("sat", "pallas", "auto") and not self.uniform:
            method = "conv"
        # fft needs no uniformity demotion: a weighted J still yields a
        # fixed per-offset weight set, i.e. still a convolution — the
        # symbol simply bakes the weights (ops/spectral.py)
        self.method = method
        self._init_precision(precision, resync_every)
        self._auto_cache: dict = {}

    def with_precision(self, precision: str, resync_every: int = 0
                       ) -> "NonlocalOp2D":
        """Twin operator differing only in precision tier (autotune's
        precision dimension and the resync full-precision step use it)."""
        return NonlocalOp2D(
            self.eps, self.k, self.dt, self.dh, influence=self._influence,
            method=self.method, precision=precision,
            resync_every=resync_every)

    def with_method(self, method: str) -> "NonlocalOp2D":
        """Twin operator differing only in evaluation method (the
        autotuner's stencil<->fft crossover probe builds these)."""
        return NonlocalOp2D(
            self.eps, self.k, self.dt, self.dh, influence=self._influence,
            method=method, precision=self.precision,
            resync_every=self.resync_every)

    def _resolve_method(self, nx: int, ny: int, dtype) -> str:
        """Concrete method for this (shape, dtype); 'auto' picks per backend:
        the Pallas kernel on TPU when the shape fits its VMEM budget and the
        dtype is f32 (Mosaic is f32-only), the f64-capable 'sat' otherwise,
        and 'conv' off-TPU (pallas would run interpreted; conv is the fast
        CPU lowering)."""
        if self.method != "auto":
            return self.method
        key = (nx, ny, jnp.dtype(dtype).name)
        m = self._auto_cache.get(key)
        if m is None:
            m = _auto_method_2d(self.eps, nx, ny, jnp.dtype(dtype))
            self._auto_cache[key] = m
        return m

    # -- neighbor sum -------------------------------------------------------
    def neighbor_sum_np(self, u: np.ndarray) -> np.ndarray:
        """Oracle path: per-offset shifted adds over the masked circle."""
        nx, ny = u.shape
        e = self.eps
        up = np.zeros((nx + 2 * e, ny + 2 * e), dtype=u.dtype)
        up[e : e + nx, e : e + ny] = u
        acc = np.zeros_like(u)
        heights = column_half_heights(e)
        for i in range(2 * e + 1):
            h = int(heights[i])
            for j in range(e - h, e + h + 1):
                w = self.weights[i, j]
                if w == 1.0:
                    acc += up[i : i + nx, j : j + ny]
                elif w:
                    acc += w * up[i : i + nx, j : j + ny]
        return acc

    def neighbor_sum(self, u: jnp.ndarray) -> jnp.ndarray:
        if self.method == "fft":
            from nonlocalheatequation_tpu.ops import spectral

            return spectral.neighbor_sum_fft(self, self._operand(u))
        e = self.eps
        return self.neighbor_sum_padded(jnp.pad(u, ((e, e), (e, e))))

    def neighbor_sum_padded(self, upad: jnp.ndarray) -> jnp.ndarray:
        """Valid-mode neighbor sum on a pre-padded block.

        ``upad`` is (nx+2*eps, ny+2*eps) — the block plus its halo, which the
        distributed path fills via collectives (zeros at the global edge).
        Returns the (nx, ny) sum.
        """
        if self.method == "fft":
            # honesty refusal: the spectral embedding is exact only when
            # the collar is genuinely zero; a distributed block's halo
            # carries neighbor data (ops/spectral.py docstring), so the
            # padded entry points never serve fft
            raise ValueError(
                "method='fft' serves whole-domain (volumetric-collar) "
                "solves only; halo-padded block evaluation (distributed/"
                "fused-comm paths) needs pallas/sat/conv/shift")
        e = self.eps
        method = self._resolve_method(
            upad.shape[0] - 2 * e, upad.shape[1] - 2 * e, upad.dtype
        )
        if method == "conv":
            return self._neighbor_sum_conv(upad)
        if method == "sat":
            return self._neighbor_sum_sat(upad)
        if method == "pallas":
            return self._neighbor_sum_pallas(upad)
        return self._neighbor_sum_shift(upad)

    def _neighbor_sum_conv(self, upad: jnp.ndarray) -> jnp.ndarray:
        if self.precision == "bf16" and self.uniform and \
                upad.dtype == jnp.float32:
            # genuine mixed-precision conv: bf16 operand and 0/1 mask (both
            # exact in bf16) accumulated in f32 via preferred_element_type —
            # the MXU/VPU-native shape of the tier
            out = lax.conv_general_dilated(
                upad.astype(jnp.bfloat16)[None, None],
                jnp.asarray(self.weights, jnp.bfloat16)[None, None],
                window_strides=(1, 1),
                padding="VALID",
                preferred_element_type=jnp.float32,
            )
            return out[0, 0]
        # general form: round the STATE operand only (weighted J masks keep
        # their full-precision weights — the tier rounds u, not the physics)
        upad = self._operand(upad)
        kern = jnp.asarray(self.weights, dtype=upad.dtype)[None, None]
        out = lax.conv_general_dilated(
            upad[None, None],
            kern,
            window_strides=(1, 1),
            padding="VALID",
        )
        return out[0, 0]

    def _neighbor_sum_shift(self, upad: jnp.ndarray) -> jnp.ndarray:
        e = self.eps
        upad = self._operand(upad)
        nx, ny = upad.shape[0] - 2 * e, upad.shape[1] - 2 * e
        acc = jnp.zeros((nx, ny), upad.dtype)
        heights = column_half_heights(e)
        for i in range(2 * e + 1):
            h = int(heights[i])
            for j in range(e - h, e + h + 1):
                w = float(self.weights[i, j])
                if w:
                    term = lax.slice(upad, (i, j), (i + nx, j + ny))
                    acc = acc + (term if w == 1.0 else w * term)
        return acc

    def _neighbor_sum_pallas(self, upad: jnp.ndarray) -> jnp.ndarray:
        """Pallas TPU strip kernel (ops/pallas_kernel.py); interpret on CPU."""
        from nonlocalheatequation_tpu.ops.pallas_kernel import build_neighbor_sum_2d

        e = self.eps
        nx, ny = upad.shape[0] - 2 * e, upad.shape[1] - 2 * e
        fn = build_neighbor_sum_2d(e, nx, ny, np.dtype(upad.dtype).name,
                                   precision=self.precision)
        return fn(upad)

    def _neighbor_sum_sat(self, upad: jnp.ndarray) -> jnp.ndarray:
        """Column running-sum: O(eps) slice ops instead of O(eps^2).

        The stencil column at x-offset i spans y offsets [-h_i, h_i]; with an
        exclusive prefix sum P along y (P[n] = sum of first n), the window sum
        at y is P[y + h_i + 1] - P[y - h_i] on the padded array.
        """
        e = self.eps
        upad = self._operand(upad)
        nx, ny = upad.shape[0] - 2 * e, upad.shape[1] - 2 * e
        # exclusive prefix sum along y, length ny + 2e + 1
        p = jnp.concatenate(
            [jnp.zeros((nx + 2 * e, 1), upad.dtype), jnp.cumsum(upad, axis=1)], axis=1
        )
        acc = jnp.zeros((nx, ny), upad.dtype)
        heights = column_half_heights(e)
        for i in range(2 * e + 1):
            h = int(heights[i])
            hi = lax.slice(p, (i, e + h + 1), (i + nx, e + h + 1 + ny))
            lo = lax.slice(p, (i, e - h), (i + nx, e - h + ny))
            acc = acc + (hi - lo)
        return acc

    # -- operator and source ------------------------------------------------
    def apply_np(self, u: np.ndarray) -> np.ndarray:
        return self.c * self.dh * self.dh * (self.neighbor_sum_np(u) - self.wsum * u)

    def apply(self, u: jnp.ndarray) -> jnp.ndarray:
        return self.c * self.dh * self.dh * (
            self.neighbor_sum(u) - self.wsum * self._operand(u)
        )

    def apply_padded(self, upad: jnp.ndarray) -> jnp.ndarray:
        """L(u) for a halo-padded block: returns the (nx, ny) interior result."""
        e = self.eps
        center = self._operand(lax.slice(
            upad, (e, e), (upad.shape[0] - e, upad.shape[1] - e)
        ))
        return self.c * self.dh * self.dh * (
            self.neighbor_sum_padded(upad) - self.wsum * center
        )

    def spatial_profile(self, nx: int, ny: int, x0: int = 0, y0: int = 0) -> np.ndarray:
        """G[x,y] = sin(2*pi*x*dh) * sin(2*pi*y*dh) on global coords."""
        x = np.arange(x0, x0 + nx, dtype=np.float64)
        y = np.arange(y0, y0 + ny, dtype=np.float64)
        return np.outer(np.sin(TWO_PI * (x * self.dh)), np.sin(TWO_PI * (y * self.dh)))

    def source_parts(self, nx: int, ny: int):
        """(G, L(G)) with zero-extension outside the nx x ny domain.

        Together these give the manufactured source of
        src/2d_nonlocal_serial.cpp:235-252:
        b_t = -2*pi*sin(2*pi*t*dt)*G - cos(2*pi*t*dt)*L(G).
        """
        g = self.spatial_profile(nx, ny)
        return g, self.apply_np(g)

    def manufactured_solution(self, nx: int, ny: int, t: int) -> np.ndarray:
        return np.cos(TWO_PI * (t * self.dt)) * self.spatial_profile(nx, ny)


def source_at(g, lg, t, dt):
    """b_t from precomputed (G, L(G)); works for np and jnp arrays, traced t.

    Uses NumPy only when both the arrays and the timestep are concrete host
    values (the oracle path); any jax array or traced ``t`` routes through jnp.
    """
    concrete_t = not isinstance(t, (jax.Array, jax.core.Tracer))
    xp = np if (isinstance(g, np.ndarray) and concrete_t) else jnp
    ang = TWO_PI * (t * dt)
    return -TWO_PI * xp.sin(ang) * g - xp.cos(ang) * lg


def make_step_fn(op, g=None, lg=None, dtype=None):
    """Build the jit-able forward-Euler step: (u, t) -> u_next.

    With (g, lg) supplied the manufactured test source is added, mirroring the
    reference's ``test`` flag (src/2d_nonlocal_serial.cpp:281-283).  NumPy
    inputs are converted to device constants up front so the step is safe to
    trace.
    """
    test = g is not None
    method = getattr(op, "method", None)
    if method in ("pallas", "auto") and isinstance(op, NonlocalOp2D):
        from nonlocalheatequation_tpu.ops.pallas_kernel import make_pallas_step_fn

        pallas_step = make_pallas_step_fn(op, g, lg, dtype)
        if method == "pallas":
            return pallas_step
        # auto: resolution is per (shape, dtype), both only known at trace
        # time — dispatch there (host-side, so the choice is static per
        # compiled shape); the fused kernel stays reachable on TPU
        generic_step = _make_generic_step(op, g, lg, dtype, test)

        def step(u, t):
            m = op._resolve_method(u.shape[0], u.shape[1], u.dtype)
            return pallas_step(u, t) if m == "pallas" else generic_step(u, t)

        return step
    return _make_generic_step(op, g, lg, dtype, test)


def _make_generic_step(op, g, lg, dtype, test):
    if test:
        g = jnp.asarray(g, dtype)
        lg = jnp.asarray(lg, dtype)

    def step(u, t):
        du = op.apply(u)
        if test:
            du = du + source_at(g, lg, t, op.dt)
        return u + op.dt * du

    return step


def make_multi_step_fn(op, nsteps: int, g=None, lg=None, dtype=None):
    """(u, t0) -> u after ``nsteps`` forward-Euler steps, via lax.scan.

    With ``NLHEAT_RESIDENT=1`` the production (source-free) 2D and 3D
    pallas paths upgrade to the VMEM-resident whole-run kernels when the
    grid fits (pallas_kernel.make_resident_multi_step_fn{,_3d} —
    bit-identical, one pallas_call for all steps).  With
    ``NLHEAT_SUPERSTEP=K`` (K >= 2) the production 2D pallas path runs K
    steps fused per pallas_call (temporal blocking of the copy-floor-bound
    kernel, pallas_kernel.make_superstep_multi_step_fn — bit-identical).
    Both opt-in until the hardware A/B lands; the contract (signature,
    numerics) is unchanged either way.  The per-shape resolution order is
    resident (when enabled and the grid fits) -> superstep (when enabled
    and the frame fits at the minimum strip) -> the per-step base path —
    so RESIDENT=1 plus SUPERSTEP=K gives residency on small grids and
    temporal blocking on the rest.  The autotuner supersedes the manual
    knobs on the 2D AND 3D production paths (2D: per-step/carried/
    superstep/resident; 3D: per-step/carried3d/resident3d): it MEASURES
    the fitting variants once per shape and runs the winner
    (utils/autotune; every candidate computes the identical function, so
    the swap cannot change results).
    It is the DEFAULT on TPU (VERDICT r3 #2: bank the measured copy-floor
    headroom as the production default); ``NLHEAT_AUTOTUNE=0`` forces the
    per-step/manual-knob path, ``NLHEAT_AUTOTUNE=1`` forces tuning on any
    backend (CPU tuning times interpreter-mode kernels — test use only).
    """
    ndim = getattr(getattr(op, "mask", None), "ndim", 0)
    ksup = int(os.environ.get("NLHEAT_SUPERSTEP", 0) or 0)
    resident_on = os.environ.get("NLHEAT_RESIDENT") == "1"
    tune_env = os.environ.get("NLHEAT_AUTOTUNE")
    bf16 = getattr(op, "precision", "f32") == "bf16"
    if bf16 and getattr(op, "resync_every", 0) > 0:
        # the periodic full-precision step lives only on the base scan path
        # (the frame variants would have to re-plumb it per kernel); the
        # knob is an accuracy lever, not a throughput one
        return make_multi_step_fn_base(op, nsteps, g, lg, dtype)

    def autotune_on():
        # evaluated only AFTER the structural gate: jax.default_backend()
        # initializes the backend, which hangs on a wedged tunnel
        # (__graft_entry__ discipline) — 1D/3D/test/sat builds must never
        # pay that just to reject this branch
        return tune_env == "1" or (
            tune_env in (None, "")
            and not resident_on and ksup < 2  # manual knobs pin the variant
            and jax.default_backend() == "tpu"
        )

    if (g is None and nsteps > 0 and ndim in (2, 3)
            and getattr(op, "method", None) == "pallas"
            and autotune_on()):
        # measure the fitting variants once per shape and run the winner
        # (all candidates compute the identical function — utils/autotune)
        from nonlocalheatequation_tpu.utils.autotune import pick_multi_step_fn

        built_at: dict = {}

        def multi_autotuned(u, t0):
            key = (u.shape, jnp.dtype(dtype or u.dtype).name)
            fn = built_at.get(key)
            if fn is None:
                fn, _winner = pick_multi_step_fn(
                    op, nsteps, u.shape, dtype or u.dtype)
                built_at[key] = fn
            return fn(u, t0)

        return multi_autotuned
    if (g is None and nsteps > 0 and ndim in (2, 3)
            and getattr(op, "method", None) == "pallas"
            and (resident_on or (ksup >= 2 and ndim == 2))):
        from nonlocalheatequation_tpu.ops.pallas_kernel import (
            fits_resident,
            fits_resident_3d,
            fits_superstep,
            make_resident_multi_step_fn,
            make_resident_multi_step_fn_3d,
            make_superstep_multi_step_fn,
        )

        # shape is only known at call time; dispatch per call (the inner
        # callables are jitted) with the built fn memoized per (shape,
        # dtype) so repeated calls reuse jit's compile cache
        built: dict = {}

        def multi_fast(u, t0):
            key = (u.shape, jnp.dtype(dtype or u.dtype).name)
            fn = built.get(key)
            if fn is None:
                dt_ = dtype or u.dtype
                # residency has no bf16 tier (zero HBM traffic between
                # steps leaves nothing for bf16 storage to halve) — the
                # bf16 production path is per-step/carried/superstep only
                if (resident_on and not bf16 and ndim == 2
                        and fits_resident(*u.shape, op.eps, dt_)):
                    fn = make_resident_multi_step_fn(op, nsteps, dtype)
                elif (resident_on and not bf16 and ndim == 3
                        and fits_resident_3d(*u.shape, op.eps, dt_)):
                    fn = make_resident_multi_step_fn_3d(op, nsteps, dtype)
                elif (ksup >= 2 and ndim == 2
                        and fits_superstep(*u.shape, op.eps, ksup, dt_)):
                    fn = make_superstep_multi_step_fn(op, nsteps,
                                                      ksteps=ksup,
                                                      dtype=dtype)
                else:
                    fn = make_multi_step_fn_base(op, nsteps, g, lg, dtype)
                built[key] = fn
            return fn(u, t0)

        return multi_fast
    return make_multi_step_fn_base(op, nsteps, g, lg, dtype)


def make_multi_step_fn_base(op, nsteps: int, g=None, lg=None, dtype=None):
    """The plain lax.scan form of make_multi_step_fn (always available).

    bf16 tier with ``resync_every=R``: every R-th step (absolute timestep
    index — stable across checkpoint/resume segment boundaries) evaluates
    the operator on the UNROUNDED state via an f32 twin op, bounding
    operand-rounding drift; ``R=1`` degenerates to the f32 path exactly.
    The state arg is donated to XLA on TPU (utils/donation.py) so the big
    rungs stop double-buffering the input frame next to the output.

    With ``NLHEAT_PROGRAM_STORE`` configured (serve/program_store.py)
    the returned callable consults the AOT program store per (shape,
    dtype): a warm boot loads the serialized executable — zero
    retrace/recompile, bit-identical results — and a cold boot persists
    this compile for the next session.  Store off (the default) returns
    exactly the pre-store object.
    """
    from nonlocalheatequation_tpu.serve.program_store import solo_store_jit
    from nonlocalheatequation_tpu.utils.donation import donated_jit

    multi = multi_step_fn_base_unjit(op, nsteps, g, lg, dtype)
    return solo_store_jit(op, nsteps, g, lg, dtype, multi, donated_jit)


def multi_step_fn_base_unjit(op, nsteps: int, g=None, lg=None, dtype=None):
    """make_multi_step_fn_base WITHOUT the jit/donation wrapper: the exact
    per-case trace the batched 'stacked' ensemble composition inlines per
    case inside one program (serve/ensemble.py) — nesting the donated jit
    there would only warn about unusable donations."""
    step = make_step_fn(op, g, lg, dtype)
    resync = (getattr(op, "precision", "f32") == "bf16"
              and getattr(op, "resync_every", 0) > 0)
    if resync:
        step_hi = make_step_fn(op.with_precision("f32"), g, lg, dtype)
        R = op.resync_every

        def body(u, t):
            nxt = lax.cond((t + 1) % R == 0,
                           lambda uu: step_hi(uu, t),
                           lambda uu: step(uu, t), u)
            return nxt, None
    else:
        def body(u, t):
            return step(u, t), None

    def multi(u, t0):
        ts = t0 + jnp.arange(nsteps)
        out, _ = lax.scan(body, u, ts)
        return out

    return multi


def case_scale(op) -> float:
    """The operator's node-volume scale c*h^d as one host float, evaluated
    with the same Python expression order as apply() so the value is
    bit-equal to the solo path's baked constant (the ensemble engine and
    the batched kernels multiply by this instead)."""
    d = op.weights.ndim
    if d == 1:
        return op.c * op.dx
    if d == 2:
        return op.c * op.dh * op.dh
    return op.c * op.dh ** 3


def check_bucket_ops(ops) -> None:
    """Validate that a batched-ensemble bucket's operators are batchable
    together: same class, same eps (hence same mask/wsum for the uniform
    J the batched paths serve), same precision tier, no resync (the
    per-step precision switch lives on the solo base path only)."""
    op0 = ops[0]
    for i, op in enumerate(ops):
        if type(op) is not type(op0) or op.eps != op0.eps:
            raise ValueError(
                f"ensemble bucket mixes operators (case {i}: "
                f"{type(op).__name__}/eps={op.eps} vs "
                f"{type(op0).__name__}/eps={op0.eps}); bucket keys must "
                "pin (shape, eps)")
        if not getattr(op, "uniform", True):
            raise ValueError(
                "the batched ensemble paths serve the uniform influence "
                f"function only (case {i} has a weighted J)")
        if getattr(op, "precision", "f32") != \
                getattr(op0, "precision", "f32"):
            raise ValueError(
                f"ensemble bucket mixes precision tiers (case {i}); the "
                "bucket key must pin the tier")
        if getattr(op, "resync_every", 0):
            raise ValueError(
                "resync_every is a solo base-scan knob; the batched "
                f"ensemble paths refuse it (case {i}) rather than "
                "silently dropping the full-precision steps")


def make_batched_multi_step_fn_vmap(ops, nsteps: int, dtype=None,
                                    test: bool = False, gs=None, lgs=None):
    """(U: (B, *shape), t0) -> U after ``nsteps`` steps, B = len(ops).

    The ensemble engine's always-available batched fallback and parity
    oracle: ``jax.vmap`` of the solo forward-Euler step over a leading
    case axis.  ``ops[0]`` serves as the bucket's prototype — eps,
    weights, wsum, method, and precision machinery are shared within a
    shape bucket by construction (:func:`check_bucket_ops`) — while the
    per-case physics (:func:`case_scale`, dt) and manufactured-source
    arrays (``test=True``: gs/lgs stacked) are baked at maker time,
    matching the solo paths' baked constants (ops/pallas_kernel.py
    section comment: traced scalars flip XLA's FMA formation and cost
    the last ulp).  Works for the 1D/2D/3D operators and every method:
    the XLA methods (shift/conv/sat) batch natively; the pallas neighbor
    sums batch through pallas_call's own vmap rule.  The op sequence per
    case is exactly the solo step's (``du = scale*(ns -
    wsum*operand(u))``, then the source, then ``u + dt*du``).
    """
    from nonlocalheatequation_tpu.utils.donation import donated_jit

    check_bucket_ops(ops)
    op = ops[0]
    wsum = op.wsum
    scales = np.array([case_scale(o) for o in ops], np.float64)
    dts = np.array([o.dt for o in ops], np.float64)

    def one_step(u, t, scale, dt_, g, lg):
        du = scale * (op.neighbor_sum(u) - wsum * op._operand(u))
        if test:
            ang = TWO_PI * (t * dt_)
            du = du + (-TWO_PI * jnp.sin(ang) * g - jnp.cos(ang) * lg)
        return u + dt_ * du

    step_v = jax.vmap(
        one_step,
        in_axes=(0, None, 0, 0, 0 if test else None, 0 if test else None))

    def multi(U, t0):
        dt_ = dtype or U.dtype
        sc = jnp.asarray(scales, dt_)
        dtv = jnp.asarray(dts, dt_)
        gd = jnp.asarray(np.asarray(gs), dt_) if test else None
        lgd = jnp.asarray(np.asarray(lgs), dt_) if test else None

        def body(Ucur, t):
            return step_v(Ucur, t, sc, dtv, gd, lgd), None

        ts = t0 + jnp.arange(nsteps)
        out, _ = lax.scan(body, U.astype(dt_), ts)
        return out

    return donated_jit(multi)


def make_batched_multi_step_fn_stacked(ops, nsteps: int, dtype=None,
                                       test: bool = False, gs=None,
                                       lgs=None):
    """(U: (B, *shape), t0) -> U after ``nsteps`` steps, B = len(ops) —
    each case's SOLO per-step trace (multi_step_fn_base_unjit, baked
    constants and all) inlined into ONE jitted program.

    This is the mixed-physics composition: when a bucket's cases differ
    in (k, dt, dh) the grid-axis batched kernels cannot bake one scalar
    set, and probing showed ref-loaded scalars cost the last ulp of the
    bit-identity contract — so instead the program simply contains B
    solo jaxprs side by side.  Still one compile and one dispatch per
    scan segment (the whole point of the ensemble engine: the ~64 ms
    tunnel dispatch+fence toll is paid once per segment, not per case),
    and bit-identical to the sequential solves by construction.  The
    state arg is donated on TPU (utils/donation.py).
    """
    from nonlocalheatequation_tpu.utils.donation import donated_jit

    check_bucket_ops(ops)
    inner = [
        multi_step_fn_base_unjit(
            op, nsteps,
            gs[i] if test else None, lgs[i] if test else None, dtype)
        for i, op in enumerate(ops)
    ]

    def multi(U, t0):
        dt_ = dtype or U.dtype
        U = U.astype(dt_)
        return jnp.stack([m(U[i], t0) for i, m in enumerate(inner)])

    return donated_jit(multi)


class NonlocalOp3D(_PrecisionPolicy):
    """3D horizon operator (extension: no 3D solver exists in the reference).

    Applies the reference's discretization recipe once more per axis: the
    eps-sphere is rasterized column-by-column (ops/stencil.horizon_mask_3d,
    the 3D analog of len_1d_line, src/2d_nonlocal_distributed.cpp:1058-1060),
    node volume dh^3, scaling constant ops/constants.c_3d.  Arrays are
    [x, y, z] of shape (nx, ny, nz).

    Methods: ``shift`` sums one padded slice per z-column (O(eps^2) slice ops);
    ``sat`` adds a z prefix sum so each column is one window difference.
    """

    def __init__(
        self,
        eps: int,
        k: float,
        dt: float,
        dh: float,
        influence=None,
        method: str = "sat",
        precision: str = "f32",
        resync_every: int = 0,
    ):
        self.eps = int(eps)
        self.k = float(k)
        self.dt = float(dt)
        self.dh = float(dh)
        self.c = c_3d(k, eps, dh)
        self.mask = horizon_mask_3d(self.eps)
        self._influence = influence  # kept so with_precision can rebuild
        self.weights = influence_weights(self.mask, influence, dh)
        self.wsum = float(self.weights.sum())
        self.uniform = influence is None
        if method in ("sat", "pallas", "auto") and not self.uniform:
            method = "shift"
        self.method = method
        self._init_precision(precision, resync_every)
        self._auto_cache: dict = {}
        # column half-heights along z per (i, j) offset, derived from the
        # mask itself so the raster rule lives only in ops/stencil.py;
        # -1 = column outside the sphere
        colsum = self.mask.sum(axis=2).astype(np.int64)
        self._zh = np.where(colsum > 0, (colsum - 1) // 2, -1)

    def with_precision(self, precision: str, resync_every: int = 0
                       ) -> "NonlocalOp3D":
        """Twin operator differing only in precision tier (see NonlocalOp2D)."""
        return NonlocalOp3D(
            self.eps, self.k, self.dt, self.dh, influence=self._influence,
            method=self.method, precision=precision,
            resync_every=resync_every)

    def with_method(self, method: str) -> "NonlocalOp3D":
        """Twin operator differing only in evaluation method (see
        NonlocalOp2D.with_method)."""
        return NonlocalOp3D(
            self.eps, self.k, self.dt, self.dh, influence=self._influence,
            method=method, precision=self.precision,
            resync_every=self.resync_every)

    # -- neighbor sum -------------------------------------------------------
    def neighbor_sum_np(self, u: np.ndarray) -> np.ndarray:
        nx, ny, nz = u.shape
        e = self.eps
        up = np.zeros((nx + 2 * e, ny + 2 * e, nz + 2 * e), dtype=u.dtype)
        up[e : e + nx, e : e + ny, e : e + nz] = u
        acc = np.zeros_like(u)
        for i in range(2 * e + 1):
            for j in range(2 * e + 1):
                h = int(self._zh[i, j])
                if h < 0:
                    continue
                for kk in range(e - h, e + h + 1):
                    w = self.weights[i, j, kk]
                    if w == 1.0:
                        acc += up[i : i + nx, j : j + ny, kk : kk + nz]
                    elif w:
                        acc += w * up[i : i + nx, j : j + ny, kk : kk + nz]
        return acc

    def neighbor_sum(self, u: jnp.ndarray) -> jnp.ndarray:
        if self.method == "fft":
            from nonlocalheatequation_tpu.ops import spectral

            return spectral.neighbor_sum_fft(self, self._operand(u))
        e = self.eps
        return self.neighbor_sum_padded(jnp.pad(u, ((e, e), (e, e), (e, e))))

    def _resolve_method(self, nx: int, ny: int, nz: int, dtype) -> str:
        """Concrete method for this (shape, dtype); see NonlocalOp2D.
        Off-TPU the 3D choice is 'sat' (the fast XLA lowering here)."""
        if self.method != "auto":
            return self.method
        key = (nx, ny, nz, jnp.dtype(dtype).name)
        m = self._auto_cache.get(key)
        if m is None:
            m = _auto_method_3d(self.eps, nx, ny, nz, jnp.dtype(dtype))
            self._auto_cache[key] = m
        return m

    def neighbor_sum_padded(self, upad: jnp.ndarray) -> jnp.ndarray:
        if self.method == "fft":
            raise ValueError(
                "method='fft' serves whole-domain (volumetric-collar) "
                "solves only; halo-padded block evaluation (distributed/"
                "fused-comm paths) needs pallas/sat/shift")
        e = self.eps
        nx, ny, nz = (s - 2 * e for s in upad.shape)
        method = self._resolve_method(nx, ny, nz, upad.dtype)
        if method == "pallas":
            from nonlocalheatequation_tpu.ops.pallas_kernel import (
                build_neighbor_sum_3d,
            )

            fn = build_neighbor_sum_3d(e, nx, ny, nz,
                                       np.dtype(upad.dtype).name,
                                       precision=self.precision)
            return fn(upad)
        upad = self._operand(upad)
        if method == "sat":
            # exclusive prefix along z: one window difference per (i, j)
            p = jnp.concatenate(
                [jnp.zeros(upad.shape[:2] + (1,), upad.dtype),
                 jnp.cumsum(upad, axis=2)], axis=2)
            acc = jnp.zeros((nx, ny, nz), upad.dtype)
            for i in range(2 * e + 1):
                for j in range(2 * e + 1):
                    h = int(self._zh[i, j])
                    if h < 0:
                        continue
                    hi = lax.slice(p, (i, j, e + h + 1), (i + nx, j + ny, e + h + 1 + nz))
                    lo = lax.slice(p, (i, j, e - h), (i + nx, j + ny, e - h + nz))
                    acc = acc + (hi - lo)
            return acc
        acc = jnp.zeros((nx, ny, nz), upad.dtype)
        for i in range(2 * e + 1):
            for j in range(2 * e + 1):
                h = int(self._zh[i, j])
                if h < 0:
                    continue
                for kk in range(e - h, e + h + 1):
                    w = float(self.weights[i, j, kk])
                    if w:
                        term = lax.slice(
                            upad, (i, j, kk), (i + nx, j + ny, kk + nz))
                        acc = acc + (term if w == 1.0 else w * term)
        return acc

    # -- operator and source ------------------------------------------------
    def apply_np(self, u: np.ndarray) -> np.ndarray:
        return self.c * self.dh**3 * (self.neighbor_sum_np(u) - self.wsum * u)

    def apply(self, u: jnp.ndarray) -> jnp.ndarray:
        return self.c * self.dh**3 * (
            self.neighbor_sum(u) - self.wsum * self._operand(u)
        )

    def apply_padded(self, upad: jnp.ndarray) -> jnp.ndarray:
        e = self.eps
        center = self._operand(lax.slice(
            upad, (e, e, e), tuple(s - e for s in upad.shape)))
        return self.c * self.dh**3 * (
            self.neighbor_sum_padded(upad) - self.wsum * center
        )

    def spatial_profile(self, nx, ny, nz, x0=0, y0=0, z0=0) -> np.ndarray:
        """G = sin(2*pi*x*dh) sin(2*pi*y*dh) sin(2*pi*z*dh) on global coords."""
        ax = np.sin(TWO_PI * (np.arange(x0, x0 + nx, dtype=np.float64) * self.dh))
        ay = np.sin(TWO_PI * (np.arange(y0, y0 + ny, dtype=np.float64) * self.dh))
        az = np.sin(TWO_PI * (np.arange(z0, z0 + nz, dtype=np.float64) * self.dh))
        return ax[:, None, None] * ay[None, :, None] * az[None, None, :]

    def source_parts(self, nx, ny, nz):
        g = self.spatial_profile(nx, ny, nz)
        return g, self.apply_np(g)

    def manufactured_solution(self, nx, ny, nz, t: int) -> np.ndarray:
        return np.cos(TWO_PI * (t * self.dt)) * self.spatial_profile(nx, ny, nz)
