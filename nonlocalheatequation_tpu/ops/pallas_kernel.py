"""Pallas TPU kernel for the 2D nonlocal horizon operator — the hot op.

This is the hand-tuned fast path for the stencil the reference evaluates with
per-point nested loops over the rasterized eps-ball
(src/2d_nonlocal_serial.cpp:256-270, src/2d_nonlocal_distributed.cpp:1102-1117;
circle raster len_1d_line src/2d_nonlocal_distributed.cpp:1058-1060).

Design (TPU-first, not a translation):

* The grid is 1D over **row strips**: each program owns a ``(TM, ny)`` output
  strip and reads an overlapping ``(TM + pad, ny + 2*eps)`` input window via an
  Element-indexed BlockSpec, so Mosaic double-buffers the HBM->VMEM streaming
  automatically.  Lane (last) dimension is always the full padded row, which
  satisfies the TPU layout constraint for any ``ny``.
* Inside the strip the circle's per-lane-offset **column sums** are built from
  **dyadic down-window sums**: D_k[r] = sum(w[r:r+k]) for powers of two k
  (log-depth roll+add chain on the VPU), then each distinct column width
  2h+1 is a minimal-weight signed (NAF) combination of a few D_k — e.g.
  width 15 = D_16 - D_1, width 7 = D_8 - D_1.  One materialized column sum
  per *distinct* half-height, reused across all lane offsets that share it:
  O(log eps + distinct-heights) window-sized vector ops instead of the
  O(eps^2) adds of the shift path, with no whole-array cumsum (f32
  reassociation error stays at the plain-accumulation level) and no masked
  rolls (all rolls read downward; wrap garbage lands in the never-read
  bottom pad rows).
* The mask is exactly ``{(i,j): i*i + j*j <= eps*eps}`` (the reference's
  truncated ``sqrt`` raster, ops/stencil.py), which is x/y symmetric, so
  summing columns along sublanes instead of lanes is exact.
* ``make_pallas_step_fn`` additionally fuses the forward-Euler update and the
  manufactured source (u + dt*(L(u) + b_t)) into the same kernel so each
  timestep is one pad + one pallas_call.

Only the uniform influence function (J == 1, the reference's only case) uses
the SAT identity; a weighted J falls back to the conv/shift paths in
ops/nonlocal_op.py.

On non-TPU backends the kernels run in Pallas interpreter mode so the same
code path is exercised by the CPU test suite (tests/conftest.py), in f64.
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from nonlocalheatequation_tpu.ops.stencil import column_half_heights
from nonlocalheatequation_tpu.utils.compat import array_vma, out_struct

TWO_PI = 2.0 * np.pi

# Mosaic stack-allocates every SSA temporary of the kernel body (no reuse
# across the prefix chain), so the scoped-VMEM footprint is ~2 window-sized
# temporaries per Hillis-Steele step plus pipeline buffers.  We raise the
# scoped limit (v5e has headroom over the 16 MB default) and size the strip
# so the whole stack fits with margin.
_VMEM_LIMIT = 100 * 1024 * 1024
# stack-model budget below the limit; the margin covers pipeline buffers
# (2x window in + 2x out).  88 MiB keeps the flagship 4096^2 eps=8 f32
# config at tm=128 (model ~81 MiB), which compiles and runs on a real v5e.
_VMEM_BUDGET = 88 * 1024 * 1024


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _kernel_params():
    if _on_tpu():
        # CompilerParams was TPUCompilerParams before the pallas rename
        cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
        return dict(compiler_params=cls(vmem_limit_bytes=_VMEM_LIMIT))
    return dict(interpret=True)


def _elem_spec(shape, index_map, memory_space):
    """Element-indexed BlockSpec, API-portable.

    The kernels below index every block in ELEMENTS (windows overlap by
    the halo/chain pad, which block-unit indexing cannot express).
    Modern pallas spells that ``pl.Element`` per dim; pre-Element pallas
    (jaxlib 0.4.x) spells the identical semantics
    ``indexing_mode=pl.unblocked`` — verified equivalent on overlapping
    windows in interpreter mode.
    """
    if hasattr(pl, "Element"):
        return pl.BlockSpec(
            tuple(pl.Element(s) for s in shape), index_map,
            memory_space=memory_space)
    return pl.BlockSpec(tuple(shape), index_map, memory_space=memory_space,
                        indexing_mode=pl.unblocked)


def _window_pad(eps: int) -> int:
    return _strip_plan(eps)[3]


def _fits(tm: int, ny: int, eps: int, itemsize: int, n_aux: int,
          batch: int = 1) -> bool:
    tmw = tm + _window_pad(eps)
    window = tmw * (ny + 2 * eps) * itemsize
    out = tm * ny * itemsize
    aux = n_aux * tm * ny * itemsize
    log_steps = max(1, int(np.ceil(np.log2(tmw))))
    lane_slots = _lane_slots({(h, L) for h, _j0, L in _lane_runs(eps)})
    stack = (2 * log_steps + 6 + lane_slots) * window + 3 * (out + aux)
    if batch > 1:
        # batched ensemble grid (case axis ahead of the strip axis): one
        # more level of pipeline double-buffering across the case
        # boundary — conservative, like the rest of the stack model
        stack += 2 * window + 2 * (out + aux)
    return stack <= _VMEM_BUDGET


def forced_tm() -> int | None:
    """Effective NLHEAT_TM strip height — the exact rounding _choose_tm
    applies — or None when the knob is unset.  The single source of truth
    for both the chooser and the bench row label (bench.py labels forced
    runs with this value so a sweep's rows stay distinguishable)."""
    v = os.environ.get("NLHEAT_TM")
    if not v:
        return None
    try:
        return max(8, _round_up(int(v), 8))
    except ValueError:
        raise ValueError(
            f"NLHEAT_TM must be an integer strip height, got {v!r}"
        ) from None


def _choose_tm(nx: int, ny: int, eps: int, itemsize: int, n_aux: int,
               fits=None) -> int:
    """Largest strip height (multiple of 8) whose stack footprint fits VMEM.

    Prefers a strip height that divides nx so the output needs no final
    slice-copy (nxp == nx) and every strip carries real rows.  ``fits``
    overrides the stack model (the carried-frame kernel has a taller
    window and a full-lane-width output).

    ``NLHEAT_TM`` (experiment knob) forces the strip height, bypassing the
    stack model: the model conservatively assumes Mosaic stack-allocates
    every SSA temporary with no reuse, so a forced-larger tm either
    compiles (model too pessimistic — measure it) or fails with a clean
    Mosaic allocation error, never a wedge.  Rounded to a multiple of 8.
    Like NLHEAT_LANE_RUNS, set it BEFORE the first kernel build: the
    builders are cached per (eps, shape, dtype), so an in-process sweep
    over settings would silently reuse the first build — run one process
    per setting (what the measurement tools do anyway).
    """
    forced = forced_tm()
    if forced:
        return forced
    if fits is None:
        fits = lambda tm: _fits(tm, ny, eps, itemsize, n_aux)  # noqa: E731
    cap = min(256, _round_up(nx, 8))
    while cap > 8 and not fits(cap):
        cap -= 8
    if not fits(cap):
        # even the minimum 8-row strip overflows the VMEM budget: ny is too
        # wide for this kernel's whole-row window layout.  Fail loudly here
        # instead of letting Mosaic die with an opaque allocation error.
        raise ValueError(
            f"pallas strip kernel: ny={ny} with eps={eps} exceeds the "
            f"{_VMEM_BUDGET >> 20} MiB VMEM budget even at the minimum strip "
            "height; use method='sat' or 'conv', or shard the y axis over "
            "the mesh so each block's row fits"
        )
    for tm in range(cap, 0, -8):
        if nx % tm == 0:
            return tm
    return max(cap, 8)


def _fits_carried(tm: int, nx: int, ny: int, eps: int, itemsize: int,
                  bf16: bool = False, batch: int = 1) -> bool:
    """_fits for the carried frame: window is (D - eps) rows taller (rounded
    to 8) and the output block spans the full Lc = ny + 2*eps lanes.  The
    bf16 tier adds the f32 carry block, the upcast window copy and the
    bf16 shadow output (conservatively one extra window + three blocks —
    the bf16-sized buffers are counted at full itemsize like everything
    else in this deliberately pessimistic model).  ``batch > 1`` adds the
    case-axis pipeline margin (see _fits)."""
    D = _round_up(eps, 8)
    tmw = tm + _round_up((D - eps) + _window_pad(eps), 8)
    Lc = ny + 2 * eps
    window = tmw * Lc * itemsize
    out = tm * Lc * itemsize
    log_steps = max(1, int(np.ceil(np.log2(tmw))))
    lane_slots = _lane_slots({(h, L) for h, _j0, L in _lane_runs(eps)})
    stack = (2 * log_steps + 6 + lane_slots) * window + 3 * out
    if bf16:
        stack += window + 3 * out
    if batch > 1:
        stack += 2 * window + 2 * out
    return stack <= _VMEM_BUDGET


def _chain_steps(run_len: int) -> int:
    """Roll+add count of the linear W_L build (shared with the VMEM model)."""
    return max(run_len - 1, 0)


def _lane_slots(run_keys) -> int:
    """VMEM stack slots of the lane-run second level: each distinct
    (h, run_len>=2) W_L chain keeps its result live through the final loop
    plus ~2 SSA temps (roll + add) per chain step; run_len==1 entries alias
    v[h] and cost nothing."""
    return sum(1 + 2 * _chain_steps(L) for _h, L in run_keys if L >= 2)


def _build_lane_wsums(v, run_keys, lane_down):
    """W_L(v[h]) per distinct (h, run_len), built with LEAF-operand rolls:
    W_L = v[h] + roll(v[h], 1) + ... + roll(v[h], L-1).

    This was a doubling chain (roll the accumulator by built powers of
    two).  For L <= 3 — every measured headline config — the two forms
    trace to the bitwise-identical op sequence (the first doubling rolls
    the still-unmodified accumulator == v[h]); at L >= 4 linear costs
    (L-1) roll+adds against the chain's ~log2(L)+popcount-ish count (one
    extra for L in 4..7, four extra at L=9 — lengths 3d eps >= 9 does
    reach) but never lane-rolls a value that is itself a lane-roll
    result.  That op pattern (first produced at L=4, a pure
    power-of-two run) is the one thing distinguishing the 2026-07-30
    compile-hang configs (2d eps=10; by the same analysis 3d eps in
    {6, 7}) from the ones that compiled green on real TPU: rolling
    computed values is routine on the sublane axis (the D_k chains roll
    their own partial sums and compile fine at every eps), so the suspect
    is roll-of-roll specifically on the LANE axis, and this build is the
    only place that produced it (see docs/bench/README.md, third wedge).
    """
    wsums = {}
    for h, run_len in run_keys:
        if (h, run_len) in wsums:
            continue
        x = v[h]
        acc_l = x
        for j in range(1, run_len):
            acc_l = acc_l + lane_down(x, j)
        wsums[h, run_len] = acc_l
    return wsums


def _naf(w: int):
    """Non-adjacent form of w: minimal-weight signed binary digits.

    Returns [(power, sign)] LSB-first; e.g. 7 -> [(0,-1),(3,+1)] (8-1).
    """
    digits = []
    p = 0
    while w:
        if w & 1:
            if (w & 3) == 3:
                digits.append((p, -1))
                w += 1
            else:
                digits.append((p, +1))
                w -= 1
        w >>= 1
        p += 1
    return digits


def _naf_parts(width: int):
    """MSB-first signed-dyadic cover of a window of ``width`` rows.

    Returns ((k, row_offset, sign), ...): the window sum of ``width`` rows
    equals sum(sign * D_k rolled down by row_offset); processing the NAF
    MSB-first keeps every partial cover non-negative so offsets stay >= 0.
    """
    parts = []
    cur = 0
    for p, sign in sorted(_naf(width), reverse=True):
        k = 1 << p
        if sign > 0:
            parts.append((k, cur, +1))
            cur += k
        else:
            cur -= k
            parts.append((k, cur, -1))
    assert cur == width
    return tuple(parts)


def _dyadic_plan(height_set, eps: int):
    """(parts_by_h, pows, pad) for a set of column half-heights."""
    parts_by_h = {}
    pows = {1}
    max_need = 1
    for h in sorted(height_set):
        parts = _naf_parts(2 * h + 1)
        parts_by_h[h] = parts
        pows.update(k for k, _, _ in parts)
        a = eps - h
        max_need = max(max_need, a + max(off + k for k, off, _ in parts))
    # chain needs all intermediate powers of two
    top = max(pows)
    k = 1
    while k < top:
        pows.add(k)
        k *= 2
    return parts_by_h, tuple(sorted(pows)), _round_up(max_need, 8)


@functools.lru_cache(maxsize=None)
def _strip_plan(eps: int):
    """Signed-dyadic evaluation plan for the circle's column-window sums.

    For each distinct column half-height h the window width 2h+1 is
    decomposed (NAF, MSB-first) into signed dyadic windows D_k[r] =
    sum(w[r:r+k]).

    Returns (heights, parts_by_h, pows, pad) where parts_by_h[h] is a list of
    (k, row_offset, sign), pows the D_k chain to build, and pad the number of
    extra window rows needed below the strip (round_up of the deepest read).
    """
    heights = tuple(int(h) for h in column_half_heights(eps))
    parts_by_h, pows, pad = _dyadic_plan(set(heights), eps)
    return heights, parts_by_h, pows, pad


def _lane_runs_enabled() -> bool:
    """NLHEAT_LANE_RUNS=0 disables the two-level lane accumulation (every
    run degenerates to length 1 == the pre-optimization per-lane slice-add
    path).  Debug/bisect knob: the two-level form is the only 2D kernel
    change between the 13/13-green compiled sweep of 2026-07-29 and the
    eps=10 compile hang observed 2026-07-30; set it BEFORE the first
    kernel build (plans are cached per enabled-state)."""
    return os.environ.get("NLHEAT_LANE_RUNS", "1") != "0"


@functools.lru_cache(maxsize=None)
def _lane_runs_cached(eps: int, enabled: bool):
    heights = _strip_plan(eps)[0]
    if not enabled:
        return tuple((h, j, 1) for j, h in enumerate(heights))
    runs = []
    j = 0
    while j < len(heights):
        j0, h = j, heights[j]
        while j < len(heights) and heights[j] == h:
            j += 1
        runs.append((h, j0, j - j0))
    return tuple(runs)


def _lane_runs(eps: int):
    """Maximal runs of equal column half-height along the lane offsets.

    The circle's profile h(jj) is flat in stretches (e.g. eps=8:
    h = 0,3,5,6,6,7,7,7,8,7,7,7,6,6,5,3,0 has runs of length 3 and 2), so
    the final per-lane-offset accumulation can sum each run with ONE
    slice-add of a lane-window sum W_L(v[h]) instead of L slice-adds —
    the same dyadic-window idea applied a second time, along lanes.
    Returns ((h, j0, L), ...): height, first lane offset, run length.
    """
    return _lane_runs_cached(eps, _lane_runs_enabled())


def _strip_neighbor_sum(w, tm: int, ny: int, eps: int, row0: int | None = None,
                        col0: int | None = None):
    """Masked-circle neighbor sum for one strip.

    ``w`` is the (tm + pad, ny + 2*eps) window whose row r holds padded row
    ``strip_start + r``; returns the (tm, ny) sum over the eps-ball centered
    at each of the strip's points.  ``row0`` is the window row holding the
    strip's first center (default eps; the carried-frame kernel passes its
    dead-band offset D).  ``col0`` is likewise the window LANE of the
    strip's first center (default eps; the fused halo kernels evaluate
    interior/ring sub-rectangles at other offsets — ops/pallas_halo.py).
    Per-element results are bitwise invariant to the (tm, ny, row0, col0)
    sub-rectangle: each element sums the same slices in the same order.

    All rolls are downward (row r reads rows >= r), so wrap-around garbage
    lands only in the bottom ``pad`` rows, which are never read — no masking
    needed, unlike an in-place prefix sum.
    """
    _heights, parts_by_h, pows, _pad = _strip_plan(eps)
    tmw = w.shape[0]
    down = lambda x, s: pltpu.roll(x, tmw - s, 0)  # noqa: E731  (shift >= 0)
    # dyadic down-window sums: D[k][r] = sum of w[r : r+k]
    d = {1: w}
    for k in pows:
        if k > 1:
            half = d[k // 2]
            d[k] = half + down(half, k // 2)
    # one materialized column-window sum per distinct half-height
    v = {}
    for h, parts in parts_by_h.items():
        acc_h = None
        for k, off, sign in parts:
            t = d[k] if off == 0 else down(d[k], off)
            if acc_h is None:
                acc_h = t if sign > 0 else -t
            else:
                acc_h = acc_h + t if sign > 0 else acc_h - t
        v[h] = acc_h
    # second level: the lane-offset accumulation dominates the kernel on
    # real hardware (measured round 3: 0.39 of 0.94 ms/step at 4096^2), so
    # sum each RUN of equal-height lane offsets with one slice-add of a
    # lane-window sum W_L(v[h]) built from leaf-operand rolls.  Symmetric runs
    # (every circle has them in pairs) share the same W_L(v[h]).  Lane-roll
    # wrap garbage lands in lanes >= wlanes - (L-1), beyond every slice's
    # read range (j0 + ny - 1 < wlanes - L + 1 since j0 + L <= 2*eps + 1).
    wlanes = w.shape[1]
    lane_down = lambda x, s: pltpu.roll(x, wlanes - s, 1)  # noqa: E731
    wsums = _build_lane_wsums(
        v, [(h, L) for h, _j0, L in _lane_runs(eps)], lane_down)
    if row0 is None:
        row0 = eps
    if col0 is None:
        col0 = eps
    acc = None
    for h, j0, run_len in _lane_runs(eps):
        a = row0 - h
        cj = (col0 - eps) + j0
        sl = wsums[h, run_len][a : a + tm, cj : cj + ny]
        acc = sl if acc is None else acc + sl
    return acc


def _pad_operand(upad, nx: int, tm: int, tmw: int, eps: int):
    """Zero-pad the halo'd operand so every strip window is in range."""
    nxp = _round_up(nx, tm)
    rows_needed = nxp - tm + tmw
    extra = rows_needed - upad.shape[0]
    if extra > 0:
        upad = jnp.pad(upad, ((0, extra), (0, 0)))
    return upad, nxp


def _reject_f64_on_tpu(dtype):
    """Mosaic has no f64 vector ops (dynamic_rotate etc.), so the compiled
    kernels are f32-only; fail with guidance instead of a compiler trace.
    Interpreter mode (off-TPU) runs f64 fine — it's how the CPU suite
    holds the oracle contract."""
    if _on_tpu() and dtype.itemsize == 8:
        raise ValueError(
            "the pallas kernel is float32-only on TPU (Mosaic has no f64 "
            "vector ops); disable x64 (--x64 0 / dtype=float32) or use "
            "method='sat' (runs f64 via XLA emulation)"
        )


def _reject_bf16_variant(op, what: str) -> None:
    """Variants without a bf16 tier must refuse a bf16-tier op loudly:
    silently running the f32 function would break the tier's rule that
    every dispatchable variant computes the identical (rounded-operand)
    result — the invariant the autotuner's swaps rely on."""
    if getattr(op, "precision", "f32") == "bf16":
        raise ValueError(
            f"the {what} has no bf16 precision tier; use the per-step, "
            "carried, or superstep 2D paths (or precision='f32')"
        )


@functools.lru_cache(maxsize=None)
def build_neighbor_sum_2d(eps: int, nx: int, ny: int, dtype_name: str,
                          precision: str = "f32"):
    """(upad: (nx+2e, ny+2e)) -> (nx, ny) masked-circle neighbor sum.

    ``precision="bf16"``: the operand window streams HBM->VMEM in
    bfloat16 (half the bytes on the kernel's dominant read) and is upcast
    to the compute dtype at load, so every add of the dyadic/NAF plan
    still accumulates at full precision — the mixed-precision tier of
    ops/nonlocal_op (bf16 storage reads, f32-or-better accumulate).
    """
    dtype = jnp.dtype(dtype_name)
    _reject_f64_on_tpu(dtype)
    bf16 = precision == "bf16"
    tm = _choose_tm(nx, ny, eps, dtype.itemsize, n_aux=0)
    tmw = tm + _window_pad(eps)

    def kernel(win_ref, out_ref):
        w = win_ref[:]
        if bf16:
            w = w.astype(dtype)  # upcast once; the plan accumulates in dtype
        out_ref[:] = _strip_neighbor_sum(w, tm, ny, eps).astype(dtype)

    def neighbor_sum(upad):
        # vma: propagate mesh-axis variance so the kernel works under
        # shard_map with check_vma (empty outside shard_map)
        vma = array_vma(upad)
        upad, nxp = _pad_operand(upad, nx, tm, tmw, eps)
        if bf16:
            upad = upad.astype(jnp.bfloat16)
        out = pl.pallas_call(
            kernel,
            grid=(nxp // tm,),
            in_specs=[
                _elem_spec((tmw, ny + 2 * eps), lambda i: (i * tm, 0),
                           pltpu.VMEM)
            ],
            out_specs=_elem_spec((tm, ny), lambda i: (i * tm, 0),
                                 pltpu.VMEM),
            out_shape=out_struct((nxp, ny), dtype, vma=vma),
            **_kernel_params(),
        )(upad)
        return out[:nx]

    return neighbor_sum


@functools.lru_cache(maxsize=None)
def _build_step_kernel(
    eps: int,
    nx: int,
    ny: int,
    dtype_name: str,
    c: float,
    dh: float,
    dt: float,
    wsum: float,
    test: bool,
    precision: str = "f32",
):
    """``precision="bf16"``: the overlapping window operand streams in
    bfloat16 and is upcast at load (the operator — neighbor sum AND its
    Wsum*center term — sees the rounded state, accumulated in ``dtype``),
    while the Euler carry reads an exact-sized full-precision center
    block, so ``u + dt*du`` never rounds the state through bf16."""
    dtype = jnp.dtype(dtype_name)
    _reject_f64_on_tpu(dtype)
    bf16 = precision == "bf16"
    n_aux = (2 if test else 0) + (1 if bf16 else 0)
    tm = _choose_tm(nx, ny, eps, dtype.itemsize, n_aux=n_aux)
    tmw = tm + _window_pad(eps)
    scale = c * dh * dh

    def kernel(*refs):
        refs = list(refs)
        win_ref = refs.pop(0)
        ctr_ref = refs.pop(0) if bf16 else None
        if test:
            g_ref, lg_ref, sc_ref = refs[0], refs[1], refs[2]
        out_ref = refs[-1]
        w = win_ref[:]
        if bf16:
            w = w.astype(dtype)
        acc = _strip_neighbor_sum(w, tm, ny, eps)
        center = w[eps : eps + tm, eps : eps + ny]
        du = scale * (acc - wsum * center)
        if test:
            # b_t = -2*pi*sin(ang)*G - cos(ang)*L(G), ang = 2*pi*t*dt
            sin_a = sc_ref[0, 0]
            cos_a = sc_ref[0, 1]
            du = du + (-TWO_PI * sin_a) * g_ref[:] + (-cos_a) * lg_ref[:]
        carry = ctr_ref[:] if bf16 else center
        nxt = carry + dt * du
        # Rows past the true domain (strip padding, when tm does not divide
        # nx) are sliced off by the caller and re-zeroed by the next step's
        # pad — no masking needed here.
        out_ref[:] = nxt.astype(dtype)

    elem = lambda *shape: _elem_spec(  # noqa: E731
        shape, (lambda i: (i * tm, 0)) if len(shape) == 2 else None,
        pltpu.VMEM,
    )

    def step_padded(upad, g, lg, sincos):
        """One fused Euler step; operands pre-padded to strip multiples."""
        vma = array_vma(upad)
        nxp = upad.shape[0] - (tmw - tm)
        in_specs = [
            _elem_spec((tmw, ny + 2 * eps), lambda i: (i * tm, 0),
                       pltpu.VMEM)
        ]
        args = [upad.astype(jnp.bfloat16) if bf16 else upad]
        if bf16:
            # full-precision Euler carry: the exact-sized center blocks of
            # the same padded state, read alongside the bf16 window
            in_specs.append(elem(tm, ny))
            args.append(lax.slice(upad, (eps, eps), (eps + nxp, eps + ny)))
        if test:
            in_specs += [
                elem(tm, ny),
                elem(tm, ny),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ]
            args += [g, lg, sincos]
        out = pl.pallas_call(
            kernel,
            grid=(nxp // tm,),
            in_specs=in_specs,
            out_specs=_elem_spec((tm, ny), lambda i: (i * tm, 0),
                                 pltpu.VMEM),
            out_shape=out_struct((nxp, ny), dtype, vma=vma),
            **_kernel_params(),
        )(*args)
        return out

    return step_padded, tm, tmw


# ---------------------------------------------------------------------------
# 3D: the same dyadic strip trick, one more axis
# ---------------------------------------------------------------------------
#
# The rasterized eps-sphere (ops/stencil.horizon_mask_3d) is exactly the
# integer ball {(i,j,k): i^2+j^2+k^2 <= eps^2} — permutation-symmetric, so
# instead of summing z-columns per (i,j) offset (the NonlocalOp3D shift/sat
# formulation) the kernel sums X-windows per lane-plane offset (j,k): the
# window half-height is h(j,k) = trunc(sqrt(eps^2 - j^2 - k^2)), and every
# distinct h reuses one signed-dyadic window sum D-chain along axis 0 —
# identical structure to the 2D kernel, with ~pi*eps^2 slice-adds instead of
# 2*eps+1.  The grid is 2D over (x strips, y blocks); z rides whole in lanes.


@functools.lru_cache(maxsize=None)
def _strip_plan_3d(eps: int):
    """((jj, kk) -> h) lane-plane heights + dyadic plan + x pad for the sphere.

    Heights derive from the 3D mask itself (column sums along axis 0), so the
    raster rule lives only in ops/stencil.py.
    """
    from nonlocalheatequation_tpu.ops.stencil import horizon_mask_3d

    mask = horizon_mask_3d(eps)
    colsum = mask.sum(axis=0)
    heights = {
        (jj, kk): int((colsum[jj, kk] - 1) // 2)
        for jj in range(2 * eps + 1)
        for kk in range(2 * eps + 1)
        if colsum[jj, kk] > 0
    }
    parts_by_h, pows, pad = _dyadic_plan(set(heights.values()), eps)
    return heights, parts_by_h, pows, pad


@functools.lru_cache(maxsize=None)
def _lane_runs_3d_cached(eps: int, enabled: bool):
    heights = _strip_plan_3d(eps)[0]
    runs = []
    for jj in sorted({j for j, _k in heights}):
        kks = sorted(k for j, k in heights if j == jj)
        i = 0
        while i < len(kks):
            k0 = kks[i]
            h = heights[jj, k0]
            L = 1
            while (enabled and i + L < len(kks) and kks[i + L] == k0 + L
                   and heights[jj, k0 + L] == h):
                L += 1
            runs.append((h, jj, k0, L))
            i += L
    return tuple(runs)


def _lane_runs_3d(eps: int):
    """Runs of equal half-height along the z (lane) offsets, per y offset.

    The 2D kernel's second-level trick, one more axis: for each fixed jj the
    sphere's column heights h(jj, kk) are flat in stretches of kk, so each
    run sums with ONE slice-add of a lane-window sum W_L(v[h]) — and W_L is
    shared across every (jj, kk0) run with the same (h, L), anywhere on the
    sphere.  Returns ((h, jj, kk0, L), ...).  NLHEAT_LANE_RUNS=0 degrades
    every run to length 1 (see _lane_runs_enabled).
    """
    return _lane_runs_3d_cached(eps, _lane_runs_enabled())


def _block_neighbor_sum_3d(w, tm: int, tn: int, nz: int, eps: int,
                           row0: int | None = None,
                           col0: int | None = None,
                           z0: int | None = None):
    """Masked-sphere neighbor sum for one (tm, tn, nz) block.

    ``w`` is the (tm + pad, tn + 2*eps, nz + 2*eps) window; row r of axis 0
    holds padded row ``strip_start + r``.  All rolls read downward along
    axis 0; wrap garbage lands in the never-read bottom pad rows.  The final
    accumulation sums each z-run of equal heights with one slice-add of a
    shared lane-window sum (see _lane_runs_3d); lane-roll wrap garbage stays
    beyond every slice's read range (kk0 + L <= 2*eps + 1).  ``row0``/
    ``col0`` are the window coordinates of the block's first center along
    x/y (default eps; the carried-frame kernel passes its dead-band D).
    """
    if row0 is None:
        row0 = eps
    if col0 is None:
        col0 = eps
    if z0 is None:
        z0 = eps
    _heights, parts_by_h, pows, _pad = _strip_plan_3d(eps)
    tmw = w.shape[0]
    down = lambda x, s: pltpu.roll(x, tmw - s, 0)  # noqa: E731
    d = {1: w}
    for k in pows:
        if k > 1:
            half = d[k // 2]
            d[k] = half + down(half, k // 2)
    v = {}
    for h, parts in parts_by_h.items():
        acc_h = None
        for k, off, sign in parts:
            t = d[k] if off == 0 else down(d[k], off)
            if acc_h is None:
                acc_h = t if sign > 0 else -t
            else:
                acc_h = acc_h + t if sign > 0 else acc_h - t
        v[h] = acc_h
    wlanes = w.shape[2]
    lane_down = lambda x, s: pltpu.roll(x, wlanes - s, 2)  # noqa: E731
    wsums = _build_lane_wsums(
        v, [(h, L) for h, _jj, _kk0, L in _lane_runs_3d(eps)], lane_down)
    acc = None
    for h, jj, kk0, run_len in _lane_runs_3d(eps):
        a = row0 - h
        cj = (col0 - eps) + jj
        ck = (z0 - eps) + kk0
        sl = wsums[h, run_len][a : a + tm, cj : cj + tn, ck : ck + nz]
        acc = sl if acc is None else acc + sl
    return acc


def _fits_3d(tm: int, tn: int, nz: int, eps: int, itemsize: int) -> bool:
    heights, parts_by_h, _pows, pad = _strip_plan_3d(eps)
    # y window widened to a multiple of 8 (Mosaic block-dim constraint)
    window = (tm + pad) * _round_up(tn + 2 * eps, 8) * (nz + 2 * eps) * itemsize
    out = tm * tn * nz * itemsize
    runs = _lane_runs_3d(eps)
    lane_slots = _lane_slots({(h, L) for h, _jj, _kk0, L in runs})
    log_steps = max(1, int(np.ceil(np.log2(tm + pad))))
    stack = ((2 * log_steps + 4 + len(parts_by_h) + lane_slots) * window
             + (2 * len(runs) + 3) * out)
    return stack <= _VMEM_BUDGET


def _fits_carried_3d(tm: int, tn: int, nz: int, eps: int,
                     itemsize: int) -> bool:
    """_fits_3d for the carried frame: taller x window, wider y window
    (dead bands), and an out block spanning the full z = nz + 2*eps."""
    heights, parts_by_h, _pows, pad = _strip_plan_3d(eps)
    D = _round_up(eps, 8)
    tmw = tm + _round_up((D - eps) + pad, 8)
    ywin = _round_up(D + tn + eps, 8)
    Lz = nz + 2 * eps
    window = tmw * ywin * Lz * itemsize
    out = tm * tn * Lz * itemsize
    runs = _lane_runs_3d(eps)
    lane_slots = _lane_slots({(h, L) for h, _jj, _kk0, L in runs})
    log_steps = max(1, int(np.ceil(np.log2(tmw))))
    stack = ((2 * log_steps + 4 + len(parts_by_h) + lane_slots) * window
             + (2 * len(runs) + 3) * out)
    return stack <= _VMEM_BUDGET


def _choose_tiles_3d(nx: int, ny: int, nz: int, eps: int, itemsize: int,
                     fits2=None):
    """(tm, tn): block footprint that fits VMEM, preferring divisors of nx/ny.

    Small blocks win on hardware: sweeping tm/tn on a v5e (round 3, post
    lowering-fix) put (8, 16) ahead of or equal to every larger choice at
    256^3 eps=4 (-7%), 192^3 eps=3 (~even), and 128^3 eps=6 (-1%, with
    (8, 8) another 13% better there but worse at 192^3) — the z axis
    already provides the long lane dimension, so growing the block only
    adds VMEM pressure without improving utilization.  Caps: tm 8, tn 16.
    """

    def pick(axis: str, n: int, fits, cap_max: int) -> int:
        cap = min(cap_max, _round_up(n, 8))
        while cap > 8 and not fits(cap):
            cap -= 8
        if not fits(cap):
            raise ValueError(
                f"pallas 3D kernel: no {axis} block of {n} fits the "
                f"{_VMEM_BUDGET >> 20} MiB VMEM budget at the minimum size "
                f"(window scales with nz={nz} and eps={eps}); "
                "use method='sat'/'shift' or shard z over the mesh"
            )
        for t in range(cap, 0, -8):
            if n % t == 0:
                return t
        return cap

    if fits2 is None:
        fits2 = lambda tm, tn: _fits_3d(tm, tn, nz, eps, itemsize)  # noqa: E731
    tn = pick("ny", ny, lambda t: fits2(8, t), 16)
    tm = pick("nx", nx, lambda t: fits2(t, tn), 8)
    return tm, tn


@functools.lru_cache(maxsize=None)
def build_neighbor_sum_3d(eps: int, nx: int, ny: int, nz: int, dtype_name: str,
                          precision: str = "f32"):
    """(upad: (nx+2e, ny+2e, nz+2e)) -> (nx, ny, nz) masked-sphere sum.

    ``precision="bf16"``: bf16 operand window, upcast at load, full-
    precision accumulation — see build_neighbor_sum_2d.
    """
    dtype = jnp.dtype(dtype_name)
    _reject_f64_on_tpu(dtype)
    bf16 = precision == "bf16"
    tm, tn = _choose_tiles_3d(nx, ny, nz, eps, dtype.itemsize)
    pad = _strip_plan_3d(eps)[3]
    tmw = tm + pad
    # Mosaic requires the last-two block dims to be (multiple of 8,
    # multiple of 128) OR equal to the array's dims.  The z block always
    # spans the full padded z axis; the y window tn + 2*eps is a multiple
    # of 8 only when eps % 4 == 0 — widen it with dead columns to the next
    # multiple of 8 (they read operand zero-padding; the kernel slices
    # them off).  Caught on real TPU in round 3: 128^3 eps=6 failed to
    # lower while the interpreter-mode CI accepted it.
    ywin = tn + 2 * eps
    ywin_blk = _round_up(ywin, 8)

    def kernel(win_ref, out_ref):
        w = win_ref[:, :ywin, :] if ywin_blk != ywin else win_ref[:]
        if bf16:
            w = w.astype(dtype)
        out_ref[:] = _block_neighbor_sum_3d(
            w, tm, tn, nz, eps
        ).astype(dtype)

    def neighbor_sum(upad):
        vma = array_vma(upad)
        if bf16:
            upad = upad.astype(jnp.bfloat16)
        nxp, nyp = _round_up(nx, tm), _round_up(ny, tn)
        # pad x so every strip window is in range; pad y so the widened
        # y window of the last block stays in range
        extra_x = (nxp - tm + tmw) - upad.shape[0]
        extra_y = (nyp - tn + ywin_blk) - upad.shape[1]
        if extra_x > 0 or extra_y > 0:
            upad = jnp.pad(
                upad, ((0, max(extra_x, 0)), (0, max(extra_y, 0)), (0, 0))
            )
        out = pl.pallas_call(
            kernel,
            grid=(nxp // tm, nyp // tn),
            in_specs=[
                _elem_spec((tmw, ywin_blk, nz + 2 * eps),
                           lambda i, j: (i * tm, j * tn, 0), pltpu.VMEM)
            ],
            out_specs=_elem_spec((tm, tn, nz),
                                 lambda i, j: (i * tm, j * tn, 0),
                                 pltpu.VMEM),
            out_shape=out_struct((nxp, nyp, nz), dtype, vma=vma),
            **_kernel_params(),
        )(upad)
        return out[:nx, :ny]

    return neighbor_sum


@functools.lru_cache(maxsize=None)
def _build_carried_kernel(eps: int, nx: int, ny: int, dtype_name: str,
                          c: float, dh: float, dt: float, wsum: float,
                          precision: str = "f32"):
    """Multi-step kernel that CARRIES the halo-padded state across steps.

    The per-step path pays a `jnp.pad` round-trip (read + write the whole
    grid) every step just to re-glue the zero halo.  Here the state lives in
    a (Rc, ny+2*eps) frame — a dead band of D = round_up(eps, 8) rows, the
    eps halo, the real rows, and the chain pad — and every step is one
    pallas_call A -> A' over that frame.  Halo rows/lanes are re-zeroed by
    an iota mask in-kernel.  Out-block row offsets use the
    (i*(tm//8) + D//8)*8 form because Mosaic's divisibility prover rejects
    the equivalent i*tm + D.

    No aliasing, no ping-pong (first carried version had both, plus a
    rotating (A, B) scan carry — which costs XLA a full-frame copy per
    step and an alias-preservation copy for the never-written dead rows;
    measured 1.33 ms/step vs the per-step path's 0.88 at 4096^2).  A plain
    scan is sound because the unwritten frame regions are never
    *observable*: out blocks write every row of [D, D+G*tm), an unmasked
    (real) output row r in [D+eps, D+eps+nx) only reads ball rows
    [r-eps, r+eps] which lie inside [D, D+G*tm) (G*tm >= nx+2*eps), and
    the rows outside that band — garbage after the first call — feed only
    outputs the iota mask forces to zero.

    Numerics are IDENTICAL to the per-step kernel (same plan, same
    summation order); only the frame bookkeeping differs.  Production
    (source-free) path only — the timed bench rungs.

    ``precision="bf16"``: the scan carries the PAIR (A_f32, A_b16) — the
    full-precision master frame and its bf16 rounding.  Each step's
    window streams from A_b16 (half the bytes on the overlapping read),
    the Euler carry reads the exact-sized f32 center block of A_f32, and
    the kernel emits both next frames (the bf16 shadow is just the
    rounding of the masked f32 output, so the next step's operand equals
    round(state) exactly — bit-identical to the per-step bf16 path).
    """
    dtype = jnp.dtype(dtype_name)
    _reject_f64_on_tpu(dtype)
    bf16 = precision == "bf16"
    tm = _choose_tm(
        nx, ny, eps, dtype.itemsize, n_aux=0,
        fits=lambda t: _fits_carried(t, nx, ny, eps, dtype.itemsize,
                                     bf16=bf16))
    D = _round_up(eps, 8)
    tmw = tm + _round_up((D - eps) + _window_pad(eps), 8)
    Lc = ny + 2 * eps
    G = -(-(nx + 2 * eps) // tm)  # out rows [D, D+G*tm) cover halo+real
    Rc = max(D + G * tm, (G - 1) * tm + tmw)
    scale = c * dh * dh

    def kernel(*refs):
        if bf16:
            win_ref, ctr_ref, out_ref, outb_ref = refs
        else:
            (win_ref, out_ref), ctr_ref, outb_ref = refs, None, None
        w = win_ref[:]
        if bf16:
            w = w.astype(dtype)
        acc = _strip_neighbor_sum(w, tm, ny, eps, row0=D)
        center = w[D : D + tm, eps : eps + ny]
        du = scale * (acc - wsum * center)
        carry = ctr_ref[:, eps : eps + ny] if bf16 else center
        nxt = carry + dt * du
        i = pl.program_id(0)
        rows = D + i * tm + jax.lax.broadcasted_iota(jnp.int32, (tm, ny), 0)
        ok = (rows >= D + eps) & (rows < D + eps + nx)
        val = jnp.where(ok, nxt, 0).astype(dtype)
        out_ref[:, eps : eps + ny] = val
        out_ref[:, :eps] = jnp.zeros((tm, eps), dtype)
        out_ref[:, eps + ny :] = jnp.zeros((tm, eps), dtype)
        if bf16:
            outb_ref[:, eps : eps + ny] = val.astype(jnp.bfloat16)
            outb_ref[:, :eps] = jnp.zeros((tm, eps), jnp.bfloat16)
            outb_ref[:, eps + ny :] = jnp.zeros((tm, eps), jnp.bfloat16)

    out_block = _elem_spec(
        (tm, Lc), lambda i: ((i * (tm // 8) + D // 8) * 8, 0), pltpu.VMEM)

    def step(A):
        return pl.pallas_call(
            kernel,
            grid=(G,),
            in_specs=[
                _elem_spec((tmw, Lc), lambda i: (i * tm, 0), pltpu.VMEM)
            ],
            out_specs=out_block,
            out_shape=jax.ShapeDtypeStruct((Rc, Lc), dtype),
            **_kernel_params(),
        )(A)

    def step_bf16(Af, Ab):
        return pl.pallas_call(
            kernel,
            grid=(G,),
            in_specs=[
                _elem_spec((tmw, Lc), lambda i: (i * tm, 0), pltpu.VMEM),
                out_block,  # f32 carry blocks, same offsets as the outputs
            ],
            out_specs=[out_block, out_block],
            out_shape=[jax.ShapeDtypeStruct((Rc, Lc), dtype),
                       jax.ShapeDtypeStruct((Rc, Lc), jnp.bfloat16)],
            **_kernel_params(),
        )(Ab, Af)

    return (step_bf16 if bf16 else step), Rc, Lc, D


def make_carried_multi_step_fn(op, nsteps: int, dtype=None):
    """(u, t0) -> u after ``nsteps`` steps, state carried in padded form.

    Drop-in for ops.nonlocal_op.make_multi_step_fn on the production
    (source-free) path when op.method == 'pallas'; see
    _build_carried_kernel.  The t0 argument is accepted for signature
    parity (the uniform-J production step is time-independent).  The
    state arg is donated on TPU (utils/donation.py).
    """
    from nonlocalheatequation_tpu.utils.donation import donated_jit

    return donated_jit(_carried_multi_unjit(op, nsteps, dtype))


def _carried_multi_unjit(op, nsteps: int, dtype=None):
    """make_carried_multi_step_fn without the jit/donation wrapper — the
    per-case trace the batched 'stacked' composition inlines."""
    eps = op.eps
    precision = getattr(op, "precision", "f32")

    def multi(u, t0):
        del t0
        dt_ = dtype or u.dtype
        nx, ny = u.shape
        step, Rc, Lc, D = _build_carried_kernel(
            eps, nx, ny, jnp.dtype(dt_).name, op.c, op.dh, op.dt, op.wsum,
            precision)
        C0 = (jnp.zeros((Rc, Lc), dt_)
              .at[D + eps : D + eps + nx, eps : eps + ny]
              .set(u.astype(dt_)))

        if precision == "bf16":
            (A, _B), _ = lax.scan(
                lambda AB, _: (step(AB[0], AB[1]), None),
                (C0, C0.astype(jnp.bfloat16)), None, length=nsteps)
        else:
            A, _ = lax.scan(
                lambda A, _: (step(A), None), C0, None, length=nsteps)
        return A[D + eps : D + eps + nx, eps : eps + ny]

    return multi


def _fits_superstep(tm: int, nx: int, ny: int, eps: int, itemsize: int,
                    ksteps: int, bf16: bool = False, batch: int = 1) -> bool:
    """_fits for the temporally blocked frame (see
    _build_superstep_kernel): the window is ~K*eps rows taller than the
    carried window and the kernel instantiates K sequential band levels,
    each with its own roll chains and band temporaries (no cross-level
    reuse assumed — conservative, like the rest of the stack model).
    ``batch > 1`` adds the case-axis pipeline margin (see _fits)."""
    D = _round_up(ksteps * eps, 8)
    tmw = tm + D + _round_up((ksteps - 1) * eps, 8) + _window_pad(eps)
    Lc = ny + 2 * eps
    window = tmw * Lc * itemsize
    out = tm * Lc * itemsize
    log_steps = max(1, int(np.ceil(np.log2(tmw))))
    lane_slots = _lane_slots({(h, L) for h, _j0, L in _lane_runs(eps)})
    stack = ksteps * (2 * log_steps + 6 + lane_slots) * window + 3 * out
    if bf16:
        # per-level rounded-operand copy + the f32 carry band + the bf16
        # shadow output (full-itemsize accounting, like the rest)
        stack += (ksteps + 1) * window + 3 * out
    if batch > 1:
        stack += 2 * window + 2 * out
    return stack <= _VMEM_BUDGET


def _build_superstep_kernel(eps: int, nx: int, ny: int, dtype_name: str,
                            c: float, dh: float, dt: float, wsum: float,
                            ksteps: int, tm: int, D: int, Rc: int,
                            precision: str = "f32"):
    """K-step temporally blocked kernel over the carried frame layout.

    The carried kernel still moves ~2 full frames of HBM traffic per step
    (read the window, write the strip) and the measured kernel is
    copy-floor-bound (docs/round3.md: copy floor 0.78 of 0.96 ms/step at
    4096^2), so the remaining lever is temporal blocking: each strip reads
    a window expanded by K*eps rows of halo, advances K steps locally in
    VMEM — level j computes a band that shrinks by eps rows per side, the
    classic trapezoidal tiling — and writes only the final tm-row strip.
    Per-step HBM traffic drops from ~(1 + tmw/tm) frames to
    ~(1 + tmw_K/tm)/K frames for ~(sum of band heights)/(K*tm) ~ 1.1-1.2x
    extra compute.

    Frame layout generalizes the carried kernel's: dead band D =
    round_up(K*eps, 8) rows (>= the K*eps rows of upward reach), halo,
    real rows, chain pad.  Soundness of garbage rows is level-wise the
    carried argument: every level masks its band to zero outside the real
    rows (the volumetric BC re-applied each level, exactly like the
    per-step path's zero pad), so dead-band/out-of-band garbage only ever
    feeds values the mask forces to zero.

    Numerics are IDENTICAL to the per-step kernel: each level runs the
    same _strip_neighbor_sum plan and the same update expression on
    identical inputs, so retained values are bit-equal (tests/test_pallas
    pins this).  Production (source-free) path only — the timed bench
    rungs.  ``ksteps`` may be smaller than the frame was sized for (the
    remainder kernel reuses the same D/Rc so scan carries stay compatible).

    ``precision="bf16"``: the scan carries the (A_f32, A_b16) pair like
    the carried kernel.  Level 1's operator reads the bf16 window (half
    the bytes on the K*eps-expanded read) and its Euler carry reads an
    aligned f32 band block of the master frame; levels >= 2 advance in
    f32 VMEM bands, each level rounding ONLY its operator operand to
    bf16 (matching the per-step bf16 path's round-per-step semantics bit
    for bit) while the carry adds stay f32 — the time integration never
    accumulates in bf16 at any level.
    """
    dtype = jnp.dtype(dtype_name)
    _reject_f64_on_tpu(dtype)
    bf16 = precision == "bf16"
    pad = _window_pad(eps)
    tmw = tm + D + _round_up((ksteps - 1) * eps, 8) + pad
    Lc = ny + 2 * eps
    G = -(-(nx + 2 * eps) // tm)  # out rows [D, D+G*tm) cover halo+real
    scale = c * dh * dh
    # f32 carry band for level 1 (bf16 tier): rows [D1, D1+H1) of the
    # master frame per strip, 8-aligned (Mosaic divisibility) with the
    # band's true start o1 rows into the block
    lvl1 = D - (ksteps - 1) * eps  # frame row of level 1's band, strip 0
    D1 = (lvl1 // 8) * 8
    o1 = lvl1 - D1
    H1 = _round_up(o1 + tm + 2 * (ksteps - 1) * eps, 8)

    def kernel(*refs):
        if bf16:
            win_ref, ctr_ref, out_ref, outb_ref = refs
        else:
            (win_ref, out_ref), ctr_ref, outb_ref = refs, None, None
        i = pl.program_id(0)
        state = win_ref[:]
        if bf16:
            state = state.astype(dtype)  # rounded OPERAND, f32 compute
        for j in range(1, ksteps + 1):
            bh = tm + 2 * (ksteps - j) * eps
            # window row of this band's first row inside `state`: the
            # level-0 window starts D-(K-1)*eps above the final band;
            # each constructed band array starts exactly at its band
            row0 = (D - (ksteps - 1) * eps) if j == 1 else eps
            opnd = (state.astype(jnp.bfloat16).astype(dtype)
                    if bf16 and j > 1 else state)
            acc = _strip_neighbor_sum(opnd, bh, ny, eps, row0=row0)
            center = opnd[row0 : row0 + bh, eps : eps + ny]
            du = scale * (acc - wsum * center)
            if bf16:
                # f32 Euler carry: level 1 reads the master-frame band,
                # later levels the f32 state advanced in VMEM
                carry = (ctr_ref[o1 : o1 + bh, eps : eps + ny] if j == 1
                         else state[row0 : row0 + bh, eps : eps + ny])
            else:
                carry = center
            nxt = carry + dt * du
            start = i * tm + D - (ksteps - j) * eps  # frame row of band[0]
            rows = start + jax.lax.broadcasted_iota(jnp.int32, (bh, ny), 0)
            ok = (rows >= D + eps) & (rows < D + eps + nx)
            nxt = jnp.where(ok, nxt, 0).astype(dtype)
            if j == ksteps:
                out_ref[:, eps : eps + ny] = nxt
                out_ref[:, :eps] = jnp.zeros((tm, eps), dtype)
                out_ref[:, eps + ny :] = jnp.zeros((tm, eps), dtype)
                if bf16:
                    outb_ref[:, eps : eps + ny] = nxt.astype(jnp.bfloat16)
                    outb_ref[:, :eps] = jnp.zeros((tm, eps), jnp.bfloat16)
                    outb_ref[:, eps + ny :] = jnp.zeros((tm, eps),
                                                        jnp.bfloat16)
            else:
                # re-glue the zero lane halo (volumetric BC on the lane
                # axis) and pad slack rows below for the next level's roll
                # garbage (2*eps + pad >= the plan's deepest read past the
                # band end, see _strip_plan)
                zl = jnp.zeros((bh, eps), dtype)
                band = jnp.concatenate([zl, nxt, zl], axis=1)
                state = jnp.concatenate(
                    [band, jnp.zeros((pad, Lc), dtype)], axis=0)
                # Materialization boundary AFTER the glue: the per-step
                # path reads each step from a materialized buffer, fixing
                # XLA's fusion context (FMA regionalization) for the next
                # level's consumers; without it the fused concat lets XLA
                # compile the level's arithmetic differently and flip last
                # ulps (observed: 40^2 eps=3 K=3, one element).  Verified:
                # barriers on `nxt` or `acc` alone do NOT restore
                # bit-identity; the opaque state does.
                state = jax.lax.optimization_barrier(state)

    out_block = _elem_spec(
        (tm, Lc), lambda i: ((i * (tm // 8) + D // 8) * 8, 0), pltpu.VMEM)

    def step(A):
        return pl.pallas_call(
            kernel,
            grid=(G,),
            in_specs=[
                _elem_spec((tmw, Lc), lambda i: (i * tm, 0), pltpu.VMEM)
            ],
            out_specs=out_block,
            out_shape=jax.ShapeDtypeStruct((Rc, Lc), dtype),
            **_kernel_params(),
        )(A)

    def step_bf16(Af, Ab):
        return pl.pallas_call(
            kernel,
            grid=(G,),
            in_specs=[
                _elem_spec((tmw, Lc), lambda i: (i * tm, 0), pltpu.VMEM),
                _elem_spec(
                    (H1, Lc), lambda i: ((i * (tm // 8) + D1 // 8) * 8, 0),
                    pltpu.VMEM),
            ],
            out_specs=[out_block, out_block],
            out_shape=[jax.ShapeDtypeStruct((Rc, Lc), dtype),
                       jax.ShapeDtypeStruct((Rc, Lc), jnp.bfloat16)],
            **_kernel_params(),
        )(Ab, Af)

    return step_bf16 if bf16 else step


def fits_superstep(nx: int, ny: int, eps: int, ksteps: int,
                   dtype=jnp.float32, precision: str = "f32") -> bool:
    """Whether the K-step temporally blocked kernel is buildable for this
    grid — i.e. even the minimum 8-row strip fits the VMEM stack model.
    The production dispatch (nonlocal_op.make_multi_step_fn) uses this to
    fall back to the per-step path instead of letting an opt-in knob turn
    a working config into a trace-time VMEM error.  A forced NLHEAT_TM
    bypasses the model in the builder, so honor it here the same way."""
    if forced_tm():
        return True  # the knob bypasses the stack model by contract
    return _fits_superstep(8, nx, ny, eps, jnp.dtype(dtype).itemsize,
                           max(1, int(ksteps)), bf16=precision == "bf16")


def superstep_k(ksteps: int, nsteps: int) -> int:
    """The effective fused-step depth make_superstep_multi_step_fn runs —
    the single source of truth for row labels (bench.py) and the maker's
    own clamp (K can never exceed the step count)."""
    return max(1, min(int(ksteps), nsteps if nsteps else 1))


def make_superstep_multi_step_fn(op, nsteps: int, ksteps: int = 2,
                                 dtype=None):
    """(u, t0) -> u after ``nsteps`` steps, ``ksteps`` fused per pallas_call.

    Drop-in for ops.nonlocal_op.make_multi_step_fn on the production
    (source-free) path when op.method == 'pallas'; see
    _build_superstep_kernel.  A remainder of nsteps % ksteps runs one
    shallower superstep call on the same frame.  The t0 argument is
    accepted for signature parity (the production step is
    time-independent).  The state arg is donated on TPU
    (utils/donation.py).
    """
    from nonlocalheatequation_tpu.utils.donation import donated_jit

    return donated_jit(_superstep_multi_unjit(op, nsteps, ksteps, dtype))


def _superstep_multi_unjit(op, nsteps: int, ksteps: int = 2, dtype=None):
    """make_superstep_multi_step_fn without the jit/donation wrapper — the
    per-case trace the batched 'stacked' composition inlines."""
    eps = op.eps
    precision = getattr(op, "precision", "f32")
    bf16 = precision == "bf16"

    def multi(u, t0):
        del t0
        dt_ = dtype or u.dtype
        nx, ny = u.shape
        K = superstep_k(ksteps, nsteps)
        itemsize = jnp.dtype(dt_).itemsize
        tm = _choose_tm(
            nx, ny, eps, itemsize, n_aux=0,
            fits=lambda t: _fits_superstep(t, nx, ny, eps, itemsize, K,
                                           bf16=bf16))
        D = _round_up(K * eps, 8)
        tmw = tm + D + _round_up((K - 1) * eps, 8) + _window_pad(eps)
        Lc = ny + 2 * eps
        G = -(-(nx + 2 * eps) // tm)
        Rc = max(D + G * tm, (G - 1) * tm + tmw)
        name = jnp.dtype(dt_).name
        step_K = _build_superstep_kernel(
            eps, nx, ny, name, op.c, op.dh, op.dt, op.wsum, K, tm, D, Rc,
            precision)
        C0 = (jnp.zeros((Rc, Lc), dt_)
              .at[D + eps : D + eps + nx, eps : eps + ny]
              .set(u.astype(dt_)))
        q, r = divmod(nsteps, K)
        if bf16:
            (A, B), _ = lax.scan(
                lambda AB, _: (step_K(AB[0], AB[1]), None),
                (C0, C0.astype(jnp.bfloat16)), None, length=q)
            if r:
                step_r = _build_superstep_kernel(
                    eps, nx, ny, name, op.c, op.dh, op.dt, op.wsum, r, tm,
                    D, Rc, precision)
                A, B = step_r(A, B)
        else:
            A, _ = lax.scan(
                lambda A, _: (step_K(A), None), C0, None, length=q)
            if r:
                step_r = _build_superstep_kernel(
                    eps, nx, ny, name, op.c, op.dh, op.dt, op.wsum, r, tm,
                    D, Rc)
                A = step_r(A)
        return A[D + eps : D + eps + nx, eps : eps + ny]

    return multi


def _fits_resident(nx: int, ny: int, eps: int, itemsize: int) -> bool:
    """VMEM model for the resident kernel: the whole (R, L) frame is the
    'window', there are two scratch frames plus the in/out blocks, and the
    fori body instantiates the step twice (A->B then B->A) — counted at
    1.5x one step's SSA stack as a middle ground between full reuse and
    none (the stack model is conservative by design; a too-big grid fails
    with a clean Mosaic allocation error, never a wedge)."""
    pad = _window_pad(eps)
    R = nx + 2 * eps + pad
    L = ny + 2 * eps
    frame = R * L * itemsize
    out = nx * ny * itemsize
    log_steps = max(1, int(np.ceil(np.log2(R))))
    lane_slots = _lane_slots({(h, Ln) for h, _j0, Ln in _lane_runs(eps)})
    stack = 1.5 * (2 * log_steps + 6 + lane_slots) * frame
    return stack + 6 * frame + 3 * out <= _VMEM_BUDGET


@functools.lru_cache(maxsize=None)
def _build_resident_kernel(eps: int, nx: int, ny: int, dtype_name: str,
                           c: float, dh: float, dt: float, wsum: float,
                           nsteps: int):
    """Whole-run kernel for grids whose frame FITS IN VMEM: one pallas_call
    executes all ``nsteps`` timesteps with the state ping-ponging between
    two VMEM scratch frames — zero HBM traffic between steps.

    Small grids are where the per-step path is overhead-bound (measured
    0.103 ms/step at 512^2 on the v5e vs 2.4 us of HBM-roofline work —
    per-call cost, not bandwidth), and they are the REFERENCE's own regime
    (100^2..400^2 ctest/README configs, tests/2d.txt).  The TPU-first
    answer is residency: the frame (nx+2eps+pad, ny+2eps) plus the NAF
    machinery's SSA stack fits VMEM up to roughly 576^2 at eps=8 f32, so
    the entire time loop runs on-core, like a cache-resident CPU stencil.

    Numerics: _strip_neighbor_sum over the full frame in ONE strip is
    bitwise identical to the strip-partitioned per-step path (each output
    element sums the same slices in the same order regardless of strip
    height — the same invariance the carried kernel's tests pin).

    Production (source-free) path, f32-on-TPU like the other fast paths.
    """
    dtype = jnp.dtype(dtype_name)
    _reject_f64_on_tpu(dtype)
    if not _fits_resident(nx, ny, eps, dtype.itemsize):
        raise ValueError(
            f"resident kernel: {nx}x{ny} eps={eps} does not fit the "
            f"{_VMEM_BUDGET >> 20} MiB VMEM budget; use the per-step path"
        )
    pad = _window_pad(eps)
    R = nx + 2 * eps + pad
    L = ny + 2 * eps
    scale = c * dh * dh

    def step_body(src_ref, dst_ref):
        w = src_ref[:]
        acc = _strip_neighbor_sum(w, nx, ny, eps)
        center = w[eps : eps + nx, eps : eps + ny]
        nxt = center + dt * (scale * (acc - wsum * center))
        # interior-only write: the halo/pad regions were zeroed once at
        # init and are never touched again
        dst_ref[eps : eps + nx, eps : eps + ny] = nxt.astype(dtype)

    def kernel(in_ref, out_ref, a_ref, b_ref):
        a_ref[...] = in_ref[...]  # zero halos come in with the operand
        b_ref[...] = jnp.zeros((R, L), dtype)

        def two(_i, carry):
            step_body(a_ref, b_ref)
            step_body(b_ref, a_ref)
            return carry

        lax.fori_loop(0, nsteps // 2, two, 0)
        if nsteps % 2:
            step_body(a_ref, b_ref)
            out_ref[...] = b_ref[...]
        else:
            out_ref[...] = a_ref[...]

    def run(frame):
        return pl.pallas_call(
            kernel,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((R, L), dtype),
            scratch_shapes=[pltpu.VMEM((R, L), dtype),
                            pltpu.VMEM((R, L), dtype)],
            **_kernel_params(),
        )(frame)

    return run, R, L


def fits_resident(nx: int, ny: int, eps: int, dtype=jnp.float32) -> bool:
    """Public gate for make_resident_multi_step_fn (see _fits_resident)."""
    return _fits_resident(nx, ny, eps, jnp.dtype(dtype).itemsize)


def make_resident_multi_step_fn(op, nsteps: int, dtype=None):
    """(u, t0) -> u after ``nsteps`` steps, entire run in one pallas_call.

    Drop-in for make_multi_step_fn on the production path when the grid
    fits VMEM (see _fits_resident; raises otherwise).  The t0 argument is
    accepted for signature parity.  The state arg is donated on TPU
    (utils/donation.py).  No bf16 tier: the resident kernel has zero HBM
    traffic between steps, so there is nothing for bf16 storage to halve
    — and silently computing the f32 function under a bf16-tier op would
    break the tier's cross-variant equality contract.
    """
    from nonlocalheatequation_tpu.utils.donation import donated_jit

    _reject_bf16_variant(op, "resident kernel")
    eps = op.eps

    def multi(u, t0):
        del t0
        dt_ = dtype or u.dtype
        nx, ny = u.shape
        run, R, L = _build_resident_kernel(
            eps, nx, ny, jnp.dtype(dt_).name, op.c, op.dh, op.dt, op.wsum,
            int(nsteps))
        frame = (jnp.zeros((R, L), dt_)
                 .at[eps : eps + nx, eps : eps + ny].set(u.astype(dt_)))
        out = run(frame)
        return out[eps : eps + nx, eps : eps + ny]

    return donated_jit(multi)


def _fits_resident_3d(nx: int, ny: int, nz: int, eps: int,
                      itemsize: int) -> bool:
    """3D residency model: same shape as _fits_resident with the sphere
    plan's pad/slot counts and a (Rx, Ry, Lz) frame."""
    _heights, parts_by_h, _pows, pad = _strip_plan_3d(eps)
    Rx = nx + 2 * eps + pad
    Ry = ny + 2 * eps
    Lz = nz + 2 * eps
    frame = Rx * Ry * Lz * itemsize
    out = nx * ny * nz * itemsize
    runs = _lane_runs_3d(eps)
    lane_slots = _lane_slots({(h, Ln) for h, _jj, _kk0, Ln in runs})
    log_steps = max(1, int(np.ceil(np.log2(Rx))))
    stack = 1.5 * (2 * log_steps + 4 + len(parts_by_h) + lane_slots) * frame
    return stack + 6 * frame + (2 * len(runs) + 3) * out <= _VMEM_BUDGET


@functools.lru_cache(maxsize=None)
def _build_resident_kernel_3d(eps: int, nx: int, ny: int, nz: int,
                              dtype_name: str, c: float, dh: float,
                              dt: float, wsum: float, nsteps: int):
    """3D mirror of _build_resident_kernel: the whole (Rx, Ry, Lz) frame
    lives in VMEM scratch for all ``nsteps`` steps (one pallas_call,
    in-kernel fori ping-pong; see the 2D builder for the design notes)."""
    dtype = jnp.dtype(dtype_name)
    _reject_f64_on_tpu(dtype)
    if not _fits_resident_3d(nx, ny, nz, eps, dtype.itemsize):
        raise ValueError(
            f"resident 3D kernel: {nx}x{ny}x{nz} eps={eps} does not fit "
            f"the {_VMEM_BUDGET >> 20} MiB VMEM budget; use the per-step path"
        )
    pad = _strip_plan_3d(eps)[3]
    Rx = nx + 2 * eps + pad
    Ry = ny + 2 * eps
    Lz = nz + 2 * eps
    scale = c * dh ** 3

    def step_body(src_ref, dst_ref):
        w = src_ref[:]
        acc = _block_neighbor_sum_3d(w, nx, ny, nz, eps)
        center = w[eps : eps + nx, eps : eps + ny, eps : eps + nz]
        nxt = center + dt * (scale * (acc - wsum * center))
        dst_ref[eps : eps + nx, eps : eps + ny, eps : eps + nz] = (
            nxt.astype(dtype))

    def kernel(in_ref, out_ref, a_ref, b_ref):
        a_ref[...] = in_ref[...]
        b_ref[...] = jnp.zeros((Rx, Ry, Lz), dtype)

        def two(_i, carry):
            step_body(a_ref, b_ref)
            step_body(b_ref, a_ref)
            return carry

        lax.fori_loop(0, nsteps // 2, two, 0)
        if nsteps % 2:
            step_body(a_ref, b_ref)
            out_ref[...] = b_ref[...]
        else:
            out_ref[...] = a_ref[...]

    def run(frame):
        return pl.pallas_call(
            kernel,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((Rx, Ry, Lz), dtype),
            scratch_shapes=[pltpu.VMEM((Rx, Ry, Lz), dtype),
                            pltpu.VMEM((Rx, Ry, Lz), dtype)],
            **_kernel_params(),
        )(frame)

    return run, Rx, Ry, Lz


def fits_resident_3d(nx: int, ny: int, nz: int, eps: int,
                     dtype=jnp.float32) -> bool:
    """Public gate for make_resident_multi_step_fn_3d."""
    return _fits_resident_3d(nx, ny, nz, eps, jnp.dtype(dtype).itemsize)


def make_resident_multi_step_fn_3d(op, nsteps: int, dtype=None):
    """(u, t0) -> u after ``nsteps`` 3D steps, entire run in one
    pallas_call; see make_resident_multi_step_fn."""
    from nonlocalheatequation_tpu.utils.donation import donated_jit

    _reject_bf16_variant(op, "resident 3D kernel")
    eps = op.eps

    def multi(u, t0):
        del t0
        dt_ = dtype or u.dtype
        nx, ny, nz = u.shape
        run, Rx, Ry, Lz = _build_resident_kernel_3d(
            eps, nx, ny, nz, jnp.dtype(dt_).name, op.c, op.dh, op.dt,
            op.wsum, int(nsteps))
        frame = (jnp.zeros((Rx, Ry, Lz), dt_)
                 .at[eps : eps + nx, eps : eps + ny, eps : eps + nz]
                 .set(u.astype(dt_)))
        out = run(frame)
        return out[eps : eps + nx, eps : eps + ny, eps : eps + nz]

    return donated_jit(multi)


@functools.lru_cache(maxsize=None)
def _build_carried_kernel_3d(eps: int, nx: int, ny: int, nz: int,
                             dtype_name: str, c: float, dh: float,
                             dt: float, wsum: float):
    """3D mirror of _build_carried_kernel: the (Rx, Ry, Lz) frame carries
    the halo-padded state across steps.  Both blocked axes get a
    round_up(eps, 8) dead band so every Element offset stays 8-aligned
    (windows at (i*tm, j*tn); out at the mul-form shifted offsets); z rides
    whole in lanes with in-kernel halo re-zeroing, rows/y re-zeroed by iota
    masks.  Alias-free plain step A -> A' (see the 2D kernel's docstring
    for why unwritten dead-band garbage is never observable; the same
    read-reach argument holds per blocked axis here)."""
    dtype = jnp.dtype(dtype_name)
    _reject_f64_on_tpu(dtype)
    tm, tn = _choose_tiles_3d(
        nx, ny, nz, eps, dtype.itemsize,
        fits2=lambda tm, tn: _fits_carried_3d(tm, tn, nz, eps,
                                              dtype.itemsize))
    D = _round_up(eps, 8)
    pad_x = _strip_plan_3d(eps)[3]
    tmw = tm + _round_up((D - eps) + pad_x, 8)
    ywin = _round_up(D + tn + eps, 8)
    Lz = nz + 2 * eps
    Gx = -(-(nx + 2 * eps) // tm)
    Gy = -(-(ny + 2 * eps) // tn)
    Rx = max(D + Gx * tm, (Gx - 1) * tm + tmw)
    Ry = max(D + Gy * tn, (Gy - 1) * tn + ywin)
    scale = c * dh ** 3

    def kernel(win_ref, out_ref):
        w = win_ref[:]
        acc = _block_neighbor_sum_3d(w, tm, tn, nz, eps, row0=D, col0=D)
        center = w[D : D + tm, D : D + tn, eps : eps + nz]
        nxt = center + dt * (scale * (acc - wsum * center))
        i, j = pl.program_id(0), pl.program_id(1)
        rows = D + i * tm + lax.broadcasted_iota(jnp.int32, (tm, tn, nz), 0)
        cols = D + j * tn + lax.broadcasted_iota(jnp.int32, (tm, tn, nz), 1)
        ok = ((rows >= D + eps) & (rows < D + eps + nx)
              & (cols >= D + eps) & (cols < D + eps + ny))
        out_ref[:, :, eps : eps + nz] = jnp.where(ok, nxt, 0).astype(dtype)
        out_ref[:, :, :eps] = jnp.zeros((tm, tn, eps), dtype)
        out_ref[:, :, eps + nz :] = jnp.zeros((tm, tn, eps), dtype)

    def step(A):
        return pl.pallas_call(
            kernel,
            grid=(Gx, Gy),
            in_specs=[
                _elem_spec((tmw, ywin, Lz),
                           lambda i, j: (i * tm, j * tn, 0), pltpu.VMEM)
            ],
            out_specs=_elem_spec(
                (tm, tn, Lz),
                lambda i, j: ((i * (tm // 8) + D // 8) * 8,
                              (j * (tn // 8) + D // 8) * 8, 0),
                pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((Rx, Ry, Lz), dtype),
            **_kernel_params(),
        )(A)

    return step, Rx, Ry, Lz, D


def make_carried_multi_step_fn_3d(op, nsteps: int, dtype=None):
    """(u, t0) -> u after ``nsteps`` 3D steps, state carried in padded form.

    Drop-in for make_multi_step_fn on the production path when
    op.method == 'pallas'; see _build_carried_kernel_3d.  The state arg
    is donated on TPU (utils/donation.py).  No bf16 tier yet: the 3D
    bf16 production path is the per-step kernel (build_neighbor_sum_3d
    reads bf16 windows); a bf16-tier op is refused loudly here."""
    from nonlocalheatequation_tpu.utils.donation import donated_jit

    _reject_bf16_variant(op, "carried 3D kernel")
    eps = op.eps

    def multi(u, t0):
        del t0
        dt_ = dtype or u.dtype
        nx, ny, nz = u.shape
        step, Rx, Ry, Lz, D = _build_carried_kernel_3d(
            eps, nx, ny, nz, jnp.dtype(dt_).name, op.c, op.dh, op.dt,
            op.wsum)
        C0 = (jnp.zeros((Rx, Ry, Lz), dt_)
              .at[D + eps : D + eps + nx, D + eps : D + eps + ny,
                  eps : eps + nz]
              .set(u.astype(dt_)))

        A, _ = lax.scan(lambda A, _: (step(A), None), C0, None, length=nsteps)
        return A[D + eps : D + eps + nx, D + eps : D + eps + ny,
                 eps : eps + nz]

    return donated_jit(multi)


def make_pallas_step_fn(op, g=None, lg=None, dtype=None):
    """Fused (u, t) -> u_next forward-Euler step for NonlocalOp2D.

    Drop-in for ops.nonlocal_op.make_step_fn when op.method == 'pallas':
    pads u with the eps halo (zeros = volumetric boundary condition) and runs
    the single fused kernel.
    """
    test = g is not None
    eps = op.eps

    def step(u, t):
        if dtype is not None:
            u = u.astype(dtype)
        nx, ny = u.shape
        step_padded, tm, tmw = _build_step_kernel(
            eps, nx, ny, np.dtype(u.dtype).name, op.c, op.dh, op.dt,
            op.wsum, test, precision=getattr(op, "precision", "f32"),
        )
        nxp = _round_up(nx, tm)
        upad = jnp.pad(u, ((eps, tmw - tm - eps + (nxp - nx)), (eps, eps)))
        if test:
            gd = jnp.asarray(g, u.dtype)
            lgd = jnp.asarray(lg, u.dtype)
            if nxp != nx:
                gd = jnp.pad(gd, ((0, nxp - nx), (0, 0)))
                lgd = jnp.pad(lgd, ((0, nxp - nx), (0, 0)))
            ang = TWO_PI * (t * op.dt)
            sincos = jnp.stack(
                [jnp.sin(ang), jnp.cos(ang)]
            ).reshape(1, 2).astype(u.dtype)
            out = step_padded(upad, gd, lgd, sincos)
        else:
            out = step_padded(upad, None, None, None)
        return out[:nx]

    return step




# ---------------------------------------------------------------------------
# Batched ensemble kernels: a leading case axis on the 2D kernel stack
# ---------------------------------------------------------------------------
#
# The ensemble engine (serve/ensemble.py) runs B independent solves that
# share (shape, eps, dtype, precision) as ONE compiled program, so the
# axon tunnel's ~64 ms dispatch+fence toll is paid once per scan segment
# instead of once per case.  Two compositions, picked per bucket chunk:
#
# * physics-UNIFORM chunks (every case has the same (scale = c*dh^2, dt)
#   — the common serving shape: one workload, many inputs): the pallas
#   grid gains a leading case axis (grid (B, strips)), every block spec a
#   leading size-1 dim indexed by the case id, and scale/dt stay BAKED
#   Python-float constants exactly like the solo kernels.  Probed at PR
#   time: baking is load-bearing — routing the scalars through an SMEM
#   ref (or a traced argument) flips XLA's FMA formation in the Euler
#   update and costs the last ulp of the bit-identity contract, while the
#   baked grid-axis kernel is bit-identical to the solo kernels per case.
# * physics-MIXED chunks: each case's SOLO trace (baked constants and
#   all) is inlined side by side into one jitted program ("stacked"
#   composition, ops/nonlocal_op.make_batched_multi_step_fn_stacked is
#   the per-step form).  Still one compile and one dispatch per segment,
#   and bit-identical to the sequential solves by construction.
#
# The public makers below take the bucket's operator LIST and dispatch
# between the two compositions themselves; jax.vmap over the solo step
# (ops/nonlocal_op.make_batched_multi_step_fn_vmap) remains the
# always-available fallback and parity oracle.


def _uniform_physics(ops) -> bool:
    """Whether one (scale, dt) scalar pair serves every case — the gate
    for the grid-axis kernels (baked constants; see section comment)."""
    from nonlocalheatequation_tpu.ops.nonlocal_op import case_scale

    return len({(case_scale(op), op.dt) for op in ops}) == 1


def _stack_cases(inners, dtype=None):
    """One jitted program inlining per-case solo multi-step traces —
    the mixed-physics composition (see section comment).  The state arg
    is donated on TPU (utils/donation.py)."""
    from nonlocalheatequation_tpu.utils.donation import donated_jit

    def multi(U, t0):
        if dtype is not None:
            U = U.astype(dtype)
        return jnp.stack([m(U[i], t0) for i, m in enumerate(inners)])

    return donated_jit(multi)


@functools.lru_cache(maxsize=None)
def _build_batched_step_kernel(eps: int, nx: int, ny: int, dtype_name: str,
                               batch: int, scale: float, dt: float,
                               wsum: float, test: bool,
                               precision: str = "f32"):
    """Leading-case-axis twin of _build_step_kernel (production AND test
    source paths), physics-uniform chunks only: scale/dt are baked
    constants, the manufactured source's per-case g/lg ride as (1, tm,
    ny) case blocks and its sincos as the solo kernel's shared SMEM row
    (dt is uniform, so the angle is too)."""
    dtype = jnp.dtype(dtype_name)
    _reject_f64_on_tpu(dtype)
    bf16 = precision == "bf16"
    n_aux = (2 if test else 0) + (1 if bf16 else 0)
    tm = _choose_tm(
        nx, ny, eps, dtype.itemsize, n_aux=n_aux,
        fits=lambda t: _fits(t, ny, eps, dtype.itemsize, n_aux, batch=batch))
    tmw = tm + _window_pad(eps)

    def kernel(*refs):
        refs = list(refs)
        win_ref = refs.pop(0)
        ctr_ref = refs.pop(0) if bf16 else None
        if test:
            g_ref, lg_ref, sc_ref = refs[0], refs[1], refs[2]
        out_ref = refs[-1]
        w = win_ref[0]
        if bf16:
            w = w.astype(dtype)
        acc = _strip_neighbor_sum(w, tm, ny, eps)
        center = w[eps : eps + tm, eps : eps + ny]
        du = scale * (acc - wsum * center)
        if test:
            sin_a = sc_ref[0, 0]
            cos_a = sc_ref[0, 1]
            du = du + (-TWO_PI * sin_a) * g_ref[0] + (-cos_a) * lg_ref[0]
        carry = ctr_ref[0] if bf16 else center
        out_ref[0] = (carry + dt * du).astype(dtype)

    case_block = lambda: _elem_spec(  # noqa: E731
        (1, tm, ny), lambda b, i: (b, i * tm, 0), pltpu.VMEM)

    def step_padded(Upad, g, lg, sincos):
        """One fused Euler step over the case stack; operands pre-padded."""
        vma = array_vma(Upad)
        nxp = Upad.shape[1] - (tmw - tm)
        in_specs = [
            _elem_spec((1, tmw, ny + 2 * eps), lambda b, i: (b, i * tm, 0),
                       pltpu.VMEM)
        ]
        args = [Upad.astype(jnp.bfloat16) if bf16 else Upad]
        if bf16:
            in_specs.append(case_block())
            args.append(lax.slice(Upad, (0, eps, eps),
                                  (batch, eps + nxp, eps + ny)))
        if test:
            in_specs += [case_block(), case_block(),
                         pl.BlockSpec(memory_space=pltpu.SMEM)]
            args += [g, lg, sincos]
        out = pl.pallas_call(
            kernel,
            grid=(batch, nxp // tm),
            in_specs=in_specs,
            out_specs=case_block(),
            out_shape=out_struct((batch, nxp, ny), dtype, vma=vma),
            **_kernel_params(),
        )(*args)
        return out

    return step_padded, tm, tmw


def make_batched_pallas_multi_step_fn(ops, nsteps: int, dtype=None,
                                      test: bool = False, gs=None,
                                      lgs=None):
    """(U: (B, nx, ny), t0) -> U after ``nsteps`` forward-Euler steps,
    all B = len(ops) cases advanced by ONE program.

    The batched twin of the per-step pallas path (make_pallas_step_fn
    under make_multi_step_fn_base): physics-uniform chunks pad the case
    stack once per scan step and run one fused grid-axis kernel;
    physics-mixed chunks inline the per-case solo traces (see the
    section comment).  ``test=True`` adds the manufactured source; gs/lgs
    are the per-case (G, L(G)) stacks.  Production outputs are
    bit-identical to the solo solves; the test-source grid-axis path is
    last-ulp-close (~1e-16: the fused source multiply-add regionalizes
    differently against the case-blocked g/lg reads — measured, inside
    the 1e-12 contract; the stacked composition is the bit-exact form).
    The state arg is donated on TPU (utils/donation.py)."""
    from nonlocalheatequation_tpu.ops.nonlocal_op import (
        case_scale,
        check_bucket_ops,
        make_batched_multi_step_fn_stacked,
    )
    from nonlocalheatequation_tpu.utils.donation import donated_jit

    check_bucket_ops(ops)
    if not _uniform_physics(ops):
        return make_batched_multi_step_fn_stacked(
            ops, nsteps, dtype=dtype, test=test, gs=gs, lgs=lgs)
    op0 = ops[0]
    eps = op0.eps
    wsum = op0.wsum
    scale = case_scale(op0)
    dt = op0.dt
    precision = getattr(op0, "precision", "f32")
    batch = len(ops)

    def multi(U, t0):
        dt_ = dtype or U.dtype
        _B, nx, ny = U.shape
        step_padded, tm, tmw = _build_batched_step_kernel(
            eps, nx, ny, jnp.dtype(dt_).name, batch, scale, dt, wsum, test,
            precision)
        nxp = _round_up(nx, tm)
        if test:
            gd = jnp.asarray(np.asarray(gs), dt_)
            lgd = jnp.asarray(np.asarray(lgs), dt_)
            if nxp != nx:
                gd = jnp.pad(gd, ((0, 0), (0, nxp - nx), (0, 0)))
                lgd = jnp.pad(lgd, ((0, 0), (0, nxp - nx), (0, 0)))
        else:
            gd = lgd = None

        def body(Ucur, t):
            Upad = jnp.pad(
                Ucur,
                ((0, 0), (eps, tmw - tm - eps + (nxp - nx)), (eps, eps)))
            if test:
                ang = TWO_PI * (t * dt)
                sincos = jnp.stack(
                    [jnp.sin(ang), jnp.cos(ang)]
                ).reshape(1, 2).astype(dt_)
                out = step_padded(Upad, gd, lgd, sincos)
            else:
                out = step_padded(Upad, None, None, None)
            return out[:, :nx, :], None

        ts = t0 + jnp.arange(nsteps)
        out, _ = lax.scan(body, U.astype(dt_), ts)
        return out

    return donated_jit(multi)


@functools.lru_cache(maxsize=None)
def _build_batched_carried_kernel(eps: int, nx: int, ny: int,
                                  dtype_name: str, batch: int, scale: float,
                                  dt: float, wsum: float,
                                  precision: str = "f32"):
    """Leading-case-axis twin of _build_carried_kernel (physics-uniform
    chunks): the frame becomes (B, Rc, Lc), the grid (B, G), scale/dt
    stay baked.  Same plan, same op order, same masks per case ->
    bit-identical to the solo carried kernel (see section comment)."""
    dtype = jnp.dtype(dtype_name)
    _reject_f64_on_tpu(dtype)
    bf16 = precision == "bf16"
    tm = _choose_tm(
        nx, ny, eps, dtype.itemsize, n_aux=0,
        fits=lambda t: _fits_carried(t, nx, ny, eps, dtype.itemsize,
                                     bf16=bf16, batch=batch))
    D = _round_up(eps, 8)
    tmw = tm + _round_up((D - eps) + _window_pad(eps), 8)
    Lc = ny + 2 * eps
    G = -(-(nx + 2 * eps) // tm)
    Rc = max(D + G * tm, (G - 1) * tm + tmw)

    def kernel(*refs):
        if bf16:
            win_ref, ctr_ref, out_ref, outb_ref = refs
        else:
            (win_ref, out_ref), ctr_ref, outb_ref = refs, None, None
        w = win_ref[0]
        if bf16:
            w = w.astype(dtype)
        acc = _strip_neighbor_sum(w, tm, ny, eps, row0=D)
        center = w[D : D + tm, eps : eps + ny]
        du = scale * (acc - wsum * center)
        carry = ctr_ref[0, :, eps : eps + ny] if bf16 else center
        nxt = carry + dt * du
        i = pl.program_id(1)
        rows = D + i * tm + jax.lax.broadcasted_iota(jnp.int32, (tm, ny), 0)
        ok = (rows >= D + eps) & (rows < D + eps + nx)
        val = jnp.where(ok, nxt, 0).astype(dtype)
        out_ref[0, :, eps : eps + ny] = val
        out_ref[0, :, :eps] = jnp.zeros((tm, eps), dtype)
        out_ref[0, :, eps + ny :] = jnp.zeros((tm, eps), dtype)
        if bf16:
            outb_ref[0, :, eps : eps + ny] = val.astype(jnp.bfloat16)
            outb_ref[0, :, :eps] = jnp.zeros((tm, eps), jnp.bfloat16)
            outb_ref[0, :, eps + ny :] = jnp.zeros((tm, eps), jnp.bfloat16)

    out_block = _elem_spec(
        (1, tm, Lc),
        lambda b, i: (b, (i * (tm // 8) + D // 8) * 8, 0), pltpu.VMEM)
    win_spec = _elem_spec(
        (1, tmw, Lc), lambda b, i: (b, i * tm, 0), pltpu.VMEM)

    def step(A):
        return pl.pallas_call(
            kernel,
            grid=(batch, G),
            in_specs=[win_spec],
            out_specs=out_block,
            out_shape=jax.ShapeDtypeStruct((batch, Rc, Lc), dtype),
            **_kernel_params(),
        )(A)

    def step_bf16(Af, Ab):
        return pl.pallas_call(
            kernel,
            grid=(batch, G),
            in_specs=[win_spec, out_block],
            out_specs=[out_block, out_block],
            out_shape=[jax.ShapeDtypeStruct((batch, Rc, Lc), dtype),
                       jax.ShapeDtypeStruct((batch, Rc, Lc), jnp.bfloat16)],
            **_kernel_params(),
        )(Ab, Af)

    return (step_bf16 if bf16 else step), Rc, Lc, D


def make_batched_carried_multi_step_fn(ops, nsteps: int, dtype=None):
    """(U: (B, nx, ny), t0) -> U after ``nsteps`` steps, the whole
    B = len(ops) case stack carried in ONE padded frame across a single
    scan — the batched twin of make_carried_multi_step_fn (production/
    source-free path only).  Physics-mixed chunks stack the per-case solo
    carried traces instead (see section comment).  The state arg is
    donated on TPU (utils/donation.py)."""
    from nonlocalheatequation_tpu.ops.nonlocal_op import (
        case_scale,
        check_bucket_ops,
    )
    from nonlocalheatequation_tpu.utils.donation import donated_jit

    check_bucket_ops(ops)
    if not _uniform_physics(ops):
        return _stack_cases(
            [_carried_multi_unjit(op, nsteps, dtype) for op in ops], dtype)
    op0 = ops[0]
    eps = op0.eps
    wsum = op0.wsum
    scale = case_scale(op0)
    dt = op0.dt
    precision = getattr(op0, "precision", "f32")
    batch = len(ops)

    def multi(U, t0):
        del t0
        dt_ = dtype or U.dtype
        _B, nx, ny = U.shape
        step, Rc, Lc, D = _build_batched_carried_kernel(
            eps, nx, ny, jnp.dtype(dt_).name, batch, scale, dt, wsum,
            precision)
        C0 = (jnp.zeros((batch, Rc, Lc), dt_)
              .at[:, D + eps : D + eps + nx, eps : eps + ny]
              .set(U.astype(dt_)))
        if precision == "bf16":
            (A, _Bb), _ = lax.scan(
                lambda AB, _: (step(AB[0], AB[1]), None),
                (C0, C0.astype(jnp.bfloat16)), None, length=nsteps)
        else:
            A, _ = lax.scan(
                lambda A, _: (step(A), None), C0, None, length=nsteps)
        return A[:, D + eps : D + eps + nx, eps : eps + ny]

    return donated_jit(multi)


@functools.lru_cache(maxsize=None)
def _build_batched_superstep_kernel(eps: int, nx: int, ny: int,
                                    dtype_name: str, batch: int,
                                    scale: float, dt: float, wsum: float,
                                    ksteps: int, tm: int, D: int, Rc: int,
                                    precision: str = "f32"):
    """Leading-case-axis twin of _build_superstep_kernel (K-step temporal
    blocking over the carried frame layout; physics-uniform chunks).
    Level structure, masks, and the inter-level optimization_barrier are
    identical per case; only the frame/grid gain the case axis."""
    dtype = jnp.dtype(dtype_name)
    _reject_f64_on_tpu(dtype)
    bf16 = precision == "bf16"
    pad = _window_pad(eps)
    tmw = tm + D + _round_up((ksteps - 1) * eps, 8) + pad
    Lc = ny + 2 * eps
    G = -(-(nx + 2 * eps) // tm)
    lvl1 = D - (ksteps - 1) * eps
    D1 = (lvl1 // 8) * 8
    o1 = lvl1 - D1
    H1 = _round_up(o1 + tm + 2 * (ksteps - 1) * eps, 8)

    def kernel(*refs):
        if bf16:
            win_ref, ctr_ref, out_ref, outb_ref = refs
        else:
            (win_ref, out_ref), ctr_ref, outb_ref = refs, None, None
        i = pl.program_id(1)
        state = win_ref[0]
        if bf16:
            state = state.astype(dtype)  # rounded OPERAND, f32 compute
        for j in range(1, ksteps + 1):
            bh = tm + 2 * (ksteps - j) * eps
            row0 = (D - (ksteps - 1) * eps) if j == 1 else eps
            opnd = (state.astype(jnp.bfloat16).astype(dtype)
                    if bf16 and j > 1 else state)
            acc = _strip_neighbor_sum(opnd, bh, ny, eps, row0=row0)
            center = opnd[row0 : row0 + bh, eps : eps + ny]
            du = scale * (acc - wsum * center)
            if bf16:
                carry = (ctr_ref[0, o1 : o1 + bh, eps : eps + ny] if j == 1
                         else state[row0 : row0 + bh, eps : eps + ny])
            else:
                carry = center
            nxt = carry + dt * du
            start = i * tm + D - (ksteps - j) * eps
            rows = start + jax.lax.broadcasted_iota(jnp.int32, (bh, ny), 0)
            ok = (rows >= D + eps) & (rows < D + eps + nx)
            nxt = jnp.where(ok, nxt, 0).astype(dtype)
            if j == ksteps:
                out_ref[0, :, eps : eps + ny] = nxt
                out_ref[0, :, :eps] = jnp.zeros((tm, eps), dtype)
                out_ref[0, :, eps + ny :] = jnp.zeros((tm, eps), dtype)
                if bf16:
                    outb_ref[0, :, eps : eps + ny] = \
                        nxt.astype(jnp.bfloat16)
                    outb_ref[0, :, :eps] = jnp.zeros((tm, eps),
                                                     jnp.bfloat16)
                    outb_ref[0, :, eps + ny :] = jnp.zeros((tm, eps),
                                                           jnp.bfloat16)
            else:
                zl = jnp.zeros((bh, eps), dtype)
                band = jnp.concatenate([zl, nxt, zl], axis=1)
                state = jnp.concatenate(
                    [band, jnp.zeros((pad, Lc), dtype)], axis=0)
                # same materialization boundary as the solo kernel (see
                # _build_superstep_kernel): pins the per-step fusion
                # context so bit-identity survives XLA regionalization
                state = jax.lax.optimization_barrier(state)

    out_block = _elem_spec(
        (1, tm, Lc),
        lambda b, i: (b, (i * (tm // 8) + D // 8) * 8, 0), pltpu.VMEM)
    win_spec = _elem_spec(
        (1, tmw, Lc), lambda b, i: (b, i * tm, 0), pltpu.VMEM)

    def step(A):
        return pl.pallas_call(
            kernel,
            grid=(batch, G),
            in_specs=[win_spec],
            out_specs=out_block,
            out_shape=jax.ShapeDtypeStruct((batch, Rc, Lc), dtype),
            **_kernel_params(),
        )(A)

    def step_bf16(Af, Ab):
        return pl.pallas_call(
            kernel,
            grid=(batch, G),
            in_specs=[
                win_spec,
                _elem_spec((1, H1, Lc),
                           lambda b, i: (b, (i * (tm // 8) + D1 // 8) * 8,
                                         0),
                           pltpu.VMEM),
            ],
            out_specs=[out_block, out_block],
            out_shape=[jax.ShapeDtypeStruct((batch, Rc, Lc), dtype),
                       jax.ShapeDtypeStruct((batch, Rc, Lc), jnp.bfloat16)],
            **_kernel_params(),
        )(Ab, Af)

    return step_bf16 if bf16 else step


def make_batched_superstep_multi_step_fn(ops, nsteps: int, ksteps: int = 2,
                                         dtype=None):
    """(U: (B, nx, ny), t0) -> U after ``nsteps`` steps, ``ksteps`` fused
    per pallas_call over the whole B = len(ops) case stack — the batched
    twin of make_superstep_multi_step_fn (production path only;
    remainder steps run a shallower superstep on the same frame).
    Physics-mixed chunks stack the per-case solo superstep traces
    instead (see section comment).  The state arg is donated on TPU
    (utils/donation.py)."""
    from nonlocalheatequation_tpu.ops.nonlocal_op import (
        case_scale,
        check_bucket_ops,
    )
    from nonlocalheatequation_tpu.utils.donation import donated_jit

    check_bucket_ops(ops)
    if not _uniform_physics(ops):
        return _stack_cases(
            [_superstep_multi_unjit(op, nsteps, ksteps, dtype)
             for op in ops], dtype)
    op0 = ops[0]
    eps = op0.eps
    wsum = op0.wsum
    scale = case_scale(op0)
    dt = op0.dt
    precision = getattr(op0, "precision", "f32")
    bf16 = precision == "bf16"
    batch = len(ops)

    def multi(U, t0):
        del t0
        dt_ = dtype or U.dtype
        _B, nx, ny = U.shape
        K = superstep_k(ksteps, nsteps)
        itemsize = jnp.dtype(dt_).itemsize
        tm = _choose_tm(
            nx, ny, eps, itemsize, n_aux=0,
            fits=lambda t: _fits_superstep(t, nx, ny, eps, itemsize, K,
                                           bf16=bf16, batch=batch))
        D = _round_up(K * eps, 8)
        tmw = tm + D + _round_up((K - 1) * eps, 8) + _window_pad(eps)
        Lc = ny + 2 * eps
        G = -(-(nx + 2 * eps) // tm)
        Rc = max(D + G * tm, (G - 1) * tm + tmw)
        name = jnp.dtype(dt_).name
        step_K = _build_batched_superstep_kernel(
            eps, nx, ny, name, batch, scale, dt, wsum, K, tm, D, Rc,
            precision)
        C0 = (jnp.zeros((batch, Rc, Lc), dt_)
              .at[:, D + eps : D + eps + nx, eps : eps + ny]
              .set(U.astype(dt_)))
        q, r = divmod(nsteps, K)
        if bf16:
            (A, Bb), _ = lax.scan(
                lambda AB, _: (step_K(AB[0], AB[1]), None),
                (C0, C0.astype(jnp.bfloat16)), None, length=q)
            if r:
                step_r = _build_batched_superstep_kernel(
                    eps, nx, ny, name, batch, scale, dt, wsum, r, tm, D,
                    Rc, precision)
                A, Bb = step_r(A, Bb)
        else:
            A, _ = lax.scan(
                lambda A, _: (step_K(A), None), C0, None, length=q)
            if r:
                step_r = _build_batched_superstep_kernel(
                    eps, nx, ny, name, batch, scale, dt, wsum, r, tm, D,
                    Rc)
                A = step_r(A)
        return A[:, D + eps : D + eps + nx, eps : eps + ny]

    return donated_jit(multi)
