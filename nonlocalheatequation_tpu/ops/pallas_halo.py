"""Fused distributed halo kernels — remote-DMA ghost exchange overlapped
with the interior stencil sweep.

The collective distributed path (parallel/halo.py + distributed2d/3d)
fences every halo exchange against the step: `lax.ppermute` collectives
run *between* kernel launches, so each timestep is exchange -> sweep with
no overlap — and the multi-hop long-horizon case pays one sequential
ppermute round per hop per axis.  The reference hides exactly this
latency with its interior/boundary two-stage dataflow: ghost-zone RPC
futures fly while interior tiles compute
(src/2d_nonlocal_distributed.cpp:1156-1261).  This module is that design
TPU-native, inside the Pallas kernel itself:

* :func:`plan_exchange` rasterizes the reference's neighbor rectangles
  (``add_neighbour_rectangle``, :982-992) for a block on a device mesh:
  one message per neighbor offset — 8 in 2D at one hop, like the
  reference's 8-neighbor tiles; ``(2m+1)^d - 1`` when the horizon spans
  m shards — with the transfer width CAPPED at the remaining hop depth
  (parallel/halo.hop_widths), and each message carrying its exact source
  rectangle (sender block coords) and destination rectangle (receiver
  frame coords).  Multi-hop bands DMA *directly* to the device m hops
  away instead of store-and-forwarding through the ring.
* the **RDMA kernel** (:func:`build_fused_nsum_2d` /
  :func:`build_fused_nsum_3d`, TPU only): each device's kernel preps a
  halo frame in VMEM scratch, barriers with its neighbors
  (``get_barrier_semaphore`` — a send may never land in a frame still
  being prepped), starts ``make_async_remote_copy`` for every plan
  message (DMA semaphores in scratch), computes the INTERIOR cells —
  which read no halo — while the bands are in flight, waits on the recv
  semaphores, and finishes the eps-wide boundary ring.  Communication
  rides under compute instead of fencing it.
* the **split compute kernel** (:func:`build_split_nsum_2d` /
  :func:`build_split_nsum_3d`): the same interior-then-ring compute body
  over a pre-filled frame, with no DMA machinery.  Off-TPU it runs in
  the Pallas interpreter under shard_map (bands moved by the existing
  ppermute transport), so the fused kernel's compute decomposition is
  exercised — and pinned BITWISE against the `halo_pad_*` oracle — by
  the CPU tier-1 suite on every run (tests/test_halo_fused.py).  What
  CPU cannot exercise is the RDMA transport itself; that evidence comes
  from the on-device dryrun/bench rungs.

The kernels emit the raw neighbor SUM; the solver forms
``du = c*h^d * (nsum - Wsum*u)`` outside, in exactly
``NonlocalOp*.apply_padded``'s expression — which is what makes the
fused path bitwise the collective path on the f64 CPU suite rather than
merely 1e-12-close: the strip plan's per-element value is invariant to
the evaluated sub-rectangle (each output element sums the same window
slices in the same order whatever ``tm``/``ny``/``row0``/``col0`` range
it is computed in — the same invariance the resident kernel's docstring
proves for strip heights), and the dyadic/NAF chains are lane- and
column-local, so stale not-yet-arrived halo values can never leak into
interior elements computed while the DMA is in flight.

Only ``method='pallas'``-capable buckets (uniform J) can run fused;
everything else refuses loudly (:func:`require_fused`).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from nonlocalheatequation_tpu.ops.pallas_kernel import (
    _VMEM_BUDGET,
    _VMEM_LIMIT,
    _block_neighbor_sum_3d,
    _lane_runs,
    _lane_runs_3d,
    _lane_slots,
    _on_tpu,
    _reject_f64_on_tpu,
    _round_up,
    _strip_neighbor_sum,
    _strip_plan_3d,
    _window_pad,
)
from nonlocalheatequation_tpu.parallel.halo import hop_widths
from nonlocalheatequation_tpu.utils.compat import array_vma, out_struct

#: collective_id of the fused kernels' neighbor barrier (2D and 3D use
#: distinct ids so a program mixing both can never cross their barriers)
_COLLECTIVE_ID_2D = 0x2D
_COLLECTIVE_ID_3D = 0x3D


# ---------------------------------------------------------------------------
# The exchange plan: the reference's neighbor rectangles on a device mesh
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class HaloMsg:
    """One directed band: sender at mesh position p pushes
    ``block[src]`` into the frame of the receiver at ``p + offset``,
    landing at ``frame[dst]``.  ``src`` is in sender block coordinates,
    ``dst`` in receiver frame coordinates (block at offset eps per
    sharded axis); both are per-axis ``(start, stop)`` pairs."""

    offset: tuple[int, ...]
    src: tuple[tuple[int, int], ...]
    dst: tuple[tuple[int, int], ...]

    @property
    def shape(self) -> tuple[int, ...]:
        return tuple(b - a for a, b in self.src)

    def size(self) -> int:
        return int(np.prod(self.shape))


def _axis_ranges(extent: int, nshards: int, eps: int):
    """Per-axis {offset: (src_range, dst_range)} for one sharded axis.

    Offset +h: the receiver sits h shards AFTER the sender, so the
    sender's trailing ``hop_widths(eps, extent)[h-1]``-wide band lands in
    the receiver's leading (low-side) halo — and mirrored for -h.  Hops
    are capped at ``nshards - 1``: a band from beyond the mesh does not
    exist, and the un-sent halo stays zero, which IS the volumetric
    boundary condition (exactly `lax.ppermute`'s un-targeted-output
    semantics, parallel/halo.py).
    """
    widths = hop_widths(eps, extent)
    hops = min(len(widths), max(nshards - 1, 0))
    ranges = {0: ((0, extent), (eps, eps + extent))}
    for h in range(1, hops + 1):
        w = widths[h - 1]
        # +h: sender's LAST w rows -> receiver frame rows ending at the
        # low-halo depth (h-1)*extent below the block edge
        lo = eps - (h - 1) * extent - w
        ranges[h] = ((extent - w, extent), (lo, lo + w))
        # -h: sender's FIRST w rows -> receiver's high-side halo
        hi = eps + extent + (h - 1) * extent
        ranges[-h] = ((0, w), (hi, hi + w))
    return ranges


def plan_exchange(
    mesh_shape: tuple[int, ...],
    block_shape: tuple[int, ...],
    eps: int,
) -> tuple[HaloMsg, ...]:
    """Every band one device pushes per exchange, in a deterministic
    order (message i on every device targets the same offset — the SPMD
    symmetry the semaphore pairing relies on: my message i lands on my
    +offset neighbor's ``recv_sems[i]``, and the message arriving on MY
    ``recv_sems[i]`` is my -offset neighbor's message i)."""
    if len(mesh_shape) != len(block_shape):
        raise ValueError(
            f"mesh_shape {mesh_shape} and block_shape {block_shape} "
            "disagree in rank")
    per_axis = [
        _axis_ranges(int(b), int(n), int(eps))
        for b, n in zip(block_shape, mesh_shape, strict=True)
    ]
    msgs = []
    offsets = [sorted(r.keys()) for r in per_axis]
    for combo in np.ndindex(*[len(o) for o in offsets]):
        off = tuple(offsets[ax][i] for ax, i in enumerate(combo))
        if all(o == 0 for o in off):
            continue
        src = tuple(per_axis[ax][o][0] for ax, o in enumerate(off))
        dst = tuple(per_axis[ax][o][1] for ax, o in enumerate(off))
        msgs.append(HaloMsg(offset=off, src=src, dst=dst))
    return tuple(msgs)


def plan_bytes(plan, itemsize: int) -> int:
    """Bytes one interior device pushes per exchange (edge devices skip
    out-of-mesh targets at runtime; this is the invariant per-exchange
    upper bound the /halo/bytes counter and the docs quote)."""
    return sum(m.size() for m in plan) * int(itemsize)


def collective_bytes(
    mesh_shape: tuple[int, ...],
    block_shape: tuple[int, ...],
    eps: int,
    itemsize: int,
) -> int:
    """Bytes one device ppermutes per `halo_pad_nd` exchange (both
    directions), with the hop-capped widths — the regression-test pin
    for the parallel/halo.py byte-cap fix.  Axis k's bands carry the
    earlier axes' halos (the two-phase corner trick), so extents grow by
    2*eps per completed axis."""
    total = 0
    extents = [int(b) for b in block_shape]
    for ax, (bs, nshards) in enumerate(zip(block_shape, mesh_shape, strict=True)):
        if int(nshards) <= 1:
            extents[ax] += 2 * eps
            continue
        other = 1
        for j, e in enumerate(extents):
            if j != ax:
                other *= e
        per_direction = sum(hop_widths(eps, int(bs)))
        total += 2 * per_direction * other * int(itemsize)
        extents[ax] += 2 * eps
    return total


# ---------------------------------------------------------------------------
# VMEM fit models (the halo-resident frame layout)
# ---------------------------------------------------------------------------


def _fits_fused(bx: int, by: int, eps: int, itemsize: int,
                bf16: bool = False) -> bool:
    """Stack model for the 2D fused/split kernels: the halo frame
    (bx+2e+pad, by+2e) lives whole in VMEM, the interior phase runs one
    frame-sized strip-plan evaluation, and the ring phase's four
    narrow-window evaluations are counted as one more frame-sized one
    (conservative, like every _fits* model — a too-big block fails here
    with guidance, never inside Mosaic)."""
    pad = _window_pad(eps)
    Rf, Lf = bx + 2 * eps + pad, by + 2 * eps
    frame = Rf * Lf * itemsize
    out = bx * by * itemsize
    log_steps = max(1, int(np.ceil(np.log2(Rf))))
    lane_slots = _lane_slots({(h, L) for h, _j0, L in _lane_runs(eps)})
    per_eval = 2 * log_steps + 6 + lane_slots
    stack = 2 * per_eval * frame + 4 * frame + 4 * out
    if bf16:
        stack += frame  # the rounded-operand copy
    return stack <= _VMEM_BUDGET


def _fits_fused_3d(bx: int, by: int, bz: int, eps: int, itemsize: int,
                   bf16: bool = False) -> bool:
    """3D twin of :func:`_fits_fused` over the (bx+2e+pad, by+2e, bz+2e)
    frame."""
    _heights, parts_by_h, _pows, pad = _strip_plan_3d(eps)
    Rf = bx + 2 * eps + pad
    Ry = by + 2 * eps
    Lz = bz + 2 * eps
    frame = Rf * Ry * Lz * itemsize
    out = bx * by * bz * itemsize
    runs = _lane_runs_3d(eps)
    lane_slots = _lane_slots({(h, L) for h, _jj, _kk0, L in runs})
    log_steps = max(1, int(np.ceil(np.log2(Rf))))
    per_eval = 2 * log_steps + 4 + len(parts_by_h) + lane_slots
    stack = 2 * per_eval * frame + 4 * frame + 4 * out
    if bf16:
        stack += frame
    return stack <= _VMEM_BUDGET


def fits_fused(block_shape: tuple[int, ...], eps: int,
               dtype=jnp.float32, precision: str = "f32") -> bool:
    """Public gate: can the fused kernel family hold this per-device
    block's halo frame in VMEM?"""
    itemsize = jnp.dtype(dtype).itemsize
    bf16 = precision == "bf16"
    if len(block_shape) == 2:
        return _fits_fused(*block_shape, eps, itemsize, bf16=bf16)
    if len(block_shape) == 3:
        return _fits_fused_3d(*block_shape, eps, itemsize, bf16=bf16)
    raise ValueError(f"fused halo kernels are 2D/3D; got {block_shape}")


def require_fused(op, block_shape: tuple[int, ...], dtype,
                  ksteps: int = 1) -> None:
    """Loud honesty gate for ``comm='fused'``: every configuration the
    kernel family cannot serve is refused with guidance instead of being
    silently downgraded to the collective path (the same policy as the
    ensemble variants and --superstep)."""
    if len(block_shape) not in (2, 3):
        raise ValueError(
            f"comm='fused' serves 2D/3D grids; got rank {len(block_shape)}")
    if op.method != "pallas":
        raise ValueError(
            f"comm='fused' runs the Pallas halo kernel family and needs "
            f"method='pallas' explicitly (got method={op.method!r}); use "
            "comm='collective' for the XLA methods")
    if not getattr(op, "uniform", True):
        raise ValueError(
            "comm='fused' supports the uniform influence function only "
            "(J == 1, the sat/pallas identity); use comm='collective'")
    if max(1, int(ksteps)) != 1:
        raise ValueError(
            "comm='fused' fuses the exchange into each step kernel; the "
            "superstep's K-wide exchange is a different schedule — use "
            "comm='collective' with superstep, or superstep=1")
    _reject_f64_on_tpu(jnp.dtype(dtype))
    if not fits_fused(block_shape, op.eps, dtype,
                      getattr(op, "precision", "f32")):
        raise ValueError(
            f"comm='fused': per-device block {block_shape} with "
            f"eps={op.eps} exceeds the {_VMEM_BUDGET >> 20} MiB VMEM "
            "budget for the halo-resident frame; shard the grid over "
            "more devices or use comm='collective'")


def fused_transport() -> str:
    """Which transport ``comm='fused'`` engages on this backend:
    ``'rdma'`` (in-kernel remote DMA) on TPU, ``'interp'`` (the split
    kernel under the ppermute transport, Pallas interpreter) elsewhere —
    the off-TPU form exists so the CPU suite exercises and pins the
    fused compute body (module docstring)."""
    return "rdma" if _on_tpu() else "interp"


# ---------------------------------------------------------------------------
# The shared compute body: interior first, eps ring second
# ---------------------------------------------------------------------------


def _lane_window(eps: int) -> int:
    """Lane width of the 2D ring phase's left/right column windows:
    reads reach 3*eps - 1 lanes plus the lane-run roll slack (the
    wrap-garbage invariant of _strip_neighbor_sum), rounded up for
    Mosaic's lane tiling."""
    lmax = max((L for _h, _j0, L in _lane_runs(eps)), default=1)
    return _round_up(3 * eps + lmax + 7, 128)


def _nsum_phases_2d(w, bx: int, by: int, eps: int, out_ref,
                    phase: str) -> None:
    """Write the neighbor-sum region(s) of one phase into ``out_ref``.

    ``w`` is the (bx+2e+pad, by+2e) frame (operand-rounded already on
    the bf16 tier).  ``phase='interior'`` writes the halo-independent
    center; ``'ring'`` the eps-wide boundary frame; ``'all'`` the whole
    block in one oracle-shaped evaluation (degenerate blocks where no
    interior exists).  Every evaluation is `_strip_neighbor_sum` with
    the same plan the per-step kernel runs, so retained elements are
    bitwise the oracle's (module docstring).
    """
    pad = _window_pad(eps)
    Lf = by + 2 * eps
    e = eps
    if phase == "all":
        out_ref[:, :] = _strip_neighbor_sum(w, bx, by, e, row0=e, col0=e)
        return
    if phase == "interior":
        out_ref[e : bx - e, e : by - e] = _strip_neighbor_sum(
            w, bx - 2 * e, by - 2 * e, e, row0=2 * e, col0=2 * e)
        return
    assert phase == "ring"
    # top band: block rows [0, e), all columns
    out_ref[:e, :] = _strip_neighbor_sum(
        w[: 3 * e + pad, :], e, by, e, row0=e, col0=e)
    # bottom band: block rows [bx - e, bx)
    out_ref[bx - e : bx, :] = _strip_neighbor_sum(
        w[bx - e : bx + 2 * e + pad, :], e, by, e, row0=e, col0=e)
    # left / right column bands: middle rows, e columns each — narrow
    # lane windows (reads stay inside; _lane_window pins the slack)
    tm = bx - 2 * e
    wlan = min(Lf, _lane_window(e))
    out_ref[e : bx - e, :e] = _strip_neighbor_sum(
        w[e : bx - e + pad, :wlan], tm, e, e, row0=e, col0=e)
    out_ref[e : bx - e, by - e : by] = _strip_neighbor_sum(
        w[e : bx - e + pad, Lf - wlan :], tm, e, e, row0=e,
        col0=wlan - 2 * e)


def _nsum_phases_3d(w, bx: int, by: int, bz: int, eps: int, out_ref,
                    phase: str) -> None:
    """3D twin of :func:`_nsum_phases_2d`: interior box first, then the
    six face slabs of the eps ring (x slabs full-face, y slabs on
    middle-x rows, z slabs on the middle-xy core), each evaluated on a
    window sliced to its reach."""
    pad = _strip_plan_3d(eps)[3]
    e = eps
    if phase == "all":
        out_ref[:, :, :] = _block_neighbor_sum_3d(
            w, bx, by, bz, e, row0=e, col0=e, z0=e)
        return
    if phase == "interior":
        out_ref[e : bx - e, e : by - e, e : bz - e] = (
            _block_neighbor_sum_3d(w, bx - 2 * e, by - 2 * e, bz - 2 * e,
                                   e, row0=2 * e, col0=2 * e, z0=2 * e))
        return
    assert phase == "ring"
    # x-low / x-high slabs: block rows [0, e) and [bx-e, bx), full y x z
    out_ref[:e, :, :] = _block_neighbor_sum_3d(
        w[: 3 * e + pad, :, :], e, by, bz, e, row0=e, col0=e, z0=e)
    out_ref[bx - e : bx, :, :] = _block_neighbor_sum_3d(
        w[bx - e : bx + 2 * e + pad, :, :], e, by, bz, e, row0=e,
        col0=e, z0=e)
    # y slabs on the middle-x rows (no rolls cross y: 3e width suffices)
    tm = bx - 2 * e
    Ry = by + 2 * e
    out_ref[e : bx - e, :e, :] = _block_neighbor_sum_3d(
        w[e : bx - e + pad, : 3 * e, :], tm, e, bz, e, row0=e, col0=e,
        z0=e)
    out_ref[e : bx - e, by - e : by, :] = _block_neighbor_sum_3d(
        w[e : bx - e + pad, Ry - 3 * e :, :], tm, e, bz, e, row0=e,
        col0=e, z0=e)
    # z slabs on the middle-xy core — narrow lane windows
    tn = by - 2 * e
    lmax = max((L for _h, _jj, _k0, L in _lane_runs_3d(eps)), default=1)
    Lz = bz + 2 * e
    wlan = min(Lz, _round_up(3 * e + lmax + 7, 128))
    out_ref[e : bx - e, e : by - e, :e] = _block_neighbor_sum_3d(
        w[e : bx - e + pad, e : by + e, :wlan], tm, tn, e, e, row0=e,
        col0=e, z0=e)
    out_ref[e : bx - e, e : by - e, bz - e : bz] = _block_neighbor_sum_3d(
        w[e : bx - e + pad, e : by + e, Lz - wlan :], tm, tn, e, e,
        row0=e, col0=e, z0=wlan - 2 * e)


def _degenerate(block_shape: tuple[int, ...], eps: int) -> bool:
    """No pure-interior cells (a multi-hop-sized block): the kernel runs
    one whole-block oracle-shaped evaluation after the wait — there is
    nothing to overlap, and we say so rather than fake a split."""
    return any(int(b) <= 2 * eps for b in block_shape)


# ---------------------------------------------------------------------------
# Split kernel: the fused compute body over a pre-filled frame
# ---------------------------------------------------------------------------


def _kernel_params_fused(collective_id: int | None = None):
    if _on_tpu():
        kw = dict(vmem_limit_bytes=_VMEM_LIMIT)
        if collective_id is not None:
            kw["collective_id"] = collective_id
            kw["has_side_effects"] = True
        cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
        try:
            return dict(compiler_params=cls(**kw))
        except TypeError:  # pre-has_side_effects TPUCompilerParams
            kw.pop("has_side_effects", None)
            return dict(compiler_params=cls(**kw))
    return dict(interpret=True)


@functools.lru_cache(maxsize=None)
def build_split_nsum_2d(eps: int, bx: int, by: int, dtype_name: str,
                        precision: str = "f32"):
    """(frame: (bx+2e+pad, by+2e)) -> (bx, by) neighbor sum, computed
    interior phase then ring phase — the fused kernel's compute body
    with the transport factored out (module docstring).  Interpreter
    mode off-TPU; bitwise the `build_neighbor_sum_2d` oracle."""
    dtype = jnp.dtype(dtype_name)
    _reject_f64_on_tpu(dtype)
    bf16 = precision == "bf16"
    degen = _degenerate((bx, by), eps)

    def kernel(frame_ref, out_ref):
        w = frame_ref[:]
        if bf16:
            # the tier's operand semantic: one bf16 round-trip of the
            # state before any accumulation (nonlocal_op._bf16_round)
            w = w.astype(jnp.bfloat16).astype(dtype)
        if degen:
            _nsum_phases_2d(w, bx, by, eps, out_ref, "all")
        else:
            _nsum_phases_2d(w, bx, by, eps, out_ref, "interior")
            _nsum_phases_2d(w, bx, by, eps, out_ref, "ring")

    def split_nsum(frame):
        vma = array_vma(frame)
        return pl.pallas_call(
            kernel,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            out_shape=out_struct((bx, by), dtype, vma=vma),
            **_kernel_params_fused(),
        )(frame)

    return split_nsum


@functools.lru_cache(maxsize=None)
def build_split_nsum_3d(eps: int, bx: int, by: int, bz: int,
                        dtype_name: str, precision: str = "f32"):
    """3D twin of :func:`build_split_nsum_2d` over the
    (bx+2e+pad, by+2e, bz+2e) frame."""
    dtype = jnp.dtype(dtype_name)
    _reject_f64_on_tpu(dtype)
    bf16 = precision == "bf16"
    degen = _degenerate((bx, by, bz), eps)

    def kernel(frame_ref, out_ref):
        w = frame_ref[:]
        if bf16:
            w = w.astype(jnp.bfloat16).astype(dtype)
        if degen:
            _nsum_phases_3d(w, bx, by, bz, eps, out_ref, "all")
        else:
            _nsum_phases_3d(w, bx, by, bz, eps, out_ref, "interior")
            _nsum_phases_3d(w, bx, by, bz, eps, out_ref, "ring")

    def split_nsum(frame):
        vma = array_vma(frame)
        return pl.pallas_call(
            kernel,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            out_shape=out_struct((bx, by, bz), dtype, vma=vma),
            **_kernel_params_fused(),
        )(frame)

    return split_nsum


# ---------------------------------------------------------------------------
# RDMA kernel: exchange started in-kernel, overlapped with the interior
# ---------------------------------------------------------------------------


def _build_rdma_kernel(dims: int, eps: int, block_shape, mesh_shape,
                       axis_names, dtype, precision, frame_shape):
    """The fused step kernel body shared by 2D/3D: prep frame -> neighbor
    barrier -> start remote DMAs -> interior phase -> recv waits -> ring
    phase -> send waits (the frame must not be re-prepped by the next
    step while a DMA still reads it)."""
    bf16 = precision == "bf16"
    plan = plan_exchange(mesh_shape, block_shape, eps)
    degen = _degenerate(block_shape, eps)
    center = tuple(slice(eps, eps + b) for b in block_shape)

    def kernel(u_ref, out_ref, frame_ref, send_sems, recv_sems):
        idx = [lax.axis_index(n) for n in axis_names]

        def exists(offsets, sign):
            """Whether my neighbor at sign*offsets is inside the mesh."""
            ok = None
            for ax, o in enumerate(offsets):
                c = idx[ax] + sign * o
                in_ax = (c >= 0) & (c < mesh_shape[ax])
                ok = in_ax if ok is None else ok & in_ax
            return ok

        # -- prep: zero collar (volumetric BC for never-targeted halo
        # regions and the chain pad) + the block in the frame center
        frame_ref[...] = jnp.zeros(frame_shape, dtype)
        frame_ref[center] = u_ref[...]
        # -- readiness barrier: tell each device that SENDS to me that
        # my frame is safe to land in; wait for the same signal from
        # each device I send to (one signal per directed plan edge).
        # Step t+1 signals can never pollute a step t wait: a neighbor
        # reaches its t+1 signal only after finishing step t, which
        # required MY step t bands — sent after my own t wait completed.
        bar = pltpu.get_barrier_semaphore()
        for msg in plan:
            @pl.when(exists(msg.offset, -1))
            def _signal(msg=msg):
                pltpu.semaphore_signal(
                    bar, inc=1,
                    device_id=tuple(idx[ax] - o
                                    for ax, o in enumerate(msg.offset)),
                    device_id_type=pltpu.DeviceIdType.MESH)
        for msg in plan:
            @pl.when(exists(msg.offset, +1))
            def _await(msg=msg):
                pltpu.semaphore_wait(bar, 1)
        # -- start every band; the DMAs fly while the interior computes
        descs = []
        for i, msg in enumerate(plan):
            src = tuple(slice(a + eps, b + eps) for a, b in msg.src)
            dst = tuple(slice(a, b) for a, b in msg.dst)
            desc = pltpu.make_async_remote_copy(
                src_ref=frame_ref.at[src],
                dst_ref=frame_ref.at[dst],
                send_sem=send_sems.at[i],
                recv_sem=recv_sems.at[i],
                device_id=tuple(idx[ax] + o
                                for ax, o in enumerate(msg.offset)),
                device_id_type=pltpu.DeviceIdType.MESH)
            descs.append(desc)

            @pl.when(exists(msg.offset, +1))
            def _start(desc=desc):
                desc.start()

        nsum_phases = _nsum_phases_2d if dims == 2 else _nsum_phases_3d

        def phases(phase):
            w = frame_ref[:]
            if bf16:
                w = w.astype(jnp.bfloat16).astype(dtype)
            nsum_phases(w, *block_shape, eps, out_ref, phase)

        if not degen:
            phases("interior")
        # -- recv waits: message i on MY recv semaphore is my -offset
        # neighbor's message i (plan_exchange docstring); absent senders
        # leave the zero collar in place
        for i, msg in enumerate(plan):
            @pl.when(exists(msg.offset, -1))
            def _wait_recv(desc=descs[i]):
                desc.wait_recv()
        phases("all" if degen else "ring")
        # -- send waits: our outbound reads of frame_ref must complete
        # before the next step's prep overwrites it
        for i, msg in enumerate(plan):
            @pl.when(exists(msg.offset, +1))
            def _wait_send(desc=descs[i]):
                desc.wait_send()

    n_msgs = max(1, len(plan))
    scratch = [
        pltpu.VMEM(frame_shape, dtype),
        pltpu.SemaphoreType.DMA((n_msgs,)),
        pltpu.SemaphoreType.DMA((n_msgs,)),
    ]
    return kernel, scratch


@functools.lru_cache(maxsize=None)
def build_fused_nsum_2d(eps: int, bx: int, by: int, dtype_name: str,
                        mesh_shape: tuple[int, int],
                        axis_names: tuple[str, str] = ("x", "y"),
                        precision: str = "f32"):
    """(u_blk: (bx, by)) -> (bx, by) neighbor sum with the halo exchange
    fused into the kernel via remote DMA (TPU only; must be called
    inside a shard_map over ``axis_names``).  See the module docstring
    for the schedule and the bit-identity argument."""
    if not _on_tpu():
        raise ValueError(
            "build_fused_nsum_2d is the TPU remote-DMA kernel; off-TPU "
            "the fused path runs the split kernel under the ppermute "
            "transport (fused_transport())")
    dtype = jnp.dtype(dtype_name)
    _reject_f64_on_tpu(dtype)
    bf16 = precision == "bf16"
    if not _fits_fused(bx, by, eps, dtype.itemsize, bf16=bf16):
        raise ValueError(
            f"fused halo kernel: block {bx}x{by} eps={eps} exceeds the "
            f"{_VMEM_BUDGET >> 20} MiB VMEM budget; shard further or use "
            "comm='collective'")
    pad = _window_pad(eps)
    frame_shape = (bx + 2 * eps + pad, by + 2 * eps)
    kernel, scratch = _build_rdma_kernel(
        2, eps, (bx, by), tuple(mesh_shape), tuple(axis_names), dtype,
        precision, frame_shape)

    def fused_nsum(u_blk):
        vma = array_vma(u_blk)
        return pl.pallas_call(
            kernel,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            out_shape=out_struct((bx, by), dtype, vma=vma),
            scratch_shapes=scratch,
            **_kernel_params_fused(_COLLECTIVE_ID_2D),
        )(u_blk)

    return fused_nsum


@functools.lru_cache(maxsize=None)
def build_fused_nsum_3d(eps: int, bx: int, by: int, bz: int,
                        dtype_name: str,
                        mesh_shape: tuple[int, int, int],
                        axis_names: tuple[str, str, str] = ("x", "y", "z"),
                        precision: str = "f32"):
    """3D twin of :func:`build_fused_nsum_2d`."""
    if not _on_tpu():
        raise ValueError(
            "build_fused_nsum_3d is the TPU remote-DMA kernel; off-TPU "
            "the fused path runs the split kernel under the ppermute "
            "transport (fused_transport())")
    dtype = jnp.dtype(dtype_name)
    _reject_f64_on_tpu(dtype)
    bf16 = precision == "bf16"
    if not _fits_fused_3d(bx, by, bz, eps, dtype.itemsize, bf16=bf16):
        raise ValueError(
            f"fused halo kernel: block {bx}x{by}x{bz} eps={eps} exceeds "
            f"the {_VMEM_BUDGET >> 20} MiB VMEM budget; shard further or "
            "use comm='collective'")
    pad = _strip_plan_3d(eps)[3]
    frame_shape = (bx + 2 * eps + pad, by + 2 * eps, bz + 2 * eps)
    kernel, scratch = _build_rdma_kernel(
        3, eps, (bx, by, bz), tuple(mesh_shape), tuple(axis_names), dtype,
        precision, frame_shape)

    def fused_nsum(u_blk):
        vma = array_vma(u_blk)
        return pl.pallas_call(
            kernel,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            out_shape=out_struct((bx, by, bz), dtype, vma=vma),
            scratch_shapes=scratch,
            **_kernel_params_fused(_COLLECTIVE_ID_3D),
        )(u_blk)

    return fused_nsum


# ---------------------------------------------------------------------------
# Solver-facing maker
# ---------------------------------------------------------------------------


def halo_stats(mesh_shape: tuple[int, ...], block_shape: tuple[int, ...],
               eps: int, comm: str, itemsize: int) -> dict:
    """Per-device, per-exchange-round traffic of one schedule — the
    numbers behind the /halo/bytes and /halo/exchanges counters and the
    halo.exchange span attributes (obs wiring in the distributed
    solvers).  Static host-side arithmetic: no fence, no device read."""
    if comm == "fused":
        plan = plan_exchange(mesh_shape, block_shape, eps)
        return {"messages": len(plan),
                "bytes": plan_bytes(plan, itemsize)}
    nmsg = sum(2 * min(len(hop_widths(eps, int(b))), max(int(n) - 1, 0))
               for b, n in zip(block_shape, mesh_shape, strict=True))
    return {"messages": nmsg,
            "bytes": collective_bytes(mesh_shape, block_shape, eps,
                                      itemsize)}


def make_fused_apply(op, mesh_shape: tuple[int, ...],
                     axis_names: tuple[str, ...]):
    """The ``comm='fused'`` local operator for a distributed solver's
    shard_map body: (u_blk) -> L(u)_blk, halos included.

    On TPU the neighbor sum comes from the remote-DMA kernel.  Off-TPU
    the SAME compute body runs as the split kernel in the Pallas
    interpreter, with the bands moved by the existing collective
    transport (`halo_pad_nd`) — the form the CPU tier-1 suite pins
    BITWISE against the collective oracle.  Either way ``du`` is formed
    outside the kernel in exactly ``apply_padded``'s expression.
    """
    from nonlocalheatequation_tpu.parallel.halo import halo_pad_nd

    eps = int(op.eps)
    precision = getattr(op, "precision", "f32")
    dims = len(mesh_shape)
    transport = fused_transport()

    def nsum_fn(u_blk):
        name = jnp.dtype(u_blk.dtype).name
        if transport == "rdma":
            build = (build_fused_nsum_2d if dims == 2
                     else build_fused_nsum_3d)
            fused = build(eps, *u_blk.shape, name, tuple(mesh_shape),
                          tuple(axis_names), precision)
            return fused(u_blk)
        pad = (_window_pad(eps) if dims == 2
               else _strip_plan_3d(eps)[3])
        frame = halo_pad_nd(u_blk, eps, mesh_shape, axis_names)
        widths = [(0, 0)] * frame.ndim
        widths[0] = (0, pad)  # the chain-roll slack below the frame
        frame = jnp.pad(frame, widths)
        build = build_split_nsum_2d if dims == 2 else build_split_nsum_3d
        return build(eps, *u_blk.shape, name, precision)(frame)

    if dims == 2:
        def apply_fused(u_blk):
            # apply_padded's expression VERBATIM, same scalar fold order
            # (c * dh * dh — a different association costs the last ulp
            # of the bitwise contract): operand-rounded center on the
            # bf16 tier, full precision else
            return op.c * op.dh * op.dh * (
                nsum_fn(u_blk) - op.wsum * op._operand(u_blk))
    else:
        def apply_fused(u_blk):
            # the 3D apply_padded folds the scale c * dh**3
            return op.c * op.dh ** 3 * (
                nsum_fn(u_blk) - op.wsum * op._operand(u_blk))

    return apply_fused
