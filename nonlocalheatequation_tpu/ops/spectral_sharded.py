"""Sharded spectral transforms — the distributed twin of ops/spectral.py.

The whole-domain spectral tier (PR 7) is exact by the zero-collar
embedding argument (ops/spectral.py module docstring); its honesty
boundary refused every *halo-padded* entry point because a block's halo
carries neighbor data.  This module serves the sharded case class
WITHOUT crossing that boundary: the global 5-smooth zero-padded box is
still the transform domain — it is merely *computed distributed*, by a
per-axis pencil decomposition with ``lax.all_to_all`` transposes over
the gang's existing mesh axes (parallel/mesh_axes.py logical axes "x",
"y"[, "z"]).  Every wrapped read of the circulant multiply therefore
still lands in domain or zero collar, exactly as in the serial path; no
halo is ever wrapped.

Layout (2D, mesh (mx, my), block (bx, by), box (BX, BY),
BYr = BY//2 + 1, BYrp = BYr rounded up to a multiple of mx*my):

forward   (bx, by)                 real block, owner (i, j)
  a2a y   (bx/my, NY)              row pencils (split ax0, concat ax1)
  rfft    (bx/my, BYr)             last-axis real FFT, n=BY (implicit
                                   zero pad NY->BY == the y collar)
  pad     (bx/my, BYrp)            zero frequency columns to divisibility
  a2a y   (bx, BYrp/my)            freq chunk j of the x-block rows
  a2a x   (NX, BYrp/(mx*my))       column pencils, freq chunk j*mx + i
  fft     (BX, BYrp/(mx*my))       axis-0 complex FFT, n=BX (x collar)

so the global frequency array is laid out ``P(None, ("y", "x"))`` —
axis 0 replicated-size BX per shard's pencil, axis 1 sharded y-major
(chunk index j*mx + i).  The inverse runs the exact mirror (ifft,
slice [:NX], two inverse transposes, slice [:BYr], irfft n=BY, slice
[:NY], final transpose back to (bx, by)).  3D adds one more transpose
pair around the middle axis; the middle-axis FFT output (length BY) is
zero-padded to the next multiple of my *after* transforming — carrying
zero spectrum columns through the later stages costs nothing and
removes every box-size divisibility constraint (fft of zeros is zeros,
and the inverse slices them off before the middle-axis ifft).

Divisibility: beyond the solver's own block uniformity (mx | NX,
my | NY[, mz | NZ]) the pencil split needs only ``NX % (mx*my) == 0``
(2D) / ``NX % (mx*mz) == 0`` (3D) — the first transpose splits the
x-block rows across the last mesh axis.  ``supports_sharded_fft`` is
the capability gate the router publishes to the picker (serve/router
``sharded_fft_capability``); ``require_sharded_fft`` is the loud
construction-time refusal.  ``NLHEAT_FFT_SHARDED=0`` is the
kill-switch: the gate reports unsupported everywhere and every sharded
spectral pick falls back to the stencil tier.

Numerics: per-axis FFTs + transposes reassociate sums differently from
the one-shot ``rfftn``, so results hold the <= 1e-12 oracle contract
against ops/spectral.py (not bitwise) — the same relation fft already
has to shift/conv.  Runs are bitwise DETERMINISTIC run-to-run: the
schedule is static and all_to_all concatenation order is the fixed
mesh order (tests/test_spectral_sharded.py pins both).

Reference parity: the transform serves the operator of
src/2d_nonlocal_serial.cpp:198-221 (volumetric u = 0 collar) on the
distributed solver's grid (src/2d_nonlocal_distributed.cpp:360-1325);
the symbol baking discipline is ops/spectral.py's (host float64, physics
scalars outside the symbol).
"""

from __future__ import annotations

import os

import numpy as np

import jax.numpy as jnp
from jax import lax

from nonlocalheatequation_tpu.ops.spectral import fft_box, neighbor_symbol
from nonlocalheatequation_tpu.utils.compat import irfft_last, rfft_last


def _round_up(n: int, mult: int) -> int:
    """Smallest multiple of ``mult`` >= ``n``."""
    return -(-int(n) // int(mult)) * int(mult)


def sharded_fft_enabled() -> bool:
    """The kill-switch: ``NLHEAT_FFT_SHARDED=0`` disables the sharded
    spectral tier everywhere (capability gate reports unsupported, the
    solvers refuse construction) — one knob to fall back to the stencil
    gang fleet-wide."""
    return os.environ.get("NLHEAT_FFT_SHARDED", "1") != "0"


def supports_sharded_fft(shape, eps: int, mesh_shape) -> bool:
    """Whether the pencil decomposition serves ``shape`` on a mesh of
    ``mesh_shape`` (pure host arithmetic — no backend touch, safe for
    the router's capability probe under wedge discipline)."""
    if not sharded_fft_enabled():
        return False
    shape = tuple(int(n) for n in shape)
    mesh_shape = tuple(int(m) for m in mesh_shape)
    if len(shape) != len(mesh_shape) or len(shape) not in (2, 3):
        return False
    if any(n % m for n, m in zip(shape, mesh_shape)):
        return False  # the solver's own uniform-block requirement
    # the first transpose splits the x-block rows across the LAST axis
    return shape[0] % (mesh_shape[0] * mesh_shape[-1]) == 0


def require_sharded_fft(shape, eps: int, mesh_shape) -> None:
    """Loud construction-time refusal (never a silent downgrade) when
    the pencil decomposition cannot serve this (grid, mesh) pair."""
    if supports_sharded_fft(shape, eps, mesh_shape):
        return
    if not sharded_fft_enabled():
        raise ValueError(
            "method='fft' on the distributed path is disabled by "
            "NLHEAT_FFT_SHARDED=0 (kill-switch); unset it or run the "
            "stencil methods")
    raise ValueError(
        f"sharded fft cannot serve grid {tuple(shape)} on mesh "
        f"{tuple(mesh_shape)}: the pencil transposes need every axis "
        "to divide its mesh extent and the leading extent to divide "
        "mesh[0]*mesh[-1] (ops/spectral_sharded.py layout); pick a "
        "compatible mesh or run the stencil methods")


class ShardedSpectralPlan:
    """Baked transpose/transform schedule for one (shape, eps, mesh).

    ``fwd``/``inv`` are per-shard functions to call INSIDE shard_map
    over the plan's mesh axes; ``freq_spec`` is the PartitionSpec of
    global frequency-domain arrays (symbols, expo tables), and
    ``pad_freq`` pads a host rfftn-layout array to that global shape.
    """

    def __init__(self, shape, eps: int, mesh_shape, axis_names=None):
        shape = tuple(int(n) for n in shape)
        mesh_shape = tuple(int(m) for m in mesh_shape)
        require_sharded_fft(shape, eps, mesh_shape)
        from jax.sharding import PartitionSpec as P

        self.shape = shape
        self.eps = int(eps)
        self.mesh_shape = mesh_shape
        self.box = fft_box(shape, eps)
        nd = len(shape)
        self.axis_names = tuple(
            axis_names if axis_names is not None
            else ("x", "y", "z")[:nd])
        ndev = 1
        for m in mesh_shape:
            ndev *= m
        last_r = self.box[-1] // 2 + 1  # rfft bins of the last box axis
        if nd == 2:
            # frequency axis 1 padded so mx*my chunks tile it exactly
            self.freq_global_shape = (
                self.box[0], _round_up(last_r, ndev))
            self.freq_spec = P(None, (self.axis_names[1],
                                      self.axis_names[0]))
        else:
            # middle axis padded to a multiple of my (the transformed-
            # axis zero-pad trick), last to a multiple of mx*my*mz
            self.freq_global_shape = (
                self.box[0],
                _round_up(self.box[1], mesh_shape[1]),
                _round_up(last_r, ndev))
            self.freq_spec = P(None, self.axis_names[1],
                               (self.axis_names[2], self.axis_names[0]))
        self._last_r = last_r

    # -- host-side helpers --------------------------------------------------

    def pad_freq(self, arr: np.ndarray) -> np.ndarray:
        """Zero-pad a host array in rfftn frequency layout (box[:-1] +
        (box[-1]//2+1,)) to ``freq_global_shape`` — the padded columns
        multiply the zero spectrum the forward path carries there."""
        arr = np.asarray(arr)
        want = tuple(self.box[:-1]) + (self._last_r,)
        if arr.shape != want:
            raise ValueError(
                f"frequency array shape {arr.shape} != rfftn layout "
                f"{want} of box {self.box}")
        pad = [(0, g - s) for s, g in
               zip(arr.shape, self.freq_global_shape, strict=True)]
        return np.pad(arr, pad)

    def neighbor_symbol_padded(self, weights) -> np.ndarray:
        """The baked neighbor symbol (ops/spectral.neighbor_symbol —
        host float64, cached) in the plan's padded frequency layout."""
        return self.pad_freq(neighbor_symbol(weights, self.box))

    def a2a_schedule(self):
        """The forward transposes as (axis_extent, elems, complex)
        triples — static host arithmetic for the observability layer
        (the inverse path is the exact mirror: same traffic)."""
        if len(self.shape) == 2:
            (mx, my), (bx, by) = self.mesh_shape, self._block()
            BYrp = self.freq_global_shape[1]
            return [
                (my, bx * by, False),
                (my, (bx // my) * BYrp, True),
                (mx, bx * (BYrp // my), True),
            ]
        (mx, my, mz), (bx, by, bz) = self.mesh_shape, self._block()
        BX, BYp, BZp = self.freq_global_shape
        return [
            (mz, bx * by * bz, False),
            (mz, (bx // mz) * by * BZp, True),
            (my, bx * by * (BZp // mz), True),
            (my, bx * BYp * (BZp // (mz * my)), True),
            (mx, self.shape[0] * (BYp // my) * (BZp // (mz * mx)), True),
        ]

    def _block(self):
        return tuple(n // m for n, m in
                     zip(self.shape, self.mesh_shape, strict=True))

    # -- the per-shard transforms (call inside shard_map) -------------------

    def fwd(self, u_blk: jnp.ndarray) -> jnp.ndarray:
        """Real block -> this shard's pencil of the global box rfft
        (module-docstring layout).  2D and 3D share the outer stages;
        3D inserts the middle-axis pair."""
        if len(self.shape) == 2:
            return self._fwd2(u_blk)
        return self._fwd3(u_blk)

    def inv(self, h_blk: jnp.ndarray) -> jnp.ndarray:
        """Frequency pencil -> the shard's (block-shaped) slice of the
        inverse transform's DOMAIN interior (collar discarded — the
        inverse of fwd up to per-axis FFT roundoff)."""
        if len(self.shape) == 2:
            return self._inv2(h_blk)
        return self._inv3(h_blk)

    def _fwd2(self, u):
        ax, ay = self.axis_names
        mx, my = self.mesh_shape
        BX = self.box[0]
        BYrp = self.freq_global_shape[1]
        if my > 1:  # (bx, by) -> (bx/my, NY) row pencils
            u = lax.all_to_all(u, ay, split_axis=0, concat_axis=1,
                               tiled=True)
        h = rfft_last(u, self.box[1])  # n=BY: the y zero collar
        h = jnp.pad(h, ((0, 0), (0, BYrp - h.shape[1])))
        if my > 1:  # back to x-block rows, freq chunk j
            h = lax.all_to_all(h, ay, split_axis=1, concat_axis=0,
                               tiled=True)
        if mx > 1:  # column pencils: all x-block rows, freq chunk j*mx+i
            h = lax.all_to_all(h, ax, split_axis=1, concat_axis=0,
                               tiled=True)
        # n=BX pads NX -> BX with zeros: the x collar
        return jnp.fft.fft(h, n=BX, axis=0)

    def _inv2(self, h):
        ax, ay = self.axis_names
        mx, my = self.mesh_shape
        NX, NY = self.shape
        u = jnp.fft.ifft(h, axis=0)[:NX]
        if mx > 1:
            u = lax.all_to_all(u, ax, split_axis=0, concat_axis=1,
                               tiled=True)
        if my > 1:
            u = lax.all_to_all(u, ay, split_axis=0, concat_axis=1,
                               tiled=True)
        u = irfft_last(u[..., : self._last_r], self.box[1])[..., :NY]
        if my > 1:
            u = lax.all_to_all(u, ay, split_axis=1, concat_axis=0,
                               tiled=True)
        return u

    def _fwd3(self, u):
        ax, ay, az = self.axis_names
        mx, my, mz = self.mesh_shape
        BX, BYp, BZp = self.freq_global_shape
        BY = self.box[1]
        if mz > 1:  # (bx, by, bz) -> (bx/mz, by, NZ) z pencils
            u = lax.all_to_all(u, az, split_axis=0, concat_axis=2,
                               tiled=True)
        h = rfft_last(u, self.box[2])  # n=BZ: the z zero collar
        h = jnp.pad(h, ((0, 0), (0, 0), (0, BZp - h.shape[2])))
        if mz > 1:  # back to x-block rows, z-freq chunk l
            h = lax.all_to_all(h, az, split_axis=2, concat_axis=0,
                               tiled=True)
        if my > 1:  # y pencils, z-freq chunk l*my + j
            h = lax.all_to_all(h, ay, split_axis=2, concat_axis=1,
                               tiled=True)
        h = jnp.fft.fft(h, n=BY, axis=1)  # n=BY: the y collar
        # transformed-axis pad BY -> BYp: zero spectrum columns ride
        # through the remaining stages (fft of zeros is zeros) so the
        # box never needs my-divisibility; inverse slices them off
        h = jnp.pad(h, ((0, 0), (0, BYp - BY), (0, 0)))
        if my > 1:  # y chunk j back, z-freq chunk l
            h = lax.all_to_all(h, ay, split_axis=1, concat_axis=2,
                               tiled=True)
        if mx > 1:  # x pencils: all rows, z-freq chunk l*mx + i
            h = lax.all_to_all(h, ax, split_axis=2, concat_axis=0,
                               tiled=True)
        return jnp.fft.fft(h, n=BX, axis=0)  # n=BX: the x collar

    def _inv3(self, h):
        ax, ay, az = self.axis_names
        mx, my, mz = self.mesh_shape
        NX, NY, NZ = self.shape
        BY = self.box[1]
        u = jnp.fft.ifft(h, axis=0)[:NX]
        if mx > 1:
            u = lax.all_to_all(u, ax, split_axis=0, concat_axis=2,
                               tiled=True)
        if my > 1:
            u = lax.all_to_all(u, ay, split_axis=2, concat_axis=1,
                               tiled=True)
        u = jnp.fft.ifft(u[:, :BY, :], axis=1)[:, :NY, :]
        if my > 1:
            u = lax.all_to_all(u, ay, split_axis=1, concat_axis=2,
                               tiled=True)
        if mz > 1:
            u = lax.all_to_all(u, az, split_axis=0, concat_axis=2,
                               tiled=True)
        u = irfft_last(u[..., : self._last_r], self.box[2])[..., :NZ]
        if mz > 1:
            u = lax.all_to_all(u, az, split_axis=2, concat_axis=0,
                               tiled=True)
        return u


#: Plan cache keyed by (shape, eps, mesh_shape, axis_names) — plans are
#: pure schedules (no device state), shared freely across solvers.
_plan_cache: dict = {}


def get_plan(shape, eps: int, mesh_shape, axis_names=None
             ) -> ShardedSpectralPlan:
    """Cached :class:`ShardedSpectralPlan` constructor."""
    key = (tuple(int(n) for n in shape), int(eps),
           tuple(int(m) for m in mesh_shape),
           tuple(axis_names) if axis_names is not None else None)
    plan = _plan_cache.get(key)
    if plan is None:
        plan = ShardedSpectralPlan(shape, eps, mesh_shape, axis_names)
        _plan_cache[key] = plan
    return plan
