"""Spectral (FFT/circulant) fast path for the nonlocal operator.

On the uniform grid the horizon operator is a convolution with a fixed
eps-ball stencil (SURVEY.md section 0: the reference's ``sum_local``
walks the same offset set at every point), so it is diagonalized exactly
by the DFT of a periodic box — an O(N log N) apply whose cost is
independent of eps, where the stencil paths pay O(N * eps^d).

Volumetric boundary (the reference's u = 0 outside the domain,
src/2d_nonlocal_serial.cpp:213-221): embed the (n_1, ..., n_d) grid in a
zero-padded periodic box with N_a >= n_a + eps points per axis.  Every
read an interior point makes at offset |o| <= eps then lands either in
the domain or in the zero collar — including the wrapped reads, which
land in the SAME collar from the other side (index -j wraps to N - j >=
n for N >= n + eps).  Circular convolution over the box therefore equals
the volumetric-boundary operator exactly; the interior slice of the
inverse transform is the answer and the collar output is discarded.
Box sizes round up to the next 5-smooth integer for FFT speed (extra
zeros keep the embedding argument intact).

The symbol is baked per (weights, box) as a host-side float64 constant —
the same discipline as the kernel paths' baked scalars (ops/pallas_kernel
section comment): ``sigma(xi) = sum_o w_o cos(xi . o)`` is the real DFT
of the centered offset kernel (real and even, so its transform is real),
computed once via ``np.fft.rfftn`` of the kernel embedding;
``symbol_direct`` is the literal cosine sum the tests pin it against.
The full operator symbol ``lambda(xi) = c*h^d * (sigma(xi) - Wsum)``
(equivalently ``c*h^d * sum_o w_o (cos(xi . o) - 1)``) is what the
exponential integrator (models/steppers.py) exponentiates; it is <= 0
everywhere, vanishing at DC, which is the unconditional-stability fact
the ``expo`` stepper rests on.

Honesty boundary: the embedding argument above is exact for ONE operator
application with the collar re-zeroed before it — exactly what the
per-step paths do — so ``method='fft'`` holds the same <= 1e-12 oracle
contract as conv/shift/sat.  It does NOT extend to halo-padded
distributed blocks (a block's halo carries neighbor data, not zeros), so
the padded entry points refuse fft loudly instead of wrapping garbage.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from nonlocalheatequation_tpu.obs.metrics import REGISTRY
from nonlocalheatequation_tpu.utils.compat import irfftn, rfftn

#: Baked neighbor-sum symbols, keyed by (weights bytes, box).  Symbols
#: are pure functions of (weights, box) — physics scalars (c, dt, k)
#: stay OUTSIDE the symbol so one baked array serves every operator that
#: shares a stencil, exactly like the stencil masks themselves.
_symbol_cache: dict = {}

#: Process-wide count of operator applications entering the fft path.
#: Python-level (incremented when the apply is TRACED or run eagerly —
#: under jit that is once per compiled program, the honest "how many fft
#: programs were built/entered" number, with zero per-step device cost).
_fft_applies = REGISTRY.counter("/op/fft-applies")


def fft_size(n: int) -> int:
    """Smallest 5-smooth integer >= n (FFT-friendly box edge)."""
    if n <= 1:
        return 1
    best = None
    p2 = 1
    while p2 < 2 * n:
        p23 = p2
        while p23 < 2 * n:
            p235 = p23
            while p235 < n:
                p235 *= 5
            if best is None or p235 < best:
                best = p235
            p23 *= 3
        p2 *= 2
    return best


def fft_box(shape, eps: int) -> tuple:
    """Padded periodic box for a grid of ``shape`` and horizon ``eps``:
    per axis the smallest 5-smooth size >= n + eps (the collar-width
    bound from the module docstring)."""
    return tuple(fft_size(int(n) + int(eps)) for n in shape)


def _kernel_embedding(weights: np.ndarray, box: tuple) -> np.ndarray:
    """The centered offset kernel placed in the periodic box: entry at
    index (o mod N) per axis carries w_o, offsets o in [-eps, eps]."""
    w = np.asarray(weights, np.float64)
    eps = (w.shape[0] - 1) // 2
    k = np.zeros(box, np.float64)
    # roll the (2eps+1)^d block so offset 0 lands at index 0
    idx = tuple(
        (np.arange(-eps, eps + 1) % n) for n in box
    )
    k[np.ix_(*idx)] = w
    return k


def neighbor_symbol(weights: np.ndarray, box: tuple) -> np.ndarray:
    """sigma(xi) = sum_o w_o cos(xi . o) on the rfftn frequency grid of
    ``box`` — the real DFT of the kernel embedding, baked float64.  The
    kernel is real and even, so the transform is real analytically; the
    float imaginary residue (~1e-17) is dropped."""
    key = (np.asarray(weights, np.float64).tobytes(),
           tuple(np.asarray(weights).shape), tuple(box))
    sig = _symbol_cache.get(key)
    if sig is None:
        sig = np.ascontiguousarray(
            np.fft.rfftn(_kernel_embedding(weights, box)).real)
        _symbol_cache[key] = sig
    return sig


def symbol_direct(weights: np.ndarray, box: tuple) -> np.ndarray:
    """The literal cosine sum sigma(xi) = sum_o w_o cos(xi . o) over the
    rfftn frequency grid — O(#offsets * #frequencies), the reference
    form the baked rfftn symbol is pinned against (tests/test_spectral).
    """
    w = np.asarray(weights, np.float64)
    eps = (w.shape[0] - 1) // 2
    d = w.ndim
    freq_shape = tuple(box[:-1]) + (box[-1] // 2 + 1,)
    xi = []
    for a, n in enumerate(box):
        npts = freq_shape[a]
        xi.append(2.0 * np.pi * np.arange(npts) / n)
    sig = np.zeros(freq_shape, np.float64)
    for o_flat, wo in np.ndenumerate(w):
        if wo == 0.0:
            continue
        phase = np.zeros(freq_shape, np.float64)
        for a in range(d):
            o = o_flat[a] - eps
            shape_a = [1] * d
            shape_a[a] = freq_shape[a]
            phase = phase + (xi[a] * o).reshape(shape_a)
        sig += wo * np.cos(phase)
    return sig


def operator_symbol(op, shape) -> np.ndarray:
    """lambda(xi) = c*h^d * (sigma(xi) - Wsum) for ``op`` on a grid of
    ``shape`` — the exact circulant spectrum of the volumetric operator
    on the padded box (<= 0 everywhere, 0 at DC).  float64; callers cast
    to their compute dtype."""
    from nonlocalheatequation_tpu.ops.nonlocal_op import case_scale

    box = fft_box(shape, op.eps)
    return case_scale(op) * (neighbor_symbol(op.weights, box) - op.wsum)


def _embed(u: jnp.ndarray, box: tuple) -> jnp.ndarray:
    return jnp.pad(u, [(0, b - s) for s, b in zip(u.shape, box, strict=True)])


def neighbor_sum_fft(op, u: jnp.ndarray) -> jnp.ndarray:
    """The eps-ball neighbor sum of an UNPADDED domain array via the
    padded-box rFFT: embed, multiply by the baked neighbor symbol,
    invert, slice the interior.  Exact for the volumetric boundary by
    the collar argument (module docstring)."""
    _fft_applies.inc()
    box = fft_box(u.shape, op.eps)
    sig = neighbor_symbol(op.weights, box)
    uh = rfftn(_embed(u, box))
    # the symbol is real: cast to the matching real dtype so complex64
    # spectra are scaled by f32 (and complex128 by f64) — no silent
    # upcast of the whole spectrum
    sig_dev = jnp.asarray(sig, jnp.real(uh).dtype)
    out = irfftn(uh * sig_dev, s=box)
    return out[tuple(slice(0, s) for s in u.shape)]


def neighbor_sum_fft_np(op, u: np.ndarray) -> np.ndarray:
    """NumPy float64 twin of :func:`neighbor_sum_fft` (oracle/test use)."""
    box = fft_box(u.shape, op.eps)
    sig = neighbor_symbol(op.weights, box)
    up = np.zeros(box, np.float64)
    up[tuple(slice(0, s) for s in u.shape)] = u
    out = np.fft.irfftn(np.fft.rfftn(up) * sig, s=box,
                        axes=tuple(range(-len(box), 0)))
    return out[tuple(slice(0, s) for s in u.shape)]
