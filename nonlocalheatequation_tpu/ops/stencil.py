"""Discrete horizon (stencil) geometry.

The reference rasterizes the eps-ball as vertical line segments: for each x
offset ``i`` in [-eps, eps] the column half-height is
``len_i = (long)sqrt(eps*eps - i*i)`` — a double->long TRUNCATION
(src/2d_nonlocal_serial.cpp:231, src/2d_nonlocal_distributed.cpp:1058-1060).
``eps`` is an integer in grid units.  That truncation defines the exact
discrete stencil shape; we reproduce it bit-for-bit here and everything else
in the framework (oracles, jit path, Pallas kernel, halo widths) derives from
these masks.

The center point is part of the stencil; it contributes ``u_j - u_i = 0`` to
the sum but DOES count toward the neighbor count, which matters because
out-of-domain points contribute ``0 - u_i`` (volumetric boundary condition,
problem_description.tex:140-142).
"""

from functools import lru_cache

import numpy as np


@lru_cache(maxsize=None)
def column_half_heights(eps: int) -> np.ndarray:
    """Half-height of the stencil column at each x offset in [-eps, eps].

    ``len_i = trunc(sqrt(eps^2 - i^2))`` computed in float64 exactly like the
    reference's ``len_1d_line`` (src/2d_nonlocal_serial.cpp:231).
    """
    i = np.arange(-eps, eps + 1, dtype=np.int64)
    out = np.sqrt(np.float64(eps * eps) - i.astype(np.float64) ** 2).astype(np.int64)
    out.setflags(write=False)  # cached: shared across callers
    return out


@lru_cache(maxsize=None)
def horizon_mask_1d(eps: int) -> np.ndarray:
    """1D stencil: every offset in [-eps, eps] (src/1d_nonlocal_serial.cpp:200)."""
    out = np.ones(2 * eps + 1, dtype=bool)
    out.setflags(write=False)
    return out


@lru_cache(maxsize=None)
def horizon_mask_2d(eps: int) -> np.ndarray:
    """(2*eps+1, 2*eps+1) bool mask of the rasterized eps-circle.

    mask[i+eps, j+eps] is True iff |j| <= trunc(sqrt(eps^2 - i^2)).
    Axis 0 is the x offset, axis 1 the y offset, matching the reference's
    sx/sy loop nesting (src/2d_nonlocal_serial.cpp:260-262).
    """
    heights = column_half_heights(eps)
    j = np.arange(-eps, eps + 1, dtype=np.int64)
    out = np.abs(j)[None, :] <= heights[:, None]
    out.setflags(write=False)
    return out


@lru_cache(maxsize=None)
def horizon_mask_3d(eps: int) -> np.ndarray:
    """(2e+1,)*3 bool mask of the rasterized eps-sphere (extension, no 3D in ref).

    Applies the reference's column-raster recipe once more per axis:
    |k| <= trunc(sqrt(eps^2 - i^2 - j^2)) for columns with i^2+j^2 <= eps^2.
    """
    i = np.arange(-eps, eps + 1, dtype=np.int64)
    rem = np.float64(eps * eps) - i[:, None] ** 2 - i[None, :] ** 2
    heights = np.where(rem >= 0, np.sqrt(np.maximum(rem.astype(np.float64), 0.0)), -1.0)
    heights = np.trunc(heights).astype(np.int64)
    out = np.abs(i)[None, None, :] <= heights[:, :, None]
    out.setflags(write=False)
    return out


def mask_point_count(mask: np.ndarray) -> int:
    """Number of stencil points (center included)."""
    return int(mask.sum())


def influence_weights(mask: np.ndarray, influence=None, dh: float = 1.0) -> np.ndarray:
    """Per-offset weights J(distance) on the stencil, float64.

    The reference's influence function is J == 1 everywhere
    (src/2d_nonlocal_serial.cpp:201); pass ``influence`` (a callable of the
    euclidean offset distance in grid units times dh) to generalize.
    """
    w = mask.astype(np.float64)
    if influence is not None:
        eps = (mask.shape[0] - 1) // 2
        axes = np.arange(-eps, eps + 1, dtype=np.float64)
        grids = np.meshgrid(*([axes] * mask.ndim), indexing="ij")
        dist = np.sqrt(sum(g * g for g in grids)) * dh
        w = w * np.vectorize(influence)(dist)
    return w
