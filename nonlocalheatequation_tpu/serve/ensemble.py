"""Shape-bucketed ensemble scheduler: N independent solves, few programs.

The reference's batch_tester protocol (src/1d_nonlocal_serial.cpp:239-266)
runs its parameter rows strictly sequentially, and so did ``run_batch``
(cli/common.py) — N cases pay N dispatch+fence roundtrips at ~64 ms each
over the axon tunnel (CLAUDE.md), plus N compiles on a cold cache.  This
module is the scheduler half of the batched ensemble engine:

* **Bucketing** — submitted :class:`EnsembleCase` rows group by
  ``(shape, nt, eps, test)``; the engine-level settings (dtype,
  precision tier, method, superstep depth, halo-comm engine) complete
  the key.  ``nt``
  joins the issue's ``(grid, eps, dtype, precision, ksteps)`` key
  because the scan length is part of the compiled program.  Cases in one
  bucket may differ in physics (k, dt, dh): the ops-layer makers bake a
  single scalar set when the chunk is physics-uniform (the grid-axis
  kernels) and fall back to inlining per-case solo traces when it is not
  (``make_batched_multi_step_fn_stacked``) — both are one compile and
  one dispatch per scan segment (ops/pallas_kernel.py section comment).
* **Padding** — each bucket is chunked to the largest allowed batch size
  and the final chunk is padded UP to the smallest allowed size that
  fits (default sizes 1/2/4/8), by duplicating the last real case.  A
  small, fixed set of batch shapes keeps the per-(shape, B) kernel set
  tiny, so the persistent XLA compile cache (bench.py, PR 1) hits across
  runs instead of compiling one program per case count.  Padding lanes
  are dropped before results are returned.
* **Dispatch** — one multi-step scan program per chunk: per chunk, the
  tunnel's dispatch toll is paid once, not once per case
  (``report.dispatches`` counts them; tests assert an 8-case bucket is
  ONE program and ONE dispatch).

Per-case results are unpadded and returned in submission order; the
caller computes ``error_l2`` exactly as the solo path does (the CLIs
feed the states back into their Solver objects — the oracle contract
``error_l2/#points <= 1e-6`` is unchanged, and the production/batched
outputs are bit-identical to the sequential solves on the f64 CPU suite,
tests/test_ensemble.py).

``NLHEAT_TUNE_BATCH=1`` adds the batch dimension to the autotuner: 2D
pallas production buckets probe the batched per-step/carried/superstep
variants plus the vmap fallback once per (shape, B) and run the winner
(utils/autotune.pick_batched_multi_step_fn).
"""

from __future__ import annotations

import json
import os
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from nonlocalheatequation_tpu.obs import trace as obs_trace
from nonlocalheatequation_tpu.obs.metrics import MetricsRegistry, backed

#: Allowed chunk sizes, ascending.  Buckets larger than the top size are
#: split into top-size chunks; the remainder pads up to the smallest
#: size that fits.
BATCH_SIZES = (1, 2, 4, 8)

#: Default bound on the in-memory compiled-program cache (LRU; env
#: ``NLHEAT_PROGRAM_CACHE_CAP`` or the ``program_cache_cap`` ctor arg
#: override).  A long-lived pipeline serving many buckets/engines must
#: not grow host memory without bound with compiled executables; evicted
#: programs rebuild on next touch (or reload from the AOT program store,
#: serve/program_store.py), and eviction can never change served results
#: — the cache holds compiled constants, not state.
PROGRAM_CACHE_CAP = 64


@dataclass
class EnsembleCase:
    """One solve submitted to the engine.

    ``shape`` is the grid ((nx,), (nx, ny) or (nx, ny, nz)); ``dh`` holds
    the 1D operator's dx for rank-1 cases.  ``test=True`` runs the
    manufactured-solution source (the batch_tester protocol);
    ``u0=None`` with ``test=True`` defaults to the spatial profile G,
    matching Solver*.test_init.

    ``mesh`` (ISSUE 17) keys an UNSTRUCTURED case: the content hash of a
    registered point cloud (serve/meshes.py).  ``shape`` is then the
    node count ``(n,)``, ``eps``/``dh`` are carried by the mesh itself
    (set them 0), and the hash joins :meth:`bucket_key` — so mesh
    buckets route sticky through the replica router and the hash
    reaches the engine's ``prog_key``/``store_key`` through the bucket
    key, which is what lets repeat-mesh traffic warm-boot compiled
    gather programs from the shared AOT store with zero retrace.
    """

    shape: tuple
    nt: int
    eps: int
    k: float
    dt: float
    dh: float
    test: bool = True
    u0: np.ndarray | None = None
    mesh: str | None = None

    def bucket_key(self):
        return (tuple(int(s) for s in self.shape), int(self.nt),
                int(self.eps), bool(self.test), self.mesh)

    def physics(self):
        return (float(self.k), float(self.dt), float(self.dh))


class EnsembleReport:
    """Observability counters for one engine lifetime (tests assert on
    them: an 8-case same-shape bucket must be 1 program / 1 dispatch).

    Since the obs subsystem (obs/metrics.py) every counter is BACKED by
    a metrics registry under HPX-style names (``/ensemble/cases``...):
    the fields below are properties over registry metrics, so the
    registry's Prometheus/JSON expositions and this report read the
    same storage.  The default registry is PRIVATE to the report (two
    engines in one process never share counters); the serving pipeline
    exposes its report's registry for scraping (cli ``--metrics-port``).
    """

    cases = backed("_m_cases")
    buckets = backed("_m_buckets")
    dispatches = backed("_m_dispatches")
    programs_built = backed("_m_programs_built")
    programs_loaded = backed("_m_programs_loaded")
    padded_cases = backed("_m_padded_cases")
    programs_evicted = backed("_m_programs_evicted")
    programs_resident = backed("_m_programs_resident")

    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        r = self.registry
        self._m_cases = r.counter("/ensemble/cases")
        self._m_buckets = r.counter("/ensemble/buckets")
        self._m_dispatches = r.counter("/ensemble/dispatches")
        self._m_programs_built = r.counter("/ensemble/programs-built")
        # programs materialized WITHOUT a build: AOT store hits
        # (serve/program_store.py) — programs-built keeps meaning
        # "traced+compiled here", so a recompile watchdog stays honest
        self._m_programs_loaded = r.counter("/ensemble/programs-loaded")
        self._m_padded_cases = r.counter("/ensemble/padded-cases")
        # the engine's LRU program cache (build_program): resident count
        # gauge + lifetime-exact eviction counter, under the /store
        # namespace with the AOT-store metrics they complement
        self._m_programs_evicted = r.counter("/store/evictions")
        self._m_programs_resident = r.gauge("/store/resident-programs")
        self.strategies: dict = {}

    def summary(self) -> str:
        # the built/loaded split stays visible here too: a fully warm
        # boot must read "0 built + N loaded", never "0 programs"
        loaded = (f" + {self.programs_loaded} loaded"
                  if self.programs_loaded else "")
        return (f"{self.cases} cases -> {self.buckets} buckets, "
                f"{self.dispatches} dispatches, "
                f"{self.programs_built} programs built{loaded} "
                f"({self.padded_cases} padding lanes)")

    def metrics(self) -> dict:
        """The engine counters as one dict (the --metrics-out payload
        for --ensemble runs; ServeReport overrides with the full serving
        dump)."""
        return {
            "cases": self.cases,
            "buckets": self.buckets,
            "dispatches": self.dispatches,
            "programs_built": self.programs_built,
            "programs_loaded": self.programs_loaded,
            "padded_cases": self.padded_cases,
            "strategies": {str(k): v for k, v in self.strategies.items()},
        }

    def metrics_json(self) -> str:
        return json.dumps(self.metrics())


class EnsembleEngine:
    """Run a list of :class:`EnsembleCase` as few batched programs.

    ``variant`` selects the multi-step composition for 2D pallas
    production buckets: ``per-step`` (default), ``carried``,
    ``superstep`` (needs ``ksteps >= 2``), ``stacked`` (per-case solo
    traces in one program), ``vmap`` (the parity oracle), or ``auto``
    (per-step, or the autotuner's batched winner under
    ``NLHEAT_TUNE_BATCH=1``).  Non-pallas methods, 1D/3D cases, and
    manufactured-source buckets under ``carried``/``superstep`` refuse
    loudly rather than silently running a different schedule.
    """

    VARIANTS = ("auto", "per-step", "carried", "superstep", "stacked",
                "vmap")

    #: halo-exchange engines a sharded (distributed-case) bucket can ask
    #: for; part of the program key so two engines differing only in
    #: comm never share compiled programs (ops/pallas_halo.py).  HONESTY
    #: NOTE: no ENGINE bucket builds a sharded program — every ensemble
    #: case this engine runs is a single-device solve, so comm='fused'
    #: changes the key (and is validated against the pallas-only rule)
    #: but not the compiled programs.  The sharded case class itself
    #: lives one tier up since ISSUE 12: the replica router dispatches
    #: grids above its ``shard_threshold`` to a GANG replica that runs
    #: them as space-parallel distributed solves (serve/router.py +
    #: parallel/gang.py ``solve_case_sharded``) — this knob keeps
    #: engine bucketing correct for any future in-engine sharding.
    COMMS = ("collective", "fused")

    def __init__(self, method: str = "auto", precision: str = "f32",
                 dtype=None, variant: str = "auto", ksteps: int = 0,
                 batch_sizes=BATCH_SIZES, comm: str = "collective",
                 stepper: str = "euler", stages: int = 0,
                 program_store=None, program_cache_cap: int | None = None,
                 store_backend: str | None = None):
        from nonlocalheatequation_tpu.models.steppers import STEPPERS

        if variant not in self.VARIANTS:
            raise ValueError(
                f"unknown ensemble variant {variant!r}; one of "
                f"{self.VARIANTS}")
        if variant == "superstep" and ksteps < 2:
            raise ValueError("variant='superstep' needs ksteps >= 2")
        if comm not in self.COMMS:
            raise ValueError(
                f"unknown comm {comm!r}; one of {self.COMMS}")
        if comm == "fused" and method != "pallas":
            # the fused halo family is pallas-only (require_fused); the
            # engine repeats the refusal up front so a sharded bucket
            # can never reach program build with an unservable key
            raise ValueError(
                "comm='fused' needs method='pallas' "
                "(ops/pallas_halo.require_fused)")
        if stepper not in STEPPERS:
            raise ValueError(
                f"unknown stepper {stepper!r}; one of {STEPPERS}")
        if stepper == "rkc" and stages < 2:
            raise ValueError("stepper='rkc' needs stages >= 2")
        if stepper == "expo" and method != "fft":
            # mirrors models/steppers.validate_stepper: the exponential
            # integrator IS the spectral symbol — refused up front so an
            # unservable key never reaches program build
            raise ValueError(
                "stepper='expo' requires method='fft' "
                "(models/steppers.validate_stepper)")
        if stepper != "euler" and variant in ("carried", "superstep",
                                              "vmap"):
            # the pallas carried/superstep schedules and the vmap
            # composition are forward-Euler programs; a non-Euler bucket
            # runs the stacked stepper composition (per-case solo scans
            # in one program) — refuse rather than silently switch
            # integrators
            raise ValueError(
                f"ensemble variant {variant!r} is Euler-only; "
                f"stepper={stepper!r} buckets run variant "
                "'auto'/'per-step'/'stacked' (the stacked stepper "
                "composition)")
        sizes = tuple(sorted({int(b) for b in batch_sizes}))
        if not sizes or sizes[0] < 1:
            raise ValueError(f"bad batch_sizes {batch_sizes!r}")
        cap = (program_cache_cap if program_cache_cap is not None
               else int(os.environ.get("NLHEAT_PROGRAM_CACHE_CAP") or
                        PROGRAM_CACHE_CAP))
        if cap < 0:
            raise ValueError(
                f"program_cache_cap must be >= 0, got {cap}")
        if cap == 0:
            # the repo-wide 0-knob convention (NLHEAT_SUPERSTEP=0,
            # NLHEAT_PROGRAM_STORE=0, ...): 0 turns the feature OFF —
            # for a cache CAP that means unbounded, the pre-LRU behavior
            cap = float("inf")
        self.method = method
        self.precision = precision
        self.dtype = dtype
        self.variant = variant
        self.ksteps = int(ksteps)
        self.batch_sizes = sizes
        self.comm = comm
        self.stepper = stepper
        self.stages = int(stages)
        self.report = EnsembleReport()
        #: LRU compiled-program cache, bounded at ``program_cache_cap``
        #: (eviction never changes served results — see PROGRAM_CACHE_CAP)
        self._programs: OrderedDict = OrderedDict()
        self.program_cache_cap = cap
        # AOT program store (serve/program_store.py): an explicit store
        # instance, a directory path, or None (consult the env at first
        # build).  Resolution is LAZY — build time is the execution path;
        # a constructor must never touch the backend (wedge discipline).
        self._program_store_arg = program_store
        self.program_store = None
        self._store_resolved = False
        # sibling engines share one store NAMESPACE keyed by backend:
        # the CPU fallback pins store_backend="cpu" so its programs can
        # never collide with the device engine's (serve/resilience.py)
        self.store_backend = store_backend

    def sibling(self, **overrides) -> "EnsembleEngine":
        """A fresh engine carrying this engine's settings (method /
        precision / dtype / variant / ksteps / batch_sizes) except
        ``overrides`` — with its OWN program cache and report.  The
        serving fault-tolerance layer (serve/resilience.py) builds its
        CPU-backend twin this way, so fallback programs never collide
        with the device engine's cache and fallback dispatches never
        perturb the device counters."""
        kw = dict(method=self.method, precision=self.precision,
                  dtype=self.dtype, variant=self.variant,
                  ksteps=self.ksteps, batch_sizes=self.batch_sizes,
                  comm=self.comm, stepper=self.stepper, stages=self.stages,
                  # the AOT store is SHARED (one namespace, backend in the
                  # key); the in-memory program cache and report are not
                  program_store=(self.program_store
                                 if self._store_resolved
                                 else self._program_store_arg),
                  program_cache_cap=self.program_cache_cap,
                  store_backend=self.store_backend)
        kw.update(overrides)
        return EnsembleEngine(**kw)

    def engine_key(self) -> tuple:
        """This engine's position on the picker's axes (serve/picker.py
        ``EngineChoice.key()``): the pool key the serving pipeline
        routes picked cases by."""
        return (self.stepper, self.stages, self.method, self.precision)

    def engine_for(self, stepper: str, stages: int, method: str,
                   precision: str) -> "EnsembleEngine":
        """A sibling configured for a PICKED engine (serve/picker.py):
        the stepper x stages x method x precision axes overridden, the
        variant forced to 'auto' (an operator-pinned Euler-only variant
        must not refuse a picked rkc bucket), the comm engine dropped
        to 'collective' when the picked method is not pallas (the fused
        halo family is pallas-only and the ctor refuses the pair — a
        fused fleet must still serve a picked fft/conv case), and the
        superstep depth kept only where it applies (the Euler pallas
        schedules).  Returns ``self`` when the pick IS this engine's
        configuration — the common case of a fleet whose default
        engine already matches."""
        if (stepper, int(stages), method, precision) == self.engine_key():
            return self
        return self.sibling(
            stepper=stepper, stages=int(stages), method=method,
            precision=precision, variant="auto",
            comm=self.comm if method == "pallas" else "collective",
            ksteps=self.ksteps if stepper == "euler" else 0)

    # -- case -> operator ---------------------------------------------------
    def _make_op(self, case: EnsembleCase):
        from nonlocalheatequation_tpu.ops.nonlocal_op import (
            NonlocalOp1D,
            NonlocalOp2D,
            NonlocalOp3D,
        )

        if case.mesh is not None:
            # mesh-keyed case: the operator is the registered point
            # cloud under this case's physics (serve/meshes.py caches
            # the rebuild; the stored edge table is hash-verified)
            from nonlocalheatequation_tpu.serve.meshes import get_mesh_op

            return get_mesh_op(case.mesh, case.k, case.dt)
        dim = len(case.shape)
        if dim == 1:
            # the 1D operator's method axis is shift|fft; the 2D/3D
            # engine settings (conv/sat/pallas/auto) all map to shift
            return NonlocalOp1D(case.eps, case.k, case.dt, case.dh,
                                method=("fft" if self.method == "fft"
                                        else "shift"),
                                precision=self.precision)
        cls = NonlocalOp2D if dim == 2 else NonlocalOp3D
        return cls(case.eps, case.k, case.dt, case.dh, method=self.method,
                   precision=self.precision)

    def _dtype(self):
        if self.dtype is not None:
            return jnp.dtype(self.dtype)
        return jnp.dtype(
            jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)

    # -- scheduling ---------------------------------------------------------
    def _chunks(self, idxs):
        """Split a bucket's case indices into top-batch-size runs; the
        padded size of each run is pad_chunk's decision alone."""
        top = self.batch_sizes[-1]
        for start in range(0, len(idxs), top):
            yield idxs[start : start + top]

    def pad_chunk(self, chunk: list) -> list:
        """Pad a closed chunk UP to the smallest allowed batch size that
        fits, duplicating the last real case (the scheduler padding rule —
        callers drop the padding lanes from the output)."""
        B = next((b for b in self.batch_sizes if b >= len(chunk)),
                 self.batch_sizes[-1])
        if len(chunk) > self.batch_sizes[-1]:
            raise ValueError(
                f"chunk of {len(chunk)} cases exceeds the top batch size "
                f"{self.batch_sizes[-1]}; split it first (engine._chunks / "
                "the serving window do)")
        pad = B - len(chunk)
        if pad:
            self.report.padded_cases += pad
            return chunk + [chunk[-1]] * pad
        return chunk

    def run(self, cases) -> list:
        """Solve every case; returns final states (np arrays, f64-exact
        casts of the engine dtype) in submission order."""
        cases = list(cases)
        self.report.cases += len(cases)
        results: list = [None] * len(cases)
        buckets: dict = {}
        for i, case in enumerate(cases):
            buckets.setdefault(case.bucket_key(), []).append(i)
        self.report.buckets += len(buckets)
        for key, idxs in buckets.items():
            for part in self._chunks(idxs):
                # span: the offline chunk lifecycle (pad -> build ->
                # dispatch -> fetch), a no-op unless a tracer is
                # installed (obs/trace.py — the serving pipeline traces
                # its own stages instead, per attempt)
                with obs_trace.span("ensemble.chunk", cat="ensemble",
                                    bucket=str(key), cases=len(part)):
                    chunk = self.pad_chunk([cases[i] for i in part])
                    out = self._run_chunk(key, chunk)
                for j, i in enumerate(part):
                    results[i] = np.asarray(out[j])
        return results

    # -- one chunk = one program, one dispatch ------------------------------
    # The chunk lifecycle is split into named stages so the offline run()
    # above and the async serving pipeline (serve/server.py) share the
    # SAME program construction and dispatch code — serving changes only
    # the schedule (when chunks close, how many dispatches are in flight,
    # when the fence happens), never the programs, which is what makes
    # served results bit-identical to run() on the same case set.
    def adopt_report(self, report) -> None:
        """Install a replacement report (the serving pipeline's
        ServeReport takes over the engine's counters).  A store already
        resolved against the OLD report's registry would keep counting
        into the discarded registry — drop the resolution so the next
        build re-binds ``/store/*`` to the new registry (an explicitly
        passed ProgramStore instance keeps its own binding: the caller
        owns that registry)."""
        from nonlocalheatequation_tpu.serve.program_store import (
            ProgramStore,
        )

        self.report = report
        if self._store_resolved and not isinstance(self._program_store_arg,
                                                   ProgramStore):
            self._store_resolved = False
            self.program_store = None

    def _resolve_store(self):
        """The engine's AOT program store (serve/program_store.py), or
        None.  Resolved lazily at first build — the execution path —
        so the constructor stays backend-free (wedge discipline); bound
        to the report's registry so ``/store/*`` metrics surface through
        the serving expositions."""
        if not self._store_resolved:
            from nonlocalheatequation_tpu.serve.program_store import (
                resolve_store,
            )

            self.program_store = resolve_store(
                self._program_store_arg, registry=self.report.registry)
            self._store_resolved = True
        return self.program_store

    def build_program(self, key, chunk):
        """Stage 1 (host): the chunk's compiled multi-step callable,
        cached per (bucket, size, variant, physics, dtype) — a cache hit
        costs nothing, so a pipeline can build chunk N+2's program while
        chunk N computes on the device.  The cache is a bounded LRU
        (``program_cache_cap``); with an AOT program store configured
        (serve/program_store.py) a cold key first tries a stored
        executable — a store hit materializes the program with ZERO
        retrace/recompile, a miss builds as always and persists the
        compiled executable for the next boot."""
        test = key[3]
        dtype = self._dtype()
        # stepper/stages join the program key (ISSUE 8): two engines
        # differing only in integrator must never share compiled
        # programs — a mixed-physics fleet buckets per integrator.
        # The mesh-hash dimension (ISSUE 17) rides in ``key`` itself
        # (EnsembleCase.bucket_key carries it), so two meshes with the
        # same node count can never share a compiled gather program,
        # while repeat traffic on ONE mesh hash warm-boots from the
        # shared AOT store below with zero retrace.
        prog_key = (key, len(chunk), self.variant,
                    tuple(c.physics() for c in chunk), dtype.name,
                    self.comm, self.stepper, self.stages)
        store = self._resolve_store()
        cache_key = prog_key
        if store is not None:
            from nonlocalheatequation_tpu.utils import donation

            # a store-materialized program is donation-FIXED (the AOT
            # binary either aliases arg 0 or not), unlike the lazy
            # per-call donated_jit wrappers the plain path caches — so
            # the donate decision joins the in-memory key too (the solo
            # wrapper's rule), and a depth/NLHEAT_DONATE change mid-life
            # re-materializes instead of serving a stale donating binary
            donate = donation.donation_on()
            cache_key = (prog_key, donate)
        multi = self._programs.get(cache_key)
        if multi is None:
            def build():
                # operators are only needed to BUILD a program (and for
                # the u0 test-mode default below); a cache/store hit
                # skips them
                with obs_trace.span("ensemble.build", cat="ensemble",
                                    bucket=str(key), cases=len(chunk),
                                    variant=self.variant):
                    ops = [self._make_op(c) for c in chunk]
                    return self._build_program(key, chunk, ops, test,
                                               dtype)

            loaded = False
            if store is None:
                multi = build()
            else:
                sds = jax.ShapeDtypeStruct((len(chunk),) + key[0], dtype)
                # the store key must carry MORE than prog_key: the
                # in-memory cache is private to one engine (whose
                # method/precision/ksteps are fixed for life), but the
                # store is shared across engines and sessions — without
                # these fields a bf16 engine could load an f32 engine's
                # executable for the same bucket.  donate joins via the
                # store digest (it changes the compiled binary).
                store_key = repr((prog_key, self.method, self.precision,
                                  self.ksteps))
                multi, outcome = store.load_or_build(
                    store_key, build, (sds, 0), donate=donate,
                    backend=self.store_backend)
                loaded = outcome == "hit"
                if loaded:
                    # _build_program never ran, so no variant label was
                    # computed; say honestly where the program came from
                    self.report.strategies[key] = "stored"
            self._programs[cache_key] = multi
            # honesty split: a store HIT materialized a program without
            # tracing or compiling anything — counted as loaded, never
            # as built (a recompile watchdog reads programs-built)
            if loaded:
                self.report.programs_loaded += 1
            else:
                self.report.programs_built += 1
            while len(self._programs) > self.program_cache_cap:
                self._programs.popitem(last=False)
                self.report.programs_evicted += 1
            self.report.programs_resident = len(self._programs)
        else:
            self._programs.move_to_end(cache_key)
        return multi

    def stage_inputs(self, chunk):
        """Stage 2 (host->device): the stacked initial state, a FRESH
        device buffer per chunk (each dispatch owns its input; nothing
        aliases an in-flight chunk's buffers)."""
        return jnp.asarray(np.stack([self._u0(c) for c in chunk]),
                           self._dtype())

    def dispatch_chunk(self, multi, U0):
        """Stage 3 (async): launch the chunk's program.  JAX dispatch is
        asynchronous — this returns a device future immediately; no fence
        happens here."""
        out = multi(U0, 0)
        self.report.dispatches += 1
        return out

    def _run_chunk(self, key, chunk):
        multi = self.build_program(key, chunk)
        out = self.dispatch_chunk(multi, self.stage_inputs(chunk))
        # stage 4, fused for the offline path: np.asarray is a full-value
        # fetch (a true fence even over the tunnel — the one host round
        # trip this schedule needs); the pipeline instead fences with a
        # scalar first so device and fetch time are observable separately
        return np.asarray(out)

    def _u0(self, case: EnsembleCase) -> np.ndarray:
        if case.u0 is not None:
            return np.asarray(case.u0, np.float64).reshape(case.shape)
        if not case.test:
            raise ValueError(
                "a production (test=False) EnsembleCase needs an initial "
                "state u0")
        if case.mesh is not None:
            # the unstructured profile is evaluated at the node coords
            return self._make_op(case).spatial_profile()
        return self._make_op(case).spatial_profile(*case.shape)

    def _build_program(self, key, chunk, ops, test, dtype):
        from nonlocalheatequation_tpu.ops.nonlocal_op import (
            make_batched_multi_step_fn_stacked,
            make_batched_multi_step_fn_vmap,
        )

        if chunk[0].mesh is not None:
            # mesh bucket (ISSUE 17): the Pallas strip-gather tier
            # (ops/pallas_gather.py) — every case in the bucket shares
            # the edge table (the hash is in the bucket key), physics
            # may differ per lane.  Euler-only, stacked composition;
            # anything the tier cannot honor refuses loudly (the
            # carried/superstep honesty rule below).
            if self.stepper != "euler":
                raise ValueError(
                    f"mesh buckets are Euler-only (the gather tier has "
                    f"no {self.stepper!r} schedule)")
            if self.method not in ("auto", "gather"):
                raise ValueError(
                    f"mesh buckets need method='gather' or 'auto' "
                    f"(engine has method={self.method!r})")
            if self.variant not in ("auto", "per-step", "stacked"):
                raise ValueError(
                    f"ensemble variant {self.variant!r} has no gather "
                    "form; mesh buckets run 'auto'/'per-step'/'stacked'")
            from nonlocalheatequation_tpu.ops.pallas_gather import (
                make_batched_gather_multi_step_fn,
            )

            self.report.strategies[key] = "gather[stacked]"
            return make_batched_gather_multi_step_fn(
                ops, key[1], dtype=dtype, test=test,
                precision=self.precision)
        shape, nt = key[0], key[1]
        dim = len(shape)
        op0 = ops[0]
        gs = lgs = None
        if test:
            parts = [op.source_parts(*shape) for op in ops]
            gs = [g for g, _ in parts]
            lgs = [lg for _, lg in parts]
        if self.stepper != "euler":
            # non-Euler buckets: the stacked stepper composition — each
            # case's solo rkc/expo scan inlined into ONE program (one
            # compile, one dispatch per chunk; bit-identical to the
            # sequential stepper solves by construction).  The ctor
            # already refused the Euler-only variants.
            from nonlocalheatequation_tpu.models.steppers import (
                make_batched_multi_step_fn,
            )

            self.report.strategies[key] = f"stacked[{self.stepper}]"
            return make_batched_multi_step_fn(
                ops, nt, dtype=dtype, test=test, gs=gs, lgs=lgs,
                stepper=self.stepper, stages=self.stages)
        resolved = self.method
        if dim == 2 and resolved == "auto":
            resolved = op0._resolve_method(shape[0], shape[1], dtype)
        elif dim == 3 and resolved == "auto":
            resolved = op0._resolve_method(*shape, dtype)
        pallas2d = dim == 2 and resolved == "pallas" and op0.uniform
        variant = self.variant
        if variant in ("carried", "superstep"):
            # honesty rule: these are 2D pallas production schedules; a
            # request that cannot engage is refused, never silently
            # downgraded (the same policy as --superstep on the CLIs)
            if not pallas2d:
                raise ValueError(
                    f"ensemble variant {variant!r} needs the 2D pallas "
                    f"method (bucket resolved to {resolved!r}, dim {dim})")
            if test:
                raise ValueError(
                    f"ensemble variant {variant!r} is production-only "
                    "(the carried/superstep kernels carry no manufactured "
                    "source); use per-step/stacked/vmap for --test_batch "
                    "solves")
        if variant == "auto":
            if (pallas2d and not test
                    and os.environ.get("NLHEAT_TUNE_BATCH") == "1"):
                from nonlocalheatequation_tpu.utils.autotune import (
                    pick_batched_multi_step_fn,
                )

                fn, winner = pick_batched_multi_step_fn(
                    ops, nt, shape, dtype, ksteps=self.ksteps)
                self.report.strategies[key] = f"tuned:{winner}"
                return fn
            variant = "per-step" if pallas2d else "vmap"
        self.report.strategies[key] = self._label(variant, ops, pallas2d)
        if variant == "vmap":
            gsa = np.stack(gs) if test else None
            lgsa = np.stack(lgs) if test else None
            return make_batched_multi_step_fn_vmap(
                ops, nt, dtype=dtype, test=test, gs=gsa, lgs=lgsa)
        if variant == "stacked":
            return make_batched_multi_step_fn_stacked(
                ops, nt, dtype=dtype, test=test, gs=gs, lgs=lgs)
        if not pallas2d:
            # per-step requested on a non-pallas bucket: the stacked
            # composition IS the per-step schedule there (each case's
            # solo scan, one program)
            return make_batched_multi_step_fn_stacked(
                ops, nt, dtype=dtype, test=test, gs=gs, lgs=lgs)
        from nonlocalheatequation_tpu.ops import pallas_kernel as pk

        if variant == "carried":
            return pk.make_batched_carried_multi_step_fn(ops, nt,
                                                         dtype=dtype)
        if variant == "superstep":
            return pk.make_batched_superstep_multi_step_fn(
                ops, nt, ksteps=self.ksteps, dtype=dtype)
        gsa = np.stack(gs) if test else None
        lgsa = np.stack(lgs) if test else None
        return pk.make_batched_pallas_multi_step_fn(
            ops, nt, dtype=dtype, test=test, gs=gsa, lgs=lgsa)

    @staticmethod
    def _label(variant, ops, pallas2d) -> str:
        if variant in ("vmap", "stacked") or not pallas2d:
            return variant
        from nonlocalheatequation_tpu.ops.pallas_kernel import (
            _uniform_physics,
        )

        form = "grid" if _uniform_physics(ops) else "stacked"
        return f"{variant}[{form}]"


def run_test_cases(cases, **engine_kwargs):
    """Convenience wrapper for the batch_tester protocol: run manufactured
    test cases through one engine; returns [(error_l2, n_points)] in
    submission order.  The error is computed exactly as the solvers do —
    f64 manufactured solution at t = nt vs the final state (the CLIs
    prefer feeding states back into their Solver objects; this helper
    serves bench/tooling callers with no Solver at hand)."""
    engine = EnsembleEngine(**engine_kwargs)
    cases = list(cases)
    states = engine.run(cases)
    out = []
    for case, u in zip(cases, states, strict=True):
        op = engine._make_op(case)
        prof = (op.spatial_profile() if case.mesh is not None
                else op.spatial_profile(*case.shape))
        want = np.cos(2.0 * np.pi * (case.nt * case.dt)) * prof
        d = np.asarray(u, np.float64) - want
        out.append((float(np.sum(d * d)), int(np.prod(case.shape))))
    return out
