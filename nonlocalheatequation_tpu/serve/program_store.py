"""AOT program store: serialized executables keyed by the engine key.

The reference is an ahead-of-time-compiled HPX binary — it pays ZERO
compile cost at startup (PAPER.md layer map).  Our JAX stack instead
re-pays a full trace+lower+compile per ``(bucket, engine)`` program key
on every replica and every session; the XLA persistent cache (bench.py
PR 1) removes only the XLA half, only same-host, and still pays trace +
lowering + cache lookup per program.  This module closes the gap: a
content-addressed on-disk store of **AOT-compiled executables**
(``jax.jit(fn).lower(*avals).compile()`` + executable serialization via
:mod:`~nonlocalheatequation_tpu.utils.compat`'s ``aot_serialize`` /
``aot_deserialize`` shims), shared across replicas and sessions, so a
warm boot loads a stored binary and dispatches — zero retrace, zero
recompile, and **bit-identical** results (the loaded executable IS the
bytes a fresh compile produced; pinned by tests/test_program_store.py on
the f64 8-virtual-device suite).

Keying (never serve a wrong program):

* the **digest** (file name) hashes the caller's full program key — the
  ensemble engine passes its ``prog_key`` (grid, nt, eps, test, batch,
  variant, physics, dtype, comm, stepper, stages; serve/ensemble.py),
  the solo path its operator/step signature — plus the input avals, the
  donation flag, the x64 mode, and the target backend name (sibling
  engines share ONE store namespace keyed by backend: a CPU-fallback
  ``conv`` program can never collide with the device engine's ``conv``).
* the **header** carries the jax/jaxlib/package **version fingerprint**
  (:func:`~nonlocalheatequation_tpu.utils.compat.aot_fingerprint`) and
  the **device topology** (platform, device kind, device count, process
  count) — verified at load with a LOUD, typed :class:`StoreRefusal` on
  any mismatch, after which the caller falls back to a fresh compile.
  A truncated or bit-rotted entry is refused the same way via a CRC32
  integrity marker (the checkpoint discipline, utils/checkpoint.py).

Crash/concurrency safety: entries are written with
:func:`~nonlocalheatequation_tpu.utils.checkpoint.atomic_file`
(same-directory host+pid-unique tmp, fsync, ``os.replace``), so N
replica processes racing to write the same key leave one complete
winner and readers never observe a torn file.

Observability: ``/store/hits``, ``/store/misses``, ``/store/refusals``
(labeled by reason), ``/store/load-ms`` and ``/store/serialize-ms``
histograms in the registry the caller provides (the ensemble engine
passes its report's registry, so ``ServeReport.metrics()`` and the
Prometheus exposition surface them), plus ``store.load`` /
``store.save`` spans — all build-time writes only; the timed dispatch
path never touches the store.

Env knobs: ``NLHEAT_PROGRAM_STORE`` — unset/``0``/empty = OFF (today's
behavior, bit-identically: the callers return exactly the callables
they always returned); ``1`` = the per-user default directory
(``~/.cache/nlheat/program_store``); any other value = the store
directory itself.  ``NLHEAT_PROGRAM_CACHE_CAP`` bounds the engine's
in-memory program cache (serve/ensemble.py LRU).
``NLHEAT_PROGRAM_STORE_CAP_MB`` (or the ``cap_bytes`` ctor arg) bounds
the store DIRECTORY itself: a replica fleet sharing one dir grows it
without bound under key diversity, so after each save the store evicts
least-recently-USED entries (every load hit refreshes its entry's
mtime) until the total fits, counting ``/store/gc-evictions``.  The
delete is two-process-safe: a racing GC's missing file is someone
else's eviction, not an error, and a reader racing a delete sees a
plain miss (fresh compile) — never a torn load.  0/unset = unbounded
(the repo's 0-knob convention).

TRUST BOUNDARY: entries deserialize through pickle, and the CRC /
fingerprint / topology headers are INTEGRITY checks, not authenticity
— anyone who can write the store directory can execute code in every
process that warm-boots from it.  Point the store only at directories
writable solely by principals you already trust to run code here (the
replicas themselves); store dirs are created ``0700`` and must never
be group/world-writable.  The same boundary applies ON THE WIRE: the
router's worker frames are pickle too, so the socket transport binds
loopback by default and refuses non-loopback listeners without a
shared-secret token (serve/transport.py) — a fleet FS dir shared
across hosts extends exactly this trust set, no further.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import sys
import time
import zlib

from nonlocalheatequation_tpu.obs import trace as obs_trace
from nonlocalheatequation_tpu.obs.metrics import MetricsRegistry
from nonlocalheatequation_tpu.utils import compat
from nonlocalheatequation_tpu.utils.checkpoint import atomic_file
from nonlocalheatequation_tpu.utils.devices import device_list

#: Entry format marker; bump on any layout change so old files refuse
#: loudly instead of deserializing garbage.
MAGIC = b"NLPROG1\n"

#: Default store location for ``NLHEAT_PROGRAM_STORE=1``.
DEFAULT_DIR = os.path.join(os.path.expanduser("~"), ".cache", "nlheat",
                           "program_store")

#: Refusal reasons (the typed, loud vocabulary the tests pin).
REFUSE_FINGERPRINT = "fingerprint-mismatch"
REFUSE_TOPOLOGY = "topology-mismatch"
REFUSE_CORRUPT = "corrupt"
REFUSE_UNSUPPORTED = "unsupported"


class StoreRefusal(RuntimeError):
    """The store cannot serve (or persist) this entry.  Always recovered
    from — the caller falls back to a fresh compile, never to wrong
    results — but LOUD: every refusal prints one stderr line and counts
    under ``/store/refusals{reason}``."""

    def __init__(self, reason: str, detail: str):
        super().__init__(f"program store refusal [{reason}]: {detail}")
        self.reason = reason
        self.detail = detail


def store_dir_from_env() -> str | None:
    """The configured store directory, or None when the store is off
    (unset/empty/``0``).  ``1`` selects :data:`DEFAULT_DIR`."""
    raw = os.environ.get("NLHEAT_PROGRAM_STORE", "")
    if raw in ("", "0"):
        return None
    if raw == "1":
        return DEFAULT_DIR
    return raw


def store_cap_from_env() -> int | None:
    """The on-disk size cap in BYTES from ``NLHEAT_PROGRAM_STORE_CAP_MB``
    (0/unset = unbounded, the 0-knob convention; negatives refuse)."""
    raw = os.environ.get("NLHEAT_PROGRAM_STORE_CAP_MB", "")
    if raw in ("", "0"):
        return None
    mb = float(raw)
    if mb < 0:
        raise ValueError(
            f"NLHEAT_PROGRAM_STORE_CAP_MB must be >= 0, got {raw!r}")
    return int(mb * 1024 * 1024)


def topology_fingerprint(backend: str | None = None) -> dict:
    """The device-topology half of the load-time check: platform, device
    kind, device count, process count.  Initializes the backend — call
    on the execution path only (the same rule as donation_on, and the
    reason the engine resolves its store lazily at first build, never
    in a constructor)."""
    import jax

    devices = device_list(backend) if backend else device_list()
    return {
        "platform": devices[0].platform,
        "device_kind": getattr(devices[0], "device_kind", ""),
        "devices": len(devices),
        "processes": jax.process_count(),
    }


#: Env knobs that shape the TRACE itself (kernel tiling, lane-run
#: experiments, autotune winner selection): two processes differing in
#: any of these may build different programs for the same logical key,
#: so they join the digest — a tile-size A/B must never be served the
#: other arm's executable.  (NLHEAT_DONATE is covered by the explicit
#: ``donate`` flag; NLHEAT_RESIDENT/SUPERSTEP shape paths above the
#: store-wrapped makers but are included for safety.)
TRACE_ENV_KNOBS = (
    "NLHEAT_TM", "NLHEAT_LANE_RUNS", "NLHEAT_AUTOTUNE",
    "NLHEAT_TUNE_BATCH", "NLHEAT_TUNE_PRECISION", "NLHEAT_TUNE_METHOD",
    "NLHEAT_RESIDENT", "NLHEAT_SUPERSTEP",
)


def _trace_env_desc() -> str:
    return ";".join(f"{k}={os.environ.get(k, '')}"
                    for k in TRACE_ENV_KNOBS)


def _digest(key_desc: str, avals_desc: str, donate: bool,
            backend: str) -> str:
    h = hashlib.sha256()
    for part in (MAGIC.decode(), key_desc, avals_desc, repr(bool(donate)),
                 backend, repr(compat.aot_fingerprint()["x64"]),
                 _trace_env_desc()):
        h.update(part.encode())
        h.update(b"\x00")
    return h.hexdigest()


def _avals_desc(example_args) -> str:
    import jax

    parts = []
    for a in example_args:
        if isinstance(a, jax.ShapeDtypeStruct):
            parts.append(f"sds{tuple(a.shape)}:{jax.numpy.dtype(a.dtype).name}")
        else:
            parts.append(f"lit:{type(a).__name__}:{a!r}")
    return ";".join(parts)


class ProgramStore:
    """One store directory + its counters.  Safe to share across sibling
    engines (CPU fallback included — the backend joins the digest); all
    methods are process-local and crash-safe, and every failure mode
    degrades to a fresh compile.

    ``registry`` receives the ``/store/*`` metrics; the ensemble engine
    passes its report's registry so the serving expositions carry them.
    """

    def __init__(self, root: str, registry: MetricsRegistry | None = None,
                 cap_bytes: int | None = None):
        self.root = str(root)
        self.registry = registry if registry is not None else MetricsRegistry()
        r = self.registry
        self._m_hits = r.counter("/store/hits")
        self._m_misses = r.counter("/store/misses")
        self._m_saves = r.counter("/store/saves")
        self._m_refusals = r.labeled("/store/refusals")
        self._m_gc_evictions = r.counter("/store/gc-evictions")
        self._h_load_ms = r.histogram("/store/load-ms")
        self._h_serialize_ms = r.histogram("/store/serialize-ms")
        if cap_bytes is None:
            cap_bytes = store_cap_from_env()
        if cap_bytes is not None and cap_bytes <= 0:
            cap_bytes = None  # 0 = unbounded, the 0-knob convention
        self.cap_bytes = cap_bytes
        # AOT wholly unavailable on this build: decided once, loudly
        self._aot_dead = not compat.aot_serialize_supported()
        self._topo_cache: dict = {}

    # -- public API ---------------------------------------------------------
    def load_or_build(self, key_desc: str, build, example_args,
                      donate: bool = False, backend: str | None = None):
        """The one entry point: return ``(callable, outcome)`` where
        outcome is ``"hit"`` (deserialized from disk — ``build`` never
        ran: zero retrace, zero recompile), ``"miss"`` (fresh
        AOT compile of ``build()``'s callable, persisted for the next
        boot), or ``"plain"`` (AOT unavailable/refused — ``build()``'s
        callable returned verbatim, today's jit-on-first-call behavior).

        ``build`` returns the program callable ``(u, t0) -> u`` exactly
        as the makers produce it; ``example_args`` are the concrete
        avals/literals of one call (``jax.ShapeDtypeStruct`` for arrays,
        python literals for weak-typed scalars).  ``donate`` must match
        the donation decision the call path would make
        (utils/donation.donation_on) — it changes the compiled binary,
        so it joins the digest.
        """
        if self._aot_dead:
            self._refuse(REFUSE_UNSUPPORTED,
                         "no executable serialization on this JAX build",
                         once=True)
            return build(), "plain"
        backend_name = self._backend_name(backend)
        digest = _digest(key_desc, _avals_desc(example_args), donate,
                         backend_name)
        path = os.path.join(self.root, digest + ".aotprog")
        loaded = self._try_load(path, backend_name)
        if loaded is not None:
            self._m_hits.inc()
            return loaded, "hit"
        self._m_misses.inc()
        fn = build()
        compiled = self._compile(fn, example_args, donate)
        if compiled is None:
            return fn, "plain"
        self._save(path, compiled, key_desc, backend_name)
        return compiled, "miss"

    def stats(self) -> dict:
        """Counter snapshot (bench's JSON fields read this)."""
        return {
            "hits": self._m_hits.value,
            "misses": self._m_misses.value,
            "saves": self._m_saves.value,
            "gc_evictions": self._m_gc_evictions.value,
            "refusals": dict(self._m_refusals),
        }

    # -- internals ----------------------------------------------------------
    def _backend_name(self, backend: str | None) -> str:
        if backend:
            return backend
        import jax

        return jax.default_backend()

    def _topology(self, backend_name: str) -> dict:
        topo = self._topo_cache.get(backend_name)
        if topo is None:
            topo = self._topo_cache[backend_name] = topology_fingerprint(
                backend_name)
        return topo

    def _refuse(self, reason: str, detail: str, once: bool = False) -> None:
        if once and self._m_refusals.get(reason):
            self._m_refusals[reason] += 1
            return
        self._m_refusals[reason] = self._m_refusals.get(reason, 0) + 1
        print(f"program store refusal [{reason}]: {detail} — "
              "falling back to a fresh compile", file=sys.stderr)

    def _try_load(self, path: str, backend_name: str):
        """A loaded executable, or None (missing entry = silent miss;
        every OTHER failure = loud typed refusal, then None)."""
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return None
        except OSError as e:
            self._refuse(REFUSE_CORRUPT, f"{path}: unreadable ({e})")
            return None
        t0 = time.perf_counter()
        try:
            loaded = self._decode(raw, path, backend_name)
        except StoreRefusal as e:
            self._refuse(e.reason, e.detail)
            return None
        except Exception as e:  # noqa: BLE001 — backend rejected the bytes
            self._refuse(REFUSE_UNSUPPORTED,
                         f"{path}: deserialization failed "
                         f"({type(e).__name__}: {e})")
            return None
        ms = (time.perf_counter() - t0) * 1e3
        self._h_load_ms.observe(ms)
        try:
            # refresh the entry's recency: the GC evicts by mtime, so a
            # hit must mark its entry as recently USED, not just
            # recently written (LRU, not FIFO)
            os.utime(path, None)
        except OSError:
            pass  # e.g. a racing GC deleted it after our read
        with obs_trace.span("store.load", cat="store", ms=round(ms, 3),
                            path=os.path.basename(path)):
            pass
        return loaded

    def _decode(self, raw: bytes, path: str, backend_name: str):
        if not raw.startswith(MAGIC):
            raise StoreRefusal(REFUSE_CORRUPT,
                               f"{path}: bad magic (foreign or torn file)")
        body = raw[len(MAGIC):]
        if len(body) < 8:
            raise StoreRefusal(REFUSE_CORRUPT, f"{path}: truncated header")
        hlen = int.from_bytes(body[:8], "little")
        if len(body) < 8 + hlen:
            raise StoreRefusal(REFUSE_CORRUPT, f"{path}: truncated header")
        try:
            header = json.loads(body[8:8 + hlen].decode())
        except Exception as e:
            raise StoreRefusal(REFUSE_CORRUPT,
                               f"{path}: unreadable header ({e})") from e
        payload = body[8 + hlen:]
        if len(payload) != header.get("payload_len", -1):
            raise StoreRefusal(REFUSE_CORRUPT,
                               f"{path}: payload truncated "
                               f"({len(payload)} of "
                               f"{header.get('payload_len')} bytes)")
        if zlib.crc32(payload) != header.get("payload_crc"):
            raise StoreRefusal(REFUSE_CORRUPT,
                               f"{path}: payload failed its integrity "
                               "check (torn write, disk fault)")
        fp_now = compat.aot_fingerprint()
        fp_saved = header.get("fingerprint", {})
        if fp_saved != fp_now:
            diff = {k: (fp_saved.get(k), fp_now.get(k))
                    for k in set(fp_saved) | set(fp_now)
                    if fp_saved.get(k) != fp_now.get(k)}
            raise StoreRefusal(REFUSE_FINGERPRINT,
                               f"{path}: saved under {diff} (saved, "
                               "current) — executables never cross builds")
        topo_now = self._topology(backend_name)
        topo_saved = header.get("topology", {})
        if topo_saved != topo_now:
            diff = {k: (topo_saved.get(k), topo_now.get(k))
                    for k in set(topo_saved) | set(topo_now)
                    if topo_saved.get(k) != topo_now.get(k)}
            raise StoreRefusal(REFUSE_TOPOLOGY,
                               f"{path}: compiled for {diff} (saved, "
                               "current) — executables never cross "
                               "topologies")
        blob = pickle.loads(payload)
        return compat.aot_deserialize(blob["exe"], blob["in_tree"],
                                      blob["out_tree"])

    def _compile(self, fn, example_args, donate: bool):
        """AOT lower+compile ``fn`` (exactly the bytes jit would build —
        jit's own path IS lower+compile, so results are bit-identical to
        the jit-on-first-call behavior).  Returns None (degrade to the
        plain callable, loudly) when this program cannot AOT-compile."""
        import jax

        try:
            jitted = jax.jit(fn, donate_argnums=(0,) if donate else ())
            return jitted.lower(*example_args).compile()
        except Exception as e:  # noqa: BLE001 — exotic maker output
            self._refuse(REFUSE_UNSUPPORTED,
                         f"AOT compile failed ({type(e).__name__}: {e}); "
                         "running the plain jit path")
            return None

    def _save(self, path: str, compiled, key_desc: str,
              backend_name: str) -> None:
        """Serialize + atomically persist; failures are loud refusals,
        never errors (the compiled program still serves this process)."""
        t0 = time.perf_counter()
        try:
            exe, in_tree, out_tree = compat.aot_serialize(compiled)
            payload = pickle.dumps(
                {"exe": exe, "in_tree": in_tree, "out_tree": out_tree})
        except Exception as e:  # noqa: BLE001 — backend refused
            self._refuse(REFUSE_UNSUPPORTED,
                         f"executable serialization failed "
                         f"({type(e).__name__}: {e}); entry not persisted")
            return
        header = json.dumps({
            "key": key_desc,
            "backend": backend_name,
            "fingerprint": compat.aot_fingerprint(),
            "topology": self._topology(backend_name),
            "payload_len": len(payload),
            "payload_crc": zlib.crc32(payload),
        }).encode()
        try:
            # 0700: the module's trust boundary (docstring) — a store
            # entry is executable content for whoever loads it, so the
            # dir must never open up to other principals.  Pre-existing
            # dirs keep their mode (the operator's explicit choice).
            os.makedirs(self.root, mode=0o700, exist_ok=True)
            with atomic_file(path, "wb") as f:
                f.write(MAGIC)
                f.write(len(header).to_bytes(8, "little"))
                f.write(header)
                f.write(payload)
        except OSError as e:
            self._refuse(REFUSE_UNSUPPORTED,
                         f"{path}: store write failed ({e}); entry not "
                         "persisted")
            return
        ms = (time.perf_counter() - t0) * 1e3
        self._h_serialize_ms.observe(ms)
        self._m_saves.inc()
        with obs_trace.span("store.save", cat="store", ms=round(ms, 3),
                            bytes=len(payload),
                            path=os.path.basename(path)):
            pass
        self._gc(keep=path)

    def _gc(self, keep: str | None = None) -> int:
        """Size-capped LRU eviction over the store dir (round11
        carried-forward: a fleet's shared dir grows without bound with
        key diversity).  Oldest-mtime entries go first — load hits
        refresh mtime, so mtime order IS use order; the entry just
        written (``keep``) is never evicted by its own save.  Returns
        the number of entries THIS process removed; a FileNotFoundError
        mid-delete is a concurrent GC's win, skipped silently (the
        two-process-safe delete), and any other OSError aborts the pass
        loudly as a refusal, never an exception."""
        if self.cap_bytes is None:
            return 0
        try:
            entries = []
            with os.scandir(self.root) as it:
                for de in it:
                    if not de.name.endswith(".aotprog"):
                        continue
                    try:
                        st = de.stat()
                    except FileNotFoundError:
                        continue  # racing GC/writer: already gone
                    entries.append((st.st_mtime, st.st_size, de.path))
        except OSError:
            return 0
        total = sum(sz for _, sz, _ in entries)
        removed = 0
        for _mtime, sz, path in sorted(entries):
            if total <= self.cap_bytes:
                break
            if keep is not None \
                    and os.path.abspath(path) == os.path.abspath(keep):
                continue
            try:
                os.remove(path)
            except FileNotFoundError:
                total -= sz  # another process evicted it: same outcome
                continue
            except OSError as e:
                self._refuse(REFUSE_UNSUPPORTED,
                             f"store GC cannot remove {path}: {e}")
                break
            total -= sz
            removed += 1
            self._m_gc_evictions.inc()
        return removed


def resolve_store(program_store, registry=None):
    """The callers' one resolution rule: an explicit
    :class:`ProgramStore` instance is used verbatim; an explicit path
    string opens a store there; ``None`` consults
    ``NLHEAT_PROGRAM_STORE`` (off when unset — today's behavior).
    ``registry`` is bound only when this call constructs the store."""
    if isinstance(program_store, ProgramStore):
        return program_store
    if program_store is not None:
        return ProgramStore(str(program_store), registry=registry)
    d = store_dir_from_env()
    if d is None:
        return None
    return ProgramStore(d, registry=registry)


# -- solo-solve wiring (ops/nonlocal_op.make_multi_step_fn_base) -------------


def solo_key_desc(op, nsteps: int, g, lg, dtype) -> str:
    """The solo multi-step program's identity: everything the trace
    bakes.  The manufactured-source arrays (g, lg) are hashed — they are
    baked constants, so two different sources are two different
    programs."""
    import numpy as np

    spacing = getattr(op, "dh", None)
    if spacing is None:
        spacing = getattr(op, "dx", 0.0)
    parts = [
        "solo", type(op).__name__,
        getattr(op, "method", ""),
        repr(int(op.eps)), repr(float(op.k)), repr(float(op.dt)),
        repr(float(spacing)),
        getattr(op, "precision", "f32"),
        repr(int(getattr(op, "resync_every", 0) or 0)),
        repr(int(nsteps)),
        "" if dtype is None else str(dtype),
        repr(bool(getattr(op, "uniform", True))),
    ]
    for arr in (g, lg):
        if arr is None:
            parts.append("none")
        else:
            a = np.ascontiguousarray(np.asarray(arr))
            parts.append(hashlib.sha256(a.tobytes()).hexdigest()
                         + f":{a.dtype}:{a.shape}")
    if not getattr(op, "uniform", True):
        # a weighted influence function J is baked into the kernel too
        w = np.ascontiguousarray(np.asarray(op.weights))
        parts.append(hashlib.sha256(w.tobytes()).hexdigest())
    return "|".join(parts)


def solo_store_jit(op, nsteps: int, g, lg, dtype, multi, donated_jit):
    """Wrap an UNJITTED solo multi-step trace for the store.  With the
    store off (the default) this returns ``donated_jit(multi)`` — the
    exact object (and therefore the exact behavior, bit for bit) the
    maker returned before the store existed.  With the store on, the
    first call per (shape, dtype) consults the store: a hit dispatches
    the loaded executable (zero retrace/recompile); a miss AOT-compiles
    this very trace and persists it; any refusal degrades to the
    donated-jit path."""
    if store_dir_from_env() is None:
        return donated_jit(multi)
    from nonlocalheatequation_tpu.obs.metrics import REGISTRY
    from nonlocalheatequation_tpu.utils import donation

    djit = donated_jit(multi)  # the refusal fallback (today's path)
    key_base = None  # computed once, lazily (hashing g/lg costs time)
    store_box: list = []  # resolved ONCE: counters/topology accumulate
    cache: dict = {}

    def wrapper(u, t0):
        nonlocal key_base
        import jax

        if type(t0) is not int:
            # store programs are lowered for the weak-typed python-int
            # t0 every solver/engine call site passes; a typed array t0
            # (e.g. an autotune probe's jnp scalar) would be an aval
            # mismatch on the loaded executable — run today's jit path
            # for such calls instead of risking a call-time refusal
            return djit(u, t0)
        donate = donation.donation_on()
        key = (tuple(u.shape), str(u.dtype), donate)
        fn = cache.get(key)
        if fn is None:
            if not store_box:
                # the solo path's store counters live in the process
                # registry, like every other solo-solve metric
                store_box.append(resolve_store(None, registry=REGISTRY))
            store = store_box[0]
            if store is None:  # knob flipped off after maker time
                fn = djit
            else:
                if key_base is None:
                    key_base = solo_key_desc(op, nsteps, g, lg, dtype)
                sds = jax.ShapeDtypeStruct(u.shape, u.dtype)
                fn, outcome = store.load_or_build(
                    key_base, lambda: multi, (sds, 0), donate=donate)
                if outcome == "plain":
                    fn = djit  # keep the jit-cached path, not a raw trace
            cache[key] = fn
        return fn(u, t0)

    return wrapper
