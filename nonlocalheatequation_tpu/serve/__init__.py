"""Serving-side machinery: batch many independent solves into few
compiled programs (serve/ensemble.py).  The reference's batch_tester
(src/1d_nonlocal_serial.cpp:239-266) treats N cases as one job but runs
them strictly sequentially; on the tunneled TPU each solve pays a ~64 ms
dispatch+fence toll, so the serving-scale answer is to schedule cases
into shape buckets and advance each bucket as ONE program."""
