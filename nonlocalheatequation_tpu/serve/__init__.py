"""Serving-side machinery: batch many independent solves into few
compiled programs, and overlap their dispatches.

``serve/ensemble.py`` is the offline scheduler: cases bucket by shape
and each bucket advances as ONE batched program — the reference's
batch_tester (src/1d_nonlocal_serial.cpp:239-266) treats N cases as one
job but runs them strictly sequentially, paying the tunneled TPU's
~64 ms dispatch+fence toll N times.

``serve/server.py`` is the request path: a continuous-batching pipeline
(microbatch windows, per-case deadlines) that keeps up to D chunks in
flight and fences only when a result is due — the reference's HPX
futures-and-dataflow overlap (README.md:12-14) applied to serving, with
served results bit-identical to the offline engine.

``serve/resilience.py`` is the fault-tolerance layer under it: the
typed ``ServeError`` a quarantined request raises, the circuit breaker
(closed -> open on K consecutive device failures -> half-open probe ->
closed), and the CPU-backend fallback chunk runner — bench.py's
ladder/watchdog discipline applied to the request path, proven by the
deterministic injector in utils/faults.py with no real TPU.

``serve/program_store.py`` is the warm-boot layer under all of them: a
content-addressed on-disk store of AOT-compiled executables keyed by
the full program key plus a version/topology fingerprint, so a fresh
replica or session loads yesterday's compiles (zero retrace/recompile,
bit-identical results) instead of re-paying them — the reference's
compiled-binary zero-startup-cost property (PAPER.md layer map)
recovered for the JAX stack.

``serve/router.py`` and ``serve/http.py`` are the fleet tier above:
a sticky-bucket router owning N ServePipeline worker processes (shared
store dir = warm caches everywhere; busy-rate elastic add/drain; death
-> re-route, re-served bit-identically) and the HTTP ingestion front
door with admission control (429 + Retry-After before any queue can
grow without bound) — the reference's many-locality/idle-rate-balancer
tier lifted to whole serving replicas.

``serve/transport.py`` is the wire under the router: the
length-prefixed frame protocol factored into worker transports —
stdin/stdout pipes (default, bit-identical to PR 10) or TCP sockets
(workers started with ``--worker-connect host:port`` dial in behind a
hello/token handshake), so one replica can be one remote host/chip.
The router also owns the SECOND case class: 2D grids above its
``shard_threshold`` dispatch to a gang replica that solves each as a
space-parallel distributed run over an N-device mesh
(parallel/gang.py ``solve_case_sharded``, ``comm='fused'`` where the
kernel family serves it), streamed back over the same frames
bit-identical to the offline ``Solver2DDistributed`` path.

``serve/sessions.py`` is the INTERACTIVE tier over all of it: a
``SessionManager`` owning long-running stateful cases as first-class
fleet citizens — chunked stepping through the pipeline/router, coarse
preview + final-f64 frame streams (SSE over serve/http.py),
chunk-boundary retargeting of the source term (legal physics: b(t,x)
is time-dependent), what-if forks from crash-safe checkpoints, resume
bit-identical to an uninterrupted run, and per-session step budgets
through the admission controller so streams can never starve the
batch tier.
"""
