"""Serving-side machinery: batch many independent solves into few
compiled programs, and overlap their dispatches.

``serve/ensemble.py`` is the offline scheduler: cases bucket by shape
and each bucket advances as ONE batched program — the reference's
batch_tester (src/1d_nonlocal_serial.cpp:239-266) treats N cases as one
job but runs them strictly sequentially, paying the tunneled TPU's
~64 ms dispatch+fence toll N times.

``serve/server.py`` is the request path: a continuous-batching pipeline
(microbatch windows, per-case deadlines) that keeps up to D chunks in
flight and fences only when a result is due — the reference's HPX
futures-and-dataflow overlap (README.md:12-14) applied to serving, with
served results bit-identical to the offline engine.
"""
