"""Live simulation sessions: a stateful streaming tier over the front door.

Everything the fleet served before this module is one-shot — submit a
case, poll a result (serve/http.py).  The interactive traffic shape
(ROADMAP item 4) is a SESSION: a user holds a simulation open, watches
the field evolve as a stream of frames, steers the source mid-flight,
forks what-if branches, and survives replica death without noticing.
The physics permits the steering — the reference's source term
``b(t,x)`` is time-dependent (problem_description.tex:131-134), so a
piecewise-constant-in-time source is a legal member of the same problem
family — and every mechanism below is a PROMOTION of machinery shipped
in PRs 3–13, not a new engine:

* **Chunked stepping** — a session advances ``chunk_steps`` Euler steps
  at a time, each chunk one ordinary production
  :class:`~nonlocalheatequation_tpu.serve.ensemble.EnsembleCase`
  (``nt=chunk_steps``, ``u0=`` the current state) submitted through the
  existing backend — a
  :class:`~nonlocalheatequation_tpu.serve.server.ServePipeline` or a
  :class:`~nonlocalheatequation_tpu.serve.router.ReplicaRouter` — so
  program build/cache/AOT-store, supervision, and fleet routing all
  work unchanged.  The session's trajectory is DEFINED over its chunk
  grid: state(step) at every chunk boundary is a deterministic function
  of (spec, retarget log), which is what makes resume bit-identity a
  testable contract rather than a hope.
* **Session-sticky routing** — a session id is a long-lived sticky
  bucket key: chunks ride the router's ``sticky_key=("session", sid)``
  so EVERY chunk (the final partial one included, whose ``nt`` differs
  and would otherwise hash to a different bucket owner) lands on the
  session's replica, keeping its program cache hot.  A fork is a NEW
  key — placed anywhere, warm-booting the parent's programs from the
  shared AOT store (same program key: same shape/chunk/physics).
* **Streaming** — every chunk boundary emits a coarse PREVIEW frame
  (``u[::stride]`` as f32 — cheap to ship, honest to look at) and
  completion emits the FINAL full-f64 frame.  Frames are keyed by
  absolute step; :meth:`SessionManager.stream` (and the SSE endpoint
  ``GET /v1/sessions/<id>/stream`` in serve/http.py) deliver them in
  step order from any cursor, so a reconnecting/resumed reader loses
  nothing and duplicates nothing.
* **Retarget** (``POST .../retarget``) — queued control verbs change
  the conductivity ``k`` and/or the additive source field ``b(x)`` AT
  THE NEXT CHUNK BOUNDARY (first-order operator splitting: a chunk of
  ``n`` steps integrates the source as ``u += n*dt*b`` at its end —
  piecewise-constant-in-time ``b(t,x)``, the legal physics above).
  The boundary step is recorded in the session's audit log, the
  EventLog, and the trace — auditable evidence, never a silent rewrite.
* **Fork** (``POST .../fork``) — a new session from any retained
  checkpoint boundary of the parent (or its live boundary state), with
  the parent lineage in its audit log.
* **Resume** — every ``checkpoint_every`` chunks the boundary state is
  saved crash-safe (utils/checkpoint.py ``save_session_checkpoint``:
  atomic replace + CRC, keyed by session id + step).  Replica death
  inside a chunk is ALREADY invisible (the router re-routes orphans and
  re-serves bit-identically); :meth:`SessionManager.resume` covers the
  tier above — a dead front door / manager restarts, reloads the newest
  uncorrupted boundary, and re-emits the stream from there, bit-identical
  to an uninterrupted run (tests/test_sessions.py pins both layers,
  ``die@`` chaos plans included).
* **Budgets** — per-session step budgets (``budget_steps`` per
  ``budget_window_s``) plus the fleet-wide session gate that joined the
  :class:`~nonlocalheatequation_tpu.serve.http.AdmissionController`
  (``session_steps_per_s``) mean a greedy streaming session DEFERS at
  chunk granularity instead of starving the batch tier; session chunks
  also submit at priority -1 so batch work wins ties inside workers.
* **Observability** — every lifecycle event (open/chunk/retarget/fork/
  resume/close) lands in the EventLog, the span tracer
  (``session.chunk`` spans, ``session.*`` instants), and the backend
  registry's ``/session/*`` counters/gauges, so one fleet scrape shows
  the session tier next to the batch tier.

Threading: the manager is pumped — :meth:`pump` advances every session
one event (submit or retire) and never blocks; ``start_driver`` runs a
daemon pump loop for the HTTP tier; tests drive pump()/drive() with an
injected clock for determinism.  Shared state is lock-guarded with
``guarded_by`` annotations enforced by graftlint L1 (tools/lint/locks.py).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from dataclasses import dataclass

import numpy as np

from nonlocalheatequation_tpu.obs import trace as obs_trace
from nonlocalheatequation_tpu.obs.export import EventLog
from nonlocalheatequation_tpu.obs.metrics import MetricsRegistry
from nonlocalheatequation_tpu.serve.ensemble import EnsembleCase
from nonlocalheatequation_tpu.serve.router import RouterOverloaded
from nonlocalheatequation_tpu.utils.checkpoint import (
    list_session_checkpoints,
    load_session_checkpoint,
    save_session_checkpoint,
)

#: Env knobs for the session tier's defaults (scrubbed by
#: tests/conftest.py like every serve-tier knob family):
#: per-session step budget per window (0 = unlimited), checkpoint
#: cadence in CHUNKS (0 = off), preview downsample stride.
SESSION_BUDGET_ENV = "NLHEAT_SESSION_BUDGET"
SESSION_CKPT_ENV = "NLHEAT_SESSION_CKPT_EVERY"
SESSION_PREVIEW_ENV = "NLHEAT_SESSION_PREVIEW"

#: Frames retained per session (the stream window).  A session outliving
#: its window keeps streaming — old frames age out of the REPLAY buffer
#: only; ``frames_total`` stays lifetime-exact.  Live readers are never
#: behind by more than their poll cadence, and a resumed reader replays
#: from the last checkpoint, which the cadence keeps inside the window.
FRAMES_CAP = 4096

#: Ended (done/closed/failed) sessions retained for result/status polls
#: (the session twin of serve/http.py RESULTS_CAP): a long-running front
#: door serving many short sessions must not grow host memory with its
#: session count — each retained session holds its full f64 state plus
#: its frame buffer.  Older ended sessions age out FIFO; their on-disk
#: checkpoints remain, so an aged-out session is still resumable.
RETAIN_ENDED = 256

#: Retained checkpoint boundaries per session (0 = keep all).  Forks can
#: branch from any RETAINED boundary; resume wants only the newest.
CKPT_KEEP = 8

#: Frame-kind order at one step: the preview streams before the final.
#: Stream cursors are (step, rank) pairs so a FINAL frame emitted at a
#: step whose preview was already consumed (close_session mid-stream)
#: is still delivered — a bare step cursor would skip it.
KIND_RANK = {"preview": 0, "final": 1}


@dataclass
class SessionSpec:
    """What a session simulates and how it streams.

    The physics fields mirror :class:`EnsembleCase` (production form:
    ``test=False``, an explicit ``u0`` — the manufactured-source test
    path bakes absolute time into its program and cannot be chunked).
    ``nt`` is the TOTAL step count (None = open-ended, runs until
    closed); ``chunk_steps`` the stream granularity — one chunk = one
    dispatched program = one preview frame.  ``budget_steps`` caps the
    session's steps per ``budget_window_s`` (0 = unlimited, the
    env default ``NLHEAT_SESSION_BUDGET``); ``checkpoint_every`` is the
    crash-safe save cadence in chunks (0 = off, env
    ``NLHEAT_SESSION_CKPT_EVERY``); ``preview_stride`` the coarse-frame
    downsample (env ``NLHEAT_SESSION_PREVIEW``, default 4)."""

    shape: tuple
    eps: int
    k: float
    dt: float
    dh: float
    u0: np.ndarray
    nt: int | None = None
    chunk_steps: int = 16
    preview_stride: int | None = None
    budget_steps: int | None = None
    budget_window_s: float = 1.0
    checkpoint_every: int | None = None
    #: mesh-keyed session (ISSUE 17, serve/meshes.py): the content hash
    #: of a registered point cloud.  ``shape`` is then the node count
    #: ``(n,)`` and ``eps``/``dh`` ride as 0 — the mesh carries its own
    #: geometry (the EnsembleCase mesh semantics, serve/ensemble.py).
    mesh: str | None = None

    def validate(self) -> "SessionSpec":
        # every coercion is ASSIGNED, not just range-checked: a JSON
        # body's 2.5/"10" must become a real int/float here or it
        # detonates later inside the pump, past the client's 400
        self.shape = tuple(int(s) for s in self.shape)
        if not 1 <= len(self.shape) <= 3 or any(s < 1 for s in self.shape):
            raise ValueError(f"bad session shape {self.shape}")
        self.eps = int(self.eps)
        if self.mesh is not None:
            self.mesh = str(self.mesh)
        elif self.eps < 1:
            # a mesh-keyed session carries eps in the registered cloud
            # (eps rides as 0); grid sessions need a real horizon
            raise ValueError(f"session eps must be >= 1, got {self.eps}")
        self.k = float(self.k)
        self.dt = float(self.dt)
        self.dh = float(self.dh)
        if self.nt is not None:
            self.nt = int(self.nt)
            if self.nt < 1:
                raise ValueError(
                    f"session nt must be >= 1 (or None = open-ended), "
                    f"got {self.nt}")
        self.chunk_steps = int(self.chunk_steps)
        if self.chunk_steps < 1:
            raise ValueError(
                f"chunk_steps must be >= 1, got {self.chunk_steps}")
        if self.u0 is None:
            raise ValueError(
                "a session needs an initial state u0 (sessions are "
                "production solves; the manufactured-source test path "
                "bakes absolute time into its program and cannot be "
                "chunked)")
        u0 = np.asarray(self.u0, np.float64)
        if u0.size != int(np.prod(self.shape)):
            raise ValueError(
                f"u0 has {u0.size} values, shape {self.shape} needs "
                f"{int(np.prod(self.shape))}")
        self.u0 = u0.reshape(self.shape)
        self.preview_stride = int(
            self.preview_stride if self.preview_stride is not None
            else os.environ.get(SESSION_PREVIEW_ENV) or 4)
        if self.preview_stride < 1:
            raise ValueError(
                f"preview_stride must be >= 1, got {self.preview_stride}")
        self.budget_steps = int(
            self.budget_steps if self.budget_steps is not None
            else os.environ.get(SESSION_BUDGET_ENV) or 0)
        if self.budget_steps < 0:
            raise ValueError(
                f"budget_steps must be >= 0 (0 = unlimited), got "
                f"{self.budget_steps}")
        self.budget_window_s = float(self.budget_window_s)
        if self.budget_window_s <= 0:
            raise ValueError(
                f"budget_window_s must be > 0, got {self.budget_window_s}")
        self.checkpoint_every = int(
            self.checkpoint_every if self.checkpoint_every is not None
            else os.environ.get(SESSION_CKPT_ENV) or 0)
        if self.checkpoint_every < 0:
            raise ValueError(
                f"checkpoint_every must be >= 0 (0 = off), got "
                f"{self.checkpoint_every}")
        return self

    def params(self, k: float, source) -> dict:
        """The checkpoint parameter block: everything a resume needs to
        continue the SAME trajectory (current retargeted physics
        included — the saved k/source, not the opening ones)."""
        return {
            "shape": list(self.shape), "eps": int(self.eps),
            "k": float(k), "dt": float(self.dt), "dh": float(self.dh),
            "mesh": self.mesh,
            "nt": self.nt if self.nt is None else int(self.nt),
            "chunk_steps": int(self.chunk_steps),
            "preview_stride": int(self.preview_stride),
            "budget_steps": int(self.budget_steps),
            "budget_window_s": float(self.budget_window_s),
            "checkpoint_every": int(self.checkpoint_every),
            "source": (None if source is None
                       else np.asarray(source).ravel().tolist()),
        }


@dataclass
class Frame:
    """One stream emission: the field at a chunk boundary.  ``step`` is
    the ABSOLUTE step index (the dedup key a reconnecting reader
    cursors on); ``kind`` is "preview" (f32, ``::stride`` downsample)
    or "final" (full f64, emitted once at completion)."""

    step: int
    kind: str
    t: float
    shape: tuple
    values: np.ndarray

    def wire(self) -> dict:
        return {"step": int(self.step), "kind": self.kind,
                "t": float(self.t), "shape": list(self.values.shape),
                "dtype": str(self.values.dtype),
                "values": self.values.ravel().tolist()}


class Session:
    """One live simulation: state, stream buffer, audit trail.

    Mutated by the manager's pump (driver thread) and read by stream
    readers (HTTP handler threads) — every mutable field below is
    guarded by the session's own lock; :class:`SessionManager` methods
    hold it via ``with s._lock``.  ``state`` moves
    ``running -> done | closed | failed`` (done = reached ``nt``;
    closed = explicit close; failed = a chunk completed exceptionally —
    the typed ServeError is kept on ``error``)."""

    def __init__(self, sid: str, spec: SessionSpec, *, t0: int = 0,
                 u=None, clock=time.monotonic, parent: tuple | None = None,
                 resumed_from: int | None = None):
        self.sid = sid
        self.spec = spec
        self._lock = threading.RLock()
        self._wake = threading.Condition(self._lock)
        self.state = "running"  # guarded_by: self._lock
        self.error = None  # guarded_by: self._lock
        self.step = int(t0)  # guarded_by: self._lock
        self.u = np.asarray(u if u is not None else spec.u0,
                            np.float64)  # guarded_by: self._lock
        self.k = float(spec.k)  # guarded_by: self._lock
        self.source = None  # guarded_by: self._lock
        self._frames: dict = {}  # (step, kind) -> Frame; guarded_by: self._lock
        self._order: list = []  # guarded_by: self._lock
        self.frames_total = 0  # guarded_by: self._lock
        self.chunks_done = 0  # guarded_by: self._lock
        self.deferrals = 0  # guarded_by: self._lock
        self.retarget_queue: list = []  # guarded_by: self._lock
        self.audit: list = []  # applied retargets/forks/resumes; guarded_by: self._lock
        self.inflight = None  # submitted chunk handle; guarded_by: self._lock
        self.inflight_steps = 0  # guarded_by: self._lock
        self.inflight_t0 = 0.0  # guarded_by: self._lock
        #: pump claim: at most ONE thread works this session's submit/
        #: retire at a time (stream() pumps from reader threads when no
        #: driver runs — without the claim two readers could both see
        #: inflight None and double-submit a chunk)
        self._pump_busy = False  # guarded_by: self._lock
        self.window_t0 = clock()  # guarded_by: self._lock
        self.steps_in_window = 0  # guarded_by: self._lock
        self.last_checkpoint: int | None = resumed_from  # guarded_by: self._lock
        self.parent = parent  # (parent sid, fork step) or None
        self.resumed_from = resumed_from

    # the router's long-lived placement identity (module docstring)
    def sticky_key(self) -> tuple:
        return ("session", self.sid)

    def _emit(self, frame: Frame) -> bool:  # locked: self._lock
        """Buffer one frame (dedup by (step, kind): a resume re-emitting
        an already-delivered boundary replaces it with the bit-identical
        recomputation instead of duplicating).  Returns True when the
        frame was NEW."""
        key = (frame.step, frame.kind)
        fresh = key not in self._frames
        if fresh:
            self._order.append(key)
            self.frames_total += 1
            while len(self._order) > FRAMES_CAP:
                self._frames.pop(self._order.pop(0), None)
        self._frames[key] = frame
        self._wake.notify_all()
        return fresh

    def frames_after(self, cursor: int, kind_rank: int = 0) -> list:
        """Buffered frames strictly past the ``(cursor, kind_rank)``
        stream position, in (step, preview-before-final) order — the
        stream reader's pull.  ``kind_rank`` (KIND_RANK) names the
        last-consumed frame AT the cursor step: the default 0 means
        only the preview there was seen, so a final frame at exactly
        ``cursor`` is still due (close_session emits one at the step
        whose preview already streamed)."""
        with self._lock:
            keys = sorted(self._frames,
                          key=lambda sk: (sk[0], KIND_RANK[sk[1]]))
            return [self._frames[sk] for sk in keys
                    if (sk[0], KIND_RANK[sk[1]]) > (cursor, kind_rank)]

    def status(self) -> dict:
        with self._lock:
            return {
                "session": self.sid, "state": self.state,
                "step": self.step,
                "nt": self.spec.nt,
                "t": self.step * self.spec.dt,
                "k": self.k,
                "source": self.source is not None,
                "chunk_steps": self.spec.chunk_steps,
                "chunks": self.chunks_done,
                "frames_total": self.frames_total,
                "deferrals": self.deferrals,
                "retargets_queued": len(self.retarget_queue),
                "audit": [dict(a) for a in self.audit],
                "last_checkpoint": self.last_checkpoint,
                "parent": self.parent,
                "resumed_from": self.resumed_from,
                "error": str(self.error) if self.error else None,
            }

    def result(self):
        """The final full-f64 field (None until done/closed)."""
        with self._lock:
            fr = self._frames.get((self.step, "final"))
            return None if fr is None else np.array(fr.values)


class SessionManager:
    """Owns every live session over one serving backend.

    ``backend`` is a ReplicaRouter (fleet form: chunks ride
    ``sticky_key``, deaths re-route invisibly) or a ServePipeline
    (in-process form: chunks fence per retire — the deterministic
    test/bench harness).  ``admission`` is the shared
    :class:`~nonlocalheatequation_tpu.serve.http.AdmissionController`
    whose session gate chunks must clear (None = no fleet-wide gate;
    per-session budgets still apply).  ``checkpoint_dir`` enables
    crash-safe resume + checkpoint forks (None = off: forks branch from
    the live boundary state only and resume refuses).  ``clock`` is
    injectable for deterministic budget/starvation tests."""

    def __init__(self, backend, *, admission=None,
                 checkpoint_dir: str | None = None,
                 chunk_steps: int = 16, clock=time.monotonic,
                 registry: MetricsRegistry | None = None,
                 ckpt_keep: int = CKPT_KEEP,
                 retain_ended: int = RETAIN_ENDED):
        self.backend = backend
        self.admission = admission
        self.checkpoint_dir = checkpoint_dir
        self.default_chunk_steps = int(chunk_steps)
        self.ckpt_keep = int(ckpt_keep)
        self.retain_ended = int(retain_ended)
        self._clock = clock
        self.registry = (registry if registry is not None
                         else getattr(backend, "registry", None))
        if self.registry is None:
            self.registry = MetricsRegistry()
        r = self.registry
        self._m_opened = r.counter("/session/opened")
        self._m_closed = r.counter("/session/closed")
        self._m_completed = r.counter("/session/completed")
        self._m_failed = r.counter("/session/failed")
        self._m_active = r.gauge("/session/active")
        self._m_chunks = r.counter("/session/chunks")
        self._m_steps = r.counter("/session/steps")
        self._m_frames = r.counter("/session/frames")
        self._m_retargets = r.counter("/session/retargets")
        self._m_forks = r.counter("/session/forks")
        self._m_resumes = r.counter("/session/resumes")
        self._m_checkpoints = r.counter("/session/checkpoints")
        self._m_deferrals = r.counter("/session/deferrals")
        self._h_chunk_ms = r.histogram("/session/chunk-ms")
        self._events = EventLog.from_env()
        self._lock = threading.RLock()
        self._sessions: dict = {}  # guarded_by: self._lock
        #: ended sids in end order (FIFO aging to retain_ended);
        #: insertion-ordered like IngressServer._done
        self._ended: dict = {}  # guarded_by: self._lock
        self._next_sid = 0  # guarded_by: self._lock
        self._closed = False  # guarded_by: self._lock
        self._driver: threading.Thread | None = None
        self._stop_driver = threading.Event()

    # -- observability (never raises; one attribute read when off) ----------
    def _event(self, kind: str, **fields) -> None:
        if self._events is not None:
            self._events.emit(event=kind, **fields)

    # -- lifecycle ----------------------------------------------------------
    def open(self, spec: SessionSpec | None = None, *, sid: str | None = None,
             _t0: int = 0, _u=None, _parent=None, _resumed=None,
             **spec_kwargs) -> Session:
        """Open a session (pass a built :class:`SessionSpec` or its
        fields as kwargs) and emit its step-``_t0`` preview frame — the
        stream's first emission is the initial state, so a reader sees
        the field before the first chunk retires."""
        if spec is None:
            spec = SessionSpec(
                chunk_steps=spec_kwargs.pop("chunk_steps",
                                            self.default_chunk_steps),
                **spec_kwargs)
        elif spec_kwargs:
            raise ValueError(
                f"pass spec fields {sorted(spec_kwargs)} OR a built "
                "SessionSpec, not both")
        spec.validate()
        with self._lock:
            if self._closed:
                raise RuntimeError("session manager is closed")
            if sid is None:
                sid = f"s{self._next_sid}"
                self._next_sid += 1
            if sid in self._sessions:
                raise ValueError(f"session id {sid!r} already live")
            s = Session(sid, spec, t0=_t0, u=_u, clock=self._clock,
                        parent=_parent, resumed_from=_resumed)
            self._sessions[sid] = s
        self._m_opened.inc()
        self._m_active.set(self._active_count())
        with s._lock:
            self._emit_preview(s)
        obs_trace.instant("session.open", cat="session", session=sid,
                          step=_t0)
        self._event("session-open", session=sid, step=_t0,
                    shape=list(spec.shape), chunk_steps=spec.chunk_steps,
                    parent=list(_parent) if _parent else None,
                    resumed_from=_resumed)
        return s

    def _active_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._sessions.values()
                       if s.state == "running")

    def _note_ended(self, sid: str) -> None:
        """Bounded retention of ended sessions (RETAIN_ENDED): the
        newest stay pollable (status/result/stream replay); older ones
        age out FIFO — their checkpoints remain on disk, so resume
        still works.  Called AFTER the session lock is released (the
        mgr -> session lock order)."""
        with self._lock:
            self._ended.setdefault(sid, None)
            while len(self._ended) > self.retain_ended:
                old = next(iter(self._ended))
                del self._ended[old]
                self._sessions.pop(old, None)

    def get(self, sid: str) -> Session:
        with self._lock:
            s = self._sessions.get(sid)
        if s is None:
            raise KeyError(f"no live session {sid!r}")
        return s

    def sessions(self) -> list:
        with self._lock:
            return list(self._sessions.values())

    def retarget(self, sid: str, *, k: float | None = None,
                 source=None, clear_source: bool = False) -> dict:
        """Queue a control verb: new conductivity ``k`` and/or additive
        source field ``b(x)`` (``clear_source`` drops an active one).
        Takes effect at the session's NEXT chunk boundary; the boundary
        step is recorded in the audit log when applied.  Returns the
        queued ticket (``requested_at_step`` = the current step)."""
        s = self.get(sid)
        if k is None and source is None and not clear_source:
            raise ValueError(
                "retarget needs k=, source=, or clear_source=True")
        if source is not None:
            source = np.asarray(source, np.float64)
            if source.size != int(np.prod(s.spec.shape)):
                raise ValueError(
                    f"source has {source.size} values, shape "
                    f"{s.spec.shape} needs {int(np.prod(s.spec.shape))}")
            source = source.reshape(s.spec.shape)
        with s._lock:
            if s.state != "running":
                raise ValueError(
                    f"session {sid!r} is {s.state}; retarget needs a "
                    "running session")
            ticket = {"verb": "retarget",
                      "requested_at_step": s.step,
                      "k": None if k is None else float(k),
                      "source": ("clear" if clear_source else
                                 "set" if source is not None else None)}
            s.retarget_queue.append(
                {"k": k, "source": source, "clear": clear_source,
                 "requested_at_step": s.step})
        self._m_retargets.inc()
        obs_trace.instant("session.retarget", cat="session", session=sid,
                          requested_at_step=ticket["requested_at_step"])
        self._event("session-retarget", session=sid,
                    requested_at_step=ticket["requested_at_step"],
                    k=ticket["k"], source=ticket["source"])
        return ticket

    def fork(self, sid: str, *, step: int | None = None) -> Session:
        """Branch a new session from ``sid``'s checkpoint at ``step``
        (None = the live boundary state when no checkpoint dir is
        configured, else the newest retained checkpoint).  The child is
        a NEW session id — a new sticky key, placed anywhere, warm-
        booting the parent's compiled programs from the shared AOT
        store — carrying the parent lineage in its audit log."""
        parent = self.get(sid)
        with parent._lock:
            # the live-state fork branches at the last retired BOUNDARY
            # (u/step) — an in-flight chunk's interior is nobody's state
            spec = parent.spec
            live_u = np.array(parent.u)
            live_step = parent.step
            k_now, src_now = parent.k, (None if parent.source is None
                                        else np.array(parent.source))
        if step is not None and self.checkpoint_dir is None:
            raise ValueError(
                "fork from a checkpoint step needs a checkpoint_dir")
        params = None
        if self.checkpoint_dir is not None:
            try:
                u, t0, params = load_session_checkpoint(
                    self.checkpoint_dir, sid, step)
            except FileNotFoundError:
                if step is not None:
                    raise
                params = None  # nothing retained yet: live-state fork
        if params is not None:
            k = float(params.get("k", spec.k))
            source = params.get("source")
            source = (None if source is None
                      else np.asarray(source,
                                      np.float64).reshape(spec.shape))
        else:
            u, t0, k, source = live_u, live_step, k_now, src_now
        child_spec = SessionSpec(
            shape=spec.shape, eps=spec.eps, k=k, dt=spec.dt, dh=spec.dh,
            mesh=spec.mesh,
            u0=u, nt=spec.nt, chunk_steps=spec.chunk_steps,
            preview_stride=spec.preview_stride,
            budget_steps=spec.budget_steps,
            budget_window_s=spec.budget_window_s,
            checkpoint_every=spec.checkpoint_every)
        child = self.open(child_spec, _t0=t0, _u=u, _parent=(sid, t0))
        with child._lock:
            child.source = source
            child.audit.append({"verb": "fork", "parent": sid,
                                "from_step": t0})
        self._m_forks.inc()
        obs_trace.instant("session.fork", cat="session", session=sid,
                          child=child.sid, from_step=t0)
        self._event("session-fork", session=sid, child=child.sid,
                    from_step=t0)
        return child

    def resume(self, sid: str) -> Session:
        """Restore ``sid`` from its newest uncorrupted checkpoint (the
        front-door/manager-death recovery; replica death inside a chunk
        never needs this — the router re-routes).  The resumed session
        keeps its id (and therefore its sticky key and stream identity)
        and re-emits frames from the checkpoint boundary onward,
        bit-identical to an uninterrupted run."""
        if self.checkpoint_dir is None:
            raise ValueError("resume needs a checkpoint_dir")
        with self._lock:
            if sid in self._sessions:
                raise ValueError(
                    f"session {sid!r} is already live; resume restores "
                    "a dead one")
        u, t0, params = load_session_checkpoint(self.checkpoint_dir, sid)
        spec = SessionSpec(
            shape=tuple(params["shape"]), eps=params["eps"],
            k=params["k"], dt=params["dt"], dh=params["dh"], u0=u,
            mesh=params.get("mesh"),
            nt=params.get("nt"), chunk_steps=params["chunk_steps"],
            preview_stride=params.get("preview_stride"),
            budget_steps=params.get("budget_steps"),
            budget_window_s=params.get("budget_window_s", 1.0),
            checkpoint_every=params.get("checkpoint_every"))
        s = self.open(spec, sid=sid, _t0=t0, _u=u, _resumed=t0)
        source = params.get("source")
        with s._lock:
            s.source = (None if source is None
                        else np.asarray(source,
                                        np.float64).reshape(spec.shape))
            s.audit.append({"verb": "resume", "from_step": t0})
        self._m_resumes.inc()
        obs_trace.instant("session.resume", cat="session", session=sid,
                          from_step=t0)
        self._event("session-resume", session=sid, from_step=t0)
        return s

    def close_session(self, sid: str) -> dict:
        """End a session now: its current boundary state becomes the
        final full-f64 frame and the stream completes."""
        s = self.get(sid)
        with s._lock:
            flipped = s.state == "running"
            if flipped:
                s.state = "closed"
                self._emit_final(s)
                s._wake.notify_all()
        if flipped:
            # idempotent: a double close (client retry, done session)
            # must not over-count /session/closed or re-emit events —
            # opened == completed + closed + failed must reconcile
            self._m_closed.inc()
            self._m_active.set(self._active_count())
            self._note_ended(sid)
            obs_trace.instant("session.close", cat="session", session=sid,
                              step=s.step)
            self._event("session-close", session=sid, step=s.step)
        return s.status()

    # -- frames -------------------------------------------------------------
    def _preview_of(self, s: Session) -> np.ndarray:  # locked: s._lock
        sl = tuple(slice(None, None, s.spec.preview_stride)
                   for _ in s.spec.shape)
        return np.ascontiguousarray(s.u[sl].astype(np.float32))

    def _emit_preview(self, s: Session) -> None:  # locked: s._lock
        fresh = s._emit(Frame(step=s.step, kind="preview",
                              t=s.step * s.spec.dt, shape=s.spec.shape,
                              values=self._preview_of(s)))
        if fresh:
            self._m_frames.inc()

    def _emit_final(self, s: Session) -> None:  # locked: s._lock
        fresh = s._emit(Frame(step=s.step, kind="final",
                              t=s.step * s.spec.dt, shape=s.spec.shape,
                              values=np.array(s.u, np.float64)))
        if fresh:
            self._m_frames.inc()

    def stream(self, sid: str, *, from_step: int = -1,
               timeout_s: float = 30.0, poll_s: float = 0.05):
        """Yield :class:`Frame` objects with ``step > from_step`` in
        step order until the session leaves ``running`` and its buffer
        is drained (or nothing new arrives for ``timeout_s`` — a parked
        reader must not leak its thread).  Pumps the manager while it
        waits when no driver thread is running, so a bare
        manager+pipeline needs no extra machinery to stream."""
        s = self.get(sid)
        # (step, kind-rank) cursor: a final frame at exactly from_step
        # is (re-)delivered — the reconnecting reader may have seen only
        # the preview there before the session closed; re-delivery is
        # idempotent under the (step, kind) dedup key
        cursor = (int(from_step), KIND_RANK["preview"])
        deadline = self._clock() + timeout_s
        while True:
            batch = s.frames_after(*cursor)
            for fr in batch:
                pos = (fr.step, KIND_RANK[fr.kind])
                if pos > cursor:
                    cursor = pos
                yield fr
            if batch:
                deadline = self._clock() + timeout_s
                continue
            with s._lock:
                running = s.state == "running"
            if not running:
                return
            if self._clock() >= deadline:
                return
            if self._driver is None:
                self.pump(block=True)
            else:
                with s._lock:
                    s._wake.wait(poll_s)

    # -- the pump (chunk submit/retire) --------------------------------------
    def pump(self, block: bool = False) -> int:
        """Advance every session one event: retire a completed chunk
        (emit frame, apply queued retargets, checkpoint) or submit the
        next one (budget + admission gates willing).  ``block=True``
        additionally waits for ONE in-flight chunk to finish (the
        deterministic drive for pipeline backends).  Returns the number
        of progress events."""
        moved = 0
        for s in self.sessions():
            moved += self._pump_session(s, block=block)
        return moved

    def drive(self, *, timeout_s: float = 300.0) -> None:
        """Pump until no session is running (the drain of the session
        tier: bounded sessions complete, open-ended ones must be closed
        first)."""
        deadline = self._clock() + timeout_s
        while self._active_count():
            if self.pump(block=True) == 0:
                time.sleep(0.001)  # every session deferred: let the
                # budget window roll instead of spinning hot
            if self._clock() >= deadline:
                raise TimeoutError(
                    f"sessions still running after {timeout_s:.0f}s")

    def start_driver(self, poll_s: float = 0.005) -> None:
        """Run the pump on a daemon thread (the HTTP tier's drive)."""
        if self._driver is not None:
            return
        self._stop_driver.clear()

        def loop():
            while not self._stop_driver.wait(poll_s):
                try:
                    self.pump(block=False)
                except Exception as e:  # noqa: BLE001 — the driver must
                    # survive a transient backend refusal; sessions fail
                    # individually through their own error path
                    print(f"sessions: pump failed ({e!r})",
                          file=sys.stderr)

        self._driver = threading.Thread(target=loop, daemon=True,
                                        name="nlheat-session-driver")
        self._driver.start()

    def _handle_done(self, h) -> bool:
        done = getattr(h, "done", None)
        if done is not None:
            return done.is_set()
        # pipeline handle: advance the scheduler, then check
        pump = getattr(self.backend, "pump", None)
        if pump is not None:
            pump()
        return h.result is not None or h.error is not None

    def _wait_handle(self, h, timeout_s: float = 600.0) -> None:
        done = getattr(h, "done", None)
        if done is not None:
            done.wait(timeout_s)
            return
        try:
            h.wait()  # pipeline fence; ServeError lands on h.error
        except Exception:  # noqa: BLE001 — the retire path classifies
            pass

    def _pump_session(self, s: Session, block: bool) -> int:
        # claim the session: stream() pumps from reader threads when no
        # driver runs, and two concurrent pumps observing inflight None
        # would double-submit a chunk (orphaning one handle and double-
        # counting the budget window)
        with s._lock:
            if s.state != "running" or s._pump_busy:
                return 0
            s._pump_busy = True
            h = s.inflight
        try:
            if h is not None:
                if block and not self._handle_done(h):
                    self._wait_handle(h)
                if not self._handle_done(h):
                    return 0
                self._retire_chunk(s, h)
                return 1
            return self._submit_chunk(s)
        finally:
            with s._lock:
                s._pump_busy = False

    def _submit_chunk(self, s: Session) -> int:
        now = self._clock()
        with s._lock:
            n = s.spec.chunk_steps
            if s.spec.nt is not None:
                n = min(n, int(s.spec.nt) - s.step)
            if n <= 0:
                # nothing left (an nt reached exactly at a boundary is
                # finished by the retire path; this covers nt == t0).
                # Only the state flip happens under the session lock:
                # _active_count takes the MANAGER lock, and metrics()
                # holds it while reading sessions — taking it here
                # would invert the mgr -> session lock order
                s.state = "done"
                self._emit_final(s)
                s._wake.notify_all()
            else:
                # per-session budget: a rolling window of budget_steps
                if s.spec.budget_steps:
                    if now - s.window_t0 >= s.spec.budget_window_s:
                        s.window_t0 = now
                        s.steps_in_window = 0
                    if s.steps_in_window + n > s.spec.budget_steps:
                        s.deferrals += 1
                        self._m_deferrals.inc()
                        return 0
                case = EnsembleCase(
                    shape=s.spec.shape, nt=n, eps=s.spec.eps, k=s.k,
                    dt=s.spec.dt, dh=s.spec.dh, test=False,
                    u0=np.array(s.u), mesh=s.spec.mesh)
                sticky = s.sticky_key()
        if n <= 0:
            self._m_completed.inc()
            self._m_active.set(self._active_count())
            self._note_ended(s.sid)
            return 1
        # the fleet-wide session gate (serve/http.py AdmissionController):
        # a saturated batch tier defers session chunks — deferral, never
        # an error (outside the session lock: the gate reads the backend)
        if self.admission is not None:
            retry = self.admission.admit_session(n)
            if retry is not None:
                with s._lock:
                    s.deferrals += 1
                self._m_deferrals.inc()
                return 0
        try:
            # session chunks yield ties to the batch tier (priority -1);
            # the sticky key is the session's placement identity (the
            # router pins it; the in-process pipeline accepts + ignores)
            h = self.backend.submit(case, priority=-1, sticky_key=sticky)
        except RouterOverloaded:
            # the router's hard cap: defer, exactly like the soft gate
            with s._lock:
                s.deferrals += 1
            self._m_deferrals.inc()
            return 0
        with s._lock:
            s.inflight = h
            s.inflight_steps = n
            s.inflight_t0 = now
            if s.spec.budget_steps:
                s.steps_in_window += n
        return 1

    def _retire_chunk(self, s: Session, h) -> None:
        t1 = self._clock()
        err = h.error
        if err is None and h.result is None:
            err = RuntimeError("chunk handle completed with no result")
        if err is not None:
            with s._lock:
                s.inflight = None
                s.state = "failed"
                s.error = err
                s._wake.notify_all()
            self._m_failed.inc()
            self._m_active.set(self._active_count())
            self._note_ended(s.sid)
            obs_trace.instant("session.failed", cat="session",
                              session=s.sid, step=s.step,
                              error=type(err).__name__)
            self._event("session-failed", session=s.sid, step=s.step,
                        detail=str(err))
            return
        applied = []
        with s._lock:
            n = s.inflight_steps
            t0 = getattr(s, "inflight_t0", t1)
            s.inflight = None
            u = np.asarray(h.result, np.float64)
            # first-order source splitting at the chunk boundary (module
            # docstring): the active piecewise-constant b(x) integrates
            # as one n*dt impulse per chunk
            if s.source is not None:
                u = u + (n * s.spec.dt) * s.source
            s.u = u
            s.step += n
            s.chunks_done += 1
            # chunk-boundary control plane: queued retargets apply HERE,
            # with the boundary step recorded as auditable evidence
            for rt in s.retarget_queue:
                entry = {"verb": "retarget", "applied_at_step": s.step,
                         "requested_at_step": rt["requested_at_step"]}
                if rt["k"] is not None:
                    s.k = float(rt["k"])
                    entry["k"] = s.k
                if rt["clear"]:
                    s.source = None
                    entry["source"] = "clear"
                elif rt["source"] is not None:
                    s.source = rt["source"]
                    entry["source"] = "set"
                s.audit.append(entry)
                applied.append(entry)
            s.retarget_queue = []
            self._emit_preview(s)
            finished = s.spec.nt is not None and s.step >= int(s.spec.nt)
            ckpt_due = (self.checkpoint_dir is not None
                        and s.spec.checkpoint_every
                        and s.chunks_done % s.spec.checkpoint_every == 0)
            if finished:
                s.state = "done"
                self._emit_final(s)
                s._wake.notify_all()
            if ckpt_due or (finished and self.checkpoint_dir is not None
                            and s.spec.checkpoint_every):
                save_session_checkpoint(
                    self.checkpoint_dir, s.sid, s.step, s.u,
                    s.spec.params(s.k, s.source), keep=self.ckpt_keep)
                s.last_checkpoint = s.step
                self._m_checkpoints.inc()
            step_now = s.step
        self._m_chunks.inc()
        self._m_steps.inc(n)
        self._h_chunk_ms.observe((t1 - t0) * 1e3)
        if obs_trace.get_tracer() is not None:
            obs_trace.get_tracer().complete(
                "session.chunk", t0, t1, cat="session", session=s.sid,
                step=step_now, steps=n)
        self._event("session-chunk", session=s.sid, step=step_now,
                    steps=n, retargets_applied=len(applied))
        for entry in applied:
            obs_trace.instant("session.retarget-applied", cat="session",
                              session=s.sid, step=entry["applied_at_step"])
            self._event("session-retarget-applied", session=s.sid,
                        **entry)
        if finished:
            self._m_completed.inc()
            self._m_active.set(self._active_count())
            self._note_ended(s.sid)
            obs_trace.instant("session.done", cat="session",
                              session=s.sid, step=step_now)
            self._event("session-done", session=s.sid, step=step_now)

    # -- checkpoint surface ---------------------------------------------------
    def checkpoints(self, sid: str) -> list:
        if self.checkpoint_dir is None:
            return []
        return list_session_checkpoints(self.checkpoint_dir, sid)

    # -- shutdown -------------------------------------------------------------
    def metrics(self) -> dict:
        with self._lock:
            per = {sid: s.status() for sid, s in self._sessions.items()}
        r = self.registry

        def val(name):
            m = r.get(name)
            return m.value if m is not None else 0

        return {
            "active": self._active_count(),
            "opened": val("/session/opened"),
            "completed": val("/session/completed"),
            "closed": val("/session/closed"),
            "failed": val("/session/failed"),
            "chunks": val("/session/chunks"),
            "steps": val("/session/steps"),
            "frames": val("/session/frames"),
            "retargets": val("/session/retargets"),
            "forks": val("/session/forks"),
            "resumes": val("/session/resumes"),
            "checkpoints": val("/session/checkpoints"),
            "deferrals": val("/session/deferrals"),
            "chunk_ms": self._h_chunk_ms.percentiles(),
            "sessions": per,
        }

    def close(self) -> None:
        """Stop the driver and end every running session (their current
        boundary state becomes the final frame — a closing front door
        must never leave a stream reader parked).  The backend is the
        caller's: never closed here."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._stop_driver.set()
        if self._driver is not None:
            self._driver.join(timeout=5.0)
            self._driver = None
        for s in self.sessions():
            with s._lock:
                if s.state == "running":
                    s.state = "closed"
                    self._emit_final(s)
                    s._wake.notify_all()
                    self._m_closed.inc()
        self._m_active.set(0)
        if self._events is not None:
            self._events.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def session_stream_bench(engine_kwargs: dict, *, sessions: int,
                         grid: int, chunk_steps: int, chunks: int,
                         batch_cases: int, replicas: int = 2,
                         dt: float = 1e-7, eps: int = 8,
                         batch_rate_factor: float = 0.5,
                         queue_wait_bound_ms: float | None = None) -> dict:
    """The session-tier measurement shared by bench.py (``BENCH_SESSION``)
    and tools/bench_table.py (``sessions`` group): ``sessions`` concurrent
    streaming sessions (each ``chunks`` chunks of ``chunk_steps`` steps)
    driven over a ``replicas``-worker fleet WHILE a paced batch load runs
    through the shared admission controller.  The session gate is set to
    HALF the fleet's measured step capacity, so the acceptance question
    is concrete: with budgets active, a saturating session tier must
    leave the batch tier's p99 inside the admission bound and shed
    nothing (``budget_held``), with the sessions' appetite visibly
    deferred (``deferrals``).  Frames/s is the stream throughput at the
    chunk cadence.  A host measurement like router_load_ab — callers pin
    BENCH_PLATFORM=cpu."""
    from nonlocalheatequation_tpu.serve.http import (
        AdmissionController,
        offered_load_run,
    )
    from nonlocalheatequation_tpu.serve.router import ReplicaRouter

    rng = np.random.default_rng(0)
    phys = dict(eps=eps, k=1.0, dt=dt, dh=1.0 / grid)
    batch = [EnsembleCase(shape=(grid, grid), nt=chunk_steps,
                          test=False,
                          u0=rng.normal(size=(grid, grid)), **phys)
             for _ in range(batch_cases)]
    out: dict = {"sessions": sessions, "chunks": chunks,
                 "chunk_steps": chunk_steps}
    with ReplicaRouter(replicas=replicas, **engine_kwargs) as router:
        router.serve_cases(batch)  # warm pass: compiles
        t0 = time.perf_counter()
        router.serve_cases(batch)
        unloaded_wall = time.perf_counter() - t0
        hist = router.registry.get("/router/request-latency-ms")
        tail = list(hist.samples)[-len(batch):]
        unloaded_p99 = float(np.percentile(tail, 99))
        capacity_hz = len(batch) / unloaded_wall
        bound_ms = (queue_wait_bound_ms if queue_wait_bound_ms
                    else max(250.0, 5.0 * unloaded_p99))
        # the session gate: HALF the measured step capacity, burst
        # pinned to ONE chunk so the gate engages at any scale (the
        # smoke harness's 32^2 runs included, not only past the first
        # second of streaming)
        rate = 0.5 * capacity_hz * chunk_steps
        adm = AdmissionController(router, session_steps_per_s=rate,
                                  session_burst_steps=chunk_steps)
        with SessionManager(router, admission=adm,
                            chunk_steps=chunk_steps) as mgr:
            t0 = time.perf_counter()
            for i in range(sessions):
                mgr.open(shape=(grid, grid),
                         u0=rng.normal(size=(grid, grid)),
                         nt=chunks * chunk_steps,
                         chunk_steps=chunk_steps, budget_steps=0,
                         checkpoint_every=0, **phys)
            mgr.start_driver()
            sweep = offered_load_run(
                adm, batch + batch, batch_rate_factor * capacity_hz)
            sweep.pop("results", None)
            mgr.drive(timeout_s=600.0)
            wall = time.perf_counter() - t0
            m = mgr.metrics()
        p99_ms = sweep["latency_s"]["p99"] * 1e3
        out.update(
            wall_s=wall,
            unloaded_wall_s=unloaded_wall,
            capacity_hz=round(capacity_hz, 3),
            frames=m["frames"],
            frames_per_s=round(m["frames"] / wall, 3),
            steps_streamed=m["steps"],
            deferrals=m["deferrals"],
            session_rate_steps_s=round(rate, 1),
            batch={"offered": sweep["offered"],
                   "accepted": sweep["accepted"],
                   "shed": sweep["shed"],
                   "p99_ms": round(p99_ms, 3)},
            bound_ms=round(bound_ms, 3),
            unloaded_p99_ms=round(unloaded_p99, 3),
            # the acceptance: budgets held IF the batch tier shed
            # nothing, its p99 stayed inside the admission bound, and
            # the sessions' appetite was genuinely deferred
            budget_held=bool(sweep["shed"] == 0 and p99_ms <= bound_ms
                             and m["deferrals"] > 0),
        )
    return out


def session_resume_ab(engine_kwargs: dict, *, grid: int,
                      chunk_steps: int, chunks: int, ckpt_dir: str,
                      dt: float = 1e-7, eps: int = 8) -> dict:
    """The resume bit-identity measurement shared by bench.py and
    tools/bench_table.py: ONE session run uninterrupted vs the same
    spec killed after half its chunks (manager close — the front-door
    death; checkpoints stay on disk) and resumed by a fresh manager.
    The resumed stream's frames, deduped by (step, kind), must equal
    the uninterrupted run's bitwise, final f64 field included."""
    from nonlocalheatequation_tpu.serve.server import ServePipeline

    rng = np.random.default_rng(1)
    phys = dict(eps=eps, k=1.0, dt=dt, dh=1.0 / grid)
    u0 = rng.normal(size=(grid, grid))
    nt = chunks * chunk_steps

    def frames_of(mgr, sid):
        return {(f.step, f.kind): np.array(f.values)
                for f in mgr.get(sid).frames_after(-1)}

    with ServePipeline(depth=1, window_ms=0.0, **engine_kwargs) as pipe:
        with SessionManager(pipe, chunk_steps=chunk_steps) as mgr:
            a = mgr.open(shape=(grid, grid), u0=u0, nt=nt,
                         checkpoint_every=0, **phys)
            mgr.drive(timeout_s=600.0)
            want_final = a.result()
            want_frames = frames_of(mgr, a.sid)
    kill_at = max(1, chunks // 2) * chunk_steps
    with ServePipeline(depth=1, window_ms=0.0, **engine_kwargs) as pipe:
        mgr = SessionManager(pipe, checkpoint_dir=ckpt_dir,
                             chunk_steps=chunk_steps)
        b = mgr.open(shape=(grid, grid), u0=u0, nt=nt,
                     checkpoint_every=1, **phys)
        sid = b.sid
        while b.step < kill_at:
            if b.state != "running":
                # a chunk completed exceptionally before the kill
                # point: fail the measurement loudly instead of
                # hot-spinning until the external budget kills us
                raise RuntimeError(
                    f"session_resume_ab: session {b.state!r} before "
                    f"the kill point ({b.status()['error']})")
            mgr.pump(block=True)
        pre = frames_of(mgr, sid)
        mgr.close()  # the injected front-door death
    with ServePipeline(depth=1, window_ms=0.0, **engine_kwargs) as pipe:
        with SessionManager(pipe, checkpoint_dir=ckpt_dir) as mgr2:
            br = mgr2.resume(sid)
            resumed_from = br.resumed_from
            mgr2.drive(timeout_s=600.0)
            got_final = br.result()
            got = dict(pre)
            got.update(frames_of(mgr2, sid))
    bit = bool(
        np.array_equal(got_final, want_final)
        and set(got) == set(want_frames)
        and all(np.array_equal(got[key], want_frames[key])
                for key in want_frames))
    return {"bit_identical": bit, "resumed_from": resumed_from,
            "kill_at": kill_at, "frames": len(want_frames)}
