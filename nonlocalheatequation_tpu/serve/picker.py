"""Deadline-aware engine picker: (physics, grid, T_final, accuracy,
deadline_ms) -> the cheapest engine that meets both targets.

The serving stack used to make USERS name kernels: a request carried
``nt``/``dt`` plus whatever ``--stepper/--method/--precision`` the fleet
was launched with, and picking the 50x-fewer-steps integrator (PR 7) or
the stencil<->fft crossover (``utils/autotune.pick_op_method``) was the
operator's job.  This module is that autotune dimension generalized
across **stepper x stages x method x precision** (ISSUE 13) and closed
over the request's real contract — an accuracy target and a deadline:

* **Stability model** — ``ops/constants.stable_dt`` (the single source
  of truth since ISSUE 8) caps each candidate's dt at the benches' 0.8x
  headroom (``models/steppers.superstep_floor``'s rule); expo is
  unconditionally stable (floor 1 step).
* **Accuracy model** — every shipped stepper is first order, so the
  manufactured-solution class (``u = cos(2 pi t) G(x)``, the protocol
  every test/bench case runs) carries a closed-form time-discretization
  error: local truncation ``(2 pi)^2 dt^2 / 2`` accumulated over
  ``T/dt`` steps gives ``err(x, T) ~ 0.5 T (2 pi)^2 dt G(x)``, hence
  ``error_l2/#points ~ (0.5 T (2 pi)^2 dt)^2 mean(G^2)`` with
  ``mean(G^2) = 0.5^d`` for the cosine-product profile.  The model is
  applied with :data:`ERR_SAFETY` margin and was checked against
  measured errors (factor ~2 conservative at the probe configs,
  docs/round15.md); a candidate whose modeled error exceeds
  ``accuracy`` at its stability-capped dt is INFEASIBLE — the picker
  never gambles accuracy for the deadline.  bf16 candidates carry the
  tier's measured error floor (``constants.BF16_L2_BUDGET``) on top.
  expo is time-exact in the interior; its collar defect now carries a
  measured per-request model (:func:`modeled_expo_defect`, ISSUE 16 —
  calibrated amplitude ``min(1, C r^2)`` with ``r`` the substep/Euler-
  bound ratio, squared over the ``2 d eps / min(shape)`` boundary
  band; conservative 5-30x at every probe point, docs/round18.md), so
  corrected expo candidates compete WITHOUT opt-in whenever
  ``ERR_SAFETY * defect <= accuracy`` at the minimal feasible substep
  count.  ``allow_expo=True`` / ``NLHEAT_PICK_EXPO=1`` still forces a
  caller-asserted candidate at ``expo_stages`` (the pre-model opt-in
  envelope); ``allow_expo=False`` excludes the stepper entirely.
* **Cost model** — steps x operator applies per step (s for rkc, 1 for
  euler, ~3.5 fft-equivalents per corrected expo substage) x
  per-apply milliseconds.  Rates come from ``rate_fn`` when the caller
  has one, else from the autotuner's persisted probe records
  (:func:`record_rate_fn` — the tuned ms_per_step entries keyed by
  device kind), else from the analytic proxy (stencil
  ``O(N (2 eps + 1)^d)``, fft ``O(N_box log N_box)``) whose CONSTANTS
  are relative-cost-grade: good enough to rank candidates, honest
  enough for a deadline only to the order of magnitude — which is why
  the refusal message names the model used.  The default is
  deliberately backend-free: the picker runs in the ROUTER/ingress
  process, which must never touch a JAX backend (the wedge
  discipline), so looking up the device kind is the caller's opt-in.

The selection is the cheapest feasible candidate; when nothing meets
both targets the picker REFUSES loudly (:class:`PickerRefusal` names
the best accuracy-feasible candidate and what it would cost) — it
never silently serves an engine that misses the accuracy target.

Env knobs (scrubbed in tests/conftest.py): ``NLHEAT_PICK_STAGES`` — the
rkc stage ladder (comma list, default ``4,8,16,32``);
``NLHEAT_PICK_EXPO=1`` — FORCE the caller-asserted expo candidate at
``expo_stages`` (the defect-model-gated candidate competes by default).
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass

#: Default rkc stage ladder the picker enumerates (beta(s) ~ 2 s^2:
#: dt reach ~15x/61x/246x/990x the Euler bound at the 0.8x headroom).
STAGE_LADDER = (4, 8, 16, 32)

#: Safety factor on the manufactured-class error model: the model
#: neglects the diffusive decay of accumulated truncation error (it
#: OVERestimates ~2x at the probe configs), so the margin guards the
#: other direction — constant slop on unusually boundary-loaded or
#: long-horizon requests.  A candidate is feasible only when
#: ``ERR_SAFETY * modeled_error <= accuracy``.
ERR_SAFETY = 4.0

#: Analytic per-apply cost constants (nanoseconds per point-op), the
#: backend-free fallback rate model.  Relative-cost grade; see the
#: module docstring for the honesty boundary.
NS_PER_STENCIL_POINT = 0.6
NS_PER_FFT_POINT = 4.0

#: Operator applies per corrected expo substage (the midpoint Duhamel
#: correction costs ~3.5 fft round trips per substep; the plain step 1).
EXPO_CORR_APPLIES = 3.5

#: Collar-defect amplitude model for corrected expo (ISSUE 16):
#: ``e ~ min(EXPO_DEFECT_CAP, EXPO_DEFECT_COEF * r^2)`` with ``r`` the
#: substep-to-Euler-bound ratio ``(T_final / S) / stable_dt(euler)``.
#: Measured across S in {1,2,4,8} and r in [0.25, 45] on 24^2/eps 3 and
#: 50^2/eps 5 (one-shot solves, the picker's usage): the fitted
#: coefficient never exceeds 1.05e-3, so 2e-3 is conservative 2x at the
#: worst probe point and 5-30x in squared err/#points units everywhere.
EXPO_DEFECT_COEF = 2e-3
EXPO_DEFECT_CAP = 1.0

#: bf16 operand windows halve the bandwidth of the memory-bound stencil
#: reads; the analytic model credits the tier conservatively.
BF16_RATE = 0.7


class PickerRefusal(ValueError):
    """No engine meets the request's accuracy + deadline.  Loud by
    design: the picker must never quietly select an engine that misses
    the accuracy target, and a deadline nothing can meet is the
    CLIENT's 422, not a silently slow solve."""

    def __init__(self, message: str, best=None):
        super().__init__(message)
        self.best = best  # the cheapest accuracy-feasible EngineChoice


@dataclass(frozen=True)
class EngineChoice:
    """One picked engine: the ensemble-engine settings plus the step
    schedule (dt, steps) and the model's evidence (est_ms, est_err,
    rate source) — everything a worker needs to run the case and a
    client needs to audit the pick."""

    stepper: str
    stages: int
    method: str
    precision: str
    dt: float
    steps: int
    est_ms: float
    est_err: float
    rates: str  # "measured" | "live" | "records" | "analytic"

    def engine_kwargs(self) -> dict:
        """The EnsembleEngine/sibling settings of this choice."""
        return {"stepper": self.stepper, "stages": self.stages,
                "method": self.method, "precision": self.precision}

    def key(self) -> tuple:
        """The engine-pool key (serve/server.py ``_engine_for``)."""
        return (self.stepper, self.stages, self.method, self.precision)

    def wire(self) -> dict:
        """Frame/JSON form (serve/router.py case frames, the ingress
        response)."""
        return {"stepper": self.stepper, "stages": self.stages,
                "method": self.method, "precision": self.precision,
                "dt": self.dt, "steps": self.steps,
                "est_ms": self.est_ms,
                "est_err": self.est_err, "rates": self.rates}

    @classmethod
    def from_wire(cls, d):
        if d is None:
            return None
        return cls(stepper=str(d["stepper"]), stages=int(d["stages"]),
                   method=str(d["method"]), precision=str(d["precision"]),
                   dt=float(d["dt"]), steps=int(d["steps"]),
                   est_ms=float(d.get("est_ms", 0.0)),
                   est_err=float(d.get("est_err", 0.0)),
                   rates=str(d.get("rates", "analytic")))


def _wsum(dim: int, eps: int) -> float:
    import numpy as np

    from nonlocalheatequation_tpu.ops.stencil import (
        horizon_mask_1d,
        horizon_mask_2d,
        horizon_mask_3d,
    )

    mask = {1: horizon_mask_1d, 2: horizon_mask_2d,
            3: horizon_mask_3d}[dim](eps)
    return float(np.asarray(mask, np.float64).sum())


def _c_const(dim: int, k: float, eps: int, h: float) -> float:
    from nonlocalheatequation_tpu.ops import constants as C

    return {1: C.c_1d, 2: C.c_2d, 3: C.c_3d}[dim](k, eps, h)


def analytic_rate_fn(method: str, shape, eps: int,
                     precision: str) -> float:
    """Per-apply milliseconds from the backend-free analytic proxy
    (module docstring honesty note): stencil O(N (2 eps + 1)^d), fft
    O(N_box log2 N_box).  ``method='gather'`` (the mesh axis) rides the
    stencil branch on purpose: with the rank-1 ``(n,)`` shape and the
    mesh's effective eps (:func:`_mesh_eps_eff`) the same formula
    prices O(nnz), the gather tier's true per-apply work."""
    n = 1
    for s in shape:
        n *= int(s)
    if method == "fft":
        from nonlocalheatequation_tpu.ops.spectral import fft_box

        nb = 1
        for s in fft_box(shape, eps):
            nb *= int(s)
        ms = nb * max(1.0, math.log2(nb)) * NS_PER_FFT_POINT * 1e-6
    else:
        ms = n * (2 * eps + 1) ** len(shape) * NS_PER_STENCIL_POINT * 1e-6
        if precision == "bf16":
            ms *= BF16_RATE
    return ms


def record_rate_fn(device_kind: str, dtype_name: str = "float32",
                   version: str | None = None):
    """A rate_fn over the autotuner's persisted probe records
    (utils/autotune file cache): per-apply ms from each record's LIVE
    recalibrated rate when serving traffic has banked one (obs/slo.py
    ``LiveRateRecorder`` — the ISSUE 20 feedback loop), else the probed
    ``per-step`` entry where one exists, else the analytic proxy.
    ``device_kind`` is the CALLER's knowledge (a worker that already
    touched its backend, a bench that measured) — the picker itself
    stays backend-free.  The closure's ``provenance`` reports ``"live"``
    when any loaded record carries a live rate (the EngineChoice.rates
    audit label then names the freshest source a lookup can hit),
    ``"records"`` otherwise."""
    from nonlocalheatequation_tpu.utils.autotune import _load_file_cache

    if version is None:
        from nonlocalheatequation_tpu import __version__ as version
    cache = _load_file_cache()

    def _num(v):
        return (float(v) if isinstance(v, (int, float))
                and not isinstance(v, bool) else None)

    def rate(method, shape, eps, precision):
        key = "/".join(
            [f"v{version}", device_kind, method,
             "x".join(str(int(s)) for s in shape), f"eps{eps}",
             dtype_name]
            + ([f"prec-{precision}"] if precision != "f32" else []))
        entry = cache.get(key) or {}
        ms = _num(((entry.get("live") or {}).get("per-step")))
        if ms is None:
            ms = _num((entry.get("ms_per_step") or {}).get("per-step"))
        if ms is not None:
            return ms
        return analytic_rate_fn(method, shape, eps, precision)

    rate.provenance = "live" if any(
        _num(((e or {}).get("live") or {}).get("per-step")) is not None
        for e in cache.values() if isinstance(e, dict)) else "records"
    return rate


def _stage_ladder() -> tuple:
    env = os.environ.get("NLHEAT_PICK_STAGES")
    if not env:
        return STAGE_LADDER
    try:
        ladder = tuple(sorted({int(t) for t in env.split(",") if t.strip()}))
    except ValueError:
        raise ValueError(
            f"NLHEAT_PICK_STAGES must be a comma list of ints, got "
            f"{env!r}") from None
    if not ladder or any(s < 2 for s in ladder):
        raise ValueError(
            f"NLHEAT_PICK_STAGES needs stage counts >= 2, got {env!r}")
    return ladder


def modeled_error(dim: int, T_final: float, dt: float) -> float:
    """The manufactured-class time-discretization error model (module
    docstring): ``(0.5 T (2 pi)^2 dt)^2 * 0.5^d`` — error_l2/#points
    units, the repo's accuracy currency."""
    amp = 0.5 * T_final * (2.0 * math.pi) ** 2 * dt
    return amp * amp * 0.5 ** dim


def _boundary_frac(shape, eps: int) -> float:
    """Fraction of grid points inside the eps-wide collar-coupled band
    (two faces per axis; the defect lives there, the interior is
    time-exact)."""
    return min(1.0, 2.0 * len(shape) * eps / min(int(s) for s in shape))


def modeled_expo_defect(shape, eps: int, euler_bound: float,
                        T_final: float, stages: int) -> float:
    """The corrected expo collar defect for ONE step to ``T_final``
    with ``stages = S >= 1`` substeps, in error_l2/#points units:
    amplitude ``min(cap, C r^2)`` (:data:`EXPO_DEFECT_COEF` calibration
    note) squared over the boundary band fraction.  Conservative by
    construction — the qualification gate multiplies ERR_SAFETY on
    top, so a defect the model clears really does sit under the
    measured one with >= 10x total margin at every probe point."""
    S = max(1, int(stages))
    r = (T_final / S) / euler_bound
    e = min(EXPO_DEFECT_CAP, EXPO_DEFECT_COEF * r * r)
    return e * e * _boundary_frac(shape, eps)


def _expo_min_stages(shape, eps: int, euler_bound: float,
                     T_final: float, accuracy: float) -> int | None:
    """Smallest S with ``ERR_SAFETY * modeled_expo_defect <= accuracy``
    (defect is monotone decreasing and cost monotone increasing in S,
    so the minimal feasible S is also the cheapest).  None when even
    the unsaturated quadratic regime cannot reach the budget."""
    e_budget = math.sqrt(accuracy / (ERR_SAFETY * _boundary_frac(shape,
                                                                 eps)))
    if e_budget >= EXPO_DEFECT_CAP:
        return 1  # any substep count models inside the budget
    r_max = math.sqrt(e_budget / EXPO_DEFECT_COEF)
    if r_max <= 0 or not math.isfinite(r_max):
        return None
    return max(1, math.ceil(T_final / (r_max * euler_bound)))


def _mesh_eps_eff(op) -> int:
    """The mesh's effective integer eps for the RATE models: chosen so
    the analytic stencil formula ``n * (2 eps + 1)^rank`` over the
    rank-1 ``(n,)`` shape prices ``O(nnz)`` — the gather tier's true
    per-apply work.  Probe records use the same key
    (``gather/<n>/eps<e>``), so measured gather rates slot in next to
    stencil/fft without a new rate_fn signature."""
    mean_deg = (len(op.tgt) / op.n) if op.n else 1.0
    return max(0, round((mean_deg - 1.0) / 2.0))


def _pick_mesh_engine(mesh: str, k: float, T_final: float,
                      accuracy: float, deadline_ms, rate_fn,
                      rates_label: str, mesh_dir) -> EngineChoice:
    """The mesh axis (ISSUE 17): candidates are the Pallas gather tier
    (ops/pallas_gather.py) — method='gather', Euler-only (the tier has
    no rkc/expo schedule), f32 + bf16 pair-frame precisions.  The
    stability bound is the mesh's REAL per-point bound
    ``1 / max(c_i * wsum_i)`` (the unstructured CLI's rule,
    cli/solve_unstructured.py), computed from the registered cloud on
    the host — no backend touched (wedge discipline: the ctor of
    UnstructuredNonlocalOp is pure NumPy)."""
    import numpy as np

    from nonlocalheatequation_tpu.ops.constants import BF16_L2_BUDGET
    from nonlocalheatequation_tpu.serve.meshes import get_mesh_op

    op = get_mesh_op(mesh, k, dt=1.0, mesh_dir=mesh_dir)
    dim = op.d
    bound = float(np.max(op.c * op.wsum))
    if not (bound > 0 and math.isfinite(bound)):
        raise PickerRefusal(
            f"mesh {mesh}: degenerate stability bound {bound!r} "
            "(empty edge table?)")
    eps_eff = _mesh_eps_eff(op)
    shape = (int(op.n),)

    def dt_cap(floor: float = 0.0) -> float:
        budget = accuracy / ERR_SAFETY - floor
        if budget <= 0:
            return 0.0
        return math.sqrt(budget / 0.5 ** dim) / (
            0.5 * T_final * (2.0 * math.pi) ** 2)

    candidates: list[EngineChoice] = []
    for prec in ("f32", "bf16"):
        cap = dt_cap(BF16_L2_BUDGET if prec == "bf16" else 0.0)
        if cap <= 0:
            continue
        dt = min(0.8 / bound, cap)
        if not math.isfinite(dt) or dt <= 0:
            continue
        steps = max(1, math.ceil(T_final / dt))
        dt = T_final / steps
        err = modeled_error(dim, T_final, dt)
        if prec == "bf16":
            err = err + BF16_L2_BUDGET
        if ERR_SAFETY * err > accuracy:
            continue
        candidates.append(EngineChoice(
            stepper="euler", stages=0, method="gather", precision=prec,
            dt=dt, steps=steps,
            est_ms=steps * rate_fn("gather", shape, eps_eff, prec),
            est_err=err, rates=rates_label))
    if not candidates:
        raise PickerRefusal(
            f"no gather engine meets accuracy {accuracy:g} for "
            f"T_final={T_final:g} on mesh {mesh} ({op.n} nodes)")
    candidates.sort(key=lambda ch: (ch.est_ms, ch.steps))
    if deadline_ms is not None:
        feasible = [ch for ch in candidates if ch.est_ms <= deadline_ms]
        if not feasible:
            best = candidates[0]
            raise PickerRefusal(
                f"no gather engine meets deadline {deadline_ms:g} ms "
                f"at accuracy {accuracy:g} on mesh {mesh}: the "
                f"cheapest accuracy-feasible engine models "
                f"{best.est_ms:.1f} ms ({best.rates} rates)", best=best)
        return feasible[0]
    return candidates[0]


def pick_engine(shape, eps: int, k: float, dh: float, T_final: float,
                accuracy: float, deadline_ms: float | None = None, *,
                method: str = "auto", rate_fn=None,
                stages_ladder=None, allow_expo: bool | None = None,
                allow_fft: bool = True,
                expo_stages: int = 2, mesh: str | None = None,
                mesh_dir=None) -> EngineChoice:
    """The cheapest (stepper, stages, method, precision) engine meeting
    ``accuracy`` (error_l2/#points, the manufactured contract's units)
    and ``deadline_ms`` (None = no deadline) for a solve of ``T_final``
    physical time on ``shape`` — or :class:`PickerRefusal`.

    ``method`` is the fleet's stencil base ('auto' models as the conv/
    sat stencil); the fft twin competes unless ``allow_fft=False``.
    ``allow_fft`` is the ROUTER's sharded-fft capability verdict for
    cases bound for the gang tier (serve/router.py
    ``sharded_fft_capability``): True when the pencil-decomposed
    sharded transform (ops/spectral_sharded.py) can serve the (grid,
    mesh) pair — sharded picks then compete over the FULL stepper x
    stages x method x precision space — and False when it cannot
    (indivisible pencil split, unknown gang mesh, or the
    NLHEAT_FFT_SHARDED=0 kill-switch), which excludes fft and expo.
    ``rate_fn(method, shape, eps, precision) -> ms`` is
    the caller's measured cost model; default analytic (backend-free).

    ``mesh`` (ISSUE 17) switches to the MESH axis: the hash of a
    registered point cloud (serve/meshes.py).  Candidates are then the
    Pallas gather tier only (:func:`_pick_mesh_engine`); ``shape``,
    ``eps``, ``dh`` and the stepper/fft knobs are ignored — the mesh
    carries its own geometry and stability bound.
    """
    from nonlocalheatequation_tpu.ops.constants import (
        BF16_L2_BUDGET,
        stable_dt,
    )

    shape = tuple(int(s) for s in shape)
    dim = len(shape)
    if T_final <= 0:
        raise ValueError(f"T_final must be > 0, got {T_final}")
    if accuracy <= 0:
        raise ValueError(f"accuracy must be > 0, got {accuracy}")
    if deadline_ms is not None and deadline_ms <= 0:
        raise ValueError(f"deadline_ms must be > 0, got {deadline_ms}")
    if mesh is not None:
        if rate_fn is None:
            mesh_rate, mesh_label = analytic_rate_fn, "analytic"
        else:
            mesh_rate = rate_fn
            mesh_label = getattr(rate_fn, "provenance", "measured")
        return _pick_mesh_engine(mesh, k, T_final, accuracy,
                                 deadline_ms, mesh_rate, mesh_label,
                                 mesh_dir)
    # cost-model provenance for the audit trail: an injected rate_fn is
    # the caller's measurement unless it declares otherwise (the
    # record_rate_fn closure tags itself "records")
    if rate_fn is None:
        rate_fn = analytic_rate_fn
        rates_label = "analytic"
    else:
        rates_label = getattr(rate_fn, "provenance", "measured")
    if allow_expo is None and os.environ.get("NLHEAT_PICK_EXPO") == "1":
        allow_expo = True  # forced opt-in; None stays the model gate
    ladder = tuple(stages_ladder) if stages_ladder else _stage_ladder()
    wsum = _wsum(dim, eps)
    c = _c_const(dim, k, eps, dh)
    stencil = method if method not in ("auto", "fft") else "auto"
    if not allow_fft:
        if method == "fft":
            raise PickerRefusal(
                "the router's sharded-fft capability gate excludes "
                "method='fft' for this case (the pencil transposes "
                "cannot serve the (grid, mesh) pair, or "
                "NLHEAT_FFT_SHARDED=0 — serve/router.py "
                "sharded_fft_capability) and the fleet's base method "
                "IS fft: no servable candidate axis")
        methods = [stencil]
        allow_expo = False  # expo is fft-only
    else:
        methods = [stencil, "fft"] if stencil != "fft" else ["fft"]

    # accuracy cap on dt per error floor (the bf16 tier carries its
    # measured floor INSIDE the budget, so an accuracy-capped bf16
    # candidate gets a genuinely smaller dt instead of being generated
    # and then unconditionally rejected by its own feasibility check):
    # ERR_SAFETY * (model(dt) + floor) <= accuracy
    def dt_cap(floor: float = 0.0) -> float:
        budget = accuracy / ERR_SAFETY - floor
        if budget <= 0:
            return 0.0
        return math.sqrt(budget / 0.5 ** dim) / (
            0.5 * T_final * (2.0 * math.pi) ** 2)

    dt_acc = dt_cap()
    candidates: list[EngineChoice] = []
    steppers = [("euler", 0)] + [("rkc", s) for s in ladder]
    for m in methods:
        for prec in ("f32", "bf16"):
            cap = dt_acc
            if prec == "bf16":
                if m == "fft":
                    # the spectral path has no bf16 operand windows
                    continue
                cap = dt_cap(BF16_L2_BUDGET)
                if cap <= 0:
                    # the tier's measured error floor alone exceeds
                    # the budget at the safety margin
                    continue
            for stepper, stages in steppers:
                bound = stable_dt(c, dh, dim, wsum, stepper=stepper,
                                  stages=stages)
                dt = min(0.8 * bound, cap)  # superstep_floor headroom
                if not math.isfinite(dt) or dt <= 0:
                    continue
                steps = max(1, math.ceil(T_final / dt))
                dt = T_final / steps
                err = modeled_error(dim, T_final, dt)
                if prec == "bf16":
                    err = err + BF16_L2_BUDGET
                if ERR_SAFETY * err > accuracy:
                    continue  # infeasible: accuracy is never gambled
                applies = steps * (stages if stepper == "rkc" else 1)
                est_ms = applies * rate_fn(m, shape, eps, prec)
                candidates.append(EngineChoice(
                    stepper=stepper, stages=stages, method=m,
                    precision=prec, dt=dt, steps=steps, est_ms=est_ms,
                    est_err=err, rates=rates_label))
    eul = stable_dt(c, dh, dim, wsum)
    if allow_expo is True:
        # forced opt-in (the pre-model envelope): the caller asserts
        # the interior contract at its chosen substep count; est_err
        # still reports the model's verdict for the audit trail
        S = max(0, int(expo_stages))
        applies = max(1.0, EXPO_CORR_APPLIES * S)
        candidates.append(EngineChoice(
            stepper="expo", stages=S, method="fft", precision="f32",
            dt=T_final, steps=1,
            est_ms=applies * rate_fn("fft", shape, eps, "f32"),
            est_err=modeled_expo_defect(shape, eps, eul, T_final,
                                        max(1, S)),
            rates=rates_label))
    elif allow_expo is None and "fft" in methods:
        # the ISSUE 16 qualification: corrected expo competes without
        # opt-in when the measured collar-defect model clears the
        # accuracy target at the minimal (= cheapest) substep count —
        # one step to the horizon, unconditionally stable, never a
        # gamble (ERR_SAFETY rides the gate like every other candidate)
        S = _expo_min_stages(shape, eps, eul, T_final, accuracy)
        if S is not None:
            defect = modeled_expo_defect(shape, eps, eul, T_final, S)
            if ERR_SAFETY * defect <= accuracy:
                candidates.append(EngineChoice(
                    stepper="expo", stages=S, method="fft",
                    precision="f32", dt=T_final, steps=1,
                    est_ms=(EXPO_CORR_APPLIES * S
                            * rate_fn("fft", shape, eps, "f32")),
                    est_err=defect, rates=rates_label))

    if not candidates:
        # the accuracy cap comes from the closed-form manufactured
        # error model, never the rate model — name it correctly
        raise PickerRefusal(
            f"no engine meets accuracy {accuracy:g} for T_final="
            f"{T_final:g} on {shape} (dt cap {dt_acc:g} from the "
            "manufactured-class error model at ERR_SAFETY margin; "
            "even the finest stable step models past the target)")
    candidates.sort(key=lambda ch: (ch.est_ms, ch.steps, ch.stages))
    if deadline_ms is not None:
        feasible = [ch for ch in candidates if ch.est_ms <= deadline_ms]
        if not feasible:
            best = candidates[0]
            raise PickerRefusal(
                f"no engine meets deadline {deadline_ms:g} ms at "
                f"accuracy {accuracy:g} on {shape}: the cheapest "
                f"accuracy-feasible engine ({best.stepper}"
                f"[s={best.stages}]/{best.method}/{best.precision}, "
                f"{best.steps} steps) models {best.est_ms:.1f} ms "
                f"({best.rates} rates)", best=best)
        return feasible[0]
    return candidates[0]
