"""Async serving runtime: continuous batching + overlapped chunk dispatch.

The reference earns its scaling from HPX's asynchronous many-task model —
futures and dataflow overlapping communication, computation, and task
launch (README.md:12-14; the interior/boundary overlap at
src/2d_nonlocal_distributed.cpp:1156-1261).  The offline
:class:`~nonlocalheatequation_tpu.serve.ensemble.EnsembleEngine` is the
opposite schedule: ``run()`` builds, dispatches, and fences one chunk at
a time, so every chunk pays the full ~64 ms tunnel dispatch+fence round
trip (docs/bench/README.md) and the host idles while the device computes.
This module applies the reference's execution model to the request path:

* **Request lifecycle** — cases are :meth:`ServePipeline.submit`-ted
  incrementally (streaming stdin, a socket loop, a test harness), NOT as
  one pre-read batch.  Each request joins its bucket's OPEN chunk (the
  ensemble engine's ``(shape, nt, eps, test) x engine`` keys); the chunk
  closes at size B (``window_size``, default the engine's top batch
  size) or after T ms (``window_ms``) — whichever first — so late
  arrivals join in-flight-adjacent chunks instead of waiting for EOF.
* **Overlapped dispatch** — up to D (``depth``) chunks stay in flight.
  Dispatch is JAX-async: launching chunk N+1 (and building chunk N+2's
  program — a host-side trace) proceeds while chunk N computes.  The
  host fences ONLY when a result is actually due (the pipe is full and
  more work waits, a caller waits on a request, or ``drain()``), via the
  scalar :func:`fence_scalar` fetch — ``block_until_ready`` lies over
  the axon tunnel (docs/bench/README.md) — and NEVER between dispatches.
* **Deadline-aware scheduling** — ``submit(deadline_ms=...)`` bounds a
  case's microbatch wait: the earliest deadline in an open chunk pulls
  the close forward (an aging case forces a partial chunk out,
  starvation-free — the window T is an upper bound for every case);
  ``priority`` orders READY chunks at equal dispatch capacity.
  ``drain()`` flushes all partial chunks and in-flight work.
* **Observability** — :class:`ServeReport` extends the engine's report
  with per-request and per-chunk timing (queue wait, program build,
  dispatch->fence wall, fetch), an occupancy trace (chunks in flight
  over time), forced-close counts, and a one-call JSON dump
  (:meth:`ServePipeline.metrics_json`) — the overlap is measured, not
  assumed.

Served results are **bit-identical** to ``EnsembleEngine.run()`` on the
same case set: the pipeline reuses the engine's chunk stages
(``build_program`` / ``stage_inputs`` / ``dispatch_chunk``) and padding
rule verbatim — only the schedule changes (tests/test_serve.py pins
this, plus the no-fence-between-dispatches discipline via spy counters).

Buffer donation (utils/donation.py) is pipeline-UNSAFE past depth 1: the
pipeline declares its depth via ``donation.set_pipeline_depth``, which
pins the lazy donate decision off and refuses an explicit
``NLHEAT_DONATE=1`` loudly at construction.

Threading note: the pipeline is single-threaded by design — the overlap
lives in the DEVICE queue (async dispatch), not in host threads, so it
is wedge-safe under the tunnel discipline (no client is ever killed
mid-compile; the only blocking calls are the fences it would need
anyway).  Corollary: window/deadline bounds are enforced at scheduler
EVENTS (``submit``/``pump``/``wait``/``drain``) — the T-ms bound holds
whenever events keep arriving (the streaming CLIs submit per stdin row
and drain at EOF); an intake that can stall for long stretches between
submissions should call ``pump()`` on its own cadence, because no
background thread fires the window for it.
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import dataclass, field

#: Bound on every observability window (per-chunk log, latency/queue-wait
#: samples, occupancy trace): a long-lived serving process must not grow
#: host memory with its request count, so percentiles, stage totals, and
#: the metrics dump cover the most recent LOG_CAP entries (the counters —
#: cases/dispatches/... — remain lifetime-exact).
LOG_CAP = 4096

import numpy as np

import jax.numpy as jnp

from nonlocalheatequation_tpu.serve.ensemble import (
    EnsembleCase,
    EnsembleEngine,
    EnsembleReport,
)
from nonlocalheatequation_tpu.utils import donation


def fence_scalar(x) -> float:
    """The device fence: a scalar device->host fetch.  On the axon tunnel
    ``block_until_ready()`` returns before execution finishes; fetching a
    reduced scalar is the only reliable completion barrier
    (docs/bench/README.md).  Module-level on purpose — the no-fence-
    between-dispatches tests spy on exactly this symbol.  Non-finite sums
    are legal here (a diverged solve is a legitimate served result; the
    caller's accuracy contract judges it)."""
    return float(jnp.sum(x))


@dataclass
class ServeRequest:
    """One submitted case: the caller's handle (a future).  ``result`` is
    populated when the request's chunk retires; ``wait()`` forces it."""

    case: EnsembleCase
    seq: int
    submit_t: float
    priority: int = 0
    deadline_t: float | None = None
    result: np.ndarray | None = None
    queue_wait_s: float | None = None  # submit -> dispatch
    latency_s: float | None = None  # submit -> result
    _chunk: "_Chunk | None" = None
    _pipe: "ServePipeline | None" = None

    def wait(self) -> np.ndarray:
        return self._pipe.wait(self)


class _OpenChunk:
    """A bucket's accumulating chunk (not yet closed)."""

    def __init__(self, key, opened_t):
        self.key = key
        self.opened_t = opened_t
        self.requests: list[ServeRequest] = []
        self.deadline_t: float | None = None
        self.priority = 0

    def due(self, now, window_s):
        if self.deadline_t is not None and now >= self.deadline_t:
            return "deadline"
        if now >= self.opened_t + window_s:
            return "window"
        return None


class _Chunk:
    """A closed chunk moving through ready -> inflight -> done."""

    def __init__(self, chunk_id, key, requests, priority, closed_by):
        self.chunk_id = chunk_id
        self.key = key
        self.requests = requests
        self.priority = priority
        self.closed_by = closed_by
        self.state = "ready"
        self.out = None  # device future once dispatched
        self.dispatch_t = None
        self.build_s = 0.0


@dataclass
class ServeReport(EnsembleReport):
    """EnsembleReport extended with the serving pipeline's observability:
    per-chunk and per-request timing, occupancy, forced-close reasons.
    The engine counters (cases/buckets/dispatches/programs_built/
    padded_cases) keep their offline meaning — the pipeline routes the
    engine's own stages, so the same counters measure the same events."""

    depth: int = 1
    window_ms: float = 0.0
    window_size: int = 0
    # bounded windows (LOG_CAP most recent entries; see the constant)
    chunk_log: deque = field(default_factory=lambda: deque(maxlen=LOG_CAP))
    request_latency_ms: deque = field(
        default_factory=lambda: deque(maxlen=LOG_CAP))
    queue_wait_ms: deque = field(
        default_factory=lambda: deque(maxlen=LOG_CAP))
    occupancy_samples: deque = field(  # (t, in_flight)
        default_factory=lambda: deque(maxlen=LOG_CAP))
    forced_closes: dict = field(default_factory=dict)
    max_inflight: int = 0

    @staticmethod
    def _pct(xs) -> dict:
        if not xs:
            return {}
        a = np.asarray(xs, np.float64)
        return {
            "p50": float(np.percentile(a, 50)),
            "p90": float(np.percentile(a, 90)),
            "p99": float(np.percentile(a, 99)),
            "mean": float(a.mean()),
            "max": float(a.max()),
        }

    def occupancy(self) -> dict:
        """Max and time-weighted mean chunks in flight over the sampled
        span (each sample is the in-flight count right after a dispatch
        or retire event)."""
        s = list(self.occupancy_samples)
        if not s:
            return {"max": 0, "time_weighted_mean": 0.0}
        span = s[-1][0] - s[0][0]
        if span <= 0:
            return {"max": self.max_inflight,
                    "time_weighted_mean": float(self.max_inflight)}
        area = sum(n * (s[i + 1][0] - s[i][0])
                   for i, (_t, n) in enumerate(s[:-1]))
        return {"max": self.max_inflight,
                "time_weighted_mean": float(area / span)}

    def metrics(self) -> dict:
        """The one-call dump: engine counters (lifetime-exact) + pipeline
        knobs + latency percentiles + stage totals + occupancy + the
        per-chunk log, the latter four over the most recent ``LOG_CAP``
        entries (``log_window`` in the dump)."""
        return {
            "log_window": LOG_CAP,
            "cases": self.cases,
            "buckets": self.buckets,
            # lifetime-exact (every chunk was closed exactly once; the
            # windowed chunk_log may hold fewer)
            "chunks": sum(self.forced_closes.values()),
            "dispatches": self.dispatches,
            "programs_built": self.programs_built,
            "padded_cases": self.padded_cases,
            "depth": self.depth,
            "window_ms": self.window_ms,
            "window_size": self.window_size,
            "forced_closes": dict(self.forced_closes),
            "request_latency_ms": self._pct(self.request_latency_ms),
            "queue_wait_ms": self._pct(self.queue_wait_ms),
            "build_ms_total": round(
                sum(c["build_ms"] for c in self.chunk_log), 3),
            "device_ms_total": round(
                sum(c["device_ms"] for c in self.chunk_log), 3),
            "fetch_ms_total": round(
                sum(c["fetch_ms"] for c in self.chunk_log), 3),
            "occupancy": self.occupancy(),
            "chunk_log": list(self.chunk_log),
        }

    def metrics_json(self) -> str:
        return json.dumps(self.metrics())


class ServePipeline:
    """Continuous-batching scheduler with up to ``depth`` chunks in
    flight over one :class:`EnsembleEngine`.

    Parameters: ``depth`` D (in-flight dispatch cap, >= 1; 1 is the
    fenced A/B schedule), ``window_ms`` T (microbatch wait bound),
    ``window_size`` B (size trigger; defaults to the engine's top batch
    size so chunk partitioning matches the offline ``run()`` exactly),
    ``clock`` (injectable for deterministic scheduler tests).  Remaining
    kwargs construct the engine (method/precision/variant/...).
    """

    def __init__(self, engine: EnsembleEngine | None = None, *,
                 depth: int = 2, window_ms: float = 5.0,
                 window_size: int | None = None, clock=time.monotonic,
                 **engine_kwargs):
        if engine is None:
            engine = EnsembleEngine(**engine_kwargs)
        elif engine_kwargs:
            raise ValueError(
                f"pass engine kwargs {sorted(engine_kwargs)} OR a built "
                "engine, not both")
        depth = int(depth)
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if window_ms < 0:
            raise ValueError(f"window_ms must be >= 0, got {window_ms}")
        ws = int(window_size if window_size is not None
                 else engine.batch_sizes[-1])
        if not 1 <= ws <= engine.batch_sizes[-1]:
            raise ValueError(
                f"window_size {ws} outside the engine batch sizes "
                f"{engine.batch_sizes} (max {engine.batch_sizes[-1]})")
        # refuses loudly on NLHEAT_DONATE=1 with depth > 1 — donation is
        # not pipeline-safe (module docstring); restored by close()
        self._prev_depth = donation.set_pipeline_depth(depth)
        self.engine = engine
        self.depth = depth
        self.window_s = window_ms / 1e3
        self.window_size = ws
        self._clock = clock
        self.report = engine.report = ServeReport(
            depth=depth, window_ms=window_ms, window_size=ws)
        self._open: dict = {}
        self._ready: list[_Chunk] = []
        self._inflight: deque[_Chunk] = deque()
        self._seen_keys: set = set()
        self._next_seq = 0
        self._next_chunk = 0
        self._closed = False

    # -- intake -------------------------------------------------------------
    def submit(self, case: EnsembleCase, *, deadline_ms: float | None = None,
               priority: int = 0) -> ServeRequest:
        """Queue one case; returns its handle.  ``deadline_ms`` (relative
        to now) pulls the case's chunk close forward; ``priority`` orders
        ready chunks competing for a dispatch slot."""
        if self._closed:
            raise RuntimeError("pipeline is closed")
        now = self._clock()
        req = ServeRequest(case=case, seq=self._next_seq, submit_t=now,
                           priority=int(priority), _pipe=self)
        self._next_seq += 1
        self.report.cases += 1
        key = case.bucket_key()
        if key not in self._seen_keys:
            self._seen_keys.add(key)
            self.report.buckets += 1
        oc = self._open.get(key)
        if oc is None:
            oc = self._open[key] = _OpenChunk(key, now)
        oc.requests.append(req)
        oc.priority = max(oc.priority, req.priority)
        if deadline_ms is not None:
            req.deadline_t = now + deadline_ms / 1e3
            oc.deadline_t = (req.deadline_t if oc.deadline_t is None
                             else min(oc.deadline_t, req.deadline_t))
        if len(oc.requests) >= self.window_size:
            self._close(key, "size")
        self.pump()
        return req

    # -- scheduling ---------------------------------------------------------
    def pump(self) -> None:
        """Advance the pipeline: close chunks whose window or deadline is
        due, then dispatch while capacity lasts.  When the pipe is full
        AND more work waits, the oldest in-flight chunk's result is due —
        that retire is the ONLY fence this schedule ever takes outside
        wait()/drain()."""
        now = self._clock()
        for key in list(self._open):
            why = self._open[key].due(now, self.window_s)
            if why:
                self._close(key, why)
        while self._ready:
            if len(self._inflight) < self.depth:
                self._dispatch(self._pop_ready())
            else:
                self._retire(self._inflight[0])

    def _close(self, key, why: str) -> _Chunk:
        oc = self._open.pop(key)
        chunk = _Chunk(self._next_chunk, key, oc.requests, oc.priority, why)
        self._next_chunk += 1
        for r in oc.requests:
            r._chunk = chunk
        self._ready.append(chunk)
        fc = self.report.forced_closes
        fc[why] = fc.get(why, 0) + 1
        return chunk

    def _pop_ready(self) -> _Chunk:
        # highest priority first; FIFO (chunk_id) within a priority —
        # starvation-free because every chunk's CLOSE is window-bounded
        # and the dispatch loop drains _ready completely
        best = min(self._ready, key=lambda c: (-c.priority, c.chunk_id))
        self._ready.remove(best)
        return best

    def _dispatch(self, chunk: _Chunk) -> None:
        t0 = self._clock()
        padded = self.engine.pad_chunk([r.case for r in chunk.requests])
        multi = self.engine.build_program(chunk.key, padded)
        U0 = self.engine.stage_inputs(padded)
        chunk.build_s = self._clock() - t0
        chunk.dispatch_t = self._clock()
        chunk.out = self.engine.dispatch_chunk(multi, U0)  # async, no fence
        chunk.state = "inflight"
        self._inflight.append(chunk)
        for r in chunk.requests:
            r.queue_wait_s = chunk.dispatch_t - r.submit_t
            self.report.queue_wait_ms.append(r.queue_wait_s * 1e3)
        n = len(self._inflight)
        self.report.max_inflight = max(self.report.max_inflight, n)
        self.report.occupancy_samples.append((chunk.dispatch_t, n))

    def _retire(self, chunk: _Chunk) -> None:
        """Fence + fetch one in-flight chunk and distribute its lanes."""
        self._inflight.remove(chunk)
        t0 = self._clock()
        fence_scalar(chunk.out)  # device completion barrier
        t1 = self._clock()
        vals = np.asarray(chunk.out)  # host fetch; padding lanes dropped
        t2 = self._clock()
        for j, r in enumerate(chunk.requests):
            r.result = np.asarray(vals[j])
            r.latency_s = t2 - r.submit_t
            self.report.request_latency_ms.append(r.latency_s * 1e3)
        chunk.state = "done"
        chunk.out = None
        self.report.chunk_log.append({
            "chunk": chunk.chunk_id,
            "cases": len(chunk.requests),
            "closed_by": chunk.closed_by,
            "build_ms": round(chunk.build_s * 1e3, 3),
            "device_ms": round((t1 - chunk.dispatch_t) * 1e3, 3),
            "fetch_ms": round((t2 - t1) * 1e3, 3),
        })
        self.report.occupancy_samples.append((t2, len(self._inflight)))

    # -- completion ---------------------------------------------------------
    def wait(self, req: ServeRequest) -> np.ndarray:
        """Force one request to completion (an implicit immediate
        deadline): close its open chunk if still accumulating, dispatch
        through the normal capacity discipline, fence its chunk."""
        while req.result is None:
            if req._chunk is None:
                self._close(req.case.bucket_key(), "wait")
            elif req._chunk.state == "ready":
                if len(self._inflight) >= self.depth:
                    self._retire(self._inflight[0])
                else:
                    self._dispatch(self._pop_ready())
            else:  # inflight
                self._retire(req._chunk)
        return req.result

    def drain(self) -> None:
        """Flush everything: close all partial chunks, dispatch them
        (retiring as capacity demands), then retire all in-flight work."""
        for key in list(self._open):
            self._close(key, "drain")
        while self._ready:
            if len(self._inflight) >= self.depth:
                self._retire(self._inflight[0])
            else:
                self._dispatch(self._pop_ready())
        while self._inflight:
            self._retire(self._inflight[0])

    def serve_cases(self, cases) -> list:
        """Convenience: submit every case, drain, return results in
        submission order — the schedule-changed twin of
        ``EnsembleEngine.run()`` (bit-identical output)."""
        handles = [self.submit(c) for c in cases]
        self.drain()
        return [h.result for h in handles]

    def close(self) -> None:
        """Drain and release the pipeline.  The process-wide donation
        depth declared at construction is restored even if the final
        drain raises (a failed serve run must not leave donation pinned
        for the rest of the process)."""
        if not self._closed:
            try:
                self.drain()
            finally:
                donation.set_pipeline_depth(self._prev_depth)
                self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- observability ------------------------------------------------------
    def metrics(self) -> dict:
        return self.report.metrics()

    def metrics_json(self) -> str:
        return self.report.metrics_json()


def serve_fence_ab(engine: EnsembleEngine, cases, depth: int,
                   iters: int = 2):
    """The pipelined-vs-fenced measurement shared by bench.py
    (``BENCH_SERVE``) and tools/bench_table.py (``serve`` group): time the
    fenced (depth 1 — a dispatch+fence roundtrip per chunk, run_batch's
    schedule) and pipelined (``depth`` in flight, fence only on retire)
    schedules of the SAME case set over ONE engine, so the shared program
    cache makes this an A/B of schedules, not compiles.  The first
    pipelined pass warms the cache and its wall is returned as the
    compile time.  Callers pin donation off themselves (the halves must
    differ only in schedule).  Returns ``(compile_s, fenced_best_s,
    pipelined_best_s, best_pipelined_report)``."""

    def run_schedule(d):
        pipe = ServePipeline(engine=engine, depth=d, window_ms=0.0)
        try:
            t0 = time.perf_counter()
            pipe.serve_cases(cases)
            return time.perf_counter() - t0, pipe.report
        finally:
            pipe.close()

    compile_s, _ = run_schedule(depth)
    fenced_best = float("inf")
    pipe_best, pipe_rep = float("inf"), None
    for _ in range(iters):
        sec_f, _ = run_schedule(1)
        fenced_best = min(fenced_best, sec_f)
        sec_p, rep = run_schedule(depth)
        if sec_p < pipe_best:
            pipe_best, pipe_rep = sec_p, rep
    return compile_s, fenced_best, pipe_best, pipe_rep
