"""Async serving runtime: continuous batching + overlapped chunk dispatch.

The reference earns its scaling from HPX's asynchronous many-task model —
futures and dataflow overlapping communication, computation, and task
launch (README.md:12-14; the interior/boundary overlap at
src/2d_nonlocal_distributed.cpp:1156-1261).  The offline
:class:`~nonlocalheatequation_tpu.serve.ensemble.EnsembleEngine` is the
opposite schedule: ``run()`` builds, dispatches, and fences one chunk at
a time, so every chunk pays the full ~64 ms tunnel dispatch+fence round
trip (docs/bench/README.md) and the host idles while the device computes.
This module applies the reference's execution model to the request path:

* **Request lifecycle** — cases are :meth:`ServePipeline.submit`-ted
  incrementally (streaming stdin, a socket loop, a test harness), NOT as
  one pre-read batch.  Each request joins its bucket's OPEN chunk (the
  ensemble engine's ``(shape, nt, eps, test) x engine`` keys); the chunk
  closes at size B (``window_size``, default the engine's top batch
  size) or after T ms (``window_ms``) — whichever first — so late
  arrivals join in-flight-adjacent chunks instead of waiting for EOF.
* **Overlapped dispatch** — up to D (``depth``) chunks stay in flight.
  Dispatch is JAX-async: launching chunk N+1 (and building chunk N+2's
  program — a host-side trace) proceeds while chunk N computes.  The
  host fences ONLY when a result is actually due (the pipe is full and
  more work waits, a caller waits on a request, or ``drain()``), via the
  scalar :func:`fence_scalar` fetch — ``block_until_ready`` lies over
  the axon tunnel (docs/bench/README.md) — and NEVER between dispatches.
* **Deadline-aware scheduling** — ``submit(deadline_ms=...)`` bounds a
  case's microbatch wait: the earliest deadline in an open chunk pulls
  the close forward (an aging case forces a partial chunk out,
  starvation-free — the window T is an upper bound for every case);
  ``priority`` orders READY chunks at equal dispatch capacity.
  ``drain()`` flushes all partial chunks and in-flight work.
* **Fault tolerance** (serve/resilience.py) — every chunk execution is
  SUPERVISED: the dispatch stage is guarded, the fence/fetch runs under
  a per-chunk deadline (``fetch_deadline_ms``: a watchdog thread joins
  the fetch and classifies a miss as a hang, ABANDONING the blocked
  thread — the wedge discipline forbids killing the client), and the
  fetched buffer is finite-scanned (``nan_policy``).  A failed attempt
  (classified ``error``/``hang``/``corrupt``) retries with exponential
  backoff up to ``retries`` times; a chunk that exhausts its budget is
  BISECTED — split in half, both halves re-dispatched with fresh
  budgets — until the failing case is isolated, which then completes
  exceptionally (:meth:`ServeRequest.wait` raises a typed
  :class:`~nonlocalheatequation_tpu.serve.resilience.ServeError`) while
  its chunk-mates are re-bucketed and served normally.  K consecutive
  device-path failures open a circuit breaker that routes chunks
  through an equivalent CPU-backend program (the serving analogue of
  bench.py's ladder; oracle-close, bit-identical when the method is an
  XLA method) until a half-open probe re-closes it.  All of it is
  provable with no real TPU via the deterministic injector in
  utils/faults.py (env ``NLHEAT_FAULT_PLAN`` or the ``faults=`` hook).
* **Observability** — :class:`ServeReport` extends the engine's report
  with per-request and per-chunk timing (queue wait, program build,
  dispatch->fence wall, fetch), an occupancy trace (chunks in flight
  over time), forced-close counts, the failure telemetry (retries,
  backoff, fault classifications, quarantined case ids, breaker
  transitions with timestamps, fallback-served chunk count), and a
  one-call JSON dump (:meth:`ServePipeline.metrics_json`) — the overlap
  is measured, not assumed, and so is the degradation.

Served results are **bit-identical** to ``EnsembleEngine.run()`` on the
same case set: the pipeline reuses the engine's chunk stages
(``build_program`` / ``stage_inputs`` / ``dispatch_chunk``) and padding
rule verbatim — only the schedule changes (tests/test_serve.py pins
this, plus the no-fence-between-dispatches discipline via spy counters;
supervision adds NO schedule change on the happy path — the inline
fence path is PR 3's, byte for byte).

Buffer donation (utils/donation.py) is pipeline-UNSAFE past depth 1: the
pipeline declares its depth via ``donation.set_pipeline_depth``, which
pins the lazy donate decision off and refuses an explicit
``NLHEAT_DONATE=1`` loudly at construction.  On the depth-1 donating
schedule, retries are safe because every attempt RE-STAGES its input
(``stage_inputs`` allocates a fresh device buffer per dispatch — a
donated-away frame is never re-read).

Threading note: the pipeline is single-threaded by design — the overlap
lives in the DEVICE queue (async dispatch), not in host threads, so it
is wedge-safe under the tunnel discipline (no client is ever killed
mid-compile; the only blocking calls are the fences it would need
anyway).  The one exception is the supervised fetch watchdog: a daemon
thread that runs the fence the scheduler would otherwise run inline,
joined with the per-chunk deadline — on a miss the thread is abandoned,
never killed.  Corollary: window/deadline bounds are enforced at
scheduler EVENTS (``submit``/``pump``/``wait``/``drain``) — the T-ms
bound holds whenever events keep arriving (the streaming CLIs submit
per stdin row and drain at EOF); an intake that can stall for long
stretches between submissions should call ``pump()`` on its own
cadence, because no background thread fires the window for it.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass

#: Bound on every observability window (per-chunk log, latency/queue-wait
#: samples, occupancy trace, quarantine trail): a long-lived serving
#: process must not grow host memory with its request count, so
#: percentiles, stage totals, and the metrics dump cover the most recent
#: LOG_CAP entries — each window's companion ``count`` (obs/metrics.py
#: Trail/Histogram) and the counters (cases/dispatches/...) remain
#: lifetime-exact.
LOG_CAP = 4096

import numpy as np

import jax.numpy as jnp

from nonlocalheatequation_tpu.obs import flightrec
from nonlocalheatequation_tpu.obs import slo as obs_slo
from nonlocalheatequation_tpu.obs import trace as obs_trace
from nonlocalheatequation_tpu.obs.export import EventLog
from nonlocalheatequation_tpu.obs.metrics import MetricsRegistry, backed
from nonlocalheatequation_tpu.serve.ensemble import (
    EnsembleCase,
    EnsembleEngine,
    EnsembleReport,
)
from nonlocalheatequation_tpu.serve.resilience import (
    CLASS_CORRUPT,
    CLASS_ERROR,
    CLASS_HANG,
    CircuitBreaker,
    CpuFallback,
    ServeError,
)
from nonlocalheatequation_tpu.utils import donation
from nonlocalheatequation_tpu.utils.faults import (
    NO_FAULTS,
    FaultPlan,
    InjectedFault,
)


def fence_scalar(x) -> float:
    """The device fence: a scalar device->host fetch.  On the axon tunnel
    ``block_until_ready()`` returns before execution finishes; fetching a
    reduced scalar is the only reliable completion barrier
    (docs/bench/README.md).  Module-level on purpose — the no-fence-
    between-dispatches tests spy on exactly this symbol.  Non-finite sums
    are legal HERE (the fence only orders; it never judges) — what the
    supervised retire does with a non-finite FETCHED buffer is
    ``nan_policy``'s call (quarantine by default, ``"serve"`` restores
    the a-diverged-solve-is-a-legitimate-result behavior)."""
    return float(jnp.sum(x))


@dataclass
class ServeRequest:
    """One submitted case: the caller's handle (a future).  ``result`` is
    populated when the request's chunk retires; ``wait()`` forces it and
    raises the typed ``ServeError`` if the case was quarantined
    (``error`` holds it either way)."""

    case: EnsembleCase
    seq: int
    submit_t: float
    priority: int = 0
    deadline_t: float | None = None
    #: fleet trace identity (obs/trace.py TraceContext) when the case
    #: arrived through a traced front door; None otherwise (zero cost)
    trace: object = None
    #: engine-pool key when the case carries a PICKED engine
    #: (serve/picker.py); None = the pipeline's default engine
    engine_sel: tuple | None = None
    result: np.ndarray | None = None
    error: ServeError | None = None
    queue_wait_s: float | None = None  # submit -> dispatch
    latency_s: float | None = None  # submit -> result
    _chunk: "_Chunk | None" = None
    _pipe: "ServePipeline | None" = None

    def wait(self) -> np.ndarray:
        return self._pipe.wait(self)


class _OpenChunk:
    """A bucket's accumulating chunk (not yet closed).  ``key`` is the
    OPEN-chunk key ``(bucket_key, engine_sel)`` — picked-engine cases
    (serve/picker.py) never share a chunk with default-engine cases of
    the same bucket, because the two compile different programs."""

    def __init__(self, key, opened_t):
        self.key = key
        self.opened_t = opened_t
        self.requests: list[ServeRequest] = []
        self.deadline_t: float | None = None
        self.priority = 0

    def due(self, now, window_s):
        if self.deadline_t is not None and now >= self.deadline_t:
            return "deadline"
        if now >= self.opened_t + window_s:
            return "window"
        return None


class _Chunk:
    """A closed chunk moving through ready -> inflight -> done, possibly
    looping back to ready on a supervised retry or being superseded by
    its two bisection halves."""

    def __init__(self, chunk_id, key, requests, priority, closed_by,
                 engine_sel=None):
        self.chunk_id = chunk_id
        self.key = key  # the BUCKET key (engine.build_program's shape)
        self.engine_sel = engine_sel  # picked-engine pool key, or None
        self.requests = requests
        self.priority = priority
        self.closed_by = closed_by
        self.state = "ready"
        self.out = None  # device future once dispatched
        self.dispatch_t = None
        self.build_s = 0.0
        self.attempts = 0  # execution attempts so far (supervision)
        self.route = "device"  # this attempt's routing (device/fallback)
        self.probe = False  # this attempt IS the breaker's half-open probe
        self.fired = NO_FAULTS  # this attempt's armed injected faults
        self.padded = None  # pad_chunk result, computed once per chunk
        self.last_failure = ("", "")  # (classification, detail)


class ServeReport(EnsembleReport):
    """EnsembleReport extended with the serving pipeline's observability:
    per-chunk and per-request timing, occupancy, forced-close reasons,
    and the failure telemetry.  The engine counters (cases/buckets/
    dispatches/programs_built/padded_cases) keep their offline meaning —
    the pipeline routes the engine's own stages, so the same counters
    measure the same events (fallback-served chunks run on a sibling CPU
    engine and are counted by ``fallback_chunks`` instead).

    Like the engine counters, every field below is BACKED by the
    report's metrics registry (obs/metrics.py) under the ``/serve``
    namespace — the registry's Prometheus text and JSON snapshot agree
    with :meth:`metrics` on every shared counter by construction.  The
    windows (chunk log, latency/queue-wait samples, occupancy trace,
    quarantine trail) are bounded at LOG_CAP with lifetime-exact
    companion counts (the windowed-trail pattern the breaker transition
    log introduced)."""

    depth = backed("_m_depth")
    window_ms = backed("_m_window_ms")
    window_size = backed("_m_window_size")
    max_inflight = backed("_m_max_inflight")
    retries = backed("_m_retries")
    backoff_ms_total = backed("_m_backoff_ms_total")
    bisections = backed("_m_bisections")
    fallback_chunks = backed("_m_fallback_chunks")

    def __init__(self, depth: int = 1, window_ms: float = 0.0,
                 window_size: int = 0, breaker: object = None,
                 registry: MetricsRegistry | None = None):
        super().__init__(registry=registry)
        r = self.registry
        self._m_depth = r.gauge("/serve/depth")
        self._m_window_ms = r.gauge("/serve/window-ms")
        self._m_window_size = r.gauge("/serve/window-size")
        self._m_max_inflight = r.gauge("/serve/max-inflight")
        self._m_retries = r.counter("/serve/retries")
        self._m_backoff_ms_total = r.counter("/serve/backoff-ms-total")
        self._m_bisections = r.counter("/serve/bisections")
        self._m_fallback_chunks = r.counter("/serve/fallback-chunks")
        # bounded windows (LOG_CAP most recent entries; see the constant)
        self.chunk_log = r.trail("/serve/chunk-log", window=LOG_CAP)
        self.request_latency_ms = r.histogram("/serve/request-latency-ms",
                                              window=LOG_CAP)
        self.queue_wait_ms = r.histogram("/serve/queue-wait-ms",
                                         window=LOG_CAP)
        self.occupancy_samples = r.trail("/serve/occupancy",  # (t, n)
                                         window=LOG_CAP)
        self.quarantined = r.trail("/serve/quarantined", window=LOG_CAP)
        self.forced_closes = r.labeled("/serve/closes")
        self.faults = r.labeled("/serve/faults")  # classification -> count
        self.depth = depth
        self.window_ms = window_ms
        self.window_size = window_size
        self.breaker = breaker  # the pipeline's CircuitBreaker, if any

    def store(self) -> dict:
        """The AOT-program-store block of :meth:`metrics`
        (serve/program_store.py): hit/miss/save counters, refusals by
        reason, load/serialize-time percentiles, plus the engine's LRU
        program-cache occupancy (resident gauge, lifetime evictions).
        All zeros when no store is configured — the keys are stable so
        dashboards need no existence checks."""
        r = self.registry

        def val(name):
            m = r.get(name)
            return m.value if m is not None else 0

        def pct(name):
            m = r.get(name)
            return m.percentiles() if m is not None else {}

        refusals = r.get("/store/refusals")
        return {
            "hits": val("/store/hits"),
            "misses": val("/store/misses"),
            "saves": val("/store/saves"),
            "refusals": dict(refusals) if refusals is not None else {},
            "load_ms": pct("/store/load-ms"),
            "serialize_ms": pct("/store/serialize-ms"),
            "resident_programs": val("/store/resident-programs"),
            "evictions": val("/store/evictions"),
        }

    def occupancy(self) -> dict:
        """Max and time-weighted mean chunks in flight over the sampled
        span (each sample is the in-flight count right after a dispatch
        or retire event)."""
        s = list(self.occupancy_samples)
        if not s:
            return {"max": 0, "time_weighted_mean": 0.0}
        span = s[-1][0] - s[0][0]
        if span <= 0:
            return {"max": self.max_inflight,
                    "time_weighted_mean": float(self.max_inflight)}
        area = sum(n * (s[i + 1][0] - s[i][0])
                   for i, (_t, n) in enumerate(s[:-1]))
        return {"max": self.max_inflight,
                "time_weighted_mean": float(area / span)}

    def resilience(self) -> dict:
        """The failure-telemetry block of :meth:`metrics`: retry/backoff
        totals, fault classifications, quarantined case ids, fallback
        chunk count, and the breaker's timestamped transition trail."""
        out = {
            "retries": self.retries,
            "faults": dict(self.faults),
            "backoff_ms_total": round(self.backoff_ms_total, 3),
            "bisections": self.bisections,
            "fallback_chunks": self.fallback_chunks,
            # windowed trail (LOG_CAP most recent) + lifetime-exact count
            "quarantined": [dict(q) for q in self.quarantined],
            "quarantined_total": self.quarantined.count,
        }
        if self.breaker is not None:
            out["breaker"] = {
                "state": self.breaker.state,
                "threshold": self.breaker.threshold,
                # most recent TRANSITION_CAP entries; the count is
                # lifetime-exact (a flapping breaker grows forever)
                "transition_count": self.breaker.transition_count,
                "transitions": [dict(t) for t in self.breaker.transitions],
            }
        else:
            out["breaker"] = {"state": "disabled", "transition_count": 0,
                              "transitions": []}
        return out

    def metrics(self) -> dict:
        """The one-call dump: engine counters (lifetime-exact) + pipeline
        knobs + latency percentiles + stage totals + occupancy + the
        failure telemetry + the per-chunk log, the latter four over the
        most recent ``LOG_CAP`` entries (``log_window`` in the dump,
        each window's lifetime-exact companion count alongside)."""
        return {
            "log_window": LOG_CAP,
            # lifetime-exact window companions: how many entries each
            # bounded window has EVER absorbed (== len until it wraps)
            "requests_completed": self.request_latency_ms.count,
            "chunks_completed": self.chunk_log.count,
            "occupancy_samples_total": self.occupancy_samples.count,
            "cases": self.cases,
            "buckets": self.buckets,
            # lifetime-exact (every chunk was closed exactly once —
            # bisection halves count as their own "bisect" closes; the
            # windowed chunk_log may hold fewer)
            "chunks": sum(self.forced_closes.values()),
            "dispatches": self.dispatches,
            "programs_built": self.programs_built,
            "programs_loaded": self.programs_loaded,
            "padded_cases": self.padded_cases,
            "depth": self.depth,
            "window_ms": self.window_ms,
            "window_size": self.window_size,
            "forced_closes": dict(self.forced_closes),
            "request_latency_ms": self.request_latency_ms.percentiles(),
            "queue_wait_ms": self.queue_wait_ms.percentiles(),
            "build_ms_total": round(
                sum(c["build_ms"] for c in self.chunk_log), 3),
            "device_ms_total": round(
                sum(c["device_ms"] for c in self.chunk_log), 3),
            "fetch_ms_total": round(
                sum(c["fetch_ms"] for c in self.chunk_log), 3),
            "occupancy": self.occupancy(),
            "resilience": self.resilience(),
            "store": self.store(),
            "chunk_log": list(self.chunk_log),
        }

    def metrics_json(self) -> str:
        return json.dumps(self.metrics())


class ServePipeline:
    """Continuous-batching scheduler with up to ``depth`` chunks in
    flight over one :class:`EnsembleEngine`, supervised end to end.

    Scheduling parameters: ``depth`` D (in-flight dispatch cap, >= 1; 1
    is the fenced A/B schedule), ``window_ms`` T (microbatch wait
    bound), ``window_size`` B (size trigger; defaults to the engine's
    top batch size so chunk partitioning matches the offline ``run()``
    exactly), ``clock`` (injectable for deterministic scheduler tests).

    Supervision parameters: ``retries`` (re-dispatches per chunk after
    its first attempt; bisection halves get fresh budgets),
    ``backoff_ms`` (base of the exponential per-chunk retry backoff,
    applied via the injectable ``sleep``), ``fetch_deadline_ms`` (per-
    chunk fence/fetch deadline; 0/None = no watchdog, the inline PR 3
    fence), ``fallback`` (route chunks through the CPU-backend sibling
    engine while the breaker is open), ``breaker`` (a prebuilt
    :class:`~nonlocalheatequation_tpu.serve.resilience.CircuitBreaker`;
    default one is built from ``breaker_threshold`` /
    ``breaker_cooldown_ms`` on the pipeline clock when ``fallback`` is
    on), ``nan_policy`` ("quarantine": a non-finite fetched buffer is a
    classified fault; "serve": PR 3's a-diverged-solve-is-a-result
    behavior), ``faults`` (a deterministic
    :class:`~nonlocalheatequation_tpu.utils.faults.FaultPlan`; defaults
    to env ``NLHEAT_FAULT_PLAN`` when set).  Remaining kwargs construct
    the engine (method/precision/variant/...).
    """

    def __init__(self, engine: EnsembleEngine | None = None, *,
                 depth: int = 2, window_ms: float = 5.0,
                 window_size: int | None = None, clock=time.monotonic,
                 retries: int = 2, backoff_ms: float = 10.0,
                 fetch_deadline_ms: float | None = None,
                 fallback: bool = True, breaker: CircuitBreaker | None = None,
                 breaker_threshold: int = 3,
                 breaker_cooldown_ms: float = 5000.0,
                 nan_policy: str = "quarantine",
                 faults: FaultPlan | None = None, sleep=time.sleep,
                 registry: MetricsRegistry | None = None, tracer=None,
                 slo=None, **engine_kwargs):
        if engine is None:
            engine = EnsembleEngine(**engine_kwargs)
        elif engine_kwargs:
            raise ValueError(
                f"pass engine kwargs {sorted(engine_kwargs)} OR a built "
                "engine, not both")
        depth = int(depth)
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if window_ms < 0:
            raise ValueError(f"window_ms must be >= 0, got {window_ms}")
        ws = int(window_size if window_size is not None
                 else engine.batch_sizes[-1])
        if not 1 <= ws <= engine.batch_sizes[-1]:
            raise ValueError(
                f"window_size {ws} outside the engine batch sizes "
                f"{engine.batch_sizes} (max {engine.batch_sizes[-1]})")
        retries = int(retries)
        if retries < 0:
            raise ValueError(f"retries must be >= 0, got {retries}")
        if backoff_ms < 0:
            raise ValueError(f"backoff_ms must be >= 0, got {backoff_ms}")
        if fetch_deadline_ms is not None and fetch_deadline_ms < 0:
            raise ValueError(
                f"fetch_deadline_ms must be >= 0, got {fetch_deadline_ms}")
        if nan_policy not in ("quarantine", "serve"):
            raise ValueError(
                f"nan_policy must be 'quarantine' or 'serve', got "
                f"{nan_policy!r}")
        # everything that can refuse parses BEFORE the donation-depth pin
        # below: a ctor that raises past the pin would leak it process-
        # wide, because close() never runs on a failed __init__
        if faults is None:
            faults = FaultPlan.from_env()
        if breaker is None and fallback:
            breaker = CircuitBreaker(threshold=breaker_threshold,
                                     cooldown_ms=breaker_cooldown_ms,
                                     clock=clock)
        # observability (obs/): the report and its registry, the span
        # tracer (an explicit one, else the process-global one — None
        # when tracing is off, the zero-cost path), and the opt-in JSONL
        # event log.  Built HERE, still before the donation pin below —
        # a ctor that raises past the pin would leak it process-wide.
        report = ServeReport(depth=depth, window_ms=window_ms,
                             window_size=ws, breaker=breaker,
                             registry=registry)
        self._tracer = (None if tracer is obs_trace.TRACE_OFF
                        else tracer if tracer is not None
                        else obs_trace.get_tracer())
        self._events = EventLog.from_env()
        # the crash flight recorder (obs/flightrec.py): the process-
        # global black box, bound to THIS pipeline's registry and
        # in-flight ledger (one pipeline per worker process; later
        # pipelines re-bind).  None when off — every tap is one
        # attribute read, the obs/ discipline.
        self._flightrec = flightrec.get_recorder()
        if self._flightrec is not None:
            self._flightrec.bind(registry=report.registry,
                                 inflight=self._inflight_ledger)
            if self._events is not None:
                self._flightrec.add_flush(self._events.flush)
        # the SLO promise-audit ledger (obs/slo.py, ISSUE 20): joins
        # every submit's promise to its retire/quarantine outcome under
        # /slo/* on THIS report's registry, so it rides the fleet stats
        # frames for free.  None when off — every tap below is one
        # attribute read, the obs/ discipline.
        self._slo = obs_slo.SloLedger.from_arg(slo,
                                               registry=report.registry,
                                               clock=clock)
        self.registry = report.registry
        if breaker is not None:
            # mirror the breaker's lifetime-exact transition count into
            # the registry (a prebuilt breaker may arrive with history)
            self.registry.counter("/breaker/transitions").set(
                breaker.transition_count)
            breaker.on_transition = self._breaker_moved
        # refuses loudly on NLHEAT_DONATE=1 with depth > 1 — donation is
        # not pipeline-safe (module docstring); restored by close()
        self._prev_depth = donation.set_pipeline_depth(depth)
        self.engine = engine
        self.depth = depth
        self.window_s = window_ms / 1e3
        self.window_size = ws
        self._clock = clock
        self._sleep = sleep
        self.retries = retries
        self.backoff_ms = float(backoff_ms)
        self.fetch_deadline_s = (fetch_deadline_ms / 1e3
                                 if fetch_deadline_ms else None)
        self.nan_policy = nan_policy
        self._faults = faults
        self._fallback_on = bool(fallback)
        self._fallback: CpuFallback | None = None
        self._fallback_dead = False
        #: picked-engine pool (serve/picker.py): engine_sel key ->
        #: sibling engine sharing this pipeline's report/registry, plus
        #: each sibling's own CPU fallback (a fallback chunk must run
        #: the CHUNK's integrator, not the default engine's)
        self._engines: dict = {}
        self._fallbacks: dict = {}
        self._breaker = breaker
        # adopt_report, not plain assignment: an engine that already ran
        # (pre-warmed caches) may have bound its program store's metrics
        # to the report being replaced — the store must re-bind to THIS
        # report's registry or pipe.metrics()["store"] goes blind
        engine.adopt_report(report)
        self.report = report
        self._open: dict = {}
        self._ready: list[_Chunk] = []
        self._inflight: deque[_Chunk] = deque()
        self._seen_keys: set = set()
        self._next_seq = 0
        self._next_chunk = 0
        self._closed = False
        # retrace watchdog (ISSUE 11 satellite): armed by
        # arm_steady_state() after warm-up; any programs_built growth
        # past the armed baseline is counted + warned loudly (a silent
        # recompile storm is the exact failure the AOT store prevents)
        self._steady_seen: int | None = None

    # -- observability emitters (obs/) --------------------------------------
    # All three are single-`if` no-ops when tracing/logging is off, emit
    # from timestamps the scheduler already took (no extra fences, no
    # extra clock reads on timed paths), and never raise (the tracer and
    # event log swallow their own failures).
    def _t_span(self, name: str, t0, t1, **args) -> None:
        tr = self._tracer
        if tr is not None:
            tr.complete(name, t0, t1, cat="serve", **args)

    def _t_instant(self, name: str, ts=None, **args) -> None:
        tr = self._tracer
        if tr is not None:
            tr.instant(name, ts=ts if ts is not None else self._clock(),
                       cat="serve", **args)

    def _t_inflight(self, ts, n: int) -> None:
        tr = self._tracer
        if tr is not None:
            tr.counter("serve.inflight", ts=ts, inflight=n)

    def _event(self, kind: str, **fields) -> None:
        """One discrete event, mirrored to BOTH sinks: the JSONL event
        log and the flight recorder's ring (obs/flightrec.py).  One
        attribute read per sink when off; never raises."""
        if self._events is not None:
            self._events.emit(event=kind, **fields)
        fr = self._flightrec
        if fr is not None:
            fr.record(kind, **fields)

    def _inflight_ledger(self) -> list:
        """The flight recorder's in-flight snapshot: every chunk not yet
        done, with its member case seqs (the postmortem's 'what was
        this process holding' answer).  Bounded by depth + ready."""
        out = []
        try:
            for oc in self._open.values():
                out.append({"state": "open",
                            "cases": [r.seq for r in oc.requests]})
            for ch in list(self._ready):
                out.append({"state": "ready", "chunk": ch.chunk_id,
                            "cases": [r.seq for r in ch.requests]})
            for ch in list(self._inflight):
                out.append({"state": "inflight", "chunk": ch.chunk_id,
                            "cases": [r.seq for r in ch.requests]})
        except Exception:  # noqa: BLE001 — a racing mutation costs the
            pass  # remainder of the ledger, never the dump
        return out

    def _breaker_moved(self, frm: str, to: str, t: float) -> None:
        """CircuitBreaker transition hook: mirror into the registry, the
        trace, and the event log (the trail itself lives on the breaker,
        surfaced by :meth:`ServeReport.resilience`).  A closed -> open
        move additionally dumps the flight recorder: the breaker opening
        IS the device path dying, and the black box should say why."""
        try:
            self.registry.counter("/breaker/transitions").inc()
            self._t_instant("breaker.transition", ts=t,
                            **{"from": frm, "to": to})
            # breaker_t, not t: the breaker's clock is the pipeline's
            # (monotonic/injected) — the bare "t" stamp on every
            # EventLog/flight-recorder line is the WALL clock the
            # cross-process merge keys on, and an explicit field of the
            # same name would override it with the wrong epoch
            self._event("breaker", breaker_t=t, frm=frm, to=to)
            fr = self._flightrec
            if fr is not None and to == "open":
                fr.dump("breaker-open", frm=frm, breaker_t=t)
        except Exception:  # noqa: BLE001 — observability never raises
            pass

    # -- intake -------------------------------------------------------------
    def submit(self, case: EnsembleCase, *, deadline_ms: float | None = None,
               priority: int = 0, trace=None,
               engine=None, sticky_key=None) -> ServeRequest:
        """Queue one case; returns its handle.  ``deadline_ms`` (relative
        to now) pulls the case's chunk close forward; ``priority`` orders
        ready chunks competing for a dispatch slot.  ``trace`` is the
        originating request's TraceContext (obs/trace.py) when the case
        arrived through a traced front door — the fleet worker re-installs
        it around this case's chunk stages so every span nests under the
        ingress request; None (the default) costs nothing.  ``engine``
        is a picked engine (serve/picker.py ``EngineChoice``, or its
        ``.key()`` tuple): the case is served by the matching sibling
        from the pipeline's engine pool — same supervision, same
        schedule, its own compiled programs; None (the default) is the
        pipeline's engine, today's behavior bit for bit.  ``sticky_key``
        is the ROUTING identity override the fleet router honors
        (serve/router.py; the session tier's long-lived placement key)
        — accepted here so both backends expose one submit surface, and
        deliberately inert: an in-process pipeline owns every bucket,
        so placement identity has nothing to change."""
        del sticky_key  # interface uniformity with ReplicaRouter.submit
        if self._closed:
            raise RuntimeError("pipeline is closed")
        now = self._clock()
        sel = None
        if engine is not None:
            sel = engine.key() if hasattr(engine, "key") else tuple(engine)
            if sel == self.engine.engine_key():
                sel = None  # the pick IS the default engine
        req = ServeRequest(case=case, seq=self._next_seq, submit_t=now,
                           priority=int(priority), trace=trace,
                           engine_sel=sel, _pipe=self)
        self._next_seq += 1
        self.report.cases += 1
        okey = (case.bucket_key(), sel)
        if okey not in self._seen_keys:
            self._seen_keys.add(okey)
            self.report.buckets += 1
        oc = self._open.get(okey)
        if oc is None:
            oc = self._open[okey] = _OpenChunk(okey, now)
        oc.requests.append(req)
        oc.priority = max(oc.priority, req.priority)
        if deadline_ms is not None:
            req.deadline_t = now + deadline_ms / 1e3
            oc.deadline_t = (req.deadline_t if oc.deadline_t is None
                             else min(oc.deadline_t, req.deadline_t))
        if self._slo is not None:
            # the promise half of the audit: the submit timestamp the
            # scheduler already took, the pick's modeled cost when the
            # front door picked (EngineChoice.est_ms), the axis either
            # way — zero extra clock reads, zero fences
            self._slo.promise(req.seq, engine=engine, engine_sel=sel,
                              deadline_ms=deadline_ms, mesh=case.mesh,
                              t=now)
        if len(oc.requests) >= self.window_size:
            self._close(okey, "size")
        self.pump()
        return req

    # -- scheduling ---------------------------------------------------------
    def pump(self) -> None:
        """Advance the pipeline: close chunks whose window or deadline is
        due, then dispatch while capacity lasts.  When the pipe is full
        AND more work waits, the oldest in-flight chunk's result is due —
        that retire is the ONLY fence this schedule ever takes outside
        wait()/drain()."""
        now = self._clock()
        for key in list(self._open):
            why = self._open[key].due(now, self.window_s)
            if why:
                self._close(key, why)
        while self._ready:
            if len(self._inflight) < self.depth:
                self._dispatch(self._pop_ready())
            else:
                self._retire(self._inflight[0])

    def _close(self, okey, why: str) -> _Chunk:
        oc = self._open.pop(okey)
        bucket, sel = okey
        chunk = _Chunk(self._next_chunk, bucket, oc.requests, oc.priority,
                       why, engine_sel=sel)
        self._next_chunk += 1
        for r in oc.requests:
            r._chunk = chunk
        self._ready.append(chunk)
        fc = self.report.forced_closes
        fc[why] = fc.get(why, 0) + 1
        self._t_instant("serve.close", chunk=chunk.chunk_id, why=why,
                        cases=len(oc.requests))
        return chunk

    def _pop_ready(self) -> _Chunk:
        # highest priority first; FIFO (chunk_id) within a priority —
        # starvation-free because every chunk's CLOSE is window-bounded
        # and the dispatch loop drains _ready completely (a retried chunk
        # keeps its chunk_id, so it also keeps its FIFO slot)
        best = min(self._ready, key=lambda c: (-c.priority, c.chunk_id))
        self._ready.remove(best)
        return best

    # -- supervised execution -----------------------------------------------
    def _route(self) -> str:
        """Breaker routing for the next chunk execution."""
        if self._breaker is None:
            return "device"
        route = self._breaker.route()
        if route == "fallback" and self._ensure_fallback() is None:
            return "device"  # no CPU backend here: keep trying the device
        return route

    def _ensure_fallback(self) -> CpuFallback | None:
        if self._fallback is None and self._fallback_on \
                and not self._fallback_dead:
            try:
                fb = CpuFallback(self.engine)
                fb._cpu_device()  # probe: is a CPU backend present at all?
                self._fallback = fb
            except Exception as e:  # noqa: BLE001 — no CPU plugin
                # loud, once: an operator reading breaker-open telemetry
                # must know degraded CPU serving never engaged and the
                # chunks are staying on the (failing) device path
                print(f"serve: CPU fallback unavailable "
                      f"({type(e).__name__}: {e}); breaker-open chunks "
                      "stay on the device path", file=sys.stderr)
                self._fallback_dead = True
        return self._fallback

    def _engine_for(self, sel) -> EnsembleEngine:
        """The chunk's engine: the pipeline's own for ``sel`` None, else
        the picked sibling from the pool (built once per engine key;
        adopt_report shares this pipeline's counters/registry, so the
        metrics dumps stay one report)."""
        if sel is None:
            return self.engine
        e = self._engines.get(sel)
        if e is None:
            e = self.engine.engine_for(*sel)
            if e is not self.engine:
                e.adopt_report(self.report)
            self._engines[sel] = e
        return e

    def _fallback_for(self, chunk: _Chunk) -> CpuFallback | None:
        """The chunk's CPU fallback: the default one for default-engine
        chunks; a per-pick sibling otherwise (a fallback must run the
        chunk's OWN integrator/method or the result would be a
        different scheme wearing the pick's name)."""
        if chunk.engine_sel is None:
            return self._ensure_fallback()
        if self._ensure_fallback() is None:
            return None  # no CPU backend at all (probe failed)
        fb = self._fallbacks.get(chunk.engine_sel)
        if fb is None:
            fb = CpuFallback(self._engine_for(chunk.engine_sel))
            self._fallbacks[chunk.engine_sel] = fb
        return fb

    def _dispatch(self, chunk: _Chunk) -> None:
        """One supervised execution attempt: route, arm injected faults,
        pad (once per chunk) + build + stage + dispatch through the
        engine's stages.  Fallback-routed chunks complete synchronously
        (their fetch is its own fence) and never enter the in-flight
        window; device-routed chunks proceed exactly as PR 3 dispatched
        them — async, no fence."""
        chunk.attempts += 1
        chunk.route = self._route()
        # tag the half-open probe: only ITS outcome may settle the probe
        # slot — a stale device chunk retiring mid-probe must not
        chunk.probe = (self._breaker is not None
                       and chunk.route == "device"
                       and self._breaker.routed_probe)
        chunk.fired = (self._faults.draw([r.seq for r in chunk.requests])
                       if self._faults is not None else NO_FAULTS)
        # fleet tracing: install the chunk's originating TraceContext for
        # the duration of the dispatch stages, so every span recorded
        # inside (serve.build/dispatch AND the engine/store spans those
        # stages emit) is stamped with the ingress request's trace id.
        # Guarded by the tracer: the disabled path stays one attribute
        # read, zero clock reads (the fence-discipline spy contract).
        _ctx_installed = False
        _ctx_prev = None
        if self._tracer is not None:
            _ctx = next((r.trace for r in chunk.requests
                         if r.trace is not None), None)
            if _ctx is not None:
                _ctx_prev = obs_trace.set_context(_ctx)
                _ctx_installed = True
        try:
            self._dispatch_body(chunk)
        finally:
            if _ctx_installed:
                obs_trace.set_context(_ctx_prev)

    def _dispatch_body(self, chunk: _Chunk) -> None:
        t0 = self._clock()
        try:
            # INSIDE the classifying try: a picked-sibling construction
            # error must fail the chunk through the supervised
            # retry/bisect/quarantine path, never unwind out of pump()
            # with the chunk already popped from the ready queue
            engine = self._engine_for(chunk.engine_sel)
            if chunk.fired.raise_ is not None:
                raise InjectedFault(chunk.fired.raise_,
                                    self._faults.attempt - 1)
            if chunk.padded is None:
                chunk.padded = engine.pad_chunk(
                    [r.case for r in chunk.requests])
            if chunk.route == "fallback":
                chunk.build_s = 0.0
                chunk.dispatch_t = self._clock()
                self._record_queue_wait(chunk)
                # no fetch deadline on the fallback: it is the host's own
                # synchronous CPU computation (first call pays the XLA
                # compile in line) — it cannot tunnel-wedge, so there is
                # nothing for the hang watchdog to guard; an armed stall
                # still classifies (the inline path's immediate hang)
                outcome, t1, payload = self._guarded(
                    chunk, lambda: self._fetch_fallback(chunk),
                    deadline_s=None)
                ok = self._complete_attempt(chunk, outcome, t1, payload)
                # the EFFECTIVE outcome: _complete_attempt's finite scan
                # can reclassify a fetched-ok payload as corrupt (the
                # end-of-span clock read stays behind the tracer guard)
                if self._tracer is not None:
                    self._t_span("serve.fallback", t0, self._clock(),
                                 chunk=chunk.chunk_id,
                                 attempt=chunk.attempts,
                                 outcome="ok" if ok else
                                 (chunk.last_failure[0] or outcome))
                if ok:
                    self.report.fallback_chunks += 1
                    self._event("fallback-chunk", chunk=chunk.chunk_id,
                                cases=len(chunk.requests))
                return
            multi = engine.build_program(chunk.key, chunk.padded)
            self._check_steady_state()
            # every attempt RE-STAGES: a fresh device input buffer per
            # dispatch, so the depth-1 donating schedule never re-reads
            # a frame a previous attempt donated away (utils/donation.py)
            U0 = engine.stage_inputs(chunk.padded)
            chunk.build_s = self._clock() - t0
            chunk.dispatch_t = self._clock()
            chunk.out = engine.dispatch_chunk(multi, U0)  # async
        except Exception as e:  # noqa: BLE001 — classified, never fatal
            if self._tracer is not None:
                self._t_span("serve.build", t0, self._clock(),
                             chunk=chunk.chunk_id, attempt=chunk.attempts,
                             error=type(e).__name__)
            self._attempt_failed(chunk, CLASS_ERROR, e)
            return
        # spans from the timestamps the scheduler already took: the
        # host-side pad/build/stage stage, then the (async) launch
        self._t_span("serve.build", t0, chunk.dispatch_t,
                     chunk=chunk.chunk_id, attempt=chunk.attempts)
        self._t_instant("serve.dispatch", ts=chunk.dispatch_t,
                        chunk=chunk.chunk_id, attempt=chunk.attempts,
                        route=chunk.route)
        chunk.state = "inflight"
        self._inflight.append(chunk)
        self._record_queue_wait(chunk)
        n = len(self._inflight)
        self.report.max_inflight = max(self.report.max_inflight, n)
        self.report.occupancy_samples.append((chunk.dispatch_t, n))
        self._t_inflight(chunk.dispatch_t, n)

    def _record_queue_wait(self, chunk: _Chunk) -> None:
        # queue wait means submit -> FIRST dispatch that actually staged
        # (a first attempt that dies in the dispatch stage never set
        # dispatch_t, so the retry records it instead); recorded once per
        # request — bisection halves keep their parent's sample
        for r in chunk.requests:
            if r.queue_wait_s is None:
                r.queue_wait_s = chunk.dispatch_t - r.submit_t
                self.report.queue_wait_ms.append(r.queue_wait_s * 1e3)

    def _fetch_device(self, chunk: _Chunk):
        """Fence + fetch one in-flight chunk (the supervised body; runs
        inline, or inside the watchdog thread when a deadline is set)."""
        if chunk.fired.stall is not None:
            # the injected hang: blocks until the supervisor's
            # classification (or close) releases it — it can never
            # "finish early" under host load
            chunk.fired.stall.wait()
        fence_scalar(chunk.out)  # device completion barrier
        t1 = self._clock()
        return t1, np.asarray(chunk.out)  # host fetch

    def _fetch_fallback(self, chunk: _Chunk):
        # no stall wait here: the only caller runs deadline-free, and
        # _guarded's no-deadline path classifies an armed stall before
        # this body is ever entered
        vals = self._fallback_for(chunk).run_chunk(chunk.key, chunk.padded)
        return self._clock(), vals

    def _guarded(self, chunk: _Chunk, fn, deadline_s="use-default"):
        """Run one fetch under the per-chunk deadline.  Returns
        ``(outcome, t_fence, payload)`` where outcome is "ok" (payload =
        fetched values), CLASS_ERROR (payload = the exception), or
        CLASS_HANG (payload = None).  Without a deadline this is the
        inline PR 3 path — no thread; an armed stall is then classified
        immediately instead of blocking the scheduler forever."""
        if deadline_s == "use-default":
            deadline_s = self.fetch_deadline_s
        if deadline_s is None:
            if chunk.fired.stall is not None:
                chunk.fired.stall.set()
                return CLASS_HANG, self._clock(), None
            try:
                t1, vals = fn()
            except Exception as e:  # noqa: BLE001
                return CLASS_ERROR, self._clock(), e
            return "ok", t1, vals
        box: dict = {}

        def worker():
            try:
                box["t1"], box["vals"] = fn()
            except Exception as e:  # noqa: BLE001
                box["exc"] = e

        th = threading.Thread(target=worker, daemon=True)
        th.start()
        th.join(deadline_s)
        if th.is_alive():
            # deadline missed: classify a hang and ABANDON the thread —
            # the wedge discipline forbids killing the client, and a
            # daemon thread blocked in a dead fetch costs nothing.  Only
            # THIS chunk's injected stall is released (so its worker
            # exits promptly) — releasing every armed stall would defuse
            # faults on OTHER in-flight chunks whenever a genuinely slow
            # fence trips the deadline, making injected outcomes depend
            # on interleaving; close() still releases everything.
            if chunk.fired.stall is not None:
                chunk.fired.stall.set()
            return CLASS_HANG, self._clock(), None
        if "exc" in box:
            return CLASS_ERROR, self._clock(), box["exc"]
        return "ok", box["t1"], box["vals"]

    def _scan(self, chunk: _Chunk, vals):
        """Post-fetch corruption check (+ the injector's nan hook)."""
        if chunk.fired.nan is not None:
            vals = self._faults.apply_nan(
                chunk.fired, vals, [r.seq for r in chunk.requests])
        if self.nan_policy == "quarantine" \
                and not np.all(np.isfinite(vals)):
            return CLASS_CORRUPT, vals
        return "ok", vals

    def _release_stalls(self) -> None:
        if self._faults is not None:
            self._faults.release_stalls()

    def _record_breaker(self, chunk: _Chunk, ok: bool) -> None:
        if self._breaker is None or chunk.route != "device":
            return
        if ok:
            self._breaker.record_success(probe=chunk.probe)
        else:
            self._breaker.record_failure(probe=chunk.probe)

    def _attempt_failed(self, chunk: _Chunk, classification: str,
                        exc=None) -> None:
        """Classify, count, and decide: bounded retry with exponential
        backoff, bisection, or quarantine."""
        chunk.out = None  # drop the device future; retries re-stage
        f = self.report.faults
        f[classification] = f.get(classification, 0) + 1
        # corruption is DATA-shaped: a legitimately divergent input
        # reproduces its NaNs on any backend, and the device path DID
        # execute and deliver a buffer — so the breaker records a
        # SUCCESS (clearing a half-open probe; never opening on bad
        # data); only error/hang attest to device-path ill-health
        self._record_breaker(chunk, ok=(classification == CLASS_CORRUPT))
        detail = f"{type(exc).__name__}: {exc}" if exc is not None else ""
        chunk.last_failure = (classification, detail)
        if chunk.attempts <= self.retries:
            self.report.retries += 1
            delay_s = (self.backoff_ms / 1e3) * (2 ** (chunk.attempts - 1))
            self._t_instant("serve.retry", chunk=chunk.chunk_id,
                            attempt=chunk.attempts,
                            classification=classification,
                            backoff_ms=delay_s * 1e3)
            self._event("retry", chunk=chunk.chunk_id,
                        attempt=chunk.attempts,
                        classification=classification)
            if delay_s > 0:
                self.report.backoff_ms_total += delay_s * 1e3
                self._sleep(delay_s)
            chunk.state = "ready"
            self._ready.append(chunk)
            return
        if len(chunk.requests) > 1:
            self._bisect(chunk)
        else:
            self._quarantine(chunk, classification, detail)

    def _bisect(self, chunk: _Chunk) -> None:
        """Poison isolation: split the exhausted chunk in half; both
        halves re-enter the ready queue as fresh chunks (fresh attempt
        budgets, re-padded on dispatch).  Repeated, this isolates the
        failing case in O(log B) extra chunk executions while every
        chunk-mate is re-bucketed and served normally."""
        mid = len(chunk.requests) // 2
        self.report.bisections += 1
        self._t_instant("serve.bisect", chunk=chunk.chunk_id,
                        cases=len(chunk.requests),
                        halves=[self._next_chunk, self._next_chunk + 1])
        fc = self.report.forced_closes
        for part in (chunk.requests[:mid], chunk.requests[mid:]):
            half = _Chunk(self._next_chunk, chunk.key, part,
                          chunk.priority, "bisect",
                          engine_sel=chunk.engine_sel)
            self._next_chunk += 1
            for r in part:
                r._chunk = half
            fc["bisect"] = fc.get("bisect", 0) + 1
            self._ready.append(half)
        chunk.state = "done"  # superseded by its halves

    def _quarantine(self, chunk: _Chunk, classification: str,
                    detail: str) -> None:
        """The isolated poison case completes exceptionally."""
        req = chunk.requests[0]
        req.error = ServeError(classification, req.seq, chunk.chunk_id,
                               chunk.attempts, detail)
        req.latency_s = self._clock() - req.submit_t
        self.report.quarantined.append({
            "case": req.seq, "classification": classification,
            "attempts": chunk.attempts, "chunk": chunk.chunk_id})
        self._t_instant("serve.quarantine", case=req.seq,
                        chunk=chunk.chunk_id,
                        classification=classification,
                        attempts=chunk.attempts)
        self._event("quarantine", case=req.seq, chunk=chunk.chunk_id,
                    classification=classification,
                    attempts=chunk.attempts, detail=detail)
        if self._slo is not None:
            # the exceptional outcome resolves the promise too — a
            # quarantined case must not linger as an open ledger entry
            self._slo.resolve(req.seq, latency_s=req.latency_s,
                              queue_wait_s=req.queue_wait_s,
                              error=classification)
        fr = self._flightrec
        if fr is not None:
            # a typed ServeError quarantine is a black-box trigger: the
            # postmortem names the poison case and what was in flight
            fr.dump("quarantine", case=req.seq,
                    classification=classification, detail=detail)
        chunk.state = "done"

    def _complete_attempt(self, chunk: _Chunk, outcome, t_fence,
                          payload) -> bool:
        """The shared tail of one supervised execution attempt, for both
        routes: scan the fetched buffer, then finish the chunk or
        classify the failure (retry / bisect / quarantine).  Returns
        True when the chunk finished with results."""
        if outcome == "ok":
            outcome, payload = self._scan(chunk, payload)
            if outcome == "ok":
                self._record_breaker(chunk, ok=True)
                self._finish(chunk, payload, t_fence)
                return True
            self._attempt_failed(chunk, outcome)
            return False
        self._attempt_failed(
            chunk, outcome, payload if outcome == CLASS_ERROR else None)
        return False

    def _retire(self, chunk: _Chunk) -> None:
        """Fence + fetch one in-flight chunk under supervision and
        distribute its lanes (or classify the failure)."""
        self._inflight.remove(chunk)
        t_f0 = None
        _ctx_installed = False
        _ctx_prev = None
        if self._tracer is not None:
            t_f0 = self._clock()
            # stamp the retire-side spans with the originating request's
            # trace (the dispatch-side twin lives in _dispatch)
            _ctx = next((r.trace for r in chunk.requests
                         if r.trace is not None), None)
            if _ctx is not None:
                _ctx_prev = obs_trace.set_context(_ctx)
                _ctx_installed = True
        try:
            outcome, t1, payload = self._guarded(
                chunk, lambda: self._fetch_device(chunk))
            ok = self._complete_attempt(chunk, outcome, t1, payload)
            t_now = self._clock()
            if t_f0 is not None:
                # the fetch span reuses the fence the retire performs
                # anyway; like serve.fallback it reports the EFFECTIVE
                # outcome — _complete_attempt's finite scan can
                # reclassify a fetched-ok payload as corrupt
                self._t_span("serve.fetch", t_f0, t_now,
                             chunk=chunk.chunk_id,
                             attempt=chunk.attempts,
                             outcome="ok" if ok else
                             (chunk.last_failure[0] or outcome))
        finally:
            if _ctx_installed:
                obs_trace.set_context(_ctx_prev)
        self.report.occupancy_samples.append((t_now, len(self._inflight)))
        self._t_inflight(t_now, len(self._inflight))

    def _finish(self, chunk: _Chunk, vals, t_fence) -> None:
        """Distribute a retired chunk's lanes (padding lanes dropped)."""
        t2 = self._clock()
        for j, r in enumerate(chunk.requests):
            r.result = np.asarray(vals[j])
            r.latency_s = t2 - r.submit_t
            self.report.request_latency_ms.append(r.latency_s * 1e3)
        tr = self._tracer
        if tr is not None:
            # flow FINISH per traced request, at the retire timestamp
            # the scheduler already took: Perfetto binds it (bp="e") to
            # the enclosing serve.fetch/serve.fallback span, closing the
            # ingress -> router -> worker arrow chain (obs/trace.py)
            for r in chunk.requests:
                if r.trace is not None:
                    tr.flow("request", "finish", r.trace.trace_id,
                            ts=t2, cat="serve", req=r.seq,
                            chunk=chunk.chunk_id)
        chunk.state = "done"
        chunk.out = None
        entry = {
            "chunk": chunk.chunk_id,
            "cases": len(chunk.requests),
            "closed_by": chunk.closed_by,
            "build_ms": round(chunk.build_s * 1e3, 3),
            "device_ms": round((t_fence - chunk.dispatch_t) * 1e3, 3),
            "fetch_ms": round((t2 - t_fence) * 1e3, 3),
            "route": chunk.route,
            "attempt": chunk.attempts,
        }
        self.report.chunk_log.append(entry)
        self._event("chunk", **entry)
        if self._slo is not None:
            self._slo_retire(chunk, entry, t2)

    def _slo_retire(self, chunk: _Chunk, entry: dict, t2) -> None:
        """The outcome half of the audit (obs/slo.py): resolve every
        retired request's promise from the timestamps the retire already
        took (zero-fence contract), then feed the live rate recorder the
        chunk's observed per-apply milliseconds so the picker's cost
        model recalibrates with traffic.  Called only when the ledger is
        on; never raises (the ledger swallows its own failures)."""
        sl = self._slo
        B = len(chunk.requests)
        dev_ms = entry["device_ms"]
        for r in chunk.requests:
            sl.resolve(r.seq, latency_s=r.latency_s,
                       queue_wait_s=r.queue_wait_s,
                       device_ms=dev_ms / B, t=t2)
        if chunk.route != "device" or dev_ms <= 0:
            return  # CPU-fallback walls must not recalibrate device picks
        try:
            case = chunk.requests[0].case
            if case.mesh is not None:
                # mesh-axis rate keys use the mesh's EFFECTIVE eps
                # (serve/picker.py _mesh_eps_eff), which needs the
                # registered cloud — not worth loading per retire
                return
            live = sl.ensure_live(self._device_kind())
            if live is None:
                return
            engine = self._engine_for(chunk.engine_sel)
            lanes = len(chunk.padded) if chunk.padded else B
            applies = obs_slo.applies_per_step(engine.stepper,
                                               engine.stages)
            per_apply = dev_ms / (lanes * max(1, int(case.nt)) * applies)
            live.record(engine.method, case.shape, case.eps,
                        engine.precision, per_apply)
        except Exception:  # noqa: BLE001 — observability never raises
            pass

    def _device_kind(self) -> str:
        """The live-rate key's device kind, cached after first use.
        Safe HERE by construction: a chunk has already retired through
        this process's backend (the wedge discipline keeps the lookup
        out of router/ingress processes — their ledgers run without a
        live recorder)."""
        dk = getattr(self, "_device_kind_cached", None)
        if dk is None:
            from nonlocalheatequation_tpu.utils.devices import device_list

            dk = self._device_kind_cached = device_list()[0].device_kind
        return dk

    # -- completion ---------------------------------------------------------
    def wait(self, req: ServeRequest) -> np.ndarray:
        """Force one request to completion (an implicit immediate
        deadline): close its open chunk if still accumulating, dispatch
        through the normal capacity discipline, fence its chunk.  Raises
        the typed ``ServeError`` if the case was quarantined."""
        while req.result is None and req.error is None:
            ch = req._chunk
            if ch is None:
                self._close((req.case.bucket_key(), req.engine_sel),
                            "wait")
            elif ch.state == "ready":
                if len(self._inflight) >= self.depth:
                    self._retire(self._inflight[0])
                else:
                    self._dispatch(self._pop_ready())
            else:  # inflight
                self._retire(ch)
        if req.error is not None:
            raise req.error
        return req.result

    def drain(self) -> None:
        """Flush everything: close all partial chunks, dispatch them
        (retiring as capacity demands), then retire all in-flight work —
        including any retries and bisection halves a failure re-queues.
        Quarantined requests do NOT raise here; their handles carry the
        ``ServeError`` (``wait()`` raises it)."""
        for key in list(self._open):
            self._close(key, "drain")
        while self._ready or self._inflight:
            if self._ready and len(self._inflight) < self.depth:
                self._dispatch(self._pop_ready())
            else:
                self._retire(self._inflight[0])

    def serve_cases(self, cases) -> list:
        """Convenience: submit every case, drain, return results in
        submission order — the schedule-changed twin of
        ``EnsembleEngine.run()`` (bit-identical output).  A quarantined
        case's slot holds None (its handle carries the ServeError)."""
        handles = [self.submit(c) for c in cases]
        self.drain()
        return [h.result for h in handles]

    def close(self) -> None:
        """Drain and release the pipeline.  The process-wide donation
        depth declared at construction is restored even if the final
        drain raises (a failed serve run must not leave donation pinned
        for the rest of the process), and any armed/abandoned injected
        stalls are released so no test leaks a blocked thread."""
        if not self._closed:
            try:
                self.drain()
            finally:
                self._release_stalls()
                donation.set_pipeline_depth(self._prev_depth)
                if self._slo is not None:
                    self._slo.close()  # flush buffered live rates
                if self._events is not None:
                    self._events.close()
                self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- observability ------------------------------------------------------
    def metrics(self) -> dict:
        m = self.report.metrics()
        if self._slo is not None:
            m["slo"] = self._slo.summary()
        return m

    def metrics_json(self) -> str:
        return json.dumps(self.metrics())

    # -- retrace watchdog (ISSUE 11 satellite) ------------------------------
    def arm_steady_state(self) -> int:
        """Arm the recompile watchdog: a steady-state server (warmed
        caches, AOT store hot) should build ZERO new programs — call
        this after warm-up (the fleet router's ``arm_steady_state``
        broadcasts it; bench/CLI drivers call it directly) and every
        later ``programs_built`` growth increments
        ``/store/steady-state-builds`` plus a LOUD EventLog warning and
        flight-recorder note, so a silent recompile storm pages instead
        of burning.  Returns the armed baseline."""
        self._steady_seen = int(self.report.programs_built)
        # materialize the counter at arm time: a scrape sees the key
        # (value 0) even before any violation
        self.registry.counter("/store/steady-state-builds")
        return self._steady_seen

    def _check_steady_state(self) -> None:
        """Post-build hook (one int compare when armed, one attribute
        read when not): count + warn on programs built past the armed
        baseline."""
        seen = self._steady_seen
        if seen is None:
            return
        built = int(self.report.programs_built)
        if built <= seen:
            return
        delta = built - seen
        self._steady_seen = built
        self.registry.counter("/store/steady-state-builds").inc(delta)
        print(f"serve: WARNING steady-state recompile — {delta} new "
              f"program(s) built after warm-up ({built} total); the AOT "
              "store should have made this a load "
              "(/store/steady-state-builds)", file=sys.stderr)
        self._event("steady-state-build", built=built, delta=delta)


def serve_fence_ab(engine: EnsembleEngine, cases, depth: int,
                   iters: int = 2):
    """The pipelined-vs-fenced measurement shared by bench.py
    (``BENCH_SERVE``) and tools/bench_table.py (``serve`` group): time the
    fenced (depth 1 — a dispatch+fence roundtrip per chunk, run_batch's
    schedule) and pipelined (``depth`` in flight, fence only on retire)
    schedules of the SAME case set over ONE engine, so the shared program
    cache makes this an A/B of schedules, not compiles.  The first
    pipelined pass warms the cache and its wall is returned as the
    compile time.  Callers pin donation off themselves (the halves must
    differ only in schedule).  Returns ``(compile_s, fenced_best_s,
    pipelined_best_s, best_pipelined_report)``."""

    def run_schedule(d):
        pipe = ServePipeline(engine=engine, depth=d, window_ms=0.0)
        try:
            t0 = time.perf_counter()
            pipe.serve_cases(cases)
            return time.perf_counter() - t0, pipe.report
        finally:
            pipe.close()

    compile_s, _ = run_schedule(depth)
    fenced_best = float("inf")
    pipe_best, pipe_rep = float("inf"), None
    for _ in range(iters):
        sec_f, _ = run_schedule(1)
        fenced_best = min(fenced_best, sec_f)
        sec_p, rep = run_schedule(depth)
        if sec_p < pipe_best:
            pipe_best, pipe_rep = sec_p, rep
    return compile_s, fenced_best, pipe_best, pipe_rep


def serve_traced_ab(engine: EnsembleEngine, cases, depth: int,
                    iters: int = 2):
    """The traced-vs-untraced measurement shared by bench.py
    (``BENCH_TRACE``) and tools/bench_table.py (``obs`` group): time the
    SAME pipelined schedule of ``cases`` over ONE engine twice per iter —
    once with tracing off (the zero-cost disabled path) and once with a
    span :class:`~nonlocalheatequation_tpu.obs.trace.Tracer` installed on
    the pipeline — so the ratio isolates the host-side cost of recording
    spans (the ISSUE 5 gate: <= 5% on the serve proxy).  The first
    traced pass warms the program cache and its wall is the compile
    time.  Returns ``(compile_s, untraced_best_s, traced_best_s,
    best_tracer, best_traced_report)``."""
    from nonlocalheatequation_tpu.obs.trace import Tracer

    # a non-positive iter count would return inf walls and a None tracer
    # that bench.py dereferences — always measure at least once
    iters = max(1, int(iters))

    def run_schedule(tracer):
        pipe = ServePipeline(engine=engine, depth=depth, window_ms=0.0,
                             tracer=tracer)
        try:
            t0 = time.perf_counter()
            pipe.serve_cases(cases)
            return time.perf_counter() - t0, pipe.report
        finally:
            pipe.close()

    compile_s, _ = run_schedule(Tracer())
    plain_best = float("inf")
    traced_best, best_tracer, best_rep = float("inf"), None, None
    for _ in range(iters):
        # TRACE_OFF, not None: the baseline must stay untraced even when
        # a process-global tracer is installed (--trace/NLHEAT_TRACE),
        # or the A/B would trace both arms and measure nothing
        sec_u, _ = run_schedule(obs_trace.TRACE_OFF)
        plain_best = min(plain_best, sec_u)
        tracer = Tracer()
        sec_t, rep = run_schedule(tracer)
        if sec_t < traced_best:
            traced_best, best_tracer, best_rep = sec_t, tracer, rep
    return compile_s, plain_best, traced_best, best_tracer, best_rep


def serve_chaos(engine: EnsembleEngine, cases, depth: int, plan_spec: str,
                *, retries: int = 2, fetch_deadline_ms: float = 2000.0,
                breaker_threshold: int = 1,
                breaker_cooldown_ms: float = 600_000.0):
    """The chaos measurement shared by bench.py (``BENCH_SERVE_FAULTS``)
    and tools/bench_table.py (``resilience`` group): serve ``cases``
    through a fully supervised pipeline while the deterministic plan
    ``plan_spec`` (utils/faults.py grammar) injects faults mid-stream.
    The default breaker opens on the FIRST device failure and stays open
    (10-minute cooldown), so any injected raise/stall fault guarantees at
    least one fallback-served chunk — the evidence the ``servefault``
    queue step gates on.  (A nan-only plan does NOT: corruption is
    data-shaped and deliberately never opens the breaker, so a chaos gate
    on ``fallback_chunks`` must inject raise or stall.)  Returns ``(wall_s, results, report)``; a quarantined
    case's results slot is None."""
    pipe = ServePipeline(
        engine=engine, depth=depth, window_ms=0.0,
        faults=FaultPlan.parse(plan_spec), retries=retries,
        fetch_deadline_ms=fetch_deadline_ms, backoff_ms=0.0,
        breaker=CircuitBreaker(threshold=breaker_threshold,
                               cooldown_ms=breaker_cooldown_ms))
    try:
        t0 = time.perf_counter()
        results = pipe.serve_cases(cases)
        return time.perf_counter() - t0, results, pipe.report
    finally:
        pipe.close()
