"""Replica-fleet router: N ServePipeline worker processes, one front door.

The reference's top tier is many HPX localities behind an idle-rate-driven
dynamic load balancer (src/2d_nonlocal_distributed.cpp:844-959); our
single-process :class:`~nonlocalheatequation_tpu.serve.server.ServePipeline`
matches the scheduler half of that story but owns exactly one backend
client.  This module is the fleet half: a router that owns N replica
WORKER PROCESSES — each a full ServePipeline over its own EnsembleEngine —
and routes submitted cases across them:

* **Sticky bucket routing** — a case's ensemble bucket key
  (``EnsembleCase.bucket_key()``) is pinned to one replica the first time
  it is seen, so every replica's bounded LRU program cache
  (serve/ensemble.py) stays hot for the buckets it owns and never
  compiles its neighbors'.  All replicas share one AOT program store dir
  (``NLHEAT_PROGRAM_STORE``, serve/program_store.py), so a bucket moved
  to (or first touched by) any replica warm-boots from the fleet's
  compiles instead of re-tracing — the PR 9 unlock this router exists
  for.
* **Elastic add/drain** — each worker reports the absolute busy fraction
  of its serving loop per stats window; the router feeds those into the
  busy-rate policy factored out of the tile executor
  (parallel/elastic.py :class:`~nonlocalheatequation_tpu.parallel.elastic.BusyRatePolicy`
  + :func:`~nonlocalheatequation_tpu.parallel.elastic.fleet_scale_decision`)
  and adds a worker when the whole fleet is saturated / drains one when
  the whole fleet is idle — the reference's idle-rate balancer lifted
  one layer up (regions = bucket sets, localities = replicas).  Adding a
  replica rebalances bucket ownership toward it (the newcomer inherits
  buckets, which it loads from the shared store: warm boot, zero
  retrace); draining reassigns the leaver's buckets and lets its
  in-flight cases finish.
* **Replica death is a first-class event** — a reader thread per worker
  notices EOF on the worker's response pipe; every case that was in
  flight on the dead worker is RE-ROUTED to a survivor (respawning one
  first when the fleet would drop below its floor) and re-served
  bit-identically (results are deterministic functions of the case —
  the same pinned contract as the pipeline's own retries).  No case is
  lost, none is delivered twice (a case leaves the outstanding map the
  moment its result frame is read; only cases still outstanding at
  death re-route).  The deterministic worker-kill plan kind ``die``
  (utils/faults.py) makes the whole path chaos-provable: the router
  draws from its plan at each case-forward event and SIGKILLs the
  worker a fired case was just routed to.

Transport: length-prefixed pickle frames over a :mod:`serve.transport`
worker transport — stdin/stdout pipes by default (the worker steals
fd 1 at startup so stray prints cannot corrupt the framing; its stderr
is inherited), or TCP sockets (``transport="tcp"``: workers started
with ``--worker-connect host:port`` dial in and speak the identical
frames, so one replica can be one remote host/chip).  The trust model
is the program store's: the router and its workers are one principal —
on one host over pipes/loopback, or across hosts behind the shared
token the socket transport's hello verifies (serve/transport.py trust
boundary).

Case classes (ISSUE 12): cases at or below ``shard_threshold`` grid
points batch onto single-chip ServePipeline replicas exactly as
before; a 2D grid ABOVE it is dispatched to the **gang replica** — a
worker that owns an N-device mesh and solves the case as ONE
space-parallel distributed solve (``comm='fused'`` remote-DMA halos
where the kernel family serves the config, the collective transport
where ``require_fused`` refuses), streaming the result back over the
same frame channel bit-identical to the offline
:class:`~nonlocalheatequation_tpu.parallel.distributed2d.Solver2DDistributed`
path (parallel/gang.py ``solve_case_sharded`` is the one adapter both
sides call).  The router is thus the component that chooses between
the case-parallel and space-parallel axes of the hybrid mesh layer
(parallel/mesh_axes.py).

Backpressure: the router's queues are BOUNDED — ``submit`` raises the
typed :class:`RouterOverloaded` (with a retry-after estimate from the
observed latency window) once ``max_outstanding`` cases per live replica
are in flight.  The HTTP ingestion tier (serve/http.py) sheds on this
(and on its own softer admission rule) with 429 + Retry-After before the
fleet's pipes can collapse.

Observability: the router's registry carries ``/router/*`` counters and
gauges (cases, routed, requeued, deaths, scale events, outstanding,
latency histogram), per-replica ``/replica{r}/busy-rate`` gauges, and —
after each stats pull — every worker's own registry snapshot absorbed
under ``/replica{r}`` prefixes (obs/metrics.absorb_snapshot), so ONE
scrape of the router registry exposes the whole fleet.
"""

from __future__ import annotations

import os
import pickle
import queue
import select
import sys
import threading
import time

import numpy as np

from nonlocalheatequation_tpu.obs import flightrec
from nonlocalheatequation_tpu.obs import slo as obs_slo
from nonlocalheatequation_tpu.obs import trace as obs_trace
from nonlocalheatequation_tpu.obs.export import REPLICA_ID_ENV
from nonlocalheatequation_tpu.obs.metrics import (
    MetricsRegistry,
    absorb_snapshot,
)
from nonlocalheatequation_tpu.obs.trace import (
    TraceContext,
    merge_chrome_traces,
    write_chrome_trace,
)
from nonlocalheatequation_tpu.parallel.elastic import (
    BusyRatePolicy,
    FleetTelemetry,
    fleet_scale_decision,
)
from nonlocalheatequation_tpu.serve.ensemble import EnsembleCase
from nonlocalheatequation_tpu.serve.picker import EngineChoice
from nonlocalheatequation_tpu.serve.resilience import ServeError
from nonlocalheatequation_tpu.serve.transport import (
    LEN as _LEN,
    MAX_FRAME_BYTES,
    WORKER_TOKEN_ENV,
    make_transport,
    write_frame as _write_frame,
    write_json_frame,
)
from nonlocalheatequation_tpu.utils.faults import FaultPlan

#: Default per-replica in-flight bound (cases routed but not yet
#: delivered).  The router's queues must stay bounded no matter how fast
#: callers submit — admission control (serve/http.py) sheds SOFTLY ahead
#: of this hard refusal.
MAX_OUTSTANDING = 64

#: Re-routes a case may survive before completing exceptionally.  A case
#: whose replica keeps dying is indistinguishable from a case that KILLS
#: its replicas — unbounded re-routing would crash-loop the entire fleet
#: on one poison request (the router-level twin of the pipeline's
#: retry-then-quarantine budget).
MAX_REQUEUES = 3


class RouterOverloaded(RuntimeError):
    """The router's bounded queue is full.  ``retry_after_s`` is the
    suggested backoff (the ingress tier's Retry-After header)."""

    def __init__(self, outstanding: int, cap: int, retry_after_s: float):
        super().__init__(
            f"router overloaded: {outstanding} cases in flight "
            f"(cap {cap}); retry in {retry_after_s:.2f}s")
        self.outstanding = outstanding
        self.cap = cap
        self.retry_after_s = retry_after_s


class RouterRequest:
    """One routed case: the caller's handle (a cross-process future)."""

    def __init__(self, case: EnsembleCase, seq: int, submit_t: float):
        self.case = case
        self.seq = seq
        self.submit_t = submit_t
        self.deadline_ms = None
        self.priority = 0
        #: picked engine (serve/picker.py EngineChoice) riding the case
        #: frame to the worker; None = the fleet's default engine
        self.engine = None
        #: routing identity override (serve/sessions.py): a session's
        #: chunks all carry ("session", sid) so the final partial chunk
        #: (different nt -> different bucket key) still lands on the
        #: session's replica; None = the case's own bucket key
        self.sticky_key = None
        self.trace: TraceContext | None = None  # fleet trace identity
        self.trace_minted = False  # router-minted (no ingress root)
        self._flow_started = False  # first flow hop already emitted
        self.result: np.ndarray | None = None
        self.error: ServeError | None = None
        self.latency_s: float | None = None
        self.replica: int | None = None  # current owner
        self.requeues = 0  # times re-routed after a replica death
        self.done = threading.Event()

    def wait(self, timeout: float | None = None) -> np.ndarray:
        if not self.done.wait(timeout):
            raise TimeoutError(
                f"case {self.seq} not served within {timeout}s")
        if self.error is not None:
            raise self.error
        return self.result


class _Replica:
    """Router-side worker handle: the process, its framed pipes, the
    reader/writer threads' state, and the bucket set it owns.

    Sends are ASYNCHRONOUS: ``send`` enqueues and a dedicated writer
    thread drains the queue into the worker's stdin pipe.  A worker
    mid-compute stops reading its pipe, and the 64 KB pipe buffer would
    otherwise block the ROUTER's submitting thread on the next frame —
    throttling intake to the fleet's service rate, which makes overload
    unobservable (the queue the admission gate bounds could never
    form).  The router-side queue is part of the case's in-flight
    accounting, so the bound still holds end to end."""

    def __init__(self, rid: int, handle, gang: bool = False):
        self.rid = rid
        self.handle = handle  # transport WorkerHandle (pipes or socket)
        self.gang = gang  # the sharded-case worker (N-device mesh)
        self.sendq: "queue.Queue" = queue.Queue()
        self.ready = threading.Event()
        self.alive = True
        self.closing = False  # router-initiated stop: EOF is not a death
        self.draining = False  # no NEW buckets/cases route here
        self.outstanding: dict[int, RouterRequest] = {}
        self.buckets: set = set()
        # token -> [event, box]: one waiter per pulled reply frame
        # (stats AND trace dumps share the token space/mechanism)
        self.stats_waiters: dict[int, list] = {}
        self.last_stats: dict | None = None
        #: the worker's (monotonic, wall) clock pair, exchanged on the
        #: hello frame — merge_chrome_traces aligns per-process
        #: monotonic-epoch span timestamps with it (obs/trace.py)
        self.clock_sync: dict | None = None

    def send(self, obj) -> bool:
        """Enqueue one frame for the writer thread (never blocks on the
        pipe).  False only when the worker is already known-dead."""
        if not self.alive:
            return False
        self.sendq.put(obj)
        return True

    def _writer(self) -> None:
        """Drain the send queue into the worker's stdin.  A broken pipe
        ends the thread quietly — the reader's EOF owns death handling.
        The ``__kill__`` sentinel (the fault plan's ``die``) is ORDERED
        with the frames before it: the case it spans is genuinely in
        flight on the worker when the SIGKILL lands."""
        while True:
            obj = self.sendq.get()
            if obj is None:
                return
            if isinstance(obj, dict) and obj.get("op") == "__kill__":
                self.handle.kill()
                continue
            try:
                self.handle.send_frame(obj)
            except (OSError, ValueError):
                return


class ReplicaRouter:
    """Own N replica worker processes; route cases sticky-by-bucket.

    ``replicas`` is the starting fleet size (also the floor unless
    ``min_replicas`` says otherwise); ``max_replicas`` caps elastic
    growth (default ``2 * replicas``).  ``program_store`` is the shared
    AOT store dir every worker resolves (None = inherit the ambient
    ``NLHEAT_PROGRAM_STORE``).  ``depth``/``window_ms``/``window_size``
    and ``serve_kwargs`` configure each worker's ServePipeline;
    remaining ``engine_kwargs`` its EnsembleEngine.  ``faults`` (or a
    spec string) is the ROUTER-level deterministic plan — the ``die``
    kind kills workers; the plan is scrubbed from worker environments so
    it can never double-inject inside their pipelines.  ``child_env``
    adds/overrides worker env vars (bench uses it to pin single-thread
    XLA for an honest scale-out A/B)."""

    def __init__(self, replicas: int = 1, *, depth: int = 1,
                 window_ms: float = 2.0, window_size: int | None = None,
                 program_store: str | None = None,
                 mesh_dir: str | None = None,
                 max_outstanding: int = MAX_OUTSTANDING,
                 min_replicas: int | None = None,
                 max_replicas: int | None = None,
                 respawn: bool = True,
                 faults: FaultPlan | str | None = None,
                 serve_kwargs: dict | None = None,
                 child_env: dict | None = None,
                 transport: str | object = "pipe",
                 worker_token: str | None = None,
                 shard_threshold: int | None = None,
                 gang_devices: int | None = None,
                 gang_comm: str = "fused",
                 cpus_per_replica: int | None = None,
                 registry: MetricsRegistry | None = None,
                 spawn_timeout_s: float = 180.0,
                 clock=time.monotonic,
                 tracer=None, trace_dir: str | None = None,
                 flight_dir: str | None = None,
                 stale_after_s: float = 60.0,
                 slo=None,
                 **engine_kwargs):
        replicas = int(replicas)
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if max_outstanding < 1:
            raise ValueError(
                f"max_outstanding must be >= 1, got {max_outstanding}")
        if isinstance(faults, str):
            faults = FaultPlan.parse(faults)
        # the sharded big-case tier (ISSUE 12): grids above the
        # threshold (in grid POINTS) go to the gang replica.  0 turns
        # the tier off per the repo's 0-knob convention.
        if shard_threshold is not None:
            shard_threshold = int(shard_threshold)
            if shard_threshold < 0:
                raise ValueError(
                    f"shard_threshold must be >= 0 (0/None = off), got "
                    f"{shard_threshold}")
            if shard_threshold == 0:
                shard_threshold = None
        self.shard_threshold = shard_threshold
        if gang_comm not in ("fused", "collective"):
            raise ValueError(
                f"gang_comm must be 'fused' or 'collective', got "
                f"{gang_comm!r}")
        self.gang_comm = gang_comm
        if gang_devices is not None and int(gang_devices) < 1:
            raise ValueError(
                f"gang_devices must be >= 1, got {gang_devices}")
        # None = the gang worker uses every device IT sees (the router
        # never touches a backend — wedge discipline)
        self.gang_devices = (int(gang_devices) if gang_devices is not None
                             else None)
        self._transport_arg = transport
        self._worker_token = worker_token
        self._transport = None  # constructed just before the spawns
        self.min_replicas = int(min_replicas if min_replicas is not None
                                else replicas)
        self.max_replicas = int(max_replicas if max_replicas is not None
                                else max(2 * replicas, replicas + 1))
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas ({self.min_replicas}) <= "
                f"max_replicas ({self.max_replicas})")
        self.max_outstanding = int(max_outstanding)
        self.respawn = bool(respawn)
        self.depth = int(depth)
        self.window_ms = float(window_ms)
        self.window_size = window_size
        self.program_store = program_store
        # the mesh registry dir (ISSUE 17, serve/meshes.py): every
        # worker resolves the SAME registry so a mesh-keyed bucket is
        # servable wherever the sticky router pins it (None = inherit
        # the ambient NLHEAT_MESH_DIR, the program_store convention)
        self.mesh_dir = mesh_dir
        self.serve_kwargs = dict(serve_kwargs or {})
        self.engine_kwargs = dict(engine_kwargs)
        self.child_env = dict(child_env or {})
        # CPU-affinity budget per worker (os.sched_setaffinity in the
        # child): the CPU proxy of per-replica hardware — one XLA CPU
        # process otherwise spreads over every host core and a fleet
        # A/B on one box would measure contention, not scale-out.
        # None = no pinning (production: each replica owns its machine)
        self.cpus_per_replica = (int(cpus_per_replica)
                                 if cpus_per_replica else None)
        try:
            self._host_cpus = sorted(os.sched_getaffinity(0))
        except AttributeError:  # non-Linux: no pinning support
            self._host_cpus = []
            self.cpus_per_replica = None
        self.spawn_timeout_s = float(spawn_timeout_s)
        self._clock = clock
        self._faults = faults
        # worker backend config mirrors THIS process's jax config (pure
        # config reads — no backend touch, the wedge discipline): the
        # re-serve bit-identity contract needs every worker on the same
        # platform and x64 mode as the offline oracle
        import jax

        self._platform = jax.config.jax_platforms or None
        self._x64 = bool(jax.config.jax_enable_x64)
        # fleet tracing (ISSUE 11): ``trace_dir`` turns on cross-process
        # tracing — the router runs its own span tracer (labeled for the
        # merged timeline) and every worker installs one too, writing
        # per-replica trace files under trace_dir; dump_fleet_trace()
        # merges them all into ONE Perfetto document.  Without it the
        # router inherits the process-global tracer (None = off, the
        # zero-cost path; TRACE_OFF forces off like ServePipeline).
        self.trace_dir = trace_dir
        if trace_dir is not None:
            os.makedirs(trace_dir, exist_ok=True)
            self._tracer = obs_trace.Tracer(label="router")
        else:
            self._tracer = (None if tracer is obs_trace.TRACE_OFF
                            else tracer if tracer is not None
                            else obs_trace.get_tracer())
        # crash flight recorder (obs/flightrec.py): the router's own
        # black box — worker death dumps a postmortem naming the killed
        # replica, its in-flight cases, and each re-route decision.
        # ``flight_dir`` explicit, else the ambient NLHEAT_FLIGHT_DIR
        # recorder if one is installed process-globally.
        if flight_dir is not None:
            self._flightrec = flightrec.FlightRecorder(flight_dir)
        else:
            self._flightrec = flightrec.get_recorder()
        self.flight_dir = (self._flightrec.dir
                           if self._flightrec is not None else None)
        # fleet-scrape staleness (ISSUE 11 satellite): absorb times per
        # replica; dead replicas' /replica{r} gauges are labeled stale
        # inside the window and DROPPED from the merged scrape after it
        self.stale_after_s = float(stale_after_s)
        self._absorb_t: dict[int, float] = {}
        self.registry = registry if registry is not None else MetricsRegistry()
        r = self.registry
        self._m_cases = r.counter("/router/cases")
        self._m_routed = r.counter("/router/routed")  # forwards, requeues incl
        self._m_sharded = r.counter("/router/sharded-cases")
        self._m_picked = r.counter("/router/picked-cases")
        self._m_requeued = r.counter("/router/requeued")
        self._m_deaths = r.counter("/router/deaths")
        self._m_spawns = r.counter("/router/spawns")
        self._m_scale_ups = r.counter("/router/scale-ups")
        self._m_scale_downs = r.counter("/router/scale-downs")
        self._m_replicas = r.gauge("/router/replicas")
        self._m_outstanding = r.gauge("/router/outstanding")
        self._m_max_outstanding = r.gauge("/router/max-outstanding")
        self._m_max_outstanding.set(self.max_outstanding)
        self._m_buckets = r.gauge("/router/buckets")
        self._h_latency = r.histogram("/router/request-latency-ms")
        # the fleet SLO ledger (ISSUE 20, obs/slo.py): promises at
        # submit, outcomes at the result frame.  ``live=False`` — the
        # router never touches a backend (wedge discipline), so only
        # the WORKERS recalibrate rates; their /slo/* metrics ride the
        # stats-frame snapshots absorbed under /replica{r}/slo/*.
        self._slo = obs_slo.SloLedger.from_arg(
            slo, registry=self.registry, clock=clock, live=False)
        # the router's shared state is written from the caller's thread,
        # every per-replica reader thread, and the elastic scale loop;
        # the guarded_by annotations are ENFORCED by graftlint L1
        # (tools/lint/locks.py)
        self._lock = threading.RLock()
        self._replicas: dict[int, _Replica] = {}  # guarded_by: self._lock
        #: every admitted-but-undelivered request, keyed by seq.  The
        #: per-replica ``outstanding`` maps are ROUTING state (who holds
        #: the case now) and go transiently empty while a death's
        #: orphans await re-routing; this map is the delivery ledger —
        #: only a result/error frame (or close) removes a request, so
        #: drain()/admission can never mistake mid-recovery for done.
        self._pending: dict[int, RouterRequest] = {}  # guarded_by: self._lock
        self._owner: dict = {}  # bucket key -> rid; guarded_by: self._lock
        self._next_rid = 0  # guarded_by: self._lock
        self._next_seq = 0  # guarded_by: self._lock
        self._closed = False  # guarded_by: self._lock
        self._telemetry = FleetTelemetry()
        self._policy = BusyRatePolicy(self._telemetry)
        if self._flightrec is not None:
            self._flightrec.bind(registry=self.registry,
                                 inflight=self._inflight_ledger)
        try:
            # transport construction may bind a listener: inside the
            # cleanup scope so a failed fleet boot cannot leak the port
            self._transport = make_transport(transport, token=worker_token)
            for _ in range(replicas):
                self._spawn()
            if self.shard_threshold is not None:
                self._spawn(gang=True)
        except BaseException:
            self.close()
            raise

    # -- worker lifecycle ---------------------------------------------------
    def _spawn(self, gang: bool = False) -> int:
        with self._lock:
            # concurrent spawns are real (a reader thread's respawn
            # racing add_replica): the id draw must be atomic
            rid = self._next_rid
            self._next_rid += 1
        env = dict(os.environ)
        # a router-level fault plan must not leak INTO the workers'
        # pipelines (the die kind is router vocabulary; raise/stall/nan
        # entries would double-inject) — worker-internal chaos goes
        # through serve_kwargs["faults"] deliberately
        env.pop("NLHEAT_FAULT_PLAN", None)
        # a leaked token must not outlive its transport: only the
        # socket transport re-injects it for its own children
        env.pop(WORKER_TOKEN_ENV, None)
        env[REPLICA_ID_ENV] = str(rid)
        env.update(self.child_env)
        handle = self._transport.spawn(rid, env,
                                       timeout_s=self.spawn_timeout_s)
        rep = _Replica(rid, handle, gang=gang)
        affinity = None
        if self.cpus_per_replica and self._host_cpus:
            k, cpus = self.cpus_per_replica, self._host_cpus
            start = (rid * k) % len(cpus)
            affinity = [cpus[(start + j) % len(cpus)] for j in range(k)]
        cfg = {
            "replica_id": rid,
            "platform": self._platform,
            "x64": self._x64,
            "depth": self.depth,
            "window_ms": self.window_ms,
            "window_size": self.window_size,
            "program_store": self.program_store,
            "mesh_dir": self.mesh_dir,
            "serve_kwargs": self.serve_kwargs,
            "engine_kwargs": self.engine_kwargs,
            "cpu_affinity": affinity,
            "trace_dir": self.trace_dir,
            "flight_dir": self.flight_dir,
            "transport": self._transport.name,
        }
        if gang:
            # the sharded-case worker: one N-device mesh, distributed
            # solves, comm='fused' where the kernel family serves it
            cfg["gang"] = {"devices": self.gang_devices,
                           "comm": self.gang_comm}
        with self._lock:
            self._replicas[rid] = rep
            self._m_replicas.set(self.live_count())
        self._m_spawns.inc()
        rep.send(cfg)
        threading.Thread(target=rep._writer, daemon=True,
                         name=f"nlheat-router-writer-{rid}").start()
        threading.Thread(target=self._reader, args=(rep,), daemon=True,
                         name=f"nlheat-router-reader-{rid}").start()
        if not rep.ready.wait(self.spawn_timeout_s):
            rep.closing = True
            handle.kill()
            raise RuntimeError(
                f"replica {rid} did not become ready within "
                f"{self.spawn_timeout_s:.0f}s")
        return rid

    def _reader(self, rep: _Replica) -> None:
        """Per-worker reader thread: parse response frames until EOF,
        then treat the EOF as a death (unless the router stopped the
        worker itself).  ``recv_frame`` returns None for EOF AND for
        any malformed/oversized/truncated length prefix or mid-frame
        disconnect (serve/transport.py) — a socket peer writing garbage
        classifies as replica death, never a router crash or a reader
        thread parked on a half-frame."""
        while True:
            try:
                msg = rep.handle.recv_frame()
            except Exception:  # noqa: BLE001 — torn frame == dead worker
                msg = None
            if msg is None:
                break
            self._on_message(rep, msg)
        self._on_eof(rep)

    def _inflight_ledger(self) -> list:
        """The flight recorder's in-flight snapshot: every undelivered
        case with its current owner (the postmortem's 'who held what'
        answer)."""
        try:
            with self._lock:
                return [{"case": req.seq, "replica": req.replica,
                         "requeues": req.requeues}
                        for req in self._pending.values()]
        except Exception:  # noqa: BLE001 — observability never raises
            return []

    def _on_message(self, rep: _Replica, msg: dict) -> None:
        op = msg.get("op")
        if op == "ready":
            rep.clock_sync = msg.get("clock_sync")
            rep.ready.set()
        elif op == "trace":
            # a pulled fleet-trace dump: deliver to its waiter (same
            # token mechanism as stats, without touching last_stats)
            waiter = rep.stats_waiters.pop(msg.get("id"), None)
            if waiter is not None:
                waiter[1].append(msg)
                waiter[0].set()
        elif op in ("result", "error"):
            with self._lock:
                req = rep.outstanding.get(msg["id"])
                if req is None:  # late frame for a requeued case: the
                    return  # survivor's copy owns delivery (no dupes)
            # assign BEFORE removing from the ledgers: a drain()/waiter
            # that observes the ledger empty must find the result (or
            # error) already in place, never a half-delivered request
            if op == "result":
                req.result = msg["values"]
            else:
                req.error = ServeError(
                    msg.get("classification", "error"), req.seq,
                    msg.get("chunk", -1), msg.get("attempts", 0),
                    msg.get("detail", ""))
            req.latency_s = self._clock() - req.submit_t
            with self._lock:
                rep.outstanding.pop(msg["id"], None)
                self._pending.pop(msg["id"], None)
                self._m_outstanding.set(self.outstanding_total())
            self._h_latency.observe(req.latency_s * 1e3)
            if self._slo is not None:
                # the promise/outcome join: exactly once per case — the
                # delivery ledger above already dropped late frames for
                # re-routed cases, so a duplicate here would be a
                # regression the ledger's /slo/duplicate counter names
                self._slo.resolve(
                    req.seq, latency_s=req.latency_s,
                    error=(None if op == "result"
                           else msg.get("classification", "error")))
            req.done.set()
        elif op == "stats":
            waiter = rep.stats_waiters.pop(msg.get("id"), None)
            rep.last_stats = msg
            if waiter is not None:
                waiter[1].append(msg)
                waiter[0].set()

    def _on_eof(self, rep: _Replica) -> None:
        with self._lock:
            rep.alive = False
            self._m_replicas.set(self.live_count())
        rep.sendq.put(None)  # release the writer thread
        # EOF means exit is imminent; reap the zombie (and close every
        # pipe/socket stream) either way — no fd leaks under chaos
        rep.handle.reap(timeout_s=10)
        with self._lock:
            if rep.closing or self._closed:
                self._replicas.pop(rep.rid, None)
                return
            self._m_deaths.inc()
            orphans = list(rep.outstanding.values())
            rep.outstanding.clear()
            buckets = set(rep.buckets)
            rep.buckets.clear()
            for key in buckets:
                if self._owner.get(key) == rep.rid:
                    del self._owner[key]
            self._telemetry.forget(rep.rid)
            self._replicas.pop(rep.rid, None)  # dead entries never
            # accumulate across a long fleet's chaos history
        print(f"router: replica {rep.rid} died with "
              f"{len(orphans)} case(s) in flight; re-routing",
              file=sys.stderr)
        fr = self._flightrec
        decisions: list = []
        if fr is not None:
            fr.record("replica-death", replica=rep.rid,
                      orphans=[r.seq for r in orphans],
                      buckets_orphaned=len(buckets))
        # release any stats pull blocked on the dead worker
        for token in list(rep.stats_waiters):
            waiter = rep.stats_waiters.pop(token, None)
            if waiter is not None:
                waiter[0].set()
        if rep.gang:
            # the gang replica is the ONLY worker that can serve the
            # sharded case class: respawn it regardless of the small-
            # fleet floor, or its orphans re-route into a refusal
            if self.respawn and self.shard_threshold is not None:
                try:
                    self._spawn(gang=True)
                except Exception as e:  # noqa: BLE001
                    print(f"router: gang respawn after replica "
                          f"{rep.rid} death failed ({e})",
                          file=sys.stderr)
        elif self.respawn and self.live_count() < self.min_replicas:
            try:
                self._spawn()
            except Exception as e:  # noqa: BLE001 — survivors still serve
                print(f"router: respawn after replica {rep.rid} death "
                      f"failed ({e}); continuing with "
                      f"{self.live_count()} replica(s)", file=sys.stderr)
        for req in orphans:
            req.requeues += 1
            self._m_requeued.inc()
            if req.requeues > MAX_REQUEUES:
                # the fleet-level quarantine: a case still in flight
                # after MAX_REQUEUES deaths is treated as the killer
                print(f"router: case {req.seq} survived "
                      f"{MAX_REQUEUES} replica deaths; quarantining",
                      file=sys.stderr)
                with self._lock:
                    self._pending.pop(req.seq, None)
                req.error = ServeError("error", req.seq, -1,
                                       req.requeues,
                                       "re-routed past MAX_REQUEUES "
                                       "(replica-killing case?)")
                if self._slo is not None:
                    self._slo.resolve(
                        req.seq, latency_s=self._clock() - req.submit_t,
                        error="replica-death")
                req.done.set()
                decisions.append({"case": req.seq, "action": "quarantine",
                                  "requeues": req.requeues})
                continue
            try:
                try:
                    self._route(req)
                except RouterOverloaded:
                    # a death cannot lose work to backpressure: the hard
                    # cap bounds CALLER intake, not recovery — force
                    self._route(req, force=True)
                decisions.append({"case": req.seq, "action": "re-route",
                                  "replica": req.replica,
                                  "requeues": req.requeues})
            except Exception as e:  # noqa: BLE001 — e.g. no live
                # replicas after a failed respawn: the request must
                # complete EXCEPTIONALLY, never hang a waiter, and the
                # remaining orphans must still get their turn
                print(f"router: re-route of case {req.seq} failed "
                      f"({e}); completing exceptionally", file=sys.stderr)
                with self._lock:
                    self._pending.pop(req.seq, None)
                req.error = ServeError("error", req.seq, -1, 0,
                                       f"re-route failed: {e}")
                if self._slo is not None:
                    self._slo.resolve(
                        req.seq, latency_s=self._clock() - req.submit_t,
                        error="re-route-failed")
                req.done.set()
                decisions.append({"case": req.seq, "action": "failed",
                                  "detail": str(e)})
        if fr is not None:
            # the black box: killed replica, its in-flight cases, and
            # the re-route decision for each (the ISSUE 11 chaos-run
            # acceptance — a die@ plan must leave this postmortem)
            for d in decisions:
                fr.record("re-route", **d)
            fr.dump("replica-death", replica=rep.rid,
                    orphans=[r.seq for r in orphans],
                    decisions=decisions)

    # -- routing ------------------------------------------------------------
    def live_count(self) -> int:
        """Live SMALL-CASE replicas — the fleet the sticky buckets,
        elastic policy, and min/max floors govern.  The gang replica is
        a different case class and is counted by :meth:`gang_live`."""
        return sum(1 for r in self._replicas.values()
                   if r.alive and not r.gang)

    def gang_live(self) -> int:
        return sum(1 for r in self._replicas.values()
                   if r.alive and r.gang)

    def is_sharded(self, shape) -> bool:
        """Does a grid of ``shape`` belong to the sharded big-case
        class?  2D grids above ``shard_threshold`` POINTS; other ranks
        keep the single-chip path (the distributed gang solver is the
        2D flagship — the reference's own top tier).  PUBLIC because
        the ingress picker gates its candidate axis on the SAME
        predicate (serve/http.py — an fft pick must never route to the
        gang, whose halo-padded blocks the spectral embedding cannot
        serve); one predicate, no drift."""
        if self.shard_threshold is None:
            return False
        try:
            shape = tuple(int(s) for s in shape)
        except (TypeError, ValueError):
            return False
        return (len(shape) == 2
                and int(np.prod(shape)) > self.shard_threshold)

    def _is_sharded(self, case) -> bool:
        return self.is_sharded(getattr(case, "shape", None))

    def sharded_fft_capability(self, shape, eps: int) -> bool:
        """Can the gang serve a SHARDED case of ``shape`` with
        method='fft' (the pencil-decomposed transform,
        ops/spectral_sharded.py)?  The ingress picker reads this to
        decide the candidate axis for gang-bound cases (ISSUE 16 —
        allow_fft stopped being a hardcoded False).  Pure host
        arithmetic: the gang's mesh is predicted with
        ``choose_mesh_shape`` from ``gang_devices``, so the router
        never touches a backend (wedge discipline).  ``gang_devices``
        None means the worker sizes its own mesh from devices the
        router cannot see — the capability is then unknown and the
        answer is the conservative False (the stencil axis always
        serves)."""
        if self.gang_devices is None:
            return False
        try:
            shape = tuple(int(s) for s in shape)
        except (TypeError, ValueError):
            return False
        if len(shape) != 2:
            return False
        from nonlocalheatequation_tpu.ops.spectral_sharded import (
            supports_sharded_fft,
        )
        from nonlocalheatequation_tpu.parallel.distributed2d import (
            choose_mesh_shape,
        )

        mesh_shape = choose_mesh_shape(shape[0], shape[1],
                                       self.gang_devices)
        return supports_sharded_fft(shape, int(eps), mesh_shape)

    def _gang_rep(self) -> _Replica:
        for r in self._replicas.values():
            if r.gang and r.alive:
                return r
        raise RuntimeError(
            "router has no live gang replica for a sharded case")

    def outstanding_total(self) -> int:
        return len(self._pending)

    def retry_after_s(self) -> float:
        """Suggested backoff for a shed request: the observed p50
        request latency (one service time frees one slot), floored so a
        cold fleet never advertises zero."""
        pct = self._h_latency.percentiles()
        return max(0.05, pct.get("p50", 0.0) / 1e3)

    def _pick_replica(self) -> _Replica:
        live = [r for r in self._replicas.values()
                if r.alive and r.ready.is_set() and not r.draining
                and not r.gang]
        if not live:
            live = [r for r in self._replicas.values()
                    if r.alive and not r.gang]
        if not live:
            raise RuntimeError("router has no live replicas")
        return min(live, key=lambda r: (len(r.buckets),
                                        len(r.outstanding), r.rid))

    def submit(self, case: EnsembleCase, *, deadline_ms: float | None = None,
               priority: int = 0, trace=None,
               engine=None, sticky_key=None) -> RouterRequest:
        """Route one case; returns its handle.  Raises
        :class:`RouterOverloaded` when the fleet's bounded in-flight
        budget is exhausted (the ingress tier turns that into 429).
        ``trace`` is the ingress-minted TraceContext; a traced router
        mints one itself for direct (non-HTTP) submissions so the fleet
        timeline still chains every span to a request identity.
        ``engine`` is a picked engine (serve/picker.py
        ``EngineChoice``): it rides the case frame — a pipeline worker
        serves the case from its engine pool, the gang worker threads
        the picked stepper/method through ``solve_case_sharded`` — so
        BOTH case classes honor the pick; None is the fleet default.
        ``sticky_key`` overrides the ROUTING identity (the session
        tier's long-lived placement key, serve/sessions.py); it changes
        which replica owns the case, never what the worker computes."""
        with self._lock:
            if self._closed:
                raise RuntimeError("router is closed")
            req = RouterRequest(case, self._next_seq, self._clock())
            req.deadline_ms = deadline_ms
            req.priority = int(priority)
            if sticky_key is not None:
                req.sticky_key = tuple(sticky_key)
            if engine is not None:
                req.engine = engine
                self._m_picked.inc()
            if trace is not None:
                req.trace = trace if isinstance(trace, TraceContext) \
                    else TraceContext.from_wire(trace)
            elif self._tracer is not None:
                req.trace = TraceContext.mint(request=self._next_seq)
                req.trace_minted = True  # the router IS the trace root
            if req.trace is not None and req.trace.request is None:
                req.trace.request = self._next_seq
            self._next_seq += 1
            self._pending[req.seq] = req
            self._m_cases.inc()
        # the pipe write happens OUTSIDE the router lock (_route's own
        # lock covers only the bookkeeping): a full worker stdin pipe
        # must never block the reader threads' result delivery
        try:
            self._route(req)
        except BaseException:
            # a shed (or any routing failure) must not leak the request
            # in the delivery ledger — a leaked entry would consume
            # in-flight capacity forever and wedge drain()
            with self._lock:
                self._pending.pop(req.seq, None)
            raise
        if self._slo is not None:
            # promise AFTER the route sticks: a shed request (429 at
            # the ingress tier) never becomes an SLO promise, so burn
            # measures promises the fleet actually accepted
            self._slo.promise(req.seq, engine=req.engine,
                              deadline_ms=req.deadline_ms,
                              mesh=getattr(req.case, "mesh", None),
                              t=req.submit_t)
        return req

    def _route(self, req: RouterRequest, force: bool = False) -> None:
        with self._lock:
            cap = self.max_outstanding * max(
                1, self.live_count() + self.gang_live())
            outstanding = self.outstanding_total()
            if outstanding >= cap and not force:
                raise RouterOverloaded(outstanding, cap,
                                       self.retry_after_s())
            if self._is_sharded(req.case):
                # the sharded case class: one space-parallel solve on
                # the gang replica's mesh — no sticky bucket (the gang
                # is a singleton; its solver cache is keyed worker-side)
                rep = self._gang_rep()
                if req.requeues == 0:
                    self._m_sharded.inc()
            else:
                key = (req.sticky_key if req.sticky_key is not None
                       else req.case.bucket_key())
                rid = self._owner.get(key)
                rep = self._replicas.get(rid) if rid is not None else None
                if rep is None or not rep.alive or rep.draining:
                    rep = self._pick_replica()
                    self._owner[key] = rep.rid
                    rep.buckets.add(key)
                    self._m_buckets.set(len(self._owner))
            req.replica = rep.rid
            rep.outstanding[req.seq] = req
            self._m_outstanding.set(self.outstanding_total())
            fired = (self._faults.draw([req.seq])
                     if self._faults is not None else None)
        tr = self._tracer
        if tr is not None and req.trace is not None:
            # the router-dispatch hop of the request's flow chain: one
            # instant + one flow STEP at a single clock read (tracing-on
            # only; the untraced path takes zero extra clock reads)
            now = self._clock()
            tr.instant("router.dispatch", ts=now, cat="router",
                       case=req.seq, replica=rep.rid,
                       requeue=req.requeues, trace=req.trace.trace_id)
            # the flow chain's router hop: a router-minted trace (no
            # ingress) roots the chain HERE ("start"); an ingress-rooted
            # one (or any re-route) continues it ("step")
            phase = ("start" if req.trace_minted and not req._flow_started
                     else "step")
            req._flow_started = True
            tr.flow("request", phase, req.trace.trace_id, ts=now,
                    cat="router", req=req.seq, replica=rep.rid)
        sent = rep.send({"op": "case", "id": req.seq, "case": req.case,
                         "deadline_ms": req.deadline_ms,
                         "priority": req.priority,
                         # the picked engine rides the frame (wire dict,
                         # not the dataclass — frames stay plain data)
                         "engine": (req.engine.wire()
                                    if req.engine is not None else None),
                         "trace": (req.trace.to_wire()
                                   if req.trace is not None else None)})
        self._m_routed.inc()
        if fired is not None and fired.die is not None:
            # the deterministic worker-kill: the __kill__ sentinel rides
            # the same send queue, so the case frame lands first — the
            # case IS in flight on rep when the SIGKILL does, and the
            # reader's EOF re-routes it (utils/faults.py "die")
            print(f"router: fault plan fired {fired.die.describe()} — "
                  f"killing replica {rep.rid}", file=sys.stderr)
            rep.send({"op": "__kill__"})
        elif not sent:
            # the pipe broke under us: the reader's EOF path re-routes
            # this case with everything else that was outstanding there
            pass

    # -- completion ---------------------------------------------------------
    def wait(self, req: RouterRequest,
             timeout: float | None = None) -> np.ndarray:
        return req.wait(timeout)

    def drain(self, timeout_s: float = 600.0) -> None:
        """Block until every outstanding case is delivered (deaths
        re-route, so a draining fleet converges as long as one replica
        can be kept alive)."""
        deadline = self._clock() + timeout_s
        while True:
            with self._lock:
                pending = list(self._pending.values())
            if not pending:
                return
            if self._clock() >= deadline:
                raise TimeoutError(
                    f"router drain: {len(pending)} case(s) still in "
                    f"flight after {timeout_s:.0f}s")
            pending[0].done.wait(timeout=0.2)

    def serve_cases(self, cases) -> list:
        """Submit every case, drain, return results in submission order
        (None for a quarantined case — its handle carries the
        ServeError), the router twin of ``ServePipeline.serve_cases``."""
        handles = [self.submit(c) for c in cases]
        self.drain()
        return [h.result for h in handles]

    # -- elasticity ---------------------------------------------------------
    def add_replica(self) -> int:
        """Scale out by one worker and rebalance bucket ownership toward
        it: the newcomer inherits a fair share of existing buckets from
        the most-loaded owners (ownership is a cache-warmth heuristic,
        never a correctness rule — any replica serves any bucket
        bit-identically), which it warm-boots from the shared program
        store instead of re-tracing."""
        rid = self._spawn()
        with self._lock:
            rep = self._replicas[rid]
            donors = sorted(
                (r for r in self._replicas.values()
                 if r.alive and r.rid != rid and not r.gang),
                key=lambda r: -len(r.buckets))
            want = len(self._owner) // max(1, self.live_count())
            for donor in donors:
                while len(rep.buckets) < want and donor.buckets \
                        and len(donor.buckets) > len(rep.buckets):
                    key = next(iter(donor.buckets))
                    donor.buckets.discard(key)
                    rep.buckets.add(key)
                    self._owner[key] = rid
        return rid

    def drain_replica(self, rid: int, timeout_s: float = 600.0) -> None:
        """Scale in: stop routing NEW work to ``rid``, reassign its
        buckets, let its in-flight cases finish, then stop the worker."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None or not rep.alive:
                return
            if rep.gang:
                raise ValueError(
                    "cannot drain the gang replica: it is the only "
                    "worker serving the sharded case class (set "
                    "shard_threshold=None to retire the tier)")
            if self.live_count() <= 1:
                raise ValueError(
                    "cannot drain the last live replica; add one first")
            rep.draining = True
            for key in list(rep.buckets):
                rep.buckets.discard(key)
                if self._owner.get(key) == rid:
                    del self._owner[key]
        deadline = self._clock() + timeout_s
        while rep.outstanding:
            if self._clock() >= deadline:
                raise TimeoutError(
                    f"replica {rid} still has {len(rep.outstanding)} "
                    f"case(s) in flight after {timeout_s:.0f}s")
            with self._lock:
                pending = next(iter(rep.outstanding.values()), None)
            if pending is not None:
                pending.done.wait(timeout=0.2)
        rep.closing = True
        rep.send({"op": "stop"})
        rep.sendq.put(None)  # writer exits after flushing the stop
        rep.handle.reap(timeout_s=30)
        self._telemetry.forget(rid)
        with self._lock:
            self._m_replicas.set(self.live_count())

    def _pull(self, op: str, timeout_s: float) -> dict:
        """Broadcast one request frame (``stats``/``trace``) to every
        live ready worker and collect the reply frames — the shared
        token/waiter mechanism.  A failed send drops its waiter
        immediately (never left for the death path to sweep).  Returns
        ``{replica_handle: reply_frame}`` for the workers that
        answered within ``timeout_s``."""
        waiters = []
        with self._lock:
            live = [r for r in self._replicas.values()
                    if r.alive and r.ready.is_set()]
        for rep in live:
            with self._lock:
                token = self._next_seq  # shares the seq space: unique
                self._next_seq += 1
            ev, box = threading.Event(), []
            rep.stats_waiters[token] = [ev, box]
            if rep.send({"op": op, "id": token}):
                waiters.append((rep, ev, box))
            else:
                rep.stats_waiters.pop(token, None)
        out = {}
        deadline = self._clock() + timeout_s
        for rep, ev, box in waiters:
            ev.wait(max(0.0, deadline - self._clock()))
            if box:
                out[rep] = box[0]
        return out

    def refresh_stats(self, timeout_s: float = 30.0) -> dict:
        """Pull one stats window from every live worker: per-replica
        metrics/snapshots (absorbed into the router registry under
        ``/replica{r}`` names) and the busy fractions feeding
        :meth:`maybe_scale`.  Returns ``{rid: stats_frame}``."""
        out = {}
        for rep, stats in self._pull("stats", timeout_s).items():
            out[rep.rid] = stats
            if not rep.gang:
                # the gang replica serves a different case class: its
                # busy window must not veto (min-aggregation) or force
                # small-fleet scale decisions
                self._telemetry.record_window(
                    rep.rid, stats.get("busy_s", 0.0),
                    stats.get("span_s", 0.0))
                self.registry.gauge(
                    f"/replica{{{rep.rid}}}/busy-rate").set(
                    round(self._telemetry.rate(rep.rid), 3))
            snap = stats.get("snapshot")
            if snap:
                absorb_snapshot(self.registry, f"/replica{{{rep.rid}}}",
                                snap)
                self._absorb_t[rep.rid] = self._clock()
                self.registry.gauge(
                    f"/replica{{{rep.rid}}}/stale").set(0)
        self._prune_stale_replicas()
        return out

    def _prune_stale_replicas(self) -> None:
        """Fleet-scrape staleness (ISSUE 11 satellite): a dead/drained
        replica's absorbed ``/replica{r}/...`` gauges are point-in-time
        copies that would otherwise linger in the merged ``/metrics``
        scrape forever.  Inside the window the replica is LABELED
        (``/replica{r}/stale`` = 1); past ``stale_after_s`` without a
        fresh absorb its whole namespace is DROPPED."""
        now = self._clock()
        with self._lock:
            live = {r.rid for r in self._replicas.values() if r.alive}
        for rid, t in list(self._absorb_t.items()):
            if rid in live:
                continue
            if now - t >= self.stale_after_s:
                self.registry.drop_prefix(f"/replica{{{rid}}}")
                del self._absorb_t[rid]
            else:
                self.registry.gauge(f"/replica{{{rid}}}/stale").set(1)

    def arm_steady_state(self) -> None:
        """Broadcast the retrace watchdog arm (ISSUE 11 satellite) to
        every live worker: after warm-up a steady-state fleet should
        build ZERO new programs — each worker's ServePipeline counts and
        warns loudly on post-arm ``programs_built`` growth."""
        with self._lock:
            live = [r for r in self._replicas.values()
                    if r.alive and r.ready.is_set()]
        for rep in live:
            rep.send({"op": "arm"})

    def maybe_scale(self) -> str | None:
        """One elastic step: pull stats, run the factored busy-rate
        policy (parallel/elastic.py), actuate.  Returns "add"/"drain"
        when the fleet changed, else None."""
        self.refresh_stats()
        busy = self._policy.window_rates()
        decision = fleet_scale_decision(
            busy, self.live_count(), n_min=self.min_replicas,
            n_max=self.max_replicas)
        if decision == "add":
            self._m_scale_ups.inc()
            self.add_replica()
        elif decision == "drain":
            with self._lock:
                live = [r for r in self._replicas.values()
                        if r.alive and not r.gang]
                # drain the emptiest worker (fewest buckets, then fewest
                # in-flight) — the cheapest ownership reassignment
                victim = min(live, key=lambda r: (len(r.buckets),
                                                  len(r.outstanding)))
            self._m_scale_downs.inc()
            self.drain_replica(victim.rid)
        self._policy.reset()
        return decision

    # -- observability ------------------------------------------------------
    def dump_fleet_trace(self, path: str,
                         timeout_s: float = 30.0) -> dict | None:
        """Pull every live worker's span ring over the frame channel,
        align the per-process clocks (each worker's tracer carries the
        monotonic/wall pair exchanged on its hello frame), and write ONE
        Perfetto-loadable Chrome trace at ``path`` — pid = replica id,
        the router's own spans alongside, request flow events intact
        (obs/trace.py merge_chrome_traces).  Returns a summary dict
        ``{path, processes, events}`` or None when nothing could be
        written (loud, never raises — a failed trace dump must not kill
        the fleet it observed)."""
        try:
            docs = []
            if self._tracer is not None:
                docs.append(self._tracer.chrome_trace())
            for rep, msg in self._pull("trace", timeout_s).items():
                doc = msg.get("doc")
                if not doc:
                    continue
                # clock alignment belt-and-braces: a pulled doc
                # normally carries its tracer's clock_sync; if not,
                # fall back to the pair this worker exchanged on its
                # hello frame (the handshake the merge relies on)
                meta = doc.setdefault("metadata", {})
                if not meta.get("clock_sync") and rep.clock_sync:
                    meta["clock_sync"] = dict(rep.clock_sync)
                docs.append(doc)
            if not docs:
                print("router: dump_fleet_trace found no tracers "
                      "(construct the router with trace_dir=...)",
                      file=sys.stderr)
                return None
            merged = merge_chrome_traces(docs)
            if not write_chrome_trace(merged, path):
                return None
            return {"path": path, "processes": len(docs),
                    "events": len(merged["traceEvents"])}
        except Exception as e:  # noqa: BLE001 — observability never raises
            print(f"router: dump_fleet_trace failed ({e!r})",
                  file=sys.stderr)
            return None

    def metrics(self) -> dict:
        with self._lock:
            live = [r.rid for r in self._replicas.values()
                    if r.alive and not r.gang]
            gang = [r.rid for r in self._replicas.values()
                    if r.alive and r.gang]
            per_replica = {
                r.rid: {"outstanding": len(r.outstanding),
                        "buckets": len(r.buckets), "alive": r.alive,
                        "draining": r.draining, "gang": r.gang}
                for r in self._replicas.values()}
        out = {
            "replicas": len(live),
            "live": live,
            "gang": gang,
            "transport": self._transport.name if self._transport else None,
            "shard_threshold": self.shard_threshold,
            "sharded_cases": self._m_sharded.value,
            "cases": self._m_cases.value,
            "routed": self._m_routed.value,
            "requeued": self._m_requeued.value,
            "deaths": self._m_deaths.value,
            "spawns": self._m_spawns.value,
            "scale_ups": self._m_scale_ups.value,
            "scale_downs": self._m_scale_downs.value,
            "outstanding": self.outstanding_total(),
            "max_outstanding": self.max_outstanding,
            "buckets": len(self._owner),
            "request_latency_ms": self._h_latency.percentiles(),
            "per_replica": per_replica,
        }
        if self._slo is not None:
            out["slo"] = self._slo.summary()
        return out

    def close(self) -> None:
        """Stop the fleet.  Outstanding handles complete exceptionally
        (a closed router must never leave a waiter blocked forever)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            reps = list(self._replicas.values())
        for rep in reps:
            rep.closing = True
            if rep.alive:
                rep.send({"op": "stop"})
            rep.sendq.put(None)  # writer exits after flushing the stop
        for rep in reps:
            rep.handle.reap(timeout_s=30)
            rep.outstanding.clear()
        if self._transport is not None:
            self._transport.close()
        # the delivery ledger: anything still undelivered completes
        # exceptionally — a closed router must never leave a waiter
        # blocked (orphans mid-re-route included)
        with self._lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for req in pending:
            if not req.done.is_set():
                req.error = ServeError("error", req.seq, -1, 0,
                                       "router closed")
                if self._slo is not None:
                    # no open promises left behind: the chaos-consistency
                    # test asserts promised == resolved after close
                    self._slo.resolve(
                        req.seq, latency_s=self._clock() - req.submit_t,
                        error="router-closed")
                req.done.set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def router_load_ab(engine_kwargs: dict, cases, replicas: int,
                   store_dir: str | None, *, window_ms: float = 2.0,
                   overload_factor: float = 2.0,
                   overload_pending: int | None = None,
                   cpus_per_replica: int | None = None,
                   child_env: dict | None = None) -> dict:
    """The fleet measurement shared by bench.py (``BENCH_ROUTER``) and
    tools/bench_table.py (``router`` group): serve the SAME case set
    through a 1-replica and an N-replica router over ONE shared AOT
    store dir (the single-replica arm populates it, the fleet arm
    warm-boots — spawn cost stays honest but compile cost does not
    multiply), then re-offer the cases at ``overload_factor`` x the
    fleet's measured capacity through a tightly-budgeted admission gate
    — the overload-honesty half: the gate must SHED (429-shaped) rather
    than queue without bound, and the accepted requests' p99 must stay
    near the unloaded p99.  Returns the walls, the speedup, both
    arms' results (callers pin bit-identity), and the offered-load
    accounting."""
    from nonlocalheatequation_tpu.serve.http import (
        AdmissionController,
        offered_load_run,
    )

    cases = list(cases)
    if cpus_per_replica is None:
        # the CPU proxy of per-replica hardware: EVERY worker — the
        # 1-replica arm's included — gets the same fixed core budget
        # (one XLA CPU process otherwise spreads over the whole host
        # and the A/B measures intra-op threading, not fleet scale-out)
        try:
            cpus_per_replica = max(
                1, len(os.sched_getaffinity(0)) // max(2, replicas))
        except AttributeError:
            cpus_per_replica = None
    if len({c.bucket_key() for c in cases}) < replicas:
        # sticky routing pins a bucket to ONE replica: a case set with
        # fewer buckets than replicas cannot scale out BY DESIGN, and a
        # silently meaningless A/B must not bank numbers
        raise ValueError(
            f"router A/B needs >= {replicas} distinct buckets (got "
            f"{len({c.bucket_key() for c in cases})}): sticky routing "
            "cannot spread one bucket over the fleet")
    walls: dict[int, float] = {}
    results: dict[int, list] = {}
    unloaded_lat: dict = {}
    arms = [1, replicas] if replicas != 1 else [1]
    for n in arms:
        with ReplicaRouter(replicas=n, program_store=store_dir,
                           window_ms=window_ms, child_env=child_env,
                           cpus_per_replica=cpus_per_replica,
                           **engine_kwargs) as router:
            # pass 1 warms (and, arm 1, populates the shared store);
            # pass 2 is the steady-state wall the speedup and the
            # offered-load capacity are computed from — program
            # compile/load time must not masquerade as serving capacity
            results[n] = router.serve_cases(cases)
            t0 = time.perf_counter()
            router.serve_cases(cases)
            walls[n] = time.perf_counter() - t0
            if n == replicas:
                # pass-2 samples ONLY: pass 1's first-case latencies
                # carry the AOT store loads, and an inflated "unloaded"
                # baseline would flatter the overload p99 comparison
                hist = router.registry.get("/router/request-latency-ms")
                tail = list(hist.samples)[-len(cases):]
                unloaded_lat = {
                    "p50": float(np.percentile(tail, 50)),
                    "p90": float(np.percentile(tail, 90)),
                    "p99": float(np.percentile(tail, 99)),
                }
    # offered-load sweep over ONE admission-gated fleet (programs warm
    # from the store): a rate-based point at overload_factor x the
    # measured capacity, then a burst point (no pacing at all — offered
    # rate >> capacity by construction, so the shed path is exercised
    # deterministically, not only when the capacity estimate is tight)
    capacity_hz = len(cases) / walls[replicas]
    sweep: dict[str, dict] = {}
    with ReplicaRouter(replicas=replicas, program_store=store_dir,
                       window_ms=window_ms, child_env=child_env,
                       cpus_per_replica=cpus_per_replica,
                       **engine_kwargs) as router:
        adm = AdmissionController(
            router,
            max_pending=(overload_pending if overload_pending is not None
                         else max(2, 2 * replicas)))
        for label, rate in ((f"x{overload_factor:g}",
                             overload_factor * capacity_hz),
                            ("burst", 0.0)):
            run = offered_load_run(adm, cases + cases, rate)
            run.pop("results", None)
            run["rate_hz"] = round(rate, 3)
            sweep[label] = run
    return {
        "walls": walls,
        "speedup": walls[1] / walls[replicas],
        "capacity_hz": capacity_hz,
        "results": results,
        "unloaded_latency_ms": {k: round(v, 3)
                                for k, v in unloaded_lat.items()},
        "sweep": sweep,
    }


def router_traced_ab(engine_kwargs: dict, cases, replicas: int,
                     store_dir: str | None, trace_dir: str, *,
                     window_ms: float = 2.0,
                     cpus_per_replica: int | None = None,
                     child_env: dict | None = None) -> dict:
    """The fleet observability A/B shared by bench.py
    (``BENCH_TRACE_FLEET``) and tools/bench_table.py (``routerobs``
    group): serve the SAME case set through two N-replica routers over
    ONE shared AOT store dir — once untraced (TRACE_OFF forced, the
    zero-cost disabled path even under an ambient global tracer: the
    serve_traced_ab discipline at fleet altitude) and once with
    cross-process tracing on (router tracer + per-worker tracers +
    trace frames + flow events).  Each arm runs a warm pass (arm 1
    populates the store; arm 2 warm-boots) then a timed pass, so the
    ratio isolates the tracing cost, not compiles.  The traced arm arms
    the retrace watchdog after its warm pass (a steady-state fleet must
    build zero new programs) and dumps the merged fleet trace.  Returns
    walls, the overhead ratio (the PR 5 gate, now <= 1.05 at fleet
    altitude), both arms' results (callers pin bit-identity), the
    merged-trace summary, and the span count."""
    cases = list(cases)
    if cpus_per_replica is None:
        # the same CPU proxy as router_load_ab: every worker in both
        # arms gets one fixed core budget, so the ratio measures
        # tracing cost, not thread-placement luck
        try:
            cpus_per_replica = max(
                1, len(os.sched_getaffinity(0)) // max(2, replicas))
        except AttributeError:
            cpus_per_replica = None
    walls: dict[str, float] = {}
    results: dict[str, list] = {}
    merged = None
    spans_total = 0
    steady = 0
    for arm in ("untraced", "traced"):
        kw = (dict(trace_dir=trace_dir) if arm == "traced"
              else dict(tracer=obs_trace.TRACE_OFF))
        with ReplicaRouter(replicas=replicas, program_store=store_dir,
                           window_ms=window_ms, child_env=child_env,
                           cpus_per_replica=cpus_per_replica, **kw,
                           **engine_kwargs) as router:
            results[arm] = router.serve_cases(cases)  # warm pass
            if arm == "traced":
                router.arm_steady_state()
            t0 = time.perf_counter()
            router.serve_cases(cases)
            walls[arm] = time.perf_counter() - t0
            if arm == "traced":
                merged = router.dump_fleet_trace(
                    os.path.join(trace_dir, "fleet_trace.json"))
                # the fleet-wide span count: every process's events in
                # the merged timeline (falls back to the router's own
                # ring if the merge could not be written)
                spans_total = (merged["events"] if merged else
                               router._tracer.spans_total
                               if router._tracer is not None else 0)
                # the retrace watchdog's verdict: armed after the warm
                # pass, so a steady-state fleet reports 0 here (a pull
                # absorbs each worker's counter under /replica{r}/...)
                router.refresh_stats()
                steady = 0
                for name in router.registry.names():
                    if name.endswith("/store/steady-state-builds"):
                        steady += int(router.registry.get(name).value)
    return {
        "walls": walls,
        "trace_overhead": walls["traced"] / walls["untraced"],
        "results": results,
        "merged": merged,
        "spans_total": spans_total,
        "steady_state_builds": steady,
    }


def router_slo_ab(engine_kwargs: dict, cases, replicas: int,
                  store_dir: str | None, *, window_ms: float = 2.0,
                  deadline_ms: float = 60_000.0,
                  corrupt_factor: float = 1e3,
                  cpus_per_replica: int | None = None,
                  child_env: dict | None = None) -> dict:
    """The SLO-audit overhead + drift A/B shared by bench.py
    (``BENCH_SLO``) and tools/bench_table.py (``slo`` group) — the
    ISSUE 20 acceptance harness: serve the SAME case set through two
    N-replica routers over ONE shared AOT store dir, once UNAUDITED
    (``slo=False`` router-side, ``NLHEAT_SLO=0`` in every worker: the
    one-attribute-read disabled path) and once AUDITED (fleet ledger on
    the router, per-worker ledgers in every pipeline).  Each arm runs a
    warm pass then a timed pass, so ``slo_overhead`` isolates the
    ledger cost (the <= 1.05 gate, same bar as PR 5/11 tracing).

    Both arms submit each case with an explicit :class:`EngineChoice`
    matching the fleet's default engine (same compute, bit-identical
    results) whose ``est_ms`` is SELF-CALIBRATED from the audited arm's
    warm-pass latencies — the modeled-vs-observed ratio of the clean
    timed pass is ~1 by construction, so the drift detector must stay
    quiet (``drift_fired_clean``).  A third pass re-offers the cases
    with ``est_ms`` divided by ``corrupt_factor`` — an injected
    cost-model corruption the detector MUST flag
    (``drift_fired_corrupt``), the acceptance pair.  ``deadline_ms`` is
    generous: an unloaded fleet's ``deadline_hit_rate`` must read
    1.0."""
    cases = list(cases)
    if cpus_per_replica is None:
        # the same CPU proxy as router_load_ab: every worker in both
        # arms gets one fixed core budget so the ratio measures ledger
        # cost, not thread-placement luck
        try:
            cpus_per_replica = max(
                1, len(os.sched_getaffinity(0)) // max(2, replicas))
        except AttributeError:
            cpus_per_replica = None

    def default_choice(case, est_ms: float) -> EngineChoice:
        # the fleet default engine's settings as an explicit pick: the
        # worker serves it from the same pool entry it would use for an
        # engine-less submission, so the audited/unaudited results stay
        # bit-identical and only the promise metadata differs
        return EngineChoice(
            stepper=str(engine_kwargs.get("stepper", "euler")),
            stages=int(engine_kwargs.get("stages", 0) or 0),
            method=str(engine_kwargs.get("method", "auto")),
            precision=str(engine_kwargs.get("precision", "f32")),
            dt=float(case.dt), steps=int(case.nt),
            est_ms=float(est_ms), est_err=0.0, rates="measured")

    def run_pass(router, scale: float, est: dict) -> float:
        # the submit loop is INSIDE the timed wall: promise() runs at
        # submit, and hiding it outside t0 would flatter the overhead
        t0 = time.perf_counter()
        handles = [router.submit(
            c, deadline_ms=deadline_ms,
            engine=default_choice(c, est[i] * scale))
            for i, c in enumerate(cases)]
        router.drain()
        wall = time.perf_counter() - t0
        for h in handles:
            if h.error is not None:
                raise h.error
        return wall

    walls: dict[str, float] = {}
    results: dict[str, list] = {}
    slo_summary: dict = {}
    drift_clean = drift_corrupt = 0
    for arm in ("unaudited", "audited"):
        audited = arm == "audited"
        env = dict(child_env or {})
        env["NLHEAT_SLO"] = "1" if audited else "0"
        # the workers' own ledgers run for overhead realism, but their
        # drift windows compare DEVICE ms against the e2e-calibrated
        # est_ms this harness injects — not the modeled-vs-observed
        # pair under test.  The router-level detector is the gated
        # surface; park the worker band out of the way.
        env.setdefault("NLHEAT_SLO_BAND", "1e-9,1e9")
        with ReplicaRouter(replicas=replicas, program_store=store_dir,
                           window_ms=window_ms, child_env=env,
                           cpus_per_replica=cpus_per_replica,
                           slo=audited,
                           **engine_kwargs) as router:
            # pass 1 warms (and, arm 1, populates the shared store);
            # pass 2 calibrates the per-case modeled cost from STEADY
            # latencies (warm-pass latencies carry store loads and
            # would skew the clean drift window); pass 3 is the timed
            # wall the overhead ratio reads
            warm = [router.submit(c, deadline_ms=deadline_ms)
                    for c in cases]
            router.drain()
            results[arm] = [h.result for h in warm]
            cal = [router.submit(c, deadline_ms=deadline_ms)
                   for c in cases]
            router.drain()
            est = {i: max(1e-3, (h.latency_s or 0.0) * 1e3)
                   for i, h in enumerate(cal)}
            walls[arm] = run_pass(router, 1.0, est)
            if audited:
                s = router.metrics()["slo"]
                drift_clean = int(s["drift_warnings"])
                slo_summary = s
                # the injected corruption: the same cases promised at
                # est_ms / corrupt_factor — observed/modeled leaves the
                # band and the detector must warn exactly here
                run_pass(router, 1.0 / corrupt_factor, est)
                drift_corrupt = int(
                    router.metrics()["slo"]["drift_warnings"])
    return {
        "walls": walls,
        "slo_overhead": walls["audited"] / walls["unaudited"],
        "results": results,
        "slo": slo_summary,
        "deadline_hit_rate": slo_summary.get("deadline_hit_rate"),
        "drift_fired_clean": drift_clean > 0,
        "drift_fired_corrupt": drift_corrupt > drift_clean,
    }


def fleet_tcp_ab(engine_kwargs: dict, cases, replicas: int,
                 store_dir: str | None, *, shard_cases=(),
                 shard_threshold: int | None = None,
                 gang_devices: int | None = None,
                 window_ms: float = 2.0, overload_factor: float = 2.0,
                 overload_pending: int | None = None,
                 cpus_per_replica: int | None = None,
                 child_env: dict | None = None) -> dict:
    """The fleet-transport measurement shared by bench.py
    (``BENCH_FLEET_TCP``) and tools/bench_table.py (``fleettcp``
    group) — ISSUE 12's two acceptance halves in one harness:

    1. **pipe vs loopback-TCP A/B**: the SAME case set served by an
       N-replica router over in-process pipes and again over the
       socket transport, both arms warm-booting from ONE shared AOT
       store dir (the pipe arm populates it).  ``tcp_overhead`` is the
       steady-pass wall ratio — the per-frame cost of the socket hop,
       with results pinned bit-identical across transports.
    2. **mixed small+sharded offered-load sweep**: a TCP fleet with
       the gang tier enabled serves an interleaved stream of small
       cases (sticky-bucket replicas) and sharded big cases (the gang
       replica's N-device mesh), paced at ``overload_factor`` x the
       measured capacity and then as one burst through the admission
       gate — queues must stay bounded (shed, not grow), sharded
       results must come back bit-identical to the offline
       ``solve_case_sharded`` oracle, and small cases must keep their
       fleet speedup.

    Returns walls, ``tcp_overhead``, both arms' results, the sharded
    oracle comparison, and the sweep accounting."""
    from nonlocalheatequation_tpu.parallel.gang import solve_case_sharded
    from nonlocalheatequation_tpu.serve.http import (
        AdmissionController,
        offered_load_run,
    )

    cases = list(cases)
    shard_cases = list(shard_cases)
    if cpus_per_replica is None:
        # the same CPU proxy as router_load_ab: every worker in both
        # arms gets one fixed core budget so the transport ratio
        # measures framing+wire cost, not thread-placement luck
        try:
            cpus_per_replica = max(
                1, len(os.sched_getaffinity(0)) // max(2, replicas))
        except AttributeError:
            cpus_per_replica = None
    if len({c.bucket_key() for c in cases}) < replicas:
        raise ValueError(
            f"fleet A/B needs >= {replicas} distinct buckets (got "
            f"{len({c.bucket_key() for c in cases})}): sticky routing "
            "cannot spread one bucket over the fleet")
    walls: dict[str, float] = {}
    results: dict[str, list] = {}
    # pipe vs tcp at fleet size, plus a 1-replica TCP arm so the fleet
    # speedup over sockets is MEASURED (the PR 10 acceptance bar must
    # survive the transport change, not be assumed from the pipe A/B);
    # every arm's workers get the same per-replica core budget
    arms = [("pipe", replicas), ("tcp", replicas)]
    if replicas != 1:
        arms.append(("tcp1", 1))
    for arm, n in arms:
        with ReplicaRouter(replicas=n,
                           transport="pipe" if arm == "pipe" else "tcp",
                           program_store=store_dir, window_ms=window_ms,
                           child_env=child_env,
                           cpus_per_replica=cpus_per_replica,
                           **engine_kwargs) as router:
            # pass 1 warms (and, arm pipe, populates the shared store);
            # pass 2 is the steady wall the overhead ratio reads
            results[arm] = router.serve_cases(cases)
            t0 = time.perf_counter()
            router.serve_cases(cases)
            walls[arm] = time.perf_counter() - t0
    out = {
        "walls": walls,
        "tcp_overhead": walls["tcp"] / walls["pipe"],
        "fleet_speedup": walls.get("tcp1", walls["tcp"]) / walls["tcp"],
        "capacity_hz": len(cases) / walls["tcp"],
        "results": results,
    }
    if shard_cases and shard_threshold is None:
        # everything offered as "small" stays small; everything in
        # shard_cases lands above the line
        shard_threshold = max(int(np.prod(c.shape)) for c in cases)
    # the offline sharded oracle: THIS process, same devices/env the
    # gang worker inherits — the bit-identity half of the case class
    ocache: dict = {}
    oracle = [solve_case_sharded(
        c, ndevices=gang_devices, comm="fused",
        method=engine_kwargs.get("method", "auto"),
        precision=engine_kwargs.get("precision", "f32"),
        dtype=engine_kwargs.get("dtype"), solver_cache=ocache)
        for c in shard_cases]
    # interleave sharded cases through the small stream so both case
    # classes are concurrently in flight (the composition under test);
    # with no shard cases the sweep still runs — transport-only mode
    mixed: list = []
    stride = max(1, len(cases) // max(1, len(shard_cases) or 1))
    si = iter(shard_cases)
    for i, c in enumerate(cases):
        mixed.append(c)
        if i % stride == stride - 1:
            mixed.extend([s for s in [next(si, None)] if s is not None])
    mixed.extend(si)
    sweep: dict[str, dict] = {}
    with ReplicaRouter(replicas=replicas, transport="tcp",
                       shard_threshold=(shard_threshold if shard_cases
                                        else None),
                       gang_devices=gang_devices,
                       program_store=store_dir, window_ms=window_ms,
                       child_env=child_env,
                       cpus_per_replica=cpus_per_replica,
                       **engine_kwargs) as router:
        got = router.serve_cases(mixed)  # warm pass + identity capture
        by_case = {id(c): v for c, v in zip(mixed, got, strict=True)}
        small_ok = all(
            by_case[id(c)] is not None
            and np.array_equal(by_case[id(c)], w)
            for c, w in zip(cases, results["tcp"], strict=True))
        shard_ok = all(
            by_case[id(c)] is not None
            and np.array_equal(by_case[id(c)], w)
            for c, (w, _info) in zip(shard_cases, oracle, strict=True))
        if not (small_ok and shard_ok):
            # name the failing HALF: a bare false bit-identity flag is
            # undiagnosable from the one-line JSON
            def _why(v, w):
                if v is None:
                    return "no result"
                return f"max diff {float(np.abs(v - w).max())!r}"

            for i, (c, w) in enumerate(zip(cases, results["tcp"], strict=True)):
                v = by_case[id(c)]
                if v is None or not np.array_equal(v, w):
                    print(f"fleet_tcp_ab: mixed small case {i} deviates "
                          f"from the tcp arm ({_why(v, w)})",
                          file=sys.stderr)
            for i, (c, (w, _)) in enumerate(zip(shard_cases, oracle, strict=True)):
                v = by_case[id(c)]
                if v is None or not np.array_equal(v, w):
                    print(f"fleet_tcp_ab: sharded case {i} deviates "
                          f"from the offline oracle ({_why(v, w)})",
                          file=sys.stderr)
        adm = AdmissionController(
            router,
            max_pending=(overload_pending if overload_pending is not None
                         else max(2, 2 * replicas)))
        rate = overload_factor * out["capacity_hz"]
        for label, r in ((f"x{overload_factor:g}", rate), ("burst", 0.0)):
            run = offered_load_run(adm, mixed + mixed, r)
            run.pop("results", None)
            run["rate_hz"] = round(r, 3)
            sweep[label] = run
        out["sharded_cases"] = router.metrics()["sharded_cases"]
    out["sharded"] = ({
        "cases": len(shard_cases),
        "threshold": shard_threshold,
        "info": oracle[0][1],
        "bit_identical": shard_ok,
    } if shard_cases else None)
    out["mixed_bit_identical"] = small_ok and shard_ok
    out["sweep"] = sweep
    return out


# -- the worker process -------------------------------------------------------


def _gang_loop(cfg: dict, out, poll, eof, tracer, trace_dir,
               ready_frame) -> None:
    """The sharded-case worker loop: each ``case`` frame is ONE whole
    space-parallel distributed solve over this worker's N-device mesh
    (parallel/gang.py ``solve_case_sharded`` — the same adapter the
    offline oracle calls, which is what makes the streamed-back result
    bit-identical to the offline ``Solver2DDistributed`` run).  Solves
    are synchronous — a gang replica is one case at a time by design
    (the mesh IS the parallelism) — so the frame channel drains between
    cases; ``stats``/``trace``/``stop`` frames queued behind a solve
    answer when it retires, inside the router's pull timeouts.  Busy
    accounting (wall time inside solves per stats window) feeds the
    fleet scrape exactly like the pipeline workers', but the router
    keeps gang windows OUT of the small-fleet scale policy."""
    from nonlocalheatequation_tpu.obs.metrics import REGISTRY
    from nonlocalheatequation_tpu.parallel.gang import solve_case_sharded

    gang = cfg.get("gang") or {}
    rid = cfg.get("replica_id")
    ek = cfg.get("engine_kwargs") or {}
    # solves run on a dedicated thread so the frame loop stays LIVE
    # mid-solve: a minutes-long sharded case must not leave the
    # router's stats/trace pulls stalling to their timeouts (the fleet
    # scrape would silently lose the gang on exactly the long cases
    # this tier exists for).  Writes to the frame channel are
    # serialized — two threads interleaving a frame would tear the
    # protocol.
    wlock = threading.Lock()

    def send(frame) -> None:
        with wlock:
            _write_frame(out, frame)

    slock = threading.Lock()  # covers the shared solve accounting
    state = {"served": 0, "busy_s": 0.0, "comm": {}, "solvers": 0,
             "active_t0": None}
    caseq: "queue.Queue" = queue.Queue()
    solver_cache: dict = {}

    def solve_loop() -> None:
        while True:
            msg = caseq.get()
            if msg is None:
                return
            t0 = time.monotonic()
            with slock:
                state["active_t0"] = t0
            ctx = TraceContext.from_wire(msg.get("trace"))
            prev = obs_trace.set_context(ctx)
            try:
                with obs_trace.span("gang.solve", cat="gang",
                                    case=msg.get("id")):
                    # the picked engine (serve/picker.py) overrides the
                    # fleet defaults per case — the sharded class honors
                    # the pick too (ISSUE 13), including fft/expo picks
                    # since ISSUE 16: solve_case_sharded serves them on
                    # the pencil-decomposed spectral tier (a fused-comm
                    # gang falls back to the collective transposes via
                    # its ValueError fallback, recorded in info)
                    pe = msg.get("engine") or {}
                    values, info = solve_case_sharded(
                        msg["case"],
                        ndevices=gang.get("devices"),
                        comm=gang.get("comm", "fused"),
                        method=pe.get("method",
                                      ek.get("method", "auto")),
                        precision=pe.get("precision",
                                         ek.get("precision", "f32")),
                        dtype=ek.get("dtype"),
                        stepper=pe.get("stepper",
                                       ek.get("stepper", "euler")),
                        stages=int(pe.get("stages",
                                          ek.get("stages", 0) or 0)),
                        solver_cache=solver_cache)
                with slock:
                    state["served"] += 1
                    state["comm"][info["comm"]] = \
                        state["comm"].get(info["comm"], 0) + 1
                    state["solvers"] = len(solver_cache)
                send({"op": "result", "id": msg["id"],
                      "values": values, "sharded": info})
            except Exception as e:  # noqa: BLE001 — an unservable
                # sharded case completes EXCEPTIONALLY, never kills
                # the gang worker (the fleet's only big-case server)
                try:
                    send({"op": "error", "id": msg["id"],
                          "classification": "error", "chunk": -1,
                          "attempts": 0,
                          "detail": f"sharded solve refused: "
                                    f"{type(e).__name__}: {e}"})
                except (OSError, ValueError):
                    return  # channel gone: the router owns recovery
            finally:
                obs_trace.set_context(prev)
                with slock:
                    state["busy_s"] += time.monotonic() - t0
                    state["active_t0"] = None

    solver = threading.Thread(target=solve_loop, daemon=True,
                              name="nlheat-gang-solver")
    solver.start()
    window_t0 = time.monotonic()
    send(ready_frame())
    stopping = False
    while not stopping:
        for msg in poll(0.05):
            op = msg.get("op")
            if op == "case":
                caseq.put(msg)  # one solve at a time, frame loop live
            elif op == "stats":
                now = time.monotonic()
                with slock:
                    busy_s = state["busy_s"]
                    state["busy_s"] = 0.0
                    if state["active_t0"] is not None:
                        # credit the IN-FLIGHT solve's window share: a
                        # gang mid-long-case must read busy, not idle
                        # (a boundary-spanning solve can double-count
                        # its pre-window slice; the telemetry clamps
                        # busy/span at 1 and the gang is excluded from
                        # the scale policy — observability-grade)
                        busy_s += now - max(window_t0,
                                            state["active_t0"])
                    metrics = {"cases": state["served"], "gang": True,
                               "devices": gang.get("devices"),
                               "comm": dict(state["comm"]),
                               "solvers": state["solvers"]}
                send({
                    "op": "stats", "id": msg.get("id"), "replica": rid,
                    "pid": os.getpid(), "gang": True,
                    "metrics": metrics,
                    # the gang's halo traffic lands in the process
                    # registry (/halo/bytes, /halo/exchanges) — absorbed
                    # under /replica{r} like the pipeline workers'
                    "snapshot": REGISTRY.snapshot(),
                    "busy_s": busy_s, "span_s": now - window_t0,
                })
                window_t0 = now
            elif op == "trace":
                send({
                    "op": "trace", "id": msg.get("id"), "replica": rid,
                    "doc": (tracer.chrome_trace() if tracer is not None
                            else None)})
            elif op == "stop":
                stopping = True
        if eof():
            stopping = True
    # drain: finish (and deliver) every accepted case before the bye —
    # the gang twin of the pipe worker's pipe.drain() at stop
    caseq.put(None)
    solver.join()
    if tracer is not None and trace_dir:
        tracer.write(os.path.join(trace_dir,
                                  f"host_trace.replica{rid}.json"))
    try:
        send({"op": "bye"})
    except OSError:
        pass


def _worker_main(connect: str | None = None) -> None:
    """The replica worker: one ServePipeline fed by framed stdin — or,
    with ``connect="host:port"`` (the ``--worker-connect`` CLI form), by
    a TCP socket it DIALS into the router's transport listener, sending
    a JSON hello (replica id + ``NLHEAT_WORKER_TOKEN``) before the
    first pickle frame (serve/transport.py trust boundary).

    Pipe mode steals fd 1 (stray prints from any library go to stderr;
    the frame channel is the ORIGINAL stdout, held privately); socket
    mode leaves stdio alone — the frame channel is the socket and
    prints cannot tear it.  Either way the worker applies the router's
    platform/x64 config before any backend touch, points
    ``NLHEAT_PROGRAM_STORE`` at the shared store, then loops: poll the
    frame fd, submit arriving cases, pump the pipeline, and — whenever
    the intake is momentarily idle with work outstanding — drain, so
    results flow without the caller-driven fences the in-process
    pipeline relies on.  The loop accounts its busy wall (time inside
    pump/drain with work outstanding) per stats window; the router
    turns that into the fleet's busy rates.  A ``gang`` config block
    switches the worker to the sharded-case loop instead
    (:func:`_gang_loop`)."""
    sock = None
    if connect is None:
        out = os.fdopen(os.dup(1), "wb")
        os.dup2(2, 1)
        fd = sys.stdin.fileno()
    else:
        import socket as _socket

        host, _, port = connect.rpartition(":")
        sock = _socket.create_connection((host or "127.0.0.1", int(port)))
        out = sock.makefile("wb")
        fd = sock.fileno()
        rid_env = os.environ.get(REPLICA_ID_ENV)
        write_json_frame(out, {
            "op": "hello",
            "replica": int(rid_env) if rid_env else None,
            "token": os.environ.get(WORKER_TOKEN_ENV)})
    # all frame-channel reads go through ONE raw-fd buffer: a
    # BufferedReader's read-ahead on the config frame could swallow the
    # front of the next frame and tear the protocol
    buf = bytearray()
    eof = False

    def read_blocking_frame():
        nonlocal eof
        while True:
            while len(buf) >= _LEN.size:
                n = _LEN.unpack(bytes(buf[:_LEN.size]))[0]
                if n > MAX_FRAME_BYTES:
                    eof = True  # a lying prefix: die cleanly, never
                    return None  # allocate the lie
                if len(buf) < _LEN.size + n:
                    break
                payload = bytes(buf[_LEN.size:_LEN.size + n])
                del buf[:_LEN.size + n]
                return pickle.loads(payload)
            if eof:
                return None
            chunk = os.read(fd, 1 << 16)
            if not chunk:
                eof = True
            else:
                buf.extend(chunk)

    cfg = read_blocking_frame()
    if cfg is None:
        return
    if cfg.get("cpu_affinity"):
        try:
            # before the backend exists, so every XLA/Eigen pool thread
            # inherits the budget (threads created later inherit the
            # process affinity)
            os.sched_setaffinity(0, set(cfg["cpu_affinity"]))
        except (AttributeError, OSError) as e:
            print(f"replica {cfg.get('replica_id')}: cpu affinity "
                  f"{cfg['cpu_affinity']} not applied ({e})",
                  file=sys.stderr)
    import jax

    if cfg.get("platform"):
        jax.config.update("jax_platforms", cfg["platform"])
    if cfg.get("x64") is not None:
        jax.config.update("jax_enable_x64", bool(cfg["x64"]))
    store = cfg.get("program_store")
    if store is not None:
        os.environ["NLHEAT_PROGRAM_STORE"] = str(store)
    mesh_dir = cfg.get("mesh_dir")
    if mesh_dir is not None:
        os.environ["NLHEAT_MESH_DIR"] = str(mesh_dir)
    rid = cfg.get("replica_id")
    # fleet tracing: a traced router hands every worker a trace_dir —
    # install the process-global tracer (so pipeline/ensemble/store
    # spans all record) before the pipeline constructs; the ring is
    # written per-replica at exit and pulled live by the "trace" op
    tracer = None
    trace_dir = cfg.get("trace_dir")
    if trace_dir:
        tracer = obs_trace.Tracer(label=f"replica {rid}", replica=rid)
        obs_trace.set_tracer(tracer)
    # crash flight recorder: per-worker black box (quarantines, breaker
    # opens, SIGTERM all dump; SIGKILL death is the ROUTER's dump)
    flight_dir = cfg.get("flight_dir")
    if flight_dir:
        rec = flightrec.FlightRecorder(flight_dir, replica=rid)
        flightrec.set_recorder(rec)
        flightrec.install_sigterm(rec)
    def poll(timeout: float) -> list:
        """Read every frame currently available (waiting up to
        ``timeout`` for the first byte)."""
        nonlocal eof
        frames = []
        wait = timeout
        while not eof:
            r, _, _ = select.select([fd], [], [], wait)
            if not r:
                break
            chunk = os.read(fd, 1 << 16)
            if not chunk:
                eof = True
                break
            buf.extend(chunk)
            wait = 0.0
        while len(buf) >= _LEN.size:
            n = _LEN.unpack(bytes(buf[:_LEN.size]))[0]
            if n > MAX_FRAME_BYTES:
                eof = True  # lying prefix: die cleanly (the router
                break  # classifies the EOF as a death)
            if len(buf) < _LEN.size + n:
                break
            payload = bytes(buf[_LEN.size:_LEN.size + n])
            del buf[:_LEN.size + n]
            frames.append(pickle.loads(payload))
        return frames

    def ready_frame() -> dict:
        return {"op": "ready", "replica": rid,
                # the clock-offset handshake: this worker's
                # (monotonic, wall) pair, matching its tracer's
                # span timestamps — the router merges on it
                "clock_sync": (tracer.clock_sync if tracer
                               is not None else
                               {"monotonic": time.monotonic(),
                                "wall": time.time()})}

    if cfg.get("gang"):
        # the sharded-case worker: no ServePipeline — one N-device
        # mesh, whole distributed solves per case frame
        _gang_loop(cfg, out, poll, lambda: eof, tracer, trace_dir,
                   ready_frame)
        return
    from nonlocalheatequation_tpu.serve.server import ServePipeline

    pipe = ServePipeline(depth=cfg.get("depth", 1),
                         window_ms=cfg.get("window_ms", 2.0),
                         window_size=cfg.get("window_size"),
                         **cfg.get("serve_kwargs") or {},
                         **cfg.get("engine_kwargs") or {})
    _write_frame(out, ready_frame())

    outstanding: dict[int, object] = {}
    busy_s = 0.0
    window_t0 = time.monotonic()

    def flush_done() -> None:
        for rid_, h in list(outstanding.items()):
            if h.result is not None:
                _write_frame(out, {"op": "result", "id": rid_,
                                   "values": h.result})
            elif h.error is not None:
                e = h.error
                _write_frame(out, {
                    "op": "error", "id": rid_,
                    "classification": e.classification,
                    "chunk": e.chunk_id, "attempts": e.attempts,
                    "detail": str(e)})
            else:
                continue
            del outstanding[rid_]

    stopping = False
    while not stopping:
        frames = poll(0.002 if outstanding else 0.05)
        got_case = False
        for msg in frames:
            op = msg.get("op")
            if op == "case":
                try:
                    h = pipe.submit(msg["case"],
                                    deadline_ms=msg.get("deadline_ms"),
                                    priority=msg.get("priority") or 0,
                                    trace=TraceContext.from_wire(
                                        msg.get("trace")),
                                    # picked engine (serve/picker.py):
                                    # served from the pipeline's pool
                                    engine=EngineChoice.from_wire(
                                        msg.get("engine")))
                except Exception as e:  # noqa: BLE001 — a malformed
                    # case must complete EXCEPTIONALLY, not kill the
                    # worker (a poison frame would otherwise crash-loop
                    # the fleet through death -> re-route -> death)
                    _write_frame(out, {
                        "op": "error", "id": msg["id"],
                        "classification": "error", "chunk": -1,
                        "attempts": 0,
                        "detail": f"submit refused: "
                                  f"{type(e).__name__}: {e}"})
                    continue
                outstanding[msg["id"]] = h
                got_case = True
            elif op == "stats":
                now = time.monotonic()
                _write_frame(out, {
                    "op": "stats", "id": msg.get("id"),
                    "replica": cfg.get("replica_id"),
                    "pid": os.getpid(),
                    "metrics": pipe.metrics(),
                    "snapshot": pipe.registry.snapshot(),
                    "busy_s": busy_s,
                    "span_s": now - window_t0,
                })
                busy_s = 0.0
                window_t0 = now
            elif op == "trace":
                # the fleet-trace pull: ship this worker's span ring
                # (with its clock_sync metadata) back over the frame
                # channel for the router's merge
                _write_frame(out, {
                    "op": "trace", "id": msg.get("id"), "replica": rid,
                    "doc": (tracer.chrome_trace() if tracer is not None
                            else None)})
            elif op == "arm":
                pipe.arm_steady_state()
            elif op == "stop":
                stopping = True
        if eof:
            stopping = True
        if stopping:
            break
        t0 = time.monotonic()
        pipe.pump()
        if outstanding and not got_case and not buf:
            # intake momentarily idle with work queued: flush partial
            # windows and fence in-flight chunks so results ship now —
            # the worker-side stand-in for the in-process caller's
            # wait()/drain() fences
            pipe.drain()
        if outstanding:
            busy_s += time.monotonic() - t0
        flush_done()
    try:
        pipe.drain()
        flush_done()
        pipe.close()
    except Exception:  # noqa: BLE001 — dying cleanly beats a stack trace
        pass
    if tracer is not None and trace_dir:
        # the per-replica trace artifact (NLHEAT_REPLICA_ID in the
        # path): loadable standalone, or merged by tools/trace_merge.py
        tracer.write(os.path.join(trace_dir,
                                  f"host_trace.replica{rid}.json"))
    try:
        _write_frame(out, {"op": "bye"})
    except OSError:
        pass


if __name__ == "__main__":
    import argparse

    _ap = argparse.ArgumentParser(
        description="replica worker child (started by ReplicaRouter; "
                    "--worker-connect dials a SocketTransport listener "
                    "instead of speaking frames over stdin/stdout)")
    _ap.add_argument(
        "--worker-connect", default=None, metavar="HOST:PORT",
        help="dial the router's socket transport at HOST:PORT, send the "
             "JSON hello (replica id from NLHEAT_REPLICA_ID, token from "
             "NLHEAT_WORKER_TOKEN), then serve the identical frames the "
             "pipe workers speak")
    _worker_main(connect=_ap.parse_args().worker_connect)
