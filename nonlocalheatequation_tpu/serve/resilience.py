"""Fault-tolerance primitives for the serving pipeline.

The reference rides an HPX runtime that keeps work flowing around
imbalance (SURVEY.md section 0); our production path instead crosses the
flaky axon tunnel, where the failure modes are a dispatch that raises,
a fetch that never returns, and a buffer that comes back corrupted
(docs/bench/README.md "Wedge trigger").  bench.py survives all three via
its subprocess ladder + watchdog + CPU fallback; this module gives the
REQUEST path (serve/server.py) the same three answers, in-process:

* :class:`ServeError` — the typed exception a poisoned request's
  ``wait()`` raises, carrying the fault classification
  ("error" / "hang" / "corrupt"), the case seq, and the attempt count.
* :class:`CircuitBreaker` — the health state machine: ``closed`` ->
  ``open`` after K consecutive device-path failures -> ``half-open``
  probe once a cooldown elapses -> ``closed`` again on probe success
  (or straight back to ``open`` on probe failure).  While open, the
  pipeline routes chunks through the CPU fallback below — the serving
  analogue of bench.py's BENCH_ALLOW_CPU_FALLBACK ladder.  The clock is
  injectable, so the chaos suite drives every transition with a virtual
  timer.
* :class:`CpuFallback` — an equivalent CPU-backend chunk runner reusing
  the engine's stage split (pad/build/stage/dispatch): a sibling
  :class:`~nonlocalheatequation_tpu.serve.ensemble.EnsembleEngine` per
  bucket dimensionality, pinned to the XLA CPU lowering of the same
  operator (conv for 2D, sat for 3D — `_auto_method_*`'s own off-TPU
  picks; an explicit XLA method is kept verbatim), executing under
  ``jax.default_device(cpu)``.  Results are oracle-close by the
  accuracy contract; when the engine's method is an XLA method
  available on both backends (the chaos suite pins one) they are
  bit-identical to the device path, which is how the CPU chaos suite
  asserts exactness end to end.

Threading note: like the pipeline itself, everything here runs on the
scheduler thread; the only thread ever created is the supervisor's
fetch watchdog (serve/server.py), and no JAX client is ever killed —
a genuinely hung fetch is ABANDONED (daemon thread), exactly the
wedge discipline bench.py follows with its killable probe children.
"""

from __future__ import annotations

import time
from collections import deque

import numpy as np

from nonlocalheatequation_tpu.utils.devices import device_list

#: Fault classifications the supervisor assigns to a failed attempt.
CLASS_ERROR = "error"  # dispatch/fetch raised
CLASS_HANG = "hang"  # fetch missed its deadline
CLASS_CORRUPT = "corrupt"  # fetched buffer failed the finite scan

#: Breaker states.
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

#: Bound on the retained transition trail (mirrors server.LOG_CAP, which
#: cannot be imported here — server.py imports this module).  A breaker
#: flapping open/half-open/open against a persistently dead device makes
#: one transition pair per cooldown forever; the metrics dump keeps the
#: most recent window plus a lifetime-exact ``transition_count``.
TRANSITION_CAP = 4096


class ServeError(RuntimeError):
    """A request that completed exceptionally: its case was isolated as
    the poison member of a failing chunk (or failed alone) after the
    retry budget.  ``classification`` is one of CLASS_ERROR/HANG/CORRUPT;
    ``detail`` carries the last underlying exception's text, if any."""

    def __init__(self, classification: str, case_seq: int, chunk_id: int,
                 attempts: int, detail: str = ""):
        msg = (f"case {case_seq} quarantined after {attempts} attempts "
               f"(chunk {chunk_id}, classified {classification!r}")
        if detail:
            msg += f": {detail}"
        super().__init__(msg + ")")
        self.classification = classification
        self.case_seq = case_seq
        self.chunk_id = chunk_id
        self.attempts = attempts
        self.detail = detail


class CircuitBreaker:
    """closed -> open on K consecutive device-path failures -> half-open
    probe after ``cooldown_ms`` -> closed on probe success.

    ``route()`` answers "device" or "fallback" for the NEXT chunk
    execution; in half-open exactly ONE probe is routed to the device
    (others keep the fallback until the probe's outcome lands — the
    pipeline may have several chunks in motion between a probe's
    dispatch and its retire).  When the device route IS the probe,
    ``routed_probe`` is True until the next ``route()`` call — the
    caller tags that chunk and passes ``probe=`` back to the outcome
    recorders, so a STALE device chunk (dispatched before the breaker
    opened, retiring while half-open) can never settle the probe for
    it.  ``transitions`` is the timestamped audit trail
    ServeReport.metrics() surfaces — the most recent
    :data:`TRANSITION_CAP` entries; ``transition_count`` is
    lifetime-exact.
    """

    def __init__(self, threshold: int = 3, cooldown_ms: float = 5000.0,
                 clock=time.monotonic):
        threshold = int(threshold)
        if threshold < 1:
            raise ValueError(f"breaker threshold must be >= 1, got "
                             f"{threshold}")
        if cooldown_ms < 0:
            raise ValueError(f"breaker cooldown_ms must be >= 0, got "
                             f"{cooldown_ms}")
        self.threshold = threshold
        self.cooldown_s = cooldown_ms / 1e3
        self._clock = clock
        self.state = CLOSED
        self.failures = 0  # consecutive device-path failures
        self.opened_t: float | None = None
        self.probe_inflight = False
        self.routed_probe = False  # last route() handed out the probe
        self.transitions: deque = deque(maxlen=TRANSITION_CAP)
        self.transition_count = 0  # lifetime-exact
        #: Optional ``(from_state, to_state, t)`` callback the serving
        #: pipeline installs to mirror transitions into the obs
        #: subsystem (registry counter, trace instant, event log).
        #: Exceptions are swallowed — observability never fails a route.
        self.on_transition = None

    def _move(self, to: str) -> None:
        frm = self.state
        t = self._clock()
        self.transitions.append({"t": t, "from": frm, "to": to})
        self.transition_count += 1
        self.state = to
        cb = self.on_transition
        if cb is not None:
            try:
                cb(frm, to, t)
            except Exception:  # noqa: BLE001 — observability never raises
                pass

    def route(self) -> str:
        self.routed_probe = False
        if self.state == CLOSED:
            return "device"
        if self.state == OPEN:
            if self._clock() >= self.opened_t + self.cooldown_s:
                self._move(HALF_OPEN)
                self.probe_inflight = True
                self.routed_probe = True
                return "device"  # the probe
            return "fallback"
        # half-open: one probe at a time
        if not self.probe_inflight:
            self.probe_inflight = True
            self.routed_probe = True
            return "device"
        return "fallback"

    def record_success(self, probe: bool = True) -> None:
        """A device-path attempt completed ok.  ``probe=False`` marks a
        stale chunk's outcome (device-routed before the breaker opened):
        it clears the failure streak but never settles a half-open
        probe."""
        self.failures = 0
        if self.state == HALF_OPEN and probe:
            self.probe_inflight = False
            self._move(CLOSED)

    def record_failure(self, probe: bool = True) -> None:
        """A device-path attempt failed in a way that attests to device
        ill-health (the pipeline reports error/hang here; corrupt is
        data-shaped and never reaches the breaker).  ``probe=False``
        marks a stale chunk's outcome: it feeds the failure streak but
        only the probe's own failure re-opens a half-open breaker."""
        self.failures += 1
        if self.state == HALF_OPEN:
            if probe:
                self.probe_inflight = False
                self.opened_t = self._clock()
                self._move(OPEN)
        elif self.state == CLOSED and self.failures >= self.threshold:
            self.opened_t = self._clock()
            self._move(OPEN)


class CpuFallback:
    """Run a padded chunk on the CPU backend via the engine's own stage
    split.  Built lazily by the pipeline (the happy path never pays for
    it); keeps its own per-method sibling engines so fallback program
    caches never collide with the device engine's."""

    #: `_auto_method_{2,3}d`'s off-TPU picks (ops/nonlocal_op.py): the
    #: fast XLA CPU lowering per dimensionality.  Pallas and "auto" must
    #: not leak into the fallback — under an ambient TPU backend "auto"
    #: resolves to the Mosaic kernel, which cannot execute on CPU.  fft
    #: is an XLA lowering too (and the only method an expo-stepper
    #: engine can run at all), so it passes through unchanged.
    _SAFE = {2: "conv", 3: "sat"}
    _XLA_METHODS = ("conv", "shift", "sat", "fft")

    def __init__(self, engine):
        self.engine = engine
        self._engines: dict = {}
        self._device = None

    def _cpu_device(self):
        if self._device is None:
            self._device = device_list("cpu")[0]
        return self._device

    def _sibling(self, dim: int):
        e = self.engine
        method = (e.method if e.method in self._XLA_METHODS
                  else self._SAFE.get(dim, "auto"))
        sib = self._engines.get(method)
        if sib is None:
            # variant pinned to "auto": the carried/superstep pallas
            # schedules cannot engage off-TPU and would refuse; auto
            # resolves to the vmap/stacked XLA compositions here.  comm
            # pinned to "collective" for the same reason — the fused
            # halo engine is pallas-only and a CPU fallback chunk runs
            # unsharded anyway.  store_backend pinned to "cpu": the
            # sibling SHARES the device engine's AOT program store
            # (serve/program_store.py — one namespace), and the backend
            # in the key is what keeps a CPU-compiled fallback program
            # from ever colliding with (or being served as) the device
            # engine's program for the same bucket
            sib = self._engines[method] = e.sibling(method=method,
                                                    variant="auto",
                                                    comm="collective",
                                                    store_backend="cpu")
        return sib

    def run_chunk(self, key, padded) -> np.ndarray:
        """Build + stage + dispatch + fetch the chunk on CPU.  The fetch
        IS the fence here (np.asarray of a CPU buffer), so a fallback
        chunk completes synchronously — there is nothing to overlap and
        nothing that can wedge."""
        import jax

        sib = self._sibling(len(key[0]))
        with jax.default_device(self._cpu_device()):
            multi = sib.build_program(key, padded)
            U0 = sib.stage_inputs(padded)
            return np.asarray(sib.dispatch_chunk(multi, U0))
