"""Mesh registry: content-hashed point clouds as a serving dimension.

ISSUE 17 tentpole (b)/(c): the realistic unstructured traffic shape is
many users, FEW meshes — a mesh is uploaded once (``POST /v1/meshes``,
serve/http.py), content-hashed, persisted under the mesh dir, and every
case referencing the hash warm-boots the compiled gather program from
the shared AOT store (the hash joins ``EnsembleCase.bucket_key`` and
through it the engine's ``prog_key``/``store_key``, serve/ensemble.py).

The hash covers exactly what the compiled program bakes: the node
coordinates, the per-point horizon field, AND the derived edge table
(build_edges is deterministic, but hashing its output means a builder
change can never silently serve a stale stored executable against a
different sparsity pattern — the same honesty rule as the program
store's trace-env knobs).

Trust boundary: like serve/program_store.py, the mesh dir is treated as
private state (0700); payload validation happens at the front door
(:func:`validate_mesh` — bounds, finiteness, dtype) so a malformed or
oversized upload is a loud 400, never a worker crash.

``partition_coarse_grid`` hook (utils/decompose.py): sharded meshes
need spatially-compact contiguous index blocks (ShardedUnstructuredOp
partitions by index), so :func:`gang_order` reorders nodes by the
refined RCB cuts of a coarse tile grid — the reference's decomposition
recipe (src/domain_decomposition.cpp:52-195) feeding gang placement.
"""

from __future__ import annotations

import hashlib
import os

import numpy as np

from nonlocalheatequation_tpu.utils.checkpoint import atomic_file

#: Env knob: the mesh directory.  ""/"0" = registry off, "1" = the
#: per-user default, anything else = an explicit directory.
MESH_DIR_ENV = "NLHEAT_MESH_DIR"

DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "nlheat", "meshes")

#: Upload bounds (validate_mesh / the HTTP front door): node count and
#: request size.  Overridable by env for soak rigs, never per-request.
MAX_NODES = 4_000_000
MAX_BODY_BYTES = 256 << 20


def mesh_dir_from_env() -> str | None:
    """The configured mesh directory, or None when the registry is off
    (unset/empty/``0``); ``1`` selects :data:`DEFAULT_DIR` — the
    program store's env vocabulary."""
    raw = os.environ.get(MESH_DIR_ENV, "")
    if raw in ("", "0"):
        return None
    if raw == "1":
        return DEFAULT_DIR
    return raw


def max_nodes() -> int:
    return int(os.environ.get("NLHEAT_MESH_MAX_NODES") or MAX_NODES)


class UnknownMesh(KeyError):
    """A referenced mesh hash is not in the registry — the HTTP layer's
    404 (a malformed hash is a ValueError/400 instead)."""

    def __str__(self) -> str:  # KeyError repr-quotes its arg; keep the
        return self.args[0] if self.args else ""  # message readable


def validate_mesh(points, eps, vol=None):
    """Normalize + validate an uploaded mesh; returns ``(points, eps,
    vol)`` as f64 arrays.  Raises ``ValueError`` with a one-line reason
    on anything malformed — the HTTP layer maps it to a 400."""
    points = np.asarray(points, np.float64)
    if points.ndim != 2:
        raise ValueError(
            f"mesh points must be 2-D (n, d), got shape {points.shape}")
    n, d = points.shape
    if not 1 <= d <= 3:
        raise ValueError(f"mesh dimension must be 1..3, got {d}")
    if n < 2:
        raise ValueError(f"mesh needs at least 2 nodes, got {n}")
    if n > max_nodes():
        raise ValueError(
            f"mesh has {n} nodes, over the {max_nodes()} cap "
            "(NLHEAT_MESH_MAX_NODES)")
    if not np.all(np.isfinite(points)):
        raise ValueError("mesh points contain non-finite values")
    eps = np.broadcast_to(np.asarray(eps, np.float64), (n,)).copy()
    if not np.all(np.isfinite(eps)) or not np.all(eps > 0):
        raise ValueError("eps field must be finite and > 0 everywhere")
    if vol is None:
        vol = np.ones(n)
    vol = np.broadcast_to(np.asarray(vol, np.float64), (n,)).copy()
    if not np.all(np.isfinite(vol)) or not np.all(vol > 0):
        raise ValueError("vol field must be finite and > 0 everywhere")
    return points, eps, vol


def mesh_hash(points, eps, tgt, src) -> str:
    """Content hash of (points, eps-field, edge table): the engine-key
    dimension.  sha256 over shapes + raw f64/int32 bytes, truncated to
    16 hex chars (the program store's digest discipline)."""
    h = hashlib.sha256()
    for a in (np.ascontiguousarray(points, np.float64),
              np.ascontiguousarray(eps, np.float64)):
        h.update(repr((a.shape, a.dtype.str)).encode())
        h.update(a.tobytes())
    for a in (np.ascontiguousarray(tgt, np.int32),
              np.ascontiguousarray(src, np.int32)):
        h.update(repr((a.shape, a.dtype.str)).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


class MeshStore:
    """Dir-backed registry of validated meshes, keyed by content hash."""

    def __init__(self, root: str):
        self.root = root

    def _path(self, mhash: str) -> str:
        if not mhash or any(c not in "0123456789abcdef" for c in mhash):
            # hashes come off the wire: a traversal-shaped "hash" must
            # die here, not resolve to a path outside the dir
            raise ValueError(f"malformed mesh hash {mhash!r}")
        return os.path.join(self.root, f"{mhash}.npz")

    def put(self, points, eps, vol=None) -> str:
        """Validate, hash, persist; returns the content hash.  Repeat
        uploads of the same content are idempotent (same hash, the
        existing file wins)."""
        points, eps, vol = validate_mesh(points, eps, vol)
        from nonlocalheatequation_tpu.ops.unstructured import build_edges

        tgt, src = build_edges(points, eps)
        mhash = mesh_hash(points, eps, tgt, src)
        path = self._path(mhash)
        if not os.path.exists(path):
            os.makedirs(self.root, mode=0o700, exist_ok=True)
            with atomic_file(path, "wb") as f:
                np.savez(f, points=points, eps=eps, vol=vol,
                         tgt=tgt.astype(np.int32), src=src.astype(np.int32))
        return mhash

    def has(self, mhash: str) -> bool:
        try:
            return os.path.exists(self._path(mhash))
        except ValueError:
            return False

    def get(self, mhash: str) -> dict:
        """The stored arrays; :class:`UnknownMesh` (a KeyError) on an
        unknown hash — the HTTP layer maps it to a 404."""
        path = self._path(mhash)
        if not os.path.exists(path):
            raise UnknownMesh(f"unknown mesh hash {mhash!r}")
        with np.load(path) as z:
            return {k: z[k] for k in z.files}

    def meta(self, mhash: str) -> dict:
        d = self.get(mhash)
        return {"hash": mhash, "nodes": int(len(d["points"])),
                "dim": int(d["points"].shape[1]),
                "edges": int(len(d["tgt"]))}


def resolve_mesh_store(mesh_dir=None) -> MeshStore | None:
    """A :class:`MeshStore` from an explicit dir or the env knob; None
    when the registry is off."""
    root = mesh_dir if mesh_dir is not None else mesh_dir_from_env()
    return MeshStore(root) if root else None


# -- mesh hash -> operator (the engine's _make_op hook) ---------------------

#: (realpath(root), hash, k, dt) -> UnstructuredNonlocalOp.  Ops are
#: immutable once built and a mesh bucket touches its op per chunk
#: (u0 default + program build), so the registry keeps a small cache.
_OP_CACHE: dict = {}
_OP_CACHE_CAP = 8


def get_mesh_op(mhash: str, k: float, dt: float, mesh_dir=None):
    """The :class:`UnstructuredNonlocalOp` for a stored mesh under the
    given physics.  The stored edge table is trusted (it is part of the
    content hash) — the op rebuild verifies it matches."""
    store = resolve_mesh_store(mesh_dir)
    if store is None:
        raise RuntimeError(
            "mesh-keyed case but no mesh registry configured "
            f"({MESH_DIR_ENV} is off)")
    key = (os.path.realpath(store.root), mhash, float(k), float(dt))
    op = _OP_CACHE.get(key)
    if op is None:
        from nonlocalheatequation_tpu.ops.unstructured import (
            UnstructuredNonlocalOp,
        )

        d = store.get(mhash)
        op = UnstructuredNonlocalOp(d["points"], d["eps"], k=float(k),
                                    dt=float(dt), vol=d["vol"])
        if (not np.array_equal(op.tgt, d["tgt"])
                or not np.array_equal(op.src, d["src"])):
            raise RuntimeError(
                f"mesh {mhash}: rebuilt edge table disagrees with the "
                "stored one — edge-builder drift; re-upload the mesh")
        while len(_OP_CACHE) >= _OP_CACHE_CAP:
            _OP_CACHE.pop(next(iter(_OP_CACHE)))
        _OP_CACHE[key] = op
    return op


# -- gang placement (tentpole c: partition_coarse_grid feeds sharding) ------

def gang_order(points: np.ndarray, ndevices: int,
               coarse: int = 16) -> np.ndarray:
    """A node permutation that makes index-contiguous equal blocks
    spatially compact: bin the nodes onto a ``coarse x coarse`` tile
    grid over their bounding box, partition the tiles with the refined
    RCB cuts of :func:`utils.decompose.partition_coarse_grid` (the
    reference's decomposition, src/domain_decomposition.cpp:157-195),
    and order nodes by (owner part, tile, index).  Feeding the permuted
    cloud to ``ShardedUnstructuredOp`` places each part's nodes on one
    device, so the ring halo carries only true cut edges."""
    from nonlocalheatequation_tpu.utils.decompose import (
        partition_coarse_grid,
    )

    points = np.asarray(points, np.float64)
    n, d = points.shape
    if ndevices < 2 or n == 0:
        return np.arange(n)
    xy = points[:, :2] if d >= 2 else np.stack(
        [points[:, 0], np.zeros(n)], axis=1)
    lo, hi = xy.min(axis=0), xy.max(axis=0)
    span = np.where(hi > lo, hi - lo, 1.0)
    ij = np.minimum((coarse * (xy - lo) / span).astype(np.int64),
                    coarse - 1)
    owner = partition_coarse_grid(coarse, coarse, ndevices)
    part = owner[ij[:, 0], ij[:, 1]]
    tile = ij[:, 0] * coarse + ij[:, 1]
    return np.lexsort((np.arange(n), tile, part))
