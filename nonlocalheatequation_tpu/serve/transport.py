"""Worker transports: the router's frame protocol over pipes or TCP.

The replica router (serve/router.py) talks to its workers in
length-prefixed frames — a little-endian u64 payload length followed by
the payload (the same length-field convention as the checkpoint and
program-store on-disk formats).  PR 10 hard-wired those frames to a
worker subprocess's stdin/stdout pipes; this module factors the
protocol out so one replica can be one REMOTE host/chip — the
reference's many-locality tier (``srun -n N`` re-running the same
binary, README.md:64-72) mapped onto sockets:

* :class:`PipeTransport` — today's shape, bit-identical and default:
  the worker is a child process, frames ride its stdin/stdout pipes,
  and the worker steals fd 1 at startup so stray prints cannot tear
  the framing.
* :class:`SocketTransport` — the router binds a listener and each
  worker is started with ``--worker-connect host:port``: it dials in,
  sends a HELLO frame, and from then on speaks the identical frames
  over the socket.  Reader-EOF death detection, the delivery ledger,
  ``die@`` chaos, and the trace/clock_sync exchange all work unchanged
  because the router only ever sees a framed byte stream.

**Trust boundary** (the program store's, now on the wire): post-hello
frames deserialize through :mod:`pickle`, which executes arbitrary
code on load — exactly like the AOT program store's on-disk entries
(serve/program_store.py docstring).  The rules that make that safe:

* the listener binds **127.0.0.1 by default**, where the router and
  its workers are one principal on one host (the pipe trust model,
  unchanged);
* a **non-loopback bind refuses to construct without a shared-secret
  token** (``--worker-token`` / ``NLHEAT_WORKER_TOKEN``), checked on
  the hello frame before anything else is read from the connection;
* the hello frame itself is **JSON, never pickle** — no bytes from a
  connection are unpickled until its token has been verified, so an
  unauthenticated peer can probe the port but never reach the
  deserializer;
* frame lengths are bounded (:data:`MAX_FRAME_BYTES`) and a
  malformed / oversized / truncated prefix or a mid-frame disconnect
  reads as ``None`` — the caller classifies that as replica DEATH
  (orphan re-route, respawn floor), never as a crash or a reader
  thread parked on a half-frame forever.

A token authenticates, it does not encrypt: on an untrusted network
put the wire inside the tunnel/mesh layer you already trust (the same
advice as the program store's "filesystem permissions are the
boundary").
"""

from __future__ import annotations

import hmac
import ipaddress
import json
import pickle
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

#: Frame header: little-endian payload length (matches the checkpoint
#: and program-store on-disk length fields).
LEN = struct.Struct("<Q")

#: Upper bound on one frame's payload.  A 4096^2 f64 state is ~134 MB;
#: 1 GiB leaves headroom for any case this stack serves while making a
#: garbage length prefix (e.g. ASCII read as u64 ~ 10^18) classify as
#: death instead of a memory-exhausting allocation.
MAX_FRAME_BYTES = 1 << 30

#: Hello frames are tiny JSON — anything bigger is not a worker.
MAX_HELLO_BYTES = 1 << 16

#: Environment variable carrying the shared-secret worker token (env,
#: not argv: command lines are world-readable in ``ps``).
WORKER_TOKEN_ENV = "NLHEAT_WORKER_TOKEN"

#: The module whose ``__main__`` is the worker child (serve/router.py).
WORKER_MODULE = "nonlocalheatequation_tpu.serve.router"


def write_frame(stream, obj) -> None:
    """One pickle frame onto a writable binary stream (flushes)."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    stream.write(LEN.pack(len(payload)))
    stream.write(payload)
    stream.flush()


def read_frame(stream, max_bytes: int = MAX_FRAME_BYTES):
    """One frame off a readable binary stream, or ``None`` for anything
    that means the peer is gone or lying: EOF, a truncated prefix, an
    OVERSIZED length (a garbage prefix must never become a giant
    allocation), or a mid-frame disconnect.  The caller classifies
    ``None`` as worker death.  A payload that unpickles to garbage
    raises — the router's reader thread treats any exception the same
    as EOF (torn frame == dead worker)."""
    head = stream.read(LEN.size)
    if len(head) < LEN.size:
        return None
    n = LEN.unpack(head)[0]
    if n > max_bytes:
        return None
    payload = stream.read(n)
    if len(payload) < n:
        return None
    return pickle.loads(payload)


def write_json_frame(stream, obj: dict) -> None:
    """A length-prefixed JSON frame — the HELLO form: parseable without
    ever handing unauthenticated bytes to pickle."""
    payload = json.dumps(obj).encode()
    stream.write(LEN.pack(len(payload)))
    stream.write(payload)
    stream.flush()


def _recv_exact(conn: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        try:
            chunk = conn.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


def read_hello(conn: socket.socket, timeout_s: float = 5.0) -> dict | None:
    """The hello frame off a fresh connection: length-prefixed JSON,
    bounded, under a read timeout (a dead or malicious connection must
    never park the accept loop).  Returns the hello dict or ``None``
    for anything malformed — the caller drops the connection."""
    try:
        conn.settimeout(timeout_s)
        head = _recv_exact(conn, LEN.size)
        if head is None:
            return None
        n = LEN.unpack(head)[0]
        if n > MAX_HELLO_BYTES:
            return None
        payload = _recv_exact(conn, n)
        if payload is None:
            return None
        hello = json.loads(payload.decode())
        if not isinstance(hello, dict) or hello.get("op") != "hello":
            return None
        return hello
    except (OSError, ValueError, UnicodeDecodeError):
        return None
    finally:
        try:
            conn.settimeout(None)
        except OSError:
            pass


def is_loopback(host: str) -> bool:
    if host in ("localhost", ""):
        return True
    try:
        return ipaddress.ip_address(host).is_loopback
    except ValueError:
        return False


class WorkerHandle:
    """One connected worker, transport-agnostic: framed reader/writer
    plus process control.  The router's writer thread calls
    :meth:`send_frame`, its reader thread :meth:`recv_frame`, the
    ``die`` chaos plan :meth:`kill`, and the death/close paths
    :meth:`reap`."""

    def __init__(self, proc: subprocess.Popen | None, reader, writer,
                 sock: socket.socket | None = None,
                 transport: str = "pipe"):
        self.proc = proc
        self.reader = reader
        self.writer = writer
        self.sock = sock
        self.transport = transport

    def send_frame(self, obj) -> None:
        write_frame(self.writer, obj)

    def recv_frame(self):
        return read_frame(self.reader)

    def kill(self) -> None:
        """SIGKILL the worker process (the deterministic ``die`` chaos;
        the socket/pipe EOF that follows is the death signal the reader
        thread acts on).  A handle without a local process closes the
        socket instead — the remote worker sees EOF and exits."""
        if self.proc is not None:
            try:
                self.proc.send_signal(signal.SIGKILL)
                return
            except OSError:
                pass
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass

    def wait(self, timeout: float | None = None) -> None:
        if self.proc is not None:
            self.proc.wait(timeout=timeout)

    def reap(self, timeout_s: float = 10.0) -> None:
        """Wait for exit (killing on timeout) and close every stream —
        no zombies, no fd leaks, under sustained chaos included."""
        if self.proc is not None:
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                try:
                    self.proc.kill()
                except OSError:
                    pass
                try:
                    self.proc.wait(timeout=timeout_s)
                except subprocess.TimeoutExpired:
                    pass
        for stream in (self.writer, self.reader, self.sock):
            if stream is None:
                continue
            try:
                stream.close()
            except OSError:
                pass


class PipeTransport:
    """Today's worker shape: a child process speaking frames over its
    own stdin/stdout pipes (the worker steals fd 1 at startup so stray
    prints go to stderr and can never tear the framing)."""

    name = "pipe"

    def spawn(self, rid: int, env: dict,
              timeout_s: float = 180.0) -> WorkerHandle:
        proc = subprocess.Popen(
            [sys.executable, "-m", WORKER_MODULE],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env)
        return WorkerHandle(proc, proc.stdout, proc.stdin,
                            transport=self.name)

    def close(self) -> None:
        pass


class SocketTransport:
    """TCP workers: the router binds ONE listener; every worker dials
    in (``python -m nonlocalheatequation_tpu.serve.router
    --worker-connect host:port``), identifies itself on a JSON hello
    frame (replica id + token), and then speaks the identical pickle
    frames the pipe transport does.

    ``host`` defaults to 127.0.0.1 — binding anything non-loopback
    REFUSES without ``token`` (the module-docstring trust boundary).
    :meth:`spawn` launches a local worker child pointed at the
    listener; a worker started by other means (another host) is
    matched to its replica by the hello's ``replica`` field when its
    connection arrives."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 token: str | None = None):
        if not is_loopback(host) and not token:
            raise ValueError(
                f"SocketTransport bind {host!r} is not loopback: frames "
                "deserialize through pickle (see serve/transport.py "
                "trust boundary) — pass a shared-secret token "
                "(--worker-token) to accept non-local workers")
        self.host = host
        self.token = token
        self._srv = socket.create_server((host, int(port)))
        self.port = self._srv.getsockname()[1]
        #: connections that helloed for a replica nobody asked for YET
        #: (two concurrent spawns can accept each other's workers)
        self._parked: dict[int, socket.socket] = {}  # guarded_by: self._lock
        #: serializes the accept loop: _spawn can run concurrently (a
        #: reader thread's respawn racing an elastic add_replica), and
        #: the listener's settimeout/accept pair is not thread-safe to
        #: interleave — the parked map hands the other spawn's worker
        #: over when the lock holder accepts it first
        self._lock = threading.Lock()
        self._closed = False

    @property
    def name(self) -> str:
        return "tcp"

    def connect_arg(self) -> str:
        host = self.host if self.host not in ("", "0.0.0.0") else "127.0.0.1"
        return f"{host}:{self.port}"

    def spawn(self, rid: int, env: dict,
              timeout_s: float = 180.0) -> WorkerHandle:
        env = dict(env)
        if self.token is not None:
            # env, not argv: command lines are world-readable in ps
            env[WORKER_TOKEN_ENV] = self.token
        proc = subprocess.Popen(
            [sys.executable, "-m", WORKER_MODULE,
             "--worker-connect", self.connect_arg()],
            stdin=subprocess.DEVNULL, env=env)
        try:
            conn = self._accept(rid, timeout_s, proc)
        except BaseException:
            try:
                proc.kill()
            except OSError:
                pass
            raise
        return WorkerHandle(proc, conn.makefile("rb"),
                            conn.makefile("wb"), sock=conn,
                            transport=self.name)

    def _accept(self, rid: int, timeout_s: float,
                proc: subprocess.Popen | None = None) -> socket.socket:
        """Accept until replica ``rid``'s authenticated hello arrives.
        A connection with a wrong/missing token, or any malformed
        hello, is CLOSED and the loop continues — a port scanner or a
        stale worker must never crash the router or occupy the slot.
        Hellos for other replica ids are parked for their own spawn."""
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                conn = self._parked.pop(rid, None)
            if conn is not None:
                return conn
            if proc is not None and proc.poll() is not None:
                raise RuntimeError(
                    f"worker {rid} exited (rc={proc.returncode}) before "
                    "dialing in")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"worker {rid} did not dial in within {timeout_s:.0f}s")
            # one accept round per lock hold — but the lock covers ONLY
            # the parked-map check and the accept() call: the hello
            # read (up to 5 s against a slow or malicious peer) happens
            # UNLOCKED, so a trickle of garbage connections can never
            # park a concurrent spawn/respawn past its deadline
            with self._lock:
                if rid in self._parked:
                    continue  # parked for us while we waited on the lock
                self._srv.settimeout(min(remaining, 1.0))
                try:
                    conn, addr = self._srv.accept()
                except socket.timeout:
                    continue
                except OSError as e:
                    raise RuntimeError(
                        f"socket listener closed: {e}") from None
            hello = read_hello(conn)
            if hello is None or not self._token_ok(hello):
                print(f"transport: rejected connection from {addr} "
                      f"({'bad hello' if hello is None else 'bad token'})",
                      file=sys.stderr)
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            try:
                got = int(hello.get("replica"))
            except (TypeError, ValueError):
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            if got == rid:
                return conn
            with self._lock:
                stale = self._parked.pop(got, None)
                self._parked[got] = conn
            if stale is not None:
                # a second dial-in for the same replica id: the older
                # connection is dead weight — close, not leak
                try:
                    stale.close()
                except OSError:
                    pass

    def _token_ok(self, hello: dict) -> bool:
        if self.token is None:
            return True
        offered = hello.get("token")
        return isinstance(offered, str) and hmac.compare_digest(
            offered, self.token)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with self._lock:
            parked = list(self._parked.values())
            self._parked.clear()
        for conn in parked:
            try:
                conn.close()
            except OSError:
                pass
        try:
            self._srv.close()
        except OSError:
            pass


def make_transport(spec, token: str | None = None):
    """Resolve the router's ``transport=`` argument: ``"pipe"`` (the
    default), ``"tcp"``/``"socket"`` (a fresh loopback
    :class:`SocketTransport`), or an already-constructed transport
    object (``spawn``/``name``/``close``) passed through.  A token with
    the pipe transport refuses loudly: pipes are the same process tree
    and a silently ignored credential would misstate the boundary."""
    if spec is None or spec == "pipe":
        if token is not None:
            raise ValueError(
                "worker_token authenticates SOCKET workers; the pipe "
                "transport is the same process tree (drop the token or "
                "use transport='tcp')")
        return PipeTransport()
    if spec in ("tcp", "socket"):
        return SocketTransport(token=token)
    if hasattr(spec, "spawn") and hasattr(spec, "name"):
        if token is not None and getattr(spec, "token", None) != token:
            raise ValueError(
                "pass the token to the transport you constructed, not "
                "to the router (one credential, one owner)")
        return spec
    raise ValueError(
        f"unknown transport {spec!r}: 'pipe', 'tcp', or a transport "
        "object with spawn()/name/close()")
