"""HTTP ingestion tier: the fleet's front door, with admission control.

obs/export.py proved the shape — a stdlib ThreadingHTTPServer on
127.0.0.1 serving process telemetry.  This module promotes that
machinery from telemetry to a REQUEST API over a serving backend (a
:class:`~nonlocalheatequation_tpu.serve.router.ReplicaRouter`, or
anything with ``submit``/``outstanding_total``/``retry_after_s``):

* ``POST /v1/cases`` — submit one case.  Two forms (ISSUE 13):
  the EXPLICIT form (JSON body: ``shape``, ``nt``, ``eps``, ``k``,
  ``dt``, ``dh``, optional ``test``/``u0``/``deadline_ms``/
  ``priority``) runs the fleet's default engine at the caller's
  schedule; the PICKED form replaces ``nt``/``dt`` with ``T_final`` +
  ``accuracy`` (error_l2/#points target) and lets the engine picker
  (serve/picker.py) choose the cheapest stepper x stages x method x
  precision meeting accuracy — and ``deadline_ms``, which in this form
  also bounds the modeled compute.  Returns 202 ``{"id": N}`` (picked
  form adds the chosen ``engine``/``nt``/``dt`` evidence), **422**
  when no engine meets accuracy+deadline (the picker refuses loudly,
  never silently serves a miss), or **429 + Retry-After** when
  admission control sheds.
* ``GET /v1/cases/<id>`` — poll: ``{"status": "queued"|"done"|"failed"}``
  plus latency/replica detail; ``?wait=1`` (optional ``&timeout_s=T``)
  blocks until the case completes — the stream/wait form.
* ``GET /v1/cases/<id>/result`` — the solved state: JSON
  ``{"shape": ..., "values": [...]}`` by default (f64 round-trip-exact),
  or raw ``.npy`` bytes with ``?bin=1``.
* **Sessions** (ISSUE 15, serve/sessions.py — present when the server
  is built with a :class:`~nonlocalheatequation_tpu.serve.sessions.SessionManager`):
  ``POST /v1/sessions`` opens a live simulation (case fields + ``nt``
  total steps + ``chunk_steps``/``preview_stride``/``budget_steps``/
  ``checkpoint_every``), 429-shedding exactly like cases;
  ``GET /v1/sessions/<id>`` is the status+audit document;
  ``GET /v1/sessions/<id>/stream[?from_step=N]`` streams frames as
  Server-Sent Events (``data: {...}\\n\\n`` per chunk boundary — coarse
  f32 previews, then the final full-f64 frame; the ``from_step``
  cursor makes a reconnect lossless and duplicate-free);
  ``POST /v1/sessions/<id>/retarget`` queues a mid-flight source/k
  change (applied at the next chunk boundary, step recorded);
  ``POST /v1/sessions/<id>/fork`` branches a what-if session from a
  checkpoint; ``POST /v1/sessions/<id>/close`` ends the stream;
  ``GET /v1/sessions/<id>/result`` fetches the final f64 field
  (``?bin=1`` for raw .npy bytes).
* **Meshes** (ISSUE 17, serve/meshes.py — present when a mesh registry
  is configured via ``mesh_dir`` or ``NLHEAT_MESH_DIR``):
  ``POST /v1/meshes`` uploads a point cloud ONCE (JSON ``points`` +
  ``eps`` field + optional ``vol``; validated + bounded — an oversized
  or malformed body is a loud 400 — then content-hashed and persisted),
  returning ``{"hash", "nodes", "dim", "edges"}``; cases and sessions
  then reference it with ``"mesh": <hash>`` INSTEAD of
  ``shape``/``eps``/``dh`` (the registered cloud carries the geometry),
  which routes the mesh's bucket sticky and warm-boots its compiled
  gather program from the shared AOT store (serve/ensemble.py).
  ``GET /v1/meshes/<hash>`` returns the stored mesh's metadata.
* ``GET /healthz`` — liveness + fleet summary.
* ``GET /v1/status`` — the one-page fleet health document (ISSUE 20):
  replica liveness/breakers/staleness, admission counters, sessions,
  and the SLO ledger's burn/drift block when auditing is on.
* ``GET /metrics`` / ``/metrics.json`` — the backend registry's
  Prometheus/JSON exposition (the router's registry already aggregates
  per-replica namespaces; obs/export.py renders it).

**Admission control** (:class:`AdmissionController`) sheds BEFORE the
pipe collapses, keyed off the gauges already in the metrics registry:
the in-flight depth (``/router/outstanding`` vs the bounded
``max_pending`` budget) and the observed queue-wait/latency window
(``/router/request-latency-ms``).  A shed is a 429 with a Retry-After
computed from the observed p50 service time — never an unbounded queue,
never a silent drop.  The router's own hard bound
(:class:`~nonlocalheatequation_tpu.serve.router.RouterOverloaded`)
backstops it: admission is the soft gate, the router cap the hard one,
and both surface as 429.

Bind address is 127.0.0.1 only, like the metrics endpoint: this tier
terminates trusted localhost traffic (a reverse proxy owns the wire).
"""

from __future__ import annotations

import io
import json
import threading
import time

import numpy as np

from nonlocalheatequation_tpu.obs import trace as obs_trace
from nonlocalheatequation_tpu.obs.export import (
    merged_prometheus,
    merged_snapshot_json,
)
from nonlocalheatequation_tpu.obs.trace import TraceContext
from nonlocalheatequation_tpu.serve.ensemble import EnsembleCase
from nonlocalheatequation_tpu.serve.meshes import (
    MAX_BODY_BYTES as MESH_MAX_BODY_BYTES,
    UnknownMesh,
    resolve_mesh_store,
)
from nonlocalheatequation_tpu.serve.picker import PickerRefusal, pick_engine
from nonlocalheatequation_tpu.serve.router import RouterOverloaded

#: Completed requests retained for polling (an abandoned client must not
#: grow the ingress's memory without bound): the most recent RESULTS_CAP
#: finished cases stay fetchable, older ones age out (410 Gone).
RESULTS_CAP = 4096

#: Default wait bound for ``?wait=1`` (a handler thread parked forever
#: on an abandoned connection is a slot leak).
WAIT_TIMEOUT_S = 300.0


class AdmissionController:
    """The soft gate in front of the router's hard in-flight cap.

    ``max_pending`` bounds the admitted-but-unfinished depth (default:
    the backend's own ``max_outstanding`` per live replica — admission
    then sheds exactly where the router would refuse, one request
    earlier and politely).  ``max_queue_wait_ms`` additionally sheds
    while the observed p50 request latency exceeds it — the queue-wait
    form of the same promise: a request we cannot serve inside the
    bound is refused NOW with a retry hint, not parked.

    The SESSION tier's fleet-wide gate lives here too (ISSUE 15,
    serve/sessions.py): ``session_steps_per_s`` rate-limits the
    aggregate step rate streaming sessions may draw (a token bucket on
    the injected ``clock``; burst = one second's tokens), and every
    session chunk additionally clears :meth:`check` — so a saturated
    batch tier DEFERS session chunks and a greedy session can never
    starve the batch tier.  A refused chunk is a deferral the session
    manager retries at its next pump, never an error.

    Counters land in the backend registry: ``/ingress/accepted``,
    ``/ingress/shed``, the ``/ingress/retry-after-s`` gauge (the most
    recent hint), and the session gate's ``/ingress/session-steps`` /
    ``/ingress/session-deferred``."""

    def __init__(self, backend, *, max_pending: int | None = None,
                 max_queue_wait_ms: float | None = None,
                 session_steps_per_s: float | None = None,
                 session_burst_steps: float | None = None,
                 clock=time.monotonic):
        self.backend = backend
        self.max_pending = max_pending
        self.max_queue_wait_ms = max_queue_wait_ms
        r = backend.registry
        self._m_accepted = r.counter("/ingress/accepted")
        self._m_shed = r.counter("/ingress/shed")
        self._m_retry_after = r.gauge("/ingress/retry-after-s")
        # the session gate's token bucket (0/None = no rate cap; the
        # batch-depth check still applies to session chunks).  The
        # burst defaults to one second's tokens; session_burst_steps
        # pins it explicitly (the bench pins one CHUNK so the gate
        # engages at any scale, not only past the first second)
        if session_steps_per_s is not None and session_steps_per_s < 0:
            raise ValueError(
                f"session_steps_per_s must be >= 0, got "
                f"{session_steps_per_s}")
        if session_burst_steps is not None and session_burst_steps <= 0:
            raise ValueError(
                f"session_burst_steps must be > 0, got "
                f"{session_burst_steps}")
        self._clock = clock
        self.session_steps_per_s = (float(session_steps_per_s)
                                    if session_steps_per_s else None)
        self._session_cap = (float(session_burst_steps)
                             if session_burst_steps is not None
                             else self.session_steps_per_s or 0.0)
        # the bucket is mutated from every pumping thread (the session
        # manager's driver, drive() callers, stream() reader threads) —
        # an unlocked read-modify-write would lose chunk debt and admit
        # above the configured rate
        self._session_lock = threading.Lock()
        self._session_tokens = self._session_cap  # guarded_by: self._session_lock
        self._session_t = clock()  # guarded_by: self._session_lock
        self._m_session_steps = r.counter("/ingress/session-steps")
        self._m_session_deferred = r.counter("/ingress/session-deferred")

    def _cap(self) -> int:
        if self.max_pending is not None:
            return int(self.max_pending)
        return self.backend.max_outstanding * max(
            1, self.backend.live_count())

    def check(self) -> float | None:
        """None to admit, else the Retry-After hint in seconds."""
        pending = self.backend.outstanding_total()
        if pending >= self._cap():
            return self._hint(pending)
        if self.max_queue_wait_ms is not None:
            pct = self.backend.registry.get(
                "/router/request-latency-ms")
            p50 = (pct.percentiles().get("p50", 0.0)
                   if pct is not None else 0.0)
            if p50 > self.max_queue_wait_ms:
                return self._hint(pending)
        return None

    def admit_session(self, steps: int) -> float | None:
        """None to admit one session chunk of ``steps``, else the
        defer hint in seconds.  Order matters: the batch-depth check
        first (a saturated fleet defers sessions regardless of
        tokens), then the rate bucket.  Tokens may go negative on an
        oversized chunk — the debt throttles later chunks, so the
        AVERAGE rate holds even when chunk_steps exceeds one window."""
        retry = self.check()
        if retry is not None:
            self._m_session_deferred.inc()
            return retry
        if self.session_steps_per_s:
            now = self._clock()
            cap = self._session_cap
            with self._session_lock:
                self._session_tokens = min(
                    cap, self._session_tokens
                    + (now - self._session_t) * self.session_steps_per_s)
                self._session_t = now
                if self._session_tokens < min(float(steps), cap):
                    short = min(float(steps), cap) - self._session_tokens
                    self._m_session_deferred.inc()
                    return max(0.05, short / self.session_steps_per_s)
                self._session_tokens -= float(steps)
        self._m_session_steps.inc(int(steps))
        return None

    def _hint(self, pending: int) -> float:
        hint = self.backend.retry_after_s()
        # a deep backlog needs more than one service time to clear
        hint *= max(1.0, pending / max(1, self._cap()))
        self._m_retry_after.set(round(hint, 3))
        return hint

    def try_submit(self, case: EnsembleCase, *, deadline_ms=None,
                   priority: int = 0, trace=None, engine=None):
        """``(request, None)`` when admitted, ``(None, retry_after_s)``
        when shed (by this gate or the router's hard cap).  ``trace``
        (a TraceContext) and ``engine`` (a picked
        :class:`~nonlocalheatequation_tpu.serve.picker.EngineChoice`)
        are forwarded to the backend only when present, so plain
        callers and router-shaped stubs are untouched."""
        retry = self.check()
        if retry is not None:
            self._m_shed.inc()
            return None, retry
        kw = {"trace": trace} if trace is not None else {}
        if engine is not None:
            kw["engine"] = engine
        try:
            req = self.backend.submit(case, deadline_ms=deadline_ms,
                                      priority=priority, **kw)
        except RouterOverloaded as e:
            self._m_shed.inc()
            self._m_retry_after.set(round(e.retry_after_s, 3))
            return None, e.retry_after_s
        self._m_accepted.inc()
        return req, None


def parse_case(body: dict, meshes=None) -> EnsembleCase:
    """Validate one JSON case body into an EnsembleCase — loudly: a
    malformed submission is the CLIENT's 400, never a worker's stack
    trace mid-chunk.

    ``meshes`` (a serve/meshes.py MeshStore, or None when no registry
    is configured) resolves mesh-keyed bodies (ISSUE 17): ``"mesh":
    <hash>`` REPLACES ``shape``/``eps``/``dh`` — the registered cloud
    carries the geometry, so shape becomes the node count ``(n,)`` and
    eps/dh ride as 0 (the EnsembleCase mesh semantics).  An unknown
    hash raises :class:`~nonlocalheatequation_tpu.serve.meshes.UnknownMesh`
    (the HTTP layer's 404); a malformed one is the usual ValueError."""
    try:
        mhash = body.get("mesh")
        if mhash is not None:
            if not isinstance(mhash, str):
                raise ValueError(f"mesh must be a hash string, got "
                                 f"{type(mhash).__name__}")
            if meshes is None:
                raise ValueError(
                    "mesh-keyed case but no mesh registry on this "
                    "server (NLHEAT_MESH_DIR off)")
            for clash in ("shape", "eps", "dh"):
                if clash in body:
                    raise ValueError(
                        f"a mesh-keyed case carries its geometry in the "
                        f"registered cloud: drop {clash!r}")
            meta = meshes.meta(mhash)  # ValueError | UnknownMesh
            shape = (int(meta["nodes"]),)
            nt = int(body["nt"])
            if nt < 1:
                raise ValueError(f"need nt >= 1 (got {nt})")
            case = EnsembleCase(
                shape=shape, nt=nt, eps=0, k=float(body["k"]),
                dt=float(body["dt"]), dh=0.0,
                test=bool(body.get("test", False)), mesh=mhash)
        else:
            shape = tuple(int(s) for s in body["shape"])
            if not 1 <= len(shape) <= 3 or any(s < 1 for s in shape):
                raise ValueError(f"bad shape {shape}")
            nt = int(body["nt"])
            eps = int(body["eps"])
            if nt < 1 or eps < 1:
                raise ValueError(
                    f"need nt >= 1 and eps >= 1 (got {nt}, {eps})")
            case = EnsembleCase(
                shape=shape, nt=nt, eps=eps, k=float(body["k"]),
                dt=float(body["dt"]), dh=float(body["dh"]),
                test=bool(body.get("test", False)))
        deadline = body.get("deadline_ms")
        if deadline is not None:
            if not isinstance(deadline, (int, float)) or deadline < 0:
                raise ValueError(
                    f"deadline_ms must be a number >= 0, got {deadline!r}")
        prio = body.get("priority", 0)
        if not isinstance(prio, int) or isinstance(prio, bool):
            raise ValueError(f"priority must be an integer, got {prio!r}")
        u0 = body.get("u0")
        if u0 is not None:
            u0 = np.asarray(u0, np.float64)
            if u0.size != int(np.prod(shape)):
                raise ValueError(
                    f"u0 has {u0.size} values, shape {shape} needs "
                    f"{int(np.prod(shape))}")
            case.u0 = u0.reshape(shape)
        elif not case.test:
            raise ValueError("a production (test=false) case needs u0")
        return case
    except UnknownMesh:
        raise  # the 404, not a missing-field 400
    except KeyError as e:
        raise ValueError(f"missing case field {e.args[0]!r}") from None


class IngressServer:
    """The front door: HTTP request API over a router, 127.0.0.1 only.

    ``backend`` is the ReplicaRouter (owned by the caller — the server
    never closes it); ``admission`` defaults to an
    :class:`AdmissionController` with the router-cap budget.  ``port``
    0 picks a free port (the resolved one is ``self.port``)."""

    def __init__(self, port: int, backend, *,
                 admission: AdmissionController | None = None,
                 max_pending: int | None = None,
                 max_queue_wait_ms: float | None = None,
                 sessions=None, mesh_dir: str | None = None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.backend = backend
        #: mesh registry root (serve/meshes.py): an explicit dir, or
        #: None = the ambient NLHEAT_MESH_DIR knob (resolved per
        #: request so tests and soak rigs can point it late); when both
        #: are off the mesh endpoints 404
        self.mesh_dir = mesh_dir
        self.admission = admission if admission is not None else \
            AdmissionController(backend, max_pending=max_pending,
                                max_queue_wait_ms=max_queue_wait_ms)
        #: the session tier (serve/sessions.py SessionManager), owned by
        #: the caller like the backend; None = session endpoints 404
        self.sessions = sessions
        self._requests: dict[int, object] = {}
        self._done: dict[int, None] = {}  # insertion-ordered: FIFO aging
        self._lock = threading.Lock()
        ingress = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _reply(self, code: int, body: bytes,
                       ctype: str = "application/json",
                       headers=()) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _json(self, code: int, obj, headers=()) -> None:
                self._reply(code, (json.dumps(obj) + "\n").encode(),
                            headers=headers)

            def do_POST(self):  # noqa: N802 — http.server API
                try:
                    ingress._post(self)
                except Exception as e:  # noqa: BLE001 — a request must
                    # not kill the server; the client gets the 500
                    try:
                        self._json(500, {"error": f"{type(e).__name__}: "
                                                  f"{e}"})
                    except Exception:  # noqa: BLE001
                        pass

            def do_GET(self):  # noqa: N802 — http.server API
                try:
                    ingress._get(self)
                except Exception as e:  # noqa: BLE001
                    try:
                        self._json(500, {"error": f"{type(e).__name__}: "
                                                  f"{e}"})
                    except Exception:  # noqa: BLE001
                        pass

            def log_message(self, *a):  # silence per-request chatter
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", int(port)), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="nlheat-ingress")
        self._thread.start()

    def _tracer(self):
        """The ingress's span tracer: the backend router's (same
        process, merged into the fleet timeline) or the ambient global
        one.  None when tracing is off — one attribute read."""
        tr = getattr(self.backend, "_tracer", None)
        return tr if tr is not None else obs_trace.get_tracer()

    def _meshes(self):
        """The mesh registry (serve/meshes.py MeshStore), or None when
        neither ``mesh_dir`` nor ``NLHEAT_MESH_DIR`` configures one."""
        return resolve_mesh_store(self.mesh_dir)

    # -- request handling (called from handler threads) ----------------------
    def _post(self, h) -> None:
        path = h.path.rstrip("/")
        if path == "/v1/sessions" or path.startswith("/v1/sessions/"):
            self._post_session(h, path)
            return
        if path == "/v1/meshes":
            self._post_mesh(h)
            return
        if path != "/v1/cases":
            h._json(404, {"error": f"no such endpoint {h.path!r}"})
            return
        # trace identity (ISSUE 11): adopt the client's X-NLHEAT-Trace
        # header or, when tracing is on, mint one HERE — the ingress is
        # the trace root every downstream span chains to
        tr = self._tracer()
        hdr = h.headers.get("X-NLHEAT-Trace")
        ctx = TraceContext.from_header(hdr) if hdr else None
        if ctx is None and tr is not None:
            ctx = TraceContext.mint()
        t0 = time.monotonic() if tr is not None else 0.0
        try:
            n = int(h.headers.get("Content-Length") or 0)
            body = json.loads(h.rfile.read(n).decode() or "{}")
            if not isinstance(body, dict):
                raise ValueError(
                    f"case body must be a JSON object, got "
                    f"{type(body).__name__}")
            case, picked = self._parse_body(body)
        except PickerRefusal as e:
            # no engine meets accuracy+deadline: the request's contract
            # is unservable — a client 422 naming the best infeasible
            # candidate, never a silently-slow or silently-wrong solve
            h._json(422, {"error": str(e), "refused": "picker"})
            return
        except UnknownMesh as e:
            h._json(404, {"error": str(e)})
            return
        except (ValueError, TypeError, json.JSONDecodeError) as e:
            h._json(400, {"error": str(e)})
            return
        req, retry = self.admission.try_submit(
            case, deadline_ms=body.get("deadline_ms"),
            priority=body.get("priority") or 0, trace=ctx,
            engine=picked)
        if req is None:
            if tr is not None and ctx is not None:
                tr.instant("ingress.shed", cat="ingress",
                           trace=ctx.trace_id,
                           retry_after_s=round(retry, 3))
            h._json(429, {"error": "overloaded",
                          "retry_after_s": round(retry, 3)},
                    headers=[("Retry-After",
                              str(max(1, int(np.ceil(retry)))))])
            return
        with self._lock:
            self._requests[req.seq] = req
        self._sweep()
        headers = []
        if ctx is not None:
            if ctx.request is None:
                ctx.request = req.seq
            headers.append(("X-NLHEAT-Trace", ctx.to_header()))
            if tr is not None:
                # the trace ROOT: one ingress span over parse+admit+
                # route, plus the flow START the router/worker chain
                # hangs off (flow events tie the pids together)
                now = time.monotonic()
                tr.flow("request", "start", ctx.trace_id, ts=t0,
                        cat="ingress", req=req.seq)
                tr.complete("ingress.request", t0, now, cat="ingress",
                            trace=ctx.trace_id, req=req.seq,
                            replica=req.replica)
        resp = {"id": req.seq, "status": "queued"}
        if picked is not None:
            # the pick's evidence: which engine serves the case and the
            # schedule it chose — auditable, never a black box
            resp["engine"] = picked.wire()
            resp["nt"] = picked.steps
            resp["dt"] = picked.dt
        if ctx is not None:
            resp["trace"] = ctx.trace_id
        h._json(202, resp, headers=headers)

    def _parse_body(self, body: dict):
        """Both POST forms (module docstring): returns ``(case,
        picked)`` — picked None for the explicit nt/dt form.  The
        picked form routes accuracy/T_final through the engine picker
        with the fleet's engine base and, for a case bound for the
        sharded tier, the router's sharded-fft capability verdict as
        the fft candidate axis (ops/spectral_sharded.py — the pencil
        transform serves compatible (grid, mesh) pairs; incompatible
        ones pick on the stencil axis)."""
        meshes = self._meshes()
        if "accuracy" not in body and "T_final" not in body:
            return parse_case(body, meshes=meshes), None
        for bad in ("nt", "dt"):
            if bad in body:
                raise ValueError(
                    f"a picked-engine case names the contract "
                    f"(T_final + accuracy), not the schedule: drop "
                    f"{bad!r} — the picker chooses dt/steps — or drop "
                    "accuracy/T_final for the explicit form")
        for need in ("accuracy", "T_final"):
            if need not in body:
                raise ValueError(
                    f"the picked form needs both T_final and accuracy "
                    f"(missing {need!r})")
        # validate every NON-schedule field through parse_case first
        # (placeholder schedule): ONE validator, shared with the
        # explicit form verbatim — missing fields, bad-rank shapes,
        # eps < 1, u0/test rules are all the client's 400 here too
        base = {k2: v for k2, v in body.items()
                if k2 not in ("accuracy", "T_final")}
        parse_case(base | {"nt": 1, "dt": 1.0}, meshes=meshes)
        if body.get("mesh") is not None:
            # the MESH axis (ISSUE 17): geometry and the stability
            # bound come from the registered cloud (serve/picker.py
            # _pick_mesh_engine); the grid shape/eps/dh knobs are
            # absent by the parse_case mesh contract, so the
            # placeholders below are ignored by the picker
            T_final = float(body["T_final"])
            accuracy = float(body["accuracy"])
            picked = pick_engine(
                (1,), 1, float(body["k"]), 1.0, T_final, accuracy,
                deadline_ms=body.get("deadline_ms"),
                mesh=str(body["mesh"]), mesh_dir=self.mesh_dir)
            case = parse_case(base | {"nt": picked.steps,
                                      "dt": picked.dt}, meshes=meshes)
            return case, picked
        shape = tuple(int(s) for s in body["shape"])
        eps = int(body["eps"])
        k = float(body["k"])
        dh = float(body["dh"])
        if not dh > 0:
            # the one rule the explicit form has no stake in: the
            # picker's stability constant divides by (eps*dh)
            raise ValueError(f"dh must be > 0, got {dh}")
        T_final = float(body["T_final"])
        accuracy = float(body["accuracy"])
        # T_final/accuracy/deadline_ms positivity: pick_engine's own
        # refusals (ValueError -> the client's 400)
        # the ROUTER's own predicates (one rule, no drift): a case the
        # router would route to the gang picks on the fft axis only
        # when the router's sharded-fft capability says the pencil
        # transform can serve it (ISSUE 16 — no more hardcoded stencil-
        # only axis); router-shaped stubs without the predicates are
        # never sharded / never fft-capable
        is_sharded = getattr(self.backend, "is_sharded", None)
        sharded = bool(is_sharded(shape)) if is_sharded else False
        cap = getattr(self.backend, "sharded_fft_capability", None)
        allow_fft = (not sharded) or bool(cap and cap(shape, eps))
        ek = getattr(self.backend, "engine_kwargs", None) or {}
        picked = pick_engine(
            shape, eps, k, dh, T_final, accuracy,
            deadline_ms=body.get("deadline_ms"),
            method=ek.get("method", "auto"), allow_fft=allow_fft)
        case = parse_case(base | {"nt": picked.steps, "dt": picked.dt},
                          meshes=meshes)
        return case, picked

    # -- the mesh registry (serve/meshes.py) ---------------------------------
    def _post_mesh(self, h) -> None:
        """``POST /v1/meshes``: validate + hash + persist one point
        cloud.  The read is BOUNDED (serve/meshes.py MAX_BODY_BYTES) —
        an oversized declared body is refused before a byte of it is
        read, and every validation failure is the client's 400."""
        store = self._meshes()
        if store is None:
            h._json(404, {"error": "no mesh registry on this server "
                                   "(serve/meshes.py — set mesh_dir or "
                                   "NLHEAT_MESH_DIR)"})
            return
        n = int(h.headers.get("Content-Length") or 0)
        if n > MESH_MAX_BODY_BYTES:
            h._json(400, {"error": f"mesh upload declares {n} bytes, "
                                   f"over the {MESH_MAX_BODY_BYTES}-"
                                   "byte cap"})
            return
        try:
            body = json.loads(h.rfile.read(n).decode() or "{}")
            if not isinstance(body, dict):
                raise ValueError(
                    f"mesh body must be a JSON object, got "
                    f"{type(body).__name__}")
            for need in ("points", "eps"):
                if need not in body:
                    raise ValueError(
                        f"a mesh upload needs {need!r} (points + eps "
                        "field + optional vol)")
            mhash = store.put(body["points"], body["eps"],
                              body.get("vol"))
        except (ValueError, TypeError, json.JSONDecodeError) as e:
            h._json(400, {"error": str(e)})
            return
        h._json(201, store.meta(mhash))

    def _get_mesh(self, h, path: str) -> None:
        store = self._meshes()
        if store is None:
            h._json(404, {"error": "no mesh registry on this server"})
            return
        mhash = path[len("/v1/meshes/"):]
        try:
            h._json(200, store.meta(mhash))
        except UnknownMesh as e:
            h._json(404, {"error": str(e)})
        except ValueError as e:
            h._json(400, {"error": str(e)})

    # -- the session tier (serve/sessions.py) --------------------------------
    def _read_body(self, h) -> dict:
        n = int(h.headers.get("Content-Length") or 0)
        body = json.loads(h.rfile.read(n).decode() or "{}")
        if not isinstance(body, dict):
            raise ValueError(
                f"body must be a JSON object, got {type(body).__name__}")
        return body

    def _post_session(self, h, path: str) -> None:
        if self.sessions is None:
            h._json(404, {"error": "no session tier on this server "
                                   "(serve/sessions.py SessionManager "
                                   "not configured)"})
            return
        if path == "/v1/sessions":
            self._open_session(h)
            return
        rest = path[len("/v1/sessions/"):]
        sid, _, verb = rest.partition("/")
        try:
            body = self._read_body(h)
            if verb == "retarget":
                out = self.sessions.retarget(
                    sid, k=body.get("k"), source=body.get("source"),
                    clear_source=bool(body.get("clear_source")))
                h._json(202, dict(out, session=sid))
            elif verb == "fork":
                child = self.sessions.fork(sid, step=body.get("step"))
                h._json(201, {"session": child.sid,
                              "parent": sid,
                              "from_step": child.step})
            elif verb == "close":
                h._json(200, self.sessions.close_session(sid))
            else:
                h._json(404, {"error": f"no session verb {verb!r}"})
        except KeyError as e:
            h._json(404, {"error": str(e.args[0]) if e.args else str(e)})
        except (ValueError, TypeError, json.JSONDecodeError) as e:
            h._json(400, {"error": str(e)})

    def _open_session(self, h) -> None:
        from nonlocalheatequation_tpu.serve.sessions import SessionSpec

        try:
            body = self._read_body(h)
            # ONE validator with the case form: every shared field
            # (shape/eps/k/dh rules, u0 size, production-needs-u0)
            # refuses exactly as POST /v1/cases would; nt is the
            # session's TOTAL steps
            case = parse_case({k2: v for k2, v in body.items()
                               if k2 in ("shape", "nt", "eps", "k", "dt",
                                         "dh", "u0", "test", "mesh")},
                              meshes=self._meshes())
            if case.test:
                raise ValueError(
                    "sessions are production solves (test=false with "
                    "u0); the manufactured-source test path cannot be "
                    "chunked")
            spec = SessionSpec(
                shape=case.shape, eps=case.eps, k=case.k, dt=case.dt,
                dh=case.dh, u0=case.u0, nt=case.nt, mesh=case.mesh,
                chunk_steps=int(body.get("chunk_steps",
                                         self.sessions.default_chunk_steps)),
                preview_stride=body.get("preview_stride"),
                budget_steps=body.get("budget_steps"),
                checkpoint_every=body.get("checkpoint_every"))
        except UnknownMesh as e:
            h._json(404, {"error": str(e)})
            return
        except (ValueError, TypeError, json.JSONDecodeError) as e:
            h._json(400, {"error": str(e)})
            return
        # open-admission mirrors case admission: a saturated fleet
        # sheds the OPEN (429 + Retry-After); a live session's chunks
        # then defer through the session gate instead of shedding
        retry = self.admission.check()
        if retry is not None:
            h._json(429, {"error": "overloaded",
                          "retry_after_s": round(retry, 3)},
                    headers=[("Retry-After",
                              str(max(1, int(np.ceil(retry)))))])
            return
        try:
            s = self.sessions.open(spec)
        except (ValueError, TypeError, RuntimeError) as e:
            h._json(400, {"error": str(e)})
            return
        h._json(201, {"session": s.sid, "status": "running",
                      "step": s.step, "nt": spec.nt,
                      "chunk_steps": spec.chunk_steps,
                      "stream": f"/v1/sessions/{s.sid}/stream"})

    def _get_session(self, h, path: str, params: dict) -> None:
        if self.sessions is None:
            h._json(404, {"error": "no session tier on this server"})
            return
        rest = path[len("/v1/sessions/"):]
        sid, _, verb = rest.partition("/")
        try:
            s = self.sessions.get(sid)
        except KeyError:
            h._json(404, {"error": f"no live session {sid!r}"})
            return
        if verb == "":
            h._json(200, s.status())
            return
        if verb == "result":
            out = s.result()
            if out is None:
                h._json(409, {"error": f"session {sid!r} is "
                                       f"{s.status()['state']}; the "
                                       "final field exists once done/"
                                       "closed"})
                return
            if params.get("bin") in ("1", "true"):
                bio = io.BytesIO()
                np.save(bio, out)
                h._reply(200, bio.getvalue(),
                         ctype="application/octet-stream")
            else:
                h._json(200, {"session": sid,
                              "step": s.status()["step"],
                              "shape": list(out.shape),
                              "values": out.ravel().tolist()})
            return
        if verb != "stream":
            h._json(404, {"error": f"no session endpoint {verb!r}"})
            return
        try:
            from_step = int(params.get("from_step", -1))
            timeout = float(params.get("timeout_s") or WAIT_TIMEOUT_S)
        except ValueError:
            h._json(400, {"error": "bad from_step/timeout_s"})
            return
        # Server-Sent Events over a close-delimited HTTP/1.1 response:
        # no Content-Length, so the connection closes when the stream
        # ends — every frame is one `data:` line, flushed immediately
        h.send_response(200)
        h.send_header("Content-Type", "text/event-stream")
        h.send_header("Cache-Control", "no-store")
        h.send_header("Connection", "close")
        h.end_headers()
        try:
            for fr in self.sessions.stream(sid, from_step=from_step,
                                           timeout_s=timeout):
                h.wfile.write(b"data: " + json.dumps(fr.wire()).encode()
                              + b"\n\n")
                h.wfile.flush()
            h.wfile.write(b"event: end\ndata: " +
                          json.dumps(s.status()).encode() + b"\n\n")
            h.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # the client hung up: its cursor makes reconnect lossless
        finally:
            h.close_connection = True

    def _get(self, h) -> None:
        path, _, query = h.path.partition("?")
        params = {}
        for kv in query.split("&"):
            if "=" in kv:
                k, _, v = kv.partition("=")
                params[k] = v
        if path.startswith("/v1/sessions/"):
            self._get_session(h, path.rstrip("/"), params)
            return
        if path.startswith("/v1/meshes/"):
            self._get_mesh(h, path.rstrip("/"))
            return
        if path == "/healthz":
            m = self.backend.metrics()
            body = {"ok": m["replicas"] > 0,
                    "replicas": m["replicas"],
                    "outstanding": m["outstanding"],
                    "deaths": m["deaths"]}
            # fleet-shape evidence (ISSUE 12): which transport the
            # workers speak and whether the sharded big-case tier is
            # up (router-shaped stubs without the fields stay valid)
            if m.get("transport") is not None:
                body["transport"] = m["transport"]
            if m.get("shard_threshold") is not None:
                body["gang"] = len(m.get("gang") or [])
                body["sharded_cases"] = m.get("sharded_cases", 0)
            if self.sessions is not None:
                # session-tier liveness rides the same health document
                body["sessions"] = self.sessions._active_count()
            h._json(200, body)
            return
        if path == "/v1/status":
            self._get_status(h)
            return
        if path.startswith("/metrics"):
            regs = [self.backend.registry]
            if path.startswith("/metrics.json"):
                h._reply(200, merged_snapshot_json(regs).encode())
            else:
                h._reply(200, merged_prometheus(regs).encode(),
                         ctype="text/plain; version=0.0.4")
            return
        if not path.startswith("/v1/cases/"):
            h._json(404, {"error": f"no such endpoint {path!r}"})
            return
        rest = path[len("/v1/cases/"):]
        want_result = rest.endswith("/result")
        if want_result:
            rest = rest[:-len("/result")]
        try:
            seq = int(rest)
        except ValueError:
            h._json(400, {"error": f"bad case id {rest!r}"})
            return
        with self._lock:
            req = self._requests.get(seq)
        if req is None:
            h._json(410 if seq < self.backend.metrics()["cases"] else 404,
                    {"error": f"case {seq} unknown or aged out"})
            return
        if params.get("wait") in ("1", "true"):
            try:
                timeout = float(params.get("timeout_s") or WAIT_TIMEOUT_S)
            except ValueError:
                h._json(400, {"error": f"bad timeout_s "
                                       f"{params.get('timeout_s')!r}"})
                return
            req.done.wait(timeout)
        if not req.done.is_set():
            h._json(200, {"id": seq, "status": "queued",
                          "replica": req.replica})
            return
        self._note_done(seq)
        if req.error is not None:
            h._json(200 if not want_result else 409, {
                "id": seq, "status": "failed",
                "classification": getattr(req.error, "classification",
                                          "error"),
                "error": str(req.error)})
            return
        if not want_result:
            h._json(200, {"id": seq, "status": "done",
                          "replica": req.replica,
                          "requeues": req.requeues,
                          "latency_s": round(req.latency_s or 0.0, 6)})
            return
        if params.get("bin") in ("1", "true"):
            bio = io.BytesIO()
            np.save(bio, req.result)
            h._reply(200, bio.getvalue(),
                     ctype="application/octet-stream")
        else:
            h._json(200, {"id": seq,
                          "shape": list(req.result.shape),
                          "values": req.result.ravel().tolist()})

    def _get_status(self, h) -> None:
        """``GET /v1/status``: the one-page fleet health document
        (ISSUE 20) — replica liveness/draining/breaker/scrape-staleness,
        in-flight accounting, ingress admission counters, the session
        tier, and the SLO block (burn, drift, per-axis hit rates) when
        the ledger is on.  Assembled from state this process ALREADY
        holds (backend metrics, the registry, each replica's last
        absorbed stats frame) — a status poll never broadcasts to the
        fleet, so dashboards can hammer it.  Router-shaped stubs and
        plain pipelines stay valid: every field is read defensively."""
        m = self.backend.metrics()
        reg = getattr(self.backend, "registry", None)

        def metric(name):
            try:
                g = reg.get(name) if reg is not None else None
                return g.value if g is not None else None
            except Exception:  # noqa: BLE001 — status must render
                return None

        body = {
            "ok": (m.get("replicas") or 0) > 0 or "replicas" not in m,
            "replicas": m.get("replicas"),
            "gang": len(m.get("gang") or []),
            "transport": m.get("transport"),
            "cases": m.get("cases"),
            "outstanding": m.get("outstanding"),
            "deaths": m.get("deaths", 0),
            "requeued": m.get("requeued", 0),
            "spawns": m.get("spawns", 0),
            "scale_ups": m.get("scale_ups", 0),
            "scale_downs": m.get("scale_downs", 0),
            "buckets": m.get("buckets"),
            "request_latency_ms": m.get("request_latency_ms") or {},
            "ingress": {
                "accepted": metric("/ingress/accepted"),
                "shed": metric("/ingress/shed"),
                "retry_after_s": metric("/ingress/retry-after-s"),
                "session_steps": metric("/ingress/session-steps"),
                "session_deferred": metric("/ingress/session-deferred"),
            },
        }
        # per-replica rows: the router's routing view, the scrape
        # staleness label (ISSUE 11), and the breaker state from the
        # replica's last absorbed stats frame (no new pull)
        reps = getattr(self.backend, "_replicas", None) or {}
        per = {}
        for rid, info in (m.get("per_replica") or {}).items():
            row = dict(info)
            stale = metric(f"/replica{{{rid}}}/stale")
            if stale is not None:
                row["stale"] = bool(stale)
            frame = getattr(reps.get(rid), "last_stats", None) or {}
            br = (frame.get("metrics") or {}).get("breaker") or {}
            if br:
                row["breaker"] = {
                    "state": br.get("state"),
                    "transitions": br.get("transition_count"),
                }
            per[str(rid)] = row
        if per:
            body["per_replica"] = per
        if self.sessions is not None:
            body["sessions"] = self.sessions._active_count()
        if m.get("slo") is not None:
            body["slo"] = m["slo"]
        h._json(200, body)

    def _note_done(self, seq: int) -> None:
        """Age out old completed requests (bounded retention)."""
        with self._lock:
            self._done.setdefault(seq, None)
            while len(self._done) > RESULTS_CAP:
                old = next(iter(self._done))
                del self._done[old]
                self._requests.pop(old, None)

    def _sweep(self) -> None:
        """Move every completed-but-unnoted request into the bounded
        done window — called on each submission, so a fire-and-forget
        client that POSTs and never polls cannot grow ``_requests``
        without bound (the RESULTS_CAP promise holds without relying on
        anyone fetching).  O(retained), all bounded."""
        with self._lock:
            done = [seq for seq, req in self._requests.items()
                    if req.done.is_set() and seq not in self._done]
        for seq in done:
            self._note_done(seq)

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:  # noqa: BLE001
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def offered_load_run(admission: AdmissionController, cases, rate_hz: float,
                     *, clock=time.monotonic, sleep=time.sleep) -> dict:
    """Offer ``cases`` at a fixed rate through the admission gate and
    account the outcome — the measurement loop shared by bench.py's
    ``BENCH_ROUTER`` rung and tools/bench_table.py's ``router`` group
    (the overload-honesty half: at an offered rate past capacity the
    gate must shed with hints, the accepted requests must still finish,
    and nothing may queue without bound).  Returns accepted/shed counts,
    the accepted requests' latency percentiles, the max observed
    in-flight depth, and the wall."""
    backend = admission.backend
    cases = list(cases)
    interval = 1.0 / rate_hz if rate_hz > 0 else 0.0
    accepted, shed = [], 0
    max_pending = 0
    t0 = clock()
    next_t = t0
    for case in cases:
        now = clock()
        if interval and now < next_t:
            sleep(next_t - now)
        next_t += interval
        req, _retry = admission.try_submit(case)
        if req is None:
            shed += 1
        else:
            accepted.append(req)
        max_pending = max(max_pending, backend.outstanding_total())
    for req in accepted:
        req.done.wait()
    wall = clock() - t0
    lat = sorted(r.latency_s for r in accepted if r.latency_s is not None)

    def pct(p):
        if not lat:
            return 0.0
        return lat[min(len(lat) - 1, int(p * (len(lat) - 1)))]

    return {
        "offered": len(cases),
        "accepted": len(accepted),
        "shed": shed,
        "max_pending": max_pending,
        "wall_s": wall,
        "latency_s": {"p50": pct(0.50), "p90": pct(0.90),
                      "p99": pct(0.99)},
        "results": [r.result for r in accepted],
    }
