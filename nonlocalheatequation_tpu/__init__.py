"""TPU-native framework for nonlocal (peridynamics-type) heat/diffusion equations.

A ground-up JAX/XLA/Pallas/pjit re-design of the capabilities of
nonlocalmodels/nonlocalheatequation (reference: /root/reference): explicit
forward-Euler time stepping of

    du/dt (t,x) = b(t,x) + c * integral_{H_eps(x)} J(|y-x|/eps) (u(t,y) - u(t,x)) dy

on uniform grids, from serial CPU oracles up to a fully distributed 2D solver.
Where the reference uses HPX tile components + remote actions + ghost-region
futures, this framework uses a sharded array on a `jax.sharding.Mesh`, a
jit-compiled whole-grid (or Pallas) horizon update, and `lax.ppermute` halo
exchange over ICI.

Layer map (mirrors SURVEY.md section 1):
  ops/       stencil geometry, scaling constants, the nonlocal operator (L1/L3 kernel)
  models/    solver front-ends: 1D/2D oracles + jit paths (L3)
  parallel/  mesh/sharding, halo exchange, distributed solver, load balancing (L0/L2/L3)
  utils/     VTU + CSV writers, timing reports, partition-map IO (L4)
  cli/       command-line drivers mirroring the reference's flags (L5)
"""

__version__ = "0.1.0"
MAJOR_VERSION, MINOR_VERSION, UPDATE_VERSION = (int(x) for x in __version__.split("."))

from nonlocalheatequation_tpu.ops.constants import c_1d, c_2d, c_3d  # noqa: F401
from nonlocalheatequation_tpu.ops.stencil import (  # noqa: F401
    column_half_heights,
    horizon_mask_1d,
    horizon_mask_2d,
    horizon_mask_3d,
)
