"""Exporters: a scrape endpoint and a JSONL event log.

The registry (obs/metrics.py) and tracer (obs/trace.py) hold telemetry
in process; this module moves it OUT:

* :func:`serve_metrics` — an opt-in stdlib-HTTP endpoint (the CLIs'
  ``--metrics-port``) answering ``/metrics`` with the Prometheus text
  exposition and ``/metrics.json`` with the one-line JSON snapshot, on
  127.0.0.1 only (telemetry, not an API; a scraper runs on the host).
  The registry argument may be a callable so the endpoint follows a
  live object — the serve CLIs bind it to the running pipeline's
  registry, which is ``ServeReport``'s own backing store, so a scrape
  mid-run and the final ``metrics_json()`` dump agree by construction.
* :class:`EventLog` — an append-only JSONL stream of discrete events
  (quarantines, breaker transitions, fallback routes, retired chunks),
  enabled by ``NLHEAT_EVENT_LOG=PATH``.  Disk-backed, so memory stays
  bounded no matter how long the server lives.

Both obey the observability contract: never raise past construction,
never fence, zero cost when off (``EventLog.from_env`` returns None
when the env var is unset; emitters hold that None and skip one ``if``).
"""

from __future__ import annotations

import heapq
import json
import os
import sys
import threading
import time

#: Env var naming the JSONL event-log path (scrubbed by tests/conftest.py
#: — a leaked developer setting must not make the suite write files).
EVENT_LOG_ENV = "NLHEAT_EVENT_LOG"

#: Env var carrying the replica id the fleet router (serve/router.py)
#: assigns each worker process; EventLog stamps it (with the pid) on
#: every line so N replicas appending to one JSONL path — or N per-replica
#: files concatenated later — merge unambiguously.
REPLICA_ID_ENV = "NLHEAT_REPLICA_ID"


class EventLog:
    """Append-only JSONL event stream.  ``emit`` never raises.

    Every line carries ``pid`` and (when the process is a fleet worker,
    ``NLHEAT_REPLICA_ID``) ``replica`` — the merge keys for multi-replica
    streams — plus ``seq`` (a per-process lifetime-exact monotonic
    sequence number: interleaved multi-replica logs are totally
    orderable WITHIN each process after the fact, the ISSUE 11 bugfix)
    and ``t`` (wall clock, the cross-process merge hint
    :func:`merge_event_streams` heap-merges on).  Explicit event fields
    of the same name win."""

    def __init__(self, path: str, replica: str | int | None = None,
                 clock=time.time):
        self.path = path
        self._lock = threading.Lock()
        self._clock = clock
        self._seq = 0  # lifetime-exact, per-process
        if replica is None:
            replica = os.environ.get(REPLICA_ID_ENV)
        self._stamp = {"pid": os.getpid()}
        if replica is not None:
            self._stamp["replica"] = int(replica) \
                if str(replica).isdigit() else replica
        # line-buffered append: events from a crashed run survive
        self._f = open(path, "a", buffering=1)

    def emit(self, **event) -> None:
        try:
            with self._lock:
                seq = self._seq
                self._seq += 1
                line = json.dumps(
                    {**self._stamp, "seq": seq,
                     "t": round(self._clock(), 6), **event}, default=str)
                self._f.write(line + "\n")
        except Exception:  # noqa: BLE001 — observability never raises
            pass

    def flush(self) -> None:
        """Force buffered lines to disk (the flight recorder calls this
        before a postmortem dump so the two artifacts never disagree on
        a torn line).  Never raises."""
        try:
            with self._lock:
                self._f.flush()
                os.fsync(self._f.fileno())
        except Exception:  # noqa: BLE001
            pass

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:  # noqa: BLE001
            pass

    @classmethod
    def from_env(cls, environ=os.environ) -> "EventLog | None":
        """The opt-in hook: an EventLog when ``NLHEAT_EVENT_LOG`` is set
        and openable, else None (one loud stderr line on an unopenable
        path — a typo'd path must not silently drop the telemetry it
        asked for, and must not kill the run either)."""
        path = environ.get(EVENT_LOG_ENV)
        if not path:
            return None
        try:
            return cls(path)
        except OSError as e:
            print(f"[obs] {EVENT_LOG_ENV}={path!r} cannot be opened "
                  f"({e}); event log disabled", file=sys.stderr)
            return None


def read_jsonl(path) -> list:
    """Parse one JSONL event file tolerantly: a torn final line (a
    crashed writer) costs that line, never the file."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except ValueError:
                continue
    return events


def merge_event_streams(streams) -> list:
    """Totally order multi-process event streams (ISSUE 11 satellite).

    ``streams`` is an iterable of event-dict lists (e.g. one
    :func:`read_jsonl` per replica file, or one combined file N
    replicas appended to).  Events are grouped by their process
    identity ``(pid, replica)``; WITHIN a process the per-process
    ``seq`` is authoritative (lifetime-exact, gap-free — clock skew can
    never reorder one process's own story); ACROSS processes the groups
    are heap-merged on the wall-clock ``t`` stamp of each group's head.
    Pre-seq lines (older logs) sort first within their process, in
    arrival order."""
    groups: dict = {}
    for events in streams:
        for i, ev in enumerate(events):
            key = (ev.get("pid"), ev.get("replica"))
            groups.setdefault(key, []).append((ev.get("seq", -1), i, ev))
    runs = []
    for key in sorted(groups, key=lambda k: (str(k[0]), str(k[1]))):
        run = [ev for _seq, _i, ev in sorted(groups[key],
                                             key=lambda x: (x[0], x[1]))]
        runs.append(run)
    heap = []
    for gi, run in enumerate(runs):
        if run:
            heapq.heappush(heap, (run[0].get("t", 0.0) or 0.0, gi, 0))
    out = []
    while heap:
        _t, gi, i = heapq.heappop(heap)
        out.append(runs[gi][i])
        if i + 1 < len(runs[gi]):
            heapq.heappush(
                heap, (runs[gi][i + 1].get("t", 0.0) or 0.0, gi, i + 1))
    return out


def merged_prometheus(registries) -> str:
    """One text exposition covering several registries (the fleet
    router's own registry plus any per-process ones).  Family TYPE lines
    are deduplicated on first sight; callers keep metric NAMES disjoint
    across registries (the router's per-replica ``/replica{r}`` prefixes
    do) so each family's samples stay contiguous as the format wants."""
    seen: set = set()
    lines: list[str] = []
    for reg in registries:
        for line in reg.prometheus().splitlines():
            if line.startswith("# TYPE"):
                if line in seen:
                    continue
                seen.add(line)
            if line:
                lines.append(line)
    return "\n".join(lines) + "\n"


def merged_snapshot_json(registries) -> str:
    """The one-line JSON twin of :func:`merged_prometheus` (later
    registries win on a (disjoint-by-convention) name clash)."""
    merged: dict = {}
    for reg in registries:
        merged.update(reg.snapshot())
    return json.dumps(merged, default=float)


class MetricsServer:
    """The ``--metrics-port`` scrape endpoint (127.0.0.1 only).

    ``registry`` may be a registry, a zero-arg callable returning one
    (a live binding), or — either way — a LIST/TUPLE of registries: the
    scrape then AGGREGATES them into one exposition (the fleet form:
    the router's registry, already carrying absorbed ``/replica{r}``
    snapshots, plus any sibling process-local registries)."""

    def __init__(self, port: int, registry):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        get_registry = registry if callable(registry) else (lambda: registry)

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                try:
                    reg = get_registry()
                    regs = (list(reg) if isinstance(reg, (list, tuple))
                            else [reg])
                    if self.path.startswith("/metrics.json"):
                        body = merged_snapshot_json(regs).encode()
                        ctype = "application/json"
                    elif self.path.startswith("/metrics"):
                        body = merged_prometheus(regs).encode()
                        ctype = "text/plain; version=0.0.4"
                    else:
                        self.send_error(404)
                        return
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except Exception:  # noqa: BLE001 — a scrape must not kill us
                    try:
                        self.send_error(500)
                    except Exception:  # noqa: BLE001
                        pass

            def log_message(self, *a):  # silence per-request stderr chatter
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", int(port)), Handler)
        self.port = self._httpd.server_address[1]  # resolved (port 0 = any)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="nlheat-metrics")
        self._thread.start()

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:  # noqa: BLE001
            pass


def serve_metrics(port: int, registry) -> MetricsServer:
    """Start the scrape endpoint; ``registry`` is a MetricsRegistry or a
    zero-arg callable returning one (a live binding)."""
    return MetricsServer(port, registry)
