"""Process-wide counter/gauge/histogram registry with HPX-style names.

The reference's observability backbone is HPX's performance-counter
namespace — hierarchical names like ``/threads{locality#0/total}/idle-rate``
read live by the load balancer (src/2d_nonlocal_distributed.cpp:112-128,
sampled :856-863).  This module is that backbone for the TPU framework:
one registry of named metrics that the serving reports
(serve/server.py ``ServeReport``, serve/ensemble.py ``EnsembleReport``),
the load-balance busy rates (parallel/load_balance.py), the AOT
program store's hit/miss/refusal counters and load/serialize timings
(serve/program_store.py, ``/store/*``), and the solver /
checkpoint / autotune counters all WRITE THROUGH — the reports' fields
are properties over registry metrics, so ``ServeReport.metrics()`` and
the registry's Prometheus/JSON expositions read the same storage and
cannot disagree.

Name grammar (the HPX counter shape)::

    /object/counter               e.g. /serve/retries
    /object{instance}/counter     e.g. /device{3}/busy-rate

Metric kinds:

* :class:`Counter` / :class:`Gauge` — one number (counters also accept
  ``set`` so a report field can be assigned, e.g. ``report.cases += 1``
  through its property).
* :class:`Histogram` — a WINDOWED sample deque (most recent ``window``
  observations feed the percentiles) plus lifetime-exact ``count`` and
  ``total`` — a long-lived server must not grow host memory with its
  request count (serve/server.py LOG_CAP discipline).
* :class:`Trail` — a windowed deque of arbitrary entries (chunk logs,
  occupancy samples, quarantine records) with a lifetime-exact
  ``count`` — the windowed-trail + exact-count pattern the breaker
  transition log introduced (serve/resilience.py TRANSITION_CAP).
* :class:`LabeledCounters` — a dict of label -> count (fault
  classifications, forced-close reasons); each key is lifetime-exact.

Expositions: :meth:`MetricsRegistry.snapshot` (plain dict),
:meth:`MetricsRegistry.snapshot_json` (ONE line), and
:meth:`MetricsRegistry.prometheus` (text exposition format, names
sanitized ``/device{3}/busy-rate`` -> ``nlheat_device_busy_rate{device="3"}``).

Hard rules: recording never raises past registration time, never fences
or touches a device (host-side numbers only), and memory is bounded
(windows + a fixed set of names).  ``REGISTRY`` is the process-wide
default; reports default to a PRIVATE registry each so concurrent
engines never share counters — the serving pipeline exposes its
report's registry for scraping (obs/export.py).
"""

from __future__ import annotations

import json
import re
import threading
from collections import deque

import numpy as np

#: Default histogram/trail window (mirrors serve/server.py LOG_CAP).
DEFAULT_WINDOW = 4096


def _stable_copy(make_copy, default):
    """Copy a container a recorder thread may be appending to: CPython
    deque/dict iteration raises RuntimeError when it races a writer, and
    the scrape endpoint (obs/export.py) reads these from its handler
    thread while the pipeline records.  Retry the copy (the window is
    one append wide), then fall back to ``default`` — exposition must
    never raise."""
    for _ in range(8):
        try:
            return make_copy()
        except RuntimeError:
            continue
    return default


class Counter:
    """A single monotonically-growing number (``set`` exists so report
    fields can be written through properties)."""

    kind = "counter"
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n=1):
        self.value += n

    def set(self, v):
        self.value = v

    def snapshot(self):
        return self.value


class Gauge(Counter):
    """A single settable number (depth, window size, busy rate)."""

    kind = "gauge"
    __slots__ = ()


class Histogram:
    """Windowed numeric samples + lifetime-exact count/total."""

    kind = "histogram"

    def __init__(self, name: str, window: int = DEFAULT_WINDOW):
        self.name = name
        self.samples: deque = deque(maxlen=int(window))
        self.count = 0  # lifetime-exact
        self.total = 0.0  # lifetime-exact

    def observe(self, v):
        self.samples.append(v)
        self.count += 1
        self.total += v

    # deque-compatible alias: report code appends samples
    append = observe

    def __iter__(self):
        return iter(self.samples)

    def __len__(self):
        return len(self.samples)

    def __bool__(self):
        return bool(self.samples)

    def percentiles(self) -> dict:
        xs = _stable_copy(lambda: list(self.samples), [])
        if not xs:
            return {}
        a = np.asarray(xs, np.float64)
        return {
            "p50": float(np.percentile(a, 50)),
            "p90": float(np.percentile(a, 90)),
            "p99": float(np.percentile(a, 99)),
            "mean": float(a.mean()),
            "max": float(a.max()),
        }

    def snapshot(self):
        return {"count": self.count, "sum": float(self.total),
                **self.percentiles()}


class Trail:
    """Windowed deque of arbitrary entries + lifetime-exact count."""

    kind = "trail"

    def __init__(self, name: str, window: int = DEFAULT_WINDOW):
        self.name = name
        self.entries: deque = deque(maxlen=int(window))
        self.count = 0  # lifetime-exact

    def append(self, entry):
        self.entries.append(entry)
        self.count += 1

    def __iter__(self):
        return iter(self.entries)

    def __len__(self):
        return len(self.entries)

    def __getitem__(self, i):
        return self.entries[i]

    def __bool__(self):
        return bool(self.entries)

    def snapshot(self):
        return {"count": self.count, "window": len(self.entries)}


class LabeledCounters(dict):
    """label -> lifetime-exact count; a dict, so report code that does
    ``d[k] = d.get(k, 0) + 1`` (and tests comparing against plain dicts)
    works unchanged while the registry exposes every label."""

    kind = "labeled"

    def __init__(self, name: str):
        super().__init__()
        self.name = name

    def snapshot(self):
        return _stable_copy(lambda: dict(self), {})


class backed:
    """Descriptor: a report field stored IN a registry metric — reads and
    writes go straight to the metric's ``value``, so the report and the
    registry expositions share one storage (they cannot disagree)."""

    def __init__(self, attr: str):
        self._attr = attr

    def __get__(self, obj, owner=None):
        if obj is None:
            return self
        return getattr(obj, self._attr).value

    def __set__(self, obj, v):
        getattr(obj, self._attr).set(v)


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram,
          "trail": Trail, "labeled": LabeledCounters}


class MetricsRegistry:
    """Thread-safe name -> metric store with the expositions above."""

    def __init__(self):
        self._metrics: dict = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, *args)
            elif type(m) is not cls:
                # registration-time programming error: one name, one kind
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, window: int = DEFAULT_WINDOW) -> Histogram:
        return self._get(name, Histogram, window)

    def trail(self, name: str, window: int = DEFAULT_WINDOW) -> Trail:
        return self._get(name, Trail, window)

    def labeled(self, name: str) -> LabeledCounters:
        return self._get(name, LabeledCounters)

    def get(self, name: str):
        return self._metrics.get(name)

    def names(self):
        with self._lock:
            return sorted(self._metrics)

    def reset(self) -> None:
        """Drop every metric (tests; a live process never resets)."""
        with self._lock:
            self._metrics.clear()

    def drop_prefix(self, prefix: str) -> int:
        """Drop every metric whose name starts with ``prefix`` — the
        fleet-scrape staleness hook (ISSUE 11 satellite): a dead/drained
        replica's absorbed ``/replica{r}/...`` gauges must not linger in
        the merged exposition forever.  Returns the number dropped."""
        with self._lock:
            doomed = [n for n in self._metrics if n.startswith(prefix)]
            for n in doomed:
                del self._metrics[n]
        return len(doomed)

    # -- expositions --------------------------------------------------------
    def snapshot(self) -> dict:
        """Plain name -> value dict (counters/gauges as numbers,
        histograms as count/sum/percentiles, trails as count/window,
        labeled counters as dicts)."""
        with self._lock:
            items = sorted(self._metrics.items())
        return {name: m.snapshot() for name, m in items}

    def snapshot_json(self) -> str:
        """The one-line JSON form of :meth:`snapshot`."""
        return json.dumps(self.snapshot(), default=float)

    def prometheus(self) -> str:
        """Prometheus text exposition of every metric."""
        with self._lock:
            items = sorted(self._metrics.items())
        families: dict = {}  # metric name -> (type, [sample lines])

        def add(metric, ptype, line):
            fam = families.setdefault(metric, (ptype, []))
            fam[1].append(line)

        for name, m in items:
            metric, labels = _prom_name(name)
            if isinstance(m, (Counter, Gauge)):  # Gauge subclasses Counter
                ptype = "gauge" if isinstance(m, Gauge) else "counter"
                add(metric, ptype,
                    f"{metric}{_labels(labels)} {_num(m.value)}")
            elif isinstance(m, Histogram):
                p = m.percentiles()
                for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                    if p:
                        add(metric, "summary",
                            f"{metric}{_labels(labels + [('quantile', str(q))])}"
                            f" {_num(p[key])}")
                add(metric, "summary",
                    f"{metric}_count{_labels(labels)} {m.count}")
                add(metric, "summary",
                    f"{metric}_sum{_labels(labels)} {_num(m.total)}")
            elif isinstance(m, Trail):
                add(metric + "_count", "counter",
                    f"{metric}_count{_labels(labels)} {m.count}")
            elif isinstance(m, LabeledCounters):
                snap = m.snapshot()  # race-stable copy
                for k in sorted(snap):
                    add(metric, "counter",
                        f"{metric}{_labels(labels + [('key', str(k))])}"
                        f" {_num(snap[k])}")
                if not snap:
                    add(metric, "counter", None)  # TYPE line only
        lines = []
        for metric in sorted(families):
            ptype, samples = families[metric]
            lines.append(f"# TYPE {metric} {ptype}")
            lines.extend(s for s in samples if s is not None)
        return "\n".join(lines) + "\n"


_SEG_RE = re.compile(r"^([^{}]+)(?:\{(.*)\})?$")


def _prom_name(name: str):
    """``/device{3}/busy-rate`` -> (``nlheat_device_busy_rate``,
    [("device", "3")])."""
    parts, labels = [], []
    for seg in (s for s in name.split("/") if s):
        m = _SEG_RE.match(seg)
        base, inst = (m.group(1), m.group(2)) if m else (seg, None)
        clean = re.sub(r"[^0-9A-Za-z_]", "_", base)
        parts.append(clean)
        if inst is not None:
            labels.append((clean, inst))
    return "nlheat_" + "_".join(parts), labels


def _esc(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _labels(items) -> str:
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_esc(v)}"' for k, v in items) + "}"


def _num(v) -> str:
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    return format(float(v), ".10g")


def absorb_snapshot(registry: MetricsRegistry, prefix: str,
                    snapshot: dict) -> None:
    """Flatten a FOREIGN registry snapshot (another process's
    ``MetricsRegistry.snapshot()``, shipped over an IPC boundary) into
    ``registry`` under ``prefix`` — the replica router's per-replica
    metric namespaces: a worker's ``/serve/retries`` lands as
    ``/replica{3}/serve/retries``, so one scrape of the router registry
    exposes the whole fleet with the replica as a Prometheus label
    (the ``{instance}`` name grammar above).

    Scalars land as gauges verbatim (a snapshot is a point-in-time copy
    — monotonicity is the source registry's business); dict-valued
    entries (histogram count/sum/percentiles, trail counts, labeled
    counters) flatten one level to ``/name/<field>`` sub-gauges;
    non-numeric leaves are skipped.  Never raises past argument errors
    (absorbing telemetry must not fail the router)."""
    for name, val in snapshot.items():
        base = prefix + name
        try:
            if isinstance(val, bool):
                registry.gauge(base).set(int(val))
            elif isinstance(val, (int, float)):
                registry.gauge(base).set(val)
            elif isinstance(val, dict):
                for k, v in val.items():
                    if isinstance(v, bool):
                        registry.gauge(f"{base}/{k}").set(int(v))
                    elif isinstance(v, (int, float)):
                        registry.gauge(f"{base}/{k}").set(v)
        except Exception:  # noqa: BLE001 — e.g. a name/kind clash with a
            continue  # router-owned metric; skip the entry, keep the rest


#: The process-wide default registry: solver/checkpoint/autotune counters
#: and the load-balance busy-rate gauges publish here.  Reports default
#: to a private registry each (see the module docstring).
REGISTRY = MetricsRegistry()
