"""Bounded span tracer exporting Chrome trace-event JSON (Perfetto).

The reference's only timeline is wall-clock CSV around ``do_work``
(src/2d_nonlocal_distributed.cpp:1390-1395); the framework's device-side
timeline is the ``jax.profiler`` capture (utils/profiling.py).  This
module adds the HOST-side timeline between them: named spans around the
serving pipeline's stages (window close, build/stage/dispatch,
fence/fetch, retries, bisection, breaker transitions, fallback routes —
serve/server.py), the ensemble engine's chunk lifecycle, solver
``do_work`` step batches, checkpoint save/load, and autotune probes —
exported in the Chrome trace-event format
(https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU)
so one file loads in ui.perfetto.dev next to the profiler capture (the
CLI ``--trace DIR`` flag captures both into the same directory).

Hard rules (the observability contract, docs/architecture.md):

* **never raises** — every record path swallows its own failures;
* **never fences** — timestamps are host clock reads the instrumented
  code mostly already makes; fetch spans reuse the fences the pipeline
  performs anyway (``Tracer.complete`` takes the CALLER's timestamps,
  so tracing adds no clock reads on timed paths);
* **bounded** — a ring buffer of ``capacity`` events (oldest evicted),
  with a lifetime-exact ``spans_total``;
* **zero-cost when off** — the module-level :func:`span`/:func:`instant`
  helpers are no-ops (one attribute read) until :func:`set_tracer`
  installs a tracer, so the disabled path of every instrumented module
  stays byte-for-byte on its old schedule (PR 3's fence-discipline and
  bit-identity tests run with tracing off and pass untouched).

The clock is injectable: tests drive a virtual clock and assert golden
span sequences deterministically (tests/test_obs.py).
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time
from collections import deque

#: Default ring-buffer capacity (events).  At ~6 events per served chunk
#: this holds hours of serving; the cap is the point — a long-lived
#: server must not grow host memory with its request count.
DEFAULT_CAPACITY = 65536


class _NullSpan:
    """The shared no-op context manager the disabled path returns."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()

#: Explicit "tracing OFF" sentinel for constructors whose ``tracer=None``
#: means "inherit the process-global tracer" (serve/server.py
#: ServePipeline): pass TRACE_OFF to force the untraced path even when a
#: global tracer is installed — the A/B baseline in serve_traced_ab
#: must never silently trace both arms.
TRACE_OFF = _NullSpan()


class _Span:
    """Context manager recording one complete ('X') event on exit."""

    __slots__ = ("_tracer", "_name", "_cat", "_tid", "_args", "_t0")

    def __init__(self, tracer, name, cat, tid, args):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._tid = tid
        self._args = args

    def __enter__(self):
        try:
            self._t0 = self._tracer._clock()
        except Exception:  # noqa: BLE001 — observability never raises
            self._t0 = 0.0
        return self

    def __exit__(self, exc_type, exc, tb):
        args = self._args
        if exc_type is not None:
            args = {**args, "error": exc_type.__name__}
        self._tracer.complete(self._name, self._t0, cat=self._cat,
                              tid=self._tid, **args)
        return False


class Tracer:
    """Thread-safe bounded span recorder with an injectable clock.

    ``complete``/``instant``/``counter`` append one Chrome trace event
    each; ``span`` is the context-manager form.  ``chrome_trace`` returns
    the loadable document; ``write`` saves it (never raises).
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 clock=time.monotonic, pid: int | None = None):
        capacity = int(capacity)
        if capacity < 1:
            raise ValueError(f"tracer capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.events: deque = deque(maxlen=capacity)
        self._clock = clock
        self._lock = threading.Lock()
        self.pid = os.getpid() if pid is None else int(pid)
        self.spans_total = 0  # lifetime-exact (evictions included)

    def _emit(self, ev: dict) -> None:
        try:
            with self._lock:
                self.events.append(ev)
                self.spans_total += 1
        except Exception:  # noqa: BLE001 — observability never raises
            pass

    def complete(self, name: str, t0: float, t1: float | None = None,
                 cat: str = "", tid: int = 0, **args) -> None:
        """One complete ('X') span from the CALLER's host-clock
        timestamps in seconds — no extra clock reads on timed paths
        (``t1=None`` reads the tracer clock once)."""
        try:
            if t1 is None:
                t1 = self._clock()
            ev = {"name": name, "cat": cat or "nlheat", "ph": "X",
                  "ts": round(t0 * 1e6, 3),
                  "dur": round(max(0.0, t1 - t0) * 1e6, 3),
                  "pid": self.pid, "tid": int(tid)}
            if args:
                ev["args"] = args
            self._emit(ev)
        except Exception:  # noqa: BLE001
            pass

    def instant(self, name: str, ts: float | None = None, cat: str = "",
                tid: int = 0, **args) -> None:
        """One instant ('i') event (retry, bisect, breaker move...)."""
        try:
            if ts is None:
                ts = self._clock()
            ev = {"name": name, "cat": cat or "nlheat", "ph": "i", "s": "t",
                  "ts": round(ts * 1e6, 3), "pid": self.pid, "tid": int(tid)}
            if args:
                ev["args"] = args
            self._emit(ev)
        except Exception:  # noqa: BLE001
            pass

    def counter(self, name: str, ts: float | None = None, tid: int = 0,
                **values) -> None:
        """One counter ('C') sample — Perfetto renders these as tracks
        (the pipeline samples chunks-in-flight here)."""
        try:
            if ts is None:
                ts = self._clock()
            self._emit({"name": name, "cat": "nlheat", "ph": "C",
                        "ts": round(ts * 1e6, 3), "pid": self.pid,
                        "tid": int(tid), "args": values})
        except Exception:  # noqa: BLE001
            pass

    def span(self, name: str, cat: str = "", tid: int = 0, **args) -> _Span:
        return _Span(self, name, cat, tid, args)

    def __len__(self) -> int:
        return len(self.events)

    def chrome_trace(self) -> dict:
        """The Perfetto-loadable document."""
        with self._lock:
            events = list(self.events)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def write(self, path: str) -> bool:
        """Save :meth:`chrome_trace` to ``path``.  Never raises (a trace
        that cannot be written must not kill the solve it observed);
        returns False and prints to stderr on failure."""
        try:
            doc = self.chrome_trace()
            # tmp + rename, hostname+pid disambiguated (the
            # utils/checkpoint.atomic_file discipline): concurrent
            # writers — distributed ranks sharing a filesystem — each
            # land a COMPLETE document; a reader can never observe
            # interleaved or truncated JSON that Perfetto rejects
            # id(self) on top of hostname+pid: two tracers flushed from
            # threads of one process must not share a tmp either
            tmp = (f"{path}.tmp.{socket.gethostname()}"
                   f".{os.getpid()}.{id(self)}")
            with open(tmp, "w") as f:
                # default=str: one exotic span arg (a numpy scalar, a
                # Path) must degrade to its repr, not discard the whole
                # artifact (obs/export.py EventLog.emit does the same)
                json.dump(doc, f, default=str)
            os.replace(tmp, path)
            return True
        except Exception as e:  # noqa: BLE001
            try:
                print(f"[obs] trace write to {path!r} failed: {e!r}",
                      file=sys.stderr)
            except Exception:  # noqa: BLE001
                pass
            return False


_tracer: Tracer | None = None


def get_tracer() -> Tracer | None:
    return _tracer


def set_tracer(tracer: Tracer | None) -> Tracer | None:
    """Install the process-global tracer (None disables); returns the
    previous one so callers can restore it."""
    global _tracer
    prev = _tracer
    _tracer = tracer
    return prev


def span(name: str, cat: str = "", **args):
    """Module-level span helper: a real span under the global tracer,
    the shared no-op context otherwise (one attribute read — the
    zero-cost disabled path)."""
    t = _tracer
    if t is None:
        return NULL_SPAN
    return t.span(name, cat=cat, **args)


def instant(name: str, cat: str = "", **args) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, cat=cat, **args)
