"""Bounded span tracer exporting Chrome trace-event JSON (Perfetto).

The reference's only timeline is wall-clock CSV around ``do_work``
(src/2d_nonlocal_distributed.cpp:1390-1395); the framework's device-side
timeline is the ``jax.profiler`` capture (utils/profiling.py).  This
module adds the HOST-side timeline between them: named spans around the
serving pipeline's stages (window close, build/stage/dispatch,
fence/fetch, retries, bisection, breaker transitions, fallback routes —
serve/server.py), the ensemble engine's chunk lifecycle, solver
``do_work`` step batches, checkpoint save/load, and autotune probes —
exported in the Chrome trace-event format
(https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU)
so one file loads in ui.perfetto.dev next to the profiler capture (the
CLI ``--trace DIR`` flag captures both into the same directory).

Hard rules (the observability contract, docs/architecture.md):

* **never raises** — every record path swallows its own failures;
* **never fences** — timestamps are host clock reads the instrumented
  code mostly already makes; fetch spans reuse the fences the pipeline
  performs anyway (``Tracer.complete`` takes the CALLER's timestamps,
  so tracing adds no clock reads on timed paths);
* **bounded** — a ring buffer of ``capacity`` events (oldest evicted),
  with a lifetime-exact ``spans_total``;
* **zero-cost when off** — the module-level :func:`span`/:func:`instant`
  helpers are no-ops (one attribute read) until :func:`set_tracer`
  installs a tracer, so the disabled path of every instrumented module
  stays byte-for-byte on its old schedule (PR 3's fence-discipline and
  bit-identity tests run with tracing off and pass untouched).

The clock is injectable: tests drive a virtual clock and assert golden
span sequences deterministically (tests/test_obs.py).

Fleet extension (ISSUE 11): :class:`TraceContext` is the compact
request identity minted at the HTTP ingress (serve/http.py) — or by
``ReplicaRouter.submit`` for non-HTTP entry — carried on the router's
length-prefixed frames and as the ``X-NLHEAT-Trace`` header, and
re-installed in the worker (:func:`set_context`) so every span a replica
records while serving that request carries the originating ``trace``
id.  :meth:`Tracer.flow` emits Chrome *flow* events (``s``/``t``/``f``)
tying the ingress span -> router dispatch -> worker chunk across
processes, and :func:`merge_chrome_traces` aligns per-process monotonic
clocks (the ``clock_sync`` pair each tracer captures at construction,
exchanged on the worker hello frame) into ONE Perfetto-loadable
timeline with pid = replica.  The disabled path is unchanged: no
context is ever read unless a tracer is emitting.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time
from collections import deque

#: Default ring-buffer capacity (events).  At ~6 events per served chunk
#: this holds hours of serving; the cap is the point — a long-lived
#: server must not grow host memory with its request count.
DEFAULT_CAPACITY = 65536


class _NullSpan:
    """The shared no-op context manager the disabled path returns."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class TraceContext:
    """The compact cross-process request identity (ISSUE 11).

    ``trace_id`` is the request's fleet-wide identity (16 hex chars —
    also the Chrome flow-event ``id``); ``span_id`` names the parent
    span that minted/forwarded it (the ingress request span, then the
    router dispatch); ``request`` is the router's case seq when known.
    Wire forms: :meth:`to_wire` (a plain tuple riding the router's
    pickle frames) and :meth:`to_header`/:meth:`from_header` (the
    ``X-NLHEAT-Trace`` HTTP header, ``trace_id[:span_id[:request]]``).
    """

    __slots__ = ("trace_id", "span_id", "request")

    def __init__(self, trace_id: str, span_id: str | None = None,
                 request: int | None = None):
        self.trace_id = str(trace_id)
        self.span_id = span_id
        self.request = request

    @classmethod
    def mint(cls, span_id: str | None = None,
             request: int | None = None) -> "TraceContext":
        """A fresh random identity (the ingress / first-touch path)."""
        return cls(os.urandom(8).hex(), span_id, request)

    def child(self, span_id: str) -> "TraceContext":
        """The same trace continuing under a new parent span."""
        return TraceContext(self.trace_id, span_id, self.request)

    # -- wire forms ---------------------------------------------------------
    def to_wire(self) -> tuple:
        return (self.trace_id, self.span_id, self.request)

    @classmethod
    def from_wire(cls, wire) -> "TraceContext | None":
        """Tolerant decode (a malformed frame field must cost the trace,
        never the case): None/garbage -> None."""
        try:
            if not wire:
                return None
            tid = str(wire[0])
            sid = wire[1] if len(wire) > 1 and wire[1] is not None else None
            req = int(wire[2]) if len(wire) > 2 and wire[2] is not None \
                else None
            return cls(tid, None if sid is None else str(sid), req)
        except Exception:  # noqa: BLE001 — observability never raises
            return None

    def to_header(self) -> str:
        parts = [self.trace_id]
        if self.span_id is not None or self.request is not None:
            parts.append(self.span_id or "")
        if self.request is not None:
            parts.append(str(self.request))
        return ":".join(parts)

    @classmethod
    def from_header(cls, header: str) -> "TraceContext | None":
        try:
            parts = [p.strip() for p in str(header).split(":")]
            if not parts or not parts[0]:
                return None
            sid = parts[1] if len(parts) > 1 and parts[1] else None
            req = int(parts[2]) if len(parts) > 2 and parts[2] else None
            return cls(parts[0], sid, req)
        except Exception:  # noqa: BLE001
            return None

    def __repr__(self):
        return (f"TraceContext({self.trace_id!r}, span_id={self.span_id!r}, "
                f"request={self.request!r})")


#: Thread-local current trace context.  Emitters never read it unless a
#: tracer is actually recording (the disabled path stays one attribute
#: read); when set, every event a tracer emits on this thread carries
#: ``args.trace`` (+ ``args.req``) so existing ServePipeline / ensemble /
#: program-store spans nest under the originating request with ZERO
#: changes at their call sites.
_context = threading.local()


def current_context() -> TraceContext | None:
    return getattr(_context, "value", None)


def set_context(ctx: TraceContext | None) -> TraceContext | None:
    """Install the thread's current trace context (None clears); returns
    the previous one so callers can restore it."""
    prev = getattr(_context, "value", None)
    _context.value = ctx
    return prev

#: Explicit "tracing OFF" sentinel for constructors whose ``tracer=None``
#: means "inherit the process-global tracer" (serve/server.py
#: ServePipeline): pass TRACE_OFF to force the untraced path even when a
#: global tracer is installed — the A/B baseline in serve_traced_ab
#: must never silently trace both arms.
TRACE_OFF = _NullSpan()


class _Span:
    """Context manager recording one complete ('X') event on exit."""

    __slots__ = ("_tracer", "_name", "_cat", "_tid", "_args", "_t0")

    def __init__(self, tracer, name, cat, tid, args):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._tid = tid
        self._args = args

    def __enter__(self):
        try:
            self._t0 = self._tracer._clock()
        except Exception:  # noqa: BLE001 — observability never raises
            self._t0 = 0.0
        return self

    def __exit__(self, exc_type, exc, tb):
        args = self._args
        if exc_type is not None:
            args = {**args, "error": exc_type.__name__}
        self._tracer.complete(self._name, self._t0, cat=self._cat,
                              tid=self._tid, **args)
        return False


class Tracer:
    """Thread-safe bounded span recorder with an injectable clock.

    ``complete``/``instant``/``counter`` append one Chrome trace event
    each; ``span`` is the context-manager form.  ``chrome_trace`` returns
    the loadable document; ``write`` saves it (never raises).
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 clock=time.monotonic, pid: int | None = None,
                 label: str | None = None, replica=None,
                 clock_sync: dict | None = None):
        capacity = int(capacity)
        if capacity < 1:
            raise ValueError(f"tracer capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.events: deque = deque(maxlen=capacity)
        self._clock = clock
        self._lock = threading.Lock()
        self.pid = os.getpid() if pid is None else int(pid)
        self.spans_total = 0  # lifetime-exact (evictions included)
        #: merge identity (ISSUE 11): a display label ("router",
        #: "replica 3"), the replica id (defaults to the fleet worker's
        #: NLHEAT_REPLICA_ID — obs/export.py REPLICA_ID_ENV), and the
        #: (monotonic, wall) clock pair captured ONCE here so
        #: merge_chrome_traces can align this process's monotonic-epoch
        #: timestamps with every other process's.  ``clock_sync`` is
        #: injectable for deterministic merge tests.
        self.label = label
        if replica is None:
            replica = os.environ.get("NLHEAT_REPLICA_ID")
        self.replica = int(replica) if replica is not None \
            and str(replica).isdigit() else replica
        if clock_sync is None:
            try:
                clock_sync = {"monotonic": time.monotonic(),
                              "wall": time.time()}
            except Exception:  # noqa: BLE001 — observability never raises
                clock_sync = None
        self.clock_sync = clock_sync

    def _emit(self, ev: dict) -> None:
        try:
            # stamp the thread's current TraceContext (fleet tracing):
            # only ever read while a tracer is RECORDING, so the
            # disabled path never touches it; explicit per-event args
            # of the same name win (setdefault).  Counter ('C') events
            # are exempt — every args key of a counter is a PLOTTED
            # SERIES in Perfetto, and a stamp would graft bogus
            # trace/req tracks onto e.g. the inflight counter
            ctx = getattr(_context, "value", None)
            if ctx is not None and ev.get("ph") != "C":
                args = ev.setdefault("args", {})
                args.setdefault("trace", ctx.trace_id)
                if ctx.request is not None:
                    args.setdefault("req", ctx.request)
            with self._lock:
                self.events.append(ev)
                self.spans_total += 1
        except Exception:  # noqa: BLE001 — observability never raises
            pass

    def complete(self, name: str, t0: float, t1: float | None = None,
                 cat: str = "", tid: int = 0, **args) -> None:
        """One complete ('X') span from the CALLER's host-clock
        timestamps in seconds — no extra clock reads on timed paths
        (``t1=None`` reads the tracer clock once)."""
        try:
            if t1 is None:
                t1 = self._clock()
            ev = {"name": name, "cat": cat or "nlheat", "ph": "X",
                  "ts": round(t0 * 1e6, 3),
                  "dur": round(max(0.0, t1 - t0) * 1e6, 3),
                  "pid": self.pid, "tid": int(tid)}
            if args:
                ev["args"] = args
            self._emit(ev)
        except Exception:  # noqa: BLE001
            pass

    def instant(self, name: str, ts: float | None = None, cat: str = "",
                tid: int = 0, **args) -> None:
        """One instant ('i') event (retry, bisect, breaker move...)."""
        try:
            if ts is None:
                ts = self._clock()
            ev = {"name": name, "cat": cat or "nlheat", "ph": "i", "s": "t",
                  "ts": round(ts * 1e6, 3), "pid": self.pid, "tid": int(tid)}
            if args:
                ev["args"] = args
            self._emit(ev)
        except Exception:  # noqa: BLE001
            pass

    def counter(self, name: str, ts: float | None = None, tid: int = 0,
                **values) -> None:
        """One counter ('C') sample — Perfetto renders these as tracks
        (the pipeline samples chunks-in-flight here)."""
        try:
            if ts is None:
                ts = self._clock()
            self._emit({"name": name, "cat": "nlheat", "ph": "C",
                        "ts": round(ts * 1e6, 3), "pid": self.pid,
                        "tid": int(tid), "args": values})
        except Exception:  # noqa: BLE001
            pass

    _FLOW_PH = {"start": "s", "step": "t", "finish": "f"}

    def flow(self, name: str, phase: str, flow_id, ts: float | None = None,
             cat: str = "flow", tid: int = 0, **args) -> None:
        """One Chrome flow event tying spans across pids: ``phase`` is
        "start" (the ingress), "step" (the router dispatch), or "finish"
        (the worker chunk retire — bound to its ENCLOSING slice via
        ``bp: "e"``); ``flow_id`` is the request's trace_id.  Perfetto
        draws one arrow chain per id across the merged timeline."""
        try:
            ph = self._FLOW_PH[phase]
            if ts is None:
                ts = self._clock()
            ev = {"name": name, "cat": cat or "flow", "ph": ph,
                  "id": str(flow_id), "ts": round(ts * 1e6, 3),
                  "pid": self.pid, "tid": int(tid)}
            if ph == "f":
                ev["bp"] = "e"
            if args:
                ev["args"] = args
            self._emit(ev)
        except Exception:  # noqa: BLE001
            pass

    def span(self, name: str, cat: str = "", tid: int = 0, **args) -> _Span:
        return _Span(self, name, cat, tid, args)

    def __len__(self) -> int:
        return len(self.events)

    def chrome_trace(self) -> dict:
        """The Perfetto-loadable document.  ``metadata`` carries the
        merge identity (clock_sync/pid/replica/label) — extra top-level
        keys are legal in the Chrome trace format and ignored by
        Perfetto; :func:`merge_chrome_traces` reads them."""
        with self._lock:
            events = list(self.events)
        meta = {"pid": self.pid}
        if self.clock_sync is not None:
            meta["clock_sync"] = dict(self.clock_sync)
        if self.replica is not None:
            meta["replica"] = self.replica
        if self.label is not None:
            meta["label"] = self.label
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "metadata": meta}

    def write(self, path: str) -> bool:
        """Save :meth:`chrome_trace` to ``path``.  Never raises (a trace
        that cannot be written must not kill the solve it observed);
        returns False and prints to stderr on failure.  One shared
        atomic-write body (:func:`write_chrome_trace`) serves both this
        and the merged-timeline writers."""
        return write_chrome_trace(self.chrome_trace(), path)


_tracer: Tracer | None = None


def get_tracer() -> Tracer | None:
    return _tracer


def set_tracer(tracer: Tracer | None) -> Tracer | None:
    """Install the process-global tracer (None disables); returns the
    previous one so callers can restore it."""
    global _tracer
    prev = _tracer
    _tracer = tracer
    return prev


def span(name: str, cat: str = "", **args):
    """Module-level span helper: a real span under the global tracer,
    the shared no-op context otherwise (one attribute read — the
    zero-cost disabled path)."""
    t = _tracer
    if t is None:
        return NULL_SPAN
    return t.span(name, cat=cat, **args)


def instant(name: str, cat: str = "", **args) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, cat=cat, **args)


def merge_chrome_traces(docs) -> dict:
    """Merge per-process Chrome trace documents into ONE Perfetto
    timeline (ISSUE 11: the fleet trace).

    Each input doc is a :meth:`Tracer.chrome_trace` (or any Chrome
    trace-event dict).  Clock alignment: a doc whose ``metadata``
    carries a ``clock_sync`` pair ``{monotonic, wall}`` — the pair each
    tracer captured at construction, exchanged on the worker hello
    frame — has its monotonic-epoch timestamps shifted onto the shared
    wall clock (``ts + (wall - monotonic)``); docs without a pair pass
    through unshifted.  The merged timeline is re-based so the earliest
    event sits at t=0 (Perfetto renders relative time anyway; small
    numbers keep the JSON compact).

    Process identity: a doc with ``metadata.replica`` is re-pid'd to
    its replica id (so pid = replica in the merged view, matching the
    EventLog/postmortem merge keys); a ``metadata.label`` becomes the
    Perfetto process name via an ``M``-phase ``process_name`` record.
    Flow events (``s``/``t``/``f`` sharing one trace id) survive
    verbatim, which is what ties one request's spans across pids.
    """
    merged: list = []
    names: list = []
    offsets: list = []
    seen_pids: set = set()
    for doc in docs:
        if not doc:
            continue
        meta = doc.get("metadata") or {}
        sync = meta.get("clock_sync") or {}
        try:
            off_us = (float(sync["wall"]) - float(sync["monotonic"])) * 1e6
        except (KeyError, TypeError, ValueError):
            off_us = 0.0
        replica = meta.get("replica")
        pid = None
        if replica is not None and str(replica).lstrip("-").isdigit():
            pid = int(replica)
        events = doc.get("traceEvents") or []
        label = meta.get("label")
        offsets.append((events, off_us, pid))
        if label is not None:
            name_pid = pid
            if name_pid is None:
                name_pid = meta.get("pid")
                if name_pid is None and events:
                    name_pid = events[0].get("pid")
            if name_pid is not None and name_pid not in seen_pids:
                seen_pids.add(name_pid)
                names.append({"name": "process_name", "ph": "M",
                              "pid": int(name_pid), "tid": 0,
                              "args": {"name": str(label)}})
    t0 = None
    for events, off_us, _pid in offsets:
        for ev in events:
            ts = ev.get("ts")
            if isinstance(ts, (int, float)):
                t = ts + off_us
                t0 = t if t0 is None else min(t0, t)
    t0 = t0 or 0.0
    for events, off_us, pid in offsets:
        for ev in events:
            ev = dict(ev)
            ts = ev.get("ts")
            if isinstance(ts, (int, float)):
                ev["ts"] = round(ts + off_us - t0, 3)
            if pid is not None:
                if ev.get("ph") == "M":
                    continue  # per-doc name records: the merge re-names
                ev["pid"] = pid
            merged.append(ev)
    merged.sort(key=lambda e: (e.get("ts") or 0.0))
    return {"traceEvents": names + merged, "displayTimeUnit": "ms"}


def write_chrome_trace(doc: dict, path: str) -> bool:
    """Atomically save a Chrome trace document (a tracer's or a merged
    timeline).  tmp + rename, hostname+pid+id disambiguated (the
    utils/checkpoint.atomic_file discipline): concurrent writers —
    distributed ranks sharing a filesystem, threads of one process —
    each land a COMPLETE document; a reader can never observe
    interleaved or truncated JSON that Perfetto rejects.  ``default=
    str``: one exotic span arg (a numpy scalar, a Path) must degrade to
    its repr, not discard the whole artifact.  Never raises; False and
    a stderr line on failure."""
    try:
        tmp = (f"{path}.tmp.{socket.gethostname()}"
               f".{os.getpid()}.{id(doc)}")
        with open(tmp, "w") as f:
            json.dump(doc, f, default=str)
        os.replace(tmp, path)
        return True
    except Exception as e:  # noqa: BLE001
        try:
            print(f"[obs] merged trace write to {path!r} failed: {e!r}",
                  file=sys.stderr)
        except Exception:  # noqa: BLE001
            pass
        return False
