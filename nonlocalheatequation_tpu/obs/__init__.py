"""Unified observability: span tracer, counter registry, exporters.

The reference's observability is HPX's: performance counters in one
hierarchical namespace (``/threads{locality#0/total}/idle-rate``,
src/2d_nonlocal_distributed.cpp:112-128) driving the load balancer, and
wall-clock CSV around ``do_work``.  This package is the TPU framework's
equivalent, grown past fragments (per-report counters, stderr dumps, a
bare ``jax.profiler`` wrapper) into one subsystem:

* ``obs/trace.py`` — a thread-safe, BOUNDED (ring-buffer) span tracer
  with an injectable clock, exporting Chrome trace-event JSON loadable
  in Perfetto; spans cover the serving pipeline's stages, the ensemble
  engine's chunk lifecycle, solver step batches, checkpoint save/load,
  and autotune probes.  The CLI ``--trace DIR`` flag captures it next
  to the ``jax.profiler`` device timeline (utils/profiling.py).
* ``obs/metrics.py`` — a counter/gauge/histogram registry with
  HPX-style names (``/serve/retries``, ``/device{3}/busy-rate``) that
  is the single BACKING STORE for ``ServeReport``/``EnsembleReport``
  (their fields are properties over registry metrics), the
  load-balance busy rates, and the resilience telemetry — with
  Prometheus text exposition and a one-line JSON snapshot.
* ``obs/export.py`` — the opt-in scrape endpoint (``--metrics-port``),
  the ``NLHEAT_EVENT_LOG`` JSONL event stream (per-process
  lifetime-exact ``seq`` + the multi-replica merge-sort helper), and
  the registry-merge helpers the fleet scrape uses.
* ``obs/flightrec.py`` — the crash flight recorder (ISSUE 11): a
  bounded black-box ring dumped to a timestamped postmortem on replica
  death, typed quarantine, breaker open, or SIGTERM
  (``--flight-dir`` / ``NLHEAT_FLIGHT_DIR``).

Fleet tracing (ISSUE 11): ``TraceContext`` carries one request's
identity across ingress -> router frames -> worker
(``X-NLHEAT-Trace``); ``merge_chrome_traces`` aligns per-process
clocks into ONE Perfetto timeline (``ReplicaRouter.dump_fleet_trace``,
tools/trace_merge.py).

Contract everywhere: observability never raises, never adds a fence or
device sync (host-side timestamps only; fetch timings come from fences
the pipeline already performs), memory is bounded, and the disabled
path is zero-cost (pinned by PR 3's fence-discipline spy test running
untouched with tracing off).
"""

from nonlocalheatequation_tpu.obs.export import (  # noqa: F401
    EventLog,
    merge_event_streams,
    serve_metrics,
)
from nonlocalheatequation_tpu.obs.flightrec import (  # noqa: F401
    FlightRecorder,
)
from nonlocalheatequation_tpu.obs.metrics import (  # noqa: F401
    REGISTRY,
    MetricsRegistry,
)
from nonlocalheatequation_tpu.obs.trace import (  # noqa: F401
    TraceContext,
    Tracer,
    get_tracer,
    merge_chrome_traces,
    set_tracer,
    span,
)
