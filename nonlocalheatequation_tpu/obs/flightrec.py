"""Crash flight recorder: a bounded black-box with postmortem dumps.

The reference debugs a dead locality with whatever HPX printed before
the crash; our fleet (serve/router.py) treats replica death as a
first-class event but, before this module, the evidence died with the
process.  The flight recorder is the black-box layer (ISSUE 11): a
per-process RING of the most recent discrete events (the same stream
the ``NLHEAT_EVENT_LOG`` JSONL carries — retries, quarantines, breaker
transitions, retired chunks, routing decisions), plus bound providers
for the live metrics registry and the in-flight ledger, dumped to a
timestamped postmortem file when something dies:

* **worker death** — the router's reaper (``ReplicaRouter._on_eof``)
  dumps a postmortem naming the killed replica, the cases that were in
  flight on it, and the re-route decision for each;
* **typed ServeError quarantine** — the pipeline dumps when a poison
  case completes exceptionally (serve/server.py ``_quarantine``);
* **breaker open** — the pipeline dumps on a closed -> open transition;
* **SIGTERM** — :func:`install_sigterm` chains a dump in front of the
  previous handler (a drained/killed CLI still leaves its black box).

Contract (the obs/ discipline): recording is bounded (ring + lifetime
count), never raises, and costs one attribute read when no recorder is
installed (emitters hold the module-global and skip one ``if``).  A
dump flushes any registered sinks first (the EventLog registers its
``flush`` — satellite: postmortems are never torn mid-line), then
writes atomically via tmp+rename.

Enable with ``NLHEAT_FLIGHT_DIR=DIR`` (the CLIs' ``--flight-dir``), or
construct one explicitly (the router does, for itself and its workers).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import sys
import threading
import time
from collections import deque

#: Env var naming the postmortem directory (scrubbed by
#: tests/conftest.py like NLHEAT_EVENT_LOG — a leaked developer setting
#: must not make the suite write files).
FLIGHT_DIR_ENV = "NLHEAT_FLIGHT_DIR"

#: Default ring capacity (events).  The black box holds the RECENT
#: story — minutes of serving at typical event rates — not the life of
#: the process; that is the EventLog's job.
DEFAULT_CAPACITY = 2048


class FlightRecorder:
    """Bounded event ring + postmortem dumper.  Never raises.

    ``record`` appends one stamped event (per-process ``seq``
    lifetime-exact, wall ``t``); ``bind`` attaches the live registry
    and an in-flight-ledger callable; ``add_flush`` registers a sink to
    flush before any dump (the EventLog); ``dump`` writes the black box
    — last-N events, registry snapshot, in-flight ledger, the trigger —
    to ``dir/postmortem-<stamp>-pid<pid>[-r<replica>]-<n>.json``."""

    def __init__(self, dir_path: str, capacity: int = DEFAULT_CAPACITY,
                 clock=time.time, replica=None):
        self.dir = str(dir_path)
        os.makedirs(self.dir, exist_ok=True)
        self.events: deque = deque(maxlen=max(1, int(capacity)))
        self.events_total = 0  # lifetime-exact through eviction
        self.dumps = 0
        self._clock = clock
        # RLock, not Lock: the SIGTERM handler (install_sigterm) runs on
        # the MAIN thread and calls record()/dump() — if the signal
        # lands while that same thread is inside a lock-held section, a
        # plain Lock would self-deadlock the shutdown path the black
        # box exists to cover
        self._lock = threading.RLock()
        self.pid = os.getpid()
        if replica is None:
            replica = os.environ.get("NLHEAT_REPLICA_ID")
        self.replica = int(replica) if replica is not None \
            and str(replica).isdigit() else replica
        self._registry = None
        self._inflight = None  # zero-arg callable -> ledger list
        self._flushes: list = []

    # -- wiring -------------------------------------------------------------
    def bind(self, registry=None, inflight=None) -> None:
        """Attach the live telemetry the postmortem snapshots: a
        MetricsRegistry (or zero-arg callable returning one) and an
        in-flight-ledger callable.  Later binds win (one recorder per
        process, one serving pipeline per worker)."""
        if registry is not None:
            self._registry = registry
        if inflight is not None:
            self._inflight = inflight

    def add_flush(self, fn) -> None:
        """Register a sink flushed before every dump (EventLog.flush:
        a postmortem must never race a half-written JSONL line)."""
        if fn is not None and fn not in self._flushes:
            self._flushes.append(fn)

    # -- recording ----------------------------------------------------------
    def record(self, kind: str, **fields) -> None:
        """Append one event to the ring.  Never raises."""
        try:
            with self._lock:
                seq = self.events_total
                self.events_total += 1
                ev = {"seq": seq, "t": self._clock(), "kind": kind}
                ev.update(fields)
                self.events.append(ev)
        except Exception:  # noqa: BLE001 — observability never raises
            pass

    def __len__(self) -> int:
        return len(self.events)

    # -- the dump -----------------------------------------------------------
    def snapshot(self, reason: str, **extra) -> dict:
        """The postmortem document (dump() writes it; tests read it)."""
        with self._lock:
            events = [dict(e) for e in self.events]
        doc = {
            "postmortem": reason,
            "t": self._clock(),
            "pid": self.pid,
            "events": events,
            "events_total": self.events_total,
        }
        if self.replica is not None:
            doc["replica"] = self.replica
        if extra:
            doc.update(extra)
        reg = self._registry
        try:
            if callable(reg):
                reg = reg()
            if reg is not None:
                doc["registry"] = reg.snapshot()
        except Exception:  # noqa: BLE001
            pass
        try:
            if self._inflight is not None:
                doc["inflight"] = self._inflight()
        except Exception:  # noqa: BLE001
            pass
        return doc

    def dump(self, reason: str, **extra) -> str | None:
        """Write one postmortem file; returns its path (None on
        failure, loudly).  Flushes registered sinks first so the
        postmortem and the JSONL event log agree on what happened."""
        try:
            for fn in self._flushes:
                try:
                    fn()
                except Exception:  # noqa: BLE001
                    pass
            doc = self.snapshot(reason, **extra)
            with self._lock:
                n = self.dumps
                self.dumps += 1
            stamp = time.strftime("%Y%m%d-%H%M%S",
                                  time.gmtime(doc["t"]))
            rep = f"-r{self.replica}" if self.replica is not None else ""
            path = os.path.join(
                self.dir, f"postmortem-{stamp}-pid{self.pid}{rep}-{n}.json")
            tmp = f"{path}.tmp.{socket.gethostname()}.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, path)
            return path
        except Exception as e:  # noqa: BLE001
            try:
                print(f"[obs] flight-recorder dump ({reason}) failed: "
                      f"{e!r}", file=sys.stderr)
            except Exception:  # noqa: BLE001
                pass
            return None

    @classmethod
    def from_env(cls, environ=os.environ) -> "FlightRecorder | None":
        """The opt-in hook: a recorder when ``NLHEAT_FLIGHT_DIR`` is set
        and creatable, else None (loud on an unusable dir, like
        EventLog.from_env)."""
        path = environ.get(FLIGHT_DIR_ENV)
        if not path:
            return None
        try:
            return cls(path)
        except OSError as e:
            print(f"[obs] {FLIGHT_DIR_ENV}={path!r} cannot be used "
                  f"({e}); flight recorder disabled", file=sys.stderr)
            return None


_recorder: FlightRecorder | None = None


def get_recorder() -> FlightRecorder | None:
    return _recorder


def set_recorder(rec: FlightRecorder | None) -> FlightRecorder | None:
    """Install the process-global recorder (None disables); returns the
    previous one so callers can restore it."""
    global _recorder
    prev = _recorder
    _recorder = rec
    return prev


def record(kind: str, **fields) -> None:
    """Module-level tap: record into the global recorder if installed
    (one attribute read when off — the obs/ disabled-path shape)."""
    r = _recorder
    if r is not None:
        r.record(kind, **fields)


def install_sigterm(rec: FlightRecorder) -> None:
    """Dump a postmortem on SIGTERM, then chain to the previous
    disposition — a terminated server still leaves its black box.  A
    previously IGNORED signal (SIG_IGN, supervisor-style setups) stays
    ignored after the dump: arming the recorder must never convert a
    signal the process was configured to survive into death.
    Main-thread only (signal API); a failed install is swallowed
    (observability never kills the run)."""
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _handler(signum, frame):
            rec.record("sigterm")
            rec.dump("sigterm")
            if prev is signal.SIG_IGN:
                return  # the process was configured to ignore SIGTERM
            if callable(prev) and prev is not signal.SIG_DFL:
                prev(signum, frame)
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _handler)
    except (ValueError, OSError, RuntimeError):
        pass  # not the main thread / restricted env: no handler
