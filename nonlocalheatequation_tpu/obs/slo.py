"""SLO promise-audit ledger: join the picker's promise to the outcome.

The serving stack makes auditable PROMISES at the front door: a picked
202 carries the engine, the step schedule, the modeled cost
(``EngineChoice.est_ms``) and the client's deadline (serve/picker.py,
serve/http.py).  Until ISSUE 20 nothing ever joined that promise to the
observed outcome — deadline hits were unmeasured, the picker's cost
model ran on stale autotune probes forever, and a silently drifting
model degraded every future pick.  This module closes the loop, in the
reference's spirit of measurement DRIVING decisions (the HPX idle-rate
counters balancing the fleet, PAPER.md L0 layer):

* :class:`SloLedger` — a per-request ledger.  ``promise()`` records the
  202 evidence at submit time (engine axis, modeled cost, deadline);
  ``resolve()`` joins the outcome (queue wait, device wall, e2e
  latency, error class, measured error when the caller has the
  manufactured oracle) exactly once — a second resolve for the same
  seq is counted (``/slo/duplicate``) and dropped, which is what makes
  the ledger chaos-proof: the router's delivery ledger already
  suppresses late frames for re-routed cases, and this ledger's
  pop-once discipline catches any future regression of that invariant.
  Everything lands in the bound registry under ``/slo/*``: hit/miss
  counters, a rolling burn-rate window, latency/queue/device
  histograms, and per-engine-axis (stepper x stages x method x
  precision [x mesh]) hit/miss tables.
* **Drift detector** — every resolve with both a modeled and an
  observed cost feeds a windowed modeled-vs-observed ratio; when the
  window's p50 leaves the configured band the ledger warns LOUDLY once
  per excursion (EventLog line + flight-recorder note +
  ``/slo/drift-warnings`` counter) and keeps ``/slo/drift`` pinned to
  the live p50 so dashboards see the trend before the warning.
* :class:`LiveRateRecorder` — live recalibration: observed per-apply
  milliseconds from retired chunks flow back into the autotuner's
  persisted probe records (utils/autotune.py file cache, the exact key
  grammar the picker's :func:`~nonlocalheatequation_tpu.serve.picker.
  record_rate_fn` reads) as EWMA ``live`` entries, so pick quality
  improves with traffic instead of decaying.  Records are buffered and
  merged-on-write in batches (the autotune cache's own concurrency
  rule); persistence follows ``NLHEAT_AUTOTUNE_CACHE`` ("" disables,
  the suite's pin).

Zero-fence discipline (the PR 5 contract): the ledger only ever
consumes timestamps the scheduler already took — ``promise``/``resolve``
take explicit times, never read a device, never fence.  The disabled
path in every instrumented component is ONE attribute read
(``self._slo is None``).  Ledger methods never raise past argument
errors: observability must not take the serving path down.

Env knobs (scrubbed in tests/conftest.py): ``NLHEAT_SLO=1`` enables
the ledger on pipelines/routers built with the default ``slo=None``;
``NLHEAT_SLO_BAND=lo,hi`` the drift band (default ``0.25,4.0`` —
generous because analytic-rate promises are order-of-magnitude by
contract, picker module docstring); ``NLHEAT_SLO_WINDOW`` the
burn/drift window (default 256); ``NLHEAT_SLO_MIN`` the minimum drift
samples before a warning can fire (default 8); ``NLHEAT_SLO_LIVE=0``
disables the live rate write-back independently of the ledger.
"""

from __future__ import annotations

import math
import os
import threading
import time

from nonlocalheatequation_tpu.obs import flightrec
from nonlocalheatequation_tpu.obs.export import EventLog
from nonlocalheatequation_tpu.obs.metrics import MetricsRegistry

#: Default rolling window for the burn-rate and drift ratios
#: (NLHEAT_SLO_WINDOW overrides).
DEFAULT_WINDOW = 256

#: Default modeled-vs-observed drift band (NLHEAT_SLO_BAND overrides):
#: the window p50 of observed_ms/modeled_ms must stay inside [lo, hi].
#: Generous by design — analytic-rate promises are honest only to the
#: order of magnitude (serve/picker.py cost-model note); record/live
#: rates sit well inside.
DEFAULT_BAND = (0.25, 4.0)

#: Minimum drift-window samples before a warning can fire
#: (NLHEAT_SLO_MIN overrides): a first slow compile-adjacent chunk must
#: not page anyone.
DEFAULT_MIN_SAMPLES = 8

#: Live write-back flush cadence: records buffered per key are merged
#: into the autotune file cache every this-many observations (and at
#: close()).  Bounds file I/O to O(chunks / cadence).
LIVE_FLUSH_EVERY = 32

#: EWMA weight of one new observation in the live per-apply rate: heavy
#: enough to converge in a few chunks, light enough that one noisy
#: chunk cannot swing the persisted rate.
LIVE_ALPHA = 0.25


def _env_float_pair(name: str, default: tuple) -> tuple:
    env = os.environ.get(name)
    if not env:
        return default
    try:
        lo, hi = (float(t) for t in env.split(","))
    except ValueError:
        raise ValueError(
            f"{name} must be 'lo,hi' floats, got {env!r}") from None
    if not (0 < lo < hi):
        raise ValueError(f"{name} needs 0 < lo < hi, got {env!r}")
    return (lo, hi)


def _env_int(name: str, default: int, floor: int = 1) -> int:
    env = os.environ.get(name)
    if not env:
        return default
    try:
        v = int(env)
    except ValueError:
        raise ValueError(f"{name} must be an int, got {env!r}") from None
    if v < floor:
        raise ValueError(f"{name} must be >= {floor}, got {env!r}")
    return v


def engine_axis(engine_sel, mesh=None) -> str:
    """The per-engine-axis table label: ``stepper[s=N]/method/precision``
    (the picker's refusal-message format) from an engine-pool key tuple
    (serve/picker.py ``EngineChoice.key()``), ``"default"`` for None,
    with the mesh hash prefix appended for mesh-keyed cases."""
    if engine_sel is None:
        label = "default"
    else:
        stepper, stages, method, precision = engine_sel
        label = f"{stepper}[s={stages}]/{method}/{precision}"
    if mesh:
        label = f"{label}/mesh-{str(mesh)[:12]}"
    return label


def applies_per_step(stepper: str, stages: int) -> float:
    """Operator applies per step for the live per-apply rate: the
    picker's cost-model convention (serve/picker.py — s for rkc, ~3.5
    fft-equivalents per corrected expo substage, 1 otherwise)."""
    if stepper == "rkc":
        return float(max(1, int(stages)))
    if stepper == "expo":
        return 3.5 * max(1, int(stages))
    return 1.0


class LiveRateRecorder:
    """EWMA observed per-apply rates, persisted into the autotuner's
    probe records (utils/autotune.py file cache) under the picker's
    exact key grammar, as each entry's ``live`` block:
    ``{"per-step": <ewma ms>, "n": <count>, "provenance": "live"}``.
    The block is DISJOINT from ``ms_per_step`` on purpose: the tuner's
    winner election must keep ranking only candidates it probed, while
    :func:`~nonlocalheatequation_tpu.serve.picker.record_rate_fn`
    prefers the live block when present.  Buffered; ``flush()`` merges
    on write (autotune's own concurrency rule).  All methods swallow
    I/O errors — recalibration is an optimization, never a crash."""

    def __init__(self, device_kind: str, dtype_name: str = "float32",
                 version: str | None = None, alpha: float = LIVE_ALPHA,
                 flush_every: int = LIVE_FLUSH_EVERY):
        if version is None:
            from nonlocalheatequation_tpu import __version__ as version
        self.device_kind = str(device_kind)
        self.dtype_name = str(dtype_name)
        self.version = str(version)
        self.alpha = float(alpha)
        self.flush_every = max(1, int(flush_every))
        self._lock = threading.Lock()
        # guarded_by: self._lock
        self._acc: dict = {}  # key -> {"ms": ewma, "n": int}
        # guarded_by: self._lock
        self._pending = 0
        self._seeded: set = set()  # guarded_by: self._lock

    def key(self, method: str, shape, eps: int, precision: str) -> str:
        """The autotune record key this observation recalibrates —
        byte-identical to the picker's record_rate_fn grammar and the
        tuner's pick_multi_step_fn keys (utils/autotune.py)."""
        return "/".join(
            [f"v{self.version}", self.device_kind, str(method),
             "x".join(str(int(s)) for s in shape), f"eps{int(eps)}",
             self.dtype_name]
            + ([f"prec-{precision}"] if precision != "f32" else []))

    def record(self, method: str, shape, eps: int, precision: str,
               ms_per_apply: float) -> None:
        """Fold one observed per-apply rate into the key's EWMA; flush
        to the file cache every ``flush_every`` observations."""
        if not (isinstance(ms_per_apply, (int, float))
                and math.isfinite(ms_per_apply) and ms_per_apply > 0):
            return
        k = self.key(method, shape, eps, precision)
        with self._lock:
            slot = self._acc.get(k)
            if slot is None:
                seed = self._persisted_rate(k)
                if seed is not None:
                    slot = {"ms": seed, "n": 0}
                else:
                    slot = {"ms": float(ms_per_apply), "n": 0}
                    self._acc[k] = slot
                    slot["n"] = 1
                    self._pending += 1
                    if self._pending >= self.flush_every:
                        self._flush_locked()
                    return
                self._acc[k] = slot
            slot["ms"] += self.alpha * (float(ms_per_apply) - slot["ms"])
            slot["n"] += 1
            self._pending += 1
            if self._pending >= self.flush_every:
                self._flush_locked()

    def _persisted_rate(self, key: str) -> float | None:
        """Seed a fresh EWMA from a previously persisted live rate so
        recalibration accumulates across process lifetimes."""
        if key in self._seeded:
            return None
        self._seeded.add(key)
        try:
            from nonlocalheatequation_tpu.utils.autotune import (
                _load_file_cache,
            )

            live = (_load_file_cache().get(key) or {}).get("live") or {}
            ms = live.get("per-step")
            if isinstance(ms, (int, float)) and not isinstance(ms, bool):
                return float(ms)
        except Exception:  # noqa: BLE001 — a broken cache seeds nothing
            pass
        return None

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        # guarded_by: self._lock (callers hold it)
        self._pending = 0
        if not self._acc:
            return
        try:
            from nonlocalheatequation_tpu.utils.autotune import (
                _cache_path,
                _load_file_cache,
                _store_file_cache,
            )

            if _cache_path() is None:
                return  # persistence disabled (NLHEAT_AUTOTUNE_CACHE="")
            cache = _load_file_cache()
            out = {}
            for k, slot in self._acc.items():
                entry = dict(cache.get(k) or {})
                prev_n = int((entry.get("live") or {}).get("n") or 0)
                entry["live"] = {"per-step": round(slot["ms"], 6),
                                 "n": prev_n + slot["n"],
                                 "provenance": "live"}
                out[k] = entry
                slot["n"] = 0
            _store_file_cache(out)  # merge-on-write with other keys
        except Exception:  # noqa: BLE001 — never take serving down
            pass


class SloLedger:
    """The per-request promise/outcome join (module docstring).  Built
    over a :class:`~nonlocalheatequation_tpu.obs.metrics.MetricsRegistry`
    so every signal is scrapeable (``/slo/*``) and rides the fleet's
    existing stats frames (a worker pipeline's registry snapshot is
    absorbed under ``/replica{r}/slo/*`` by serve/router.py).  Thread-
    safe: the router resolves from its reader threads."""

    def __init__(self, registry: MetricsRegistry | None = None, *,
                 clock=time.monotonic, window: int | None = None,
                 band: tuple | None = None,
                 min_samples: int | None = None,
                 live: LiveRateRecorder | bool | None = None,
                 events: EventLog | None = None):
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._clock = clock
        self.window = window if window is not None \
            else _env_int("NLHEAT_SLO_WINDOW", DEFAULT_WINDOW)
        self.band = tuple(band) if band is not None \
            else _env_float_pair("NLHEAT_SLO_BAND", DEFAULT_BAND)
        self.min_samples = min_samples if min_samples is not None \
            else _env_int("NLHEAT_SLO_MIN", DEFAULT_MIN_SAMPLES)
        #: live recalibration sink: a prebuilt LiveRateRecorder, or None
        #: (False / NLHEAT_SLO_LIVE=0 also disable; True defers to the
        #: owner, which builds one lazily once it knows its device kind)
        if live is False or os.environ.get("NLHEAT_SLO_LIVE") == "0":
            self.live = None
            self._live_wanted = False
        else:
            self.live = live if isinstance(live, LiveRateRecorder) else None
            self._live_wanted = True
        self._events = events if events is not None else EventLog.from_env()
        self._lock = threading.Lock()
        # guarded_by: self._lock
        self._open: dict = {}  # seq -> promise record
        # guarded_by: self._lock
        self._burn = []  # rolling 0/1 deadline-miss window
        # guarded_by: self._lock
        self._ratios = []  # rolling observed/modeled cost ratios
        # guarded_by: self._lock
        self._drift_excursion = False
        r = self.registry
        self._c_promised = r.counter("/slo/promised")
        self._c_resolved = r.counter("/slo/resolved")
        self._c_hit = r.counter("/slo/deadline-hit")
        self._c_miss = r.counter("/slo/deadline-miss")
        self._c_errors = r.counter("/slo/errors")
        self._c_dup = r.counter("/slo/duplicate")
        self._c_unmatched = r.counter("/slo/unmatched")
        self._c_drift_warn = r.counter("/slo/drift-warnings")
        self._g_burn = r.gauge("/slo/burn")
        self._g_drift = r.gauge("/slo/drift")
        self._g_open = r.gauge("/slo/open")
        self._h_e2e = r.histogram("/slo/e2e-ms", window=self.window)
        self._h_queue = r.histogram("/slo/queue-wait-ms",
                                    window=self.window)
        self._h_device = r.histogram("/slo/device-ms", window=self.window)
        self._h_ratio = r.histogram("/slo/cost-ratio", window=self.window)
        self._h_err = r.histogram("/slo/measured-err", window=self.window)
        self._l_axis_req = r.labeled("/slo/axis-requests")
        self._l_axis_hit = r.labeled("/slo/axis-hit")
        self._l_axis_miss = r.labeled("/slo/axis-miss")

    # -- construction helpers ------------------------------------------------
    @classmethod
    def from_arg(cls, arg, *, registry=None, clock=time.monotonic,
                 live=None):
        """The component-ctor contract (ServePipeline / ReplicaRouter
        ``slo=`` kwarg): an :class:`SloLedger` is used as-is, ``True``
        builds one, ``False`` disables, ``None`` defers to the
        ``NLHEAT_SLO=1`` env knob.  Returns the ledger or None — the
        disabled path every instrumented site guards with one attribute
        read."""
        if isinstance(arg, cls):
            return arg
        if arg is False:
            return None
        if arg is None and os.environ.get("NLHEAT_SLO") != "1":
            return None
        return cls(registry=registry, clock=clock, live=live)

    # -- the ledger ----------------------------------------------------------
    def promise(self, seq: int, *, engine=None, engine_sel=None,
                deadline_ms: float | None = None, mesh=None,
                t: float | None = None) -> None:
        """Record one request's promise.  ``engine`` is the picked
        :class:`~nonlocalheatequation_tpu.serve.picker.EngineChoice`
        when the front door picked (its ``est_ms`` is the modeled-cost
        side of the drift ratio); ``engine_sel`` the pool-key tuple for
        named-engine submissions (axis attribution, no cost model);
        both None = the default engine.  Never raises."""
        try:
            axis = engine_axis(
                engine.key() if hasattr(engine, "key") else engine_sel,
                mesh=mesh)
            est_ms = getattr(engine, "est_ms", None)
            rec = {
                "axis": axis,
                "est_ms": float(est_ms) if est_ms else None,
                "rates": getattr(engine, "rates", None),
                "deadline_ms": (float(deadline_ms)
                                if deadline_ms is not None else None),
                "t": t if t is not None else self._clock(),
            }
            with self._lock:
                self._open[seq] = rec
            self._c_promised.inc()
            self._g_open.set(len(self._open))
            ar = self._l_axis_req
            ar[axis] = ar.get(axis, 0) + 1
        except Exception:  # noqa: BLE001 — observability never raises
            pass

    def resolve(self, seq: int, *, latency_s: float | None = None,
                queue_wait_s: float | None = None,
                device_ms: float | None = None, error: str | None = None,
                err_l2: float | None = None,
                t: float | None = None) -> dict | None:
        """Join one outcome to its promise — exactly once (pop
        discipline; a duplicate increments ``/slo/duplicate`` and
        changes nothing, an unknown seq ``/slo/unmatched``).  All
        timings are the CALLER's timestamps (zero-fence contract).
        Returns the joined record, or None."""
        try:
            with self._lock:
                rec = self._open.pop(seq, None)
            if rec is None:
                # distinguish "resolved twice" from "never promised":
                # both are ledger-consistency signals the chaos test
                # asserts on, with different meanings
                (self._c_dup if seq in self._resolved_window
                 else self._c_unmatched).inc()
                return None
            self._resolved_window.add(seq)
            self._g_open.set(len(self._open))
            self._c_resolved.inc()
            rec.update(latency_s=latency_s, queue_wait_s=queue_wait_s,
                       device_ms=device_ms, error=error)
            if latency_s is not None:
                self._h_e2e.append(latency_s * 1e3)
            if queue_wait_s is not None:
                self._h_queue.append(queue_wait_s * 1e3)
            if device_ms is not None:
                self._h_device.append(device_ms)
            if err_l2 is not None:
                self._h_err.append(float(err_l2))
                rec["err_l2"] = float(err_l2)
            if error is not None:
                self._c_errors.inc()
            hit = None
            if rec["deadline_ms"] is not None and latency_s is not None:
                hit = (error is None
                       and latency_s * 1e3 <= rec["deadline_ms"])
                (self._c_hit if hit else self._c_miss).inc()
                table = self._l_axis_hit if hit else self._l_axis_miss
                table[rec["axis"]] = table.get(rec["axis"], 0) + 1
                with self._lock:
                    self._burn.append(0 if hit else 1)
                    del self._burn[:-self.window]
                    burn = sum(self._burn) / len(self._burn)
                self._g_burn.set(round(burn, 6))
            rec["deadline_hit"] = hit
            observed = device_ms if device_ms is not None else (
                latency_s * 1e3 if latency_s is not None else None)
            if rec["est_ms"] and observed and error is None:
                ratio = observed / rec["est_ms"]
                rec["cost_ratio"] = ratio
                self._h_ratio.append(ratio)
                self._check_drift(ratio)
            return rec
        except Exception:  # noqa: BLE001 — observability never raises
            return None

    # the duplicate-vs-unmatched discriminator: a bounded window of
    # recently resolved seqs (a set would grow with lifetime traffic)
    @property
    def _resolved_window(self):
        w = getattr(self, "_resolved_w", None)
        if w is None:
            w = self._resolved_w = _SeqWindow(self.window)
        return w

    def _check_drift(self, ratio: float) -> None:
        with self._lock:
            self._ratios.append(ratio)
            del self._ratios[:-self.window]
            rs = sorted(self._ratios)
            p50 = rs[len(rs) // 2]
            n = len(rs)
            lo, hi = self.band
            inside = lo <= p50 <= hi
            fire = (not inside and n >= self.min_samples
                    and not self._drift_excursion)
            self._drift_excursion = not inside and n >= self.min_samples
        self._g_drift.set(round(p50, 6))
        if fire:
            # loud, once per excursion: the picker's cost model left
            # the band — every future pick is priced wrong until the
            # live rates pull it back (or someone looks)
            self._c_drift_warn.inc()
            import sys

            print(f"slo: WARNING cost-model drift — modeled-vs-observed "
                  f"p50 ratio {p50:.3g} outside [{lo:g}, {hi:g}] over "
                  f"{n} requests (/slo/drift)", file=sys.stderr)
            if self._events is not None:
                self._events.emit(event="slo-drift", p50=round(p50, 6),
                                  band=[lo, hi], samples=n)
            flightrec.record("slo-drift", p50=round(p50, 6),
                             band=[lo, hi], samples=n)

    # -- surfaces ------------------------------------------------------------
    def axes(self) -> dict:
        """The per-engine-axis hit-rate table."""
        out = {}
        for axis, n in dict(self._l_axis_req).items():
            hit = dict(self._l_axis_hit).get(axis, 0)
            miss = dict(self._l_axis_miss).get(axis, 0)
            out[axis] = {
                "requests": n, "deadline_hit": hit,
                "deadline_miss": miss,
                "hit_rate": (round(hit / (hit + miss), 6)
                             if hit + miss else None),
            }
        return out

    def summary(self) -> dict:
        """The one-page SLO block (``GET /v1/status``, worker stats
        frames, bench.py's ``slo`` fields)."""
        hit, miss = self._c_hit.value, self._c_miss.value
        ratio_pct = self._h_ratio.percentiles()
        return {
            "promised": self._c_promised.value,
            "resolved": self._c_resolved.value,
            "open": len(self._open),
            "errors": self._c_errors.value,
            "duplicate": self._c_dup.value,
            "unmatched": self._c_unmatched.value,
            "deadline_hit": hit,
            "deadline_miss": miss,
            "deadline_hit_rate": (round(hit / (hit + miss), 6)
                                  if hit + miss else None),
            "burn": self._g_burn.value,
            "drift_ratio_p50": ratio_pct.get("p50"),
            "drift": self._g_drift.value,
            "drift_warnings": self._c_drift_warn.value,
            "drift_band": list(self.band),
            "e2e_ms": self._h_e2e.percentiles(),
            "queue_wait_ms": self._h_queue.percentiles(),
            "device_ms": self._h_device.percentiles(),
            "cost_ratio": ratio_pct,
            "measured_err": self._h_err.percentiles(),
            "axes": self.axes(),
        }

    def ensure_live(self, device_kind: str,
                    dtype_name: str = "float32") -> LiveRateRecorder | None:
        """Build the live rate recorder lazily, once the OWNER knows its
        device kind (a worker that already touched its backend — the
        picker/router processes stay backend-free by the wedge
        discipline).  No-op when live recalibration is disabled."""
        if not self._live_wanted:
            return None
        if self.live is None:
            self.live = LiveRateRecorder(device_kind,
                                         dtype_name=dtype_name)
        return self.live

    def close(self) -> None:
        if self.live is not None:
            self.live.flush()


class _SeqWindow:
    """A bounded membership window over recently seen seqs (the
    duplicate-vs-unmatched discriminator): O(1) add/contains, memory
    bounded at ``cap``."""

    def __init__(self, cap: int):
        self.cap = max(1, int(cap))
        self._set: set = set()
        self._order: list = []

    def add(self, seq) -> None:
        if seq in self._set:
            return
        self._set.add(seq)
        self._order.append(seq)
        if len(self._order) > self.cap:
            self._set.discard(self._order.pop(0))

    def __contains__(self, seq) -> bool:
        return seq in self._set
