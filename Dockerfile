# Runnable image of the framework — the analog of the reference's published
# Docker image (.circleci/config.yml:35-62 + .circleci/Docker/Dockerfile):
# everything installed, native components built, batch tests as the default
# command so `docker run` proves the install the same way `make test` does.
FROM python:3.12-slim

RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /opt/nonlocalheatequation_tpu
COPY . .

RUN pip install --no-cache-dir -e . pytest \
    && make -C native

# CPU backend inside the container; TPU hosts mount their own runtime
ENV JAX_PLATFORMS=cpu
CMD ["python", "-m", "pytest", "tests/", "-q"]
