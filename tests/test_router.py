"""Replica-fleet front door (ISSUE 10): router + HTTP ingestion tier.

Covers the tentpole contracts end to end on the CPU suite:

* sticky bucket routing (same bucket -> same replica, spy-pinned via the
  router's ownership map and the workers' own counters),
* warm fleet boot (a replica added mid-run inherits buckets and serves
  them from the shared AOT program store: ``store_hits >= 1``, ZERO
  programs built — the zero-retrace spy),
* replica-kill chaos via the deterministic ``die`` plan kind
  (utils/faults.py): no lost results, no duplicates, re-served output
  bit-identical to the offline ``EnsembleEngine.run()``,
* admission control: bounded queues, 429 + Retry-After shedding
  (deterministic via a stub backend whose completion the test controls),
* the factored busy-rate scale policy (parallel/elastic.py) and the
  router's elastic add/drain actuation,
* the obs satellites: per-replica metric namespaces (absorb_snapshot),
  the aggregated /metrics scrape, and the EventLog pid/replica stamp.

Worker processes are real (subprocess + jax import each), so the fleet
tests batch several assertions per spawned router to hold the tier-1
budget.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from nonlocalheatequation_tpu.obs.export import EventLog, MetricsServer
from nonlocalheatequation_tpu.obs.metrics import (
    MetricsRegistry,
    absorb_snapshot,
)
from nonlocalheatequation_tpu.parallel.elastic import (
    BusyRatePolicy,
    FleetTelemetry,
    fleet_scale_decision,
)
from nonlocalheatequation_tpu.parallel.load_balance import BUSY_SCALE
from nonlocalheatequation_tpu.serve.ensemble import (
    EnsembleCase,
    EnsembleEngine,
)
from nonlocalheatequation_tpu.serve.http import (
    AdmissionController,
    IngressServer,
    parse_case,
)
from nonlocalheatequation_tpu.serve.router import (
    ReplicaRouter,
    RouterOverloaded,
)

assert jax.config.jax_enable_x64  # the oracle contract (conftest forces it)


def make_cases(n, grid=16, nt=4, buckets=2, seed=0):
    """n production cases over `buckets` distinct bucket keys (nt
    varies — the bucket key is (shape, nt, eps, test))."""
    rng = np.random.default_rng(seed)
    return [EnsembleCase(shape=(grid, grid), nt=nt + (i % buckets), eps=2,
                         k=1.0, dt=1e-5, dh=1.0 / grid, test=False,
                         u0=rng.normal(size=(grid, grid)))
            for i in range(n)]


def offline(cases, **kw):
    return EnsembleEngine(method="sat", batch_sizes=(1,), **kw).run(cases)


# ---------------------------------------------------------------------------
# the fleet itself (real worker processes)
# ---------------------------------------------------------------------------


def test_sticky_routing_bit_identity_and_fleet_scrape():
    cases = make_cases(8, buckets=2)
    want = offline(cases)
    with ReplicaRouter(replicas=2, method="sat",
                       batch_sizes=(1,)) as router:
        got = router.serve_cases(cases)
        # bit-identical to the offline engine, in submission order
        assert all(np.array_equal(a, b) for a, b in zip(want, got, strict=True))
        m = router.metrics()
        assert m["cases"] == 8 and m["outstanding"] == 0
        assert m["deaths"] == 0 and m["buckets"] == 2
        # sticky: each bucket owned by exactly one replica, balanced
        owners = {}
        for c in cases:
            key = c.bucket_key()
            assert key in router._owner
            owners[key] = router._owner[key]
        assert len(set(owners.values())) == 2  # spread over the fleet
        # a second pass reuses the SAME owners (the cache-warmth rule)
        router.serve_cases(cases)
        for key, rid in owners.items():
            assert router._owner[key] == rid
        # per-replica namespaces: a stats pull absorbs each worker's
        # registry under /replica{r}, and busy-rate gauges appear
        stats = router.refresh_stats()
        assert set(stats) == {0, 1}
        for rid, frame in stats.items():
            assert frame["pid"] > 0 and frame["replica"] == rid
            assert frame["metrics"]["cases"] >= 1
        names = router.registry.names()
        assert any(n.startswith("/replica{0}/serve/") for n in names)
        assert any(n.startswith("/replica{1}/serve/") for n in names)
        assert "/replica{0}/busy-rate" in names
        # ONE scrape exposes the whole fleet (merged exposition)
        text = router.registry.prometheus()
        assert 'nlheat_replica_serve_depth{replica="0"}' in text
        assert 'nlheat_replica_serve_depth{replica="1"}' in text


def test_warm_added_replica_boots_from_shared_store(tmp_path):
    store = str(tmp_path / "store")
    cases = make_cases(6, buckets=2)
    want = offline(cases)
    with ReplicaRouter(replicas=1, method="sat", batch_sizes=(1,),
                       program_store=store, max_replicas=2) as router:
        got = router.serve_cases(cases)
        assert all(np.array_equal(a, b) for a, b in zip(want, got, strict=True))
        # replica 0 populated the shared store (one save per bucket)
        stats0 = router.refresh_stats()[0]
        assert stats0["metrics"]["store"]["saves"] >= 2
        # scale out mid-run: the newcomer inherits a fair share of the
        # buckets (1 of 2) ...
        rid = router.add_replica()
        rep = router._replicas[rid]
        assert len(rep.buckets) == 1
        moved = next(iter(rep.buckets))
        assert router._owner[moved] == rid
        # ... and serves its first chunks from the store: store_hits
        # >= 1 with ZERO programs built — the zero-retrace spy
        got2 = router.serve_cases(cases)
        assert all(np.array_equal(a, b) for a, b in zip(want, got2, strict=True))
        stats = router.refresh_stats()
        new = stats[rid]["metrics"]
        assert new["cases"] >= 1  # the moved bucket's cases landed here
        assert new["store"]["hits"] >= 1
        assert new["programs_loaded"] >= 1
        assert new["programs_built"] == 0
        # drain the newcomer back out: ownership reassigns, results flow
        router.drain_replica(rid)
        got3 = router.serve_cases(cases)
        assert all(np.array_equal(a, b) for a, b in zip(want, got3, strict=True))
        assert router.live_count() == 1


def test_replica_kill_chaos_reroutes_bit_identically():
    cases = make_cases(8, buckets=2)
    want = offline(cases)
    # die@2: the worker the THIRD case-forward was routed to is killed
    # with that case (and its chunk-mates) in flight
    with ReplicaRouter(replicas=2, method="sat", batch_sizes=(1,),
                       faults="die@2", respawn=False) as router:
        handles = [router.submit(c) for c in cases]
        router.drain()
        m = router.metrics()
        assert m["deaths"] == 1
        assert m["requeued"] >= 1
        # no lost results: every handle delivered exactly once, and the
        # re-served output is bit-identical to the offline oracle
        for h, w in zip(handles, want, strict=True):
            assert h.error is None
            assert np.array_equal(h.result, w)
        assert m["outstanding"] == 0
    # respawn path: a 1-replica fleet whose only worker dies must come
    # back (the floor) and still serve everything
    with ReplicaRouter(replicas=1, method="sat", batch_sizes=(1,),
                       faults="die@1", respawn=True) as router:
        got = router.serve_cases(cases)
        assert all(np.array_equal(a, b) for a, b in zip(want, got, strict=True))
        m = router.metrics()
        assert m["deaths"] == 1 and m["spawns"] == 2
        assert m["replicas"] == 1


def test_poison_frame_classifies_without_killing_the_worker():
    # a case the worker's pipeline refuses at submit must complete
    # EXCEPTIONALLY (error frame) — not kill the worker, which would
    # crash-loop the fleet through death -> re-route -> death
    good = make_cases(2, buckets=1)
    want = offline(good)
    with ReplicaRouter(replicas=1, method="sat",
                       batch_sizes=(1,)) as router:
        # a deadline the worker's pipeline cannot arithmetic on (the
        # HTTP tier 400s this; the router API passes it through)
        h_bad = router.submit(good[0], deadline_ms="soon")
        with pytest.raises(Exception, match="submit refused"):
            h_bad.wait(timeout=60)
        # the worker survived and keeps serving
        got = router.serve_cases(good)
        assert all(np.array_equal(a, b) for a, b in zip(want, got, strict=True))
        assert router.metrics()["deaths"] == 0
        # parent-side poison (an unhashable bucket key) refuses in
        # submit() itself without leaking a ledger entry
        bad = EnsembleCase(shape=None, nt=3, eps=2, k=1.0, dt=1e-5,
                           dh=0.1, test=False, u0=None)
        with pytest.raises(TypeError):
            router.submit(bad)
        assert router.outstanding_total() == 0


def test_replica_killing_case_quarantines_at_requeue_cap():
    # the fleet-level quarantine: die@0x* kills the replica of EVERY
    # forward, so the case's re-route budget (MAX_REQUEUES) must end the
    # cycle with a typed error instead of respawn-looping forever
    case = make_cases(1, buckets=1)[0]
    with ReplicaRouter(replicas=1, method="sat", batch_sizes=(1,),
                       faults="die@0x*", respawn=True) as router:
        h = router.submit(case)
        with pytest.raises(Exception, match="MAX_REQUEUES"):
            h.wait(timeout=180)
        m = router.metrics()
        assert m["deaths"] >= 1 and m["outstanding"] == 0


def test_elastic_scale_actuation(monkeypatch):
    with ReplicaRouter(replicas=1, method="sat", batch_sizes=(1,),
                       min_replicas=1, max_replicas=2) as router:
        monkeypatch.setattr(router, "refresh_stats", lambda: {})
        # every replica saturated -> add
        router._telemetry.record_window(0, 0.95, 1.0)
        assert router.maybe_scale() == "add"
        assert router.live_count() == 2
        assert router.metrics()["scale_ups"] == 1
        # every replica idle -> drain back to the floor
        for rep in router._replicas.values():
            if rep.alive:
                router._telemetry.record_window(rep.rid, 0.01, 1.0)
        assert router.maybe_scale() == "drain"
        assert router.live_count() == 1
        assert router.metrics()["scale_downs"] == 1
        # inside the hysteresis band -> no action
        for rep in router._replicas.values():
            if rep.alive:
                router._telemetry.record_window(rep.rid, 0.5, 1.0)
        assert router.maybe_scale() is None


# ---------------------------------------------------------------------------
# the factored policy (pure units — no processes)
# ---------------------------------------------------------------------------


def test_fleet_scale_decision_watermarks():
    hi, lo = 0.9 * BUSY_SCALE, 0.1 * BUSY_SCALE
    # all saturated + headroom -> add; at the ceiling -> hold
    assert fleet_scale_decision([hi, hi], 2, n_max=4) == "add"
    assert fleet_scale_decision([hi, hi], 4, n_max=4) is None
    # ONE idle replica disproves saturation (min aggregation)
    assert fleet_scale_decision([hi, lo], 2, n_max=4) is None
    # all idle + above the floor -> drain; at the floor -> hold
    assert fleet_scale_decision([lo, lo], 2) == "drain"
    assert fleet_scale_decision([lo], 1) is None
    # the hysteresis band holds steady
    mid = 0.5 * BUSY_SCALE
    assert fleet_scale_decision([mid, mid], 2, n_max=4) is None
    assert fleet_scale_decision([], 1, n_max=4) is None


def test_fleet_telemetry_and_policy_window_fallback():
    t = FleetTelemetry()
    t.record_window(0, 0.5, 1.0)
    t.record_window(1, 2.0, 1.0)  # clamped to a full window
    assert t.busy_rates().tolist() == [0.5 * BUSY_SCALE, BUSY_SCALE]
    assert t.rate(1) == BUSY_SCALE
    policy = BusyRatePolicy(t)
    rates = policy.window_rates()
    assert rates.any()
    policy.reset()  # FleetTelemetry.reset clears the window ...
    assert not t.busy_rates().any() if t.busy_rates().size else True
    # ... but the last non-empty window still backs the reports
    assert policy.rates_or_last().tolist() == rates.tolist()
    t.forget(1)
    assert t.rate(1) == 0.0


# ---------------------------------------------------------------------------
# admission control + the HTTP tier (deterministic stub backend)
# ---------------------------------------------------------------------------


class _StubRequest:
    def __init__(self, case, seq):
        self.case = case
        self.seq = seq
        self.result = None
        self.error = None
        self.latency_s = None
        self.replica = 0
        self.requeues = 0
        self.done = threading.Event()


class _StubBackend:
    """A router-shaped backend whose completion the TEST controls: cases
    queue until ``finish(n)`` releases them — so the 2x-saturating-load
    scenario is a deterministic sequence of events, not a timing race."""

    def __init__(self, max_outstanding=4):
        self.registry = MetricsRegistry()
        self.max_outstanding = max_outstanding
        self._pending = []
        self._seq = 0
        self._gauge = self.registry.gauge("/router/outstanding")
        self.registry.histogram("/router/request-latency-ms").observe(100.0)

    def live_count(self):
        return 1

    def outstanding_total(self):
        return len(self._pending)

    def retry_after_s(self):
        return 0.25

    def submit(self, case, deadline_ms=None, priority=0):
        if len(self._pending) >= self.max_outstanding:
            raise RouterOverloaded(len(self._pending),
                                   self.max_outstanding, 0.25)
        req = _StubRequest(case, self._seq)
        self._seq += 1
        self._pending.append(req)
        self._gauge.set(len(self._pending))
        return req

    def finish(self, n=1):
        for _ in range(n):
            req = self._pending.pop(0)
            req.result = np.asarray(req.case.u0, np.float64)
            req.latency_s = 0.1
            req.done.set()
        self._gauge.set(len(self._pending))

    def metrics(self):
        return {"replicas": 1, "outstanding": len(self._pending),
                "deaths": 0, "cases": self._seq}


def test_admission_sheds_before_queues_grow():
    backend = _StubBackend(max_outstanding=4)
    adm = AdmissionController(backend, max_pending=4)
    cases = make_cases(8, buckets=1)
    granted, sheds = [], []
    for c in cases:  # 2x the admitted budget, offered all at once
        req, retry = adm.try_submit(c)
        (granted if req is not None else sheds).append(retry)
    # the queue is BOUNDED: exactly the budget admitted, the rest shed
    # with a positive retry hint (scaled up as the backlog deepens)
    assert len(granted) == 4 and len(sheds) == 4
    assert backend.outstanding_total() == 4
    assert all(r and r > 0 for r in sheds)
    reg = backend.registry
    assert reg.get("/ingress/accepted").value == 4
    assert reg.get("/ingress/shed").value == 4
    # capacity freed -> admission opens again
    backend.finish(2)
    req, retry = adm.try_submit(cases[0])
    assert req is not None and retry is None
    # the queue-wait bound sheds too: observed p50 (100 ms seeded) over
    # a 50 ms budget refuses even with depth available
    tight = AdmissionController(backend, max_pending=100,
                                max_queue_wait_ms=50.0)
    req, retry = tight.try_submit(cases[0])
    assert req is None and retry > 0


def test_http_ingress_end_to_end_over_stub():
    backend = _StubBackend(max_outstanding=2)
    ing = IngressServer(0, backend, max_pending=2)
    try:
        base = f"http://127.0.0.1:{ing.port}"
        rng = np.random.default_rng(3)
        u0 = rng.normal(size=(4, 4))
        body = dict(shape=[4, 4], nt=3, eps=1, k=1.0, dt=1e-5, dh=0.25,
                    u0=u0.tolist())

        def post(payload):
            try:
                r = urllib.request.urlopen(urllib.request.Request(
                    base + "/v1/cases", json.dumps(payload).encode()))
                return r.status, dict(r.headers), json.load(r)
            except urllib.error.HTTPError as e:
                return e.code, dict(e.headers), json.load(e)

        s1, _, r1 = post(body)
        s2, _, _r2 = post(body)
        assert (s1, s2) == (202, 202) and r1 == {"id": 0,
                                                 "status": "queued"}
        # 2x the budget: the third submission sheds with Retry-After
        s3, h3, r3 = post(body)
        assert s3 == 429
        assert int(h3["Retry-After"]) >= 1
        assert r3["error"] == "overloaded" and r3["retry_after_s"] > 0
        # malformed case -> a client 400, never a worker stack trace
        s4, _, r4 = post({"shape": [4, 4]})
        assert s4 == 400 and "missing case field" in r4["error"]
        # malformed scheduling fields are 400s too (they would otherwise
        # reach — and kill — a worker process downstream)
        s5, _, r5 = post({**body, "deadline_ms": "soon"})
        assert s5 == 400 and "deadline_ms" in r5["error"]
        s6, _, r6 = post({**body, "priority": "high"})
        assert s6 == 400 and "priority" in r6["error"]
        # a non-dict body and a bad timeout_s are client errors as well
        s7, _, r7 = post([1, 2, 3])
        assert s7 == 400 and "JSON object" in r7["error"]
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                base + "/v1/cases/0?wait=1&timeout_s=abc")
        assert exc.value.code == 400
        # poll while queued, then wait -> done -> fetch the result
        r = urllib.request.urlopen(base + "/v1/cases/0")
        assert json.load(r)["status"] == "queued"
        backend.finish(2)
        r = urllib.request.urlopen(base + "/v1/cases/0?wait=1&timeout_s=10")
        assert json.load(r)["status"] == "done"
        r = urllib.request.urlopen(base + "/v1/cases/0/result")
        res = json.load(r)
        got = np.asarray(res["values"]).reshape(res["shape"])
        assert np.array_equal(got, u0)
        # health + the aggregated scrape
        r = urllib.request.urlopen(base + "/healthz")
        assert json.load(r)["ok"] is True
        r = urllib.request.urlopen(base + "/metrics")
        text = r.read().decode()
        assert "nlheat_ingress_shed 1" in text
        assert "nlheat_router_outstanding" in text
        r = urllib.request.urlopen(base + "/metrics.json")
        assert json.load(r)["/ingress/accepted"] == 2
        # unknown id -> 404
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(base + "/v1/cases/999")
        assert exc.value.code == 404
    finally:
        ing.close()


def test_parse_case_refusals():
    ok = parse_case({"shape": [4], "nt": 2, "eps": 1, "k": 1.0,
                     "dt": 1e-5, "dh": 0.25, "test": True})
    assert ok.shape == (4,) and ok.test
    for bad, msg in [
        ({"shape": [4, 4], "nt": 2, "eps": 1, "k": 1, "dt": 1, "dh": 1},
         "needs u0"),  # production case without a state
        ({"shape": [0], "nt": 2, "eps": 1, "k": 1, "dt": 1, "dh": 1},
         "bad shape"),
        ({"shape": [4], "nt": 0, "eps": 1, "k": 1, "dt": 1, "dh": 1},
         "nt >= 1"),
        ({"shape": [4], "nt": 2, "eps": 1, "k": 1, "dt": 1, "dh": 1,
          "u0": [1.0, 2.0]}, "u0 has 2 values"),
        ({"nt": 2}, "missing case field"),
    ]:
        with pytest.raises(ValueError, match=msg):
            parse_case(bad)


# ---------------------------------------------------------------------------
# obs satellites: per-replica namespaces, merged scrape, event-log stamp
# ---------------------------------------------------------------------------


def test_absorb_snapshot_flattens_foreign_registries():
    src = MetricsRegistry()
    src.counter("/serve/retries").inc(3)
    src.gauge("/serve/depth").set(2)
    src.histogram("/serve/request-latency-ms").observe(5.0)
    src.labeled("/serve/faults")["hang"] = 1
    dst = MetricsRegistry()
    absorb_snapshot(dst, "/replica{7}", src.snapshot())
    assert dst.get("/replica{7}/serve/retries").value == 3
    assert dst.get("/replica{7}/serve/depth").value == 2
    assert dst.get("/replica{7}/serve/request-latency-ms/count").value == 1
    assert dst.get("/replica{7}/serve/faults/hang").value == 1
    text = dst.prometheus()
    assert 'nlheat_replica_serve_retries{replica="7"} 3' in text
    # absorbing a refreshed snapshot UPDATES in place (gauges, no dupes)
    src.counter("/serve/retries").inc()
    absorb_snapshot(dst, "/replica{7}", src.snapshot())
    assert dst.get("/replica{7}/serve/retries").value == 4


def test_metrics_server_aggregates_registries():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("/router/cases").inc(5)
    b.gauge("/replica{0}/serve/depth").set(1)
    server = MetricsServer(0, [a, b])
    try:
        base = f"http://127.0.0.1:{server.port}"
        text = urllib.request.urlopen(base + "/metrics").read().decode()
        assert "nlheat_router_cases 5" in text
        assert 'nlheat_replica_serve_depth{replica="0"} 1' in text
        snap = json.load(urllib.request.urlopen(base + "/metrics.json"))
        assert snap["/router/cases"] == 5
        assert snap["/replica{0}/serve/depth"] == 1
    finally:
        server.close()


def test_event_log_stamps_pid_replica_seq_and_time(tmp_path, monkeypatch):
    path = tmp_path / "events.jsonl"
    log = EventLog(str(path), clock=lambda: 100.5)
    log.emit(event="chunk", chunk=1)
    log.close()
    monkeypatch.setenv("NLHEAT_REPLICA_ID", "3")
    log = EventLog(str(path), clock=lambda: 101.5)  # replica from env
    log.emit(event="chunk", chunk=2)
    log.emit(event="chunk", chunk=3)
    log.close()
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert len(lines) == 3
    import os as _os

    assert lines[0]["pid"] == _os.getpid() and "replica" not in lines[0]
    assert lines[1] == {"pid": _os.getpid(), "replica": 3, "seq": 0,
                        "t": 101.5, "event": "chunk", "chunk": 2}
    # seq is per-process lifetime-exact: the second emit of the second
    # process is seq 1, while the FIRST process's line stays seq 0 —
    # interleaved multi-replica logs total-order within each process
    assert lines[0]["seq"] == 0 and lines[2]["seq"] == 1


# ---------------------------------------------------------------------------
# refusals
# ---------------------------------------------------------------------------


def test_router_ctor_refusals():
    with pytest.raises(ValueError, match="replicas must be >= 1"):
        ReplicaRouter(replicas=0)
    with pytest.raises(ValueError, match="max_outstanding"):
        ReplicaRouter(replicas=1, max_outstanding=0)
    with pytest.raises(ValueError, match="min_replicas"):
        ReplicaRouter(replicas=2, min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="bad fault-plan entry"):
        ReplicaRouter(replicas=1, faults="explode@1")


def test_router_load_ab_refuses_bucket_starvation():
    from nonlocalheatequation_tpu.serve.router import router_load_ab

    with pytest.raises(ValueError, match="distinct buckets"):
        router_load_ab({}, make_cases(4, buckets=1), 2, None)
