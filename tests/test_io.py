"""IO & observability: VTU round-trip, CSV column formats, partition maps,
timing report layout (reference parity targets in each module docstring)."""

import json
import os

import numpy as np
import pytest

from nonlocalheatequation_tpu.models.solver2d import Solver2D
from nonlocalheatequation_tpu.utils.csvlog import SimulationCsvLogger
from nonlocalheatequation_tpu.utils.partition_map import (
    PartitionMap,
    default_assignment,
    read_partition_map,
    write_partition_map,
)
from nonlocalheatequation_tpu.utils.timing import (
    print_time_results_distributed,
)
from nonlocalheatequation_tpu.utils.vtu import VtuWriter, read_vtu_point_data


def test_vtu_round_trip(tmp_path):
    rng = np.random.default_rng(0)
    nodes = np.zeros((12, 3))
    nodes[:, 0] = np.arange(12)
    temp = rng.normal(size=12)
    w = VtuWriter(str(tmp_path / "snap"))
    w.append_nodes(nodes)
    w.append_point_data("Temperature", temp)
    w.add_time_step(0.25)
    w.close()

    data = read_vtu_point_data(str(tmp_path / "snap.vtu"))
    assert np.allclose(data["Temperature"], temp)
    assert np.allclose(data["Points"].reshape(-1, 3), nodes)
    assert data["TIME"][0] == 0.25


def test_vtu_zlib(tmp_path):
    temp = np.linspace(0, 1, 100)
    w = VtuWriter(str(tmp_path / "z"), compress_type="zlib")
    w.append_nodes(np.zeros((100, 3)))
    w.append_point_data("Temperature", temp)
    w.close()
    data = read_vtu_point_data(str(tmp_path / "z.vtu"))
    assert np.allclose(data["Temperature"], temp)


def test_csv_logger_columns(tmp_path):
    s = Solver2D(8, 8, 6, eps=2, k=1.0, dt=1e-4, dh=0.02, backend="oracle")
    s.test_init()
    s.logger = SimulationCsvLogger(
        s.op, test=True, out_csv=str(tmp_path / "c"), out_vtk=str(tmp_path / "v"),
        nlog=s.nlog,
    )
    s.do_work()
    sim_lines = open(tmp_path / "c" / "simulate_2d.csv").read().strip().splitlines()
    # logged at t=0 and t=5: two snapshots x 64 points
    assert len(sim_lines) == 2 * 64
    # row: time,sx,sy,numeric,analytic,sq_err,abs_err,  (trailing comma)
    first = sim_lines[0].split(",")
    assert first[0] == "0" and first[1] == "0" and first[2] == "0"
    assert len(first) == 8 and first[-1] == ""
    score_lines = open(tmp_path / "c" / "score_2d.csv").read().strip().splitlines()
    assert len(score_lines) == 2
    t0 = score_lines[0].split(",")
    assert t0[0] == "0" and float(t0[1]) >= 0
    # vtu snapshots written as simulate_<lognum>.vtu
    assert (tmp_path / "v" / "simulate_0.vtu").exists()
    assert (tmp_path / "v" / "simulate_1.vtu").exists()


def test_partition_map_round_trip(tmp_path):
    pm = PartitionMap(20, 20, 2, 2, 0.0025,
                     np.array([[0, 1], [1, 1]], dtype=np.int64))
    path = str(tmp_path / "map.txt")
    write_partition_map(path, pm)
    back = read_partition_map(path)
    assert (back.nx, back.ny, back.npx, back.npy) == (20, 20, 2, 2)
    assert back.dh == 0.0025
    assert (back.assignment == pm.assignment).all()
    # format matches the reference fixture layout (tests/load_balance_4s_2n.txt)
    lines = open(path).read().strip().splitlines()
    assert lines[0] == "20 20 2 2 0.0025"
    assert lines[1] == "0 0 0" and lines[2] == "0 1 1"


def test_reference_fixture_readable():
    # the reference ships fixture maps; ours must parse the same format the
    # reference's param_file_input consumes (generated here, same layout)
    a = default_assignment(5, 5, 2)
    assert a.min() == 0 and a.max() == 1
    # block map: first half of flat tiles on 0, second on 1
    flat = np.array([a[i % 5, i // 5] for i in range(25)])
    assert (np.sort(flat) == flat).all()


def test_timing_layout(capsys):
    print_time_results_distributed(4, 16, 1.2345, 25, 25, 2, 2, 45)
    out = capsys.readouterr().out.splitlines()
    assert out[0].startswith("Localities,OS_Threads,Execution_Time_sec")
    row = out[1]
    assert row.startswith("4,") and "1.2345" in row and row.rstrip().endswith("45")
