"""Batched ensemble engine (serve/ensemble.py + the batched ops layer).

What these tests pin, on the CPU/f64 interpreter suite:

* an 8-case same-shape bucket compiles ONE program (trace counter on
  pallas_call for the grid-axis kernel; engine report counters for the
  general case) and issues ONE dispatch per scan segment;
* every case of a mixed-physics bucket is bit-identical to its solo
  solve across the per-step, carried, and superstep compositions, and
  under the bf16 precision tier;
* mixed grids land in separate buckets and padding lanes are dropped;
* the vmap parity oracle stays 1e-12-close; the manufactured-source
  grid-axis path stays inside the documented last-ulp bound;
* honesty refusals: production-only variants on test buckets, resync
  ops, production cases without u0.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from nonlocalheatequation_tpu.ops import pallas_kernel as pk
from nonlocalheatequation_tpu.ops.nonlocal_op import (
    NonlocalOp1D,
    NonlocalOp2D,
    NonlocalOp3D,
    make_batched_multi_step_fn_stacked,
    make_batched_multi_step_fn_vmap,
    make_multi_step_fn_base,
)
from nonlocalheatequation_tpu.serve.ensemble import (
    EnsembleCase,
    EnsembleEngine,
)

NX, NY, EPS, NSTEPS = 40, 36, 3, 5
MIXED = [(1.0, 1e-4, 0.02), (0.5, 2e-4, 0.02), (0.2, 1e-4, 0.01),
         (1.0, 5e-5, 0.03)]


def _cases(n, params, rng, shape=(NX, NY), nt=NSTEPS, test=False):
    out = []
    for i in range(n):
        k, dt, dh = params[i % len(params)]
        out.append(EnsembleCase(shape=shape, nt=nt, eps=EPS, k=k, dt=dt,
                                dh=dh, test=test,
                                u0=rng.normal(size=shape)))
    return out


def _superstep2_maker(op, nsteps):
    return pk.make_superstep_multi_step_fn(op, nsteps, ksteps=2)


_SOLO_MAKERS: dict = {}


def _solo(case, maker=make_multi_step_fn_base, **kw):
    # one jitted solo program per (maker, physics, nt) reused across every
    # case/u0 — per-case re-tracing of identical reference programs was
    # the suite's dominant cost (the jit cache serves repeat calls)
    key = (getattr(maker, "__name__", id(maker)), case.k, case.dt, case.dh,
           case.eps, case.nt, tuple(sorted(kw.items())))
    fn = _SOLO_MAKERS.get(key)
    if fn is None:
        op = NonlocalOp2D(case.eps, case.k, case.dt, case.dh,
                          method="pallas", **kw)
        fn = _SOLO_MAKERS[key] = maker(op, case.nt)
    return np.asarray(fn(jnp.asarray(case.u0), 0))


def test_uniform_8case_bucket_one_trace_one_dispatch(monkeypatch):
    # physics-uniform bucket -> the grid-axis kernel: the pallas kernel
    # is traced ONCE for the whole 8-case bucket (the compile/trace
    # counter of the acceptance criteria), dispatched once, and each
    # lane is bit-identical to its solo solve
    rng = np.random.default_rng(0)
    cases = _cases(8, MIXED[:1], rng)
    solos = [_solo(c) for c in cases]
    calls = []
    real = pk.pl.pallas_call

    def spy(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(pk.pl, "pallas_call", spy)
    pk._build_batched_step_kernel.cache_clear()
    engine = EnsembleEngine(method="pallas")
    res = engine.run(cases)
    assert len(calls) == 1, f"expected ONE kernel trace, saw {len(calls)}"
    assert engine.report.buckets == 1
    assert engine.report.programs_built == 1
    assert engine.report.dispatches == 1
    assert engine.report.strategies.popitem()[1] == "per-step[grid]"
    for got, want in zip(res, solos, strict=True):
        assert np.array_equal(got, want)


def test_mixed_8case_bucket_bit_identical_per_step():
    rng = np.random.default_rng(1)
    cases = _cases(8, MIXED, rng)
    engine = EnsembleEngine(method="pallas")
    res = engine.run(cases)
    assert engine.report.buckets == 1
    assert engine.report.programs_built == 1
    assert engine.report.dispatches == 1
    assert engine.report.strategies.popitem()[1] == "per-step[stacked]"
    for case, got in zip(cases, res, strict=True):
        assert np.array_equal(got, _solo(case))


@pytest.mark.parametrize("params", [MIXED[:1], MIXED],
                         ids=["uniform", "mixed"])
def test_carried_and_superstep_bit_identical(params):
    rng = np.random.default_rng(2)
    cases = _cases(2, params, rng)
    resc = EnsembleEngine(method="pallas", variant="carried").run(cases)
    ress = EnsembleEngine(method="pallas", variant="superstep",
                          ksteps=2).run(cases)
    for case, gc, gs in zip(cases, resc, ress, strict=True):
        assert np.array_equal(
            gc, _solo(case, pk.make_carried_multi_step_fn))
        assert np.array_equal(gs, _solo(case, _superstep2_maker))


@pytest.mark.parametrize("params", [MIXED[:1], MIXED],
                         ids=["uniform", "mixed"])
def test_bf16_tier_bit_identical(params):
    rng = np.random.default_rng(3)
    cases = _cases(2, params, rng)
    engine = EnsembleEngine(method="pallas", precision="bf16")
    res = engine.run(cases)
    for case, got in zip(cases, res, strict=True):
        assert np.array_equal(got, _solo(case, precision="bf16"))
    # the carried bf16 pair-frame path too
    resc = EnsembleEngine(method="pallas", precision="bf16",
                          variant="carried").run(cases)
    for case, got in zip(cases, resc, strict=True):
        assert np.array_equal(
            got, _solo(case, pk.make_carried_multi_step_fn,
                       precision="bf16"))


def test_bucket_boundary_mixed_grids_and_padding():
    # mixed grids land in separate buckets; 3 cases pad to batch size 4
    # and the padding lane is dropped from the output
    rng = np.random.default_rng(4)
    cases = _cases(3, MIXED[:1], rng, shape=(NX, NY))
    cases += _cases(2, MIXED[:1], rng, shape=(48, 48))
    engine = EnsembleEngine(method="pallas")
    res = engine.run(cases)
    assert engine.report.buckets == 2
    assert engine.report.dispatches == 2
    assert engine.report.padded_cases == 1  # 3 -> 4
    assert len(res) == 5
    assert res[0].shape == (NX, NY) and res[3].shape == (48, 48)
    for case, got in zip(cases, res, strict=True):
        assert np.array_equal(got, _solo(case))


def test_manufactured_source_bucket_matches_solo():
    # the batch_tester shape: test=True cases (G init, manufactured
    # source).  The uniform grid-axis source path is documented
    # last-ulp-close; the mixed (stacked) path is bit-exact.
    rng = np.random.default_rng(5)
    for params, exact in ((MIXED[:1], False), (MIXED[:3], True)):
        cases = _cases(3, params, rng, test=True)
        for c in cases:
            op = NonlocalOp2D(c.eps, c.k, c.dt, c.dh)
            c.u0 = op.spatial_profile(*c.shape)
        engine = EnsembleEngine(method="pallas")
        res = engine.run(cases)
        for case, got in zip(cases, res, strict=True):
            op = NonlocalOp2D(case.eps, case.k, case.dt, case.dh,
                              method="pallas")
            g, lg = op.source_parts(*case.shape)
            solo = np.asarray(make_multi_step_fn_base(
                op, case.nt, g, lg)(jnp.asarray(case.u0), 0))
            if exact:
                assert np.array_equal(got, solo)
            else:
                assert float(np.max(np.abs(got - solo))) < 1e-12


def test_vmap_oracle_and_stacked_parity():
    rng = np.random.default_rng(6)
    cases = _cases(4, MIXED, rng)
    ops = [NonlocalOp2D(c.eps, c.k, c.dt, c.dh, method="pallas")
           for c in cases]
    U = jnp.asarray(np.stack([c.u0 for c in cases]))
    got_v = np.asarray(make_batched_multi_step_fn_vmap(ops, NSTEPS)(U, 0))
    got_s = np.asarray(
        make_batched_multi_step_fn_stacked(ops, NSTEPS)(U, 0))
    for i, case in enumerate(cases):
        solo = _solo(case)
        assert float(np.max(np.abs(got_v[i] - solo))) < 1e-12
        assert np.array_equal(got_s[i], solo)


def test_1d_and_3d_buckets():
    rng = np.random.default_rng(7)
    c1 = [EnsembleCase(shape=(50,), nt=6, eps=5, k=k, dt=dt, dh=0.02,
                       test=False, u0=rng.normal(size=50))
          for k, dt in [(1.0, 1e-3), (0.5, 2e-3), (1.0, 1e-3)]]
    res1 = EnsembleEngine().run(c1)
    for case, got in zip(c1, res1, strict=True):
        op = NonlocalOp1D(case.eps, case.k, case.dt, case.dh)
        solo = np.asarray(
            make_multi_step_fn_base(op, case.nt)(jnp.asarray(case.u0), 0))
        assert float(np.max(np.abs(got - solo))) < 1e-12
    c3 = [EnsembleCase(shape=(12, 12, 12), nt=4, eps=2, k=k, dt=dt,
                       dh=0.05, test=False, u0=rng.normal(size=(12,) * 3))
          for k, dt in [(1.0, 1e-5), (0.5, 2e-5)]]
    eng3 = EnsembleEngine(method="sat")
    res3 = eng3.run(c3)
    for case, got in zip(c3, res3, strict=True):
        op = NonlocalOp3D(case.eps, case.k, case.dt, case.dh, method="sat")
        solo = np.asarray(
            make_multi_step_fn_base(op, case.nt)(jnp.asarray(case.u0), 0))
        assert float(np.max(np.abs(got - solo))) < 1e-12


def test_tune_batch_dimension(monkeypatch):
    from nonlocalheatequation_tpu.utils import autotune

    monkeypatch.setattr(autotune, "_memory_cache", {})
    monkeypatch.setenv("NLHEAT_AUTOTUNE_CACHE", "")
    monkeypatch.setattr(autotune, "PROBE_STEPS", 2)
    monkeypatch.setattr(autotune, "PROBE_ITERS", 1)
    monkeypatch.setenv("NLHEAT_TUNE_BATCH", "1")
    rng = np.random.default_rng(8)
    cases = _cases(4, MIXED[:1], rng, shape=(40, 40))
    engine = EnsembleEngine(method="pallas", variant="auto")
    res = engine.run(cases)
    label = engine.report.strategies.popitem()[1]
    assert label.startswith("tuned:"), label
    for case, got in zip(cases, res, strict=True):
        assert float(np.max(np.abs(got - _solo(case)))) < 1e-12


def test_tune_batch_errored_probe_retry_and_all_errored_fallback(
        monkeypatch, tmp_path):
    # review findings r7: (a) an errored (None) probe persisted by
    # another process must be retried once per process, not pin the
    # variant out for the version key's lifetime; (b) if EVERY batched
    # probe errors, the pick must fall back to the always-available
    # stacked composition instead of rebuilding a known-failing variant
    import json

    from nonlocalheatequation_tpu.utils import autotune

    monkeypatch.setattr(autotune, "_memory_cache", {})
    cache_file = tmp_path / "autotune.json"
    monkeypatch.setenv("NLHEAT_AUTOTUNE_CACHE", str(cache_file))
    monkeypatch.setattr(autotune, "PROBE_STEPS", 2)
    monkeypatch.setattr(autotune, "PROBE_ITERS", 1)
    ops = [NonlocalOp2D(EPS, 1.0, 1e-4, 0.02, method="pallas")] * 2
    _fn, w = autotune.pick_batched_multi_step_fn(ops, 4, (NX, NY),
                                                 jnp.float64)
    rec = json.load(open(cache_file))
    key = next(iter(rec))
    rec[key]["ms_per_step"]["batched-carried"] = None
    rec[key]["winner"] = "batched-carried"
    json.dump(rec, open(cache_file, "w"))
    autotune._memory_cache.clear()
    calls = []
    real = autotune._measure_batched
    monkeypatch.setattr(
        autotune, "_measure_batched",
        lambda *a: calls.append(1) or real(*a))
    _fn2, w2 = autotune.pick_batched_multi_step_fn(ops, 4, (NX, NY),
                                                   jnp.float64)
    assert calls, "errored file-cache probe was not retried"
    assert w2 in dict(autotune.batched_candidates(ops, (NX, NY), 4,
                                                  jnp.float64))

    autotune._memory_cache.clear()
    cache_file.unlink()

    def boom(*a):
        raise RuntimeError("probe boom")

    monkeypatch.setattr(autotune, "_measure_batched", boom)
    fn3, w3 = autotune.pick_batched_multi_step_fn(ops, 4, (NX, NY),
                                                  jnp.float64)
    assert "stacked" in w3
    rng = np.random.default_rng(0)
    out = fn3(jnp.asarray(rng.normal(size=(2, NX, NY))), 0)
    assert np.isfinite(np.asarray(out)).all()


def test_honesty_refusals():
    rng = np.random.default_rng(9)
    test_cases = _cases(1, MIXED[:1], rng, test=True)
    with pytest.raises(ValueError, match="production-only"):
        EnsembleEngine(method="pallas", variant="carried").run(test_cases)
    with pytest.raises(ValueError, match="needs ksteps"):
        EnsembleEngine(method="pallas", variant="superstep")
    with pytest.raises(ValueError, match="needs an initial state"):
        EnsembleEngine(method="pallas").run(
            [EnsembleCase(shape=(NX, NY), nt=2, eps=EPS, k=1.0, dt=1e-4,
                          dh=0.02, test=False)])
    # a resync-tier op cannot slip through the batched paths
    ops = [NonlocalOp2D(EPS, 1.0, 1e-4, 0.02, precision="bf16",
                        resync_every=3)]
    with pytest.raises(ValueError, match="resync"):
        make_batched_multi_step_fn_vmap(ops, 2)
    # carried/superstep need the 2D pallas method
    with pytest.raises(ValueError, match="pallas"):
        EnsembleEngine(method="conv", variant="carried").run(
            _cases(1, MIXED[:1], rng))
