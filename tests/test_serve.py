"""Async serving pipeline (serve/server.py).

What these tests pin, on the CPU/f64 suite:

* microbatch window closes by SIZE (the engine's top batch size) and by
  TIME (window_ms, via an injected clock — no wall-clock racing);
* a per-case deadline forces its bucket's chunk closed early (partial,
  padded) — the starvation bound;
* ``drain()`` flushes open chunks, ready chunks, and in-flight work;
* the in-flight cap D is respected (occupancy never exceeds D and
  genuinely reaches it — the overlap is real, not nominal);
* donation refuses loudly at D > 1 under NLHEAT_DONATE=1
  (utils/donation.py pipeline guard), both at pipeline construction and
  at the lazy donate decision;
* the fence discipline: >= 2 chunks in flight with ZERO host fences
  between their dispatches (spy counters on the module-level
  fence_scalar and the engine dispatch stage), one fence per retire;
* served results are BIT-IDENTICAL to the offline
  ``EnsembleEngine.run()`` on the same case set — same bucketing, same
  chunk programs, only the schedule changes.
"""

import json

import numpy as np
import pytest

from nonlocalheatequation_tpu.serve import server as server_mod
from nonlocalheatequation_tpu.serve.ensemble import (
    EnsembleCase,
    EnsembleEngine,
)
from nonlocalheatequation_tpu.serve.server import ServePipeline
from nonlocalheatequation_tpu.utils import donation

NX, NY, EPS, NSTEPS = 16, 16, 2, 2
MIXED = [(1.0, 1e-4, 0.02), (0.5, 2e-4, 0.02), (0.2, 1e-4, 0.01)]


def _cases(n, rng, shape=(NX, NY), nt=NSTEPS):
    out = []
    for i in range(n):
        k, dt, dh = MIXED[i % len(MIXED)]
        out.append(EnsembleCase(shape=shape, nt=nt, eps=EPS, k=k, dt=dt,
                                dh=dh, test=False,
                                u0=rng.normal(size=shape)))
    return out


class FakeClock:
    """Injected scheduler clock: window/deadline tests advance time
    explicitly instead of racing host load."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _spies(pipe, monkeypatch):
    """Event log of (kind,) for every dispatch and every fence."""
    events = []
    real_fence = server_mod.fence_scalar
    monkeypatch.setattr(
        server_mod, "fence_scalar",
        lambda x: (events.append("fence"), real_fence(x))[1])
    real_dispatch = pipe.engine.dispatch_chunk
    pipe.engine.dispatch_chunk = (
        lambda multi, U0: (events.append("dispatch"),
                           real_dispatch(multi, U0))[1])
    return events


def test_size_triggered_close_and_single_fence(monkeypatch):
    rng = np.random.default_rng(0)
    with ServePipeline(depth=1, window_ms=10_000.0) as pipe:
        events = _spies(pipe, monkeypatch)
        handles = [pipe.submit(c) for c in _cases(8, rng)]
        # the 8th submit hit the size trigger: closed + dispatched, but
        # NOT fenced — no result is due yet
        assert pipe.report.dispatches == 1
        assert pipe.report.forced_closes == {"size": 1}
        assert events == ["dispatch"]
        assert all(h.result is None for h in handles)
        pipe.drain()
        assert events == ["dispatch", "fence"]
        assert all(h.result is not None for h in handles)


def test_time_triggered_close_with_injected_clock():
    rng = np.random.default_rng(1)
    clock = FakeClock()
    with ServePipeline(depth=1, window_ms=10.0, clock=clock) as pipe:
        for c in _cases(3, rng):
            pipe.submit(c)
        assert pipe.report.dispatches == 0  # 3 < size trigger, window open
        clock.advance(0.005)
        pipe.pump()
        assert pipe.report.dispatches == 0  # still inside the window
        clock.advance(0.006)  # past 10 ms
        pipe.pump()
        assert pipe.report.dispatches == 1
        assert pipe.report.forced_closes == {"window": 1}
        assert pipe.report.padded_cases == 1  # 3 real lanes pad up to 4
        pipe.drain()
    assert pipe.report.cases == 3


def test_deadline_forces_partial_chunk():
    rng = np.random.default_rng(2)
    clock = FakeClock()
    with ServePipeline(depth=1, window_ms=10_000.0, clock=clock) as pipe:
        a, b = _cases(2, rng)
        pipe.submit(a)
        pipe.submit(b, deadline_ms=5.0)  # far inside the huge window
        assert pipe.report.dispatches == 0
        clock.advance(0.006)
        pipe.pump()
        # the aging case forced the whole bucket's chunk out early
        assert pipe.report.dispatches == 1
        assert pipe.report.forced_closes == {"deadline": 1}
        pipe.drain()
        assert pipe.report.chunk_log[0]["cases"] == 2
        assert pipe.report.chunk_log[0]["closed_by"] == "deadline"


def test_drain_flushes_open_ready_and_inflight():
    rng = np.random.default_rng(3)
    cases = _cases(3, rng) + _cases(2, rng, shape=(20, 16))
    with ServePipeline(depth=2, window_ms=10_000.0) as pipe:
        handles = [pipe.submit(c) for c in cases]
        assert pipe.report.dispatches == 0  # everything still accumulating
        pipe.drain()
        assert all(h.result is not None for h in handles)
        assert pipe.report.buckets == 2
        assert pipe.report.dispatches == 2
        assert pipe.report.forced_closes == {"drain": 2}
        assert len(pipe._inflight) == 0 and not pipe._ready


def test_inflight_cap_respected_and_reached():
    rng = np.random.default_rng(4)
    # batch size 1: every case is its own chunk -> 6 dispatches compete
    # for 2 in-flight slots
    with ServePipeline(depth=2, window_ms=0.0, batch_sizes=(1,)) as pipe:
        pipe.serve_cases(_cases(6, rng))
        occ = [n for _t, n in pipe.report.occupancy_samples]
        assert max(occ) == 2  # cap reached (real overlap)...
        assert all(n <= 2 for n in occ)  # ...and never exceeded
        assert pipe.report.dispatches == 6
    m = pipe.metrics()
    assert m["occupancy"]["max"] == 2


def test_donation_refused_loudly_when_pipelined(monkeypatch):
    monkeypatch.setenv("NLHEAT_DONATE", "1")
    with pytest.raises(ValueError, match="NLHEAT_DONATE"):
        ServePipeline(depth=2)
    # depth 1 (the fenced schedule) still accepts forced donation
    with ServePipeline(depth=1, window_ms=0.0) as pipe:
        assert pipe.depth == 1
    # belt at the lazy decision too: a depth declared after construction
    # cannot be combined with a flipped-on env knob
    prev = donation.set_pipeline_depth(1)
    monkeypatch.delenv("NLHEAT_DONATE")
    donation.set_pipeline_depth(3)
    try:
        assert donation.donation_on() is False  # pinned off, no backend query
        monkeypatch.setenv("NLHEAT_DONATE", "1")
        with pytest.raises(RuntimeError, match="in flight"):
            donation.donation_on()
    finally:
        donation.set_pipeline_depth(prev)


def test_no_fence_between_dispatches_and_bit_identity(monkeypatch):
    # the acceptance spy: with D=3 and single-case chunks, the pipeline
    # must put >= 2 chunks in flight with ZERO host fences between their
    # dispatches, then retire with exactly one fence per chunk — and the
    # served results must be bit-identical to the offline engine
    rng = np.random.default_rng(5)
    cases = _cases(5, rng)
    offline = EnsembleEngine(batch_sizes=(1,)).run(cases)
    with ServePipeline(depth=3, window_ms=0.0, batch_sizes=(1,)) as pipe:
        events = _spies(pipe, monkeypatch)
        served = pipe.serve_cases(cases)
    # pipe fill: the first D dispatches are back to back, no fence between
    assert events[:3] == ["dispatch"] * 3
    assert events.count("dispatch") == 5
    assert events.count("fence") == 5  # one per retire, none elsewhere
    assert max(n for _t, n in pipe.report.occupancy_samples) >= 2
    for got, want in zip(served, offline, strict=True):
        assert np.array_equal(got, want)


def test_bit_identity_mixed_buckets_vs_offline():
    # mixed physics AND mixed shapes, chunk padding engaged: the served
    # set must reproduce run() bit for bit with the same padding count
    rng = np.random.default_rng(6)
    cases = _cases(6, rng) + _cases(3, rng, shape=(20, 16))
    offline_engine = EnsembleEngine()
    offline = offline_engine.run(cases)
    with ServePipeline(depth=3, window_ms=10_000.0) as pipe:
        served = pipe.serve_cases(cases)
    for got, want in zip(served, offline, strict=True):
        assert np.array_equal(got, want)
    assert pipe.report.padded_cases == offline_engine.report.padded_cases
    assert pipe.report.buckets == offline_engine.report.buckets
    assert pipe.report.dispatches == offline_engine.report.dispatches


def test_wait_forces_one_request():
    rng = np.random.default_rng(7)
    with ServePipeline(depth=2, window_ms=10_000.0) as pipe:
        h = pipe.submit(_cases(1, rng)[0])
        assert h.result is None
        out = h.wait()  # implicit immediate deadline for its chunk
        assert out is not None and out.shape == (NX, NY)
        assert pipe.report.forced_closes == {"wait": 1}
        assert h.latency_s is not None and h.queue_wait_s is not None


def test_priority_orders_ready_chunks():
    rng = np.random.default_rng(8)
    clock = FakeClock()
    with ServePipeline(depth=1, window_ms=5.0, clock=clock) as pipe:
        pipe.submit(_cases(1, rng)[0], priority=0)
        for c in _cases(2, rng, shape=(20, 16)):
            pipe.submit(c, priority=5)
        clock.advance(0.01)
        pipe.pump()  # both buckets close; the prio-5 chunk dispatches first
        pipe.drain()
        assert [c["cases"] for c in pipe.report.chunk_log] == [2, 1]


def test_metrics_json_one_call_dump():
    rng = np.random.default_rng(9)
    with ServePipeline(depth=2, window_ms=0.0, batch_sizes=(1, 2)) as pipe:
        pipe.serve_cases(_cases(4, rng))
        line = pipe.metrics_json()
    m = json.loads(line)
    for key in ("cases", "chunks", "dispatches", "depth", "window_ms",
                "request_latency_ms", "queue_wait_ms", "occupancy",
                "forced_closes", "chunk_log", "build_ms_total",
                "device_ms_total", "fetch_ms_total"):
        assert key in m, key
    assert m["cases"] == 4 and m["depth"] == 2
    assert {"p50", "p90", "p99", "mean", "max"} <= set(
        m["request_latency_ms"])
    for c in m["chunk_log"]:
        assert {"build_ms", "device_ms", "fetch_ms", "closed_by"} <= set(c)


def test_pipeline_validation_refusals():
    with pytest.raises(ValueError, match="depth"):
        ServePipeline(depth=0)
    with pytest.raises(ValueError, match="window_size"):
        ServePipeline(window_size=16)  # above the top batch size
    with pytest.raises(ValueError, match="window_ms"):
        ServePipeline(window_ms=-1.0)
    with pytest.raises(ValueError, match="not both"):
        ServePipeline(EnsembleEngine(), method="sat")
    pipe = ServePipeline(depth=1)
    pipe.close()
    with pytest.raises(RuntimeError, match="closed"):
        pipe.submit(EnsembleCase(shape=(NX, NY), nt=1, eps=EPS, k=1.0,
                                 dt=1e-4, dh=0.02, test=False,
                                 u0=np.zeros((NX, NY))))
