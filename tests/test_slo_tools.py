"""ISSUE 20 tooling: bench_history regression sentinel + fleet_report.

The sentinel's acceptance criterion is pinned DETERMINISTICALLY here
(CI runs the live gate with a generous band because hosted-runner
hardware varies): against a synthetic banked history, an injected 2x
slowdown must fail (rc != 0, offending row named) and the clean row
must pass.  fleet_report renders its one-page markdown from synthetic
artifacts of the exact shapes the serving stack writes.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_tool(name, *args, stdin=None):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", name), *args],
        capture_output=True, text=True, input=stdin, timeout=120)


def bench_row(value=1e8, variant=None, grid=256, backend="cpu", **extra):
    row = {"metric": "points*steps/sec/chip", "value": value,
           "grid": grid, "steps": 5, "ms_per_step": 1.0,
           "backend": backend, "partial": False, **extra}
    if variant is not None:
        row["variant"] = variant
    return row


def write_rows(path, rows):
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    return str(path)


def test_bench_history_catches_2x_slowdown_and_passes_clean(tmp_path):
    hist = tmp_path / "history.jsonl"
    # three banked readings for the (base, 256, cpu) key, median 1e8
    write_rows(hist, [bench_row(0.95e8), bench_row(1.0e8),
                      bench_row(1.05e8)])
    clean = write_rows(tmp_path / "clean.json", [bench_row(0.98e8)])
    slow = write_rows(tmp_path / "slow.json", [bench_row(0.5e8)])
    r = run_tool("bench_history.py", "--history", str(hist), "check",
                 clean)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PASS" in r.stdout
    # the injected 2x slowdown: rc != 0 and the offending row is NAMED
    r = run_tool("bench_history.py", "--history", str(hist), "check",
                 slow)
    assert r.returncode == 1
    assert "FAIL" in r.stdout and "offending row" in r.stdout
    assert '"value": 50000000.0' in r.stdout
    assert "variant=base grid=256 backend=cpu" in r.stdout


def test_bench_history_keys_and_edges(tmp_path):
    hist = tmp_path / "history.jsonl"
    write_rows(hist, [bench_row(1e8),
                      bench_row(2e6, variant="router4"),
                      # a wedged-tunnel fallback row is its OWN class:
                      # it must never drag the healthy baseline down
                      bench_row(1e5, cpu_fallback=True)])
    # per-variant keys: a router row checks against the router median,
    # never the base one (2x the router baseline passes, and the much
    # larger base baseline is not consulted)
    ok = write_rows(tmp_path / "r.json",
                    [bench_row(1.9e6, variant="router4")])
    r = run_tool("bench_history.py", "--history", str(hist), "check", ok)
    assert r.returncode == 0 and "variant=router4" in r.stdout
    # a brand-new variant has no baseline: PASS with the seed note
    new = write_rows(tmp_path / "n.json", [bench_row(1.0, variant="slo8")])
    r = run_tool("bench_history.py", "--history", str(hist), "check", new)
    assert r.returncode == 0 and "no baseline" in r.stdout
    # an empty candidate set is a plumbing FAILURE, not a clean pass
    empty = write_rows(tmp_path / "e.json", [])
    r = run_tool("bench_history.py", "--history", str(hist), "check",
                 empty)
    assert r.returncode == 1 and "no candidate rows" in r.stdout
    # a missing history gates nothing but still passes candidates
    r = run_tool("bench_history.py", "--history",
                 str(tmp_path / "absent.jsonl"), "check", ok)
    assert r.returncode == 0 and "no baseline" in r.stdout


def test_bench_history_bank_appends_and_dedups(tmp_path):
    hist = tmp_path / "history.jsonl"
    src = write_rows(tmp_path / "row.json",
                     [bench_row(1e8, banked_tpu_evidence={"huge": 1})])
    r = run_tool("bench_history.py", "--history", str(hist), "bank", src)
    assert r.returncode == 0 and "banked 1 row(s)" in r.stdout
    banked = json.loads(hist.read_text())
    # the ledger strips the banked-evidence blob and stamps the source
    assert "banked_tpu_evidence" not in banked
    assert banked["source"] == src
    # re-banking the same row is a no-op (idempotent evidence ledger)
    r = run_tool("bench_history.py", "--history", str(hist), "bank", src)
    assert "banked 0 row(s) (1 duplicate(s)" in r.stdout
    assert len(hist.read_text().splitlines()) == 1
    # stdin banking: the CI pipe shape
    r = run_tool("bench_history.py", "--history", str(hist), "bank", "-",
                 stdin="log chatter\n" + json.dumps(bench_row(2e8)) + "\n")
    assert r.returncode == 0 and "banked 1 row(s)" in r.stdout


def test_committed_history_gates_the_ci_smoke_row():
    # the CI step checks the 256^2 CPU smoke row against the COMMITTED
    # ledger — so that ledger must actually hold a (base, 256, cpu)
    # baseline; an empty or mis-keyed seed would make the sentinel
    # vacuously green forever
    hist = os.path.join(REPO, "docs", "bench", "history.jsonl")
    rows = [json.loads(line) for line in open(hist) if line.strip()]
    assert any(r.get("grid") == 256 and r.get("backend") == "cpu"
               and "variant" not in r and
               isinstance(r.get("value"), (int, float))
               for r in rows)


def test_fleet_report_renders_all_sections(tmp_path):
    metrics = tmp_path / "metrics.json"
    metrics.write_text("router: serving\n" + json.dumps({
        "replicas": 2, "transport": "pipe", "cases": 6, "outstanding": 0,
        "deaths": 1, "requeued": 1, "spawns": 1,
        "request_latency_ms": {"p50": 10.0, "p99": 25.0},
        "per_replica": {"0": {"cases": 3, "deaths": 0},
                        "1": {"cases": 3, "deaths": 1}},
        "slo": {"promised": 6, "resolved": 6, "open": 0, "duplicate": 0,
                "unmatched": 0, "deadline_hit_rate": 1.0, "burn": 0.0,
                "drift_ratio_p50": 1.2, "drift_warnings": 1,
                "e2e_ms": {"p50": 9.0, "p99": 24.0},
                "axes": {"default": {"requests": 6,
                                     "deadline_hit_rate": 1.0}}},
    }) + "\n")
    ev = tmp_path / "events.jsonl"
    ev.write_text("".join(json.dumps(e) + "\n" for e in [
        {"pid": 1, "seq": 0, "t": 10.0, "event": "submit"},
        {"pid": 2, "seq": 0, "t": 10.5, "event": "submit"},
        {"pid": 1, "seq": 1, "t": 11.0, "event": "slo-drift",
         "p50": 5.0},
    ]))
    tr = tmp_path / "trace.json"
    tr.write_text(json.dumps({"traceEvents": [
        {"pid": 1, "tid": 1, "ph": "X", "ts": 0, "dur": 5,
         "name": "chunk#0"},
        {"pid": 2, "tid": 1, "ph": "X", "ts": 1, "dur": 5,
         "name": "chunk#1"},
        {"pid": 2, "tid": 1, "ph": "X", "ts": 2, "dur": 1,
         "name": "router.submit"},
    ]}))
    r = run_tool("fleet_report.py", "--metrics", str(metrics),
                 "--events", str(ev), "--trace", str(tr))
    assert r.returncode == 0, r.stderr
    out = r.stdout
    # every section rendered from its artifact
    assert "# Fleet report" in out and "## Fleet" in out
    assert "| replica deaths | 1 |" in out
    assert "## SLO ledger" in out
    assert "| deadline_hit_rate | 1.0 |" in out
    assert "| drift_warnings | 1 |" in out
    assert "| default | 6 | 1.0 |" in out
    assert "## Events (3 from 1 stream(s))" in out
    assert "slo-drift" in out and "warning-class" in out
    assert "## Trace (3 events" in out
    assert "| chunk | 2 |" in out
    # partial artifacts still render: metrics-only, no ledger block
    metrics2 = tmp_path / "m2.json"
    metrics2.write_text(json.dumps({"replicas": 1, "cases": 2}) + "\n")
    r = run_tool("fleet_report.py", "--metrics", str(metrics2))
    assert r.returncode == 0
    assert "_no ledger in the snapshot" in r.stdout
    # no artifacts at all is a usage error
    r = run_tool("fleet_report.py")
    assert r.returncode == 2
