"""Property tests for the Pallas kernels' host-side evaluation plans.

The kernels are only as correct as the static plans they are built from:
the NAF signed-dyadic covers, the circle/sphere height profiles, the
lane-run partitions, and the VMEM chain-step model.  These properties pin
each plan against its defining identity for every eps up to well past the
reference's largest test case (eps=40, tests/1d.txt).
"""

import numpy as np
import pytest

from nonlocalheatequation_tpu.ops.pallas_kernel import (
    _chain_steps,
    _lane_runs,
    _lane_runs_3d,
    _naf,
    _naf_parts,
    _strip_plan,
    _strip_plan_3d,
)
from nonlocalheatequation_tpu.ops.stencil import (
    column_half_heights,
    horizon_mask_2d,
)

EPS_RANGE = list(range(1, 41))
EPS_RANGE_3D = list(range(1, 13))


@pytest.mark.parametrize("w", range(1, 130))
def test_naf_reconstructs_and_is_sparse(w):
    digits = _naf(w)
    assert sum(sign * (1 << p) for p, sign in digits) == w
    # non-adjacency: no two consecutive powers used
    pows = sorted(p for p, _ in digits)
    assert all(b - a >= 2 for a, b in zip(pows, pows[1:], strict=False))
    # minimal weight: NAF uses at most ceil((bitlen+1)/2) digits
    assert len(digits) <= (w.bit_length() + 2) // 2


@pytest.mark.parametrize("width", range(1, 130))
def test_naf_parts_cover_exact_window(width):
    """sum(sign * D_k shifted by off) over parts == the width-window sum,
    with every intermediate offset in range [0, width)."""
    n = 4 * width + 16
    x = np.random.default_rng(width).normal(size=n)
    D = {k: np.array([x[r:r + k].sum() for r in range(n)])
         for k, _, _ in _naf_parts(width)}
    acc = np.zeros(n)
    for k, off, sign in _naf_parts(width):
        # offsets never negative; reads PAST the window (off + k > width,
        # e.g. width 7 = D_8 - D_1@7) are legal — the strip plan's pad
        # bounds them (test_strip_plan_pad_covers_deepest_read)
        assert off >= 0
        shifted = np.zeros(n)
        shifted[: n - off] = D[k][off:]
        acc += sign * shifted
    deepest = max(off + k for k, off, _ in _naf_parts(width))
    valid = n - deepest  # rows whose every part read stays in range
    assert valid >= width
    want = np.array([x[r:r + width].sum() for r in range(valid)])
    assert np.allclose(acc[:valid], want, atol=1e-9)


@pytest.mark.parametrize("eps", EPS_RANGE)
def test_heights_match_mask_columns(eps):
    """column_half_heights IS the mask's column heights (2h+1 cells)."""
    mask = horizon_mask_2d(eps)
    heights = column_half_heights(eps)
    assert len(heights) == 2 * eps + 1
    np.testing.assert_array_equal(mask.sum(axis=0), 2 * np.asarray(heights) + 1)


@pytest.mark.parametrize("eps", EPS_RANGE)
def test_lane_runs_partition_offsets(eps):
    """Runs exactly tile [0, 2eps] with the profile's heights, maximally."""
    heights = [int(h) for h in column_half_heights(eps)]
    runs = _lane_runs(eps)
    covered = []
    for h, j0, L in runs:
        assert L >= 1
        for j in range(j0, j0 + L):
            assert heights[j] == h
            covered.append(j)
        # maximality: the run cannot extend either way
        if j0 > 0:
            assert heights[j0 - 1] != h
        if j0 + L < len(heights):
            assert heights[j0 + L] != h
    assert covered == list(range(2 * eps + 1))
    # wrap-garbage invariant the kernel relies on: j0 + L <= 2*eps + 1
    assert all(j0 + L <= 2 * eps + 1 for _h, j0, L in runs)


@pytest.mark.parametrize("eps", EPS_RANGE_3D)
def test_lane_runs_3d_partition_sphere(eps):
    """3D runs cover every (jj, kk) mask column exactly once, same heights."""
    heights = _strip_plan_3d(eps)[0]
    seen = set()
    for h, jj, k0, L in _lane_runs_3d(eps):
        for kk in range(k0, k0 + L):
            assert heights[jj, kk] == h
            assert (jj, kk) not in seen
            seen.add((jj, kk))
        assert k0 + L <= 2 * eps + 1  # lane wrap-garbage bound
    assert seen == set(heights)
    # (heights-vs-mask equivalence itself is covered by
    # tests/test_pallas.py::test_3d_plan_covers_exact_sphere)


@pytest.mark.parametrize("eps", EPS_RANGE)
def test_strip_plan_pad_covers_deepest_read(eps):
    """The window pad bounds every read the plan can issue: a = eps - h plus
    the deepest NAF part (off + k) within each height's window."""
    heights, parts_by_h, pows, pad = _strip_plan(eps)
    deepest = max(
        (eps - h) + max(off + k for k, off, _ in parts)
        for h, parts in parts_by_h.items()
    )
    assert pad >= deepest
    assert pad % 8 == 0
    # chain completeness: every power's half is present
    for k in pows:
        assert k == 1 or k // 2 in pows


@pytest.mark.parametrize("run_len", range(1, 20))
def test_chain_steps_counts_actual_wsum_ops(run_len):
    """_chain_steps (the VMEM model) equals the lane_down ops that
    _build_lane_wsums ACTUALLY emits, counted via an instrumented stub —
    a divergence would make _lane_slots under-count VMEM stack slots."""
    from nonlocalheatequation_tpu.ops.pallas_kernel import _build_lane_wsums

    calls = {"lane_down": 0}

    class Arr:  # counts the roll+add chain's vector ops symbolically
        def __add__(self, other):
            return Arr()

    def lane_down(x, s):
        calls["lane_down"] += 1
        return Arr()

    wsums = _build_lane_wsums({7: Arr()}, [(7, run_len)], lane_down)
    assert set(wsums) == {(7, run_len)}
    assert _chain_steps(run_len) == calls["lane_down"]
    if run_len == 1:
        assert calls["lane_down"] == 0  # aliases v[h]: no temporaries


@pytest.mark.parametrize("eps,K,tm", [
    (3, 2, 40), (3, 3, 40), (5, 2, 64), (7, 4, 56), (8, 2, 128),
    (8, 3, 32), (12, 2, 48), (16, 2, 64), (1, 2, 16),
])
def test_superstep_frame_geometry_invariants(eps, K, tm):
    """Analytic coverage bounds of the temporally blocked frame
    (_build_superstep_kernel): every read any level can issue stays inside
    the window/band arrays, independent of the empirical bit-identity
    tests.  Mirrors the construction's derivation (docs in the builder)."""
    from nonlocalheatequation_tpu.ops.pallas_kernel import (
        _round_up,
        _strip_plan,
        _window_pad,
    )

    heights, parts_by_h, _pows, pad = _strip_plan(eps)
    max_need = max(
        (eps - h) + max(off + k for k, off, _ in parts)
        for h, parts in parts_by_h.items()
    )
    D = _round_up(K * eps, 8)
    tmw = tm + D + _round_up((K - 1) * eps, 8) + pad

    # dead band covers the upward reach of the shallowest level
    assert D >= K * eps and D % 8 == 0
    # level 1 (row0 = D - (K-1)*eps, band tm + 2*(K-1)*eps): slices start
    # at row0 - h >= 0 and the deepest read stays inside the window
    row0_1 = D - (K - 1) * eps
    bh_1 = tm + 2 * (K - 1) * eps
    assert row0_1 - max(heights) >= 0
    assert row0_1 + bh_1 - 1 + max_need <= tmw - 1
    # levels j >= 2 read from the constructed band array (height
    # bh_{j-1} + pad, row0 = eps): top margin and bottom slack both hold
    for j in range(2, K + 1):
        bh_prev = tm + 2 * (K - j + 1) * eps
        bh_j = tm + 2 * (K - j) * eps
        assert max(heights) <= eps  # slice anchors a = eps - h >= 0
        assert eps + bh_j - 1 + max_need <= bh_prev + pad - 1
    # the frame covers the last strip's window and all out blocks
    for nx in (tm, 3 * tm - 8, 4 * tm):
        G = -(-(nx + 2 * eps) // tm)
        Rc = max(D + G * tm, (G - 1) * tm + tmw)
        assert Rc >= (G - 1) * tm + tmw
        assert Rc >= D + G * tm
        assert G * tm >= nx + 2 * eps
    # out-block offsets stay 8-aligned in the Mosaic mul-form
    assert tm % 8 == 0 and D % 8 == 0
