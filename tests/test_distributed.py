"""Distributed solver on an 8-virtual-device CPU mesh.

Mirrors the reference's Test_2d_distributed batch cases
(CMakeLists.txt:140-154) and adds the framework's structural race-freedom
check: multi-device == single-device == serial oracle (SURVEY.md section 5,
"race detection").
"""

import numpy as np
import pytest

import jax

from tests.cases import CASES_2D_DISTRIBUTED, L2_THRESHOLD

from nonlocalheatequation_tpu.models.solver2d import Solver2D
from nonlocalheatequation_tpu.parallel.distributed2d import (
    Solver2DDistributed,
    choose_mesh_for_grid,
)
from nonlocalheatequation_tpu.parallel.mesh import make_mesh


@pytest.mark.parametrize("nx,ny,npx,npy,nt,eps,k,dt,dh", CASES_2D_DISTRIBUTED)
def test_batch_case_distributed(nx, ny, npx, npy, nt, eps, k, dt, dh):
    s = Solver2DDistributed(nx, ny, npx, npy, nt, eps, k=k, dt=dt, dh=dh)
    s.test_init()
    s.do_work()
    assert s.error_l2 / (nx * ny * npx * npy) <= L2_THRESHOLD
    assert s.mesh.devices.size > 1  # actually exercised the collectives


def test_multi_device_equals_single_device():
    # same problem on a 1-device mesh and on a 4x2 mesh; must agree ~bitwise
    kw = dict(nt=25, eps=5, k=1.0, dt=0.0005, dh=0.02)
    a = Solver2DDistributed(10, 10, 4, 4, mesh=make_mesh(1, 1), **kw)
    b = Solver2DDistributed(10, 10, 4, 4, mesh=make_mesh(4, 2), **kw)
    a.test_init()
    b.test_init()
    ua, ub = a.do_work(), b.do_work()
    assert abs(ua - ub).max() < 1e-12


def test_distributed_equals_serial_oracle():
    o = Solver2D(40, 40, 30, eps=6, k=0.2, dt=0.0005, dh=0.02, backend="oracle")
    d = Solver2DDistributed(10, 10, 4, 4, nt=30, eps=6, k=0.2, dt=0.0005, dh=0.02)
    o.test_init()
    d.test_init()
    uo, ud = o.do_work(), d.do_work()
    assert abs(uo - ud).max() < 1e-12


def test_multihop_halo_when_eps_exceeds_shard():
    # global 20x20 on a 4x2 mesh -> shard edge 5; eps=7 needs 2 hops in x.
    o = Solver2D(20, 20, 20, eps=7, k=0.2, dt=0.0005, dh=0.02, backend="oracle")
    d = Solver2DDistributed(
        20, 20, 1, 1, nt=20, eps=7, k=0.2, dt=0.0005, dh=0.02, mesh=make_mesh(4, 2)
    )
    o.test_init()
    d.test_init()
    uo, ud = o.do_work(), d.do_work()
    assert abs(uo - ud).max() < 1e-12


def test_nbalance_rejected_on_spmd_solver():
    # the SPMD solver shards uniformly — no tile imbalance exists to correct;
    # asking it to rebalance must be a loud error, not a silent no-op
    # (rebalancing lives on ElasticSolver2D)
    with pytest.raises(ValueError, match="ElasticSolver2D"):
        Solver2DDistributed(10, 10, 2, 2, nt=5, eps=3, nbalance=10)


def test_choose_mesh_divides_grid():
    mesh = choose_mesh_for_grid(50, 50)
    mx, my = mesh.shape["x"], mesh.shape["y"]
    assert 50 % mx == 0 and 50 % my == 0 and mx * my <= len(jax.devices())


def test_free_run_no_source_distributed():
    # non-test path (input_init): distributed matches oracle on a decay run
    rng = np.random.default_rng(7)
    u0 = rng.normal(size=(24, 24))
    o = Solver2D(24, 24, 15, eps=4, k=0.5, dt=0.001, dh=0.02, backend="oracle")
    d = Solver2DDistributed(6, 6, 4, 4, nt=15, eps=4, k=0.5, dt=0.001, dh=0.02)
    o.input_init(u0)
    d.input_init(u0)
    uo, ud = o.do_work(), d.do_work()
    assert abs(uo - ud).max() < 1e-12


@pytest.mark.parametrize("K", [2, 3, 5])
def test_superstep_equals_per_step(K):
    """Communication-avoiding superstep (one K*eps-wide halo exchange per K
    steps, shrinking-band local levels) must reproduce the per-step path —
    production and manufactured-source modes, nt not divisible by K (the
    remainder runs a shallower superstep)."""
    # k=0.2 keeps forward Euler stable at this dt/dh/eps (like the oracle
    # tests above): an unstable run amplifies last-ulp program differences
    # exponentially and would make any cross-program bar meaningless
    kw = dict(nt=11, eps=3, k=0.2, dt=0.0005, dh=0.02, method="conv")
    rng = np.random.default_rng(3)
    u0 = rng.normal(size=(40, 40))
    for init in ("test", "input"):
        a = Solver2DDistributed(10, 10, 4, 4, **kw)
        b = Solver2DDistributed(10, 10, 4, 4, superstep=K, **kw)
        for s in (a, b):
            if init == "test":
                s.test_init()
            else:
                s.input_init(u0)
        ua, ub = a.do_work(), b.do_work()
        # f64 last-ulp flips accumulate over the run (the fused source adds
        # happen at extended band shapes); the repo contract is 1e-12
        assert abs(ua - ub).max() < 1e-12, (K, init)
    # collective count: K supersteps exchange a K*eps halo once each
    assert b.ksteps == K


def test_superstep_multihop_and_oracle():
    """K*eps wider than the shard edge forces the multi-hop ring inside the
    superstep exchange; result still matches the serial oracle."""
    o = Solver2D(20, 20, 12, eps=4, k=0.2, dt=0.0005, dh=0.02,
                 backend="oracle")
    d = Solver2DDistributed(
        20, 20, 1, 1, nt=12, eps=4, k=0.2, dt=0.0005, dh=0.02,
        mesh=make_mesh(4, 2), superstep=3
    )  # shard edge 5 in x; K*eps = 12 -> 3 hops
    o.test_init()
    d.test_init()
    uo, ud = o.do_work(), d.do_work()
    assert abs(uo - ud).max() < 1e-12
    assert d.error_l2 / (20 * 20) <= L2_THRESHOLD
