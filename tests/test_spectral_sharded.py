"""Sharded spectral tier (ISSUE 16): pencil-FFT transposes + the
distributed method='fft' steppers.

Pins the tentpole contracts on the f64 8-virtual-device CPU suite:

* the sharded forward transform assembles the SAME global frequency
  array np.fft.rfftn produces on the zero-collar box (<= 1e-12; the
  2D path has measured bitwise equality, pinned as <= 1e-12 per the
  reassociation caveat in ops/spectral_sharded.py), meshes (8,1) /
  (4,2) / (2,4) and 3D (2,2,2), non-square grids, odd 5-smooth boxes,
* roundtrip inv(fwd(u)) == u and the sharded neighbor sum vs the
  NumPy whole-domain oracle (ops/spectral.neighbor_sum_fft_np),
* distributed euler-on-fft / rkc-on-fft / expo (S=0 and S>=1) match
  the serial spectral solvers <= 1e-12 and hold the manufactured
  ``error_l2 / #points <= 1e-6`` contract,
* bitwise run-to-run determinism of a sharded spectral solve,
* the honesty gates: fused/superstep/divisibility/kill-switch
  refusals are loud ValueErrors, never silent downgrades,
* the compat real-FFT fallbacks (utils/compat.py) against np.fft —
  including ODD last-axis lengths, where the n//2+1 inverse rounding
  is the regression the pencil transposes rely on (satellite 1).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from nonlocalheatequation_tpu.models.solver2d import Solver2D
from nonlocalheatequation_tpu.models.solver3d import Solver3D
from nonlocalheatequation_tpu.ops.nonlocal_op import NonlocalOp2D
from nonlocalheatequation_tpu.ops.spectral import (
    fft_box,
    neighbor_sum_fft_np,
)
from nonlocalheatequation_tpu.ops.spectral_sharded import (
    get_plan,
    require_sharded_fft,
    supports_sharded_fft,
)
from nonlocalheatequation_tpu.parallel.distributed2d import Solver2DDistributed
from nonlocalheatequation_tpu.parallel.distributed3d import Solver3DDistributed
from nonlocalheatequation_tpu.parallel.mesh import make_mesh, make_mesh_3d
from nonlocalheatequation_tpu.parallel.spectral_halo import spectral_halo_obs
from nonlocalheatequation_tpu.utils import compat
from nonlocalheatequation_tpu.utils.compat import shard_map

assert jax.config.jax_enable_x64  # the oracle contract (conftest forces it)


def _embed_np(u, box):
    up = np.zeros(box, np.float64)
    up[tuple(slice(0, s) for s in u.shape)] = u
    return up


def _global_freq_oracle(u, plan):
    """np.fft.rfftn on the zero-collar box, zero-padded to the plan's
    global frequency layout (the padded columns carry zero spectrum on
    the sharded path too — ops/spectral_sharded.py docstring)."""
    F = np.fft.rfftn(_embed_np(u, plan.box))
    pad = [(0, g - s) for s, g in
           zip(F.shape, plan.freq_global_shape, strict=True)]
    return np.pad(F, pad)


def _run_fwd_inv(u, mesh, plan):
    spec = P(*plan.axis_names)
    sharding = NamedSharding(mesh, spec)
    fwd = jax.jit(shard_map(plan.fwd, mesh=mesh, in_specs=spec,
                            out_specs=plan.freq_spec))
    inv = jax.jit(shard_map(plan.inv, mesh=mesh, in_specs=plan.freq_spec,
                            out_specs=spec))
    ud = jax.device_put(jnp.asarray(u), sharding)
    h = fwd(ud)
    return np.asarray(h), np.asarray(inv(h))


# ---------------------------------------------------------------------------
# the raw transform vs the whole-domain rfftn oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2), (2, 4)])
def test_fwd_matches_rfftn_oracle_2d(mesh_shape):
    # non-square grid; eps 3 makes the y box 5-smooth 27 (odd-adjacent
    # sizes are covered by the odd-box test below)
    NX, NY = 16, 24
    rng = np.random.default_rng(7)
    u = rng.standard_normal((NX, NY))
    plan = get_plan((NX, NY), 3, mesh_shape)
    h, rt = _run_fwd_inv(u, make_mesh(*mesh_shape), plan)
    F = _global_freq_oracle(u, plan)
    scale = np.max(np.abs(F))
    assert np.max(np.abs(h - F)) / scale <= 1e-12
    # roundtrip: inv discards the collar and returns the domain interior
    assert np.max(np.abs(rt - u)) <= 1e-12


def test_fwd_matches_rfftn_oracle_2d_odd_box():
    # eps 3 on NY=22 -> y box 25 (odd): the rfft bin count (n+1)//2
    # rounding and the frequency padding to a multiple of 8 both bite
    NX, NY, eps = 16, 22, 3
    plan = get_plan((NX, NY), eps, (4, 2))
    assert plan.box[1] % 2 == 1  # the config actually exercises odd n
    rng = np.random.default_rng(11)
    u = rng.standard_normal((NX, NY))
    h, rt = _run_fwd_inv(u, make_mesh(4, 2), plan)
    F = _global_freq_oracle(u, plan)
    assert np.max(np.abs(h - F)) / np.max(np.abs(F)) <= 1e-12
    assert np.max(np.abs(rt - u)) <= 1e-12


def test_fwd_matches_rfftn_oracle_3d():
    # (8, 12, 10) on the full 2x2x2 mesh: odd middle box (15), padded
    # frequency axes on both the middle (transformed-axis pad) and last
    NX, NY, NZ, eps = 8, 12, 10, 2
    plan = get_plan((NX, NY, NZ), eps, (2, 2, 2))
    assert plan.box[1] % 2 == 1
    rng = np.random.default_rng(13)
    u = rng.standard_normal((NX, NY, NZ))
    h, rt = _run_fwd_inv(u, make_mesh_3d(2, 2, 2), plan)
    F = _global_freq_oracle(u, plan)
    assert np.max(np.abs(h - F)) / np.max(np.abs(F)) <= 1e-12
    assert np.max(np.abs(rt - u)) <= 1e-12


def test_sharded_neighbor_sum_matches_np_oracle():
    # the full apply chain the steppers use: fwd * sigma -> inv equals
    # the NumPy whole-domain spectral oracle
    NX, NY, eps = 16, 24, 3
    op = NonlocalOp2D(eps, 1.0, 5e-4, 0.02, method="fft")
    plan = get_plan((NX, NY), eps, (4, 2))
    mesh = make_mesh(4, 2)
    sig = jax.device_put(
        jnp.asarray(plan.neighbor_symbol_padded(op.weights)),
        NamedSharding(mesh, plan.freq_spec))
    spec = P("x", "y")

    def ns_blk(u_blk, sig_blk):
        return plan.inv(plan.fwd(u_blk) * sig_blk)

    ns = jax.jit(shard_map(ns_blk, mesh=mesh,
                           in_specs=(spec, plan.freq_spec),
                           out_specs=spec))
    rng = np.random.default_rng(17)
    u = rng.standard_normal((NX, NY))
    got = np.asarray(
        ns(jax.device_put(jnp.asarray(u), NamedSharding(mesh, spec)), sig))
    want = neighbor_sum_fft_np(op, u)
    assert np.max(np.abs(got - want)) / np.max(np.abs(want)) <= 1e-12


# ---------------------------------------------------------------------------
# the distributed spectral steppers vs the serial spectral solvers
# ---------------------------------------------------------------------------


def _serial2d(stepper, stages, dt, nt):
    s = Solver2D(24, 24, nt, 3, backend="jit", method="fft",
                 stepper=stepper, stages=stages, dt=dt)
    s.test_init()
    s.do_work()
    return s


def _dist2d(stepper, stages, dt, nt, mx, my):
    d = Solver2DDistributed(24 // mx, 24 // my, mx, my, nt, 3,
                            method="fft", stepper=stepper, stages=stages,
                            dt=dt, mesh=make_mesh(mx, my))
    d.test_init()
    d.do_work()
    return d


@pytest.mark.parametrize("stepper,stages,dt",
                         [("euler", 0, 5e-4), ("rkc", 4, 2e-3),
                          ("expo", 0, 1e-3), ("expo", 2, 1e-3)])
def test_distributed_fft_steppers_match_serial_2d(stepper, stages, dt):
    s = _serial2d(stepper, stages, dt, nt=5)
    for mx, my in ((4, 2), (2, 4), (8, 1)):
        d = _dist2d(stepper, stages, dt, 5, mx, my)
        rel = np.max(np.abs(d.u - s.u)) / np.max(np.abs(s.u))
        assert rel <= 1e-12, (mx, my, rel)


def test_distributed_fft_steppers_match_serial_3d():
    N = (8, 12, 10)
    for stepper, stages in (("euler", 0), ("rkc", 4), ("expo", 2)):
        s = Solver3D(*N, 4, 2, backend="jit", method="fft",
                     stepper=stepper, stages=stages, dt=5e-4, dh=0.05)
        s.test_init()
        s.do_work()
        d = Solver3DDistributed(*N, 4, 2, method="fft", stepper=stepper,
                                stages=stages, dt=5e-4, dh=0.05,
                                mesh=make_mesh_3d(2, 2, 2))
        d.test_init()
        d.do_work()
        rel = np.max(np.abs(d.u - s.u)) / np.max(np.abs(s.u))
        assert rel <= 1e-12, (stepper, rel)


def test_distributed_fft_manufactured_contract():
    # the reference pass criterion holds THROUGH the sharded tier —
    # euler under its stability bound (1.4e-4 at eps=3, dh=0.02) and
    # expo with the boundary correction at a dt where the measured
    # collar defect sits under the target (2x the Euler-stable dt)
    d = _dist2d("euler", 0, 1e-4, 20, 4, 2)
    assert d.error_l2 / (24 * 24) <= 1e-6
    d = _dist2d("expo", 2, 2e-4, 10, 4, 2)
    assert d.error_l2 / (24 * 24) <= 1e-6


def test_distributed_fft_bitwise_deterministic():
    # static schedule + fixed mesh concatenation order: two fresh
    # solves are BITWISE equal (the determinism claim the module
    # docstring makes; a tolerance here would hide nondeterminism)
    a = _dist2d("expo", 2, 1e-3, 5, 4, 2)
    b = _dist2d("expo", 2, 1e-3, 5, 4, 2)
    assert np.array_equal(np.asarray(a.u), np.asarray(b.u))


# ---------------------------------------------------------------------------
# capability gate + honesty refusals
# ---------------------------------------------------------------------------


def test_supports_sharded_fft_table():
    # pure host arithmetic: (shape, mesh) -> served or not
    assert supports_sharded_fft((16, 24), 3, (4, 2))
    assert supports_sharded_fft((16, 24), 3, (1, 1))
    assert supports_sharded_fft((8, 12, 10), 2, (2, 2, 2))
    # leading extent must divide mesh[0]*mesh[-1]
    assert not supports_sharded_fft((10, 10), 3, (2, 2))
    # blocks must be uniform
    assert not supports_sharded_fft((16, 25), 3, (4, 5))
    # rank mismatch / unsupported rank
    assert not supports_sharded_fft((16, 24), 3, (2, 2, 2))
    assert not supports_sharded_fft((64,), 3, (8,))


def test_require_sharded_fft_refusals(monkeypatch):
    with pytest.raises(ValueError, match="pencil"):
        require_sharded_fft((10, 10), 3, (2, 2))
    monkeypatch.setenv("NLHEAT_FFT_SHARDED", "0")
    assert not supports_sharded_fft((16, 24), 3, (4, 2))
    with pytest.raises(ValueError, match="kill-switch"):
        require_sharded_fft((16, 24), 3, (4, 2))


def test_solver_ctor_refusals(monkeypatch):
    # fft + the fused stencil transport: loud, never a downgrade
    with pytest.raises(ValueError, match="pencil"):
        Solver2DDistributed(6, 12, 4, 2, 5, 3, method="fft",
                            comm="fused", mesh=make_mesh(4, 2))
    # fft + communication-avoiding superstep: the transform is global
    with pytest.raises(ValueError, match="superstep"):
        Solver2DDistributed(6, 12, 4, 2, 5, 3, method="fft",
                            superstep=2, mesh=make_mesh(4, 2))
    # indivisible pencil split: named (grid, mesh) pair in the message
    with pytest.raises(ValueError, match="pencil"):
        Solver2DDistributed(5, 5, 2, 2, 5, 3, method="fft",
                            mesh=make_mesh(2, 2))
    # the kill-switch reaches the ctor too
    monkeypatch.setenv("NLHEAT_FFT_SHARDED", "0")
    with pytest.raises(ValueError, match="kill-switch"):
        Solver2DDistributed(6, 12, 4, 2, 5, 3, method="fft",
                            mesh=make_mesh(4, 2))


def test_pad_freq_shape_check():
    plan = get_plan((16, 24), 3, (4, 2))
    with pytest.raises(ValueError, match="rfftn layout"):
        plan.pad_freq(np.zeros((3, 3)))


def test_spectral_halo_obs_traffic_model():
    plan = get_plan((16, 24), 3, (4, 2))
    obs = spectral_halo_obs(plan, "rkc", 4, steps=10, itemsize=8,
                            comm="collective")
    assert obs["transport"] == "alltoall"
    assert obs["devices"] == 8
    assert obs["rounds"] == 10 * 4  # one transform pair per rkc stage
    assert obs["bytes_per_device_round"] > 0
    # expo with S=2 substeps: the step transform + 3 projections per
    # substep (the documented approximation)
    obs2 = spectral_halo_obs(plan, "expo", 2, steps=10, itemsize=8,
                             comm="collective")
    assert obs2["rounds"] == 10 * (1 + 3 * 2)


# ---------------------------------------------------------------------------
# compat real-FFT fallbacks vs np.fft (satellite 1: odd last axes)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n", [8, 9, 25])
def test_compat_rfft_last_fallback_odd_even(n):
    rng = np.random.default_rng(n)
    x = rng.standard_normal((3, 5))  # zero-padded 5 -> n by the fft
    got = np.asarray(compat._rfft_last_fallback(jnp.asarray(x), n))
    want = np.fft.rfft(x, n=n, axis=-1)
    assert got.shape[-1] == n // 2 + 1
    assert np.max(np.abs(got - want)) <= 1e-12


@pytest.mark.parametrize("n", [8, 9, 25])
def test_compat_irfft_last_fallback_odd_even(n):
    # the n//2+1 inverse rounding: for odd n the Nyquist bin is absent
    # and the hermitian tail starts at bin 1 — the regression the
    # sharded pencils rely on for odd 5-smooth boxes
    rng = np.random.default_rng(n + 1)
    x = rng.standard_normal((3, n))
    xh = np.fft.rfft(x, axis=-1)
    got = np.asarray(compat._irfft_last_fallback(jnp.asarray(xh), n))
    assert got.shape[-1] == n
    assert np.max(np.abs(got - x)) <= 1e-12
    # and the public entry points agree with np.fft on this build too
    got_pub = np.asarray(compat.irfft_last(jnp.asarray(xh), n))
    assert np.max(np.abs(got_pub - x)) <= 1e-12


@pytest.mark.parametrize("shape", [(4, 6), (4, 5), (3, 4, 5)])
def test_compat_rfftn_irfftn_fallback_roundtrip(shape):
    rng = np.random.default_rng(sum(shape))
    x = rng.standard_normal(shape)
    got = np.asarray(compat._rfftn_fallback(jnp.asarray(x)))
    want = np.fft.rfftn(x)
    assert np.max(np.abs(got - want)) <= 1e-12
    back = np.asarray(
        compat._irfftn_fallback(jnp.asarray(want), shape))
    assert back.shape == tuple(shape)
    assert np.max(np.abs(back - x)) <= 1e-12


# ---------------------------------------------------------------------------
# the picker lift: the collar-defect model qualifies expo; allow_fft is
# the router's capability verdict (ISSUE 16)
# ---------------------------------------------------------------------------


def _euler_bound(eps, k, dh):
    from nonlocalheatequation_tpu.ops.constants import c_2d, stable_dt
    from nonlocalheatequation_tpu.ops.stencil import horizon_mask_2d

    wsum = float(np.asarray(horizon_mask_2d(eps), np.float64).sum())
    return stable_dt(c_2d(k, eps, dh), dh, 2, wsum)


def test_expo_defect_model_is_conservative():
    # the model must OVERestimate the measured one-shot defect at every
    # calibration-class point (feasibility gates multiply ERR_SAFETY on
    # top; an underestimate here would gamble the accuracy target)
    from nonlocalheatequation_tpu.serve.picker import modeled_expo_defect

    eps, dh = 3, 0.02
    eul = _euler_bound(eps, 1.0, dh)
    for S, mult in ((1, 2), (2, 5), (4, 10), (8, 2)):
        T = mult * eul
        s = Solver2D(24, 24, 1, eps, backend="jit", method="fft",
                     stepper="expo", stages=S, dt=T, dh=dh)
        s.test_init()
        s.do_work()
        measured = s.error_l2 / (24 * 24)
        model = modeled_expo_defect((24, 24), eps, eul, T, S)
        assert model >= measured, (S, mult, model, measured)


def test_picker_expo_qualifies_without_opt_in():
    from nonlocalheatequation_tpu.serve.picker import (
        ERR_SAFETY,
        PickerRefusal,
        modeled_expo_defect,
        pick_engine,
    )

    from nonlocalheatequation_tpu.serve.picker import _expo_min_stages

    eps, k, dh = 2, 1.0, 0.01
    eul = _euler_bound(eps, k, dh)
    # short horizon, loose target: one corrected substep covers T at
    # fewer modeled applies (3.5*1) than euler (4 steps at 0.8*bound)
    # or rkc-4 (one 4-stage step), so the model's verdict decides
    T = 3 * eul

    def rate(method, shape, e, precision):
        # stencil applies priced out: only the spectral axis can win
        return 1e-6 if method == "fft" else 1e3

    # the defect model clears the target and expo leaves the opt-in
    # envelope — no NLHEAT_PICK_EXPO, no allow_expo=True
    ch = pick_engine((32, 32), eps, k, dh, T, 1e-3, rate_fn=rate)
    assert (ch.stepper, ch.method, ch.steps) == ("expo", "fft", 1)
    assert ch.stages >= 1  # the boundary correction is always armed
    # est_err is the MODEL's defect, and the target is never gambled
    assert ch.est_err == modeled_expo_defect((32, 32), eps, eul, T,
                                             ch.stages)
    assert ERR_SAFETY * ch.est_err <= 1e-3
    # tighter accuracy needs more substeps — monotone qualification
    # (the pick itself then falls back to rkc, which outprices the
    # extra corrector applies: qualification is never a free pass)
    s_loose = _expo_min_stages((32, 32), eps, eul, T, 1e-3)
    s_tight = _expo_min_stages((32, 32), eps, eul, T, 1e-5)
    assert s_loose == ch.stages and s_tight > s_loose
    ch2 = pick_engine((32, 32), eps, k, dh, T, 1e-5, rate_fn=rate)
    assert ch2.stepper == "rkc"
    # allow_expo=False still excludes the stepper outright
    ch3 = pick_engine((32, 32), eps, k, dh, T, 1e-3, rate_fn=rate,
                      allow_expo=False)
    assert ch3.stepper != "expo"
    # and the capability-gated axis excludes fft AND expo together
    ch4 = pick_engine((32, 32), eps, k, dh, T, 1e-3, rate_fn=rate,
                      allow_fft=False)
    assert ch4.method != "fft" and ch4.stepper != "expo"
    # an fft-base fleet with no fft capability refuses as a 422-class
    # PickerRefusal naming the capability gate (satellite 2)
    with pytest.raises(PickerRefusal, match="capability gate"):
        pick_engine((32, 32), eps, k, dh, T, 1e-3, method="fft",
                    allow_fft=False)


def test_router_sharded_fft_capability_predicate():
    # unit form: the predicate is pure host arithmetic over
    # (gang_devices, shape, eps) — no router spawn, no backend touch
    from nonlocalheatequation_tpu.serve.router import ReplicaRouter

    class Stub:
        gang_devices = 8

    cap = ReplicaRouter.sharded_fft_capability
    assert cap(Stub(), (64, 64), 3)  # choose_mesh_shape(64,64,8)=(8,1)
    assert not cap(Stub(), (64, 64, 64), 3)  # gang tier is 2D
    assert not cap(Stub(), (65, 64), 3)  # indivisible pencil split
    assert not cap(Stub(), "bad", 3)

    class NoGang:
        gang_devices = None  # worker-sized mesh: unknowable, so False

    assert not cap(NoGang(), (64, 64), 3)


def test_http_sharded_fft_pick_and_422_body():
    import json
    import urllib.error
    import urllib.request

    from nonlocalheatequation_tpu.obs.metrics import MetricsRegistry
    from nonlocalheatequation_tpu.serve.http import IngressServer

    class _Req:
        def __init__(self, case, seq):
            self.case, self.seq = case, seq
            self.result = self.error = None
            self.latency_s = 0.0
            self.replica = 0
            import threading

            self.done = threading.Event()

    class _Backend:
        """Router-shaped stub: every case is sharded; the fft
        capability is the TEST's knob."""

        max_outstanding = 4

        def __init__(self, fft_ok):
            self.registry = MetricsRegistry()
            self.fft_ok = fft_ok
            self.engine_kwargs = {"method": "sat"}
            self.submitted = []
            self.registry.histogram(
                "/router/request-latency-ms").observe(1.0)

        def is_sharded(self, shape):
            return True

        def sharded_fft_capability(self, shape, eps):
            return self.fft_ok

        def live_count(self):
            return 1

        def outstanding_total(self):
            return 0

        def retry_after_s(self):
            return 0.25

        def metrics(self):
            return {}

        def submit(self, case, deadline_ms=None, priority=0, **kw):
            req = _Req(case, len(self.submitted))
            self.submitted.append((case, kw.get("engine")))
            return req

    eps, k, dh = 2, 1.0, 0.01
    T = 30 * _euler_bound(eps, k, dh)
    body = {"shape": [32, 32], "eps": eps, "k": k, "dh": dh,
            "T_final": T, "accuracy": 1e-3, "test": True}

    def post(ing, payload):
        try:
            r = urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{ing.port}/v1/cases",
                json.dumps(payload).encode()))
            return r.status, json.load(r)
        except urllib.error.HTTPError as e:
            return e.code, json.load(e)

    # capability True: the sharded pick competes on the full axis (the
    # analytic rate model prices the 32^2 fft under the priced stencil
    # dt cap here — what matters is the axis is OPEN and the pick rides
    # the case frame to the backend)
    be = _Backend(fft_ok=True)
    with IngressServer(0, be) as ing:
        status, resp = post(ing, body)
        assert status == 202 and "engine" in resp
        _case, engine = be.submitted[0]
        assert engine is not None
    # capability False + an fft-base fleet: the picker's refusal is the
    # client's 422 naming the capability gate (satellite 2 pin)
    be2 = _Backend(fft_ok=False)
    be2.engine_kwargs = {"method": "fft"}
    with IngressServer(0, be2) as ing:
        status, resp = post(ing, body)
        assert status == 422
        assert resp["refused"] == "picker"
        assert "capability gate" in resp["error"]
        assert "sharded_fft_capability" in resp["error"]
        assert be2.submitted == []  # refused before any routing
