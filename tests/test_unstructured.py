"""Variable-horizon unstructured-mesh path (ops/unstructured.py).

Key invariant: on a uniform grid with the grid constant, the gather/segment
operator reproduces NonlocalOp2D exactly on interior points (the grid's
volumetric boundary adds zero-valued ghost neighbors the point cloud does
not have, so the boundary collar differs by construction).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from tests.cases import L2_THRESHOLD

from nonlocalheatequation_tpu.ops.nonlocal_op import NonlocalOp2D
from nonlocalheatequation_tpu.ops.unstructured import (
    UnstructuredNonlocalOp,
    UnstructuredSolver,
    build_edges,
)


def grid_cloud(n, dh):
    ii, jj = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    return np.stack([ii.ravel() * dh, jj.ravel() * dh], axis=1)


def test_edges_match_grid_stencil():
    n, eps, dh = 12, 3, 1.0 / 12
    pts = grid_cloud(n, dh)
    tgt, src = build_edges(pts, eps * dh)
    # center point of the grid: neighbor count == mask point count
    from nonlocalheatequation_tpu.ops.stencil import horizon_mask_2d

    center = (n // 2) * n + n // 2
    assert (tgt == center).sum() == horizon_mask_2d(eps).sum()


def test_matches_grid_operator_interior():
    n, eps, dh = 16, 3, 1.0 / 16
    pts = grid_cloud(n, dh)
    gop = NonlocalOp2D(eps, k=1.0, dt=1e-4, dh=dh, method="shift")
    uop = UnstructuredNonlocalOp(
        pts, eps * dh, k=1.0, dt=1e-4, vol=dh * dh, c=gop.c
    )
    rng = np.random.default_rng(0)
    u = rng.normal(size=(n, n))
    a = gop.apply_np(u)
    b = uop.apply_np(u.ravel()).reshape(n, n)
    interior = (slice(eps, n - eps),) * 2
    assert np.abs(a[interior] - b[interior]).max() < 1e-10
    # jit path == numpy path everywhere
    c = np.asarray(uop.apply(jnp.asarray(u.ravel()))).reshape(n, n)
    assert np.abs(b - c).max() < 1e-10


@pytest.mark.parametrize("backend", ["oracle", "jit"])
def test_manufactured_solve_uniform(backend):
    n, dh = 20, 1.0 / 20
    pts = grid_cloud(n, dh)
    op = UnstructuredNonlocalOp(pts, 3 * dh, k=1.0, dt=1e-4, vol=dh * dh)
    s = UnstructuredSolver(op, nt=20, backend=backend)
    s.test_init()
    s.do_work()
    assert s.error_l2 / op.n <= L2_THRESHOLD


def test_manufactured_solve_variable_horizon():
    # horizon field varying by a factor of 2 across the domain
    n, dh = 20, 1.0 / 20
    pts = grid_cloud(n, dh)
    eps = (2.0 + pts[:, 0] * 2.0 / 1.0) * dh  # 2*dh .. 4*dh
    op = UnstructuredNonlocalOp(pts, eps, k=1.0, dt=1e-4, vol=dh * dh)
    s = UnstructuredSolver(op, nt=20, backend="jit")
    s.test_init()
    s.do_work()
    assert s.error_l2 / op.n <= L2_THRESHOLD


def test_manufactured_solve_jittered_cloud():
    # a genuinely unstructured node set: jittered lattice + random volumes
    rng = np.random.default_rng(1)
    n, dh = 18, 1.0 / 18
    pts = grid_cloud(n, dh) + rng.uniform(-0.2 * dh, 0.2 * dh, size=(n * n, 2))
    op = UnstructuredNonlocalOp(pts, 3.2 * dh, k=0.5, dt=1e-4, vol=dh * dh)
    s = UnstructuredSolver(op, nt=15, backend="jit")
    s.test_init()
    s.do_work()
    assert s.error_l2 / op.n <= L2_THRESHOLD


def test_moment_matched_constant_converges_to_laplacian():
    n, dh = 48, 1.0 / 48
    pts = grid_cloud(n, dh)
    op = UnstructuredNonlocalOp(pts, 5 * dh, k=1.0, dt=1e-4, vol=dh * dh)
    g = op.spatial_profile()
    lg = op.apply_np(g)
    lap = -2.0 * (2 * np.pi) ** 2 * g
    interior = (
        (pts[:, 0] > 5.5 * dh) & (pts[:, 0] < 1 - 5.5 * dh)
        & (pts[:, 1] > 5.5 * dh) & (pts[:, 1] < 1 - 5.5 * dh)
    )
    rel = np.abs(lg[interior] - lap[interior]).max() / np.abs(lap[interior]).max()
    assert rel < 0.05


def test_ell_layout_matches_edge_layout():
    # same edges, two reductions: padded-row gather+sum vs segment_sum
    rng = np.random.default_rng(5)
    pts = rng.uniform(size=(300, 2))
    op = UnstructuredNonlocalOp(pts, 0.12, k=0.7, dt=1e-5, vol=1.0 / 300)
    u = jnp.asarray(rng.normal(size=300))
    a = np.asarray(op.apply(u, layout="ell"))
    b = np.asarray(op.apply(u, layout="edges"))
    ref = op.apply_np(np.asarray(u))
    assert np.allclose(a, b, rtol=1e-12, atol=1e-12)
    assert np.allclose(a, ref, rtol=1e-9, atol=1e-9)


def test_auto_layout_falls_back_to_edges_for_hub_node():
    # one wide-horizon hub makes kmax ~ n; dense ELL padding would square
    # the memory, so "auto" must keep the O(edges) edge-list reduction
    rng = np.random.default_rng(9)
    pts = rng.uniform(size=(200, 2))
    eps = np.full(200, 0.08)
    eps[0] = 2.0  # hub sees everyone
    op = UnstructuredNonlocalOp(pts, eps, k=1.0, dt=1e-5, vol=1.0 / 200)
    assert not op._ell_worthwhile()
    assert op._ell_arrays is None  # lazy: nothing built yet
    u = jnp.asarray(rng.normal(size=200))
    got = np.asarray(op.apply(u))  # auto -> edges
    assert op._ell_arrays is None  # still not built
    assert np.allclose(got, op.apply_np(np.asarray(u)), rtol=1e-9, atol=1e-9)


def test_native_edge_builder_parity():
    # the OpenMP builder (native/edges.cc) must reproduce the NumPy
    # builder's edge list EXACTLY (membership rule, tolerance, and
    # (tgt, src)-sorted order) across dimensions and variable horizons
    from nonlocalheatequation_tpu.ops import unstructured as U

    if U._native_lib is None:
        pytest.skip("native/build/libedges.so not built")
    rng = np.random.default_rng(11)
    cases = [
        (rng.uniform(size=(400, 2)), 0.06 * (1 + rng.uniform(size=400))),
        (rng.uniform(size=(300, 3)), 0.15),
        (rng.uniform(size=(200, 1)), 0.02),
    ]
    for pts, eps in cases:
        eps_b = np.broadcast_to(np.asarray(eps, np.float64), (len(pts),))
        nat = U._build_edges_native(np.asarray(pts, np.float64), eps_b)
        lib = U._native_lib
        U._native_lib = None
        try:
            ref = U.build_edges(pts, eps)
        finally:
            U._native_lib = lib
        assert nat is not None
        assert np.array_equal(nat[0], ref[0])
        assert np.array_equal(nat[1], ref[1])


def test_native_edge_builder_parity_at_cell_boundary():
    # 0.3/0.1 floors to 2 but 0.3*(1/0.1) floors to 3: a reciprocal-multiply
    # binning would place the point in a different cell than the NumPy
    # builder and change the edge list (review finding, round 3)
    from nonlocalheatequation_tpu.ops import unstructured as U

    if U._native_lib is None:
        pytest.skip("native/build/libedges.so not built")
    pts = np.array([[0.0], [0.3], [0.4]])
    eps = np.full(3, 0.1)
    nat = U._build_edges_native(pts, eps)
    lib = U._native_lib
    U._native_lib = None
    try:
        ref = U.build_edges(pts, eps)
    finally:
        U._native_lib = lib
    assert np.array_equal(nat[0], ref[0]) and np.array_equal(nat[1], ref[1])
