"""Live simulation sessions (ISSUE 15): the stateful streaming tier.

Pins the tentpole contracts on the CPU suite:

* chunked stepping bit-identity — a session's final f64 field equals
  the offline chunk-by-chunk EnsembleEngine composition, and the frame
  stream (initial + per-boundary previews + final) is a deterministic
  function of (spec, retarget log),
* retarget-at-chunk-boundary determinism — queued k/source verbs apply
  exactly at the next boundary, audited by step, bit-identical to the
  manually composed two-phase run,
* fork + checkpoint resume — a branch from a retained boundary equals
  a fresh run from that state; a manager killed mid-session resumes
  from the newest uncorrupted checkpoint and the combined stream
  (pre-death + post-resume frames, deduped by step) is bit-identical
  to an uninterrupted run with no lost or duplicated frames,
* `die@` chaos — a replica SIGKILLed mid-session and mid-fork is
  invisible to the stream (the router re-routes; results bit-identical),
* budget starvation — with per-session budgets through the admission
  controller's session gate, a greedy streaming session defers and the
  batch tier keeps admitting within its latency bound (deterministic
  injected-clock test; the gateless contrast arm shows batch shed).

The in-process ServePipeline backs every test that doesn't need real
worker processes — the fleet tests (chaos, HTTP/SSE) spawn one router
each and batch their assertions to hold the tier-1 budget.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from nonlocalheatequation_tpu.obs.metrics import MetricsRegistry
from nonlocalheatequation_tpu.serve.ensemble import (
    EnsembleCase,
    EnsembleEngine,
)
from nonlocalheatequation_tpu.serve.http import (
    AdmissionController,
    IngressServer,
)
from nonlocalheatequation_tpu.serve.router import (
    ReplicaRouter,
    RouterOverloaded,
)
from nonlocalheatequation_tpu.serve.server import ServePipeline
from nonlocalheatequation_tpu.serve.sessions import (
    Session,
    SessionManager,
    SessionSpec,
)
from nonlocalheatequation_tpu.utils.checkpoint import (
    list_session_checkpoints,
    session_checkpoint_path,
)

assert jax.config.jax_enable_x64  # the oracle contract (conftest forces it)

G = 12
PHYS = dict(eps=2, k=1.0, dt=1e-5, dh=1.0 / G)


def u0_of(seed=0):
    return np.random.default_rng(seed).normal(size=(G, G))


def chunked_oracle(u0, plan):
    """The session trajectory, composed by hand: ``plan`` is a list of
    ``(n_steps, k, source)`` chunks — each one offline engine run plus
    the session tier's first-order source splitting (u += n*dt*b at the
    chunk's end).  Returns every boundary state (incl. the initial)."""
    eng = EnsembleEngine(method="sat", batch_sizes=(1,))
    states = [np.asarray(u0, np.float64)]
    u = states[0]
    for n, k, source in plan:
        u = eng.run([EnsembleCase(shape=u.shape, nt=n, eps=PHYS["eps"],
                                  k=k, dt=PHYS["dt"], dh=PHYS["dh"],
                                  test=False, u0=u)])[0]
        u = np.asarray(u, np.float64)
        if source is not None:
            u = u + n * PHYS["dt"] * np.asarray(source, np.float64)
        states.append(u)
    return states


def make_pipe():
    return ServePipeline(method="sat", batch_sizes=(1,), depth=1,
                         window_ms=0.0)


def frames_by_step(frames):
    return {(f.step, f.kind): np.array(f.values) for f in frames}


# ---------------------------------------------------------------------------
# chunked stepping + stream (in-process pipeline)
# ---------------------------------------------------------------------------


def test_session_chunked_stream_bit_identity(tmp_path):
    u0 = u0_of(1)
    with make_pipe() as pipe:
        with SessionManager(pipe, checkpoint_dir=str(tmp_path),
                            chunk_steps=4) as mgr:
            s = mgr.open(shape=(G, G), u0=u0, nt=10, checkpoint_every=1,
                         preview_stride=3, **PHYS)
            mgr.drive(timeout_s=120)
            assert s.state == "done" and s.step == 10
            # boundary oracle: 4 + 4 + 2 steps (the final partial chunk)
            states = chunked_oracle(u0, [(4, 1.0, None), (4, 1.0, None),
                                         (2, 1.0, None)])
            assert np.array_equal(s.result(), states[-1])
            assert s.result().dtype == np.float64
            # the stream: initial preview, one per boundary, final f64 —
            # previews are the f32 ::stride downsample of the boundary
            frames = list(mgr.stream(s.sid, from_step=-1, timeout_s=5))
            kinds = [(f.step, f.kind) for f in frames]
            assert kinds == [(0, "preview"), (4, "preview"),
                             (8, "preview"), (10, "preview"),
                             (10, "final")]
            for f, u in zip(frames[:-1], states, strict=True):
                assert f.values.dtype == np.float32
                assert np.array_equal(f.values, u[::3, ::3]
                                      .astype(np.float32))
            # cursor semantics: a reconnecting reader loses nothing and
            # duplicates nothing
            tail = list(mgr.stream(s.sid, from_step=4, timeout_s=5))
            assert [(f.step, f.kind) for f in tail] == [
                (8, "preview"), (10, "preview"), (10, "final")]
            # checkpoints retained at every boundary (cadence 1)
            assert mgr.checkpoints(s.sid) == [4, 8, 10]
            m = mgr.metrics()
            assert m["chunks"] == 3 and m["steps"] == 10
            assert m["completed"] == 1 and m["frames"] == 5
            # the registry is the backend's: one scrape shows the tier
            assert pipe.registry.get("/session/chunks").value == 3


def test_retarget_at_chunk_boundary_determinism(tmp_path, monkeypatch):
    events = tmp_path / "events.jsonl"
    monkeypatch.setenv("NLHEAT_EVENT_LOG", str(events))
    u0 = u0_of(2)
    b = np.full((G, G), 0.25)
    with make_pipe() as pipe:
        with SessionManager(pipe, chunk_steps=3) as mgr:
            s = mgr.open(shape=(G, G), u0=u0, nt=9, **PHYS)
            # queued BEFORE any chunk retires: applies at step 3, so
            # chunk 1 runs the opening physics, chunks 2..3 the new k
            # with the source active
            ticket = mgr.retarget(s.sid, k=1.5, source=b)
            assert ticket["requested_at_step"] == 0
            mgr.drive(timeout_s=120)
            assert s.state == "done"
            states = chunked_oracle(u0, [(3, 1.0, None), (3, 1.5, b),
                                         (3, 1.5, b)])
            assert np.array_equal(s.result(), states[-1])
            # the audit trail: the boundary step is recorded evidence
            audit = s.status()["audit"]
            assert audit == [{"verb": "retarget", "applied_at_step": 3,
                              "requested_at_step": 0, "k": 1.5,
                              "source": "set"}]
            # clearing the source is a verb too (fresh session)
            s2 = mgr.open(shape=(G, G), u0=u0, nt=6, **PHYS)
            mgr.retarget(s2.sid, source=b)
            while s2.step < 3:  # chunk 1 retires; source now active
                mgr.pump(block=True)
            mgr.retarget(s2.sid, clear_source=True)
            mgr.drive(timeout_s=120)
            states2 = chunked_oracle(u0, [(3, 1.0, None), (3, 1.0, b)])
            # chunk 2 ran WITH the source (cleared only at step 6)
            assert np.array_equal(s2.result(), states2[-1])
    lines = [json.loads(ln) for ln in events.read_text().splitlines()]
    kinds = [ln["event"] for ln in lines]
    assert "session-open" in kinds and "session-chunk" in kinds
    assert "session-retarget" in kinds
    assert "session-retarget-applied" in kinds and "session-done" in kinds
    applied = next(ln for ln in lines
                   if ln["event"] == "session-retarget-applied")
    assert applied["applied_at_step"] == 3


def test_fork_and_manager_death_resume_bit_identical(tmp_path, capsys):
    ckpt = str(tmp_path / "ckpt")
    u0 = u0_of(3)
    # arm A: the uninterrupted run — every boundary frame + final field
    with make_pipe() as pipe:
        with SessionManager(pipe, checkpoint_dir=ckpt,
                            chunk_steps=4) as mgr:
            a = mgr.open(shape=(G, G), u0=u0, nt=16, checkpoint_every=1,
                         **PHYS)
            mgr.drive(timeout_s=180)
            want_frames = frames_by_step(
                mgr.stream(a.sid, from_step=-1, timeout_s=5))
            want_final = a.result()
            sid_a = a.sid
    # arm B: same spec, the manager DIES after 2 chunks (close() is the
    # stand-in for the front-door crash — checkpoints are already on
    # disk); a fresh manager resumes from the newest boundary and the
    # combined stream re-emits from there, bit-identical, no dup/loss
    ckpt_b = str(tmp_path / "ckpt_b")
    with make_pipe() as pipe:
        mgr = SessionManager(pipe, checkpoint_dir=ckpt_b, chunk_steps=4)
        b = mgr.open(shape=(G, G), u0=u0, nt=16, checkpoint_every=1,
                     **PHYS)
        sid = b.sid
        while b.step < 8:
            mgr.pump(block=True)
        pre_frames = b.frames_after(-1)  # passive read: stream() would
        # pump a driverless manager and finish the run we mean to kill
        assert b.step == 8 and list_session_checkpoints(ckpt_b, sid) \
            == [4, 8]
        mgr.close()  # the "death" (sessions end closed, ckpts remain)
    with make_pipe() as pipe:
        with SessionManager(pipe, checkpoint_dir=ckpt_b) as mgr2:
            br = mgr2.resume(sid)
            assert br.resumed_from == 8 and br.step == 8
            mgr2.drive(timeout_s=180)
            post_frames = list(mgr2.stream(sid, from_step=-1,
                                           timeout_s=5))
            got = frames_by_step(pre_frames)
            dupes = 0
            for f in post_frames:
                key = (f.step, f.kind)
                if key in got:
                    dupes += 1
                    # a re-emitted boundary must be bit-identical
                    assert np.array_equal(got[key], f.values)
                got[key] = np.array(f.values)
            # the resume re-emitted its boundary (step 8): dup by
            # design, deduped by the cursor/step key
            assert dupes >= 1
            # no lost, no extra: the union equals the uninterrupted set
            want = {(k[0], k[1]) for k in want_frames}
            assert set(got) == want
            for key in want:
                assert np.array_equal(got[key], want_frames[key]), key
            assert np.array_equal(br.result(), want_final)
            assert mgr2.metrics()["resumes"] == 1
    # corrupt-newest fallback: torn final checkpoint -> resume falls
    # back to the previous boundary, loudly
    newest = session_checkpoint_path(ckpt, sid_a,
                                     list_session_checkpoints(
                                         ckpt, sid_a)[-1])
    with open(newest, "wb") as f:
        f.write(b"torn")
    with make_pipe() as pipe:
        with SessionManager(pipe, checkpoint_dir=ckpt) as mgr3:
            c = mgr3.resume(sid_a)
            assert c.step == 12  # newest UNCORRUPTED boundary
            mgr3.drive(timeout_s=180)
            assert np.array_equal(c.result(), want_final)
    assert "unreadable" in capsys.readouterr().err


def test_fork_branches_and_parent_unaffected(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    u0 = u0_of(4)
    b = np.full((G, G), -0.5)
    with make_pipe() as pipe:
        with SessionManager(pipe, checkpoint_dir=ckpt,
                            chunk_steps=4) as mgr:
            parent = mgr.open(shape=(G, G), u0=u0, nt=12,
                              checkpoint_every=1, **PHYS)
            # run to the first boundary, then branch a what-if with a
            # retargeted source while the parent continues unchanged
            while parent.step < 4:
                mgr.pump(block=True)
            assert parent.step == 4
            child = mgr.fork(parent.sid, step=4)
            assert child.parent == (parent.sid, 4) and child.step == 4
            mgr.retarget(child.sid, source=b)
            mgr.drive(timeout_s=180)
            p_states = chunked_oracle(u0, [(4, 1.0, None)] * 3)
            assert np.array_equal(parent.result(), p_states[-1])
            c_states = chunked_oracle(p_states[1], [(4, 1.0, None),
                                                    (4, 1.0, b)])
            assert np.array_equal(child.result(), c_states[-1])
            assert child.status()["audit"][0] == {
                "verb": "fork", "parent": parent.sid, "from_step": 4}
            assert mgr.metrics()["forks"] == 1


# ---------------------------------------------------------------------------
# die@ chaos over a real fleet
# ---------------------------------------------------------------------------


def test_die_chaos_mid_session_and_mid_fork_bit_identical(tmp_path):
    u0 = u0_of(5)
    # the oracle: boundary states of the uninterrupted trajectory
    states = chunked_oracle(u0, [(4, 1.0, None)] * 3)
    # die@1 kills the replica serving the SECOND session chunk mid-
    # flight; die@4 kills again while the fork's first chunk is in
    # flight — both re-route and re-serve bit-identically (the session
    # never notices; checkpoint resume is for manager death, above)
    with ReplicaRouter(replicas=2, method="sat", batch_sizes=(1,),
                       faults="die@1,die@4", respawn=True) as router:
        with SessionManager(router, checkpoint_dir=str(tmp_path),
                            chunk_steps=4) as mgr:
            s = mgr.open(shape=(G, G), u0=u0, nt=12, checkpoint_every=1,
                         **PHYS)
            # drive the parent through its chunks; fork at step 8
            while True:
                mgr.pump(block=True)
                if s.step >= 8:
                    break
            child = mgr.fork(s.sid, step=8)
            mgr.drive(timeout_s=300)
            assert s.state == "done" and child.state == "done"
            assert np.array_equal(s.result(), states[-1])
            # the fork continued the same trajectory from step 8
            assert np.array_equal(child.result(), states[-1])
            frames = list(mgr.stream(s.sid, from_step=-1, timeout_s=5))
            assert [(f.step, f.kind) for f in frames] == [
                (0, "preview"), (4, "preview"), (8, "preview"),
                (12, "preview"), (12, "final")]
        m = router.metrics()
        assert m["deaths"] >= 1 and m["requeued"] >= 1
        assert m["outstanding"] == 0
        # session placement was sticky-by-session-id, not bucket key
        assert any(key[0] == "session" for key in router._owner)


# ---------------------------------------------------------------------------
# budgets: a greedy stream cannot starve the batch tier (injected clock)
# ---------------------------------------------------------------------------


class _FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class _StubRequest:
    def __init__(self, case, seq, submit_t):
        self.case = case
        self.seq = seq
        self.submit_t = submit_t
        self.result = None
        self.error = None
        self.latency_s = None
        self.replica = 0
        self.requeues = 0
        self.done = threading.Event()


class _StubBackend:
    """Router-shaped backend with test-controlled completion and an
    injected clock — the 'fleet' whose capacity the starvation test
    reasons about deterministically."""

    def __init__(self, clock, max_outstanding=4):
        self.registry = MetricsRegistry()
        self.max_outstanding = max_outstanding
        self.clock = clock
        self._pending = []
        self._seq = 0
        self._lat = self.registry.histogram("/router/request-latency-ms")
        self.registry.gauge("/router/outstanding")

    def live_count(self):
        return 1

    def outstanding_total(self):
        return len(self._pending)

    def retry_after_s(self):
        return 0.25

    def submit(self, case, deadline_ms=None, priority=0, sticky_key=None,
               trace=None, engine=None):
        if len(self._pending) >= self.max_outstanding:
            raise RouterOverloaded(len(self._pending),
                                   self.max_outstanding, 0.25)
        req = _StubRequest(case, self._seq, self.clock())
        self._seq += 1
        self._pending.append(req)
        return req

    def finish(self, n=1):
        for _ in range(n):
            req = self._pending.pop(0)
            req.result = np.asarray(req.case.u0, np.float64)
            req.latency_s = self.clock() - req.submit_t
            self._lat.observe(req.latency_s * 1e3)
            req.done.set()


def _greedy_sessions(mgr, n, budget=0):
    return [mgr.open(shape=(G, G), u0=u0_of(10 + i), nt=None,
                     chunk_steps=4, budget_steps=budget,
                     preview_stride=4, checkpoint_every=0, **PHYS)
            for i in range(n)]


def test_session_budget_cannot_starve_batch():
    clock = _FakeClock()
    backend = _StubBackend(clock, max_outstanding=4)
    # the session gate: 8 steps/s fleet-wide (2 chunks of 4), batch
    # bound 250 ms — the admission controller's promise under load
    adm = AdmissionController(backend, max_pending=4,
                              max_queue_wait_ms=250.0,
                              session_steps_per_s=8.0, clock=clock)
    with SessionManager(backend, admission=adm, clock=clock) as mgr:
        _greedy_sessions(mgr, 8)
        # 8 greedy open-ended sessions race: the token bucket admits
        # exactly 2 chunks (burst = one second = 8 steps), the rest
        # DEFER — the fleet keeps 2 of 4 slots free for batch
        assert mgr.pump() == 2
        assert backend.outstanding_total() == 2
        assert mgr.metrics()["deferrals"] == 6
        assert adm.backend.registry.get(
            "/ingress/session-deferred").value == 6
        # batch keeps flowing: both offered cases admitted, no shed
        batch = [EnsembleCase(shape=(G, G), nt=2, test=False,
                              u0=u0_of(30 + i), **PHYS)
                 for i in range(2)]
        for c in batch:
            req, retry = adm.try_submit(c)
            assert req is not None and retry is None
        backend.finish(4)  # everything in flight completes this tick
        clock.advance(0.1)
        # batch latency stayed inside the admission bound (the
        # deterministic p99-within-bound half of the acceptance)
        lat = adm.backend.registry.get("/router/request-latency-ms")
        assert lat.percentiles()["p99"] <= 250.0
        assert adm.backend.registry.get("/ingress/shed").value == 0
        # the rolling average holds: 0.6 s later only ONE more chunk's
        # worth of tokens has accrued — the pump retires the two
        # finished chunks and admits exactly one new one
        clock.advance(0.5)
        assert mgr.pump() == 3
        assert backend.outstanding_total() == 1
        assert adm.backend.registry.get(
            "/ingress/session-steps").value == 12
    # CONTRAST arm — no session gate: the same greedy sessions fill
    # every slot and the batch tier sheds.  This is the starvation the
    # gate exists to prevent.
    clock2 = _FakeClock()
    backend2 = _StubBackend(clock2, max_outstanding=4)
    adm2 = AdmissionController(backend2, max_pending=4, clock=clock2)
    with SessionManager(backend2, admission=adm2, clock=clock2) as mgr2:
        _greedy_sessions(mgr2, 8)
        mgr2.pump()
        assert backend2.outstanding_total() == 4  # saturated
        req, retry = adm2.try_submit(
            EnsembleCase(shape=(G, G), nt=2, test=False, u0=u0_of(40),
                         **PHYS))
        assert req is None and retry > 0
        assert backend2.registry.get("/ingress/shed").value == 1


def test_per_session_budget_window(monkeypatch):
    # the PER-session budget (no fleet gate): 4 steps per window means
    # one chunk per window — the second submit defers until the window
    # rolls on the injected clock
    clock = _FakeClock()
    backend = _StubBackend(clock, max_outstanding=8)
    with SessionManager(backend, clock=clock) as mgr:
        s = mgr.open(shape=(G, G), u0=u0_of(11), nt=12, chunk_steps=4,
                     budget_steps=4, budget_window_s=1.0,
                     checkpoint_every=0, **PHYS)
        assert mgr.pump() == 1
        backend.finish(1)
        assert mgr.pump() == 1  # retire chunk 1
        assert s.step == 4
        assert mgr.pump() == 0  # budget spent: deferred
        assert s.status()["deferrals"] == 1
        clock.advance(1.1)  # the window rolls
        assert mgr.pump() == 1
        backend.finish(1)
        # env default wiring: NLHEAT_SESSION_BUDGET backs specs that
        # don't name a budget
        monkeypatch.setenv("NLHEAT_SESSION_BUDGET", "16")
        s2 = mgr.open(shape=(G, G), u0=u0_of(12), nt=4, chunk_steps=4,
                      checkpoint_every=0, **PHYS)
        assert s2.spec.budget_steps == 16


def test_close_mid_stream_delivers_final_frame():
    # regression: close_session emits the final f64 frame at the SAME
    # step as the last preview — a reader that already consumed that
    # preview (cursor == step) must still receive the final (the
    # (step, kind-rank) cursor; a bare step cursor skipped it)
    clock = _FakeClock()
    backend = _StubBackend(clock)
    with SessionManager(backend, clock=clock) as mgr:
        s = mgr.open(shape=(G, G), u0=u0_of(13), nt=None, chunk_steps=4,
                     checkpoint_every=0, **PHYS)
        mgr.pump()
        backend.finish(1)
        mgr.pump()  # boundary at step 4: preview emitted
        seen = s.frames_after(-1)
        assert [(f.step, f.kind) for f in seen] == [(0, "preview"),
                                                    (4, "preview")]
        mgr.close_session(s.sid)
        # the final at step 4 is strictly PAST the consumed-preview
        # position (4, rank 0) ...
        due = s.frames_after(4, 0)
        assert [(f.step, f.kind) for f in due] == [(4, "final")]
        assert due[0].values.dtype == np.float64
        # ... and the stream generator delivers it from the same cursor
        frames = list(mgr.stream(s.sid, from_step=4, timeout_s=1))
        assert [(f.step, f.kind) for f in frames] == [(4, "final")]
        # the pump claim: a session already being worked by one thread
        # is skipped by every other pump (no double-submit)
        s2 = mgr.open(shape=(G, G), u0=u0_of(14), nt=8, chunk_steps=4,
                      checkpoint_every=0, **PHYS)
        with s2._lock:
            s2._pump_busy = True
        assert mgr.pump() == 0
        with s2._lock:
            s2._pump_busy = False
        assert mgr.pump() == 1


# ---------------------------------------------------------------------------
# HTTP surface: open / stream (SSE) / retarget / fork / close / result
# ---------------------------------------------------------------------------


def _post(base, path, payload):
    try:
        r = urllib.request.urlopen(urllib.request.Request(
            base + path, json.dumps(payload).encode()))
        return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


def test_http_session_endpoints_end_to_end(tmp_path):
    u0 = u0_of(6)
    states = chunked_oracle(u0, [(4, 1.0, None), (4, 1.0, None)])
    with ReplicaRouter(replicas=1, method="sat",
                       batch_sizes=(1,)) as router:
        adm = AdmissionController(router)
        with SessionManager(router, admission=adm,
                            checkpoint_dir=str(tmp_path),
                            chunk_steps=4) as mgr:
            mgr.start_driver()
            with IngressServer(0, router, admission=adm,
                               sessions=mgr) as ing:
                base = f"http://127.0.0.1:{ing.port}"
                body = dict(shape=[G, G], nt=8, eps=PHYS["eps"],
                            k=PHYS["k"], dt=PHYS["dt"], dh=PHYS["dh"],
                            u0=u0.tolist(), chunk_steps=4,
                            checkpoint_every=1)
                st, r = _post(base, "/v1/sessions", body)
                assert st == 201 and r["status"] == "running"
                sid = r["session"]
                assert r["stream"] == f"/v1/sessions/{sid}/stream"
                # the SSE stream: read to EOF (the server closes when
                # the session completes), parse `data:` lines
                raw = urllib.request.urlopen(
                    base + f"/v1/sessions/{sid}/stream?timeout_s=60",
                    timeout=120).read().decode()
                frames = [json.loads(ln[len("data: "):])
                          for ln in raw.splitlines()
                          if ln.startswith("data: ")]
                assert [(f["step"], f["kind"]) for f in frames[:-1]] == [
                    (0, "preview"), (4, "preview"), (8, "preview"),
                    (8, "final")]
                assert "event: end" in raw
                final = np.asarray(
                    frames[-2]["values"]).reshape(frames[-2]["shape"])
                # JSON f64 round-trips exactly: the streamed final field
                # IS the oracle composition, bitwise
                assert np.array_equal(final, states[-1])
                # status document + result endpoint
                r = json.load(urllib.request.urlopen(
                    base + f"/v1/sessions/{sid}"))
                assert r["state"] == "done" and r["step"] == 8
                r = json.load(urllib.request.urlopen(
                    base + f"/v1/sessions/{sid}/result"))
                got = np.asarray(r["values"]).reshape(r["shape"])
                assert np.array_equal(got, states[-1])
                # fork over HTTP from a retained checkpoint boundary:
                # the child re-runs 4 -> 8 on the same physics, so its
                # final field must equal the parent's, bitwise
                st, r = _post(base, f"/v1/sessions/{sid}/fork",
                              {"step": 4})
                assert st == 201 and r["from_step"] == 4
                child = r["session"]
                raw2 = urllib.request.urlopen(
                    base + f"/v1/sessions/{child}/stream?timeout_s=60",
                    timeout=120).read().decode()
                finals = [json.loads(ln[len("data: "):])
                          for ln in raw2.splitlines()
                          if ln.startswith("data: ")
                          and '"final"' in ln]
                got = np.asarray(finals[-1]["values"]).reshape(
                    finals[-1]["shape"])
                assert np.array_equal(got, states[-1])
                # retarget + close ride HTTP too (a long-running
                # session this time, so the verbs race nothing)
                st, r = _post(base, "/v1/sessions",
                              dict(body, nt=4000))
                assert st == 201
                slow = r["session"]
                st, r = _post(base, f"/v1/sessions/{slow}/retarget",
                              {"k": 2.0})
                assert st == 202 and r["session"] == slow
                st, r = _post(base, f"/v1/sessions/{slow}/close", {})
                assert st == 200 and r["state"] == "closed"
                # client errors: bad body, unknown session, bad verb
                st, r = _post(base, "/v1/sessions", {"shape": [G, G]})
                assert st == 400 and "missing case field" in r["error"]
                st, r = _post(base, "/v1/sessions/nope/retarget",
                              {"k": 2.0})
                assert st == 404
                st, _ = _post(base, f"/v1/sessions/{sid}/explode", {})
                assert st == 404
                # a test=true session is refused: chunked manufactured
                # sources would restart time every chunk
                st, r = _post(base, "/v1/sessions",
                              dict(body, test=True))
                assert st == 400 and "test" in r["error"]
                # the health document carries the session tier
                r = json.load(urllib.request.urlopen(base + "/healthz"))
                assert "sessions" in r
                # /session/* metrics ride the same fleet scrape
                text = urllib.request.urlopen(
                    base + "/metrics").read().decode()
                assert "nlheat_session_opened" in text
                assert "nlheat_session_chunks" in text


# ---------------------------------------------------------------------------
# refusals
# ---------------------------------------------------------------------------


def test_session_spec_and_manager_refusals(tmp_path):
    clock = _FakeClock()
    backend = _StubBackend(clock)
    ok = dict(shape=(G, G), u0=u0_of(7), nt=8, **PHYS)
    for bad, msg in [
        (dict(ok, u0=None), "needs an initial state"),
        (dict(ok, nt=0), "nt must be"),
        (dict(ok, shape=(0,)), "bad session shape"),
        (dict(ok, chunk_steps=0), "chunk_steps"),
        (dict(ok, u0=np.zeros(3)), "u0 has 3 values"),
        (dict(ok, budget_steps=-1), "budget_steps"),
        (dict(ok, preview_stride=0), "preview_stride"),
        (dict(ok, checkpoint_every=-1), "checkpoint_every"),
    ]:
        with pytest.raises(ValueError, match=msg):
            SessionSpec(**bad).validate()
    with SessionManager(backend, clock=clock) as mgr:
        s = mgr.open(**ok)
        # JSON-shaped values COERCE at validate (a 2.5 stride or "10"
        # budget must never detonate later inside the pump)
        sp = SessionSpec(**dict(ok, preview_stride=2.5,
                                budget_steps="10",
                                chunk_steps=4.0)).validate()
        assert sp.preview_stride == 2 and sp.budget_steps == 10
        assert sp.chunk_steps == 4 and isinstance(sp.chunk_steps, int)
        with pytest.raises(ValueError, match="retarget needs"):
            mgr.retarget(s.sid)
        with pytest.raises(ValueError, match="source has"):
            mgr.retarget(s.sid, source=[1.0, 2.0])
        with pytest.raises(KeyError):
            mgr.get("nope")
        with pytest.raises(ValueError, match="checkpoint_dir"):
            mgr.resume("nope")
        with pytest.raises(ValueError, match="checkpoint_dir"):
            mgr.fork(s.sid, step=4)
        mgr.close_session(s.sid)
        with pytest.raises(ValueError, match="running"):
            mgr.retarget(s.sid, k=2.0)
        # double close is idempotent: /session/closed counts ONE end
        mgr.close_session(s.sid)
        assert backend.registry.get("/session/closed").value == 1
    # bounded retention of ended sessions (the RESULTS_CAP twin): the
    # oldest ended sessions age out; checkpoints on disk would remain
    with SessionManager(backend, clock=clock, retain_ended=2) as mgr:
        sids = []
        for i in range(4):
            si = mgr.open(**dict(ok, u0=u0_of(20 + i)))
            sids.append(si.sid)
            mgr.close_session(si.sid)
        live = set(mgr._sessions)
        assert sids[0] not in live and sids[1] not in live
        assert sids[2] in live and sids[3] in live
    with SessionManager(backend, clock=clock,
                        checkpoint_dir=str(tmp_path)) as mgr:
        s = mgr.open(**ok)
        with pytest.raises(ValueError, match="already live"):
            mgr.resume(s.sid)
        with pytest.raises(FileNotFoundError):
            mgr.resume("never-existed")
        with pytest.raises(FileNotFoundError, match="no checkpoints"):
            mgr.fork(s.sid, step=99)  # nothing retained yet at all
    # a session is pinned by sticky key, and Session exposes it
    assert Session("s9", SessionSpec(**ok).validate()).sticky_key() \
        == ("session", "s9")
