"""SLO promise-audit ledger (ISSUE 20, obs/slo.py): unit + fleet tests.

Covers the tentpole contracts deterministically on the CPU suite:

* the promise/outcome join: counts, hit rates, per-engine-axis tables,
  the rolling burn window — all under an injected clock,
* pop-once discipline: a duplicate resolve counts ``/slo/duplicate``
  and changes nothing; an unknown seq counts ``/slo/unmatched``,
* the drift detector: quiet on a clean run, fires exactly once per
  excursion when the modeled-vs-observed p50 leaves the band, and
  re-arms after the window recovers,
* live recalibration (LiveRateRecorder -> autotune file cache ->
  picker.record_rate_fn): the persisted ``live`` block, the picker's
  live-first preference and ``"live"`` provenance, and the acceptance
  criterion — recalibrated cost ratios are STRICTLY tighter around 1.0
  than the stale-probe baseline on the same observation sequence,
* ledger consistency under chaos: a replica killed mid-chunk
  (``die@2``, tests/test_router.py machinery) leaves no orphaned or
  duplicated entries — the re-routed outcome is attributed exactly
  once,
* ``GET /v1/status``: the one-page fleet document over a stub backend.
"""

import json
import math
import urllib.request

import numpy as np

import jax

from nonlocalheatequation_tpu.obs.metrics import MetricsRegistry
from nonlocalheatequation_tpu.obs.slo import (
    LiveRateRecorder,
    SloLedger,
    applies_per_step,
    engine_axis,
)
from nonlocalheatequation_tpu.serve.ensemble import (
    EnsembleCase,
    EnsembleEngine,
)
from nonlocalheatequation_tpu.serve.http import IngressServer
from nonlocalheatequation_tpu.serve.picker import (
    EngineChoice,
    record_rate_fn,
)
from nonlocalheatequation_tpu.serve.router import ReplicaRouter

assert jax.config.jax_enable_x64  # the oracle contract (conftest forces it)


def make_ledger(**kw):
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("live", False)
    return SloLedger(**kw)


def choice(est_ms=2.0, stepper="rkc", stages=8, method="fft",
           precision="bf16", rates="records"):
    return EngineChoice(stepper=stepper, stages=stages, method=method,
                        precision=precision, dt=1e-5, steps=100,
                        est_ms=est_ms, est_err=1e-9, rates=rates)


# ---------------------------------------------------------------------------
# the ledger itself
# ---------------------------------------------------------------------------


def test_promise_resolve_join_summary_and_axes():
    led = make_ledger()
    # three picked requests (engine axis + modeled cost), two default
    for seq in range(3):
        led.promise(seq, engine=choice(est_ms=2.0), deadline_ms=1000.0,
                    t=0.0)
    for seq in (3, 4):
        led.promise(seq, deadline_ms=1000.0, t=0.0)
    assert led.summary()["open"] == 5
    # outcomes: all inside deadline; device wall feeds the cost ratio
    for seq in range(3):
        rec = led.resolve(seq, latency_s=0.010, queue_wait_s=0.002,
                          device_ms=2.2)
        assert rec["deadline_hit"] is True
        assert math.isclose(rec["cost_ratio"], 2.2 / 2.0)
    for seq in (3, 4):
        rec = led.resolve(seq, latency_s=0.020)
        assert rec["deadline_hit"] is True
        assert "cost_ratio" not in rec  # no modeled cost on default
    s = led.summary()
    assert s["promised"] == 5 and s["resolved"] == 5 and s["open"] == 0
    assert s["deadline_hit"] == 5 and s["deadline_miss"] == 0
    assert s["deadline_hit_rate"] == 1.0 and s["burn"] == 0.0
    assert s["errors"] == 0
    assert math.isclose(s["drift_ratio_p50"], 1.1)
    assert s["e2e_ms"]["p50"] > 0 and s["queue_wait_ms"]["p50"] > 0
    # the per-engine-axis table: picked vs default attribution
    axes = led.axes()
    assert set(axes) == {"rkc[s=8]/fft/bf16", "default"}
    assert axes["rkc[s=8]/fft/bf16"] == {
        "requests": 3, "deadline_hit": 3, "deadline_miss": 0,
        "hit_rate": 1.0}
    assert axes["default"]["requests"] == 2
    # the registry surface: every signal scrapeable under /slo/*
    names = led.registry.names()
    assert "/slo/promised" in names and "/slo/burn" in names
    assert "/slo/drift" in names


def test_pop_once_duplicate_vs_unmatched_and_miss_burn():
    led = make_ledger(window=4)
    led.promise(0, deadline_ms=5.0, t=0.0)
    assert led.resolve(0, latency_s=0.050) is not None  # 50 ms > 5 ms
    # duplicate: the same seq again — dropped, counted, nothing changes
    assert led.resolve(0, latency_s=0.001) is None
    # unmatched: never promised
    assert led.resolve(99, latency_s=0.001) is None
    s = led.summary()
    assert s["duplicate"] == 1 and s["unmatched"] == 1
    assert s["resolved"] == 1 and s["deadline_miss"] == 1
    assert s["burn"] == 1.0  # every promise in the window missed
    assert led.axes()["default"]["hit_rate"] == 0.0
    # an error outcome never counts as a hit, whatever the latency
    led.promise(1, deadline_ms=1e6, t=0.0)
    rec = led.resolve(1, latency_s=0.001, error="replica-death")
    assert rec["deadline_hit"] is False
    assert led.summary()["errors"] == 1
    # the burn window ROLLS: hits push the early misses out
    for seq in range(2, 8):
        led.promise(seq, deadline_ms=1000.0, t=0.0)
        led.resolve(seq, latency_s=0.001)
    assert led.summary()["burn"] == 0.0


def test_drift_quiet_on_clean_fires_once_per_excursion():
    led = make_ledger(window=32, band=(0.5, 2.0), min_samples=4)

    def feed(n, observed_ms, start):
        for seq in range(start, start + n):
            led.promise(seq, engine=choice(est_ms=1.0), t=0.0)
            led.resolve(seq, latency_s=0.001, device_ms=observed_ms)

    # clean: ratios pinned at 1.0 -> the warning NEVER fires
    feed(12, 1.0, 0)
    assert led.summary()["drift_warnings"] == 0
    assert led.summary()["drift"] == 1.0
    # corruption: observed 10x the model -> p50 leaves the band; the
    # warning fires ONCE for the whole excursion, not once per request
    feed(40, 10.0, 100)
    s = led.summary()
    assert s["drift_warnings"] == 1
    assert s["drift_ratio_p50"] > 2.0
    # recovery re-arms the detector: back in band, then a second
    # excursion fires a second (single) warning
    feed(64, 1.0, 200)
    assert led.summary()["drift_warnings"] == 1
    feed(64, 0.01, 300)
    assert led.summary()["drift_warnings"] == 2


def test_axis_grammar_and_applies_per_step():
    assert engine_axis(None) == "default"
    assert engine_axis(("euler", 0, "sat", "f32")) == "euler[s=0]/sat/f32"
    assert engine_axis(("rkc", 16, "fft", "bf16"),
                       mesh="abcdef0123456789") == \
        "rkc[s=16]/fft/bf16/mesh-abcdef012345"
    assert applies_per_step("euler", 0) == 1.0
    assert applies_per_step("rkc", 16) == 16.0
    assert applies_per_step("expo", 2) == 7.0


def test_from_arg_contract(monkeypatch):
    reg = MetricsRegistry()
    led = make_ledger()
    assert SloLedger.from_arg(led) is led          # instance: as-is
    assert SloLedger.from_arg(False) is None       # explicit off
    monkeypatch.delenv("NLHEAT_SLO", raising=False)
    assert SloLedger.from_arg(None) is None        # default: env-gated
    monkeypatch.setenv("NLHEAT_SLO", "1")
    built = SloLedger.from_arg(None, registry=reg, live=False)
    assert isinstance(built, SloLedger) and built.registry is reg
    monkeypatch.setenv("NLHEAT_SLO", "0")
    assert SloLedger.from_arg(None) is None
    assert isinstance(SloLedger.from_arg(True, live=False), SloLedger)


# ---------------------------------------------------------------------------
# live recalibration: the ISSUE 20 feedback loop
# ---------------------------------------------------------------------------


def test_live_rates_persist_and_picker_prefers_them(tmp_path, monkeypatch):
    monkeypatch.setenv("NLHEAT_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    rec = LiveRateRecorder("cpu", version="t", flush_every=1)
    rec.record("sat", (64, 64), 8, "f32", 3.0)
    # the persisted entry carries the DISJOINT live block — the tuner's
    # winner election keys (ms_per_step) are untouched
    cache = json.load(open(tmp_path / "autotune.json"))
    entry = cache["vt/cpu/sat/64x64/eps8/float32"]
    assert entry["live"] == {"per-step": 3.0, "n": 1,
                             "provenance": "live"}
    assert "ms_per_step" not in entry
    # EWMA folding + observation counting across flushes
    rec.record("sat", (64, 64), 8, "f32", 7.0)
    cache = json.load(open(tmp_path / "autotune.json"))
    live = cache["vt/cpu/sat/64x64/eps8/float32"]["live"]
    assert math.isclose(live["per-step"], 3.0 + 0.25 * (7.0 - 3.0))
    assert live["n"] == 2
    # the picker's rate_fn prefers the live rate and audits provenance
    rate = record_rate_fn("cpu", version="t")
    assert math.isclose(rate("sat", (64, 64), 8, "f32"), 4.0)
    assert rate.provenance == "live"
    # an unknown key still falls through to the analytic proxy
    assert rate("sat", (128, 128), 8, "f32") > 0
    # non-finite and non-positive observations are dropped, not folded
    rec.record("sat", (64, 64), 8, "f32", float("nan"))
    rec.record("sat", (64, 64), 8, "f32", -1.0)
    rec.flush()
    cache = json.load(open(tmp_path / "autotune.json"))
    assert cache["vt/cpu/sat/64x64/eps8/float32"]["live"]["n"] == 2


def test_live_recalibration_narrows_cost_ratio_spread(tmp_path,
                                                      monkeypatch):
    """The ISSUE 20 acceptance criterion, deterministically: against a
    device whose true per-apply rate drifted 4x away from the stale
    probe, the live-recalibrated model's cost ratios (observed/modeled)
    sit STRICTLY tighter around 1.0 than the stale-probe baseline over
    the same observation sequence."""
    monkeypatch.setenv("NLHEAT_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    key_args = ("sat", (64, 64), 8, "f32")
    stale_ms = 1.0  # what the probe banked long ago
    # the true device rate today: ~4x slower, with deterministic jitter
    true_ms = [4.0, 3.8, 4.3, 4.1, 3.9, 4.2, 4.0, 3.7, 4.1, 4.0,
               3.95, 4.15, 4.05, 3.85, 4.1, 4.0]
    rec = LiveRateRecorder("cpu", version="t", flush_every=1)

    def spread(ratios):
        # distance of the ratio distribution from the ideal 1.0 —
        # median |log ratio|, scale-symmetric (2x under == 2x over)
        devs = sorted(abs(math.log(r)) for r in ratios)
        return devs[len(devs) // 2]

    stale_ratios, live_ratios = [], []
    for ms in true_ms:
        stale_ratios.append(ms / stale_ms)
        # the live model: what record_rate_fn would price the NEXT
        # chunk at, given everything recalibration has banked so far
        # (seeded by the stale probe before the first observation)
        rate = record_rate_fn("cpu", version="t")
        modeled = rate(*key_args)
        if not live_ratios:
            modeled = stale_ms  # first pick predates any live rate
        live_ratios.append(ms / modeled)
        rec.record(*key_args, ms)
    assert spread(live_ratios) < spread(stale_ratios)
    # and not marginally: the recalibrated model converges near truth
    assert live_ratios[-1] < 1.2
    assert stale_ratios[-1] > 3.0


# ---------------------------------------------------------------------------
# ledger consistency under chaos (tests/test_router.py machinery)
# ---------------------------------------------------------------------------


def make_cases(n, grid=16, nt=4, buckets=2, seed=0):
    rng = np.random.default_rng(seed)
    return [EnsembleCase(shape=(grid, grid), nt=nt + (i % buckets), eps=2,
                         k=1.0, dt=1e-5, dh=1.0 / grid, test=False,
                         u0=rng.normal(size=(grid, grid)))
            for i in range(n)]


def test_router_chaos_leaves_ledger_balanced():
    # die@2: the worker holding the THIRD case-forward dies mid-chunk;
    # its in-flight cases re-route.  The delivery ledger suppresses the
    # dead replica's late frames, so every outcome must be attributed
    # EXACTLY once: promised == resolved, nothing open, no duplicates,
    # no unmatched strays — the ledger stays balanced through chaos.
    cases = make_cases(8, buckets=2)
    want = EnsembleEngine(method="sat", batch_sizes=(1,)).run(cases)
    with ReplicaRouter(replicas=2, method="sat", batch_sizes=(1,),
                       faults="die@2", respawn=False,
                       slo=True) as router:
        got = router.serve_cases(cases)
        assert all(np.array_equal(a, b)
                   for a, b in zip(want, got, strict=True))
        m = router.metrics()
        assert m["deaths"] == 1 and m["requeued"] >= 1
        s = m["slo"]
        assert s["promised"] == 8 and s["resolved"] == 8
        assert s["open"] == 0
        assert s["duplicate"] == 0 and s["unmatched"] == 0
        # a mid-chunk death is re-served, not surfaced: no error
        # outcomes reached the ledger
        assert s["errors"] == 0
        assert router.registry.get("/slo/promised").value == 8


# ---------------------------------------------------------------------------
# GET /v1/status — the one-page fleet document
# ---------------------------------------------------------------------------


class _StatusStub:
    """Router-shaped backend for the status page: canned metrics plus a
    live registry carrying ingress/staleness signals."""

    def __init__(self):
        self.registry = MetricsRegistry()
        self.registry.counter("/ingress/accepted").inc()
        self.registry.gauge("/replica{0}/stale").set(1)
        self.registry.gauge("/replica{1}/stale").set(0)

    def live_count(self):
        return 2

    def outstanding_total(self):
        return 0

    def retry_after_s(self):
        return 0.25

    def submit(self, case, deadline_ms=None, priority=0):
        raise AssertionError("status never submits")

    def metrics(self):
        return {"replicas": 2, "cases": 5, "outstanding": 0,
                "deaths": 1, "requeued": 1, "spawns": 1, "buckets": 2,
                "transport": "pipe",
                "per_replica": {0: {"cases": 3, "deaths": 1},
                                1: {"cases": 2, "deaths": 0}},
                "request_latency_ms": {"p50": 10.0, "p99": 20.0},
                "slo": {"promised": 5, "resolved": 5, "open": 0,
                        "deadline_hit_rate": 1.0, "burn": 0.0}}


def test_status_endpoint_renders_fleet_and_slo():
    backend = _StatusStub()
    ing = IngressServer(0, backend, max_pending=2)
    try:
        r = urllib.request.urlopen(
            f"http://127.0.0.1:{ing.port}/v1/status")
        assert r.status == 200
        body = json.load(r)
        assert body["ok"] is True and body["replicas"] == 2
        assert body["deaths"] == 1 and body["transport"] == "pipe"
        assert body["ingress"]["accepted"] == 1
        # per-replica rows carry the staleness verdict from the gauges
        per = body["per_replica"]
        assert per["0"]["stale"] is True and per["1"]["stale"] is False
        assert per["0"]["cases"] == 3 and per["1"]["deaths"] == 0
        # the SLO block rides through verbatim when auditing is on
        assert body["slo"]["deadline_hit_rate"] == 1.0
        assert body["slo"]["open"] == 0
    finally:
        ing.close()
