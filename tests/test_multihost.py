"""Multi-host helpers (parallel/multihost.py): single-process degeneration
plus a REAL two-controller loopback run.

A real pod cannot run in CI, but multi-controller JAX can: the loopback
test launches two separate processes wired by `jax.distributed.initialize`
(2 virtual CPU devices each) and solves over a mesh that SPANS the process
boundary — the halo `ppermute`s actually cross the gloo transport, the DCN
analog of the reference's multi-locality parcelport under `srun -n 2`
(README.md:64-72).  The remaining tests pin the other half of the
contract: every helper degrades to exact single-host behavior (the
reference's one-locality degradation,
src/2d_nonlocal_distributed.cpp:118-120), so the same script works in
both worlds.
"""

import os
import socket
import subprocess
import sys

import numpy as np

import jax

from nonlocalheatequation_tpu.parallel import multihost
from nonlocalheatequation_tpu.parallel.mesh import make_mesh
from nonlocalheatequation_tpu.parallel.distributed2d import Solver2DDistributed


def test_init_from_env_noop_single_process(monkeypatch):
    for var in ("COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "SLURM_NTASKS",
                "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(var, raising=False)
    assert multihost.init_from_env() is False
    assert jax.process_count() == 1


def test_multiprocess_signals(monkeypatch):
    for var in ("COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "SLURM_NTASKS",
                "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(var, raising=False)
    assert multihost._multiprocess_signals() is False
    monkeypatch.setenv("SLURM_NTASKS", "1")
    assert multihost._multiprocess_signals() is False  # single task
    monkeypatch.setenv("SLURM_NTASKS", "4")
    assert multihost._multiprocess_signals() is True  # srun -N 1 -n 4
    monkeypatch.setenv("SLURM_NTASKS", "1")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "w0")
    assert multihost._multiprocess_signals() is False  # one-worker slice
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "w0,w1,w2,w3")
    assert multihost._multiprocess_signals() is True  # pod slice


def test_host_block_slice_partitions_exactly():
    # 100 rows over 8 "processes": equal blocks, last one short, no overlap
    rows = [multihost.host_block_slice(100, axis_size=8, index=p)
            for p in range(8)]
    covered = np.zeros(100, dtype=int)
    for sl in rows:
        covered[sl] += 1
    assert (covered == 1).all()
    # single process: whole grid
    assert multihost.host_block_slice(64, axis_size=1, index=0) == slice(0, 64)


def test_assert_same_noop_single_process():
    multihost.assert_same_on_all_hosts(np.arange(5), "params")


def test_solver_on_global_mesh_single_process():
    """The documented flow: init_from_env + make_mesh + solver, one process."""
    multihost.init_from_env()
    mesh = make_mesh()  # all (virtual) devices
    nx = 8 * mesh.shape["x"]
    ny = 8 * mesh.shape["y"]
    s = Solver2DDistributed(nx, ny, 1, 1, nt=5, eps=3, k=1.0, dt=1e-5,
                            dh=0.02, mesh=mesh)
    s.test_init()
    u = s.do_work()
    assert np.isfinite(u).all()


def test_two_controller_loopback_solve():
    """Two real processes, one global mesh: the DCN-analog halo exchange.

    Spawns two controllers (2 virtual CPU devices each) wired by
    jax.distributed.initialize; tests/multihost_child.py solves 2D 16x16
    on a 2x2 mesh (eps=3 one-hop, eps=9 multi-hop ring) and 3D 8^3 on a
    (2,2,1) mesh (eps=2 one-hop, eps=5 multi-hop), every mesh spanning
    the process boundary, asserting cross-host determinism
    (assert_same_on_all_hosts) and <=1e-12 agreement with the serial
    oracle in each process.
    """
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    child = os.path.join(os.path.dirname(__file__), "multihost_child.py")
    env = dict(os.environ)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "device_count" not in f]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=2"])
    procs = [
        subprocess.Popen(
            [sys.executable, child, f"localhost:{port}", "2", str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            # drain whatever the child printed before hanging — the only
            # diagnostics a distributed-init flake leaves behind — and reap
            p.kill()
            out, _ = p.communicate()
            out = (out or "") + "\n[parent] killed after 240s timeout"
        outs.append(out)
    for p in procs:
        if p.poll() is None:
            p.kill()
            p.wait()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out[-2000:]}"
        assert f"MH-OK p{pid} eps=3" in out
        assert f"MH-OK p{pid} superstep" in out
        assert f"MH-OK p{pid} eps=9" in out
        assert f"MH-OK p{pid} 3d eps=2" in out
        assert f"MH-OK p{pid} 3d eps=5" in out
        assert f"MH-OK p{pid} unstructured " in out
        assert f"MH-OK p{pid} unstructured-solver" in out
