"""Multi-host helpers (parallel/multihost.py): single-process degeneration
plus a REAL two-controller loopback run.

A real pod cannot run in CI, but multi-controller JAX can: the loopback
test launches two separate processes wired by `jax.distributed.initialize`
(2 virtual CPU devices each) and solves over a mesh that SPANS the process
boundary — the halo `ppermute`s actually cross the gloo transport, the DCN
analog of the reference's multi-locality parcelport under `srun -n 2`
(README.md:64-72).  The remaining tests pin the other half of the
contract: every helper degrades to exact single-host behavior (the
reference's one-locality degradation,
src/2d_nonlocal_distributed.cpp:118-120), so the same script works in
both worlds.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax

from nonlocalheatequation_tpu.parallel import multihost
from nonlocalheatequation_tpu.parallel.mesh import make_mesh
from nonlocalheatequation_tpu.parallel.distributed2d import Solver2DDistributed

REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_init_from_env_noop_single_process(monkeypatch):
    for var in ("COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "SLURM_NTASKS",
                "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(var, raising=False)
    assert multihost.init_from_env() is False
    assert jax.process_count() == 1


def test_multiprocess_signals(monkeypatch):
    for var in ("COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "SLURM_NTASKS",
                "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(var, raising=False)
    assert multihost._multiprocess_signals() is False
    monkeypatch.setenv("SLURM_NTASKS", "1")
    assert multihost._multiprocess_signals() is False  # single task
    monkeypatch.setenv("SLURM_NTASKS", "4")
    assert multihost._multiprocess_signals() is True  # srun -N 1 -n 4
    monkeypatch.setenv("SLURM_NTASKS", "1")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "w0")
    assert multihost._multiprocess_signals() is False  # one-worker slice
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "w0,w1,w2,w3")
    assert multihost._multiprocess_signals() is True  # pod slice


def test_host_block_slice_partitions_exactly():
    # 100 rows over 8 "processes": equal blocks, last one short, no overlap
    rows = [multihost.host_block_slice(100, axis_size=8, index=p)
            for p in range(8)]
    covered = np.zeros(100, dtype=int)
    for sl in rows:
        covered[sl] += 1
    assert (covered == 1).all()
    # single process: whole grid
    assert multihost.host_block_slice(64, axis_size=1, index=0) == slice(0, 64)


def test_assert_same_noop_single_process():
    multihost.assert_same_on_all_hosts(np.arange(5), "params")


def test_solver_on_global_mesh_single_process():
    """The documented flow: init_from_env + make_mesh + solver, one process."""
    multihost.init_from_env()
    mesh = make_mesh()  # all (virtual) devices
    nx = 8 * mesh.shape["x"]
    ny = 8 * mesh.shape["y"]
    s = Solver2DDistributed(nx, ny, 1, 1, nt=5, eps=3, k=1.0, dt=1e-5,
                            dh=0.02, mesh=mesh)
    s.test_init()
    u = s.do_work()
    assert np.isfinite(u).all()


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _controller_env(local_devices, extra_env=None):
    """The one launch-environment recipe every loopback spawn shares:
    ambient env, ``local_devices`` virtual CPU devices, extra vars."""
    env = dict(os.environ, **(extra_env or {}))
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "device_count" not in f]
    env["XLA_FLAGS"] = " ".join(
        flags + [f"--xla_force_host_platform_device_count={local_devices}"])
    return env


def _spawn_controllers(port, dev_counts, extra_env=None):
    """One child per entry of ``dev_counts`` (its local device count —
    UNEVEN splits welcome); returns the Popen list."""
    child = os.path.join(os.path.dirname(__file__), "multihost_child.py")
    nproc = len(dev_counts)
    ndev = sum(dev_counts)
    procs = []
    for pid, local in enumerate(dev_counts):
        env = _controller_env(local, extra_env)
        env["MH_NDEV"] = str(ndev)
        procs.append(subprocess.Popen(
            [sys.executable, child, f"localhost:{port}", str(nproc),
             str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        ))
    return procs


def _harvest(procs, timeout=240):
    """Collect each child's stdout; when stderr is a separate pipe it is
    preserved on the Popen (``p.stderr_text``) so failure diagnostics
    survive even though the silence assertions need stdout pure."""
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            # drain whatever the child printed before hanging — the only
            # diagnostics a distributed-init flake leaves behind — and reap
            p.kill()
            out, err = p.communicate()
            out = (out or "") + f"\n[parent] killed after {timeout}s timeout"
        p.stderr_text = err or ""
        outs.append(out)
    for p in procs:
        if p.poll() is None:
            p.kill()
            p.wait()
    return outs


def _run_loopback(dev_counts, extra_env=None, timeout=240):
    procs = _spawn_controllers(_free_port(), dev_counts, extra_env)
    outs = _harvest(procs, timeout)
    for pid, (p, out) in enumerate(zip(procs, outs, strict=True)):
        assert p.returncode == 0, f"process {pid} failed:\n{out[-2000:]}"
    return outs


def test_two_controller_loopback_solve():
    """Two real processes, one global mesh: the DCN-analog halo exchange.

    Spawns two controllers (2 virtual CPU devices each) wired by
    jax.distributed.initialize; tests/multihost_child.py solves 2D 16x16
    on a 2x2 mesh (eps=3 one-hop, eps=9 multi-hop ring) and 3D 8^3 on a
    (2,2,1) mesh (eps=2 one-hop, eps=5 multi-hop), every mesh spanning
    the process boundary, asserting cross-host determinism
    (assert_same_on_all_hosts) and <=1e-12 agreement with the serial
    oracle in each process.
    """
    outs = _run_loopback([2, 2])
    for pid, out in enumerate(outs):
        assert f"MH-OK p{pid} eps=3" in out
        assert f"MH-OK p{pid} superstep" in out
        assert f"MH-OK p{pid} eps=9" in out
        assert f"MH-OK p{pid} 3d eps=2" in out
        assert f"MH-OK p{pid} 3d eps=5" in out
        assert f"MH-OK p{pid} unstructured " in out
        assert f"MH-OK p{pid} unstructured-solver" in out
        # 4 global devices: B=256 fits the K=2 ring superstep
        assert f"MH-OK p{pid} unstructured-superstep" in out


@pytest.mark.slow  # multi-controller depth coverage: the 2-controller
# loopback and the unstructured kill-resume stay in the tier-1 budget
def test_four_controller_loopback_solve():
    """VERDICT r4 #6: beyond the 2-process loopback.  Four controllers
    (2 devices each, 8 global), meshes (2,4) / (2,2,2) spanning all four
    process boundaries: the grid SPMD one-hop AND multi-hop halo rings,
    the 3D exchange, and the sharded-offsets unstructured path all ride
    gloo across four ranks."""
    outs = _run_loopback(
        [2, 2, 2, 2], extra_env={"MH_LEGS": "2d,3d,unstructured"},
        timeout=360)
    for pid, out in enumerate(outs):
        assert f"MH-OK p{pid} eps=3" in out
        assert f"MH-OK p{pid} eps=9" in out
        assert f"MH-OK p{pid} 3d eps=2" in out
        assert f"MH-OK p{pid} 3d eps=5" in out
        assert f"MH-OK p{pid} unstructured " in out
        assert f"MH-OK p{pid} unstructured-solver" in out


@pytest.mark.slow  # multi-controller depth coverage: the 2-controller
# loopback and the unstructured kill-resume stay in the tier-1 budget
def test_uneven_device_split_loopback():
    """VERDICT r4 #6: processes need not own equal device counts (a real
    cluster can expose asymmetric slices).  Process 0 owns 3 devices,
    process 1 owns 1; the (2,2) mesh therefore crosses the process
    boundary mid-row, and every leg must still agree with the oracle."""
    outs = _run_loopback([3, 1], extra_env={"MH_LEGS": "2d,unstructured"})
    for pid, out in enumerate(outs):
        assert f"MH-OK p{pid} eps=3" in out
        assert f"MH-OK p{pid} eps=9" in out
        assert f"MH-OK p{pid} unstructured " in out
        assert f"MH-OK p{pid} unstructured-solver" in out


@pytest.mark.parametrize("cli_args, banner, footer", [
    (["nonlocalheatequation_tpu.cli.solve2d_distributed",
      "--nx", "8", "--ny", "8", "--npx", "2", "--npy", "2",
      "--nt", "5", "--eps", "3", "--dt", "0.0005", "--dh", "0.02"],
     "2d_nonlocal_distributed", "Localities"),
    (["nonlocalheatequation_tpu.cli.solve3d", "--distributed", "--test",
      "--nx", "8", "--ny", "8", "--nz", "8", "--nt", "2", "--eps", "2",
      "--dt", "0.0001", "--dh", "0.05"],
     "3d_nonlocal", "z dimension"),
])
def test_cli_runs_multicontroller_like_srun(cli_args, banner, footer):
    """The reference's flagship workflow is ``srun -n N
    ./2d_nonlocal_distributed`` — every rank runs the SAME binary
    (README.md:64-72).  Our CLIs must do the same: launched as two
    processes with the standard env wiring (COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID — also the only coverage of
    init_from_env's env-var path), they solve over a process-spanning
    mesh, rank 0 owns the console, and non-zero ranks stay silent."""
    port = _free_port()
    procs = []
    for pid, local in enumerate([2, 2]):
        env = _controller_env(local, {
            "COORDINATOR_ADDRESS": f"localhost:{port}",
            "JAX_NUM_PROCESSES": "2", "JAX_PROCESS_ID": str(pid)})
        procs.append(subprocess.Popen(
            [sys.executable, "-m", *cli_args, "--platform", "cpu"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=REPO_DIR,
        ))
    outs = _harvest(procs, timeout=180)
    for pid, (p, out) in enumerate(zip(procs, outs, strict=True)):
        assert p.returncode == 0, (
            f"rank {pid} failed:\n{out[-1500:]}\n[stderr]\n"
            f"{p.stderr_text[-1500:]}")
    assert banner in outs[0]
    assert "l2:" in outs[0]  # the error report reached rank 0
    assert footer in outs[0]  # ... and the right CLI's timing footer
    # rank 1 may only emit transport connection chatter (C++ lines printed
    # DURING jax.distributed.initialize, before the rank is known); every
    # framework line belongs to rank 0
    noise = [ln for ln in outs[1].splitlines()
             if ln.strip() and not ln.startswith("[Gloo]")]
    assert noise == [], f"rank 1 printed to stdout:\n{noise[:5]}"


def test_cli_batch_multicontroller_verifies_token_stream():
    """--test_batch under two controllers: identical stdin on every rank
    passes (rank 0 prints the verdict), DIVERGENT stdin is caught by the
    cross-rank token check on every rank instead of silently violating
    the SPMD contract."""
    batch = "1\n25 25 2 2 45 5 1 0.0005 0.02\n"
    for divergent in (False, True):
        port = _free_port()
        procs = []
        for pid in range(2):
            env = _controller_env(2, {
                "COORDINATOR_ADDRESS": f"localhost:{port}",
                "JAX_NUM_PROCESSES": "2", "JAX_PROCESS_ID": str(pid)})
            procs.append(subprocess.Popen(
                [sys.executable, "-m",
                 "nonlocalheatequation_tpu.cli.solve2d_distributed",
                 "--test_batch", "--platform", "cpu"],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True, env=env, cwd=REPO_DIR,
            ))
        try:
            for pid, p in enumerate(procs):
                text = batch
                if divergent and pid == 1:
                    text = "1\n25 25 2 2 45 5 1 0.0006 0.02\n"  # one off
                # close every rank's stdin NOW: the children block in
                # stdin.read() until EOF, and a serialized close
                # (communicate per proc) would leave rank 1 blocked while
                # rank 0 enters the collective and trips gloo's 30s
                # deadline.  stdin = None so _harvest's communicate() does
                # not re-touch the closed pipe.
                p.stdin.write(text)
                p.stdin.close()
                p.stdin = None
        except BrokenPipeError:
            # a rank died before reading (port clash, import error): kill
            # the siblings rather than leaking them into later tests —
            # _harvest below reaps and surfaces the output
            for p in procs:
                if p.poll() is None:
                    p.kill()
        outs = _harvest(procs, timeout=180)
        if divergent:
            for pid, p in enumerate(procs):
                assert p.returncode != 0, f"rank {pid} missed divergence"
            assert "batch input" in "".join(outs)
        else:
            for pid, (p, out) in enumerate(zip(procs, outs, strict=True)):
                assert p.returncode == 0, f"rank {pid}:\n{out[-1500:]}"
            assert "Tests Passed" in outs[0]


def test_assert_same_detects_divergence():
    """The determinism checker must FAIL when hosts hold different values
    (a checker that can only pass proves nothing) — here under an uneven
    1+2 device split, where each process contributes its own rows."""
    code = (
        "import sys, numpy as np, jax;"
        "jax.config.update('jax_platforms', 'cpu');"
        "sys.path.insert(0, sys.argv[4]);"
        "from nonlocalheatequation_tpu.parallel import multihost;"
        "multihost.init_from_env(sys.argv[1], int(sys.argv[2]),"
        " int(sys.argv[3]));"
        # x64 is OFF in these children (only the platform is forced):
        # identical f64 host values must STILL pass — the digest exchange
        # must not let device-side f32 canonicalization corrupt the
        # comparison
        "multihost.assert_same_on_all_hosts(np.arange(3.0) + 0.123456789,"
        " 'same-f64');"
        "x = np.arange(3.0) + jax.process_index();"
        "\ntry:\n"
        "    multihost.assert_same_on_all_hosts(x, 'divergent')\n"
        "    print('NO-RAISE')\n"
        "except AssertionError:\n"
        "    print('RAISED-OK')\n"
    )
    port = _free_port()
    procs = []
    for pid, local in enumerate([1, 2]):
        procs.append(subprocess.Popen(
            [sys.executable, "-c", code, f"localhost:{port}", "2", str(pid),
             REPO_DIR],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=_controller_env(local),
        ))
    outs = _harvest(procs, timeout=120)
    for pid, out in enumerate(outs):
        assert "RAISED-OK" in out, f"process {pid} did not detect:\n{out[-1500:]}"
        assert "NO-RAISE" not in out


@pytest.mark.slow  # multi-controller depth coverage: the 2-controller
# loopback and the unstructured kill-resume stay in the tier-1 budget
def test_kill_one_then_resume_on_different_process_counts(tmp_path):
    """VERDICT r4 #6: kill-one + checkpoint-resume across a different
    process count.  A 2-controller checkpointed run is SIGKILLed
    mid-flight (rank 1 first — the peer then stalls in its next
    collective — then rank 0); the checkpoint must stay loadable (atomic
    tmp+rename under a hard kill), and the SAME file must resume both
    single-process (serial solver, in this test process) and on FOUR
    controllers, each matching the serial oracle's full trajectory."""
    import signal
    import time

    from nonlocalheatequation_tpu.models.solver2d import Solver2D
    from nonlocalheatequation_tpu.utils.checkpoint import load_state

    ck = tmp_path / "mh-crash.npz"
    procs = _spawn_controllers(
        _free_port(), [2, 2],
        extra_env={"MH_LEGS": "crash2d", "MH_CK": str(ck)})
    try:
        # wait for at least one checkpoint to land, then kill rank 1 hard
        deadline = time.time() + 180
        while not ck.exists() and time.time() < deadline:
            if all(p.poll() is not None for p in procs):
                break
            time.sleep(0.2)
        assert ck.exists(), "no checkpoint appeared within 180s"
        procs[1].send_signal(signal.SIGKILL)
        time.sleep(1.0)  # rank 0 runs into the dead peer's collective
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    _harvest(procs, timeout=30)

    # the checkpoint a hard-killed job leaves behind must load cleanly
    u, t, params = load_state(str(ck))
    assert t > 0 and u.shape == (16, 16)
    nt_total = t + 4

    # resume leg 1: single process (count 2 -> 1), the serial solver
    s = Solver2D(16, 16, nt_total, eps=3, k=1.0, dt=1e-4, dh=1.0 / 16,
                 backend="jit")
    s.test_init()
    s.resume(str(ck))
    assert s.t0 == t
    ur = s.do_work()
    o = Solver2D(16, 16, nt_total, eps=3, k=1.0, dt=1e-4, dh=1.0 / 16,
                 backend="oracle")
    o.test_init()
    err = float(np.abs(ur - o.do_work()).max())
    assert err < 1e-12, f"serial resume deviates from oracle by {err:.3e}"

    # resume leg 2: FOUR controllers (count 2 -> 4), mesh (2, 4)
    outs = _run_loopback(
        [2, 2, 2, 2],
        extra_env={"MH_LEGS": "resume2d", "MH_CK": str(ck),
                   "MH_NT_TOTAL": str(nt_total)})
    for pid, out in enumerate(outs):
        assert f"MH-OK p{pid} resume2d t0={t} " in out


def test_kill_one_then_resume_unstructured(tmp_path):
    """The crash2d/resume2d pair for the SHARDED-OFFSETS unstructured
    path (VERDICT r4 #6 names both paths): a 2-controller checkpointed
    run over the process-spanning cloud is SIGKILLed mid-flight; the
    checkpoint must stay loadable, resume single-process on the
    unsharded op, AND resume on FOUR controllers, each matching the f64
    oracle trajectory to 1e-12."""
    import signal
    import time

    from tests.test_unstructured_sharded import cloud_op

    from nonlocalheatequation_tpu.ops.unstructured import UnstructuredSolver
    from nonlocalheatequation_tpu.utils.checkpoint import load_state

    ck = tmp_path / "mh-crashu.npz"
    procs = _spawn_controllers(
        _free_port(), [2, 2],
        extra_env={"MH_LEGS": "crashu", "MH_CK": str(ck)})
    try:
        deadline = time.time() + 180
        while not ck.exists() and time.time() < deadline:
            if all(p.poll() is not None for p in procs):
                break
            time.sleep(0.2)
        assert ck.exists(), "no checkpoint appeared within 180s"
        procs[1].send_signal(signal.SIGKILL)
        time.sleep(1.0)  # rank 0 runs into the dead peer's collective
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    _harvest(procs, timeout=30)

    u, t, params = load_state(str(ck))
    assert t > 0 and u.shape == (1024,)
    nt_total = t + 4

    # resume leg 1: single process (count 2 -> 1), the UNSHARDED op —
    # the checkpoint is the global node vector, portable across wrappers.
    # cloud_op is the ONE definition of this operator's physics (shared
    # with the multihost children); rebuilding it here from hand-copied
    # constants let the legs drift apart silently (advisor finding r5)
    uop = cloud_op(m=32, seed=0)
    s = UnstructuredSolver(uop, nt=nt_total, backend="jit")
    s.test_init()
    s.resume(str(ck))
    assert s.t0 == t
    ur = s.do_work()
    o = UnstructuredSolver(uop, nt=nt_total, backend="oracle")
    o.test_init()
    err = float(np.abs(ur - o.do_work()).max())
    assert err < 1e-12, f"serial resume deviates from oracle by {err:.3e}"

    # resume leg 2: FOUR controllers (count 2 -> 4)
    outs = _run_loopback(
        [2, 2, 2, 2],
        extra_env={"MH_LEGS": "resumeu", "MH_CK": str(ck),
                   "MH_NT_TOTAL": str(nt_total)})
    for pid, out in enumerate(outs):
        assert f"MH-OK p{pid} resumeu t0={t} " in out
