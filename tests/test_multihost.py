"""Multi-host helpers (parallel/multihost.py): single-process degeneration.

A real pod cannot run in CI; the contract tested here is that every helper
degrades to the exact single-host behavior (the reference's one-locality
degradation, src/2d_nonlocal_distributed.cpp:118-120), so the same script
works in both worlds.
"""

import numpy as np

import jax

from nonlocalheatequation_tpu.parallel import multihost
from nonlocalheatequation_tpu.parallel.mesh import make_mesh
from nonlocalheatequation_tpu.parallel.distributed2d import Solver2DDistributed


def test_init_from_env_noop_single_process(monkeypatch):
    for var in ("COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "SLURM_NTASKS",
                "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(var, raising=False)
    assert multihost.init_from_env() is False
    assert jax.process_count() == 1


def test_multiprocess_signals(monkeypatch):
    for var in ("COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "SLURM_NTASKS",
                "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(var, raising=False)
    assert multihost._multiprocess_signals() is False
    monkeypatch.setenv("SLURM_NTASKS", "1")
    assert multihost._multiprocess_signals() is False  # single task
    monkeypatch.setenv("SLURM_NTASKS", "4")
    assert multihost._multiprocess_signals() is True  # srun -N 1 -n 4
    monkeypatch.setenv("SLURM_NTASKS", "1")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "w0")
    assert multihost._multiprocess_signals() is False  # one-worker slice
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "w0,w1,w2,w3")
    assert multihost._multiprocess_signals() is True  # pod slice


def test_host_block_slice_partitions_exactly():
    # 100 rows over 8 "processes": equal blocks, last one short, no overlap
    rows = [multihost.host_block_slice(100, axis_size=8, index=p)
            for p in range(8)]
    covered = np.zeros(100, dtype=int)
    for sl in rows:
        covered[sl] += 1
    assert (covered == 1).all()
    # single process: whole grid
    assert multihost.host_block_slice(64, axis_size=1, index=0) == slice(0, 64)


def test_assert_same_noop_single_process():
    multihost.assert_same_on_all_hosts(np.arange(5), "params")


def test_solver_on_global_mesh_single_process():
    """The documented flow: init_from_env + make_mesh + solver, one process."""
    multihost.init_from_env()
    mesh = make_mesh()  # all (virtual) devices
    nx = 8 * mesh.shape["x"]
    ny = 8 * mesh.shape["y"]
    s = Solver2DDistributed(nx, ny, 1, 1, nt=5, eps=3, k=1.0, dt=1e-5,
                            dh=0.02, mesh=mesh)
    s.test_init()
    u = s.do_work()
    assert np.isfinite(u).all()
